"""Device-resident evaluation metrics (docs/Performance.md).

Before this module, every eval tick pulled the full [K, n] training
score matrix to host (`np.asarray(self.scores)` in GBDT.eval_train) and
each host Metric additionally round-tripped the scores through the
device for `objective.convert_output` — one D2H plus one H2D+D2H *per
(dataset, metric)*, a per-iteration host sync that de-pipelines JAX's
async dispatch (the training loop's only other sync is the pipelined
tree materialization).  Here the built-in metrics are computed in-jit
over the device score buffers and the whole tick returns ONE packed f32
vector: [metric values..., gradients_finite, scores_finite] — a single
small D2H that also feeds the engine's non-finite sentinel (which used
to sample `scores[:, :256]` to host separately).

The formulas mirror the host classes in metric.py exactly (which mirror
src/metric/*_metric.hpp); AUC and average_precision use EXACT sorted
forms (stable sort + tie grouping, like binary_metric.hpp:159), not the
binned multi-process approximations in metric.py — this evaluator only
runs when the score buffer is fully addressable.  Values differ from
the float64 host path by float32 summation rounding only
(tests/test_device_metrics.py pins parity).

Coverage: a metric set is served on device only when EVERY configured
metric has a device form and the objective's conversion runs on device
(run_on_host objectives — per-query host ranking — keep the host path).
Mixed device/host evaluation would reintroduce the score fetch, so the
gate is all-or-nothing and the fallback is the unchanged host path.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..utils import log


def device_exact_auc(score, label, weight):
    """Exact weighted rank-sum AUC on device (ref: binary_metric.hpp:159
    AUCMetric): stable sort by descending score, equal-score blocks give
    positives half credit — the same block form as the host class, as
    segment sums over tie groups.  NaN scores sort last and form
    singleton groups on both paths (np diff(NaN) and s[i] != s[i+1] both
    mark a boundary)."""
    import jax.numpy as jnp
    order = jnp.argsort(-score, stable=True)
    lab = label[order] > 0
    ws = weight[order]
    s = score[order]
    pos_w = jnp.where(lab, ws, 0.0)
    neg_w = jnp.where(lab, 0.0, ws)
    n = s.shape[0]
    first = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    gid = jnp.cumsum(first.astype(jnp.int32)) - 1
    grp_pos = jnp.zeros(n, jnp.float32).at[gid].add(pos_w)
    grp_neg = jnp.zeros(n, jnp.float32).at[gid].add(neg_w)
    pos_above = jnp.cumsum(grp_pos) - grp_pos
    accum = jnp.sum(grp_neg * (pos_above + 0.5 * grp_pos))
    tp, tn = jnp.sum(pos_w), jnp.sum(neg_w)
    return jnp.where((tp == 0) | (tn == 0), 1.0,
                     accum / jnp.maximum(tp * tn, 1e-30))


def device_exact_average_precision(score, label, weight):
    """Exact weighted average precision on device (ref:
    binary_metric.hpp AveragePrecisionMetric): descending stable sort,
    cumulative tp/fp — the host class verbatim in jnp."""
    import jax.numpy as jnp
    order = jnp.argsort(-score, stable=True)
    lab = label[order] > 0
    ws = weight[order]
    delta_tp = jnp.where(lab, ws, 0.0)
    tp = jnp.cumsum(delta_tp)
    fp = jnp.cumsum(jnp.where(lab, 0.0, ws))
    prec = tp / jnp.maximum(tp + fp, 1e-20)
    total_pos = tp[-1]
    ap = jnp.sum(prec * delta_tp) / jnp.maximum(total_pos, 1e-30)
    return jnp.where(total_pos == 0, 1.0, ap)


def _binary_pointwise(name: str, config):
    """jnp pointwise loss over CONVERTED single-class scores, or None.
    Extends metric.device_pointwise_loss with the cross-entropy family
    (those take the untransformed weight, handled by the caller)."""
    import jax.numpy as jnp
    from ..metric import device_pointwise_loss
    eps15 = 1e-15
    if name == "cross_entropy":
        return device_pointwise_loss("xentropy", config)
    if name == "kullback_leibler":
        def _kl(p, y):
            p = jnp.clip(p, eps15, 1 - eps15)
            y = jnp.clip(y, eps15, 1 - eps15)
            return (y * jnp.log(y / p)
                    + (1 - y) * jnp.log((1 - y) / (1 - p)))
        return _kl
    return device_pointwise_loss(name, config)


# metric name -> reduction kind for the single-class plans
_KIND_SQRT = "sqrt"        # weighted avg then sqrt (rmse)
_KIND_AVG = "avg"          # weighted avg (pointwise / sum_weights)
_KIND_MEAN = "mean"        # plain mean over rows (cross_entropy_lambda)
_KIND_AUC = "auc"
_KIND_AP = "average_precision"


def build_plans(metrics, config, objective, num_class: int):
    """[(name, kind, loss_fn_or_None)] when EVERY metric has a device
    form, else None.  `metrics` are host Metric instances (their .name
    is the canonical metric name)."""
    plans: List[Tuple[str, str, object]] = []
    for m in metrics:
        name = m.name
        if num_class > 1:
            if name in ("multi_logloss", "multi_error"):
                plans.append((name, name, None))
                continue
            return None
        if name == "auc":
            plans.append((name, _KIND_AUC, None))
            continue
        if name == "average_precision":
            plans.append((name, _KIND_AP, None))
            continue
        if name == "cross_entropy_lambda":
            plans.append((name, _KIND_MEAN, None))
            continue
        fn = _binary_pointwise(name, config)
        if fn is None:
            return None
        plans.append((name, _KIND_SQRT if name == "rmse" else _KIND_AVG,
                      fn))
    return plans


def make_tick_fn(plans, obj, K: int, top_k: int):
    """The packed eval-tick program: (scores [K, n], label [n], weight
    [n]|None, pad_mask [n], grad_ok scalar) -> packed f32 vector
    [metric values..., gradients_finite, scores_finite].  Module-level
    so the tpulint IR audit can abstractly trace the SAME program
    DeviceEval jits (lightgbm_tpu/_lint_entries.py) without a trained
    booster; DeviceEval.__init__ is the only runtime caller."""
    import jax.numpy as jnp

    def _tick(scores, label, weight, pad_mask, grad_ok):
        w = pad_mask if weight is None else weight * pad_mask
        den = jnp.sum(w)
        outs = []
        if K > 1:
            prob = (obj.convert_output(scores) if obj is not None
                    else scores)
            lab_oh = (label[None, :]
                      == jnp.arange(K, dtype=prob.dtype)[:, None])
            p_lab = jnp.sum(jnp.where(lab_oh, prob, 0.0), axis=0)
            for _name, kind, _fn in plans:
                if kind == "multi_logloss":
                    pt = -jnp.log(jnp.clip(p_lab, 1e-15, 1.0))
                else:  # multi_error: ties count AGAINST the row
                    # (ref: multiclass_metric.hpp:142 LossOnPoint)
                    num_ge = jnp.sum(prob >= p_lab[None, :], axis=0)
                    pt = (num_ge > top_k).astype(jnp.float32)
                outs.append(jnp.sum(pt * w) / den)
        else:
            sc = scores[0]
            conv = obj.convert_output(sc) if obj is not None else sc
            for _name, kind, fn in plans:
                if kind == _KIND_AUC:
                    # raw scores, like the host class (AUC is
                    # rank-based; conversion is monotone)
                    outs.append(device_exact_auc(sc, label, w))
                elif kind == _KIND_AP:
                    outs.append(device_exact_average_precision(
                        sc, label, w))
                elif kind == _KIND_MEAN:
                    # cross_entropy_lambda: z from the UNmasked
                    # weight, plain mean (xentropy_metric.hpp)
                    wz = 1.0 if weight is None else weight
                    z = jnp.clip(1.0 - jnp.exp(-wz * conv),
                                 1e-15, 1 - 1e-15)
                    pt = -(label * jnp.log(z)
                           + (1.0 - label) * jnp.log(1.0 - z))
                    outs.append(jnp.sum(pt * pad_mask)
                                / jnp.sum(pad_mask))
                else:
                    v = jnp.sum(fn(conv, label) * w) / den
                    outs.append(jnp.sqrt(v) if kind == _KIND_SQRT
                                else v)
        # the non-finite sentinel flags ride the same packed fetch
        # (engine._check_finite used to sample scores[:, :256])
        outs.append(grad_ok.astype(jnp.float32))
        outs.append(jnp.all(jnp.isfinite(scores)).astype(jnp.float32))
        return jnp.stack(outs)

    return _tick


class DeviceEval:
    """One-fetch-per-tick metric evaluator bound to a GBDT's training
    buffers.  `ok` is False when the configuration has no full device
    form (the caller falls back to the host path); `fetches` counts D2H
    transfers (tests pin exactly one per eval tick)."""

    def __init__(self, gbdt):
        self.ok = False
        self.fetches = 0
        cfg = gbdt.config
        obj = gbdt.objective
        if str(getattr(cfg, "device_eval", "auto")) == "false":
            return
        if obj is not None and getattr(obj, "run_on_host", False):
            return
        K = gbdt.num_tree_per_iteration
        plans = build_plans(gbdt.train_metrics, cfg, obj, K)
        if plans is None:
            if gbdt.train_metrics:
                log.debug("device_eval: falling back to host metrics "
                          "(a configured metric has no device form)")
            return
        import jax
        import jax.numpy as jnp

        md = gbdt.train_data.metadata
        n_pad = gbdt.n_pad
        label = np.zeros(n_pad, np.float32)
        label[:gbdt.num_data] = np.asarray(md.label, np.float32)
        self._label_dev = gbdt._put_by_row(label)
        self._weight_dev = None
        if md.weight is not None:
            w = np.zeros(n_pad, np.float32)
            w[:gbdt.num_data] = np.asarray(md.weight, np.float32)
            self._weight_dev = gbdt._put_by_row(w)
        self._plans = plans
        top_k = int(cfg.multi_error_top_k)
        _tick = make_tick_fn(plans, obj, K, top_k)

        # recompile watchdog + compiled-cost roofline accounting: the
        # packed eval tick is a hot jitted entry like grow/gradients —
        # a mid-run shape change must warn, and the cost model wants
        # its flops/bytes keyed by the same signature
        from ..observability import RecompileDetector
        # tpulint: disable-next=donate-argnums -- eval reads the live training score buffer; the boosting loop keeps updating it
        self._fn = RecompileDetector(jax.jit(_tick), "device_eval")
        self._pad_mask = gbdt.pad_mask
        self._true_flag = jnp.asarray(True)
        self.ok = True

    def run(self, scores, grad_ok) -> Tuple[List[Tuple[str, float]],
                                            bool, bool]:
        """Evaluate one tick: returns ([(metric, value)], grads_finite,
        scores_finite) with exactly one device->host transfer."""
        flag = self._true_flag if grad_ok is None else grad_ok
        vec = np.asarray(self._fn(scores, self._label_dev,
                                  self._weight_dev, self._pad_mask, flag))
        self.fetches += 1
        out = [(name, float(v))
               for (name, _kind, _fn), v in zip(self._plans, vec)]
        return out, bool(vec[-2] > 0), bool(vec[-1] > 0)
