"""Best-split finding: the reference's sequential per-bin gain scan, vectorized.

TPU-native replacement for FeatureHistogram::FindBestThresholdSequentially
(ref: src/treelearner/feature_histogram.hpp:831-1057) and the CUDA kernels
FindBestSplitsForLeafKernel / SyncBestSplitForLeafKernel
(ref: src/treelearner/cuda/cuda_best_split_finder.cu:772,1920): instead of a
serial loop per feature, both scan directions are evaluated for ALL features
and ALL candidate thresholds at once via masked prefix/suffix cumsums, then a
single argmax picks the winner — the shape XLA tiles well.

Behavioral parity notes (each mirrors a reference line):
  * counts are derived from hessians: cnt(bin) = RoundInt(hess * cnt_factor),
    cnt_factor = num_data / sum_hessian (feature_histogram.hpp:871-874).
  * accumulators are seeded with kEpsilon=1e-15 and the leaf hessian carries
    +2*kEpsilon (feature_histogram.hpp:169-171, 856, 941).
  * REVERSE scan (default_left=True) excludes the NaN bin so missing joins the
    left side; the forward scan leaves it on the right (hpp:859-867, 946-963).
  * MissingType::Zero skips the zero ("default") bin in both scans, so the zero
    bin always follows default_left (hpp:865-869 SKIP_DEFAULT_BIN).
  * `break` conditions (left side runs out of data/hessian) are monotone in the
    threshold, so masking is exactly equivalent to breaking.
  * within a scan, ties keep the first-visited threshold: largest for REVERSE,
    smallest for forward; the forward result replaces the reverse one only on
    strictly larger gain (hpp:1031).
  * across features, gain ties pick the smaller feature index
    (split_info.hpp:138-163 operator>).

The scan works in the "full bin" layout (bins 0..num_bin-1 present for every
feature, no most_freq_bin offset packing) — equivalent results, simpler tensors.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15  # ref: include/LightGBM/meta.h:54
K_MIN_SCORE = -jnp.inf

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class SplitParams(NamedTuple):
    """Static split hyperparameters (subset of ref Config used by the gain scan)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    path_smooth: float = 0.0
    # categorical split finding (ref: feature_histogram.cpp:144
    # FindBestThresholdCategoricalInner); has_categorical=False skips the
    # whole categorical branch at trace time
    has_categorical: bool = False
    # static inner-feature indices of the categorical features: the scan
    # (argsort + two sequential prefix scans) runs only over these rows,
    # not all F features; () falls back to scanning every feature
    cat_features: tuple = ()
    max_cat_to_onehot: int = 4
    # monotone constraints, basic mode (ref: monotone_constraints.hpp:465
    # BasicLeafConstraints; feature_histogram.hpp:758 GetSplitGains USE_MC):
    # candidate outputs are clamped to the leaf's [min, max] and splits
    # violating the ordering are rejected.  False skips all of it at trace
    # time.  monotone_penalty is the config value fed to
    # ComputeMonotoneSplitGainPenalty (monotone_constraints.hpp:357).
    has_monotone: bool = False
    monotone_penalty: float = 0.0
    # extra-trees mode (ref: feature_histogram.hpp:192 USE_RAND): each
    # numerical feature is evaluated at ONE random threshold per leaf scan
    # instead of the full sweep; extra_seed seeds the per-scan draw
    extra_trees: bool = False
    extra_seed: int = 6
    # cost-effective gradient boosting (ref:
    # cost_effective_gradient_boosting.hpp:79 DeltaGain): per-feature gain
    # penalty = tradeoff * (penalty_split * num_data_in_leaf
    #                       + coupled_penalty[f] * not_yet_used[f])
    has_cegb: bool = False
    cegb_tradeoff: float = 1.0
    cegb_penalty_split: float = 0.0
    # lazy per-(row, feature) acquisition penalty (ref:
    # cost_effective_gradient_boosting.hpp:139 CalculateOndemandCosts):
    # the scan receives the per-feature cost already summed over the
    # leaf's not-yet-fetched rows
    has_cegb_lazy: bool = False
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    min_data_per_group: int = 100


def cat_bitset_words(max_bin: int) -> int:
    """int32 bitset words needed for a categorical split over max_bin bins."""
    return max(1, (max_bin + 31) // 32)


class SplitResult(NamedTuple):
    """Device-side SplitInfo (ref: src/treelearner/split_info.hpp:22)."""
    gain: jnp.ndarray            # shifted gain (<=0 means no valid split)
    feature: jnp.ndarray         # inner feature index (int32)
    threshold: jnp.ndarray       # bin threshold (int32)
    default_left: jnp.ndarray    # bool
    left_sum_gradient: jnp.ndarray
    left_sum_hessian: jnp.ndarray
    left_count: jnp.ndarray      # int32
    left_output: jnp.ndarray
    right_sum_gradient: jnp.ndarray
    right_sum_hessian: jnp.ndarray
    right_count: jnp.ndarray     # int32
    right_output: jnp.ndarray
    is_cat: jnp.ndarray          # bool: categorical split (bitset routing)
    cat_bitset: jnp.ndarray      # [W] int32 words: bins going LEFT


def threshold_l1(s: jnp.ndarray, l1: float) -> jnp.ndarray:
    """ref: feature_histogram.hpp:710 ThresholdL1."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(sum_g, sum_h, count, parent_output, p: SplitParams):
    """ref: feature_histogram.hpp:716 CalculateSplittedLeafOutput."""
    ret = -threshold_l1(sum_g, p.lambda_l1) / (sum_h + p.lambda_l2)
    if p.max_delta_step > 0:
        ret = jnp.clip(ret, -p.max_delta_step, p.max_delta_step)
    if p.path_smooth > K_EPSILON:
        ratio = count.astype(ret.dtype) / p.path_smooth
        ret = ret * ratio / (ratio + 1.0) + parent_output / (ratio + 1.0)
    return ret


def leaf_gain(sum_g, sum_h, count, parent_output, p: SplitParams):
    """ref: feature_histogram.hpp:800 GetLeafGain."""
    if p.max_delta_step <= 0 and p.path_smooth <= K_EPSILON:
        sg_l1 = threshold_l1(sum_g, p.lambda_l1)
        return (sg_l1 * sg_l1) / (sum_h + p.lambda_l2)
    out = leaf_output(sum_g, sum_h, count, parent_output, p)
    return leaf_gain_given_output(sum_g, sum_h, out, p)


def leaf_gain_given_output(sum_g, sum_h, out, p: SplitParams):
    """ref: feature_histogram.hpp:820 GetLeafGainGivenOutput."""
    sg_l1 = threshold_l1(sum_g, p.lambda_l1)
    return -(2.0 * sg_l1 * out + (sum_h + p.lambda_l2) * out * out)


def _round_int(x: jnp.ndarray) -> jnp.ndarray:
    """ref: utils/common.h RoundInt: static_cast<int>(x + 0.5)."""
    return jnp.floor(x + 0.5).astype(jnp.int32)


def _cat_best_split(grad, hess, cnt_factor, num_bin, sum_g, sum_h, num_data,
                    parent_output, min_gain_shift, p: SplitParams,
                    rand_u=None):
    """Per-feature best CATEGORICAL split (ref: feature_histogram.cpp:144
    FindBestThresholdCategoricalInner), vectorized over features.

    Bin 0 is the NaN/other bin and never enters a left set (the reference
    scans actual bins [1, num_bin); unseen/NaN categories route right).

    Under extra_trees (USE_RAND), rand_u is [F, 2] uniforms: one draw
    picks the single one-hot candidate bin (rand.NextInt(bin_start,
    bin_end), cpp:187), the other the single sorted-subset prefix length
    (rand.NextInt(0, max_threshold), cpp:268); the group-count reset
    still runs for skipped candidates (cpp:310-317 order).

    Returns per-feature (gain [F], left_g, left_h, left_c, use_onehot,
    onehot_bin, dir_is_fwd, prefix_len, used_bin, sorted_bins [F, B]).
    """
    F, B = grad.shape
    f32 = jnp.float32
    i32 = jnp.int32
    bins = jnp.arange(B, dtype=i32)[None, :]
    # cat_l2-augmented params for the sorted-subset branch only
    pcat = p._replace(lambda_l2=p.lambda_l2 + p.cat_l2)

    in_range = (bins >= 1) & (bins < num_bin[:, None])
    grad = jnp.where(in_range, grad, 0.0)
    hess = jnp.where(in_range, hess, 0.0)
    cnt = jnp.where(in_range, _round_int(hess * cnt_factor), 0)

    def split_gain(lg, lh, lc, rg, rh, rc, ok, pp):
        ok = (ok
              & (lc >= p.min_data_in_leaf)
              & (lh >= p.min_sum_hessian_in_leaf)
              & (rc >= p.min_data_in_leaf)
              & (rh >= p.min_sum_hessian_in_leaf))
        gain = (leaf_gain(lg, lh, lc.astype(f32), parent_output, pp)
                + leaf_gain(rg, rh, rc.astype(f32), parent_output, pp))
        return jnp.where(ok & (gain > min_gain_shift), gain, K_MIN_SCORE)

    # ---- one-hot mode: left = single category (hpp use_onehot branch) ----
    # cat_l2 does NOT apply here: the reference adds it to l2 only in the
    # sorted-subset else-branch (feature_histogram.cpp:250)
    oh_ok = in_range
    if p.extra_trees and rand_u is not None:
        # single random candidate bin in [1, num_bin)
        span = jnp.maximum(num_bin - 1, 1).astype(f32)
        oh_rand = 1 + jnp.clip((rand_u[:, 0] * span).astype(i32), 0,
                               jnp.maximum(num_bin - 2, 0))
        oh_ok = oh_ok & (bins == oh_rand[:, None])
    oh_gain = split_gain(grad, hess + K_EPSILON, cnt,
                         sum_g - grad, sum_h - hess - K_EPSILON,
                         num_data - cnt, oh_ok, p)
    oh_best = jnp.argmax(oh_gain, axis=1).astype(i32)
    take1 = lambda a, idx: jnp.take_along_axis(a, idx[:, None], 1)[:, 0]
    oh_best_gain = take1(oh_gain, oh_best)

    # ---- sorted-subset mode ----
    # categories with enough data, stably sorted by grad/(hess+cat_smooth)
    valid = in_range & (cnt >= p.cat_smooth)
    ratio = jnp.where(valid, grad / (hess + p.cat_smooth), jnp.inf)
    order = jnp.argsort(ratio, axis=1, stable=True).astype(i32)  # [F, B]
    sg_s = jnp.take_along_axis(grad, order, 1)
    sh_s = jnp.take_along_axis(hess, order, 1)
    sc_s = jnp.take_along_axis(cnt, order, 1)
    used_bin = jnp.sum(valid, axis=1, dtype=i32)                # [F]
    max_num_cat = jnp.minimum(p.max_cat_threshold, (used_bin + 1) // 2)
    steps = min(p.max_cat_threshold, B)
    if p.extra_trees and rand_u is not None:
        # single random prefix length in [0, max_threshold) where
        # max_threshold = max(min(max_num_cat, used_bin) - 1, 0)
        max_thr = jnp.maximum(
            jnp.minimum(max_num_cat, used_bin) - 1, 0).astype(f32)
        sub_rand = jnp.clip((rand_u[:, 1] * jnp.maximum(max_thr, 1.0))
                            .astype(i32), 0,
                            jnp.maximum(max_thr.astype(i32) - 1, 0))

    def scan_dir(fwd: bool):
        if fwd:
            g_d, h_d, c_d = sg_s, sh_s, sc_s
        else:  # from the largest-ratio end over the VALID entries
            pos = used_bin[:, None] - 1 - jnp.arange(B, dtype=i32)[None, :]
            idx = jnp.clip(pos, 0, B - 1)
            g_d = jnp.take_along_axis(sg_s, idx, 1)
            h_d = jnp.take_along_axis(sh_s, idx, 1)
            c_d = jnp.take_along_axis(sc_s, idx, 1)

        def step(carry, i):
            cum, lg, lh, lc = carry
            lg = lg + g_d[:, i]
            lh = lh + h_d[:, i]
            lc = lc + c_d[:, i]
            cum = cum + c_d[:, i]
            rc = num_data - lc
            rh = sum_h - lh
            rg = sum_g - lg
            # the reference's break conditions (right side shrinking) are
            # monotone in i, so masking == breaking; the group counter
            # resets whenever a candidate reaches evaluation, even if its
            # gain then fails min_gain_shift (cpp:296-318 order)
            left_ok = ((lc >= p.min_data_in_leaf)
                       & (lh >= p.min_sum_hessian_in_leaf))
            right_ok = ((rc >= p.min_data_in_leaf)
                        & (rc >= p.min_data_per_group)
                        & (rh >= p.min_sum_hessian_in_leaf))
            in_limit = i < jnp.minimum(used_bin, max_num_cat)
            eligible = (in_limit & left_ok & right_ok
                        & (cum >= p.min_data_per_group))
            raw = (leaf_gain(lg, lh, lc.astype(f32), parent_output, pcat)
                   + leaf_gain(rg, rh, rc.astype(f32), parent_output, pcat))
            gain_ok = eligible & (raw > min_gain_shift)
            if p.extra_trees and rand_u is not None:
                # USE_RAND: only the random prefix scores, but the group
                # counter still resets on skipped candidates (cpp:310-317)
                gain_ok = gain_ok & (i == sub_rand)
            gain = jnp.where(gain_ok, raw, K_MIN_SCORE)
            cum = jnp.where(eligible, 0, cum)
            return (cum, lg, lh, lc), (gain, lg, lh, lc)

        init = (jnp.zeros(F, i32), jnp.zeros(F, f32),
                jnp.full(F, K_EPSILON, f32), jnp.zeros(F, i32))
        _, (gains, lgs, lhs, lcs) = jax.lax.scan(
            step, init, jnp.arange(steps, dtype=i32))
        # [steps, F] -> best prefix per feature
        best_i = jnp.argmax(gains, axis=0).astype(i32)
        takeS = lambda a: jnp.take_along_axis(a, best_i[None, :], 0)[0]
        return takeS(gains), takeS(lgs), takeS(lhs), takeS(lcs), best_i

    fw_gain, fw_lg, fw_lh, fw_lc, fw_i = scan_dir(True)
    bw_gain, bw_lg, bw_lh, bw_lc, bw_i = scan_dir(False)
    use_fw = fw_gain >= bw_gain
    so_gain = jnp.where(use_fw, fw_gain, bw_gain)
    so_lg = jnp.where(use_fw, fw_lg, bw_lg)
    so_lh = jnp.where(use_fw, fw_lh, bw_lh)
    so_lc = jnp.where(use_fw, fw_lc, bw_lc)
    so_i = jnp.where(use_fw, fw_i, bw_i)

    # per-feature mode choice is static in num_bin (hpp use_onehot)
    use_onehot = num_bin <= p.max_cat_to_onehot
    gain = jnp.where(use_onehot, oh_best_gain, so_gain)
    left_g = jnp.where(use_onehot, take1(grad, oh_best), so_lg)
    left_h = jnp.where(use_onehot, take1(hess, oh_best) + K_EPSILON, so_lh)
    left_c = jnp.where(use_onehot, take1(cnt, oh_best), so_lc)
    return (gain, left_g, left_h, left_c, use_onehot, oh_best, use_fw,
            so_i, used_bin, order)


@functools.partial(jax.jit,
                   static_argnames=("params", "return_feature_gains"))
def find_best_split(hist: jnp.ndarray, num_bin: jnp.ndarray,
                    missing_type: jnp.ndarray, default_bin: jnp.ndarray,
                    feature_penalty: jnp.ndarray, col_mask: jnp.ndarray,
                    sum_gradient: jnp.ndarray, sum_hessian: jnp.ndarray,
                    num_data: jnp.ndarray, parent_output: jnp.ndarray,
                    params: SplitParams,
                    is_cat_feature: jnp.ndarray = None,
                    rand_bin: jnp.ndarray = None,
                    cegb_coupled: jnp.ndarray = None,
                    cegb_used: jnp.ndarray = None,
                    monotone: jnp.ndarray = None,
                    constraint_min: jnp.ndarray = None,
                    constraint_max: jnp.ndarray = None,
                    constraint_min_left: jnp.ndarray = None,
                    constraint_max_left: jnp.ndarray = None,
                    constraint_min_right: jnp.ndarray = None,
                    constraint_max_right: jnp.ndarray = None,
                    mono_penalty: jnp.ndarray = None,
                    cegb_lazy_cost: jnp.ndarray = None,
                    rand_cat_u: jnp.ndarray = None,
                    return_feature_gains: bool = False) -> SplitResult:
    """Scan all (feature, threshold, direction) candidates; return the leaf's best.

    Args:
      hist: [F, B, 2] (sum_gradient, sum_hessian) per bin.
      num_bin/missing_type/default_bin: [F] int32 per-feature bin metadata.
      feature_penalty: [F] gain multiplier (ref: meta_->penalty, feature_contri).
      col_mask: [F] bool, feature_fraction sampling mask.
      sum_gradient/sum_hessian: leaf totals (hessian WITHOUT the +2eps; added here,
        ref: feature_histogram.hpp:169 FindBestThreshold).
      num_data: actual row count in leaf (int32).
      parent_output: leaf's current output (for path smoothing).
    """
    num_features, max_bin, _ = hist.shape
    f32 = jnp.float32
    sum_g = sum_gradient.astype(f32)
    sum_h = sum_hessian.astype(f32) + 2 * K_EPSILON
    n_leaf = num_data.astype(f32)
    cnt_factor = n_leaf / sum_h

    bins = jnp.arange(max_bin, dtype=jnp.int32)[None, :]           # [1, B]
    nb = num_bin[:, None]
    mt = missing_type[:, None]
    db = default_bin[:, None]
    na_extra = (mt == MISSING_NAN).astype(jnp.int32)               # [F, 1]

    in_range = bins < nb
    is_na_bin = (mt == MISSING_NAN) & (bins == nb - 1)
    is_def_bin = (mt == MISSING_ZERO) & (bins == db)
    acc = in_range & ~is_na_bin & ~is_def_bin
    grad = jnp.where(acc, hist[:, :, 0], 0.0)
    hess = jnp.where(acc, hist[:, :, 1], 0.0)
    cnt = jnp.where(acc, _round_int(hist[:, :, 1] * cnt_factor), 0)

    pg = jnp.cumsum(grad, axis=1)
    ph = jnp.cumsum(hess, axis=1)
    pc = jnp.cumsum(cnt, axis=1)
    tg, th, tc = pg[:, -1:], ph[:, -1:], pc[:, -1:]

    min_gain_shift = (leaf_gain(sum_g, sum_h, n_leaf, parent_output, params)
                      + params.min_gain_to_split)

    def eval_candidates(left_g, left_h_raw, left_c, tau_ok):
        """Gain for candidates where left side = (left_g, left_h_raw+eps, left_c)."""
        left_h = left_h_raw + K_EPSILON
        right_g = sum_g - left_g
        right_h = sum_h - left_h
        right_c = num_data - left_c
        ok = (tau_ok
              & (left_c >= params.min_data_in_leaf)
              & (left_h >= params.min_sum_hessian_in_leaf)
              & (right_c >= params.min_data_in_leaf)
              & (right_h >= params.min_sum_hessian_in_leaf))
        gain = (leaf_gain(left_g, left_h, left_c.astype(f32), parent_output, params)
                + leaf_gain(right_g, right_h, right_c.astype(f32), parent_output,
                            params))
        if params.has_monotone:
            # constrained gain for monotone features: outputs clamped to
            # the leaf's [min, max]; ordering violations score 0
            # (feature_histogram.hpp:758-797 GetSplitGains USE_MC branch).
            # Advanced mode (monotone_constraints.hpp:858
            # AdvancedLeafConstraints) passes PER-CHILD, PER-THRESHOLD
            # [F, B] constraint surfaces instead of the leaf scalar.
            mc = monotone[:, None]
            cmin_l = (constraint_min_left if constraint_min_left is not None
                      else constraint_min)
            cmax_l = (constraint_max_left if constraint_max_left is not None
                      else constraint_max)
            cmin_r = (constraint_min_right
                      if constraint_min_right is not None
                      else constraint_min)
            cmax_r = (constraint_max_right
                      if constraint_max_right is not None
                      else constraint_max)
            lout = jnp.clip(leaf_output(left_g, left_h, left_c.astype(f32),
                                        parent_output, params),
                            cmin_l, cmax_l)
            rout = jnp.clip(leaf_output(right_g, right_h,
                                        right_c.astype(f32),
                                        parent_output, params),
                            cmin_r, cmax_r)
            bad = (((mc > 0) & (lout > rout)) | ((mc < 0) & (lout < rout)))
            # clamping applies to EVERY feature once the leaf is
            # constrained (USE_MC templates the whole learner); the
            # ordering rejection only to monotone features
            gain_mc = (leaf_gain_given_output(left_g, left_h, lout, params)
                       + leaf_gain_given_output(right_g, right_h, rout,
                                                params))
            gain = jnp.where(bad & (mc != 0), 0.0, gain_mc)
        ok = ok & (gain > min_gain_shift)
        return jnp.where(ok, gain, K_MIN_SCORE)

    # ---- canonical tie-break across empty-bin runs -------------------------
    # Candidate thresholds separated only by EMPTY bins (zero accumulated
    # grad/hess/count between them) induce the identical row partition; in
    # the reference's sequential scan their left sums tie bit-exactly, so
    # its strict `>` keeps the first-visited candidate (largest tau for
    # REVERSE, smallest for forward).  jnp.cumsum is a TREE scan: the
    # prefix sums at two such candidates can disagree in the last ulp, and
    # which side the noise lands on depends on the summands — a serial and
    # a psum'd (data-parallel) histogram can therefore flip the argmax
    # between truly-tied thresholds (the test_parallel threshold
    # "off-by-two").  Snap the winner to its run's canonical end; the
    # partition is unchanged by construction, so only the float payload
    # moves (by ulps).
    nonempty = (grad != 0.0) | (hess != 0.0) | (cnt != 0)
    last_ne = jax.lax.cummax(jnp.where(nonempty, bins, -1), axis=1)

    def snap_over_empty(best_idx, gain_2d, up):
        t0 = best_idx[:, None]
        valid = gain_2d > K_MIN_SCORE  # candidate passed every gate
        if up:
            run = valid & (bins >= t0) & (last_ne <= t0)
            return jnp.max(jnp.where(run, bins, t0), axis=1)
        lo = jnp.take_along_axis(last_ne, t0, 1)  # last non-empty <= t0
        run = valid & (bins <= t0) & (bins >= lo)
        return jnp.min(jnp.where(run, bins, t0), axis=1)

    # ---- REVERSE scan: left = bins <= tau (+NaN, +zero-bin when default_left) ----
    # right side accumulates bins > tau; candidate at threshold tau = t-1
    # (ref: hpp:856-930), so left sums are the inclusive prefix at tau.
    rev_tau_ok = (bins <= nb - 2 - na_extra) & in_range
    rev_tau_ok &= ~((mt == MISSING_ZERO) & (bins == db - 1))  # skipped iteration
    if params.extra_trees:
        # only the leaf's random threshold is a candidate (USE_RAND:
        # hpp:899 `t - 1 + offset != rand_threshold -> continue`)
        rev_tau_ok &= bins == rand_bin[:, None]
    # REVERSE accumulates right_h = kEps + suffix; left_h = sum_h - right_h.
    # eval_candidates re-adds its own eps to the raw left, so raw subtracts both.
    rev_left_g = sum_g - (tg - pg)
    rev_left_h_raw = sum_h - (th - ph) - 2 * K_EPSILON
    rev_left_c = num_data - (tc - pc)
    rev_gain = eval_candidates(rev_left_g, rev_left_h_raw, rev_left_c, rev_tau_ok)
    # tie-break: largest tau wins (scan visits from the right)
    rev_best_idx = (max_bin - 1
                    - jnp.argmax(rev_gain[:, ::-1], axis=1)).astype(jnp.int32)
    rev_best_idx = snap_over_empty(rev_best_idx, rev_gain, up=True)
    rev_best_gain = jnp.take_along_axis(rev_gain, rev_best_idx[:, None], 1)[:, 0]

    # ---- FORWARD scan: left = inclusive prefix at tau; missing goes right ----
    fwd_tau_ok = (bins <= nb - 2) & in_range & (mt != MISSING_NONE)
    fwd_tau_ok &= ~((mt == MISSING_ZERO) & (bins == db))      # skipped iteration
    if params.extra_trees:
        fwd_tau_ok &= bins == rand_bin[:, None]
    fwd_gain = eval_candidates(pg, ph, pc, fwd_tau_ok)
    fwd_best_idx = jnp.argmax(fwd_gain, axis=1).astype(jnp.int32)
    fwd_best_idx = snap_over_empty(fwd_best_idx, fwd_gain, up=False)
    fwd_best_gain = jnp.take_along_axis(fwd_gain, fwd_best_idx[:, None], 1)[:, 0]

    # forward replaces reverse only on strictly larger gain (ref: hpp:1031)
    use_fwd = fwd_best_gain > rev_best_gain
    best_gain_f = jnp.where(use_fwd, fwd_best_gain, rev_best_gain)
    best_thr_f = jnp.where(use_fwd, fwd_best_idx, rev_best_idx)
    # per-feature left sums at the winning threshold
    take = lambda a, idx: jnp.take_along_axis(a, idx[:, None], 1)[:, 0]
    lg = jnp.where(use_fwd, take(pg, fwd_best_idx), take(rev_left_g, rev_best_idx))
    lh_raw = jnp.where(use_fwd, take(ph, fwd_best_idx),
                       take(rev_left_h_raw, rev_best_idx))
    lc = jnp.where(use_fwd, take(pc, fwd_best_idx), take(rev_left_c, rev_best_idx))
    default_left_f = ~use_fwd

    W = cat_bitset_words(max_bin)
    if params.has_categorical:
        # the expensive scan (argsort + two sequential prefix scans) runs
        # only over the categorical rows, gathered into a static
        # F_cat-sized subarray; results scatter back into the [F] arrays
        is_cat_f = is_cat_feature
        cat_idx = (params.cat_features if params.cat_features
                   else tuple(range(num_features)))
        ci = jnp.asarray(cat_idx, jnp.int32)
        (cgain, clg, clh, clc, c_onehot, c_ohbin, c_fwd, c_plen, c_ub,
         c_order) = _cat_best_split(
            hist[ci, :, 0], hist[ci, :, 1], cnt_factor,
            num_bin[ci], sum_g, sum_h, num_data, parent_output,
            min_gain_shift, params,
            rand_u=None if rand_cat_u is None else rand_cat_u[ci])
        # categorical features replace their numerical scan results;
        # double-guard with is_cat_f (a numerical feature listed in
        # cat_features must keep its numerical result)
        catset = jnp.zeros(num_features, bool).at[ci].set(True) & is_cat_f
        best_gain_f = jnp.where(catset, best_gain_f.at[ci].set(cgain),
                                best_gain_f)
        lg = jnp.where(catset, lg.at[ci].set(clg), lg)
        lh_raw = jnp.where(catset, lh_raw.at[ci].set(clh - K_EPSILON),
                           lh_raw)
        lc = jnp.where(catset, lc.at[ci].set(clc), lc)
        default_left_f = jnp.where(catset, False, default_left_f)
        # map a winning full-F index back to its compact cat row
        pos_of_f = jnp.zeros(num_features, jnp.int32).at[ci].set(
            jnp.arange(len(cat_idx), dtype=jnp.int32))

    # feature penalty + column sampling, then pick the best feature
    # (gain tie -> smaller index, matching SplitInfo::operator>)
    shifted = (best_gain_f - min_gain_shift) * feature_penalty
    if params.has_cegb:
        # ref: serial_tree_learner.cpp:983 new_split.gain -= DeltaGain(...)
        delta = params.cegb_tradeoff * (
            params.cegb_penalty_split * num_data.astype(f32))
        if cegb_coupled is not None:
            delta = delta + params.cegb_tradeoff * jnp.where(
                cegb_used, 0.0, cegb_coupled)
        if params.has_cegb_lazy and cegb_lazy_cost is not None:
            # ref: cost_effective_gradient_boosting.hpp:91 DeltaGain's
            # CalculateOndemandCosts term
            delta = delta + params.cegb_tradeoff * cegb_lazy_cost
        shifted = shifted - delta
    if params.has_monotone and params.monotone_penalty > 0:
        # depth-based penalty on monotone features' gains
        # (serial_tree_learner.cpp:987-991)
        shifted = jnp.where(monotone != 0, shifted * mono_penalty, shifted)
    shifted = jnp.where(col_mask & (best_gain_f > K_MIN_SCORE), shifted, K_MIN_SCORE)
    if return_feature_gains:
        # per-feature shifted best gains, for the voting-parallel learner's
        # local vote (ref: voting_parallel_tree_learner.cpp:151 GlobalVoting
        # ranks features by their local best split gains)
        return shifted
    best_f = jnp.argmax(shifted, axis=0).astype(jnp.int32)

    g_ = shifted[best_f]
    lg_, lc_ = lg[best_f], lc[best_f]
    lh_ = lh_raw[best_f] + K_EPSILON
    rg_, rc_ = sum_g - lg_, num_data - lc_
    rh_ = sum_h - lh_

    if params.has_categorical:
        won_cat = catset[best_f]
        cpos = pos_of_f[best_f]          # winner's compact cat row
        # leaf outputs use lambda_l2 + cat_l2 only for sorted-subset
        # categorical winners, not one-hot (feature_histogram.cpp:250)
        pcat = params._replace(lambda_l2=params.lambda_l2 + params.cat_l2)
        won_subset = won_cat & ~c_onehot[cpos]
        left_out = jnp.where(
            won_subset,
            leaf_output(lg_, lh_, lc_.astype(f32), parent_output, pcat),
            leaf_output(lg_, lh_, lc_.astype(f32), parent_output, params))
        right_out = jnp.where(
            won_subset,
            leaf_output(rg_, rh_, rc_.astype(f32), parent_output, pcat),
            leaf_output(rg_, rh_, rc_.astype(f32), parent_output, params))
        # winning left-category set as a bin bitset (ref: split_info.hpp
        # cat_threshold; bins, not raw category values, on device)
        bins_b = jnp.arange(max_bin, dtype=jnp.int32)
        sorted_w = c_order[cpos]                         # [B] sorted bins
        ub = c_ub[cpos]
        plen = c_plen[cpos] + 1
        pos = jnp.arange(max_bin, dtype=jnp.int32)
        in_set_sorted = jnp.where(
            c_fwd[cpos], pos < plen, (pos >= ub - plen) & (pos < ub))
        member = jnp.zeros(max_bin, bool).at[sorted_w].set(
            in_set_sorted, mode="drop")
        member = jnp.where(c_onehot[cpos],
                           bins_b == c_ohbin[cpos], member)
        member = member & won_cat
        word_idx = bins_b // 32
        bit = (member.astype(jnp.int32) << (bins_b % 32))
        cat_bitset = jnp.zeros(W, jnp.int32).at[word_idx].add(bit)
        is_cat_out = won_cat
        thr_out = jnp.where(won_cat, 0, best_thr_f[best_f])
    else:
        left_out = leaf_output(lg_, lh_, lc_.astype(f32), parent_output,
                               params)
        right_out = leaf_output(rg_, rh_, rc_.astype(f32), parent_output,
                                params)
        cat_bitset = jnp.zeros(W, jnp.int32)
        is_cat_out = jnp.asarray(False)
        thr_out = best_thr_f[best_f]

    if params.has_monotone:
        # the leaf's [min, max] clamps the winner's stored outputs too
        # (CalculateSplittedLeafOutput USE_MC, feature_histogram.hpp:740).
        # Advanced mode clamps with the constraint surface AT the winning
        # (feature, threshold); categorical winners keep the conservative
        # whole-leaf scalar (their surfaces are threshold-indexed).
        if constraint_min_left is not None:
            thr_n = best_thr_f[best_f]
            lmin_w = jnp.where(is_cat_out, constraint_min,
                               constraint_min_left[best_f, thr_n])
            lmax_w = jnp.where(is_cat_out, constraint_max,
                               constraint_max_left[best_f, thr_n])
            rmin_w = jnp.where(is_cat_out, constraint_min,
                               constraint_min_right[best_f, thr_n])
            rmax_w = jnp.where(is_cat_out, constraint_max,
                               constraint_max_right[best_f, thr_n])
            left_out = jnp.clip(left_out, lmin_w, lmax_w)
            right_out = jnp.clip(right_out, rmin_w, rmax_w)
        else:
            left_out = jnp.clip(left_out, constraint_min, constraint_max)
            right_out = jnp.clip(right_out, constraint_min, constraint_max)

    return SplitResult(
        gain=g_, feature=best_f, threshold=thr_out,
        default_left=default_left_f[best_f],
        left_sum_gradient=lg_, left_sum_hessian=lh_ - K_EPSILON,
        left_count=lc_, left_output=left_out,
        right_sum_gradient=rg_, right_sum_hessian=rh_ - K_EPSILON,
        right_count=rc_, right_output=right_out,
        is_cat=is_cat_out, cat_bitset=cat_bitset)
