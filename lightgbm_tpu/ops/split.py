"""Best-split finding: the reference's sequential per-bin gain scan, vectorized.

TPU-native replacement for FeatureHistogram::FindBestThresholdSequentially
(ref: src/treelearner/feature_histogram.hpp:831-1057) and the CUDA kernels
FindBestSplitsForLeafKernel / SyncBestSplitForLeafKernel
(ref: src/treelearner/cuda/cuda_best_split_finder.cu:772,1920): instead of a
serial loop per feature, both scan directions are evaluated for ALL features
and ALL candidate thresholds at once via masked prefix/suffix cumsums, then a
single argmax picks the winner — the shape XLA tiles well.

Behavioral parity notes (each mirrors a reference line):
  * counts are derived from hessians: cnt(bin) = RoundInt(hess * cnt_factor),
    cnt_factor = num_data / sum_hessian (feature_histogram.hpp:871-874).
  * accumulators are seeded with kEpsilon=1e-15 and the leaf hessian carries
    +2*kEpsilon (feature_histogram.hpp:169-171, 856, 941).
  * REVERSE scan (default_left=True) excludes the NaN bin so missing joins the
    left side; the forward scan leaves it on the right (hpp:859-867, 946-963).
  * MissingType::Zero skips the zero ("default") bin in both scans, so the zero
    bin always follows default_left (hpp:865-869 SKIP_DEFAULT_BIN).
  * `break` conditions (left side runs out of data/hessian) are monotone in the
    threshold, so masking is exactly equivalent to breaking.
  * within a scan, ties keep the first-visited threshold: largest for REVERSE,
    smallest for forward; the forward result replaces the reverse one only on
    strictly larger gain (hpp:1031).
  * across features, gain ties pick the smaller feature index
    (split_info.hpp:138-163 operator>).

The scan works in the "full bin" layout (bins 0..num_bin-1 present for every
feature, no most_freq_bin offset packing) — equivalent results, simpler tensors.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

K_EPSILON = 1e-15  # ref: include/LightGBM/meta.h:54
K_MIN_SCORE = -jnp.inf

MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2


class SplitParams(NamedTuple):
    """Static split hyperparameters (subset of ref Config used by the gain scan)."""
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    path_smooth: float = 0.0


class SplitResult(NamedTuple):
    """Device-side SplitInfo (ref: src/treelearner/split_info.hpp:22)."""
    gain: jnp.ndarray            # shifted gain (<=0 means no valid split)
    feature: jnp.ndarray         # inner feature index (int32)
    threshold: jnp.ndarray       # bin threshold (int32)
    default_left: jnp.ndarray    # bool
    left_sum_gradient: jnp.ndarray
    left_sum_hessian: jnp.ndarray
    left_count: jnp.ndarray      # int32
    left_output: jnp.ndarray
    right_sum_gradient: jnp.ndarray
    right_sum_hessian: jnp.ndarray
    right_count: jnp.ndarray     # int32
    right_output: jnp.ndarray


def threshold_l1(s: jnp.ndarray, l1: float) -> jnp.ndarray:
    """ref: feature_histogram.hpp:710 ThresholdL1."""
    return jnp.sign(s) * jnp.maximum(jnp.abs(s) - l1, 0.0)


def leaf_output(sum_g, sum_h, count, parent_output, p: SplitParams):
    """ref: feature_histogram.hpp:716 CalculateSplittedLeafOutput."""
    ret = -threshold_l1(sum_g, p.lambda_l1) / (sum_h + p.lambda_l2)
    if p.max_delta_step > 0:
        ret = jnp.clip(ret, -p.max_delta_step, p.max_delta_step)
    if p.path_smooth > K_EPSILON:
        ratio = count.astype(ret.dtype) / p.path_smooth
        ret = ret * ratio / (ratio + 1.0) + parent_output / (ratio + 1.0)
    return ret


def leaf_gain(sum_g, sum_h, count, parent_output, p: SplitParams):
    """ref: feature_histogram.hpp:800 GetLeafGain."""
    if p.max_delta_step <= 0 and p.path_smooth <= K_EPSILON:
        sg_l1 = threshold_l1(sum_g, p.lambda_l1)
        return (sg_l1 * sg_l1) / (sum_h + p.lambda_l2)
    out = leaf_output(sum_g, sum_h, count, parent_output, p)
    sg_l1 = threshold_l1(sum_g, p.lambda_l1)
    return -(2.0 * sg_l1 * out + (sum_h + p.lambda_l2) * out * out)


def _round_int(x: jnp.ndarray) -> jnp.ndarray:
    """ref: utils/common.h RoundInt: static_cast<int>(x + 0.5)."""
    return jnp.floor(x + 0.5).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("params",))
def find_best_split(hist: jnp.ndarray, num_bin: jnp.ndarray,
                    missing_type: jnp.ndarray, default_bin: jnp.ndarray,
                    feature_penalty: jnp.ndarray, col_mask: jnp.ndarray,
                    sum_gradient: jnp.ndarray, sum_hessian: jnp.ndarray,
                    num_data: jnp.ndarray, parent_output: jnp.ndarray,
                    params: SplitParams) -> SplitResult:
    """Scan all (feature, threshold, direction) candidates; return the leaf's best.

    Args:
      hist: [F, B, 2] (sum_gradient, sum_hessian) per bin.
      num_bin/missing_type/default_bin: [F] int32 per-feature bin metadata.
      feature_penalty: [F] gain multiplier (ref: meta_->penalty, feature_contri).
      col_mask: [F] bool, feature_fraction sampling mask.
      sum_gradient/sum_hessian: leaf totals (hessian WITHOUT the +2eps; added here,
        ref: feature_histogram.hpp:169 FindBestThreshold).
      num_data: actual row count in leaf (int32).
      parent_output: leaf's current output (for path smoothing).
    """
    num_features, max_bin, _ = hist.shape
    f32 = jnp.float32
    sum_g = sum_gradient.astype(f32)
    sum_h = sum_hessian.astype(f32) + 2 * K_EPSILON
    n_leaf = num_data.astype(f32)
    cnt_factor = n_leaf / sum_h

    bins = jnp.arange(max_bin, dtype=jnp.int32)[None, :]           # [1, B]
    nb = num_bin[:, None]
    mt = missing_type[:, None]
    db = default_bin[:, None]
    na_extra = (mt == MISSING_NAN).astype(jnp.int32)               # [F, 1]

    in_range = bins < nb
    is_na_bin = (mt == MISSING_NAN) & (bins == nb - 1)
    is_def_bin = (mt == MISSING_ZERO) & (bins == db)
    acc = in_range & ~is_na_bin & ~is_def_bin
    grad = jnp.where(acc, hist[:, :, 0], 0.0)
    hess = jnp.where(acc, hist[:, :, 1], 0.0)
    cnt = jnp.where(acc, _round_int(hist[:, :, 1] * cnt_factor), 0)

    pg = jnp.cumsum(grad, axis=1)
    ph = jnp.cumsum(hess, axis=1)
    pc = jnp.cumsum(cnt, axis=1)
    tg, th, tc = pg[:, -1:], ph[:, -1:], pc[:, -1:]

    min_gain_shift = (leaf_gain(sum_g, sum_h, n_leaf, parent_output, params)
                      + params.min_gain_to_split)

    def eval_candidates(left_g, left_h_raw, left_c, tau_ok):
        """Gain for candidates where left side = (left_g, left_h_raw+eps, left_c)."""
        left_h = left_h_raw + K_EPSILON
        right_g = sum_g - left_g
        right_h = sum_h - left_h
        right_c = num_data - left_c
        ok = (tau_ok
              & (left_c >= params.min_data_in_leaf)
              & (left_h >= params.min_sum_hessian_in_leaf)
              & (right_c >= params.min_data_in_leaf)
              & (right_h >= params.min_sum_hessian_in_leaf))
        gain = (leaf_gain(left_g, left_h, left_c.astype(f32), parent_output, params)
                + leaf_gain(right_g, right_h, right_c.astype(f32), parent_output,
                            params))
        ok = ok & (gain > min_gain_shift)
        return jnp.where(ok, gain, K_MIN_SCORE)

    # ---- REVERSE scan: left = bins <= tau (+NaN, +zero-bin when default_left) ----
    # right side accumulates bins > tau; candidate at threshold tau = t-1
    # (ref: hpp:856-930), so left sums are the inclusive prefix at tau.
    rev_tau_ok = (bins <= nb - 2 - na_extra) & in_range
    rev_tau_ok &= ~((mt == MISSING_ZERO) & (bins == db - 1))  # skipped iteration
    # REVERSE accumulates right_h = kEps + suffix; left_h = sum_h - right_h.
    # eval_candidates re-adds its own eps to the raw left, so raw subtracts both.
    rev_left_g = sum_g - (tg - pg)
    rev_left_h_raw = sum_h - (th - ph) - 2 * K_EPSILON
    rev_left_c = num_data - (tc - pc)
    rev_gain = eval_candidates(rev_left_g, rev_left_h_raw, rev_left_c, rev_tau_ok)
    # tie-break: largest tau wins (scan visits from the right)
    rev_best_idx = (max_bin - 1
                    - jnp.argmax(rev_gain[:, ::-1], axis=1)).astype(jnp.int32)
    rev_best_gain = jnp.take_along_axis(rev_gain, rev_best_idx[:, None], 1)[:, 0]

    # ---- FORWARD scan: left = inclusive prefix at tau; missing goes right ----
    fwd_tau_ok = (bins <= nb - 2) & in_range & (mt != MISSING_NONE)
    fwd_tau_ok &= ~((mt == MISSING_ZERO) & (bins == db))      # skipped iteration
    fwd_gain = eval_candidates(pg, ph, pc, fwd_tau_ok)
    fwd_best_idx = jnp.argmax(fwd_gain, axis=1).astype(jnp.int32)
    fwd_best_gain = jnp.take_along_axis(fwd_gain, fwd_best_idx[:, None], 1)[:, 0]

    # forward replaces reverse only on strictly larger gain (ref: hpp:1031)
    use_fwd = fwd_best_gain > rev_best_gain
    best_gain_f = jnp.where(use_fwd, fwd_best_gain, rev_best_gain)
    best_thr_f = jnp.where(use_fwd, fwd_best_idx, rev_best_idx)
    # per-feature left sums at the winning threshold
    take = lambda a, idx: jnp.take_along_axis(a, idx[:, None], 1)[:, 0]
    lg = jnp.where(use_fwd, take(pg, fwd_best_idx), take(rev_left_g, rev_best_idx))
    lh_raw = jnp.where(use_fwd, take(ph, fwd_best_idx),
                       take(rev_left_h_raw, rev_best_idx))
    lc = jnp.where(use_fwd, take(pc, fwd_best_idx), take(rev_left_c, rev_best_idx))

    # feature penalty + column sampling, then pick the best feature
    # (gain tie -> smaller index, matching SplitInfo::operator>)
    shifted = (best_gain_f - min_gain_shift) * feature_penalty
    shifted = jnp.where(col_mask & (best_gain_f > K_MIN_SCORE), shifted, K_MIN_SCORE)
    best_f = jnp.argmax(shifted, axis=0).astype(jnp.int32)

    g_ = shifted[best_f]
    lg_, lc_ = lg[best_f], lc[best_f]
    lh_ = lh_raw[best_f] + K_EPSILON
    rg_, rc_ = sum_g - lg_, num_data - lc_
    rh_ = sum_h - lh_
    left_out = leaf_output(lg_, lh_, lc_.astype(f32), parent_output, params)
    right_out = leaf_output(rg_, rh_, rc_.astype(f32), parent_output, params)
    return SplitResult(
        gain=g_, feature=best_f, threshold=best_thr_f[best_f],
        default_left=~use_fwd[best_f],
        left_sum_gradient=lg_, left_sum_hessian=lh_ - K_EPSILON,
        left_count=lc_, left_output=left_out,
        right_sum_gradient=rg_, right_sum_hessian=rh_ - K_EPSILON,
        right_count=rc_, right_output=right_out)
