"""Distributed training over jax.sharding meshes.

TPU-native replacement for src/network/ (socket/MPI collectives, ref:
network.h:89-275) and the three parallel tree learners (ref:
feature_parallel_tree_learner.cpp, data_parallel_tree_learner.cpp,
voting_parallel_tree_learner.cpp): instead of hand-rolled Bruck allgather /
recursive-halving reduce-scatter over TCP, rows are sharded over a mesh axis
and XLA inserts the psum/all_gather collectives over ICI/DCN.
"""

from .binning import merged_bin_mappers, sample_rows
from .data_parallel import (data_parallel_shardings, grow_params_for_mesh,
                            make_mesh, make_sharded_wave_fn,
                            shard_for_data_parallel)
from .elastic import ReshardPlan, ShardSegment, reshard_plan, rows_of

__all__ = [
    "merged_bin_mappers", "sample_rows", "data_parallel_shardings",
    "grow_params_for_mesh", "make_mesh", "make_sharded_wave_fn",
    "shard_for_data_parallel",
    "ReshardPlan", "ShardSegment", "reshard_plan", "rows_of"]
