"""shard_map across jax versions.

`jax.shard_map` is the stable entry point on current jax; older
releases (<= 0.4.x, the CPU container's pin) only ship
`jax.experimental.shard_map.shard_map`, whose replication-checker
keyword is `check_rep` instead of `check_vma`.  Every shard_map in the
package goes through this wrapper so the sharded engines run on both
runtimes — the virtual 8-device CPU mesh the tests use and the real
TPU driver.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    native = getattr(jax, "shard_map", None)
    if native is not None:
        return native(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
