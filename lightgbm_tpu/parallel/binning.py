"""Distributed binning for multi-host data loading.

The reference shards bin-FINDING across ranks and allgathers the bin
mappers (ref: dataset_loader.cpp:1070 ConstructBinMappersFromTextData:
rank k finds bins for its feature block, then Network::Allgather merges
the serialized mappers).  Under JAX's single-controller SPMD model the
natural equivalent is sample-replicated binning: each host samples its
local row shard, the small samples are allgathered
(bin_construct_sample_cnt rows total), and every host computes IDENTICAL
mappers deterministically from the merged sample — no mapper
serialization, and cross-rank determinism holds by construction (the
property the reference's SyncUpGlobalBestSplit relies on downstream).
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..io.binning import (BIN_NUMERICAL, BinMapper,
                          prep_find_bin_values)


def sample_rows(X_local: np.ndarray, sample_cnt: int,
                seed: int = 1) -> np.ndarray:
    """Per-host row sample (ref: dataset_loader.cpp:1022
    SampleTextDataFromFile)."""
    n = X_local.shape[0]
    if n <= sample_cnt:
        return np.asarray(X_local)
    rng = np.random.RandomState(seed)
    return np.asarray(X_local)[rng.choice(n, sample_cnt, replace=False)]


def merged_bin_mappers(local_samples: Sequence[np.ndarray],
                       max_bin: int = 255, min_data_in_bin: int = 3,
                       bin_types: Sequence[int] = None,
                       **find_kwargs) -> List[BinMapper]:
    """Bin mappers every rank agrees on, from the allgathered per-host
    samples.  `local_samples` stands in for the result of an all_gather
    over hosts (in-process here; jax.experimental.multihost_utils.
    process_allgather in a real multi-host job).  `bin_types` gives each
    feature's BIN_NUMERICAL/BIN_CATEGORICAL type (numerical default)."""
    merged = np.concatenate([np.asarray(s, np.float64)
                             for s in local_samples], axis=0)
    total = merged.shape[0]
    mappers = []
    for f in range(merged.shape[1]):
        col = merged[:, f]
        btype = (bin_types[f] if bin_types is not None else BIN_NUMERICAL)
        vals = (prep_find_bin_values(col) if btype == BIN_NUMERICAL
                else col)
        m = BinMapper()
        m.find_bin(vals, total, max_bin,
                   min_data_in_bin=min_data_in_bin,
                   bin_type=btype, **find_kwargs)
        mappers.append(m)
    return mappers
