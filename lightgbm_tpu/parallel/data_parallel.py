"""Data-parallel GBDT: rows sharded over a 1-D mesh (ref: SURVEY.md §2.3 #3).

Mapping from the reference's DataParallelTreeLearner
(ref: src/treelearner/data_parallel_tree_learner.cpp):

  reference (socket collectives)              TPU (XLA collectives over mesh)
  ------------------------------------------- -------------------------------
  rows pre-partitioned per machine            binned [F, n] sharded on axis n
  local histograms then Network::ReduceScatter  histogram = reduction over the
    + HistogramSumReducer (:284)                sharded row axis -> GSPMD psum
  SyncUpGlobalBestSplit allreduce of           best-split argmax runs on the
    serialized SplitInfo (:441)                 replicated [F,B,2] histogram:
                                                no explicit sync needed
  root sums Allreduce in BeforeTrain (:167)    jnp.sum over sharded axis
  global_data_count_in_leaf_ tracking (:450)   actual counts psum'd the same way

Because `grow_tree` touches sharded data only through row-axis reductions
(histograms, sums, counts) and row-wise maps (recoloring), annotating the row
axis is sufficient: XLA partitions the program SPMD and the collectives ride
ICI — there is no separate "distributed learner" class, which is the point of
the redesign.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._shard_compat import shard_map


DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None,
              devices=None) -> Mesh:
    """1-D data-parallel mesh (multi-axis meshes come with feature-parallel)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None and len(devices) < n_devices:
            # fall back to the virtual CPU devices (multi-chip dry-run model)
            try:
                devices = jax.devices("cpu")
            except RuntimeError:
                pass
        if n_devices is not None:
            if len(devices) < n_devices:
                raise RuntimeError(
                    f"need {n_devices} devices, have {len(devices)}")
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (DATA_AXIS,))


def grow_params_for_mesh(params):
    """Adjust GrowParams for sharded rows: the partitioned-segment engine
    gathers rows by global index, which under GSPMD would all-gather the
    binned matrix per split — so sharded training uses the masked engine
    (compact_min=0), whose only row-axis ops are reductions and maps."""
    return params._replace(compact_min=0)


def make_sharded_wave_fn(mesh: Mesh, donate: bool = False):
    """Wave engine under explicit jax.shard_map over the data axis — the
    DEFAULT (Pallas) engine's distributed form.

    GSPMD cannot partition a pallas_call, so annotation-only sharding had
    to fall back to the leaf-wise/segment engine.  shard_map instead runs
    the per-shard Pallas histogram kernel on each device's local rows and
    the engine psums the computed-slot histograms (wave.py `_psum`) —
    exactly the reference's ReduceScatter of the same histograms its
    serial learner computes (ref: data_parallel_tree_learner.cpp:282-295
    HistogramSumReducer; :441 SyncUpGlobalBestSplit is a no-op here
    because the gain scan runs replicated on the reduced histograms).

    Returns a callable with the `_grow_fn` signature
    (binned, grad, hess, row_mask, col_mask, meta, params, **kw);
    jit-compiled once per (params, extra-kw-set) pair.
    """
    import functools

    @functools.lru_cache(maxsize=None)
    def _build(params, keys):
        from ..learner.wave import grow_tree_wave_impl
        sh_params = params._replace(data_axis=DATA_AXIS)

        def inner(binned, grad, hess, row_mask, col_mask, meta, *extras):
            return grow_tree_wave_impl(binned, grad, hess, row_mask,
                                       col_mask, meta, sh_params,
                                       **dict(zip(keys, extras)))

        ax = DATA_AXIS
        # tree arrays replicated (every shard computes identical
        # bookkeeping from the psum'd histograms); leaf_id stays sharded.
        # check_vma off: replication of the tree outputs is by
        # construction (all inputs to the bookkeeping are psum results),
        # which the static checker cannot see through the Pallas calls.
        mapped = shard_map(
            inner, mesh=mesh,
            in_specs=(P(None, ax), P(ax), P(ax), P(ax), P(), P())
            + (P(),) * len(keys),
            out_specs=(P(), P(ax)),
            check_vma=False)
        if not donate:
            return jax.jit(mapped)
        # donated buffers entering a shard_map'd entry must carry
        # EXPLICIT shardings: leaving XLA to infer the donated layout
        # from the arguments is the donation x SPMD interaction the
        # MULTICHIP_r05 round implicated (tpulint spmd-axis-discipline
        # enforces this statically).  The sharded grad/hess slices die
        # at the grow call, like the single-device donated entry
        # (learner/wave.py).
        row = NamedSharding(mesh, P(ax))
        repl = NamedSharding(mesh, P())
        return jax.jit(
            mapped,
            in_shardings=(NamedSharding(mesh, P(None, ax)), row, row,
                          row, repl, repl) + (repl,) * len(keys),
            donate_argnums=(1, 2))

    def call(binned, grad, hess, row_mask, col_mask, meta, params,
             cegb_used=None, extra_tag=None, quant_scales=None):
        opt = (("cegb_used", cegb_used), ("extra_tag", extra_tag),
               ("quant_scales", quant_scales))
        keys = tuple(k for k, v in opt if v is not None)
        extras = tuple(v for _, v in opt if v is not None)
        import jax.numpy as jnp
        extras = tuple(jnp.asarray(e) for e in extras)
        return _build(params, keys)(binned, grad, hess, row_mask,
                                    col_mask, meta, *extras)

    # expose the jitted builder so tests can .lower() the EXACT
    # production shard_map (specs included) for collective accounting
    call.build = _build
    return call


def data_parallel_shardings(mesh: Mesh) -> Tuple:
    """(binned, per-row vectors, replicated) shardings for grow_tree args."""
    row = NamedSharding(mesh, P(DATA_AXIS))
    feat_by_row = NamedSharding(mesh, P(None, DATA_AXIS))
    repl = NamedSharding(mesh, P())
    return feat_by_row, row, repl


def shard_for_data_parallel(mesh: Mesh, binned, grad, hess, row_mask):
    """Place the per-row tensors on the mesh; n must divide the mesh size."""
    feat_by_row, row, _ = data_parallel_shardings(mesh)
    return (jax.device_put(binned, feat_by_row),
            jax.device_put(grad, row),
            jax.device_put(hess, row),
            jax.device_put(row_mask, row))
