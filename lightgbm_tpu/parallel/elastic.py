"""Deterministic row resharding for elastic world-size changes.

When the distributed supervisor shrinks a cluster around a permanently
lost rank (docs/Reliability.md §Elastic recovery), every surviving rank
must agree — without any communication — on how the training rows map
onto the new, smaller mesh.  The reference engine cannot do this at all:
its `Network::Init` ring is sized once and a lost machine ends the run.

`reshard_plan` is that agreement: a pure function of
`(old_n, new_n, num_rows)` only, so every rank (and the supervising
parent) computes the identical plan from the checkpoint's recorded row
count.  Rows are balanced-contiguous partitioned exactly like
`np.array_split`: shard `i` of `k` owns `rows_of(num_rows, k, i)`, the
same block layout GSPMD produces for a 1-D row sharding, so the plan
doubles as documentation of which host held which rows before and after
the shrink.
"""

from __future__ import annotations

from typing import List, NamedTuple, Tuple


def rows_of(num_rows: int, world: int, rank: int) -> Tuple[int, int]:
    """[start, stop) of the contiguous row block shard `rank` of `world`
    owns — balanced like np.array_split: the first `num_rows % world`
    shards get one extra row."""
    if world <= 0:
        raise ValueError(f"world size must be positive, got {world}")
    if not 0 <= rank < world:
        raise ValueError(f"rank {rank} outside world of {world}")
    q, r = divmod(int(num_rows), world)
    start = rank * q + min(rank, r)
    return start, start + q + (1 if rank < r else 0)


class ShardSegment(NamedTuple):
    """One contiguous run of rows moving (old_rank -> new_rank)."""
    new_rank: int
    old_rank: int
    start: int   # global row index, inclusive
    stop: int    # global row index, exclusive


class ReshardPlan(NamedTuple):
    old_n: int
    new_n: int
    num_rows: int
    segments: Tuple[ShardSegment, ...]

    def sources_of(self, new_rank: int) -> List[ShardSegment]:
        return [s for s in self.segments if s.new_rank == new_rank]

    def moved_rows(self) -> int:
        """Rows whose owner changed — the D2D/DCN traffic a live
        reshard would pay (informational; the local launcher reloads
        from host arrays instead)."""
        return sum(s.stop - s.start for s in self.segments
                   if s.old_rank != s.new_rank)

    def summary(self) -> dict:
        """Compact JSON-able form for the `elastic_shrink` event."""
        return {"old_n": self.old_n, "new_n": self.new_n,
                "num_rows": self.num_rows,
                "moved_rows": self.moved_rows(),
                "segments": len(self.segments)}


def reshard_plan(old_n: int, new_n: int, num_rows: int) -> ReshardPlan:
    """Deterministic mapping of row ownership from an `old_n`-rank mesh
    onto a `new_n`-rank mesh.

    Pure arithmetic — no RNG, no clock, no environment — so any two
    processes given the same three integers produce byte-identical
    plans (pinned in tests/test_elastic.py).  Segments are emitted in
    (new_rank, start) order; together they cover [0, num_rows) exactly
    once.
    """
    if old_n <= 0 or new_n <= 0:
        raise ValueError(f"world sizes must be positive "
                         f"(old={old_n}, new={new_n})")
    if num_rows < 0:
        raise ValueError(f"num_rows must be >= 0, got {num_rows}")
    segments: List[ShardSegment] = []
    for nr in range(new_n):
        n_start, n_stop = rows_of(num_rows, new_n, nr)
        for orank in range(old_n):
            o_start, o_stop = rows_of(num_rows, old_n, orank)
            lo, hi = max(n_start, o_start), min(n_stop, o_stop)
            if lo < hi:
                segments.append(ShardSegment(nr, orank, lo, hi))
    return ReshardPlan(int(old_n), int(new_n), int(num_rows),
                       tuple(segments))
