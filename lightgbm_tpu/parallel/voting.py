"""Voting-parallel (PV-Tree) tree learner over a device mesh.

TPU-native redesign of VotingParallelTreeLearner
(ref: src/treelearner/voting_parallel_tree_learner.cpp:151-181 GlobalVoting,
:184 CopyLocalHistogram, :296 FindBestSplitsFromHistograms):

  reference (socket collectives)            TPU (explicit collectives in a
                                            shard_map region inside the jit)
  ----------------------------------------- -------------------------------
  rows pre-partitioned per machine          binned [F, n] sharded on axis n
  local histograms per worker               per-device hist in the region
  local best split per feature with         find_best_split(..., return_
    min_data/min_hessian scaled by 1/M        feature_gains=True) on local
    (voting_parallel_tree_learner.cpp:62)     sums with the scaled params
  each worker proposes its top-k features   lax.top_k on the count-weighted
    by gain*count/mean_count (:165)           local gain vector
  Allgather proposals; global election =    lax.pmax of the masked proposal
    top-k features by max weighted gain       vector, then lax.top_k
    (GlobalVoting :151)
  ReduceScatter ONLY the elected            lax.psum of the gathered
    features' histograms (:184)               [k, B, 2] sub-histogram
  best split among elected features,        the usual global gain scan with
    SyncUpGlobalBestSplit (:296)              col_mask &= elected

The point of PV-Tree is traffic: per leaf scan the wire carries
k*B*2 + F floats instead of the full F*B*2 histogram.  On an ICI mesh this
matters once F is large or the mesh spans DCN (multi-pod).

Approximation note (same spirit as the reference): the *election* ranks
features by unconstrained local gains — monotone/CEGB/extra-trees
adjustments apply in the exact global scan over the elected features.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ._shard_compat import shard_map

from ..ops.histogram import build_histogram
from ..ops.split import K_MIN_SCORE, SplitParams, find_best_split


class VotingSpec(NamedTuple):
    """Static voting-parallel configuration (hashable: jit static arg)."""
    mesh: Mesh
    top_k: int          # ref: config.h top_k (default 20)
    num_machines: int   # mesh size M


def local_split_params(sp: SplitParams, num_machines: int) -> SplitParams:
    """The reference scales the per-leaf minima by 1/M for the LOCAL scans
    (ref: voting_parallel_tree_learner.cpp:62-63).  The election ranks
    features by plain unconstrained gains: monotone/CEGB/extra-trees need
    per-leaf state the vote region does not carry, and they apply exactly
    in the global scan over the elected features."""
    return sp._replace(
        min_data_in_leaf=max(1, sp.min_data_in_leaf // num_machines),
        min_sum_hessian_in_leaf=sp.min_sum_hessian_in_leaf / num_machines,
        extra_trees=False, has_monotone=False, has_cegb=False)


def voting_hist_elect(binned, gh, member_mask, col_mask, parent_output,
                      meta, spec: VotingSpec, sp: SplitParams,
                      max_bin: int, hist_method: str):
    """Per-leaf voted histogram: returns ([F, B, 2] histogram that is exact
    for the elected features and zero elsewhere, [F] elected mask).

    Runs as a shard_map region over the mesh's data axis so the collectives
    are explicit: pmax carries the vote, psum reduces only the winners.
    """
    axis = spec.mesh.axis_names[0]
    M = spec.num_machines
    k = spec.top_k
    sp_local = local_split_params(sp, M)
    f32 = jnp.float32
    is_cat = (meta.is_cat if meta.is_cat is not None
              else jnp.zeros_like(meta.num_bin, bool))

    def local_fn(b_l, gh_l, mask_l, num_bin, missing_type, default_bin,
                 penalty, is_cat_f, cm, parent_out):
        # local leaf sums + histogram over this device's row shard
        hist_l = build_histogram(b_l, gh_l, mask_l, max_bin=max_bin,
                                 method=hist_method)
        sum_g_l = jnp.sum(gh_l[:, 0] * mask_l)
        sum_h_l = jnp.sum(gh_l[:, 1] * mask_l)
        cnt_l = jnp.sum(mask_l).astype(jnp.int32)
        gains = find_best_split(
            hist_l, num_bin, missing_type, default_bin,
            penalty, cm, sum_g_l, sum_h_l, cnt_l, parent_out,
            sp_local, is_cat_feature=is_cat_f,
            return_feature_gains=True)                      # [F]
        # count-weighted gain (ref: GlobalVoting :165: gain * count/mean)
        cnt_g = jax.lax.psum(cnt_l, axis)
        w = cnt_l.astype(f32) / jnp.maximum(cnt_g.astype(f32) / M, 1.0)
        weighted = jnp.where(gains > K_MIN_SCORE, gains * w, K_MIN_SCORE)
        # local proposal: this worker's top-k features
        kth = jax.lax.top_k(weighted, k)[0][-1]
        prop = jnp.where(weighted >= kth, weighted, K_MIN_SCORE)
        # global election by per-feature MAX weighted gain, exactly the
        # reference's GlobalVoting (voting_parallel_tree_learner.cpp:
        # 151-180): it concatenates every worker's proposals and keeps the
        # top-k features by the largest weighted gain any worker reported
        # (ArrayArgs::MaxK) — it never tallies votes.  pmax of the masked
        # proposal vectors gives each feature its max proposed gain;
        # non-proposed features stay at K_MIN_SCORE.
        glob = jax.lax.pmax(prop, axis)
        top_v, top_i = jax.lax.top_k(glob, k)
        valid = top_v > K_MIN_SCORE
        # reduce ONLY the elected features' histograms
        sub = jax.lax.psum(hist_l[top_i], axis)             # [k, B, 2]
        F = hist_l.shape[0]
        dst = jnp.where(valid, top_i, F)                    # drop invalid
        hist = jnp.zeros_like(hist_l).at[dst].set(sub, mode="drop")
        elected = jnp.zeros((F,), bool).at[dst].set(True, mode="drop")
        return hist, elected

    repl = P()
    # outputs are replicated by construction (psum/pmax of replicated
    # election indices) but the static replication checker cannot infer
    # it through top_k/scatter — hence check_vma=False
    return shard_map(
        local_fn, mesh=spec.mesh,
        in_specs=(P(None, axis), P(axis, None), P(axis),
                  repl, repl, repl, repl, repl, repl, repl),
        out_specs=(P(), P()), check_vma=False)(
            binned, gh, member_mask, meta.num_bin, meta.missing_type,
            meta.default_bin, meta.penalty, is_cat, col_mask,
            jnp.asarray(parent_output, f32))
