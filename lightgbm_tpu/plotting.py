"""Plotting utilities (ref: python-package/lightgbm/plotting.py):
plot_importance, plot_metric, plot_split_value_histogram, and
graphviz-based tree rendering when graphviz is installed."""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .basic import Booster
from .utils import log


def _check_matplotlib():
    try:
        import matplotlib.pyplot as plt
        return plt
    except ImportError:  # pragma: no cover
        log.fatal("matplotlib is required for plotting")


def plot_importance(booster, ax=None, height: float = 0.2,
                    xlim=None, ylim=None,
                    title: str = "Feature importance",
                    xlabel: str = "Feature importance",
                    ylabel: str = "Features",
                    importance_type: str = "auto",
                    max_num_features: Optional[int] = None,
                    ignore_zero: bool = True, figsize=None, dpi=None,
                    grid: bool = True, precision: int = 3, **kwargs):
    """Horizontal bar chart of feature importances
    (ref: plotting.py:37 plot_importance)."""
    plt = _check_matplotlib()
    if isinstance(booster, Booster):
        if importance_type == "auto":
            importance_type = "split"
        importance = booster.feature_importance(importance_type)
        names = booster.feature_name()
    else:  # sklearn estimator
        if importance_type == "auto":
            importance_type = booster.importance_type
        importance = booster.booster_.feature_importance(importance_type)
        names = booster.booster_.feature_name()
    pairs = [(n, v) for n, v in zip(names, importance)
             if not (ignore_zero and v == 0)]
    pairs.sort(key=lambda t: t[1])
    if max_num_features is not None and max_num_features > 0:
        pairs = pairs[-max_num_features:]
    labels, values = ([p[0] for p in pairs], [p[1] for p in pairs])
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y,
                f"{x:.{precision}f}" if importance_type == "gain"
                else str(int(x)), va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric: Optional[str] = None,
                dataset_names=None, ax=None, xlim=None, ylim=None,
                title: str = "Metric during training",
                xlabel: str = "Iterations",
                ylabel: str = "@metric@", figsize=None, dpi=None,
                grid: bool = True):
    """Plot recorded eval history (ref: plotting.py:231 plot_metric).
    `booster` is the dict produced by the record_evaluation callback."""
    plt = _check_matplotlib()
    if isinstance(booster, dict):
        eval_results = booster
    else:
        log.fatal("plot_metric needs the eval history dict recorded by "
                  "the record_evaluation callback")
    if not eval_results:
        log.fatal("eval results are empty")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    names = list(dataset_names or eval_results.keys())
    first = eval_results[names[0]]
    if metric is None:
        metric = next(iter(first.keys()))
    for name in names:
        ax.plot(eval_results[name][metric], label=name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    ax.set_title(title.replace("@metric@", metric))
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel.replace("@metric@", metric))
    ax.grid(grid)
    return ax


def plot_split_value_histogram(booster, feature, bins=None, ax=None,
                               width_coef: float = 0.8, xlim=None,
                               ylim=None,
                               title="Split value histogram for "
                                     "feature with @index/name@ @feature@",
                               xlabel="Feature split value",
                               ylabel="Count", figsize=None, dpi=None,
                               grid: bool = True):
    """Histogram of a feature's split thresholds across the model
    (ref: plotting.py:141)."""
    plt = _check_matplotlib()
    b = booster if isinstance(booster, Booster) else booster.booster_
    b._gbdt._sync_model()
    names = b.feature_name()
    fidx = (names.index(feature) if isinstance(feature, str)
            else int(feature))
    values = []
    for tree in b._gbdt.models_:
        ni = max(tree.num_leaves - 1, 0)
        for i in range(ni):
            if (tree.split_feature[i] == fidx
                    and not (tree.decision_type[i] & 1)):
                values.append(float(tree.threshold[i]))
    if not values:
        log.fatal(f"Feature {feature} was not used in splitting")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    hist, edges = np.histogram(values, bins=bins or "auto")
    centers = (edges[:-1] + edges[1:]) / 2
    ax.bar(centers, hist,
           width=width_coef * (edges[1] - edges[0]))
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    kind = "name" if isinstance(feature, str) else "index"
    ax.set_title(title.replace("@index/name@", kind)
                 .replace("@feature@", str(feature)))
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index: int = 0, show_info=None,
                        precision: int = 3, **kwargs):
    """Graphviz Digraph of one tree (ref: plotting.py:404)."""
    try:
        import graphviz
    except ImportError:
        log.fatal("graphviz is required for tree plotting")
    b = booster if isinstance(booster, Booster) else booster.booster_
    b._gbdt._sync_model()
    tree = b._gbdt.models_[tree_index]
    names = b.feature_name()
    g = graphviz.Digraph(**kwargs)

    def add(node):
        if node < 0:
            leaf = ~node
            g.node(f"leaf{leaf}",
                   label=f"leaf {leaf}: "
                         f"{tree.leaf_value[leaf]:.{precision}f}")
            return f"leaf{leaf}"
        f = int(tree.split_feature[node])
        fname = names[f] if f < len(names) else f"Column_{f}"
        if tree.decision_type[node] & 1:  # categorical membership
            cats = "||".join(str(c) for c in tree._cats_of_node(node))
            label = f"{fname} in {{{cats}}}"
        else:
            label = f"{fname} <= {tree.threshold[node]:.{precision}f}"
        g.node(f"split{node}", label=label)
        left = add(int(tree.left_child[node]))
        right = add(int(tree.right_child[node]))
        g.edge(f"split{node}", left, label="yes")
        g.edge(f"split{node}", right, label="no")
        return f"split{node}"

    if tree.num_leaves > 1:
        add(0)
    else:
        g.node("leaf0", label=f"leaf 0: {tree.leaf_value[0]:.{precision}f}")
    return g


def plot_tree(booster, tree_index: int = 0, ax=None, figsize=None,
              dpi=None, **kwargs):
    """Render one tree via graphviz into a matplotlib axes
    (ref: plotting.py:560)."""
    plt = _check_matplotlib()
    graph = create_tree_digraph(booster, tree_index=tree_index, **kwargs)
    from io import BytesIO
    import matplotlib.image as mpimg
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize, dpi=dpi)
    s = BytesIO(graph.pipe(format="png"))
    ax.imshow(mpimg.imread(s))
    ax.axis("off")
    return ax
