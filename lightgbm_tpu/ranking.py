"""Ranking objectives: LambdaRank-NDCG and RankXENDCG.

ref: src/objective/rank_objective.hpp (RankingObjective:28, LambdarankNDCG:131,
RankXENDCG:362) and the CUDA twin src/objective/cuda/cuda_rank_objective.cu.

Per-query lambda computation is vectorized over the full pairwise matrix of a
query (no scalar pair loops); queries are processed host-side per iteration.
Deviations from the reference, both noted for parity review:
  * the exact sigmoid is used instead of the reference's 1024-bin lookup table
    (rank_objective.hpp GetSigmoid/ConstructSigmoidTable);
  * RankXENDCG's per-query RNG is a NumPy Generator seeded with seed+query_id
    rather than the reference's custom LCG (utils/random.h).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .config import Config
from .metric import default_label_gain
from .objective import ObjectiveFunction
from .utils import log

K_EPSILON = 1e-15


def _discounts(n: int) -> np.ndarray:
    return 1.0 / np.log2(np.arange(n) + 2.0)


class RankingObjective(ObjectiveFunction):
    """Common per-query driver (ref: rank_objective.hpp:28)."""

    run_on_host = True  # gradients computed host-side per query

    def __init__(self, config: Config):
        super().__init__(config)
        self.seed = config.objective_seed
        self.learning_rate = config.learning_rate
        self.position_bias_regularization = (
            config.lambdarank_position_bias_regularization)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Ranking tasks require query information")
        self.query_boundaries = np.asarray(metadata.query_boundaries)
        self.num_queries = len(self.query_boundaries) - 1
        # position bias factors (ref: rank_objective.hpp:43-60,290):
        # per-position offsets added to scores before the pairwise
        # lambdas, updated by a Newton step every iteration
        self.positions = (None if metadata.position is None
                          else np.asarray(metadata.position, np.int64))
        if self.positions is not None:
            self.num_position_ids = int(self.positions.max()) + 1
            self.pos_biases = np.zeros(self.num_position_ids)

    @property
    def pos_biases(self):
        """Learned per-position offsets.  When the device gradient
        program is active the Newton state lives on device
        (_pos_biases_dev); reading here pulls it to host lazily."""
        dev = getattr(self, "_pos_biases_dev", None)
        if dev is not None:
            return np.asarray(dev, np.float64)
        return self._pos_biases_host

    @pos_biases.setter
    def pos_biases(self, v):
        # a host write takes over: drop the device snapshot so reads
        # and the host Newton loop stay coherent (re-init, host path)
        self._pos_biases_dev = None
        self._pos_biases_host = v

    def get_gradients_host(self, score: np.ndarray):
        """score [n] -> (grad, hess) on host (ref: RankingObjective::GetGradients)."""
        n = len(score)
        lambdas = np.zeros(n, dtype=np.float64)
        hessians = np.zeros(n, dtype=np.float64)
        if self.positions is not None:
            score = score + self.pos_biases[self.positions]  # hpp:68
        for q in range(self.num_queries):
            a, b = int(self.query_boundaries[q]), int(self.query_boundaries[q + 1])
            l, h = self._one_query(q, self.label[a:b], score[a:b])
            lambdas[a:b] = l
            hessians[a:b] = h
        if self.weight is not None:
            lambdas *= self.weight
            hessians *= self.weight
        if self.positions is not None:
            self._update_position_bias(lambdas, hessians)
        return lambdas.astype(np.float32), hessians.astype(np.float32)

    def _update_position_bias(self, lambdas, hessians):
        """Newton step on the per-position utility derivatives
        (ref: rank_objective.hpp:290 UpdatePositionBiasFactors)."""
        P = self.num_position_ids
        fd = -np.bincount(self.positions, weights=lambdas, minlength=P)
        sd = -np.bincount(self.positions, weights=hessians, minlength=P)
        cnt = np.bincount(self.positions, minlength=P)
        reg = self.position_bias_regularization
        fd -= self.pos_biases * reg * cnt
        sd -= reg * cnt
        self.pos_biases += (self.learning_rate * fd
                            / (np.abs(sd) + 0.001))

    def get_gradients(self, score, label, weight):  # pragma: no cover
        raise RuntimeError("ranking objectives compute gradients host-side; "
                           "use get_gradients_host")

    def _one_query(self, qid, label, score):
        raise NotImplementedError


class LambdarankNDCG(RankingObjective):
    """ref: rank_objective.hpp:131 LambdarankNDCG.

    Gradients run ON DEVICE by default (make_device_grad_fn): queries are
    bucketed by padded pow2 length, each bucket computes its pairwise
    lambdas as one masked [Qb, T, m] tensor program (the TPU analogue of
    the per-query CUDA kernels in cuda_rank_objective.cu:131
    GetGradientsKernel_LambdarankNDCG), and results scatter back through
    the precomputed doc-index map; position-bias offsets and their
    Newton update run on device too, the bias vector threaded as
    explicit state."""
    name = "lambdarank"

    def __init__(self, config: Config):
        super().__init__(config)
        self.sigmoid = config.sigmoid
        self.norm = config.lambdarank_norm
        self.truncation_level = config.lambdarank_truncation_level
        self.label_gain = np.asarray(list(config.label_gain) or
                                     default_label_gain())
        if self.sigmoid <= 0:
            log.fatal(f"Sigmoid param {self.sigmoid} should be greater than zero")

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if (self.label >= len(self.label_gain)).any() or (self.label < 0).any():
            log.fatal("Label exceeds label_gain size in lambdarank")
        # inverse max DCG at truncation level per query (ref: hpp:160-170)
        self.inverse_max_dcgs = np.zeros(self.num_queries)
        disc = _discounts(self.truncation_level)
        for q in range(self.num_queries):
            a, b = int(self.query_boundaries[q]), int(self.query_boundaries[q + 1])
            g = np.sort(self.label_gain[self.label[a:b].astype(np.int64)])[::-1]
            k = min(self.truncation_level, b - a)
            max_dcg = float((g[:k] * disc[:k]).sum())
            self.inverse_max_dcgs[q] = 1.0 / max_dcg if max_dcg > 0 else 0.0

    # ------------------------------------------------------------------
    def make_device_grad_fn(self, n_pad: int):
        """Build the jitted device gradient program (always available
        for lambdarank; position-bias mode included).

        Bucket tensors (doc indices, labels, valid masks, 1/maxDCG) are
        passed as explicit jit arguments — closing over large device
        arrays embeds them as constants, which degrades every subsequent
        dispatch on the remote-TPU runtime (see gbdt.py _grad_fn note).

        Position bias (ref: rank_objective.hpp:43-60,290) also runs on
        device: scores are offset by the per-position biases before the
        pairwise lambdas, and the Newton bias update is computed from
        the weighted lambdas/hessians via segment sums — the bias vector
        rides as explicit state threaded through each call."""
        import jax
        import jax.numpy as jnp

        from .metric import bucket_queries
        qb = self.query_boundaries
        self._dev_buckets = []
        for b in bucket_queries(qb, n_pad):
            Qb, m = len(b["qs"]), b["m"]
            lab = np.zeros((Qb, m), np.int32)
            imd = np.zeros(Qb, np.float32)
            for r, q in enumerate(b["qs"]):
                a, e = int(qb[q]), int(qb[q + 1])
                lab[r, :e - a] = self.label[a:e].astype(np.int32)
                imd[r] = self.inverse_max_dcgs[q]
            self._dev_buckets.append(dict(
                m=m, idx=jnp.asarray(b["idx"]), lab=jnp.asarray(lab),
                val=jnp.asarray(b["val"]), imd=jnp.asarray(imd)))
        lg = jnp.asarray(self.label_gain, jnp.float32)
        sigmoid, norm, trunc = self.sigmoid, self.norm, self.truncation_level
        f32 = jnp.float32

        def bucket_lambdas(sc_b, lab_b, val_b, imd_b, m):
            """[Qb, m] padded query block -> (lambdas, hessians) in the
            block's doc positions (mirrors _one_query, vectorized)."""
            Tm = max(1, min(trunc, m - 1))
            key = jnp.where(val_b, sc_b, -jnp.inf)
            order = jnp.argsort(-key, axis=1, stable=True)
            ss = jnp.take_along_axis(sc_b, order, 1)
            sl = jnp.take_along_axis(lab_b, order, 1)
            sv = jnp.take_along_axis(val_b, order, 1)
            ssz = jnp.where(sv, ss, 0.0)
            cnt = jnp.sum(sv.astype(jnp.int32), axis=1)
            gains = jnp.take(lg, jnp.clip(sl, 0, lg.shape[0] - 1))
            disc = (1.0 / jnp.log2(jnp.arange(m, dtype=f32) + 2.0))
            best = ssz[:, 0]
            worst = jnp.take_along_axis(
                ssz, jnp.maximum(cnt - 1, 0)[:, None], 1)[:, 0]
            gi, gj = gains[:, :Tm, None], gains[:, None, :]
            si, sj = ssz[:, :Tm, None], ssz[:, None, :]
            di, dj = disc[None, :Tm, None], disc[None, None, :]
            li, lj = sl[:, :Tm, None], sl[:, None, :]
            pair_ok = ((jnp.arange(m)[None, None, :]
                        > jnp.arange(Tm)[None, :, None])
                       & (li != lj) & sv[:, :Tm, None] & sv[:, None, :])
            delta_ndcg = (jnp.abs(gi - gj) * jnp.abs(di - dj)
                          * imd_b[:, None, None])
            if norm:
                dsa = jnp.abs(si - sj)
                delta_ndcg = jnp.where(
                    (best != worst)[:, None, None],
                    delta_ndcg / (0.01 + dsa), delta_ndcg)
            i_is_high = li > lj
            d_s = jnp.where(i_is_high, si - sj, sj - si)
            p = 1.0 / (1.0 + jnp.exp(sigmoid * d_s))
            p_lambda = jnp.where(pair_ok, -sigmoid * delta_ndcg * p, 0.0)
            p_hess = jnp.where(pair_ok,
                               p * (1.0 - p) * sigmoid * sigmoid
                               * delta_ndcg, 0.0)
            sign_i = jnp.where(i_is_high, 1.0, -1.0)
            lam_s = jnp.zeros_like(sc_b).at[:, :Tm].add(
                jnp.sum(p_lambda * sign_i, axis=2))
            lam_s = lam_s + jnp.sum(-p_lambda * sign_i, axis=1)
            hes_s = jnp.zeros_like(sc_b).at[:, :Tm].add(
                jnp.sum(p_hess, axis=2))
            hes_s = hes_s + jnp.sum(p_hess, axis=1)
            if norm:
                sum_lam = -2.0 * jnp.sum(p_lambda, axis=(1, 2))
                nf = jnp.where(sum_lam > 0,
                               jnp.log2(1.0 + sum_lam)
                               / jnp.maximum(sum_lam, K_EPSILON), 1.0)
                lam_s = lam_s * nf[:, None]
                hes_s = hes_s * nf[:, None]
            inv_order = jnp.argsort(order, axis=1)
            lam = jnp.take_along_axis(lam_s, inv_order, 1)
            hes = jnp.take_along_axis(hes_s, inv_order, 1)
            return lam, hes

        use_pos = self.positions is not None
        if use_pos:
            P = self.num_position_ids
            pos_dev = jnp.asarray(
                np.concatenate([self.positions.astype(np.int32),
                                np.zeros(n_pad - len(self.positions),
                                         np.int32)]))
            pos_mask = jnp.asarray(
                np.concatenate([np.ones(len(self.positions), np.float32),
                                np.zeros(n_pad - len(self.positions),
                                         np.float32)]))
            # per-position doc counts are static: precompute host-side
            # instead of a scatter-add every iteration
            pos_cnt = jnp.asarray(np.bincount(
                self.positions, minlength=P).astype(np.float32))
            self._pos_biases_dev = jnp.zeros(P, f32)
            lr = self.learning_rate
            reg = self.position_bias_regularization

        def grad_fn(scores, weight, bucket_args, biases, pos_dev,
                    pos_mask, pos_cnt):
            sc = scores[0].astype(f32)
            if use_pos:
                sc = sc + jnp.take(biases, pos_dev)     # hpp:68
            g = jnp.zeros(n_pad, f32)
            h = jnp.zeros(n_pad, f32)
            for bk in bucket_args:
                m = bk["idx"].shape[1]
                sc_b = jnp.take(sc, bk["idx"])
                lam, hes = bucket_lambdas(sc_b, bk["lab"], bk["val"],
                                          bk["imd"], m)
                lam = jnp.where(bk["val"], lam, 0.0)
                hes = jnp.where(bk["val"], hes, 0.0)
                g = g.at[bk["idx"].reshape(-1)].add(lam.reshape(-1))
                h = h.at[bk["idx"].reshape(-1)].add(hes.reshape(-1))
            if weight is not None:
                g = g * weight
                h = h * weight
            if use_pos:
                # Newton step on the per-position utility derivatives
                # (ref: rank_objective.hpp:290 UpdatePositionBiasFactors),
                # from the WEIGHTED lambdas like the host path
                fd = -(jnp.zeros(P, f32).at[pos_dev].add(g * pos_mask))
                sd = -(jnp.zeros(P, f32).at[pos_dev].add(h * pos_mask))
                fd = fd - biases * reg * pos_cnt
                sd = sd - reg * pos_cnt
                biases = biases + lr * fd / (jnp.abs(sd) + 0.001)
            return g[None, :], h[None, :], biases

        # tpulint: disable-next=donate-argnums -- gradient maps read the live score buffer; the boosting loop keeps updating it
        jitted = jax.jit(grad_fn, static_argnames=())
        zero1 = jnp.zeros(1, f32)
        zeroi = jnp.zeros(1, jnp.int32)
        if not use_pos:
            def call(scores, weight):
                g, h, _ = jitted(scores, weight, self._dev_buckets,
                                 zero1, zeroi, zero1, zero1)
                return g, h
            return call

        def call(scores, weight):
            g, h, nb = jitted(scores, weight, self._dev_buckets,
                              self._pos_biases_dev, pos_dev, pos_mask,
                              pos_cnt)
            self._pos_biases_dev = nb
            return g, h
        return call

    def _one_query(self, qid, label, score):
        cnt = len(label)
        lambdas = np.zeros(cnt)
        hessians = np.zeros(cnt)
        if cnt <= 1 or self.inverse_max_dcgs[qid] == 0.0:
            return lambdas, hessians
        inv_max_dcg = self.inverse_max_dcgs[qid]
        order = np.argsort(-score, kind="stable")
        sl = label[order].astype(np.int64)
        ss = score[order].astype(np.float64)
        best_score, worst_score = ss[0], ss[-1]
        gains = self.label_gain[sl]
        disc = _discounts(cnt)
        T = min(self.truncation_level, cnt - 1)

        # pairwise over (i in [0,T), j in (i, cnt)) in sorted space
        gi, gj = gains[:T, None], gains[None, :]
        si, sj = ss[:T, None], ss[None, :]
        di, dj = disc[:T, None], disc[None, :]
        li, lj = sl[:T, None], sl[None, :]
        valid = (np.arange(cnt)[None, :] > np.arange(T)[:, None]) & (li != lj)

        delta_ndcg = np.abs(gi - gj) * np.abs(di - dj) * inv_max_dcg
        delta_score_abs = np.abs(si - sj)
        if self.norm and best_score != worst_score:
            delta_ndcg = delta_ndcg / (0.01 + delta_score_abs)
        # high = larger label; delta_score = s_high - s_low
        i_is_high = li > lj
        d_s = np.where(i_is_high, si - sj, sj - si)
        p = 1.0 / (1.0 + np.exp(self.sigmoid * d_s))
        p_lambda = -self.sigmoid * delta_ndcg * p          # negative
        p_hess = p * (1.0 - p) * self.sigmoid * self.sigmoid * delta_ndcg
        p_lambda = np.where(valid, p_lambda, 0.0)
        p_hess = np.where(valid, p_hess, 0.0)

        # accumulate into sorted positions, then unsort
        lam_sorted = np.zeros(cnt)
        hes_sorted = np.zeros(cnt)
        # high gets +p_lambda, low gets -p_lambda
        sign_i = np.where(i_is_high, 1.0, -1.0)
        lam_sorted[:T] += (p_lambda * sign_i).sum(axis=1)
        np.add.at(lam_sorted, np.broadcast_to(np.arange(cnt)[None, :],
                                              p_lambda.shape).ravel(),
                  (-p_lambda * sign_i).ravel())
        hes_sorted[:T] += p_hess.sum(axis=1)
        np.add.at(hes_sorted, np.broadcast_to(np.arange(cnt)[None, :],
                                              p_hess.shape).ravel(),
                  p_hess.ravel())
        sum_lambdas = -2.0 * p_lambda.sum()
        if self.norm and sum_lambdas > 0:
            nf = np.log2(1 + sum_lambdas) / sum_lambdas
            lam_sorted *= nf
            hes_sorted *= nf
        lambdas[order] = lam_sorted
        hessians[order] = hes_sorted
        return lambdas, hessians


class RankXENDCG(RankingObjective):
    """ref: rank_objective.hpp:362 RankXENDCG.

    Gradients run ON DEVICE by default (make_device_grad_fn), like
    lambdarank: queries are bucketed by padded pow2 length and each
    bucket computes its masked-softmax + three order-correction passes
    as one [Qb, m] tensor program — the TPU analogue of the per-query
    CUDA kernels (ref: cuda_rank_objective.cu:385,502,618
    GetGradientsKernel_RankXENDCG variants).  Per-query Gumbel draws use
    `jax.random.fold_in(iteration_key, query_id)` instead of the host's
    per-query numpy RandomState streams — same independence structure,
    different streams (the documented RNG deviation this file already
    makes for extra-trees seeds)."""
    name = "rank_xendcg"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        self.rands = [np.random.RandomState(self.seed + q)
                      for q in range(self.num_queries)]

    # ------------------------------------------------------------------
    def make_device_grad_fn(self, n_pad: int):
        """Bucketed device gradient program; None when position bias is
        active (the generic host Newton loop handles that rare mode)."""
        if self.positions is not None:
            return None
        import jax
        import jax.numpy as jnp

        from .metric import bucket_queries
        qb = self.query_boundaries
        buckets = []
        for b in bucket_queries(qb, n_pad):
            Qb, m = len(b["qs"]), b["m"]
            lab = np.zeros((Qb, m), np.int32)
            for r, q in enumerate(b["qs"]):
                a, e = int(qb[q]), int(qb[q + 1])
                lab[r, :e - a] = self.label[a:e].astype(np.int32)
            buckets.append(dict(
                idx=jnp.asarray(b["idx"]), lab=jnp.asarray(lab),
                val=jnp.asarray(b["val"]),
                qid=jnp.asarray(np.asarray(b["qs"], np.int32))))
        f32 = jnp.float32
        seed = self.seed

        def bucket_grads(key_it, sc_b, lab_b, val_b, qid_b):
            """Vectorized mirror of _one_query over a [Qb, m] block."""
            m = sc_b.shape[1]
            scm = jnp.where(val_b, sc_b, -jnp.inf)
            mx = jnp.max(scm, axis=1, keepdims=True)
            e = jnp.where(val_b, jnp.exp(sc_b - mx), 0.0)
            rho = e / jnp.maximum(jnp.sum(e, axis=1, keepdims=True),
                                  K_EPSILON)
            keys = jax.vmap(lambda q: jax.random.fold_in(key_it, q))(qid_b)
            u = jax.vmap(lambda k: jax.random.uniform(k, (m,)))(keys)
            params = jnp.where(val_b,
                               jnp.exp2(lab_b.astype(f32)) - u, 0.0)
            inv_den = 1.0 / jnp.maximum(
                jnp.sum(params, axis=1, keepdims=True), K_EPSILON)
            # guard 1/(1-rho): float32 rho can saturate to 1.0 on widely
            # separated scores (the float64 host loop cannot)
            inv_1m = 1.0 / jnp.maximum(1.0 - rho, K_EPSILON)
            l1 = jnp.where(val_b, -params * inv_den + rho, 0.0)
            lambdas = l1
            p1 = l1 * inv_1m
            sum_l1 = jnp.sum(jnp.where(val_b, p1, 0.0), 1, keepdims=True)
            l2 = rho * (sum_l1 - p1)
            lambdas = lambdas + jnp.where(val_b, l2, 0.0)
            p2 = l2 * inv_1m
            sum_l2 = jnp.sum(jnp.where(val_b, p2, 0.0), 1, keepdims=True)
            lambdas = lambdas + jnp.where(val_b, rho * (sum_l2 - p2), 0.0)
            hess = jnp.where(val_b, rho * (1.0 - rho), 0.0)
            keep = (jnp.sum(val_b, axis=1) > 1)[:, None]   # cnt<=1: zeros
            return (jnp.where(keep & val_b, lambdas, 0.0),
                    jnp.where(keep & val_b, hess, 0.0))

        def grad_fn(scores, weight, bucket_args, it):
            sc = scores[0].astype(f32)
            key_it = jax.random.fold_in(jax.random.PRNGKey(seed), it)
            g = jnp.zeros(n_pad, f32)
            h = jnp.zeros(n_pad, f32)
            for bk in bucket_args:
                sc_b = jnp.take(sc, bk["idx"])
                lam, hes = bucket_grads(key_it, sc_b, bk["lab"],
                                        bk["val"], bk["qid"])
                g = g.at[bk["idx"].reshape(-1)].add(lam.reshape(-1))
                h = h.at[bk["idx"].reshape(-1)].add(hes.reshape(-1))
            if weight is not None:
                g = g * weight
                h = h * weight
            return g[None, :], h[None, :]

        # tpulint: disable-next=donate-argnums -- gradient maps read the live score buffer; the boosting loop keeps updating it
        jitted = jax.jit(grad_fn)
        self._xe_iter = 0

        def call(scores, weight):
            g, h = jitted(scores, weight, buckets,
                          jnp.asarray(self._xe_iter, jnp.int32))
            self._xe_iter += 1
            return g, h

        return call

    def _one_query(self, qid, label, score):
        cnt = len(label)
        if cnt <= 1:
            return np.zeros(cnt), np.zeros(cnt)
        sc = score.astype(np.float64)
        e = np.exp(sc - sc.max())
        rho = e / e.sum()
        params = np.power(2.0, label.astype(np.int64)) - \
            self.rands[qid].random_sample(cnt)
        inv_denominator = 1.0 / max(K_EPSILON, params.sum())
        # first-order
        l1 = -params * inv_denominator + rho
        lambdas = l1.copy()
        params = l1 / (1.0 - rho)
        sum_l1 = params.sum()
        # second-order
        l2 = rho * (sum_l1 - params)
        lambdas += l2
        params = l2 / (1.0 - rho)
        sum_l2 = params.sum()
        # third-order
        lambdas += rho * (sum_l2 - params)
        hessians = rho * (1.0 - rho)
        return lambdas, hessians


def create_ranking_objective(name: str, config: Config) -> RankingObjective:
    if name == "lambdarank":
        return LambdarankNDCG(config)
    if name == "rank_xendcg":
        return RankXENDCG(config)
    log.fatal(f"Unknown ranking objective: {name}")
