"""Fault tolerance for long training runs (ROADMAP: production-scale serving).

Three pillars, mirroring what the reference engine gets from its socket
layer and whole-file model writes (ref survey §1, src/network/):

* checkpoint/resume — `CheckpointManager` writes atomic, rotated
  checkpoints (model text + exact trainer state) so a job killed at
  iteration k restarts from k, not from zero (`checkpoint.py`).
* worker supervision — poll-based process watchdog + retry/backoff for
  the multi-process launcher (`supervisor.py`, used by `distributed.py`).
* fault injection — env-driven crash/NaN/write-failure hooks so the
  recovery paths above are testable without real hardware faults
  (`faults.py`, `LGBM_TPU_FAULT=worker_crash@3,...`).
* stall watchdog + degradation ladder — `guard.py` turns live-but-hung
  runs (the MULTICHIP_r05 shape: a rank wedged in a collective) into a
  structured stall diagnosis and a distinct exit code, and with
  `auto_degrade=true` relaunches from checkpoint with the next risky
  knob disabled.
"""

from __future__ import annotations

from ..utils.log import LightGBMError


class NonFiniteError(LightGBMError):
    """Raised when NaN/Inf gradients or eval scores are detected: boosting
    on non-finite values silently produces garbage trees, so training
    fails fast (or rolls back to the last checkpoint when one exists)."""


from . import faults  # noqa: E402
from .checkpoint import Checkpoint, CheckpointManager  # noqa: E402
from .elastic import ElasticDecision, ElasticPolicy  # noqa: E402
from .faults import WORKER_LOST_EXIT_CODE  # noqa: E402
from .guard import (DEGRADE_LADDER, STALL_EXIT_CODE,  # noqa: E402
                    RunGuard, classify_returncode)

__all__ = ["Checkpoint", "CheckpointManager", "NonFiniteError", "faults",
           "RunGuard", "STALL_EXIT_CODE", "DEGRADE_LADDER",
           "classify_returncode", "ElasticDecision", "ElasticPolicy",
           "WORKER_LOST_EXIT_CODE"]
