"""Checkpoint/resume for boosting runs.

Layout of a checkpoint directory:

    ckpt_0000012.txt   -- full model text at iteration 12 (the standard
                          LightGBM v4 format: a checkpoint IS a model)
    ckpt_0000012.npz   -- exact trainer state: float32 score buffer and
                          bagging/feature RNG streams, so a resumed run
                          reproduces the uninterrupted run byte-for-byte
                          (predict-based reseeding differs in ulps)
    manifest.json      -- {"iteration", "model", "state", "params_hash"}

Every write is atomic (temp file + os.replace) and the manifest is
written last, so a crash mid-checkpoint leaves the previous checkpoint
fully intact.  Rotation keeps the newest `keep_last` checkpoints.

Resume semantics vs `init_model`: `init_model` adopts a model's trees
and re-seeds scores from its predictions (good enough for continued
training on *new* data); a checkpoint resume additionally restores the
exact score buffer and RNG state of the interrupted run, so training
continues as if never interrupted.
"""

from __future__ import annotations

import glob
import hashlib
import io
import json
import os
import zipfile
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from ..utils import atomic_write_bytes, atomic_write_text, log
from . import faults

MANIFEST = "manifest.json"
_FORMAT = 1

# knobs that do not affect the trained model: a checkpoint taken with a
# different output path, verbosity, telemetry or serving configuration
# is still resumable
_HASH_EXCLUDE = frozenset((
    "verbosity", "verbose", "output_model", "input_model", "output_result",
    "data", "valid", "snapshot_freq", "checkpoint_dir", "checkpoint_freq",
    "checkpoint_keep", "resume", "max_retries", "retry_backoff",
    "nonfinite_check_freq", "machines", "machine_list_filename",
    "local_listen_port", "num_machines", "time_out",
    "metrics_dir", "metrics_rotate_mb", "profile_dir",
    "async_host_io", "compile_cache_dir", "device_eval",
    "device_predict", "device_predict_min_bucket",
    # the degradation ladder (reliability/guard.py) flips these between
    # attempts; all are model-neutral perf/telemetry knobs, and a
    # degraded relaunch MUST still resume the interrupted checkpoint
    "tpu_donate_buffers", "auto_degrade", "stall_floor_s", "stall_factor",
))


def hash_params(params: Dict[str, Any]) -> str:
    """Canonical hash of the training-relevant parameters: a checkpoint
    is only resumed into a run with the same boosting configuration."""
    from ..config import Config
    changed = Config(dict(params or {})).changed_params()
    key = {k: v for k, v in sorted(changed.items()) if k not in _HASH_EXCLUDE}
    blob = json.dumps(key, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


@dataclass
class Checkpoint:
    iteration: int
    model_path: str
    state_path: Optional[str]
    params_hash: Optional[str]

    def load_state(self) -> Optional[Dict[str, np.ndarray]]:
        if not self.state_path or not os.path.exists(self.state_path):
            return None
        try:
            with np.load(self.state_path, allow_pickle=True) as z:
                return {k: z[k] for k in z.files}
        except (OSError, ValueError) as e:
            log.warning(f"Unreadable checkpoint state {self.state_path}: "
                        f"{e}; resuming from model text only")
            return None


def _state_bytes(state: Dict[str, Any]) -> bytes:
    """Deterministic npz: np.savez stamps each zip member with the
    current wall clock (2 s DOS resolution), so two runs writing the
    SAME state produce different bytes — which breaks the async-vs-sync
    byte-exactness contract (tests/test_async_io.py).  Write the same
    .npy-in-zip layout with a fixed epoch timestamp instead; np.load
    reads it unchanged."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_STORED) as zf:
        for key, value in state.items():
            member = io.BytesIO()
            np.lib.format.write_array(member, np.asarray(value),
                                      allow_pickle=True)
            info = zipfile.ZipInfo(key + ".npy",
                                   date_time=(1980, 1, 1, 0, 0, 0))
            zf.writestr(info, member.getvalue())
    return buf.getvalue()


class CheckpointManager:
    """Atomic, rotated checkpoints of a training run.

    With `writer` (observability.hostio.AsyncWriter) the serialization
    and file I/O run off the training thread (docs/Performance.md): the
    training thread only captures the state — the model text plus a
    device-side score snapshot whose D2H copy is started asynchronously
    — and the worker fetches, packs and atomically renames.  Failure
    accounting flows through `on_done` in both modes, so a failed async
    write still warns/counts and never kills training."""

    def __init__(self, directory: str, keep_last: int = 3,
                 params: Optional[Dict[str, Any]] = None, writer=None):
        self.dir = os.fspath(directory)
        self.keep_last = max(int(keep_last), 1)
        self.params_hash = hash_params(params) if params is not None else None
        self.writer = writer
        os.makedirs(self.dir, exist_ok=True)

    # ------------------------------------------------------------- save
    def _name(self, iteration: int, ext: str) -> str:
        return os.path.join(self.dir, f"ckpt_{iteration:07d}.{ext}")

    def save(self, booster, iteration: int, on_done=None) -> Checkpoint:
        """Checkpoint `booster` as of `iteration` completed rounds.

        Synchronous mode raises OSError on write failure when no
        `on_done` is given (direct callers decide); with `on_done(ok,
        err, ck)` — the training callback's accounting hook — failures
        are reported through the hook instead.  Async mode returns
        immediately after capture; the hook fires from the writer
        thread once the files land (or fail)."""
        from ..utils.timer import global_timer
        with global_timer.scope("Checkpoint::save"):
            it = int(iteration)
            model_txt = booster.model_to_string(num_iteration=-1)
            state = None
            gbdt = getattr(booster, "_gbdt", None)
            if gbdt is not None and hasattr(gbdt, "capture_train_state"):
                state = gbdt.capture_train_state(
                    async_copy=self.writer is not None)
            ck = Checkpoint(it, self._name(it, "txt"),
                            self._name(it, "npz") if state is not None
                            else None, self.params_hash)
            if self.writer is not None:
                self.writer.submit(self._write_reporting, it, model_txt,
                                   state, ck, on_done)
                return ck
            try:
                self._write(it, model_txt, state)
            except OSError as e:
                if on_done is not None:
                    on_done(False, e, ck)
                    return ck
                raise
        if on_done is not None:
            on_done(True, None, ck)
        return ck

    def _write_reporting(self, it, model_txt, state, ck, on_done) -> None:
        """Worker-side write wrapper: route the outcome through on_done
        and swallow the failure (reliability contract: a lost checkpoint
        must never kill a long run)."""
        try:
            self._write(it, model_txt, state)
        except OSError as e:
            if on_done is not None:
                on_done(False, e, ck)
            else:
                log.warning(f"Async checkpoint write failed at iteration "
                            f"{it}: {e}; training continues")
            return
        if on_done is not None:
            on_done(True, None, ck)

    def _write(self, it: int, model_txt: str, state) -> None:
        """Serialize + atomically rename one captured checkpoint (runs
        on the writer thread in async mode)."""
        faults.maybe_ckpt_write_fail(it)
        model_path = self._name(it, "txt")
        atomic_write_text(model_path, model_txt)
        state_path = None
        if state is not None:
            state_path = self._name(it, "npz")
            atomic_write_bytes(state_path, _state_bytes(state))
        manifest = {"format": _FORMAT, "iteration": it,
                    "model": os.path.basename(model_path),
                    "state": (os.path.basename(state_path)
                              if state_path else None),
                    "params_hash": self.params_hash}
        atomic_write_text(os.path.join(self.dir, MANIFEST),
                          json.dumps(manifest, indent=1))
        self._rotate()
        log.debug(f"Checkpoint written at iteration {it} -> {model_path}")

    def _rotate(self) -> None:
        models = sorted(glob.glob(os.path.join(self.dir, "ckpt_*.txt")))
        for stale in models[:-self.keep_last]:
            for p in (stale, stale[:-4] + ".npz"):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    # ----------------------------------------------------------- latest
    def latest(self) -> Optional[Checkpoint]:
        """Newest complete checkpoint, or None.  Prefers the manifest;
        falls back to scanning ckpt_*.txt when the manifest is missing
        or damaged (it is written atomically, but be lenient)."""
        mpath = os.path.join(self.dir, MANIFEST)
        if os.path.exists(mpath):
            try:
                with open(mpath) as f:
                    m = json.load(f)
                model = os.path.join(self.dir, m["model"])
                if os.path.exists(model):
                    state = (os.path.join(self.dir, m["state"])
                             if m.get("state") else None)
                    return Checkpoint(int(m["iteration"]), model, state,
                                      m.get("params_hash"))
                log.warning(f"Checkpoint manifest points at missing file "
                            f"{model}; scanning {self.dir} instead")
            except (OSError, ValueError, KeyError) as e:
                log.warning(f"Damaged checkpoint manifest {mpath}: {e}; "
                            f"scanning {self.dir} instead")
        models = sorted(glob.glob(os.path.join(self.dir, "ckpt_*.txt")))
        if not models:
            return None
        model = models[-1]
        try:
            it = int(os.path.basename(model)[5:-4])
        except ValueError:
            return None
        state = model[:-4] + ".npz"
        return Checkpoint(it, model, state if os.path.exists(state) else None,
                          None)

    def resumable(self, params: Optional[Dict[str, Any]] = None
                  ) -> Optional[Checkpoint]:
        """latest(), gated on a params-hash match: a checkpoint from a
        different configuration is reported and ignored."""
        ck = self.latest()
        if ck is None:
            return None
        want = (hash_params(params) if params is not None
                else self.params_hash)
        if ck.params_hash is not None and want is not None \
                and ck.params_hash != want:
            log.warning(
                f"Ignoring checkpoint at iteration {ck.iteration} in "
                f"{self.dir}: it was written with different training "
                f"parameters (hash {ck.params_hash} != {want}). Delete the "
                f"directory or pass resume=False to start over.")
            return None
        return ck
