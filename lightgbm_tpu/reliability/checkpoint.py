"""Checkpoint/resume for boosting runs.

Layout of a checkpoint directory:

    ckpt_0000012.txt   -- full model text at iteration 12 (the standard
                          LightGBM v4 format: a checkpoint IS a model)
    ckpt_0000012.npz   -- exact trainer state: float32 score buffer and
                          bagging/feature RNG streams, so a resumed run
                          reproduces the uninterrupted run byte-for-byte
                          (predict-based reseeding differs in ulps)
    manifest.json      -- {"iteration", "model", "state", "params_hash"}

Every write is atomic (temp file + os.replace) and the manifest is
written last, so a crash mid-checkpoint leaves the previous checkpoint
fully intact.  Rotation keeps the newest `keep_last` checkpoints.

Integrity (docs/Reliability.md §Checkpoint integrity): the manifest
records a SHA-256 digest per artifact for every retained generation
(`"generations"`, format 2).  Resume verifies the newest generation's
digests before trusting it; a torn or bit-flipped checkpoint is
QUARANTINED (artifacts renamed `*.corrupt-<ts>`, generation dropped
from the manifest) and resume falls back to the previous rotation
generation with a structured `ckpt_fallback` event — instead of
crashing on a half-written npz or, worse, silently training from a
corrupt score buffer.  Format-1 manifests (no digests) stay loadable.

Resume semantics vs `init_model`: `init_model` adopts a model's trees
and re-seeds scores from its predictions (good enough for continued
training on *new* data); a checkpoint resume additionally restores the
exact score buffer and RNG state of the interrupted run, so training
continues as if never interrupted.
"""

from __future__ import annotations

import glob
import hashlib
import io
import json
import os
import threading
import time
import zipfile
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils import atomic_write_bytes, atomic_write_text, log
from . import faults

MANIFEST = "manifest.json"
_FORMAT = 2

# knobs that do not affect the trained model: a checkpoint taken with a
# different output path, verbosity, telemetry or serving configuration
# is still resumable
_HASH_EXCLUDE = frozenset((
    "verbosity", "verbose", "output_model", "input_model", "output_result",
    "data", "valid", "snapshot_freq", "checkpoint_dir", "checkpoint_freq",
    "checkpoint_keep", "resume", "max_retries", "retry_backoff",
    "nonfinite_check_freq", "machines", "machine_list_filename",
    "local_listen_port", "num_machines", "time_out",
    "metrics_dir", "metrics_rotate_mb", "profile_dir",
    "async_host_io", "compile_cache_dir", "device_eval",
    "device_predict", "device_predict_min_bucket",
    # serving-daemon knobs (docs/Serving.md): pure inference-side
    # configuration, model-neutral by construction
    "serve_models", "serve_max_coalesce_wait_ms", "serve_queue_depth",
    "serve_max_batch_rows", "serve_warmup", "serve_port",
    "serve_drain_timeout_s",
    # serving-fleet knobs (docs/Serving.md fleet section): router /
    # replica / canary topology, likewise model-neutral
    "serve_request_timeout_s", "serve_replicas",
    "serve_max_replica_restarts", "serve_health_interval_s",
    "serve_retry_max", "serve_retry_backoff_ms", "serve_canary_pct",
    "serve_canary_min_samples", "serve_canary_max_divergence",
    "serve_canary_max_error_rate", "serve_ready_file",
    # fleet SLO / tracing knobs (docs/Observability.md): telemetry only
    "serve_slo_p99_ms", "serve_slo_error_pct", "serve_slo_fast_window_s",
    "serve_slo_slow_window_s", "serve_slo_burn_threshold",
    "serve_trace_sample", "serve_adaptive_coalesce", "serve_uds_path",
    # online continual-learning loop knobs (docs/Online.md): the chunk
    # cadence, publish topology and freshness SLO never change what a
    # given (model text, chunk bytes) pair trains into — a checkpoint
    # must resume across any of them (the SIGTERM drill relaunches with
    # a different online_idle_exit_s, for one)
    "online_chunk_dir", "online_mode", "online_trees_per_chunk",
    "online_poll_interval_s", "online_model_name", "online_max_lag_s",
    "online_publish_retry_max", "online_publish_backoff_ms",
    "online_publish_addr", "online_max_generations", "online_idle_exit_s",
    # the degradation ladder (reliability/guard.py) flips these between
    # attempts; all are model-neutral perf/telemetry knobs, and a
    # degraded relaunch MUST still resume the interrupted checkpoint
    "tpu_donate_buffers", "auto_degrade", "stall_floor_s", "stall_factor",
    # elastic recovery knobs (docs/Reliability.md): a shrunken or
    # preempted relaunch must still resume the interrupted checkpoint
    "preempt_ckpt_grace_s", "elastic_rank_grace_s", "elastic_min_machines",
))


def hash_params(params: Dict[str, Any]) -> str:
    """Canonical hash of the training-relevant parameters: a checkpoint
    is only resumed into a run with the same boosting configuration."""
    from ..config import Config
    changed = Config(dict(params or {})).changed_params()
    key = {k: v for k, v in sorted(changed.items()) if k not in _HASH_EXCLUDE}
    blob = json.dumps(key, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _sha256_file(path: str) -> Optional[str]:
    """Streaming SHA-256 of a file, None when unreadable."""
    try:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
    except OSError:
        return None


@dataclass
class Checkpoint:
    iteration: int
    model_path: str
    state_path: Optional[str]
    params_hash: Optional[str]
    # per-artifact SHA-256 digests keyed by basename (format-2
    # manifests); None for legacy checkpoints, which skip verification
    digests: Optional[Dict[str, str]] = None
    num_rows: Optional[int] = None

    def verify(self) -> Tuple[bool, str]:
        """Recompute artifact digests against the manifest's record.
        Legacy checkpoints (no digests) pass vacuously — lenient, like
        the manifest handling everywhere else in this module."""
        if not self.digests:
            return True, "no digests recorded (legacy checkpoint)"
        for path in (self.model_path, self.state_path):
            if not path:
                continue
            want = self.digests.get(os.path.basename(path))
            if want is None:
                continue
            have = _sha256_file(path)
            if have is None:
                return False, f"{os.path.basename(path)}: unreadable"
            if have != want:
                return False, (f"{os.path.basename(path)}: digest mismatch "
                               f"(manifest {want[:12]}…, disk {have[:12]}…)")
        return True, "ok"

    def load_state(self) -> Optional[Dict[str, np.ndarray]]:
        if not self.state_path or not os.path.exists(self.state_path):
            return None
        try:
            with np.load(self.state_path, allow_pickle=True) as z:
                return {k: z[k] for k in z.files}
        except (OSError, ValueError) as e:
            log.warning(f"Unreadable checkpoint state {self.state_path}: "
                        f"{e}; resuming from model text only")
            return None


def _state_bytes(state: Dict[str, Any]) -> bytes:
    """Deterministic npz: np.savez stamps each zip member with the
    current wall clock (2 s DOS resolution), so two runs writing the
    SAME state produce different bytes — which breaks the async-vs-sync
    byte-exactness contract (tests/test_async_io.py).  Write the same
    .npy-in-zip layout with a fixed epoch timestamp instead; np.load
    reads it unchanged."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_STORED) as zf:
        for key, value in state.items():
            member = io.BytesIO()
            np.lib.format.write_array(member, np.asarray(value),
                                      allow_pickle=True)
            info = zipfile.ZipInfo(key + ".npy",
                                   date_time=(1980, 1, 1, 0, 0, 0))
            zf.writestr(info, member.getvalue())
    return buf.getvalue()


class CheckpointManager:
    """Atomic, rotated checkpoints of a training run.

    With `writer` (observability.hostio.AsyncWriter) the serialization
    and file I/O run off the training thread (docs/Performance.md): the
    training thread only captures the state — the model text plus a
    device-side score snapshot whose D2H copy is started asynchronously
    — and the worker fetches, packs and atomically renames.  Failure
    accounting flows through `on_done` in both modes, so a failed async
    write still warns/counts and never kills training."""

    def __init__(self, directory: str, keep_last: int = 3,
                 params: Optional[Dict[str, Any]] = None, writer=None):
        self.dir = os.fspath(directory)
        self.keep_last = max(int(keep_last), 1)
        self.params_hash = hash_params(params) if params is not None else None
        self.writer = writer
        os.makedirs(self.dir, exist_ok=True)
        # serializes the generations-list read/modify/write and the
        # manifest rewrite: in async mode `_write` runs on the writer
        # thread while `save_now` (the SIGTERM preemption checkpoint)
        # runs the SAME code on the training thread — unserialized, the
        # two read-modify-writes race and the manifest can lose a
        # generation (tpulint thread-shared-state, ISSUE 9).  RLock so
        # _write may call _write_manifest while holding it.
        self._gen_lock = threading.RLock()
        # per-generation manifest records {iteration, model, state,
        # digests, num_rows}, oldest -> newest; reloaded from an
        # existing manifest so a resumed process keeps the history it
        # needs for digest verification and generation fallback
        self._generations: List[Dict[str, Any]] = self._load_generations()

    def _load_generations(self) -> List[Dict[str, Any]]:
        try:
            with open(os.path.join(self.dir, MANIFEST)) as f:
                m = json.load(f)
            gens = m.get("generations")
            if isinstance(gens, list):
                return [g for g in gens if isinstance(g, dict)
                        and "iteration" in g and "model" in g]
        except (OSError, ValueError):
            pass
        return []

    # ------------------------------------------------------------- save
    def _name(self, iteration: int, ext: str) -> str:
        return os.path.join(self.dir, f"ckpt_{iteration:07d}.{ext}")

    def save(self, booster, iteration: int, on_done=None) -> Checkpoint:
        """Checkpoint `booster` as of `iteration` completed rounds.

        Synchronous mode raises OSError on write failure when no
        `on_done` is given (direct callers decide); with `on_done(ok,
        err, ck)` — the training callback's accounting hook — failures
        are reported through the hook instead.  Async mode returns
        immediately after capture; the hook fires from the writer
        thread once the files land (or fail)."""
        from ..utils.timer import global_timer
        with global_timer.scope("Checkpoint::save"):
            it = int(iteration)
            model_txt = booster.model_to_string(num_iteration=-1)
            state = None
            num_rows = None
            gbdt = getattr(booster, "_gbdt", None)
            if gbdt is not None and hasattr(gbdt, "capture_train_state"):
                state = gbdt.capture_train_state(
                    async_copy=self.writer is not None)
                num_rows = int(getattr(gbdt, "num_data", 0)) or None
            ck = Checkpoint(it, self._name(it, "txt"),
                            self._name(it, "npz") if state is not None
                            else None, self.params_hash, num_rows=num_rows)
            if self.writer is not None:
                self.writer.submit(self._write_reporting, it, model_txt,
                                   state, ck, on_done, num_rows)
                return ck
            try:
                self._write(it, model_txt, state, num_rows)
            except OSError as e:
                if on_done is not None:
                    on_done(False, e, ck)
                    return ck
                raise
        if on_done is not None:
            on_done(True, None, ck)
        return ck

    def save_now(self, booster, iteration: int,
                 grace_s: Optional[float] = None) -> Optional[Checkpoint]:
        """Out-of-band SYNCHRONOUS checkpoint for the preemption handler
        (docs/Reliability.md §Preemption): capture on the calling
        (training) thread, write without the AsyncWriter — whose queue
        the dying process may never drain — and keep the whole save
        inside `grace_s`: when the capture alone has eaten the budget,
        the exact-state npz is dropped and the model text (which still
        resumes, predict-seeded) is written alone.  Returns None when
        there is nothing worth saving (no completed iteration)."""
        it = int(iteration)
        if it <= 0:
            return None
        t0 = time.monotonic()
        # serialize exactly `it` iterations: the pipelined engine may
        # hold trees past the declared boundary, and a checkpoint whose
        # model text disagrees with its iteration cannot resume exactly
        model_txt = booster.model_to_string(num_iteration=it)
        state = None
        num_rows = None
        gbdt = getattr(booster, "_gbdt", None)
        if gbdt is not None and hasattr(gbdt, "capture_train_state"):
            state = gbdt.capture_train_state(async_copy=False)
            num_rows = int(getattr(gbdt, "num_data", 0)) or None
        if grace_s is not None and time.monotonic() - t0 > float(grace_s):
            log.warning(f"Preemption checkpoint capture overran the "
                        f"{grace_s:.1f}s grace budget; writing model text "
                        "without the exact-state npz")
            state = None
        self._write(it, model_txt, state, num_rows)
        return Checkpoint(it, self._name(it, "txt"),
                          self._name(it, "npz") if state is not None
                          else None, self.params_hash, num_rows=num_rows)

    def _write_reporting(self, it, model_txt, state, ck, on_done,
                         num_rows=None) -> None:
        """Worker-side write wrapper: route the outcome through on_done
        and swallow the failure (reliability contract: a lost checkpoint
        must never kill a long run)."""
        try:
            self._write(it, model_txt, state, num_rows)
        except OSError as e:
            if on_done is not None:
                on_done(False, e, ck)
            else:
                log.warning(f"Async checkpoint write failed at iteration "
                            f"{it}: {e}; training continues")
            return
        if on_done is not None:
            on_done(True, None, ck)

    def _write(self, it: int, model_txt: str, state,
               num_rows: Optional[int] = None) -> None:
        """Serialize + atomically rename one captured checkpoint (runs
        on the writer thread in async mode).  Digests are computed over
        the exact bytes handed to the atomic writer, so a later
        mismatch can only mean on-disk damage, never a race."""
        faults.maybe_ckpt_write_fail(it)
        model_path = self._name(it, "txt")
        model_bytes = model_txt.encode()
        atomic_write_bytes(model_path, model_bytes)
        digests = {os.path.basename(model_path):
                   hashlib.sha256(model_bytes).hexdigest()}
        state_path = None
        if state is not None:
            state_path = self._name(it, "npz")
            sbytes = _state_bytes(state)
            atomic_write_bytes(state_path, sbytes)
            digests[os.path.basename(state_path)] = \
                hashlib.sha256(sbytes).hexdigest()
        entry = {"iteration": it,
                 "model": os.path.basename(model_path),
                 "state": (os.path.basename(state_path)
                           if state_path else None),
                 "digests": digests, "num_rows": num_rows,
                 "params_hash": self.params_hash}
        with self._gen_lock:
            self._generations = sorted(
                [g for g in self._generations if g.get("iteration") != it]
                + [entry], key=lambda g: g["iteration"])[-self.keep_last:]
            self._write_manifest()
            self._rotate()
        # post-landing damage injection (ckpt_corrupt drill): the
        # manifest now describes a healthy write the disk no longer holds
        if faults.active():
            faults.maybe_ckpt_corrupt(it, model_path, state_path)
        log.debug(f"Checkpoint written at iteration {it} -> {model_path}")

    def _write_manifest(self) -> None:
        with self._gen_lock:
            if not self._generations:
                try:
                    os.unlink(os.path.join(self.dir, MANIFEST))
                except OSError:
                    pass
                return
            newest = self._generations[-1]
            manifest = {"format": _FORMAT, "iteration": newest["iteration"],
                        "model": newest["model"], "state": newest["state"],
                        "params_hash": self.params_hash,
                        "num_rows": newest.get("num_rows"),
                        "digests": newest.get("digests"),
                        "generations": self._generations}
            atomic_write_text(os.path.join(self.dir, MANIFEST),
                              json.dumps(manifest, indent=1))

    def _rotate(self) -> None:
        models = sorted(glob.glob(os.path.join(self.dir, "ckpt_*.txt")))
        for stale in models[:-self.keep_last]:
            for p in (stale, stale[:-4] + ".npz"):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    # ----------------------------------------------------------- latest
    def _ck_from_entry(self, g: Dict[str, Any]) -> Checkpoint:
        return Checkpoint(
            int(g["iteration"]), os.path.join(self.dir, g["model"]),
            (os.path.join(self.dir, g["state"]) if g.get("state") else None),
            g.get("params_hash", self.params_hash),
            digests=g.get("digests"), num_rows=g.get("num_rows"))

    def _candidates(self) -> List[Checkpoint]:
        """Resumable candidates, newest first: the manifest's retained
        generations when available, else the single newest checkpoint
        the manifest or a directory scan yields (legacy layouts)."""
        # re-read: another process (async writer, preempt handler,
        # previous attempt) may have advanced the manifest on disk
        with self._gen_lock:
            self._generations = self._load_generations() \
                or self._generations
            gens = list(self._generations)
        if gens:
            return [self._ck_from_entry(g) for g in reversed(gens)]
        ck = self.latest()
        return [ck] if ck is not None else []

    def latest(self) -> Optional[Checkpoint]:
        """Newest complete checkpoint, or None.  Prefers the manifest;
        falls back to scanning ckpt_*.txt when the manifest is missing
        or damaged (it is written atomically, but be lenient)."""
        mpath = os.path.join(self.dir, MANIFEST)
        if os.path.exists(mpath):
            try:
                with open(mpath) as f:
                    m = json.load(f)
                model = os.path.join(self.dir, m["model"])
                if os.path.exists(model):
                    state = (os.path.join(self.dir, m["state"])
                             if m.get("state") else None)
                    return Checkpoint(int(m["iteration"]), model, state,
                                      m.get("params_hash"),
                                      digests=m.get("digests"),
                                      num_rows=m.get("num_rows"))
                log.warning(f"Checkpoint manifest points at missing file "
                            f"{model}; scanning {self.dir} instead")
            except (OSError, ValueError, KeyError) as e:
                log.warning(f"Damaged checkpoint manifest {mpath}: {e}; "
                            f"scanning {self.dir} instead")
        models = sorted(glob.glob(os.path.join(self.dir, "ckpt_*.txt")))
        if not models:
            return None
        model = models[-1]
        try:
            it = int(os.path.basename(model)[5:-4])
        except ValueError:
            return None
        state = model[:-4] + ".npz"
        return Checkpoint(it, model, state if os.path.exists(state) else None,
                          None)

    def quarantine(self, ck: Checkpoint, reason: str) -> None:
        """Move a failed-verification generation out of the resume path:
        artifacts renamed `*.corrupt-<ts>` (kept for forensics, invisible
        to the ckpt_*.txt scan) and the generation dropped from the
        manifest, so neither this process nor the next one can resume
        into the damage."""
        ts = int(time.time())
        for path in (ck.model_path, ck.state_path):
            if not path or not os.path.exists(path):
                continue
            try:
                os.replace(path, f"{path}.corrupt-{ts}")
            except OSError as e:
                log.warning(f"Could not quarantine {path}: {e}")
        with self._gen_lock:
            self._generations = [g for g in self._generations
                                 if int(g.get("iteration", -1))
                                 != ck.iteration]
            self._write_manifest()
        log.warning(f"Quarantined corrupt checkpoint at iteration "
                    f"{ck.iteration} in {self.dir}: {reason}")

    def resumable(self, params: Optional[Dict[str, Any]] = None
                  ) -> Optional[Checkpoint]:
        """Newest VERIFIED checkpoint, gated on a params-hash match: a
        checkpoint from a different configuration is reported and
        ignored; a corrupt one (manifest digest mismatch — torn write,
        bad disk, injected `ckpt_corrupt`) is quarantined and resume
        falls back to the previous rotation generation, emitting one
        structured `ckpt_fallback` event per generation skipped."""
        candidates = self._candidates()
        if not candidates:
            return None
        want = (hash_params(params) if params is not None
                else self.params_hash)
        ck = candidates[0]
        if ck.params_hash is not None and want is not None \
                and ck.params_hash != want:
            log.warning(
                f"Ignoring checkpoint at iteration {ck.iteration} in "
                f"{self.dir}: it was written with different training "
                f"parameters (hash {ck.params_hash} != {want}). Delete the "
                f"directory or pass resume=False to start over.")
            return None
        skipped: List[Tuple[Checkpoint, str]] = []
        winner = None
        for ck in candidates:
            ok, detail = ck.verify()
            if ok:
                winner = ck
                break
            self.quarantine(ck, detail)
            skipped.append((ck, detail))
        if skipped:
            from ..observability import emit_event, global_registry
            for bad, detail in skipped:
                global_registry.inc("ckpt_fallbacks")
                emit_event("ckpt_fallback", from_iteration=bad.iteration,
                           to_iteration=(winner.iteration
                                         if winner is not None else None),
                           reason=detail)
            log.warning(
                f"Checkpoint integrity: quarantined "
                f"{len(skipped)} corrupt generation(s) in {self.dir}; "
                + (f"resuming from generation at iteration "
                   f"{winner.iteration} instead"
                   if winner is not None else
                   "no intact generation remains — starting over"))
        return winner
