"""Elastic shrink-to-fit policy for the distributed supervisor.

The reference engine's `Network::Init` sizes a socket ring once: lose a
machine and the run is over.  PR 1's supervisor improved that to
"relaunch the whole cluster at the original world size", which still
loops forever when a rank is PERMANENTLY gone — a dead host, a revoked
reservation, a tombstoned worker.  `ElasticPolicy` closes that gap: it
watches the per-attempt failure reports and decides when a rank should
stop being waited for and the cluster should shrink around it
(docs/Reliability.md §Elastic recovery).

A rank is classified permanently lost when

* it exited with `WORKER_LOST_EXIT_CODE` (it tombstoned itself — the
  drillable `worker_lost@N` fault), or
* the SAME rank has failed on consecutive relaunch attempts (dead PID
  or stale heartbeat alike) spanning at least `rank_grace_s` seconds —
  a transient crash recovers on the first relaunch; one that keeps
  recurring on one rank past the grace window is a host problem, not a
  software race.

Preemption (`kind == "preempt"`) never counts toward permanence: a
preempted host is expected back, so the policy answers "retry".

The decision is advisory — `distributed._train_distributed_in` owns the
relaunch loop and composes this with PR 7's degradation ladder (shrink
first, then walk knobs: a shrink changes the collective topology, which
invalidates any hang evidence gathered on the old one).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .supervisor import SuperviseResult

# actions, in the order _train_distributed_in consults them
RETRY = "retry"        # relaunch at the current world size
SHRINK = "shrink"      # relaunch at a smaller world size
GIVE_UP = "give_up"    # permanent loss below the min-machines floor


@dataclass
class ElasticDecision:
    action: str
    num_machines: int              # world size for the next attempt
    lost_ranks: List[int] = field(default_factory=list)
    reason: str = ""


@dataclass
class _Streak:
    count: int
    first_ts: float


class ElasticPolicy:
    """Tracks per-rank failure streaks across relaunch attempts and
    turns them into shrink decisions.  Pure bookkeeping — no I/O — so
    it is drillable without processes (tests/test_elastic.py)."""

    def __init__(self, num_machines: int, min_machines: int = 1,
                 rank_grace_s: float = 60.0, clock=time.monotonic):
        self.num_machines = int(num_machines)
        self.min_machines = max(int(min_machines), 1)
        self.rank_grace_s = float(rank_grace_s)
        self._clock = clock
        self._streaks: Dict[int, _Streak] = {}
        self.shrinks = 0

    # ------------------------------------------------------------ policy
    def _permanent(self, result: SuperviseResult) -> List[int]:
        now = self._clock()
        failed = {f.rank: f for f in result.failures}
        # a rank that did NOT fail this attempt has proven itself alive:
        # its streak resets (alternating-rank crashes are a cluster
        # problem, not a single lost host)
        for rank in list(self._streaks):
            if rank not in failed:
                del self._streaks[rank]
        lost: List[int] = []
        for rank, f in failed.items():
            if f.kind == "preempt":
                self._streaks.pop(rank, None)
                continue
            if f.kind == "lost":
                lost.append(rank)
                continue
            streak = self._streaks.get(rank)
            if streak is None:
                self._streaks[rank] = _Streak(1, now)
                continue
            streak.count += 1
            if streak.count >= 2 and now - streak.first_ts \
                    >= self.rank_grace_s:
                lost.append(rank)
        return sorted(lost)

    def observe(self, result: SuperviseResult) -> ElasticDecision:
        """Digest one failed attempt's SuperviseResult into the next
        attempt's topology.  Call once per failed attempt."""
        lost = self._permanent(result)
        if not lost:
            return ElasticDecision(RETRY, self.num_machines,
                                   reason="no rank classified "
                                          "permanently lost")
        new_n = self.num_machines - len(lost)
        if new_n < self.min_machines:
            return ElasticDecision(
                GIVE_UP, self.num_machines, lost_ranks=lost,
                reason=f"rank(s) {lost} permanently lost but shrinking to "
                       f"{new_n} would cross elastic_min_machines="
                       f"{self.min_machines}")
        old_n = self.num_machines
        self.num_machines = new_n
        self.shrinks += 1
        for rank in lost:
            self._streaks.pop(rank, None)
        # rank indices renumber with the new world size: old streak
        # anchors would blame the wrong hosts
        self._streaks.clear()
        return ElasticDecision(
            SHRINK, new_n, lost_ranks=lost,
            reason=f"rank(s) {lost} permanently lost; shrinking "
                   f"{old_n} -> {new_n}")


def plan_for_shrink(old_n: int, new_n: int,
                    num_rows: Optional[int]):
    """The deterministic row plan the `elastic_shrink` event records —
    every rank (and the parent) derives the identical plan from the
    checkpoint's row count; None when the row count is unknown (no
    checkpoint yet: the relaunch rebins from scratch anyway)."""
    if not num_rows:
        return None
    from ..parallel import reshard_plan
    return reshard_plan(old_n, new_n, int(num_rows))
