"""Env-driven fault injection.

`LGBM_TPU_FAULT` holds a comma-separated list of `kind@iteration` specs,
optionally `kind@iteration@attempt` (attempt defaults to 0, matched
against `LGBM_TPU_FAULT_ATTEMPT` so a supervised retry does not re-fire
the fault).  Kinds:

* `worker_crash@3`   — `os._exit(17)` at the start of boosting iteration 3
* `nan_grad@5`       — poison the iteration-5 gradients with NaN
* `ckpt_write_fail@2`— raise OSError from the iteration-2 checkpoint write
* `hang@3`           — wedge forever at the start of iteration 3 (the
  MULTICHIP_r05 shape: the process stays LIVE, so only the stall
  watchdog / heartbeat staleness can catch it)
* `slow_iter@4`      — sleep `LGBM_TPU_FAULT_SLOW_S` (default 2.0)
  seconds inside iteration 4: slow, but NOT a stall — the watchdog's
  rolling-median deadline must not trip on it
* `collective_stall@2` — wedge forever immediately BEFORE the grow
  program dispatch; rank-gated, it models one rank entering a
  collective late so every peer blocks inside psum
* `ckpt_corrupt@4`    — AFTER the iteration-4 checkpoint lands on disk
  (manifest included), truncate or bit-flip its newest artifact — the
  torn-write/bad-disk shape the manifest digests exist to catch.
  `LGBM_TPU_FAULT_CORRUPT=truncate|bitflip` picks the damage (default
  truncate; bitflip targets the state npz when one exists)
* `worker_lost@3`     — permanent rank loss: write a tombstone file
  keyed by (rank, world size) and `os._exit(WORKER_LOST_EXIT_CODE)`;
  on every relaunch at the SAME world size the worker main finds its
  tombstone and refuses to start, so only an elastic shrink (smaller
  world, different tombstone key) recovers the run

Serve-side fault points (docs/Reliability.md serving fault domain):
the serving daemon ticks a per-process REQUEST counter at submit and
the `@N` in these specs matches it — "the N-th request this replica
accepts" — instead of a boosting iteration.  The fleet bench and
tests drill the router's retry/shed/restart paths with them:

* `serve_crash@N`     — `os._exit(CRASH_EXIT_CODE)` when request N is
  submitted: the replica dies with requests in flight, the fleet
  supervisor must relaunch it and the router must retry elsewhere
* `serve_shed@N`      — force the queue-full path for request N: the
  daemon raises the structured `shed` error exactly as if the bounded
  queue were full
* `serve_slow@N`      — arm a `LGBM_TPU_FAULT_SLOW_S` (default 2.0)
  sleep consumed by the coalescer IMMEDIATELY BEFORE its next
  dispatch: latency injection on the dispatcher thread, the shape a
  wedged device presents to the frontend (queue backs up -> shed)

Online-loop fault points (docs/Online.md failure semantics): the `@N`
matches the CHUNK GENERATION id the online trainer is processing:

* `online_chunk_corrupt@N` — damage chunk generation N before the
  trainer reads it: an on-disk chunk is truncated in place (the read
  that follows fails, the torn-upload shape); an in-memory chunk is
  poisoned via the True return.  The trainer must SKIP the generation
  (counted `online_generations_skipped`) and keep the previous
  generation serving
* `online_publish_fail@N`  — raise from the publish of generation N:
  the trainer must keep the old generation serving and retry with
  backoff — a half-published model must never serve

Rank gating applies to replicas too: the fleet sets
`LGBM_TPU_FAULT_SELF_RANK` to each replica's index, so
`LGBM_TPU_FAULT_RANK=1` drills exactly one replica of a fleet.

`LGBM_TPU_FAULT_RANK` (optional) restricts firing to one worker: it is
compared against `LGBM_TPU_FAULT_SELF_RANK`, which the distributed worker
main sets to its own rank (unset processes count as rank 0).

Each spec fires at most once per process, so an in-process rollback retry
(engine.train's NaN sentinel) re-runs the poisoned iteration cleanly.
When `LGBM_TPU_FAULT` is unset every hook is a no-op behind a single
`active()` check — zero steady-state cost.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional, Tuple

from ..utils import log

CRASH_EXIT_CODE = 17
# a rank that exits with this code has declared itself PERMANENTLY lost
# (tombstoned): relaunching it at the same world size is futile, so the
# supervisor's elastic policy shrinks the cluster around it instead
WORKER_LOST_EXIT_CODE = 77

# parsed (kind, iteration, attempt) specs; None = env not parsed yet
_specs: Optional[List[Tuple[str, int, int]]] = None

_KINDS = ("worker_crash", "nan_grad", "ckpt_write_fail",
          "hang", "slow_iter", "collective_stall",
          "ckpt_corrupt", "worker_lost",
          "serve_crash", "serve_shed", "serve_slow",
          "online_chunk_corrupt", "online_publish_fail")


def _parse() -> List[Tuple[str, int, int]]:
    raw = os.environ.get("LGBM_TPU_FAULT", "")
    specs: List[Tuple[str, int, int]] = []
    for item in raw.split(","):
        item = item.strip()
        if not item:
            continue
        parts = item.split("@")
        if len(parts) not in (2, 3) or parts[0] not in _KINDS:
            log.warning(f"Ignoring malformed LGBM_TPU_FAULT spec {item!r}; "
                        f"expected kind@iteration[@attempt] with kind in "
                        f"{_KINDS}")
            continue
        try:
            it = int(parts[1])
            attempt = int(parts[2]) if len(parts) == 3 else 0
        except ValueError:
            log.warning(f"Ignoring malformed LGBM_TPU_FAULT spec {item!r}: "
                        "iteration/attempt must be integers")
            continue
        specs.append((parts[0], it, attempt))
    return specs


def reload() -> None:
    """Re-read LGBM_TPU_FAULT (tests change the env mid-process)."""
    global _specs, _serve_requests, _serve_slow_pending
    # tpulint: disable-next=thread-shared-state -- test-only injection state: both sides rebind the same env-derived value, a duplicate parse is idempotent, and one-shot firing tolerates the benign GIL-serialized race
    _specs = None
    _serve_requests = 0
    # tpulint: disable-next=thread-shared-state -- test-only reset racing the dispatcher's consume: a GIL-atomic float rebind either side of the reset, worst case one injected sleep is dropped or kept — acceptable for an injection drill
    _serve_slow_pending = 0.0


def active() -> bool:
    global _specs
    if _specs is None:
        _specs = _parse()
    return bool(_specs)


def _rank_matches() -> bool:
    want = os.environ.get("LGBM_TPU_FAULT_RANK")
    if want is None:
        return True
    have = os.environ.get("LGBM_TPU_FAULT_SELF_RANK", "0")
    return want.strip() == have.strip()


def _should_fire(kind: str, iteration: int) -> bool:
    if not active() or not _rank_matches():
        return False
    attempt = int(os.environ.get("LGBM_TPU_FAULT_ATTEMPT", "0"))
    for i, (k, it, at) in enumerate(_specs):
        if k == kind and it == iteration and at == attempt:
            del _specs[i]  # one-shot
            return True
    return False


def _record_injection(kind: str, iteration: int) -> None:
    """Count the fired fault and put it on the structured event log (the
    telemetry record every injected fault leaves behind, so a metrics run
    under LGBM_TPU_FAULT is self-describing)."""
    from ..observability import emit_event, global_registry
    global_registry.inc("faults_injected")
    emit_event("fault_injected", kind=kind, iteration=iteration)


def maybe_crash(iteration: int) -> None:
    """worker_crash hook (boosting update loop / worker main)."""
    if _should_fire("worker_crash", iteration):
        _record_injection("worker_crash", iteration)
        sys.stderr.write(f"[LGBM_TPU_FAULT] injected worker_crash at "
                         f"iteration {iteration}: exiting "
                         f"{CRASH_EXIT_CODE}\n")
        sys.stderr.flush()
        os._exit(CRASH_EXIT_CODE)


def maybe_nan_grad(grad, hess, iteration: int):
    """nan_grad hook: returns (grad, hess), poisoned when the spec fires."""
    if _should_fire("nan_grad", iteration):
        _record_injection("nan_grad", iteration)
        log.warning(f"[LGBM_TPU_FAULT] injecting NaN gradients at "
                    f"iteration {iteration}")
        return grad * float("nan"), hess
    return grad, hess


def _wedge(kind: str, iteration: int) -> None:
    """Simulate a live-but-hung process: sleep forever in short slices
    (so os._exit from the watchdog thread, SIGTERM/SIGKILL from the
    supervisor, and SIGUSR1 stack dumps all still work)."""
    sys.stderr.write(f"[LGBM_TPU_FAULT] injected {kind} at iteration "
                     f"{iteration}: process stays alive but makes no "
                     "progress\n")
    sys.stderr.flush()
    import time
    while True:
        time.sleep(1.0)


def maybe_hang(iteration: int) -> None:
    """hang / slow_iter hooks, at the start of a boosting iteration."""
    if _should_fire("hang", iteration):
        _record_injection("hang", iteration)
        _wedge("hang", iteration)
    if _should_fire("slow_iter", iteration):
        _record_injection("slow_iter", iteration)
        import time
        dur = float(os.environ.get("LGBM_TPU_FAULT_SLOW_S", "2.0"))
        log.warning(f"[LGBM_TPU_FAULT] injecting slow_iter at iteration "
                    f"{iteration}: sleeping {dur:.1f}s")
        time.sleep(dur)


def maybe_collective_stall(iteration: int) -> None:
    """collective_stall hook, immediately before the grow-program
    dispatch: with rank gating, the other ranks enter the histogram
    psum and block on this one."""
    if _should_fire("collective_stall", iteration):
        _record_injection("collective_stall", iteration)
        _wedge("collective_stall", iteration)


# serve-side fault state: the daemon ticks `_serve_requests` once per
# accepted request (under its own submit path, GIL-serialized int adds;
# the off-by-one a torn increment could cause is acceptable for an
# injection drill), and serve_slow arms a sleep the coalescer consumes
# just before its next dispatch
_serve_requests = 0
_serve_slow_pending = 0.0


def serve_request_tick() -> int:
    """Count one accepted serving request; returns the 1-based request
    index this process has seen (the `@N` the serve_* specs match)."""
    global _serve_requests
    _serve_requests += 1
    return _serve_requests


def maybe_serve_crash(request_n: int) -> None:
    """serve_crash hook (daemon submit path): replica dies mid-load."""
    if _should_fire("serve_crash", request_n):
        _record_injection("serve_crash", request_n)
        sys.stderr.write(f"[LGBM_TPU_FAULT] injected serve_crash at "
                         f"request {request_n}: exiting "
                         f"{CRASH_EXIT_CODE}\n")
        sys.stderr.flush()
        os._exit(CRASH_EXIT_CODE)


def maybe_serve_shed(request_n: int) -> bool:
    """serve_shed hook: True = treat this submit as queue-full and fail
    fast with the structured shed error (coalescer.ShedError)."""
    if _should_fire("serve_shed", request_n):
        _record_injection("serve_shed", request_n)
        log.warning(f"[LGBM_TPU_FAULT] injecting serve_shed at request "
                    f"{request_n}: forcing the queue-full path")
        return True
    return False


def maybe_serve_slow(request_n: int) -> None:
    """serve_slow hook (submit path): arm the dispatcher-side sleep."""
    global _serve_slow_pending
    if _should_fire("serve_slow", request_n):
        _record_injection("serve_slow", request_n)
        dur = float(os.environ.get("LGBM_TPU_FAULT_SLOW_S", "2.0"))
        log.warning(f"[LGBM_TPU_FAULT] arming serve_slow at request "
                    f"{request_n}: next dispatch sleeps {dur:.1f}s")
        _serve_slow_pending = dur


def consume_serve_slow() -> None:
    """Dispatcher-side half of serve_slow: sleep the armed duration
    once, immediately before the next coalesced dispatch."""
    global _serve_slow_pending
    dur, _serve_slow_pending = _serve_slow_pending, 0.0
    if dur > 0:
        import time
        time.sleep(dur)


def maybe_online_chunk_corrupt(generation: int,
                               path: Optional[str] = None) -> bool:
    """online_chunk_corrupt hook (online chunk sources, per generation):
    models a torn upload / bad-sector chunk.  An on-disk chunk is
    truncated in place so the read that follows fails exactly like real
    damage; an in-memory chunk has no bytes to damage, so the True
    return poisons it.  The trainer's contract either way: skip the
    generation, keep the previous one serving."""
    if not _should_fire("online_chunk_corrupt", generation):
        return False
    _record_injection("online_chunk_corrupt", generation)
    if path:
        try:
            size = os.path.getsize(path)
            # tpulint: disable-next=atomic-write-discipline -- fault injection: deliberate in-place truncation models the torn chunk upload the source's read validation must catch
            with open(path, "r+b") as f:
                f.truncate(max(size // 2, 1))
        except OSError as e:
            log.warning(f"[LGBM_TPU_FAULT] online_chunk_corrupt could not "
                        f"damage {path}: {e}")
    log.warning(f"[LGBM_TPU_FAULT] injected online_chunk_corrupt at "
                f"generation {generation}")
    return True


def maybe_online_publish_fail(generation: int) -> None:
    """online_publish_fail hook (online trainer, before the publish of
    one generation): the publish raises, the trainer must keep the old
    generation serving and retry — never serve a half-published
    model."""
    if _should_fire("online_publish_fail", generation):
        _record_injection("online_publish_fail", generation)
        raise RuntimeError(f"[LGBM_TPU_FAULT] injected online_publish_fail "
                           f"at generation {generation}")


def register_stack_dump_signal() -> bool:
    """Register faulthandler on SIGUSR1 so an operator (or the
    supervisor) can get an all-thread stack dump from a LIVE worker
    without killing it: `kill -USR1 <pid>`.  Returns False where
    unsupported (non-main thread, platforms without SIGUSR1)."""
    try:
        import faulthandler
        import signal
        faulthandler.register(signal.SIGUSR1, all_threads=True, chain=True)
        return True
    except (AttributeError, ImportError, ValueError, RuntimeError):
        return False


def register_flight_dump_signal(directory: str,
                                rank: Optional[int] = None) -> bool:
    """SIGUSR1's sibling: `kill -USR2 <pid>` dumps the flight recorder
    plus a registry snapshot to `<directory>/flight-rank<r>.json`
    WITHOUT killing the process — recent iteration history, sampled
    serving traces and counters from a live (possibly misbehaving)
    worker, where SIGUSR1 only gives stacks.  The dump rides the
    signal-safe synchronous write path (flightrec.dump_flight_record:
    lock-free reads, atomic write, no AsyncWriter, no jax — the PR-9
    terminal-event rule; the rank is resolved HERE, at registration,
    because resolving it queries the jax runtime, which a handler on a
    wedged process must never touch).  Returns False where
    unsupported."""
    directory = os.fspath(directory)
    if rank is None:
        from ..observability.registry import process_rank
        rank = process_rank()

    def _handler(signum, frame):
        from ..observability.flightrec import dump_flight_record
        dump_flight_record(directory, rank=rank, reason="sigusr2")

    try:
        import signal
        signal.signal(signal.SIGUSR2, _handler)
        return True
    except (AttributeError, ImportError, ValueError, OSError,
            RuntimeError):
        return False  # non-main thread / no SIGUSR2 on this platform


def maybe_ckpt_write_fail(iteration: int) -> None:
    """ckpt_write_fail hook, called before the checkpoint touches disk."""
    if _should_fire("ckpt_write_fail", iteration):
        _record_injection("ckpt_write_fail", iteration)
        raise OSError(f"[LGBM_TPU_FAULT] injected ckpt_write_fail at "
                      f"iteration {iteration}")


def maybe_ckpt_corrupt(iteration: int, model_path: str,
                       state_path: Optional[str]) -> None:
    """ckpt_corrupt hook, called AFTER a checkpoint (manifest included)
    has fully landed: damages the artifact bytes on disk while the
    manifest's digests still describe the healthy write — exactly what
    a torn write or bad sector leaves behind.  The integrity check on
    the next resume must quarantine this generation and fall back."""
    if not _should_fire("ckpt_corrupt", iteration):
        return
    _record_injection("ckpt_corrupt", iteration)
    mode = os.environ.get("LGBM_TPU_FAULT_CORRUPT", "truncate").strip()
    target = (state_path if mode == "bitflip" and state_path
              and os.path.exists(state_path) else model_path)
    try:
        size = os.path.getsize(target)
        if mode == "bitflip":
            # tpulint: disable-next=atomic-write-discipline -- fault injection: the in-place damage IS the point, modeling the torn write the atomic path prevents
            with open(target, "r+b") as f:
                f.seek(size // 2)
                byte = f.read(1) or b"\0"
                f.seek(size // 2)
                f.write(bytes([byte[0] ^ 0xFF]))
        else:
            # tpulint: disable-next=atomic-write-discipline -- fault injection: deliberate truncation models the bad-sector/torn-write shape the manifest digests must catch
            with open(target, "r+b") as f:
                f.truncate(max(size // 2, 1))
        log.warning(f"[LGBM_TPU_FAULT] injected ckpt_corrupt ({mode}) at "
                    f"iteration {iteration}: damaged {target}")
    except OSError as e:
        log.warning(f"[LGBM_TPU_FAULT] ckpt_corrupt could not damage "
                    f"{target}: {e}")


def tombstone_path(directory: str, rank: int, world: int) -> str:
    """Tombstone key: (rank, world size).  A shrink relaunch renumbers
    the surviving ranks into a smaller world, so its workers never
    collide with the dead rank's tombstone — which keeps refusing
    same-world relaunches forever, like the dead host it models."""
    return os.path.join(os.fspath(directory),
                        f"tombstone-rank{rank}-of-{world}")


def write_tombstone(directory: str, rank: int, world: int,
                    reason: str) -> None:
    """Atomically drop a rank's tombstone.  The file's EXISTENCE is the
    permanent-loss signal every later relaunch gates on, so it must
    never be observable half-written: a torn tombstone read as present
    is correct, but a crash that leaves a zero-byte temp where the
    marker should be would let a dead rank rejoin (ISSUE 9
    atomic-write-discipline sweep)."""
    from ..utils import atomic_write_text
    try:
        os.makedirs(directory, exist_ok=True)
        atomic_write_text(tombstone_path(directory, rank, world),
                          reason + "\n")
    except OSError:
        pass


def _tombstone_ctx() -> Optional[Tuple[str, int, int]]:
    d = os.environ.get("LGBM_TPU_TOMBSTONE_DIR")
    if not d:
        return None
    rank = int(os.environ.get("LGBM_TPU_FAULT_SELF_RANK", "0"))
    world = int(os.environ.get("LGBM_TPU_WORLD_SIZE", "1"))
    return d, rank, world


def check_tombstone() -> None:
    """Worker-startup gate: a rank that died with worker_lost refuses
    every relaunch at the same world size (`os._exit`, before any jax
    initialization, so the refusal is fast and never wedges peers in
    collectives)."""
    ctx = _tombstone_ctx()
    if ctx is None:
        return
    d, rank, world = ctx
    path = tombstone_path(d, rank, world)
    if os.path.exists(path):
        sys.stderr.write(f"[LGBM_TPU_FAULT] rank {rank}/{world} is "
                         f"tombstoned ({path}): refusing relaunch, "
                         f"exiting {WORKER_LOST_EXIT_CODE}\n")
        sys.stderr.flush()
        os._exit(WORKER_LOST_EXIT_CODE)


def maybe_worker_lost(iteration: int) -> None:
    """worker_lost hook (boosting update loop): tombstone this rank and
    exit WORKER_LOST_EXIT_CODE — a permanent host loss, as opposed to
    worker_crash's transient one."""
    if not _should_fire("worker_lost", iteration):
        return
    _record_injection("worker_lost", iteration)
    ctx = _tombstone_ctx()
    if ctx is not None:
        d, rank, world = ctx
        write_tombstone(d, rank, world,
                        f"worker_lost injected at iteration {iteration}")
    sys.stderr.write(f"[LGBM_TPU_FAULT] injected worker_lost at iteration "
                     f"{iteration}: exiting {WORKER_LOST_EXIT_CODE} "
                     "(permanent)\n")
    sys.stderr.flush()
    os._exit(WORKER_LOST_EXIT_CODE)
