"""Stall watchdog + graceful-degradation ladder (docs/Reliability.md).

MULTICHIP_r05 died at the wall-clock cap with rc=124 and one stderr
line: a rank wedged inside a collective is LIVE, so PR 1's dead-PID
supervision never fires, and the run eats the full deadline with no
stack, no last-iteration marker and no record of which risky knobs were
active.  The reference engine's posture is the opposite — its network
layer surfaces per-rank failure context instead of stalling silently
(PAPER.md §Network).  `RunGuard` brings that posture to the JAX runtime:

* the boosting loop ticks a heartbeat once per iteration (and touches a
  per-rank heartbeat FILE when the distributed supervisor asked for one,
  so the parent can see liveness from outside the process);
* a daemon watchdog thread trips when no tick lands within
  `max(stall_floor_s, stall_factor * rolling-median iteration time)` —
  with a separate, much larger deadline while the first iteration is
  still compiling;
* on a trip it writes a structured stall diagnosis —
  `<metrics_dir>/stall-rank<r>.json` with a faulthandler all-thread
  stack dump, a jax live-array/device-memory snapshot, the last event
  the run logged, and the active risky-knob fingerprint — then exits
  with `STALL_EXIT_CODE` so the supervisor classifies *hang*, not
  *crash*.

The degradation ladder turns the diagnosis into a recovered run: with
`auto_degrade=true`, a relaunch after a hang resumes from the newest
checkpoint with the next risky knob disabled, in the fixed order
`DEGRADE_LADDER` (donation -> compile cache -> async host I/O -> device
eval), logging a `degrade` event each step.  The single-process engine
applies the ladder itself at startup (it finds the previous attempt's
stall file in `metrics_dir`); the distributed supervisor applies it to
the worker spec before relaunching the cluster.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import atomic_write_text, log
from .faults import WORKER_LOST_EXIT_CODE

# Distinct from faults.CRASH_EXIT_CODE (17), POSIX signal codes (>128)
# and timeout(1)'s 124: a process that exits with this code diagnosed
# its own stall and wrote a stall-rank<r>.json before dying.
STALL_EXIT_CODE = 86

# Deterministic degradation order: (knob, disabled-value, predicate
# "is this knob currently enabled").  Donation first — the r05 suspect —
# then the compile cache, then async host I/O, then device eval.
DEGRADE_LADDER: List[Tuple[str, Any]] = [
    ("tpu_donate_buffers", False),
    ("compile_cache_dir", ""),
    ("async_host_io", False),
    ("device_eval", "false"),
]

_LADDER_KNOBS = [k for k, _ in DEGRADE_LADDER]

# rolling window for the per-iteration median (odd so the median is a
# real sample, long enough to ride out eval/checkpoint ticks)
_MEDIAN_WINDOW = 31

DEGRADE_STATE = "degrade-state.json"


def knob_enabled(knob: str, value: Any) -> bool:
    """Is a ladder knob active at this value?  (device_eval "auto" counts
    as enabled: the ladder's job is to force it off.)"""
    if knob == "tpu_donate_buffers" or knob == "async_host_io":
        return bool(value)
    if knob == "compile_cache_dir":
        return bool(str(value or "").strip())
    if knob == "device_eval":
        return str(value).strip().lower() != "false"
    return bool(value)


def stall_file_path(directory: str, rank: int) -> str:
    return os.path.join(os.fspath(directory), f"stall-rank{rank}.json")


def classify_returncode(returncode: Optional[int]) -> str:
    """Supervisor-side classification of a worker exit: 'hang' when the
    worker's own watchdog diagnosed a stall (STALL_EXIT_CODE) or an
    external timeout killed it (None / 124 / SIGKILL-shaped); 'preempt'
    when the worker died of SIGTERM — the preemption-notice shape, where
    the handler saved an on-demand checkpoint before re-delivering the
    signal; 'lost' when the rank declared itself permanently gone
    (tombstoned — relaunching at this world size is futile, shrink
    instead); 'crash' for every other non-zero exit, 'ok' for zero."""
    if returncode == 0:
        return "ok"
    if returncode == STALL_EXIT_CODE:
        return "hang"
    if returncode is None or returncode == 124:
        return "hang"  # killed for overrunning a deadline: live-but-hung
    if returncode in (143, -15):
        return "preempt"  # SIGTERM: a preemption notice, not a bug
    if returncode == WORKER_LOST_EXIT_CODE:
        return "lost"
    return "crash"


def _dump_all_stacks() -> List[str]:
    """faulthandler all-thread stack dump, captured as text lines.
    faulthandler writes to a real fd, so bounce through a temp file."""
    import faulthandler
    try:
        with tempfile.TemporaryFile(mode="w+") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
            f.seek(0)
            return f.read().splitlines()
    except Exception as e:  # noqa: BLE001 - diagnosis must not throw
        return [f"(stack dump unavailable: {e})"]


def _jax_snapshot() -> Dict[str, Any]:
    """Live-array census + device-memory stats, best-effort: on a hang
    the device runtime may itself be wedged, so every probe is fenced."""
    out: Dict[str, Any] = {}
    try:
        import jax
        arrs = jax.live_arrays()
        out["live_arrays"] = len(arrs)
        out["live_array_bytes"] = int(sum(
            getattr(a, "nbytes", 0) or 0 for a in arrs))
    except Exception as e:  # noqa: BLE001
        out["live_arrays_error"] = str(e)
    try:
        from ..observability import sample_device_memory
        mem = sample_device_memory()
        if mem:
            out["device_memory"] = mem
    except Exception as e:  # noqa: BLE001
        out["device_memory_error"] = str(e)
    return out


class RunGuard:
    """Watchdog around one training run's boosting loop.

    `tick(iteration)` is called by the engine after each completed
    iteration; `start()`/`stop()` bracket the loop.  The watchdog thread
    polls the time since the last tick against the active deadline:

    * before the first tick: `first_deadline_s` (default
      `max(10 * stall_floor_s, 600)`) — the first iteration compiles the
      whole device program and legitimately takes minutes;
    * after it: `max(stall_floor_s, stall_factor * median(recent iteration
      times))` — adapts to the workload instead of hardcoding a budget.

    On a trip the guard writes the stall diagnosis (atomic JSON), then
    calls `on_stall(diagnosis)` if given (tests), else flushes the host
    I/O writer with a bounded wait and `os._exit(STALL_EXIT_CODE)` —
    the main thread is by definition wedged, so a thread-side process
    exit is the only honest way out.
    """

    def __init__(self, diagnosis_dir: str, rank: int = 0, *,
                 stall_floor_s: float = 120.0, stall_factor: float = 20.0,
                 first_deadline_s: Optional[float] = None,
                 knobs: Optional[Dict[str, Any]] = None,
                 heartbeat_path: Optional[str] = None,
                 writer=None,
                 on_stall: Optional[Callable[[Dict[str, Any]], None]] = None,
                 poll_interval: Optional[float] = None):
        self.dir = os.fspath(diagnosis_dir)
        self.rank = int(rank)
        self.stall_floor_s = float(stall_floor_s)
        self.stall_factor = float(stall_factor)
        self.first_deadline_s = (float(first_deadline_s)
                                 if first_deadline_s is not None
                                 else max(10.0 * self.stall_floor_s, 600.0))
        self.knobs: Dict[str, Any] = dict(knobs or {})
        self.heartbeat_path = heartbeat_path
        self.writer = writer
        self.on_stall = on_stall
        self.poll_interval = (float(poll_interval) if poll_interval
                              else min(1.0, max(self.stall_floor_s / 4.0,
                                                0.05)))
        self._durations: deque = deque(maxlen=_MEDIAN_WINDOW)
        self._last_tick: Optional[float] = None
        self._last_iteration: Optional[int] = None
        self._started_at: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tripped = False
        # guards the tick state shared with the watchdog thread
        # (_last_tick/_last_iteration/_durations/_tripped): uncontended
        # acquisition is ~100ns, noise next to one boosting iteration —
        # and the unsynchronized read/write pair was the first true
        # finding of tpulint's thread-shared-state sweep (ISSUE 9)
        self._state_lock = threading.Lock()

    # ----------------------------------------------------------- engine API
    def start(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        self._started_at = time.monotonic()
        self._touch_heartbeat()
        self._thread = threading.Thread(target=self._watch,
                                        name="lgbm-tpu-stall-watchdog",
                                        daemon=True)
        self._thread.start()

    def tick(self, iteration: int) -> None:
        """One boosting iteration completed.  Cheap: a lock, a monotonic
        read, a deque append and (in supervised runs) one utime on the
        heartbeat file."""
        now = time.monotonic()
        with self._state_lock:
            prev = self._last_tick if self._last_tick is not None \
                else self._started_at
            if prev is not None and self._last_tick is not None:
                self._durations.append(now - prev)
            self._last_tick = now
            self._last_iteration = int(iteration)
        self._touch_heartbeat()

    def update_knobs(self, **knobs) -> None:
        """Refresh the risky-knob fingerprint (the engine learns e.g.
        whether the sharded wave engaged only after the booster builds)."""
        self.knobs.update(knobs)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def tripped(self) -> bool:
        with self._state_lock:
            return self._tripped

    # ------------------------------------------------------------ deadlines
    def median_iter_s(self) -> Optional[float]:
        with self._state_lock:
            return self._median_locked()

    def _median_locked(self) -> Optional[float]:
        if not self._durations:
            return None
        s = sorted(self._durations)
        return s[len(s) // 2]

    def current_deadline_s(self) -> float:
        with self._state_lock:
            med = self._median_locked()
            if self._last_tick is None or med is None:
                return self.first_deadline_s
            return max(self.stall_floor_s, self.stall_factor * med)

    # ------------------------------------------------------------- watchdog
    def _touch_heartbeat(self) -> None:
        if not self.heartbeat_path:
            return
        try:
            with open(self.heartbeat_path, "a"):
                os.utime(self.heartbeat_path, None)
        except OSError:
            pass  # a lost heartbeat must never kill training

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval):
            with self._state_lock:
                anchor = self._last_tick if self._last_tick is not None \
                    else self._started_at
            if anchor is None:
                continue
            silent_s = time.monotonic() - anchor
            deadline = self.current_deadline_s()
            if silent_s < deadline:
                continue
            with self._state_lock:
                self._tripped = True
            diagnosis = self.build_diagnosis(silent_s, deadline)
            self.write_diagnosis(diagnosis)
            if self.on_stall is not None:
                try:
                    self.on_stall(diagnosis)
                except Exception:  # noqa: BLE001
                    pass
                return
            self._flush_and_exit(diagnosis)
            return

    # ------------------------------------------------------------ diagnosis
    def build_diagnosis(self, silent_s: float,
                        deadline_s: float) -> Dict[str, Any]:
        from ..observability.events import get_event_logger
        from ..observability.flightrec import flight_recorder
        last_event = None
        lg = get_event_logger()
        if lg is not None:
            last_event = getattr(lg, "last_record", None)
        with self._state_lock:
            med = self._median_locked()
            first = self._last_tick is None
            last_it = self._last_iteration
        return {
            "kind": "stall",
            "rank": self.rank,
            "pid": os.getpid(),
            "ts": time.time(),
            "silent_s": round(silent_s, 3),
            "deadline_s": round(deadline_s, 3),
            "stall_floor_s": self.stall_floor_s,
            "stall_factor": self.stall_factor,
            "first_iteration": first,
            "last_iteration": last_it,
            "median_iter_s": round(med, 6) if med is not None else None,
            "knobs": dict(self.knobs),
            "last_event": last_event,
            # what the run was DOING just before it went silent: the
            # flight recorder's newest iteration records (lock-free
            # read — this thread is diagnosing a wedged process)
            "flight": flight_recorder.tail(32),
            "jax": _jax_snapshot(),
            "stacks": _dump_all_stacks(),
            "exit_code": STALL_EXIT_CODE,
        }

    def write_diagnosis(self, diagnosis: Dict[str, Any]) -> Optional[str]:
        """Atomic, SYNCHRONOUS write — never through the AsyncWriter,
        whose thread may be part of what is hung.  The full flight
        record lands next to it (flight-rank<r>.json) through the same
        sync path, so the supervisor can surface both tails."""
        try:
            from ..observability.flightrec import dump_flight_record
            dump_flight_record(self.dir, rank=self.rank, reason="stall")
        except Exception:  # noqa: BLE001 - diagnosis must not throw
            pass
        path = stall_file_path(self.dir, self.rank)
        try:
            atomic_write_text(path, json.dumps(diagnosis, indent=1,
                                               default=str))
            return path
        except OSError as e:
            log.warning(f"Could not write the stall diagnosis to {path}: "
                        f"{e}")
            return None

    def _flush_and_exit(self, diagnosis: Dict[str, Any]) -> None:
        import sys
        msg = (f"[stall-watchdog] rank {self.rank}: no boosting iteration "
               f"completed in {diagnosis['silent_s']:.1f}s (deadline "
               f"{diagnosis['deadline_s']:.1f}s, last iteration "
               f"{diagnosis['last_iteration']}); wrote "
               f"{stall_file_path(self.dir, self.rank)}; exiting "
               f"{STALL_EXIT_CODE} (hang)\n")
        try:
            sys.stderr.write(msg)
            sys.stderr.flush()
        except Exception:  # noqa: BLE001
            pass
        # bounded flush FIRST (the writer thread may itself be wedged —
        # never wait on it without a deadline), then the terminal stall
        # record bypasses the writer entirely (emit_event_sync: private
        # handle, no queue — queueing through the AsyncWriter here could
        # block this exit path forever on a full bounded queue, the
        # signal-handler-safety hazard)
        if self.writer is not None:
            try:
                from ..observability import hostio
                self.writer.flush(timeout=hostio.TERMINAL_FLUSH_TIMEOUT_S)
            except Exception:  # noqa: BLE001
                pass
        try:
            from ..observability.events import emit_event_sync
            emit_event_sync("stall", rank=self.rank,
                            silent_s=diagnosis["silent_s"],
                            deadline_s=diagnosis["deadline_s"],
                            last_iteration=diagnosis["last_iteration"])
        except Exception:  # noqa: BLE001
            pass
        try:
            from ..observability.events import get_event_logger
            lg = get_event_logger()
            if lg is not None:
                lg.flush(timeout=1.0)
        except Exception:  # noqa: BLE001
            pass
        os._exit(STALL_EXIT_CODE)


# --------------------------------------------------------------------------
# degradation ladder
# --------------------------------------------------------------------------

def next_degradation(effective: Dict[str, Any],
                     already: List[str]) -> Optional[str]:
    """First ladder knob that is still enabled under `effective` values
    and not already degraded, or None when the ladder is exhausted."""
    for knob, _off in DEGRADE_LADDER:
        if knob in already:
            continue
        if knob_enabled(knob, effective.get(knob)):
            return knob
    return None


def disabled_value(knob: str) -> Any:
    for k, off in DEGRADE_LADDER:
        if k == knob:
            return off
    raise KeyError(knob)


def _load_state(metrics_dir: str) -> Dict[str, Any]:
    path = os.path.join(metrics_dir, DEGRADE_STATE)
    try:
        with open(path) as f:
            state = json.load(f)
        if isinstance(state.get("degraded_knobs"), list):
            return state
    except (OSError, ValueError):
        pass
    return {"degraded_knobs": [], "stalls_handled": 0}


def _save_state(metrics_dir: str, state: Dict[str, Any]) -> None:
    atomic_write_text(os.path.join(metrics_dir, DEGRADE_STATE),
                      json.dumps(state, indent=1))


def apply_auto_degrade(cfg, params: Dict[str, Any],
                       metrics_dir: Optional[str],
                       rank: int = 0) -> Dict[str, Any]:
    """Engine-side ladder step (single-process runs): called at train()
    startup when `auto_degrade=true`.

    Consumes a pending `stall-rank<rank>.json` left by the previous
    attempt's watchdog: picks the next enabled ladder knob, persists the
    accumulated set in `<metrics_dir>/degrade-state.json`, archives the
    stall file (so the NEXT stall degrades the NEXT knob), and applies
    every accumulated degradation to both `cfg` and `params` so the
    restarted run actually trains without them.  Returns
    `{"applied": [...all active degradations...], "new": [knob-or-none],
    "stall": <diagnosis dict or None>}`.
    """
    out = {"applied": [], "new": [], "stall": None}
    if not metrics_dir:
        return out
    state = _load_state(metrics_dir)
    spath = stall_file_path(metrics_dir, rank)
    if os.path.exists(spath):
        try:
            with open(spath) as f:
                out["stall"] = json.load(f)
        except (OSError, ValueError) as e:
            log.warning(f"Unreadable stall diagnosis {spath}: {e}")
        effective = {k: getattr(cfg, k) for k in _LADDER_KNOBS}
        # the previous run already trained with the accumulated set off;
        # its fingerprint (if readable) is authoritative for what was
        # live when it hung
        fp = (out["stall"] or {}).get("knobs") or {}
        for k in _LADDER_KNOBS:
            if k in fp:
                effective[k] = fp[k]
        knob = next_degradation(effective, state["degraded_knobs"])
        handled = int(state.get("stalls_handled", 0))
        # archive: the stall file is consumed exactly once per stall
        try:
            os.replace(spath, f"{spath}.handled-{handled}")
        except OSError:
            pass
        state["stalls_handled"] = handled + 1
        if knob is not None:
            state["degraded_knobs"].append(knob)
            out["new"].append(knob)
            log.warning(
                f"auto_degrade: previous attempt hung (stall diagnosis "
                f"consumed from {spath}); disabling {knob} and resuming "
                f"from the last checkpoint "
                f"(ladder: {' -> '.join(_LADDER_KNOBS)})")
        else:
            log.warning("auto_degrade: previous attempt hung but the "
                        "degradation ladder is exhausted (all risky knobs "
                        "already disabled); retrying unchanged")
        _save_state(metrics_dir, state)
    for knob in state["degraded_knobs"]:
        off = disabled_value(knob)
        setattr(cfg, knob, off)
        params[knob] = off
        out["applied"].append(knob)
    return out


def degraded_knobs(metrics_dir: Optional[str]) -> List[str]:
    """The accumulated degradations recorded for a run directory."""
    if not metrics_dir:
        return []
    return list(_load_state(metrics_dir)["degraded_knobs"])
