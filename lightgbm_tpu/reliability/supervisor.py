"""Worker-process supervision for the multi-process launcher.

The reference engine's socket linkers notice a dead peer quickly; the
TPU-native launcher's workers instead block inside XLA collectives when
a peer dies, so the SPAWNING process must watch the children: poll every
worker, and on the first non-zero exit kill the rest of the cluster
immediately instead of letting the survivors stall to the global
timeout (ISSUE: a rank dead at t=0 previously blocked every other rank
for the full 900 s deadline).

Dead PIDs are the easy half.  The MULTICHIP_r05 failure mode is a rank
that stays LIVE while wedged inside a collective — no exit code ever
arrives.  Two complementary detectors close that hole (ISSUE 7):

* each worker's `RunGuard` (reliability/guard.py) ticks a per-rank
  heartbeat FILE once per boosting iteration; the supervisor polls the
  files' mtimes and, when every process is still alive but a heartbeat
  has gone stale past `stall_timeout`, kills the cluster and classifies
  the stale rank as HUNG — surfacing its `stall-rank<r>.json` tail (the
  guard usually wrote one just before, or will not get the chance —
  either way the mtime is the ground truth);
* a worker whose own watchdog fired exits with `STALL_EXIT_CODE`, which
  `classify_returncode` maps to "hang" rather than "crash", so the retry
  layer can choose the degradation ladder instead of a plain relaunch.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional

from .guard import classify_returncode, stall_file_path


@dataclass
class WorkerFailure:
    rank: int
    returncode: Optional[int]  # None = killed after timeout/stall
    log_tail: str
    kind: str = "crash"  # "crash" | "hang" | "timeout"
    stall_tail: str = ""  # tail of stall-rank<r>.json when one exists
    flight_tail: str = ""  # tail of flight-rank<r>.json when one exists


@dataclass
class SuperviseResult:
    ok: bool
    timed_out: bool
    failures: List[WorkerFailure] = field(default_factory=list)

    @property
    def hang(self) -> bool:
        """True when the attempt died of a stall (live-but-hung rank or
        a worker's own watchdog), not a crash — the degradation ladder
        only makes sense for hangs."""
        return any(f.kind == "hang" for f in self.failures)

    @property
    def classification(self) -> str:
        """Dominant failure kind of the attempt, for event logs and the
        retry policy: permanence ('lost') outranks hangs, hangs outrank
        crashes, and 'preempt' only when nothing worse happened (a
        preempted rank plus a crashed rank is still a crash)."""
        kinds = {f.kind for f in self.failures}
        for k in ("lost", "hang", "crash", "preempt", "timeout"):
            if k in kinds:
                return k
        return "timeout" if self.timed_out else "ok"

    def describe(self) -> str:
        if self.ok:
            return "all workers exited 0"
        parts = []
        if self.timed_out:
            parts.append("cluster hit the launch deadline")
        for f in self.failures:
            if f.returncode is None:
                rc = ("killed (heartbeat stale: live-but-hung)"
                      if f.kind == "hang" else "killed (timeout)")
            else:
                rc = f"exit code {f.returncode} ({f.kind})"
            parts.append(f"rank {f.rank} failed ({rc}); log tail:\n"
                         f"{f.log_tail or '(empty log)'}")
            if f.stall_tail:
                parts.append(f"rank {f.rank} stall diagnosis "
                             f"(stall-rank{f.rank}.json):\n{f.stall_tail}")
            if f.flight_tail:
                parts.append(f"rank {f.rank} flight record "
                             f"(flight-rank{f.rank}.json):\n"
                             f"{f.flight_tail}")
        return "\n".join(parts)


def tail_file(path: str, max_bytes: int = 4096) -> str:
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(0, size - max_bytes))
            data = f.read().decode("utf-8", "replace")
        if size > max_bytes:
            data = "...(truncated)...\n" + data
        return data.strip()
    except OSError:
        return "(log unavailable)"


def _stall_tail(stall_dir: Optional[str], rank: int) -> str:
    """Tail of rank's stall diagnosis, '' when none was written."""
    if not stall_dir:
        return ""
    path = stall_file_path(stall_dir, rank)
    if not os.path.exists(path):
        return ""
    return tail_file(path, max_bytes=2048)


def _flight_tail(stall_dir: Optional[str], rank: int) -> str:
    """Tail of rank's flight record — the guard dumps one on a stall
    and the engine dumps one on a crash, so a classified failure
    carries what the rank was DOING, not just where it died."""
    if not stall_dir:
        return ""
    from ..observability.flightrec import flight_file_path
    path = flight_file_path(stall_dir, rank)
    if not os.path.exists(path):
        return ""
    return tail_file(path, max_bytes=2048)


def _stale_ranks(heartbeats: Optional[List[str]], stall_timeout: float,
                 started: float, pending) -> List[int]:
    """Ranks whose heartbeat file has not been touched for
    `stall_timeout` seconds.  A missing file counts from launch time:
    a worker that never completed one iteration is exactly the
    wedged-in-first-collective shape."""
    if not heartbeats or stall_timeout <= 0:
        return []
    now = time.time()
    stale = []
    for r in sorted(pending):
        try:
            age = now - os.path.getmtime(heartbeats[r])
        except OSError:
            age = now - started
        if age >= stall_timeout:
            stale.append(r)
    return stale


def supervise(procs, log_paths: List[str], timeout: float,
              poll_interval: float = 0.25,
              heartbeats: Optional[List[str]] = None,
              stall_timeout: float = 0.0,
              stall_dir: Optional[str] = None) -> SuperviseResult:
    """Watch `procs` until they all exit, one fails, a heartbeat goes
    stale, or `timeout` passes.

    On the first non-zero exit the remaining workers are killed at once
    (they are wedged in collectives waiting for the dead rank).  With
    `heartbeats` (one path per rank) and `stall_timeout > 0`, a rank
    that is ALIVE but has not ticked for `stall_timeout` seconds is
    classified as hung and the cluster is killed the same way — the old
    behavior was to wait out the full `timeout` on such ranks.  Always
    reaps every process before returning."""
    started = time.time()
    deadline = time.monotonic() + timeout
    pending = set(range(len(procs)))
    failed: List[int] = []
    stalled: List[int] = []
    timed_out = False
    while pending:
        for r in sorted(pending):
            rc = procs[r].poll()
            if rc is None:
                continue
            pending.discard(r)
            if rc != 0:
                failed.append(r)
        if failed or not pending:
            break
        stalled = _stale_ranks(heartbeats, stall_timeout, started, pending)
        if stalled:
            break
        if time.monotonic() >= deadline:
            timed_out = True
            break
        time.sleep(poll_interval)

    for r in pending:  # kill survivors: wedged (peer died/hung) or overdue
        procs[r].kill()
    for p in procs:
        try:
            p.wait(timeout=30)
        except Exception:
            p.kill()
            p.wait()

    failures = [
        WorkerFailure(r, procs[r].returncode, tail_file(log_paths[r]),
                      kind=classify_returncode(procs[r].returncode),
                      stall_tail=_stall_tail(stall_dir, r),
                      flight_tail=_flight_tail(stall_dir, r))
        for r in failed]
    for r in stalled:
        # killed by US for heartbeat staleness: the returncode is the
        # kill signal, which classify_returncode would miscall "crash"
        failures.append(WorkerFailure(
            r, None, tail_file(log_paths[r]), kind="hang",
            stall_tail=_stall_tail(stall_dir, r),
            flight_tail=_flight_tail(stall_dir, r)))
    if timed_out:
        failures.extend(
            WorkerFailure(r, None, tail_file(log_paths[r]), kind="timeout",
                          stall_tail=_stall_tail(stall_dir, r),
                          flight_tail=_flight_tail(stall_dir, r))
            for r in sorted(pending))
    ok = not failures and not timed_out
    return SuperviseResult(ok=ok, timed_out=timed_out, failures=failures)
