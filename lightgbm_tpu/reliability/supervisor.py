"""Worker-process supervision for the multi-process launcher.

The reference engine's socket linkers notice a dead peer quickly; the
TPU-native launcher's workers instead block inside XLA collectives when
a peer dies, so the SPAWNING process must watch the children: poll every
worker, and on the first non-zero exit kill the rest of the cluster
immediately instead of letting the survivors stall to the global
timeout (ISSUE: a rank dead at t=0 previously blocked every other rank
for the full 900 s deadline)."""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class WorkerFailure:
    rank: int
    returncode: Optional[int]  # None = killed after timeout
    log_tail: str


@dataclass
class SuperviseResult:
    ok: bool
    timed_out: bool
    failures: List[WorkerFailure] = field(default_factory=list)

    def describe(self) -> str:
        if self.ok:
            return "all workers exited 0"
        parts = []
        if self.timed_out:
            parts.append("cluster hit the launch deadline")
        for f in self.failures:
            rc = "killed (timeout)" if f.returncode is None \
                else f"exit code {f.returncode}"
            parts.append(f"rank {f.rank} failed ({rc}); log tail:\n"
                         f"{f.log_tail or '(empty log)'}")
        return "\n".join(parts)


def tail_file(path: str, max_bytes: int = 4096) -> str:
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(0, size - max_bytes))
            data = f.read().decode("utf-8", "replace")
        if size > max_bytes:
            data = "...(truncated)...\n" + data
        return data.strip()
    except OSError:
        return "(log unavailable)"


def supervise(procs, log_paths: List[str], timeout: float,
              poll_interval: float = 0.25) -> SuperviseResult:
    """Watch `procs` until they all exit, one fails, or `timeout` passes.

    On the first non-zero exit the remaining workers are killed at once
    (they are wedged in collectives waiting for the dead rank).  Always
    reaps every process before returning."""
    deadline = time.monotonic() + timeout
    pending = set(range(len(procs)))
    failed: List[int] = []
    timed_out = False
    while pending:
        for r in sorted(pending):
            rc = procs[r].poll()
            if rc is None:
                continue
            pending.discard(r)
            if rc != 0:
                failed.append(r)
        if failed or not pending:
            break
        if time.monotonic() >= deadline:
            timed_out = True
            break
        time.sleep(poll_interval)

    for r in pending:  # kill survivors: wedged (peer died) or overdue
        procs[r].kill()
    for p in procs:
        try:
            p.wait(timeout=30)
        except Exception:
            p.kill()
            p.wait()

    failures = [WorkerFailure(r, procs[r].returncode, tail_file(log_paths[r]))
                for r in failed]
    if timed_out:
        failures.extend(
            WorkerFailure(r, None, tail_file(log_paths[r]))
            for r in sorted(pending))
    ok = not failures and not timed_out
    return SuperviseResult(ok=ok, timed_out=timed_out, failures=failures)
