"""Persistent multi-model serving daemon (docs/Serving.md).

The "millions of users" layer over the device inference stack: a
long-lived process that owns the device and composes the compiled
bucket ladder (inference/), a hot-swap model registry (registry.py),
and a request coalescer (coalescer.py) into sustained throughput with
bounded tail latency.  `python -m lightgbm_tpu serve` is the CLI front
end; `ServingClient` the in-process API; `bench.py --serve` the
closed-loop p50/p99 bench.
"""

from .coalescer import Coalescer, ServeFuture, ServeRequest
from .daemon import ServingClient, ServingDaemon, serve_counters_reset
from .frontend import ServeFrontend, start_frontend
from .registry import LoadHandle, ModelEntry, ModelRegistry

__all__ = [
    "Coalescer", "ServeFuture", "ServeRequest",
    "ServingClient", "ServingDaemon", "serve_counters_reset",
    "ServeFrontend", "start_frontend",
    "LoadHandle", "ModelEntry", "ModelRegistry",
]
