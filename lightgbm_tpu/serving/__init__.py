"""Persistent multi-model serving daemon + replica fleet
(docs/Serving.md).

The "millions of users" layer over the device inference stack: a
long-lived process that owns the device and composes the compiled
bucket ladder (inference/), a hot-swap model registry (registry.py),
and a request coalescer (coalescer.py) into sustained throughput with
bounded tail latency — and, above it, the serving FAULT DOMAIN
(ISSUE 13): K replica daemons under poll-based supervision (fleet.py)
behind a router (router.py) that retries across replicas with deadline
propagation, sheds load when the fleet saturates, and rolls new model
versions out replica-by-replica with canary auto-rollback.
`python -m lightgbm_tpu serve` / `serve-fleet` are the CLI front ends;
`ServingClient` the in-process/TCP API; `bench.py --serve` /
`--serve-fleet` the closed-loop benches.
"""

from .coalescer import Coalescer, ServeFuture, ServeRequest, ShedError
from .daemon import ServingClient, ServingDaemon, serve_counters_reset
from .fleet import (FleetAggregator, ReplicaEndpoint, ReplicaFleet,
                    ReplicaState)
from .frontend import (LineClient, ServeFrontend, ServeUdsFrontend,
                       start_frontend, start_uds_frontend)
from .registry import LoadHandle, ModelEntry, ModelRegistry
from .router import (NoReplicaError, OverloadedError, Router, RouterReply,
                     start_router_frontend)

__all__ = [
    "Coalescer", "ServeFuture", "ServeRequest", "ShedError",
    "ServingClient", "ServingDaemon", "serve_counters_reset",
    "FleetAggregator", "ReplicaEndpoint", "ReplicaFleet", "ReplicaState",
    "LineClient", "ServeFrontend", "ServeUdsFrontend", "start_frontend",
    "start_uds_frontend",
    "LoadHandle", "ModelEntry", "ModelRegistry",
    "NoReplicaError", "OverloadedError", "Router", "RouterReply",
    "start_router_frontend",
]
