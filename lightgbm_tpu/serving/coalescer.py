"""Request coalescing: a bounded queue + one dispatcher thread.

A TPU serves a 4096-row padded bucket in roughly the time it serves 4
rows — per-dispatch overhead (host -> device transfer, program launch)
dominates tiny batches.  So concurrent small requests are MERGED: the
coalescer thread pops the first queued request, drains more for up to
`serve_max_coalesce_wait_ms` (bounded by `serve_max_batch_rows`), and
dispatches ONE padded bucket per (model entry, mode, width) group, then
splits the result rows back per request.  The wait knob is the explicit
batching-efficiency vs p99 trade: 0 disables waiting (drain whatever is
already queued, lowest latency), larger values build fuller buckets.

Invariants the tests pin:
* order/identity — responses are row-slices of the request's own rows;
  grouping keys include the model ENTRY (a specific version acquired at
  submit), so a hot swap can never cross-wire rows between versions;
* bounded queue, shed fast — a full queue FAILS the submit immediately
  with the structured `ShedError` instead of blocking the submitter
  (ISSUE 13): a blocked frontend thread turns one slow replica into a
  stalled fleet, while a structured shed lets the router retry the
  request on another replica within its deadline.  `serve_shed` counts
  every shed and `last_shed_age_s()` feeds the health probe's
  `shedding` flag so the fleet admission controller can reject before
  even trying;
* drain — `stop(drain=True)` completes every queued request before the
  thread exits (the SIGTERM path), failed dispatches park the error on
  every affected future rather than killing the thread, and requests
  the drain DEADLINE abandons are counted and announced with one
  `serve_drain_abandoned` event (sync write path — stop() runs from
  the SIGTERM hook) instead of disappearing silently.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..observability.flightrec import flight_recorder
from ..observability.registry import LatencyWindow, global_registry
from ..observability.tracing import (MAX_SPANS_PER_REQUEST, TraceContext,
                                     make_span)
from ..utils import log
from ..utils.timer import global_timer


class ShedError(RuntimeError):
    """Structured load-shed rejection: the replica's bounded queue is
    full (or a serve_shed fault forced the path), so this submit failed
    FAST instead of blocking.  Idempotent predicts make a retry on a
    different replica safe — the router does exactly that, and answers
    `overloaded` only once every replica sheds."""

    def __init__(self, message: str, pending: int = 0, depth: int = 0):
        super().__init__(message)
        self.pending = int(pending)
        self.depth = int(depth)


class ServeFuture:
    """Completion handle for one request: result rows, model version,
    submit->response latency; `result()` blocks and re-raises errors."""

    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result = None
        self._error: Optional[BaseException] = None
        self._version: Optional[int] = None
        self._latency_ms: Optional[float] = None
        self._spans: Optional[List[dict]] = None

    def _set(self, result=None, error=None, version=None,
             latency_ms=None, spans=None) -> None:
        with self._lock:
            self._result = result
            self._error = error
            self._version = version
            self._latency_ms = latency_ms
            self._spans = spans
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("Serving request did not complete in "
                               f"{timeout}s")
        with self._lock:
            if self._error is not None:
                raise self._error
            return self._result

    @property
    def version(self) -> Optional[int]:
        with self._lock:
            return self._version

    @property
    def latency_ms(self) -> Optional[float]:
        with self._lock:
            return self._latency_ms

    @property
    def spans(self) -> Optional[List[dict]]:
        """Completed child spans for a trace-sampled request (None
        otherwise): the replica-side half of the cross-process waterfall
        the response envelope carries back to the router."""
        with self._lock:
            return self._spans


class ServeRequest:
    __slots__ = ("entry", "X", "mode", "n", "future", "t_submit",
                 "early_stop", "t_coalesce", "trace")

    def __init__(self, entry, X: np.ndarray, mode: str,
                 early_stop: Optional[Tuple[int, float]] = None,
                 trace: Optional[TraceContext] = None):
        self.entry = entry
        self.X = X
        self.mode = mode
        self.early_stop = early_stop
        self.n = int(X.shape[0])
        self.future = ServeFuture()
        self.t_submit = time.monotonic()
        # propagated trace context (docs/Observability.md "Distributed
        # tracing"): when present its id correlates this request across
        # processes; when additionally `sampled`, the dispatcher builds
        # real child spans from the stage stamps below
        self.trace = trace
        # stamped by the dispatcher when the request leaves the queue;
        # the flight recorder's stage breakdown reads it
        self.t_coalesce: Optional[float] = None


class Coalescer:
    """One dispatcher thread merging queued requests into bucket
    dispatches (docs/Serving.md)."""

    # EWMA weight of the newest inter-arrival gap (adaptive mode): ~10
    # arrivals of history, enough to ride out one odd gap without
    # lagging a real load change by more than a few requests
    _EWMA_ALPHA = 0.2

    def __init__(self, max_wait_ms: float = 2.0, queue_depth: int = 1024,
                 max_batch_rows: int = 65536,
                 latency_window: Optional[LatencyWindow] = None,
                 trace_sample: int = 0, adaptive: bool = False):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(queue_depth), 1))
        self._max_wait_s = max(float(max_wait_ms), 0.0) / 1000.0
        self._max_rows = max(int(max_batch_rows), 1)
        self._window = latency_window
        # adaptive coalescing (serve_adaptive_coalesce=auto): track an
        # EWMA of request inter-arrival gaps at submit and derive the
        # per-batch wait from it — capped at the static window under
        # burst (batch shapes unchanged), shrunk to 0 when arrivals are
        # sparse (nobody else is coming inside the window, so waiting
        # would only buy p50).  Guarded by self._lock: submit threads
        # write, the dispatcher reads.
        self._adaptive = bool(adaptive)
        self._ewma_gap_s: Optional[float] = None
        self._last_arrival: Optional[float] = None
        # flight-recorder request tracing: every `trace_sample`-th
        # request gets a full enqueue->coalesce->dispatch->settle->
        # respond stage record (0 = off); only touched by the dispatcher
        # thread, so a plain counter suffices
        self._trace_sample = max(int(trace_sample), 0)
        self._req_seq = 0
        self._stop = threading.Event()
        # set when the drain deadline has passed (or drain was not
        # requested): the dispatcher must NOT start another batch —
        # whatever is still queued gets failed as abandoned
        self._abandon = threading.Event()
        self._lock = threading.Lock()
        self._closing = False
        self._thread: Optional[threading.Thread] = None
        # monotonic stamp of the most recent shed; the health probe's
        # `shedding` flag reads it through last_shed_age_s()
        self._last_shed: Optional[float] = None
        # requests failed by the most recent drain deadline (stop())
        self.last_abandoned = 0

    # -------------------------------------------------------------- control
    def start(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._abandon.clear()
                self._closing = False
                self._thread = threading.Thread(
                    target=self._loop, name="lgbm-serve-coalescer",
                    daemon=True)
                self._thread.start()

    @property
    def running(self) -> bool:
        with self._lock:
            t = self._thread
        return t is not None and t.is_alive()

    def submit(self, req: ServeRequest) -> None:
        """Queue one request; a FULL queue sheds (raises ShedError)
        instead of blocking — fail fast so the router can retry on
        another replica while the deadline still has budget."""
        with self._lock:
            closing = self._closing or self._thread is None
            if not closing and self._adaptive:
                now = time.monotonic()
                if self._last_arrival is not None:
                    gap = now - self._last_arrival
                    self._ewma_gap_s = gap if self._ewma_gap_s is None \
                        else ((1.0 - self._EWMA_ALPHA) * self._ewma_gap_s
                              + self._EWMA_ALPHA * gap)
                self._last_arrival = now
        if closing:
            raise RuntimeError("Serving daemon is not accepting requests "
                               "(stopped or draining)")
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self.shed(reason="queue full")

    def shed(self, reason: str = "queue full") -> None:
        """Record one load shed and raise the structured ShedError (the
        serve_shed fault point calls this to force the path)."""
        with self._lock:
            self._last_shed = time.monotonic()
        global_registry.inc("serve_shed")
        raise ShedError(
            f"request shed: {reason} "
            f"({self._q.qsize()}/{self._q.maxsize} queued); retry on "
            "another replica", pending=self._q.qsize(),
            depth=self._q.maxsize)

    def last_shed_age_s(self) -> Optional[float]:
        """Seconds since the most recent shed, None when never shed —
        the health probe's `shedding` flag is `age < window`."""
        with self._lock:
            last = self._last_shed
        return None if last is None else time.monotonic() - last

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> bool:
        """Stop the dispatcher.  `drain=True` first completes everything
        queued (bounded by `timeout`); anything still queued after the
        deadline fails with a RuntimeError on its future.  Returns True
        when the queue fully drained."""
        with self._lock:
            self._closing = True
        drained = True
        if drain:
            deadline = (time.monotonic() + timeout) if timeout else None
            while self._q.unfinished_tasks > 0:
                if deadline is not None and time.monotonic() >= deadline:
                    drained = False
                    break
                if not self.running:
                    drained = self._q.unfinished_tasks == 0
                    break
                time.sleep(0.005)
        # past this point nothing more may dispatch: a missed drain
        # deadline (or drain=False) means the remaining queue is
        # ABANDONED, not quietly served during the thread join below
        self._abandon.set()
        self._stop.set()
        with self._lock:
            t = self._thread
            self._thread = None
        if t is not None and t.is_alive():
            # bounded: the dispatcher pops with a 50 ms timeout and
            # re-checks the stop event, so this join is capped
            t.join(timeout=10.0)
        # fail whatever the drain deadline abandoned — and SAY SO: a
        # preemption drain that quietly dropped queued requests would
        # read as a clean exit in the event log (ISSUE 13 satellite)
        leftovers: List[ServeRequest] = []
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except queue.Empty:
                break
        for req in leftovers:
            req.future._set(error=RuntimeError("Serving daemon stopped "
                                               "before dispatch"))
            req.entry.release()
            self._q.task_done()
        self.last_abandoned = len(leftovers)
        if leftovers:
            global_registry.inc("serve_drain_abandoned", len(leftovers))
            # sync write path: stop() runs from the SIGTERM preemption
            # hook, where the AsyncWriter may be exactly what is stuck
            # (the PR-9 terminal-event rule)
            from ..observability.events import emit_event_sync
            try:
                emit_event_sync("serve_drain_abandoned",
                                abandoned=len(leftovers),
                                timeout_s=timeout)
            except Exception:  # noqa: BLE001 - telemetry must not block the exit
                pass
            log.warning(f"Serving drain abandoned {len(leftovers)} queued "
                        f"request(s) at the {timeout}s deadline")
        return drained and not leftovers

    @property
    def pending(self) -> int:
        return self._q.qsize()

    def effective_wait_s(self) -> float:
        """The wait window for the NEXT batch.  Static mode: the
        configured window unconditionally.  Adaptive mode: arrivals
        coming faster than the window (EWMA gap <= window) keep the FULL
        static window — burst batches coalesce exactly as before — while
        sparse arrivals (EWMA gap beyond the window, or no history yet)
        shrink it to 0: the expected next arrival misses the window
        anyway, so waiting only inflates p50 (docs/Serving.md)."""
        if not self._adaptive:
            return self._max_wait_s
        with self._lock:
            gap = self._ewma_gap_s
        if gap is None or gap > self._max_wait_s:
            return 0.0
        return self._max_wait_s

    # --------------------------------------------------------------- worker
    def _loop(self) -> None:
        while True:
            if self._abandon.is_set():
                return  # stop() fails the remaining queue itself
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            first.t_coalesce = time.monotonic()
            batch = [first]
            rows = first.n
            wait_s = self.effective_wait_s()
            if wait_s > 0 and not self._stop.is_set():
                deadline = time.monotonic() + wait_s
                while rows < self._max_rows:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=rem)
                    except queue.Empty:
                        break
                    nxt.t_coalesce = time.monotonic()
                    batch.append(nxt)
                    rows += nxt.n
            else:
                while rows < self._max_rows:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                    nxt.t_coalesce = time.monotonic()
                    batch.append(nxt)
                    rows += nxt.n
            try:
                # serve_slow fault point: latency injected on the
                # dispatcher thread, just before the dispatch — the
                # queue keeps filling behind it (docs/Reliability.md).
                # Unconditional (not behind active()): the one-shot spec
                # already fired at submit, arming the pending sleep.
                from ..reliability import faults
                faults.consume_serve_slow()
                self._dispatch(batch)
            finally:
                for _ in batch:
                    self._q.task_done()

    def _dispatch(self, batch: List[ServeRequest]) -> None:
        # group by (entry, mode, width): the ENTRY key pins each request
        # to the model version it acquired at submit, so a concurrent
        # hot swap splits cleanly into an old-version group and a
        # new-version group — never a mixed dispatch
        groups: Dict[tuple, List[ServeRequest]] = {}
        for req in batch:
            key = (id(req.entry), req.mode, req.X.shape[1], req.early_stop)
            groups.setdefault(key, []).append(req)
        global_registry.inc("serve_batches")
        # coalesce-shape telemetry: the batch-size histogram says what
        # the wait-knob trade actually bought (flight recorder + dump)
        flight_recorder.record_batch(len(batch),
                                     sum(r.n for r in batch))
        for reqs in groups.values():
            self._dispatch_group(reqs)

    def _dispatch_group(self, reqs: List[ServeRequest]) -> None:
        entry = reqs[0].entry
        mode = reqs[0].mode
        dp = entry.predictor
        try:
            t_dispatch = time.monotonic()
            with global_timer.scope("Serve::dispatch"):
                X = (np.concatenate([r.X for r in reqs], axis=0)
                     if len(reqs) > 1 else reqs[0].X)
                if mode == "leaf":
                    out = dp.predict_leaf(X)
                elif mode == "raw":
                    out = dp.predict_raw(X, early_stop=reqs[0].early_stop)
                else:
                    out = dp.predict(X, early_stop=reqs[0].early_stop)
            # the predictor returned a host ndarray, so the device has
            # settled by here: t_settle - t_dispatch covers pad + H2D +
            # program + D2H for the whole fused group
            t_settle = time.monotonic()
            span_map = self._group_spans(reqs, entry, mode, dp,
                                         t_dispatch, t_settle)
            off = 0
            for r in reqs:
                lat = (t_settle - r.t_submit) * 1000.0
                r.future._set(result=out[off:off + r.n],
                              version=entry.version, latency_ms=lat,
                              spans=span_map.get(id(r)))
                off += r.n
                if self._window is not None:
                    self._window.record(lat)
                self._req_seq += 1
                if self._trace_sample and \
                        self._req_seq % self._trace_sample == 0:
                    self._record_trace(r, entry, mode, len(reqs),
                                       t_dispatch, t_settle)
            global_registry.inc("serve_requests", len(reqs))
            global_registry.inc("serve_rows", int(off))
            global_registry.inc("serve_dispatches")
            # per-model serve counts + dispatch seconds: the Prometheus
            # page renders the `::name` suffix as a {model=...} label,
            # and the serving roofline divides the cost model's totals
            # by the accumulated dispatch seconds
            global_registry.inc(f"serve_requests_by_model::{entry.name}",
                                len(reqs))
            global_registry.inc(f"serve_rows_by_model::{entry.name}",
                                int(off))
            global_registry.inc("serve_dispatch_s", t_settle - t_dispatch)
        except Exception as e:  # noqa: BLE001 - a bad request must not kill the thread
            trace_ids = sorted({r.trace.trace_id for r in reqs
                                if r.trace is not None})
            log.warning(f"Serving dispatch failed for model "
                        f"{entry.name!r} v{entry.version}: {e}"
                        + (f" (traces: {', '.join(trace_ids)})"
                           if trace_ids else ""))
            global_registry.inc("serve_errors", len(reqs))
            if trace_ids:
                # failures stay greppable by trace id in the flight
                # recorder even when the client never reads the future
                flight_recorder.record_trace(
                    kind="dispatch_error", model=entry.name,
                    version=entry.version, error=str(e)[:200],
                    trace_ids=trace_ids)
            for r in reqs:
                r.future._set(error=e)
        finally:
            for r in reqs:
                r.entry.release()

    @staticmethod
    def _group_spans(reqs: List[ServeRequest], entry, mode: str, dp,
                     t_dispatch: float, t_settle: float
                     ) -> Dict[int, List[dict]]:
        """Child spans for the trace-SAMPLED requests of one fused
        dispatch: serve (submit->respond) wrapping queue
        (enqueue->coalesce), dispatch (dispatch->device-settle) and
        respond (settle->now).  The dispatch spans of all batch-mates
        CROSS-LINK (span links, OpenTelemetry-style): one physical
        device dispatch served N requests, and each request's waterfall
        says so — plus how the chip time was spent (the PR-11
        cost-model flop/byte delta of exactly this dispatch, stamped by
        DevicePredictor at the dispatch site)."""
        traced = [r for r in reqs if r.trace is not None
                  and r.trace.sampled]
        if not traced:
            return {}
        # wall-clock anchors derived from ONE time.time() read: spans
        # are cross-process comparable, monotonic stamps stay the
        # latency source of truth
        m_now = time.monotonic()
        w_now = time.time()

        def wall(mono: Optional[float]) -> float:
            return w_now - (m_now - (mono if mono is not None else m_now))

        info = dp.last_dispatch_info() if hasattr(
            dp, "last_dispatch_info") else None
        group_rows = sum(r.n for r in reqs)
        # span contexts first: links need every mate's dispatch span id
        # before any span is finalized
        serve_ctxs = {id(r): r.trace.child() for r in traced}
        dispatch_ctx = {id(r): serve_ctxs[id(r)].child() for r in traced}
        anon_mates = len(reqs) - len(traced)
        out: Dict[int, List[dict]] = {}
        for r in traced:
            serve_ctx = serve_ctxs[id(r)]
            d_ctx = dispatch_ctx[id(r)]
            links = [{"trace_id": m.trace.trace_id,
                      "span_id": dispatch_ctx[id(m)].span_id}
                     for m in traced if m is not r]
            links += [{"trace_id": m.trace.trace_id}
                      for m in reqs
                      if m is not r and m.trace is not None
                      and not m.trace.sampled]
            spans = [
                make_span(serve_ctx, "serve", wall(r.t_submit), wall(None),
                          model=entry.name, version=entry.version,
                          mode=mode, rows=r.n),
                make_span(serve_ctx.child(), "queue", wall(r.t_submit),
                          wall(r.t_coalesce)),
                make_span(d_ctx, "dispatch", wall(t_dispatch),
                          wall(t_settle), links=links or None,
                          group_requests=len(reqs),
                          group_rows=group_rows,
                          unsampled_mates=anon_mates or None,
                          **(info or {})),
                make_span(serve_ctx.child(), "respond", wall(t_settle),
                          wall(None)),
            ]
            out[id(r)] = spans[:MAX_SPANS_PER_REQUEST]
        return out

    @staticmethod
    def _record_trace(r: ServeRequest, entry, mode: str,
                      group_requests: int, t_dispatch: float,
                      t_settle: float) -> None:
        """One sampled request's stage breakdown into the flight
        recorder: all stamps as ms offsets from enqueue, so a dumped
        trace reads as a waterfall without clock context."""
        t0 = r.t_submit
        ms = lambda t: (round((t - t0) * 1000.0, 3)
                        if t is not None else None)
        t_respond = time.monotonic()
        flight_recorder.record_trace(
            trace_id=flight_recorder.next_trace_id(),
            model=entry.name, version=entry.version, mode=mode,
            rows=r.n, group_requests=group_requests,
            coalesce_ms=ms(r.t_coalesce), dispatch_ms=ms(t_dispatch),
            device_settle_ms=ms(t_settle), respond_ms=ms(t_respond))
