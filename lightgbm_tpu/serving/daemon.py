"""ServingDaemon: one long-lived process owning the device, serving many
models (docs/Serving.md).

Composition of parts that already existed: the compiled bucket ladder +
slice-keyed packing (inference/, PR 4), the persistent compile cache
(PR 5), and the SIGTERM drain machinery (observability/hostio.py, PRs
7-8) — the daemon wires them behind a model registry (hot swap) and a
request coalescer (tail-latency-bounded batching).  The reference's
analogue is the long-lived `Predictor` the CLI keeps per model
(ref: src/application/predictor.hpp); "millions of users" needs that
predictor to be multi-model, swap-safe, and batched.

Request path: `submit()` validates and copies the rows to an immutable
float32 matrix (float64 accepted when losslessly f32-representable —
the same exactness gate as GBDT._device_predictor), acquires the
CURRENT registry entry, and queues; the coalescer thread merges queued
requests into one padded bucket dispatch and splits the rows back.
SIGTERM = drain notice: `install_signal_handlers()` reuses the
preemption-hook slot so a supervisor kill completes every queued
request, emits a final `serve_drain` event, flushes host I/O, and
re-delivers the signal (exit stays 143).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ..config import Config
from ..observability import emit_event
from ..observability.costmodel import global_cost_model
from ..observability.registry import LatencyWindow, global_registry
from ..utils import log
from .coalescer import Coalescer, ServeFuture, ServeRequest
from .registry import ModelRegistry

_MODES = ("predict", "raw", "leaf")


def _as_f32_rows(X) -> np.ndarray:
    """Validate + copy request rows to an immutable float32 matrix.

    The copy is deliberate: the request sits in a queue after submit
    returns, so serving must never alias caller-owned memory the caller
    may mutate.  float64 is accepted only when losslessly
    f32-representable (NaN kept as missing) — the bit-exact routing
    argument (docs/Inference.md) needs float32 inputs; lossy float64
    is the caller's error, not a silent precision downgrade."""
    arr = np.asarray(X)
    if arr.ndim == 1:
        arr = arr[None, :]
    if arr.ndim != 2 or arr.shape[0] == 0:
        raise ValueError(f"Serving rows must be a non-empty 2-D matrix "
                         f"(got shape {arr.shape})")
    if arr.dtype == np.float32:
        return np.array(arr, np.float32, copy=True)
    if arr.dtype == np.float64 or np.issubdtype(arr.dtype, np.integer):
        x64 = arr.astype(np.float64, copy=False)
        x32 = x64.astype(np.float32)
        if bool(np.all((x32 == x64) | np.isnan(x64))):
            return x32
        raise ValueError(
            "float64 request is not losslessly float32-representable; "
            "the device traversal serves float32 (docs/Serving.md "
            "fallback matrix) — downcast client-side to accept the "
            "rounding")
    raise ValueError(f"Unsupported request dtype {arr.dtype}")


class ServingDaemon:
    """Long-lived multi-model serving daemon (threads front end).

    Parameters arrive as a `Config` (or `key=value` params), using the
    `serve_*` family plus `device_predict_min_bucket` and the
    `pred_early_stop*` knobs (early stopping runs device-side via the
    masked accumulation scan, so it serves with zero extra traces)."""

    def __init__(self, config: Optional[Config] = None, **params):
        if config is None:
            config = Config(params)
        self.config = config
        es: Optional[Tuple[int, float]] = None
        if config.pred_early_stop and config.pred_early_stop_freq > 0:
            es = (int(config.pred_early_stop_freq),
                  float(config.pred_early_stop_margin))
        self._early_stop = es
        self.latency = LatencyWindow()
        self.registry = ModelRegistry(
            min_bucket=config.device_predict_min_bucket,
            warmup_rows=config.serve_max_batch_rows,
            warmup=config.serve_warmup, early_stop=es)
        self.coalescer = Coalescer(
            max_wait_ms=config.serve_max_coalesce_wait_ms,
            queue_depth=config.serve_queue_depth,
            max_batch_rows=config.serve_max_batch_rows,
            latency_window=self.latency,
            trace_sample=config.serve_trace_sample,
            adaptive=config.serve_adaptive_coalesce == "auto")
        self._stopped = threading.Event()
        self.metrics_server = None
        # compiled-cost roofline accounting (costmodel.py): enabled for
        # the daemon's lifetime so stats()/`/metrics` carry measured MFU
        # per dispatch; the harvest uses .lower() only, so the
        # serve_recompiles == 0 invariant is untouched
        self._prev_cost_enabled = global_cost_model.enabled
        if config.roofline:
            global_cost_model.enabled = True

    # -------------------------------------------------------------- control
    def start(self) -> "ServingDaemon":
        self.coalescer.start()
        if self.config.metrics_port >= 0 and self.metrics_server is None:
            # fleet scrape surface (observability/prom.py): routers,
            # canary controllers and Prometheus pull GET /metrics here
            from ..observability import start_metrics_http
            self.metrics_server = start_metrics_http(
                port=self.config.metrics_port, daemon=self)
        emit_event("serve_start", pid=os.getpid(),
                   max_coalesce_wait_ms=self.config
                   .serve_max_coalesce_wait_ms,
                   queue_depth=self.config.serve_queue_depth,
                   max_batch_rows=self.config.serve_max_batch_rows,
                   metrics_port=(self.metrics_server.port
                                 if self.metrics_server else None))
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> bool:
        """Stop serving: reject new submits, optionally complete the
        queued backlog (bounded), then retire every model.  Idempotent."""
        if self._stopped.is_set():
            return True
        drained = self.coalescer.stop(drain=drain, timeout=timeout)
        self.registry.close()
        if self.metrics_server is not None:
            self.metrics_server.shutdown()
            self.metrics_server = None
        global_cost_model.enabled = self._prev_cost_enabled
        self._stopped.set()
        emit_event("serve_stop", drained=drained,
                   requests=int(global_registry.counter("serve_requests")))
        return drained

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def install_signal_handlers(self) -> bool:
        """SIGTERM = drain notice: complete the queued requests (bounded
        by serve_drain_timeout_s), emit `serve_drain`, flush host I/O,
        re-deliver — the daemon analogue of training's
        checkpoint-on-demand preemption hook, riding the exact same
        hostio machinery (install_sigterm_flush + preemption hook)."""
        from ..observability import install_sigterm_flush, set_preemption_hook
        ok = install_sigterm_flush()
        if ok:
            set_preemption_hook(self._sigterm_drain)
        return ok

    def _sigterm_drain(self):
        pending = self.coalescer.pending
        drained = self.stop(drain=True,
                            timeout=self.config.serve_drain_timeout_s)
        from ..observability.events import emit_event_sync
        try:
            emit_event_sync(
                "serve_drain", pending_at_signal=int(pending),
                drained=bool(drained),
                # a missed drain deadline abandons queued requests; the
                # count rides the terminal event (and its own
                # serve_drain_abandoned event from coalescer.stop) so
                # rc=143 with drained=false is diagnosable
                abandoned=int(self.coalescer.last_abandoned),
                requests=int(global_registry.counter("serve_requests")))
        except Exception:  # noqa: BLE001 - dying anyway; flush next
            pass
        return None  # finish_preemption() flushes and re-delivers

    # -------------------------------------------------------------- serving
    def submit(self, model: str, X, mode: str = "predict",
               trace=None) -> ServeFuture:
        """Queue one request; returns its future.  Rejects (without
        queueing) unknown models, bad dtypes/shapes and feature-count
        mismatches — a malformed request must fail ITS caller, never
        poison a coalesced bucket or force a fresh trace.  `trace` is a
        propagated TraceContext (docs/Observability.md "Distributed
        tracing"): its id correlates this request across processes, and
        a SAMPLED context makes the dispatcher attach the replica-side
        child spans to the future (`future.spans`)."""
        if mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES} (got {mode!r})")
        from ..reliability import faults
        if faults.active():
            # serve-side fault points (docs/Reliability.md): @N matches
            # the per-process request counter ticked here
            n = faults.serve_request_tick()
            faults.maybe_serve_crash(n)
            if faults.maybe_serve_shed(n):
                self.coalescer.shed(reason="serve_shed fault injected")
            faults.maybe_serve_slow(n)
        rows = _as_f32_rows(X)
        entry = self.registry.get(model)   # acquired; release on response
        try:
            if rows.shape[1] != entry.num_features:
                raise ValueError(
                    f"Model {model!r} serves {entry.num_features} "
                    f"features, request has {rows.shape[1]} (a varying "
                    "width would re-trace the bucket program)")
            req = ServeRequest(entry, rows, mode,
                               early_stop=self._early_stop, trace=trace)
            self.coalescer.submit(req)
            return req.future
        except BaseException:
            entry.release()
            raise

    def predict(self, model: str, X, mode: str = "predict",
                timeout: Optional[float] = None, trace=None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(model, X, mode=mode,
                           trace=trace).result(timeout=timeout)

    # --------------------------------------------------------------- health
    # a shed inside this window marks the replica `shedding` on the
    # health probe, so the router's admission controller can reject
    # fleet-wide BEFORE burning a round trip on a replica that just shed
    _SHED_WINDOW_S = 1.0

    def health(self) -> Dict[str, object]:
        """Readiness + load state for the fleet health probe
        (`op=health`): `ready` means every registered model finished its
        load AND its warmup ledger (a replica serving cold would pay
        compiles on live traffic), `shedding` means the bounded queue
        shed within the last second — the router skips shedding
        replicas and answers `overloaded` once all of them are."""
        shed_age = self.coalescer.last_shed_age_s()
        pending = self.coalescer.pending
        return {
            "ready": (not self._stopped.is_set()
                      and self.registry.ready()),
            "models": {n: v for n, v in self.registry.versions().items()},
            "pending": pending,
            # a shed counts as CURRENT pressure only while the queue is
            # still backed up — one isolated shed followed by a drained
            # queue must not advertise saturation for a whole window
            "shedding": (shed_age is not None
                         and shed_age < self._SHED_WINDOW_S
                         and pending > 0),
            "stopped": self._stopped.is_set(),
            "pid": os.getpid(),
        }

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, object]:
        p50, p99 = self.latency.percentiles((50.0, 99.0))
        out = {
            "serve_requests": global_registry.counter("serve_requests"),
            "serve_rows": global_registry.counter("serve_rows"),
            "serve_batches": global_registry.counter("serve_batches"),
            "serve_dispatches": global_registry.counter("serve_dispatches"),
            "serve_errors": global_registry.counter("serve_errors"),
            "serve_swaps": global_registry.counter("serve_swaps"),
            "serve_shed": global_registry.counter("serve_shed"),
            "serve_p50_ms": p50,
            "serve_p99_ms": p99,
            "queue_pending": self.coalescer.pending,
        }
        out.update(self.registry.stats())
        rl = self.roofline_stats()
        if rl is not None:
            out["roofline"] = rl
        return out

    def roofline_stats(self) -> Optional[Dict[str, object]]:
        """Measured serving roofline (docs/Observability.md): compiled
        flops/bytes and wall seconds accumulated AT THE DISPATCH SITE
        (DevicePredictor._run, warmup excluded), so the MFU numerator
        and denominator describe the same work.  None when the cost
        model is off or nothing dispatched yet."""
        if not global_cost_model.enabled:
            return None
        flops = float(global_registry.counter("device_predict_flops"))
        bytes_accessed = float(
            global_registry.counter("device_predict_bytes"))
        seconds = float(global_registry.counter("device_predict_s"))
        dispatches = int(
            global_registry.counter("device_predict_dispatches"))
        if dispatches <= 0:
            return None
        from ..observability.costmodel import roofline
        out = roofline(flops, bytes_accessed, seconds)
        out["dispatch_s"] = round(seconds, 6)
        out["dispatches"] = dispatches
        out["measured_mfu"] = out.pop("mfu")
        return out


class ServingClient:
    """Client handle for a serving daemon — in-process (wrap the
    `ServingDaemon` directly) or remote over the line-JSON TCP wire
    (`ServingClient.connect(host, port)`).

    The in-process form is thread-safe: any number of client threads
    may call concurrently (that is the point).  The TCP form owns ONE
    connection (the wire is one-request-one-response), serializes
    calls behind a lock, and RECONNECTS with exponential backoff when
    the connection drops — a replica restart no longer raises to the
    caller on the next call (ISSUE 13 satellite).  `deadline_ms` rides
    each request: in-process it bounds the future wait; over TCP it
    propagates to the replica so the server gives up when the client
    has.

    Tracing (docs/Observability.md "Distributed tracing"): the client
    is the outermost EDGE, so every request is stamped with a fresh
    TraceContext (ids make failures greppable end to end); every
    `trace_sample`-th request is stamped SAMPLED, which makes each hop
    attach real spans.  `last_trace_id`/`last_spans` expose the most
    recent request's identity and (sampled only) replica-side spans."""

    def __init__(self, daemon: Optional[ServingDaemon] = None,
                 address: Optional[Tuple[str, int]] = None,
                 request_timeout_s: float = 60.0,
                 retry_backoff_ms: float = 25.0,
                 trace_sample: int = 0,
                 uds_path: Optional[str] = None):
        if sum(x is not None for x in (daemon, address, uds_path)) != 1:
            raise ValueError("ServingClient needs exactly one of daemon= "
                             "(in-process), address= (TCP) or uds_path= "
                             "(Unix socket)")
        self._daemon = daemon
        self._conn = None
        self._timeout_s = float(request_timeout_s)
        self._trace_sample = max(int(trace_sample), 0)
        self._trace_lock = threading.Lock()
        self._trace_seq = 0
        self.last_trace_id: Optional[str] = None
        self.last_spans = None
        if address is not None or uds_path is not None:
            from .frontend import LineClient
            if address is not None:
                self._conn = LineClient(address[0], int(address[1]),
                                        backoff_ms=retry_backoff_ms)
            else:
                self._conn = LineClient(uds_path=uds_path,
                                        backoff_ms=retry_backoff_ms)
            self._conn_lock = threading.Lock()

    @classmethod
    def connect(cls, host: str, port: int,
                request_timeout_s: float = 60.0,
                retry_backoff_ms: float = 25.0,
                trace_sample: int = 0) -> "ServingClient":
        """TCP client for a daemon's front end (`serve_port`)."""
        return cls(address=(host, port),
                   request_timeout_s=request_timeout_s,
                   retry_backoff_ms=retry_backoff_ms,
                   trace_sample=trace_sample)

    @classmethod
    def connect_uds(cls, path: str,
                    request_timeout_s: float = 60.0,
                    retry_backoff_ms: float = 25.0,
                    trace_sample: int = 0) -> "ServingClient":
        """Unix-socket client for a daemon's UDS front end
        (`serve_uds_path`) — same wire, same semantics as TCP."""
        return cls(uds_path=path,
                   request_timeout_s=request_timeout_s,
                   retry_backoff_ms=retry_backoff_ms,
                   trace_sample=trace_sample)

    def _edge_context(self, trace_ctx=None):
        """Stamp (or pass through) the request's trace context."""
        from ..observability.tracing import TraceContext
        if trace_ctx is not None:
            return trace_ctx
        with self._trace_lock:
            self._trace_seq += 1
            sampled = (self._trace_sample > 0
                       and self._trace_seq % self._trace_sample == 0)
        return TraceContext.new(sampled=sampled)

    # ---------------------------------------------------------------- wire
    def _request(self, msg: dict,
                 timeout_s: Optional[float] = None) -> dict:
        wait = self._timeout_s if timeout_s is None else timeout_s
        with self._conn_lock:
            try:
                reply = self._conn.request(msg, timeout_s=wait)
            except ConnectionError:
                # the daemon restarted between calls (hot replica
                # churn): reconnect-with-backoff and resend ONCE —
                # predict/stats/health are idempotent
                reply = self._conn.request(msg, timeout_s=wait)
        if reply.get("ok"):
            return reply
        from .coalescer import ShedError
        err = reply.get("error", "serving error")
        if reply.get("shed"):
            exc: BaseException = ShedError(
                err, pending=int(reply.get("pending", 0)))
        elif reply.get("timeout"):
            exc = TimeoutError(err)
        else:
            exc = RuntimeError(err)
        # the server echoes the request's trace id on error replies so a
        # client-side failure is greppable in replica logs / the flight
        # recorder; surface it on the raised exception too
        exc.trace_id = reply.get("trace_id")  # type: ignore[attr-defined]
        raise exc

    # ----------------------------------------------------------------- API
    def predict(self, model: str, X, mode: str = "predict",
                timeout: Optional[float] = None,
                deadline_ms: Optional[float] = None,
                trace_ctx=None):
        ctx = self._edge_context(trace_ctx)
        if self._daemon is not None:
            if deadline_ms is not None:
                t = float(deadline_ms) / 1000.0
                timeout = t if timeout is None else min(timeout, t)
            fut = self._daemon.submit(model, X, mode=mode, trace=ctx)
            out = fut.result(timeout=timeout)
            with self._trace_lock:
                self.last_trace_id = ctx.trace_id
                self.last_spans = fut.spans
            return out
        msg = {"model": model, "rows": np.asarray(X).tolist(),
               "mode": mode, "trace": ctx.to_wire()}
        if deadline_ms is not None:
            msg["deadline_ms"] = float(deadline_ms)
        wait = timeout if timeout is not None else (
            float(deadline_ms) / 1000.0 + 1.0
            if deadline_ms is not None else None)
        reply = self._request(msg, timeout_s=wait)
        with self._trace_lock:
            self.last_trace_id = reply.get("trace_id", ctx.trace_id)
            self.last_spans = reply.get("spans")
        return np.asarray(reply["preds"])

    def predict_async(self, model: str, X,
                      mode: str = "predict") -> ServeFuture:
        if self._daemon is None:
            raise RuntimeError("predict_async is in-process only; the "
                               "TCP wire is one-request-one-response")
        return self._daemon.submit(model, X, mode=mode)

    def models(self):
        if self._daemon is not None:
            return self._daemon.registry.names()
        return self._request({"op": "models"})["models"]

    def stats(self):
        if self._daemon is not None:
            return self._daemon.stats()
        return self._request({"op": "stats"})["stats"]

    def health(self):
        if self._daemon is not None:
            return self._daemon.health()
        return self._request({"op": "health"})

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()


def serve_counters_reset() -> None:
    """Zero the serve_* counters (tests and the bench isolate phases);
    the registry is process-global, so only the serving keys reset —
    including the per-model `serve_*_by_model::<name>` series and the
    dispatch-seconds accumulator."""
    for key in list(global_registry.snapshot()["counters"]):
        if key.startswith("serve_"):
            global_registry.inc(key, -global_registry.counter(key))
    log.debug("serve counters reset")
