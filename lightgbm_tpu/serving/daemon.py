"""ServingDaemon: one long-lived process owning the device, serving many
models (docs/Serving.md).

Composition of parts that already existed: the compiled bucket ladder +
slice-keyed packing (inference/, PR 4), the persistent compile cache
(PR 5), and the SIGTERM drain machinery (observability/hostio.py, PRs
7-8) — the daemon wires them behind a model registry (hot swap) and a
request coalescer (tail-latency-bounded batching).  The reference's
analogue is the long-lived `Predictor` the CLI keeps per model
(ref: src/application/predictor.hpp); "millions of users" needs that
predictor to be multi-model, swap-safe, and batched.

Request path: `submit()` validates and copies the rows to an immutable
float32 matrix (float64 accepted when losslessly f32-representable —
the same exactness gate as GBDT._device_predictor), acquires the
CURRENT registry entry, and queues; the coalescer thread merges queued
requests into one padded bucket dispatch and splits the rows back.
SIGTERM = drain notice: `install_signal_handlers()` reuses the
preemption-hook slot so a supervisor kill completes every queued
request, emits a final `serve_drain` event, flushes host I/O, and
re-delivers the signal (exit stays 143).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ..config import Config
from ..observability import emit_event
from ..observability.costmodel import global_cost_model
from ..observability.registry import LatencyWindow, global_registry
from ..utils import log
from .coalescer import Coalescer, ServeFuture, ServeRequest
from .registry import ModelRegistry

_MODES = ("predict", "raw", "leaf")


def _as_f32_rows(X) -> np.ndarray:
    """Validate + copy request rows to an immutable float32 matrix.

    The copy is deliberate: the request sits in a queue after submit
    returns, so serving must never alias caller-owned memory the caller
    may mutate.  float64 is accepted only when losslessly
    f32-representable (NaN kept as missing) — the bit-exact routing
    argument (docs/Inference.md) needs float32 inputs; lossy float64
    is the caller's error, not a silent precision downgrade."""
    arr = np.asarray(X)
    if arr.ndim == 1:
        arr = arr[None, :]
    if arr.ndim != 2 or arr.shape[0] == 0:
        raise ValueError(f"Serving rows must be a non-empty 2-D matrix "
                         f"(got shape {arr.shape})")
    if arr.dtype == np.float32:
        return np.array(arr, np.float32, copy=True)
    if arr.dtype == np.float64 or np.issubdtype(arr.dtype, np.integer):
        x64 = arr.astype(np.float64, copy=False)
        x32 = x64.astype(np.float32)
        if bool(np.all((x32 == x64) | np.isnan(x64))):
            return x32
        raise ValueError(
            "float64 request is not losslessly float32-representable; "
            "the device traversal serves float32 (docs/Serving.md "
            "fallback matrix) — downcast client-side to accept the "
            "rounding")
    raise ValueError(f"Unsupported request dtype {arr.dtype}")


class ServingDaemon:
    """Long-lived multi-model serving daemon (threads front end).

    Parameters arrive as a `Config` (or `key=value` params), using the
    `serve_*` family plus `device_predict_min_bucket` and the
    `pred_early_stop*` knobs (early stopping runs device-side via the
    masked accumulation scan, so it serves with zero extra traces)."""

    def __init__(self, config: Optional[Config] = None, **params):
        if config is None:
            config = Config(params)
        self.config = config
        es: Optional[Tuple[int, float]] = None
        if config.pred_early_stop and config.pred_early_stop_freq > 0:
            es = (int(config.pred_early_stop_freq),
                  float(config.pred_early_stop_margin))
        self._early_stop = es
        self.latency = LatencyWindow()
        self.registry = ModelRegistry(
            min_bucket=config.device_predict_min_bucket,
            warmup_rows=config.serve_max_batch_rows,
            warmup=config.serve_warmup, early_stop=es)
        self.coalescer = Coalescer(
            max_wait_ms=config.serve_max_coalesce_wait_ms,
            queue_depth=config.serve_queue_depth,
            max_batch_rows=config.serve_max_batch_rows,
            latency_window=self.latency,
            trace_sample=config.serve_trace_sample)
        self._stopped = threading.Event()
        self.metrics_server = None
        # compiled-cost roofline accounting (costmodel.py): enabled for
        # the daemon's lifetime so stats()/`/metrics` carry measured MFU
        # per dispatch; the harvest uses .lower() only, so the
        # serve_recompiles == 0 invariant is untouched
        self._prev_cost_enabled = global_cost_model.enabled
        if config.roofline:
            global_cost_model.enabled = True

    # -------------------------------------------------------------- control
    def start(self) -> "ServingDaemon":
        self.coalescer.start()
        if self.config.metrics_port >= 0 and self.metrics_server is None:
            # fleet scrape surface (observability/prom.py): routers,
            # canary controllers and Prometheus pull GET /metrics here
            from ..observability import start_metrics_http
            self.metrics_server = start_metrics_http(
                port=self.config.metrics_port, daemon=self)
        emit_event("serve_start", pid=os.getpid(),
                   max_coalesce_wait_ms=self.config
                   .serve_max_coalesce_wait_ms,
                   queue_depth=self.config.serve_queue_depth,
                   max_batch_rows=self.config.serve_max_batch_rows,
                   metrics_port=(self.metrics_server.port
                                 if self.metrics_server else None))
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> bool:
        """Stop serving: reject new submits, optionally complete the
        queued backlog (bounded), then retire every model.  Idempotent."""
        if self._stopped.is_set():
            return True
        drained = self.coalescer.stop(drain=drain, timeout=timeout)
        self.registry.close()
        if self.metrics_server is not None:
            self.metrics_server.shutdown()
            self.metrics_server = None
        global_cost_model.enabled = self._prev_cost_enabled
        self._stopped.set()
        emit_event("serve_stop", drained=drained,
                   requests=int(global_registry.counter("serve_requests")))
        return drained

    @property
    def stopped(self) -> bool:
        return self._stopped.is_set()

    def install_signal_handlers(self) -> bool:
        """SIGTERM = drain notice: complete the queued requests (bounded
        by serve_drain_timeout_s), emit `serve_drain`, flush host I/O,
        re-deliver — the daemon analogue of training's
        checkpoint-on-demand preemption hook, riding the exact same
        hostio machinery (install_sigterm_flush + preemption hook)."""
        from ..observability import install_sigterm_flush, set_preemption_hook
        ok = install_sigterm_flush()
        if ok:
            set_preemption_hook(self._sigterm_drain)
        return ok

    def _sigterm_drain(self):
        pending = self.coalescer.pending
        drained = self.stop(drain=True,
                            timeout=self.config.serve_drain_timeout_s)
        from ..observability.events import emit_event_sync
        try:
            emit_event_sync(
                "serve_drain", pending_at_signal=int(pending),
                drained=bool(drained),
                requests=int(global_registry.counter("serve_requests")))
        except Exception:  # noqa: BLE001 - dying anyway; flush next
            pass
        return None  # finish_preemption() flushes and re-delivers

    # -------------------------------------------------------------- serving
    def submit(self, model: str, X, mode: str = "predict") -> ServeFuture:
        """Queue one request; returns its future.  Rejects (without
        queueing) unknown models, bad dtypes/shapes and feature-count
        mismatches — a malformed request must fail ITS caller, never
        poison a coalesced bucket or force a fresh trace."""
        if mode not in _MODES:
            raise ValueError(f"mode must be one of {_MODES} (got {mode!r})")
        rows = _as_f32_rows(X)
        entry = self.registry.get(model)   # acquired; release on response
        try:
            if rows.shape[1] != entry.num_features:
                raise ValueError(
                    f"Model {model!r} serves {entry.num_features} "
                    f"features, request has {rows.shape[1]} (a varying "
                    "width would re-trace the bucket program)")
            req = ServeRequest(entry, rows, mode,
                               early_stop=self._early_stop)
            self.coalescer.submit(req)
            return req.future
        except BaseException:
            entry.release()
            raise

    def predict(self, model: str, X, mode: str = "predict",
                timeout: Optional[float] = None):
        """Blocking convenience wrapper over submit()."""
        return self.submit(model, X, mode=mode).result(timeout=timeout)

    # ---------------------------------------------------------------- stats
    def stats(self) -> Dict[str, object]:
        p50, p99 = self.latency.percentiles((50.0, 99.0))
        out = {
            "serve_requests": global_registry.counter("serve_requests"),
            "serve_rows": global_registry.counter("serve_rows"),
            "serve_batches": global_registry.counter("serve_batches"),
            "serve_dispatches": global_registry.counter("serve_dispatches"),
            "serve_errors": global_registry.counter("serve_errors"),
            "serve_swaps": global_registry.counter("serve_swaps"),
            "serve_p50_ms": p50,
            "serve_p99_ms": p99,
            "queue_pending": self.coalescer.pending,
        }
        out.update(self.registry.stats())
        rl = self.roofline_stats()
        if rl is not None:
            out["roofline"] = rl
        return out

    def roofline_stats(self) -> Optional[Dict[str, object]]:
        """Measured serving roofline (docs/Observability.md): compiled
        flops/bytes and wall seconds accumulated AT THE DISPATCH SITE
        (DevicePredictor._run, warmup excluded), so the MFU numerator
        and denominator describe the same work.  None when the cost
        model is off or nothing dispatched yet."""
        if not global_cost_model.enabled:
            return None
        flops = float(global_registry.counter("device_predict_flops"))
        bytes_accessed = float(
            global_registry.counter("device_predict_bytes"))
        seconds = float(global_registry.counter("device_predict_s"))
        dispatches = int(
            global_registry.counter("device_predict_dispatches"))
        if dispatches <= 0:
            return None
        from ..observability.costmodel import roofline
        out = roofline(flops, bytes_accessed, seconds)
        out["dispatch_s"] = round(seconds, 6)
        out["dispatches"] = dispatches
        out["measured_mfu"] = out.pop("mfu")
        return out


class ServingClient:
    """In-process client handle for a ServingDaemon — the API surface a
    front end (socket, RPC) would wrap.  Thread-safe: any number of
    client threads may call concurrently (that is the point)."""

    def __init__(self, daemon: ServingDaemon):
        self._daemon = daemon

    def predict(self, model: str, X, mode: str = "predict",
                timeout: Optional[float] = None):
        return self._daemon.predict(model, X, mode=mode, timeout=timeout)

    def predict_async(self, model: str, X,
                      mode: str = "predict") -> ServeFuture:
        return self._daemon.submit(model, X, mode=mode)

    def models(self):
        return self._daemon.registry.names()

    def stats(self):
        return self._daemon.stats()


def serve_counters_reset() -> None:
    """Zero the serve_* counters (tests and the bench isolate phases);
    the registry is process-global, so only the serving keys reset —
    including the per-model `serve_*_by_model::<name>` series and the
    dispatch-seconds accumulator."""
    for key in list(global_registry.snapshot()["counters"]):
        if key.startswith("serve_"):
            global_registry.inc(key, -global_registry.counter(key))
    log.debug("serve counters reset")
