"""Replica fleet: K serving daemons under poll-based supervision.

One daemon process is a fault domain of one: a crash loses every
in-flight request and a wedged device stalls every caller.  The fleet
layer runs K replica daemons (each its own process, its own device
context, its own bounded queue) behind the router (router.py), and
supervises them the way `reliability/supervisor.py` supervises training
ranks — poll the PIDs, classify the exit (`classify_returncode`:
crash / preempt / hang / lost), surface the log tail, and relaunch with
exponential backoff, capped by `serve_max_replica_restarts` per
replica.  A dead replica is detected in seconds (poll interval), not
when a client times out.

Replica lifecycle:

    spawn -> (daemon warms its models) -> ready file lands
          -> health probes (`op=health`) pass -> ROUTABLE
          -> exit observed -> `serve_replica_down` event
          -> backoff (0.5 s * 2^restarts, capped) -> respawn, new port
          -> restart budget exhausted -> permanently down

Readiness is the daemon's own warmup ledger (`op=health` `ready`): a
replica is never routed to until every registered model finished load
AND bucket-ladder warmup, so replica churn cannot leak compiles into
live traffic.  The probe also carries `shedding` (the replica's bounded
queue shed within the last second) — the router skips shedding replicas
and the fleet-wide admission controller answers `overloaded` once all
of them shed.

The fleet also ADOPTS replicas it did not spawn (`adopt_endpoints`):
externally managed daemons (k8s pods, another host) get health-checked
and routed to, just not relaunched.

Fault drills: `fault_envs={idx: {"LGBM_TPU_FAULT": "serve_crash@40"}}`
injects the serve-side fault points (reliability/faults.py) into chosen
replicas; every replica gets `LGBM_TPU_FAULT_SELF_RANK=<idx>` so
rank-gated specs drill exactly one replica of a fleet.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..observability import emit_event
from ..observability.prom import parse_prometheus_text
from ..observability.registry import global_registry
from ..reliability.guard import classify_returncode
from ..reliability.supervisor import tail_file
from ..utils import log

# the CLI bootstrap for spawned replicas: LGBM_TPU_SERVE_FORCE_CPU=1
# pins the child to the CPU backend BEFORE any jax dispatch — the axon
# TPU plugin ignores JAX_PLATFORMS, so a bare `python -m` child would
# hang on backend init (the bench _backend_guard workaround, applied at
# spawn time for benches/tests; production fleets leave it unset)
_BOOTSTRAP = (
    "import os, sys\n"
    "if os.environ.get('LGBM_TPU_SERVE_FORCE_CPU') == '1':\n"
    "    import jax\n"
    "    jax.config.update('jax_platforms', 'cpu')\n"
    "from lightgbm_tpu.cli import main\n"
    "sys.exit(main(sys.argv[1:]))\n")


class ReplicaState:
    """One replica's supervised state.  All mutable fields are guarded
    by the owning fleet's lock; router threads read through
    `ReplicaFleet.endpoints()` snapshots only."""

    def __init__(self, idx: int, adopted: bool = False,
                 host: str = "127.0.0.1", port: Optional[int] = None):
        self.idx = idx
        self.adopted = adopted
        self.host = host
        self.port = port
        self.proc: Optional[subprocess.Popen] = None
        self.ready = False          # daemon's warmup ledger complete
        self.healthy = False        # last health probe answered
        self.shedding = False       # shed within the probe's window
        self.restarts = 0           # relaunches consumed (budgeted)
        self.gen = 0                # bumped per (re)spawn
        self.down = False           # permanently out of budget
        self.spawned_at = 0.0
        self.relaunch_at: Optional[float] = None  # backoff deadline
        self.last_probe = 0.0
        self.versions: Dict[str, int] = {}

    def describe(self) -> Dict[str, object]:
        return {"idx": self.idx, "port": self.port, "gen": self.gen,
                "ready": self.ready, "healthy": self.healthy,
                "shedding": self.shedding, "restarts": self.restarts,
                "down": self.down, "adopted": self.adopted,
                "pid": self.proc.pid if self.proc else None,
                "versions": dict(self.versions)}


class ReplicaEndpoint:
    """Immutable routing view of one replica (snapshot semantics: the
    router holds these across a request; staleness is resolved by the
    retry path, not by locking)."""

    __slots__ = ("idx", "host", "port", "gen", "shedding", "versions")

    def __init__(self, idx: int, host: str, port: int, gen: int,
                 shedding: bool, versions: Dict[str, int]):
        self.idx = idx
        self.host = host
        self.port = port
        self.gen = gen
        self.shedding = shedding
        self.versions = versions


class FleetAggregator:
    """Merged fleet view of every replica's `/metrics` scrape
    (docs/Observability.md "Fleet metrics & SLO").

    The supervisor's health probe pulls each routable replica's
    Prometheus page (`op=metrics` on the same wire round trip as
    `op=health`) and records the parsed snapshot here; `render()`
    produces ONE text block for the router's own `/metrics` page:

    * `lgbm_fleet_<name>` counters — the per-series SUM over every
      replica with a live scrape (so one router scrape answers "how
      many requests did the FLEET serve" without K per-replica pulls);
    * `lgbm_fleet_replica_{up,routable,restarts}{replica="i"}` gauges
      from the supervisor's own state (a down replica has no scrape to
      speak for it);
    * merged latency quantiles: `lgbm_fleet_latency_ms{quantile=}` —
      p50 as the serve-request-weighted mean of the replica p50s, p99
      as the MAX over replicas (quantiles do not sum; the weighted
      mean is the honest central estimate and the max is the
      conservative tail bound — documented approximation).

    A replica's snapshot is dropped when it goes down or restarts
    (`forget`): a relaunched daemon restarts its counters from zero,
    and a stale pre-crash snapshot would double-count its history."""

    def __init__(self):
        self._lock = threading.Lock()
        # replica idx -> {"ts", "counters", "gauges"}
        self._scrapes: Dict[int, Dict[str, object]] = {}

    # ------------------------------------------------------------- writers
    def record_scrape(self, idx: int, page: str) -> None:
        parsed = parse_prometheus_text(page)
        with self._lock:
            self._scrapes[int(idx)] = {"ts": time.time(),
                                       "counters": parsed["counters"],
                                       "gauges": parsed["gauges"]}

    def forget(self, idx: int) -> None:
        """Drop a replica's snapshot (down or relaunched: its counter
        history must not double-count into the merged view)."""
        with self._lock:
            self._scrapes.pop(int(idx), None)

    # ------------------------------------------------------------- readers
    def snapshot(self) -> Dict[int, Dict[str, object]]:
        """Per-replica parsed scrapes (copies)."""
        with self._lock:
            return {i: {"ts": s["ts"],
                        "counters": dict(s["counters"]),
                        "gauges": dict(s["gauges"])}
                    for i, s in self._scrapes.items()}

    def merged_counters(self) -> Dict[str, float]:
        """Per-series sums over every live replica scrape."""
        out: Dict[str, float] = {}
        with self._lock:
            scrapes = list(self._scrapes.values())
        for s in scrapes:
            for name, val in s["counters"].items():
                out[name] = out.get(name, 0.0) + val
        return out

    def replica_counter(self, idx: int, series: str) -> float:
        with self._lock:
            s = self._scrapes.get(int(idx))
            return float(s["counters"].get(series, 0.0)) if s else 0.0

    def merged_latency_ms(self) -> Dict[str, Optional[float]]:
        """{"p50": weighted mean, "p99": max} over replica quantile
        gauges (see class docstring for the approximation)."""
        with self._lock:
            scrapes = list(self._scrapes.values())
        p50s, p99s = [], []
        for s in scrapes:
            g = s["gauges"]
            p50 = g.get('lgbm_serve_latency_ms{quantile="0.5"}')
            p99 = g.get('lgbm_serve_latency_ms{quantile="0.99"}')
            weight = s["counters"].get("lgbm_serve_requests", 0.0)
            if p50 is not None and p50 == p50:       # NaN-safe
                p50s.append((p50, max(weight, 1.0)))
            if p99 is not None and p99 == p99:
                p99s.append(p99)
        p50 = (sum(v * w for v, w in p50s) / sum(w for _, w in p50s)
               if p50s else None)
        return {"p50": p50, "p99": max(p99s) if p99s else None}

    # -------------------------------------------------------------- render
    def render(self, describe: List[Dict[str, object]]) -> str:
        """The router /metrics `text_cb` block (Prometheus text)."""
        lines: List[str] = []
        merged = self.merged_counters()
        families: Dict[str, List[str]] = {}
        for name in sorted(merged):
            rest = name[len("lgbm_"):] if name.startswith("lgbm_") else name
            base = "lgbm_fleet_" + rest.split("{", 1)[0]
            series = ("lgbm_fleet_" + rest).split("{", 1)
            rendered = series[0] + ("{" + series[1] if len(series) > 1
                                    else "")
            val = merged[name]
            sval = str(int(val)) if val == int(val) else repr(val)
            families.setdefault(base, []).append(f"{rendered} {sval}")
        for base in sorted(families):
            lines.append(f"# TYPE {base} counter")
            lines.extend(families[base])
        for field, kind in (("up", "healthy"), ("routable", "ready"),
                            ("restarts", "restarts")):
            lines.append(f"# TYPE lgbm_fleet_replica_{field} gauge")
            for r in describe:
                if field == "restarts":
                    val = int(r.get("restarts", 0))
                else:
                    val = int(bool(r.get(kind)) and not r.get("down"))
                lines.append(
                    f'lgbm_fleet_replica_{field}{{replica="{r["idx"]}"}} '
                    f"{val}")
        lat = self.merged_latency_ms()
        lines.append("# TYPE lgbm_fleet_latency_ms gauge")
        for q, key in (("0.5", "p50"), ("0.99", "p99")):
            v = lat[key]
            lines.append(f'lgbm_fleet_latency_ms{{quantile="{q}"}} '
                         + ("NaN" if v is None else f"{float(v):g}"))
        return "\n".join(lines)


class ReplicaFleet:
    """Spawn/adopt + supervise K serving replicas (docs/Serving.md).

    `model_entries` are the `(name, path)` pairs every replica serves;
    `params` flow to each replica daemon's CLI as `key=value` (the
    `serve_*` family, `device_predict*`, verbosity...).  `spawn_cmd`
    overrides the command factory — tests supervise stub processes
    through the very same machinery that runs real daemons."""

    POLL_INTERVAL_S = 0.2
    BACKOFF_BASE_S = 0.5
    BACKOFF_CAP_S = 10.0
    READY_TIMEOUT_S = 180.0

    def __init__(self, num_replicas: int, model_entries: Sequence[Tuple[str, str]],
                 workdir: str, params: Optional[Dict[str, object]] = None,
                 max_restarts: int = 3, health_interval_s: float = 0.5,
                 force_cpu: bool = False,
                 fault_envs: Optional[Dict[int, Dict[str, str]]] = None,
                 spawn_cmd: Optional[Callable[[int, str], List[str]]] = None,
                 adopt_endpoints: Sequence[Tuple[str, int]] = ()):
        self.workdir = os.fspath(workdir)
        self.model_entries = [(str(n), str(p)) for n, p in model_entries]
        self.params = dict(params or {})
        self.max_restarts = int(max_restarts)
        self.health_interval_s = max(float(health_interval_s), 0.05)
        self.force_cpu = bool(force_cpu)
        self.fault_envs = {int(k): dict(v)
                           for k, v in (fault_envs or {}).items()}
        self.spawn_cmd = spawn_cmd
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # merged fleet /metrics view, refreshed on the health-probe tick
        # (docs/Observability.md "Fleet metrics & SLO"); always on — one
        # op=metrics round trip per probe is noise next to the probe
        self.aggregator = FleetAggregator()
        self.replicas: List[ReplicaState] = [
            ReplicaState(i) for i in range(int(num_replicas))]
        for host, port in adopt_endpoints:
            r = ReplicaState(len(self.replicas), adopted=True,
                             host=host, port=int(port))
            self.replicas.append(r)
        if not self.replicas:
            raise ValueError("ReplicaFleet needs num_replicas >= 1 or "
                             "adopt_endpoints")

    # ------------------------------------------------------------ spawning
    def _ready_file(self, idx: int) -> str:
        return os.path.join(self.workdir, f"replica-{idx}.ready.json")

    def _log_file(self, idx: int) -> str:
        return os.path.join(self.workdir, f"replica-{idx}.log")

    def _default_cmd(self, idx: int, ready_file: str) -> List[str]:
        with self._lock:  # RLock: _spawn's callers already hold it
            entries = ",".join(f"{n}={p}" for n, p in self.model_entries)
        argv = [sys.executable, "-c", _BOOTSTRAP, "task=serve",
                f"serve_models={entries}", "serve_port=0",
                f"serve_ready_file={ready_file}"]
        for k, v in sorted(self.params.items()):
            if isinstance(v, bool):
                v = "true" if v else "false"
            argv.append(f"{k}={v}")
        return argv

    def _spawn(self, r: ReplicaState) -> None:
        """Launch (or relaunch) replica r; caller holds the lock."""
        ready_file = self._ready_file(r.idx)
        try:
            os.makedirs(self.workdir, exist_ok=True)
            if os.path.exists(ready_file):
                os.unlink(ready_file)  # a stale port must never route
        except OSError:
            pass
        env = dict(os.environ)
        # the package must be importable from the bootstrap -c child:
        # prepend the REPO root (the directory CONTAINING lightgbm_tpu
        # — the package dir itself would shadow stdlib `io`/`models`)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH",
                                                             "")
        env["LGBM_TPU_FAULT_SELF_RANK"] = str(r.idx)
        # relaunch = next attempt: one-shot fault specs (serve_crash@N)
        # must not re-fire on every generation, exactly like the
        # training supervisor's attempt gating (reliability/faults.py)
        env["LGBM_TPU_FAULT_ATTEMPT"] = str(r.gen)
        if self.force_cpu:
            env["LGBM_TPU_SERVE_FORCE_CPU"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
        env.update(self.fault_envs.get(r.idx, {}))
        cmd = (self.spawn_cmd(r.idx, ready_file) if self.spawn_cmd
               else self._default_cmd(r.idx, ready_file))
        logf = open(self._log_file(r.idx), "ab")
        try:
            r.proc = subprocess.Popen(cmd, stdout=logf, stderr=logf,
                                      env=env, cwd=self.workdir)
        finally:
            logf.close()  # the child inherited the fd
        r.gen += 1
        r.ready = False
        r.healthy = False
        r.shedding = False
        r.port = None
        r.spawned_at = time.monotonic()
        r.relaunch_at = None
        log.info(f"Fleet replica {r.idx} spawned (gen {r.gen}, "
                 f"pid {r.proc.pid})")

    # ------------------------------------------------------------- control
    def start(self) -> "ReplicaFleet":
        with self._lock:
            for r in self.replicas:
                if not r.adopted:
                    self._spawn(r)
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._supervise, name="lgbm-fleet-supervisor",
                    daemon=True)
                self._thread.start()
        emit_event("serve_fleet_start",
                   replicas=len(self.replicas),
                   models=[n for n, _ in self.model_entries])
        return self

    def wait_ready(self, timeout: Optional[float] = None,
                   min_replicas: Optional[int] = None) -> bool:
        """Block until `min_replicas` (default: all non-down) replicas
        are routable.  False on timeout."""
        deadline = (time.monotonic() + timeout) if timeout else None
        while True:
            with self._lock:
                up = sum(1 for r in self.replicas
                         if r.healthy and r.ready)
                want = (min_replicas if min_replicas is not None
                        else sum(1 for r in self.replicas if not r.down))
            if want > 0 and up >= want:
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            if self._stop.is_set():
                return False
            time.sleep(0.05)

    def stop(self, drain: bool = True, timeout: float = 30.0
             ) -> Dict[int, Optional[int]]:
        """Stop supervision and the replicas: SIGTERM each spawned
        replica (its own drain machinery completes the queued backlog
        and exits 143), bounded wait, then SIGKILL stragglers.  Returns
        {idx: returncode}.  Adopted replicas are left running."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        rcs: Dict[int, Optional[int]] = {}
        with self._lock:
            procs = [(r.idx, r.proc) for r in self.replicas
                     if r.proc is not None]
        sig = signal.SIGTERM if drain else signal.SIGKILL
        for _idx, proc in procs:
            if proc.poll() is None:
                try:
                    proc.send_signal(sig)
                except OSError:
                    pass
        deadline = time.monotonic() + max(float(timeout), 0.1)
        for idx, proc in procs:
            rem = max(deadline - time.monotonic(), 0.1)
            try:
                rcs[idx] = proc.wait(timeout=rem)
            except subprocess.TimeoutExpired:
                proc.kill()
                rcs[idx] = proc.wait()
        emit_event("serve_fleet_stop", returncodes={
            str(k): v for k, v in sorted(rcs.items())})
        return rcs

    # ---------------------------------------------------------- supervision
    def _supervise(self) -> None:
        """Poll loop: exits, ready files, health probes, relaunches."""
        while not self._stop.is_set():
            with self._lock:
                replicas = list(self.replicas)
            now = time.monotonic()
            for r in replicas:
                try:
                    self._tick_replica(r, now)
                except Exception as e:  # noqa: BLE001 - supervision must survive a probe error
                    log.warning(f"Fleet supervisor tick failed for "
                                f"replica {r.idx}: {e}")
            self._stop.wait(self.POLL_INTERVAL_S)

    def _tick_replica(self, r: ReplicaState, now: float) -> None:
        # snapshot under the lock; the slow work (waitpid, file read,
        # health round trip) runs lock-free on locals, and the writes
        # re-take the lock — endpoints() must never block on a probe
        with self._lock:
            proc, down, relaunch_at = r.proc, r.down, r.relaunch_at
            port, adopted, spawned_at = r.port, r.adopted, r.spawned_at
            probe_due = (now - r.last_probe >= self.health_interval_s)
        # 1) exit detection + classified relaunch (spawned replicas)
        if proc is not None and not down and relaunch_at is None:
            rc = proc.poll()
            if rc is not None and not self._stop.is_set():
                self._on_replica_exit(r, rc)
                return
        # 2) pending relaunch after backoff
        if relaunch_at is not None and now >= relaunch_at and not down:
            with self._lock:
                self._spawn(r)
                gen, restarts = r.gen, r.restarts
            global_registry.inc("serve_replica_restarts")
            emit_event("serve_replica_restart", replica=r.idx,
                       gen=gen, restarts=restarts)
            return
        # 3) ready-file discovery (port lands once the daemon warmed)
        if port is None and not adopted:
            if proc is None or relaunch_at is not None:
                return
            info = self._read_ready_file(r.idx)
            if info is not None:
                new_port = int(info.get("port", -1))
                with self._lock:
                    # <0 = replica runs without a TCP front end
                    r.port = new_port if new_port >= 0 else None
                    port = r.port
            elif now - spawned_at > self.READY_TIMEOUT_S:
                log.warning(f"Fleet replica {r.idx} produced no ready "
                            f"file within {self.READY_TIMEOUT_S}s")
        # 4) health probe
        if port is not None and probe_due:
            with self._lock:
                r.last_probe = now
            self._probe(r, port)

    def _on_replica_exit(self, r: ReplicaState, rc: int) -> None:
        kind = classify_returncode(rc)
        tail = tail_file(self._log_file(r.idx), max_bytes=2048)
        global_registry.inc("serve_replica_down")
        # the dead process's counters are gone; a relaunch restarts them
        # from zero — keeping the stale scrape would double-count
        self.aggregator.forget(r.idx)
        with self._lock:
            r.healthy = False
            r.ready = False
            r.port = None
            exhausted = r.restarts >= self.max_restarts
            if exhausted:
                r.down = True
            else:
                r.restarts += 1
                backoff = min(self.BACKOFF_BASE_S * (2 ** (r.restarts - 1)),
                              self.BACKOFF_CAP_S)
                r.relaunch_at = time.monotonic() + backoff
            restarts = r.restarts
        emit_event("serve_replica_down", replica=r.idx, returncode=rc,
                   kind=kind, restarts=restarts,
                   permanent=bool(exhausted), log_tail=tail[-512:])
        log.warning(f"Fleet replica {r.idx} exited rc={rc} ({kind}); "
                    + ("restart budget exhausted — replica is down"
                       if exhausted else
                       f"relaunching (restart {restarts}/"
                       f"{self.max_restarts})"))

    def _read_ready_file(self, idx: int) -> Optional[Dict[str, object]]:
        path = self._ready_file(idx)
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None  # not landed yet (atomic write: never torn)

    def _probe(self, r: ReplicaState, port: int) -> None:
        """One `op=health` round trip (+ an `op=metrics` scrape for the
        fleet aggregator on the same connection); mutates r under the
        lock."""
        from .frontend import LineClient
        client = LineClient(r.host, port, connect_timeout_s=0.75,
                            max_connect_attempts=1)
        try:
            h = client.request({"op": "health"}, timeout_s=2.0)
            with self._lock:
                r.healthy = bool(h.get("ok"))
                r.ready = bool(h.get("ready"))
                r.shedding = bool(h.get("shedding"))
                r.versions = {str(k): int(v) for k, v in
                              (h.get("models") or {}).items()}
            if h.get("ok"):
                # the aggregator's scrape rides the probe tick: same
                # wire, same connection, one extra round trip
                m = client.request({"op": "metrics"}, timeout_s=2.0)
                if m.get("ok") and m.get("metrics"):
                    self.aggregator.record_scrape(r.idx, m["metrics"])
        except (ConnectionError, OSError):
            with self._lock:
                r.healthy = False
                r.ready = False
        finally:
            client.close()

    def scrape_all(self) -> int:
        """Force one synchronous aggregator refresh of every ROUTABLE
        replica (tests and the bench compare merged-vs-per-replica
        counters and need a consistent snapshot, not a probe-tick-stale
        one).  Returns the number of replicas scraped."""
        from .frontend import LineClient
        n = 0
        for ep in self.endpoints():
            client = LineClient(ep.host, ep.port, connect_timeout_s=0.75,
                                max_connect_attempts=1)
            try:
                m = client.request({"op": "metrics"}, timeout_s=5.0)
                if m.get("ok") and m.get("metrics"):
                    self.aggregator.record_scrape(ep.idx, m["metrics"])
                    n += 1
            except (ConnectionError, OSError):
                pass
            finally:
                client.close()
        return n

    # -------------------------------------------------------------- access
    def endpoints(self, model: Optional[str] = None
                  ) -> List[ReplicaEndpoint]:
        """Snapshot of the ROUTABLE replicas (healthy + ready + port
        known), optionally filtered to those serving `model`."""
        with self._lock:
            out = []
            for r in self.replicas:
                if r.down or not r.healthy or not r.ready \
                        or r.port is None:
                    continue
                if model is not None and r.versions \
                        and model not in r.versions:
                    continue
                out.append(ReplicaEndpoint(r.idx, r.host, r.port, r.gen,
                                           r.shedding, dict(r.versions)))
            return out

    def describe(self) -> List[Dict[str, object]]:
        with self._lock:
            return [r.describe() for r in self.replicas]

    def alive(self) -> bool:
        with self._lock:
            return any(not r.down for r in self.replicas)

    def set_model_path(self, name: str, path: str) -> None:
        """Fleet-coordinated rollout, relaunch half: after a publish
        lands (router.publish / canary promotion), future RELAUNCHES
        must load the new version — otherwise a crash during steady
        state would resurrect the retired incumbent into the fleet."""
        with self._lock:
            found = False
            for i, (n, _p) in enumerate(self.model_entries):
                if n == name:
                    self.model_entries[i] = (name, str(path))
                    found = True
            if not found:
                self.model_entries.append((name, str(path)))
