"""Optional line-delimited-JSON TCP front end for the serving daemon.

One request per line, one response per line (the reference CLI's
analogue is file-in/file-out prediction; a daemon needs a wire):

    {"model": "m", "rows": [[...], ...], "mode": "predict"}
      -> {"ok": true, "version": 2, "preds": [...]}
    {"op": "stats"}      -> {"ok": true, "stats": {...}}
    {"op": "models"}     -> {"ok": true, "models": [...]}
    {"op": "metrics"}    -> {"ok": true, "metrics": "<prometheus text>"}

Deliberately minimal: newline-framed JSON over TCP is debuggable with
`nc`, needs no dependency, and each connection gets its own handler
thread (socketserver.ThreadingTCPServer) feeding the SAME coalescer —
concurrent connections batch together exactly like in-process clients.
Malformed input answers `{"ok": false, "error": ...}` on that line and
keeps the connection; serving errors never kill the server.
"""

from __future__ import annotations

import json
import socketserver
import threading

import numpy as np

from ..utils import log


class _Handler(socketserver.StreamRequestHandler):
    def _reply(self, obj) -> None:
        self.wfile.write((json.dumps(obj) + "\n").encode())
        self.wfile.flush()

    def handle(self) -> None:
        daemon = self.server.serving_daemon
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
                op = msg.get("op", "predict")
                if op == "stats":
                    self._reply({"ok": True, "stats": daemon.stats()})
                    continue
                if op == "models":
                    self._reply({"ok": True,
                                 "models": daemon.registry.names()})
                    continue
                if op == "metrics":
                    # the Prometheus text page inline, for clients
                    # already on this wire (the HTTP listener on
                    # `metrics_port` is the scraper-facing surface)
                    from ..observability import render_prometheus
                    self._reply({"ok": True,
                                 "metrics": render_prometheus(
                                     daemon=daemon)})
                    continue
                rows = np.asarray(msg["rows"], np.float64)
                fut = daemon.submit(msg.get("model", "default"), rows,
                                    mode=msg.get("mode", "predict"))
                out = fut.result(timeout=self.server.request_timeout_s)
                self._reply({"ok": True, "version": fut.version,
                             "latency_ms": round(fut.latency_ms, 3),
                             "preds": np.asarray(out).tolist()})
            except Exception as e:  # noqa: BLE001 - per-line error reply
                try:
                    self._reply({"ok": False, "error": str(e)})
                except OSError:
                    return  # peer went away mid-reply


class ServeFrontend(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def start_frontend(daemon, port: int = 0, host: str = "127.0.0.1",
                   request_timeout_s: float = 60.0) -> ServeFrontend:
    """Bind (port 0 = ephemeral) and serve on a background thread.
    Returns the server; `server.server_address[1]` is the bound port and
    `server.shutdown()` stops it (the daemon drain path calls that)."""
    srv = ServeFrontend((host, int(port)), _Handler)
    srv.serving_daemon = daemon
    srv.request_timeout_s = float(request_timeout_s)
    t = threading.Thread(target=srv.serve_forever,
                         name="lgbm-serve-frontend", daemon=True)
    t.start()
    log.info(f"Serving front end listening on "
             f"{srv.server_address[0]}:{srv.server_address[1]}")
    return srv
