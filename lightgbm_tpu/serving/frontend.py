"""Line-delimited-JSON TCP front end for the serving daemon, plus the
reconnecting client the router and remote callers use.

One request per line, one response per line (the reference CLI's
analogue is file-in/file-out prediction; a daemon needs a wire):

    {"model": "m", "rows": [[...], ...], "mode": "predict",
     "deadline_ms": 250}
      -> {"ok": true, "version": 2, "preds": [...]}
    {"op": "stats"}      -> {"ok": true, "stats": {...}}
    {"op": "models"}     -> {"ok": true, "models": [...]}
    {"op": "health"}     -> {"ok": true, "ready": true, "pending": 0,
                             "shedding": false, "models": {...}}
    {"op": "metrics"}    -> {"ok": true, "metrics": "<prometheus text>"}
    {"op": "publish", "model": "m", "path": "model.txt"}
      -> {"ok": true, "version": 3}

Deliberately minimal: newline-framed JSON over TCP is debuggable with
`nc`, needs no dependency, and each connection gets its own handler
thread (socketserver.ThreadingTCPServer) feeding the SAME coalescer —
concurrent connections batch together exactly like in-process clients.
Malformed input answers `{"ok": false, "error": ...}` on that line and
keeps the connection; serving errors never kill the server.

Fleet semantics (ISSUE 13):

* `deadline_ms` rides each predict request and BOUNDS the wait on this
  replica — the router decrements it by time already spent, so a
  request near its budget fails fast here instead of camping on a
  replica the client has already given up on;
* a full queue answers `{"ok": false, "shed": true, ...}` — a
  structured, retryable rejection the router maps to "try another
  replica", distinct from a caller error (bad rows, unknown model)
  which retrying cannot fix;
* `op=health` is the fleet probe (readiness = warmup ledger complete);
  `op=publish` is the rollout hook — the router rolls a new model
  version replica-by-replica through it (load + warmup on the
  replica's background thread, atomic swap at the end).
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
from typing import Optional

import numpy as np

from ..observability.tracing import TraceContext
from ..utils import log
from .coalescer import ShedError


class _Handler(socketserver.StreamRequestHandler):
    def _reply(self, obj) -> None:
        self.wfile.write((json.dumps(obj) + "\n").encode())
        self.wfile.flush()

    def handle(self) -> None:
        daemon = self.server.serving_daemon
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            trace_id = None   # echoed on EVERY reply shape, errors included
            try:
                msg = json.loads(line)
                op = msg.get("op", "predict")
                ctx = TraceContext.from_wire(msg.get("trace"))
                trace_id = ctx.trace_id if ctx is not None else None
                if op == "stats":
                    self._reply({"ok": True, "stats": daemon.stats()})
                    continue
                if op == "models":
                    self._reply({"ok": True,
                                 "models": daemon.registry.names()})
                    continue
                if op == "health":
                    # the fleet probe: cheap (no device interaction),
                    # answered even while models are still warming
                    h = daemon.health()
                    h["ok"] = True
                    self._reply(h)
                    continue
                if op == "metrics":
                    # the Prometheus text page inline, for clients
                    # already on this wire (the HTTP listener on
                    # `metrics_port` is the scraper-facing surface)
                    from ..observability import render_prometheus
                    self._reply({"ok": True,
                                 "metrics": render_prometheus(
                                     daemon=daemon)})
                    continue
                if op == "publish":
                    # rollout hook: load + warm the new version on the
                    # registry's background thread, swap atomically,
                    # answer with the live version.  block=True — the
                    # ROUTER paces the rollout replica-by-replica, so
                    # the reply must mean "this replica serves it now"
                    daemon.registry.register(
                        msg["model"], model_file=msg["path"], block=True,
                        timeout=msg.get("timeout_s"))
                    self._reply({"ok": True,
                                 "version": daemon.registry
                                 .versions().get(msg["model"])})
                    continue
                rows = np.asarray(msg["rows"], np.float64)
                timeout_s = self.server.request_timeout_s
                deadline_ms = msg.get("deadline_ms")
                if deadline_ms is not None:
                    # fail fast below 1 ms remaining: even a warm
                    # coalesced dispatch cannot answer inside that, and
                    # the router's per-hop decrement clamps forwarded
                    # deadlines to >= 1 ms — so sub-millisecond budgets
                    # only arrive from clients that have already given
                    # up (deterministic, instead of racing the
                    # dispatcher for a microsecond future wait)
                    if float(deadline_ms) < 1.0:
                        raise TimeoutError(
                            "deadline_ms exhausted before dispatch")
                    timeout_s = min(timeout_s, float(deadline_ms) / 1000.0)
                fut = daemon.submit(msg.get("model", "default"), rows,
                                    mode=msg.get("mode", "predict"),
                                    trace=ctx)
                out = fut.result(timeout=timeout_s)
                reply = {"ok": True, "version": fut.version,
                         "latency_ms": round(fut.latency_ms, 3),
                         "preds": np.asarray(out).tolist()}
                if trace_id is not None:
                    reply["trace_id"] = trace_id
                    # sampled context: the replica-side child spans ride
                    # the envelope back to the router's SpanAssembler
                    spans = fut.spans
                    if spans:
                        reply["spans"] = spans
                self._reply(reply)
            except ShedError as e:
                # structured shed: retryable elsewhere, by contract
                try:
                    self._reply({"ok": False, "shed": True,
                                 "error": str(e), "pending": e.pending,
                                 "trace_id": trace_id})
                except OSError:
                    return
            except TimeoutError as e:
                try:
                    self._reply({"ok": False, "timeout": True,
                                 "error": str(e), "trace_id": trace_id})
                except OSError:
                    return
            except Exception as e:  # noqa: BLE001 - per-line error reply
                try:
                    self._reply({"ok": False, "error": str(e),
                                 "trace_id": trace_id})
                except OSError:
                    return  # peer went away mid-reply


class ServeFrontend(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class ServeUdsFrontend(socketserver.ThreadingUnixStreamServer):
    """The SAME line-JSON wire over a Unix domain socket
    (`serve_uds_path`): one handler class serves both transports, so
    predict/publish/health/metrics/trace behave identically — no TCP
    stack, no port allocation, natural for same-host sidecars."""
    allow_reuse_address = True
    daemon_threads = True


def start_frontend(daemon, port: int = 0, host: str = "127.0.0.1",
                   request_timeout_s: float = 60.0) -> ServeFrontend:
    """Bind (port 0 = ephemeral) and serve on a background thread.
    Returns the server; `server.server_address[1]` is the bound port and
    `server.shutdown()` stops it (the daemon drain path calls that).
    `request_timeout_s` (param `serve_request_timeout_s`) bounds each
    request's wait when the caller sends no `deadline_ms`."""
    srv = ServeFrontend((host, int(port)), _Handler)
    srv.serving_daemon = daemon
    srv.request_timeout_s = float(request_timeout_s)
    t = threading.Thread(target=srv.serve_forever,
                         name="lgbm-serve-frontend", daemon=True)
    t.start()
    log.info(f"Serving front end listening on "
             f"{srv.server_address[0]}:{srv.server_address[1]}")
    return srv


def start_uds_frontend(daemon, path: str,
                       request_timeout_s: float = 60.0
                       ) -> ServeUdsFrontend:
    """Bind the line-JSON wire on a Unix socket at `path` and serve on
    a background thread.  A stale socket file from a previous process
    is unlinked first (the bind would fail on it); the live socket is
    left for the OS/operator on shutdown, like any pidfile-adjacent
    artifact."""
    path = os.fspath(path)
    try:
        os.unlink(path)
    except OSError:
        pass  # no stale socket — the common case
    srv = ServeUdsFrontend(path, _Handler)
    srv.serving_daemon = daemon
    srv.request_timeout_s = float(request_timeout_s)
    t = threading.Thread(target=srv.serve_forever,
                         name="lgbm-serve-uds", daemon=True)
    t.start()
    log.info(f"Serving UDS front end listening on {path}")
    return srv


class LineClient:
    """One line-JSON connection to a replica, with
    reconnect-with-backoff (ISSUE 13 satellite: a dropped TCP
    connection used to raise straight to the caller).

    NOT thread-safe by design: the wire is strictly
    one-request-one-response per connection, so each router worker
    thread owns its own LineClient (thread-local pool).  `request()`
    reconnects lazily — when the socket is gone it retries the
    CONNECT with exponential backoff inside the deadline; it never
    silently re-SENDS a request on a connection that died mid-exchange
    (the caller decides whether the operation is idempotent enough to
    retry, which for predicts the router does, on a different
    replica)."""

    def __init__(self, host: Optional[str] = None,
                 port: Optional[int] = None,
                 connect_timeout_s: float = 5.0,
                 backoff_ms: float = 25.0, max_connect_attempts: int = 4,
                 uds_path: Optional[str] = None):
        if (uds_path is None) == (host is None or port is None):
            raise ValueError("LineClient needs either host+port (TCP) or "
                             "uds_path (Unix socket)")
        self.host = host
        self.port = int(port) if port is not None else None
        self.uds_path = os.fspath(uds_path) if uds_path else None
        self._connect_timeout_s = float(connect_timeout_s)
        self._backoff_ms = float(backoff_ms)
        self._max_connect_attempts = max(int(max_connect_attempts), 1)
        self._sock: Optional[socket.socket] = None
        self._file = None

    @property
    def _peer(self) -> str:
        return self.uds_path if self.uds_path is not None \
            else f"{self.host}:{self.port}"

    def _open_socket(self, timeout: float) -> socket.socket:
        if self.uds_path is None:
            return socket.create_connection((self.host, self.port),
                                            timeout=timeout)
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            s.settimeout(timeout)
            s.connect(self.uds_path)
        except OSError:
            s.close()
            raise
        return s

    # ------------------------------------------------------------ plumbing
    def _connect(self, deadline: Optional[float]) -> None:
        delay = self._backoff_ms / 1000.0
        last: Optional[Exception] = None
        for attempt in range(self._max_connect_attempts):
            if deadline is not None and time.monotonic() >= deadline:
                break
            try:
                timeout = self._connect_timeout_s
                if deadline is not None:
                    timeout = min(timeout,
                                  max(deadline - time.monotonic(), 0.05))
                self._sock = self._open_socket(timeout)
                self._file = self._sock.makefile("rwb")
                return
            except OSError as e:
                last = e
                self.close()
                if attempt + 1 < self._max_connect_attempts:
                    time.sleep(delay)
                    delay *= 2
        raise ConnectionError(
            f"could not connect to {self._peer} within "
            f"{self._max_connect_attempts} attempts: {last}")

    def close(self) -> None:
        for obj in (self._file, self._sock):
            if obj is not None:
                try:
                    obj.close()
                except OSError:
                    pass
        self._file = None
        self._sock = None

    @property
    def connected(self) -> bool:
        return self._sock is not None

    # ------------------------------------------------------------- request
    def request(self, msg: dict, timeout_s: Optional[float] = None) -> dict:
        """One request -> one decoded reply.  Reconnects (with backoff)
        when the connection is gone BEFORE sending; a connection that
        dies mid-exchange raises ConnectionError and is closed — the
        caller owns the retry decision."""
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        if self._sock is None:
            self._connect(deadline)
        try:
            # per-exchange socket timeout; a bounded default even with
            # no explicit deadline — a vanished peer must never wedge a
            # router worker forever
            self._sock.settimeout(max(timeout_s, 0.05)
                                  if timeout_s is not None else 120.0)
            self._file.write((json.dumps(msg) + "\n").encode())
            self._file.flush()
            line = self._file.readline()
        except (OSError, ValueError) as e:
            self.close()
            raise ConnectionError(
                f"connection to {self._peer} failed "
                f"mid-request: {e}") from e
        if not line:
            self.close()
            raise ConnectionError(
                f"connection to {self._peer} closed by peer")
        try:
            return json.loads(line)
        except ValueError as e:
            self.close()
            raise ConnectionError(
                f"malformed reply from {self._peer}: "
                f"{line[:128]!r}") from e
