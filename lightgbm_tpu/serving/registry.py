"""Model registry: background load + bucket-ladder warmup + atomic hot swap.

The reference serves through one long-lived `Predictor` per model
(ref: src/application/predictor.hpp — parse once, reuse buffers per
call); the daemon generalizes that to MANY models behind one device.
Each registered model becomes an immutable `ModelEntry`: the Booster is
loaded and packed (inference/pack.py) and the whole bucket ladder is
compiled (DevicePredictor.warmup) on a BACKGROUND thread, then the
name -> entry binding swaps atomically under the registry lock.  The
serving path therefore never pays a load, a pack, or a compile:

* hot swap — re-registering a name builds the new version completely
  off the serving path; requests keep landing on the old entry until
  the one-pointer swap, and requests already holding the old entry
  (acquired at submit) finish on it — no request ever sees a half
  -loaded model or a torn mix of two versions;
* eviction — an entry is freed (device buffers + compiled entries
  dropped, `serve_evict` event) only when it is BOTH retired (swapped
  out or unregistered) and idle (per-entry refcount at zero);
* failed loads — a load/warmup error parks on the LoadHandle and emits
  `serve_load_failed`; the previous version keeps serving.

`serve_recompiles` distinguishes warmup compiles (expected, counted per
entry at ready time) from serving-path compiles (a bug the bench gates
on zero): it sums `traces - warmup_traces` over live and retired
entries.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from ..observability import emit_event
from ..observability.registry import global_registry
from ..utils import log


class ModelEntry:
    """One immutable packed model version with device-buffer refcounting.

    Requests `acquire()` the entry at submit time and `release()` it
    after their response is set; `retire()` marks it evicted.  The
    device buffers are freed exactly once, when retired AND idle."""

    def __init__(self, name: str, version: int, predictor, num_features: int,
                 num_class: int, source: str = ""):
        self.name = name
        self.version = version
        self.predictor = predictor
        self.num_features = int(num_features)
        self.num_class = int(num_class)
        self.source = source
        self.warmup_traces = 0
        self._lock = threading.Lock()
        self._refs = 0
        self._retired = False
        self.released = False

    def acquire(self) -> "ModelEntry":
        with self._lock:
            self._refs += 1
        return self

    def release(self) -> None:
        with self._lock:
            self._refs -= 1
            free = self._retired and self._refs <= 0 and not self.released
            if free:
                self.released = True
        if free:
            self._free()

    def retire(self) -> None:
        with self._lock:
            self._retired = True
            free = self._refs <= 0 and not self.released
            if free:
                self.released = True
        if free:
            self._free()

    def _free(self) -> None:
        self.predictor.release_device()
        emit_event("serve_evict", model=self.name, version=self.version)

    def traces(self) -> int:
        return self.predictor.total_traces()

    @property
    def in_flight(self) -> int:
        with self._lock:
            return self._refs


class LoadHandle:
    """Future for one background register(): `wait()` blocks until the
    load+warmup finished; `entry` / `error` carry the outcome."""

    def __init__(self, name: str):
        self.name = name
        self._done = threading.Event()
        self.entry: Optional[ModelEntry] = None
        self.error: Optional[BaseException] = None

    def wait(self, timeout: Optional[float] = None) -> "LoadHandle":
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"Model {self.name!r} load did not finish in {timeout}s")
        if self.error is not None:
            raise RuntimeError(
                f"Model {self.name!r} failed to load: {self.error}"
            ) from self.error
        return self

    def done(self) -> bool:
        return self._done.is_set()

    def _finish(self, entry=None, error=None) -> None:
        self.entry = entry
        self.error = error
        self._done.set()


class ModelRegistry:
    """name -> ModelEntry map with background loading and hot swap."""

    def __init__(self, min_bucket: int = 4096, warmup_rows: int = 65536,
                 warmup: bool = True,
                 early_stop: Optional[Tuple[int, float]] = None):
        self._lock = threading.RLock()
        self._models: Dict[str, ModelEntry] = {}
        self._versions: Dict[str, int] = {}
        self._pending: Dict[str, LoadHandle] = {}
        self._retired_extra_traces = 0
        self._min_bucket = int(min_bucket)
        self._warmup_rows = int(warmup_rows)
        self._warmup = bool(warmup)
        self._early_stop = early_stop

    # ------------------------------------------------------------ register
    def register(self, name: str, model_file: Optional[str] = None,
                 model_str: Optional[str] = None, booster=None,
                 block: bool = False,
                 timeout: Optional[float] = None) -> LoadHandle:
        """Load/repack a model and swap it in under `name`.  Exactly one
        of model_file / model_str / booster; the load, pack and warmup
        run on a background thread and the swap is atomic — `block=True`
        waits for readiness (and raises on a failed load)."""
        if sum(x is not None for x in (model_file, model_str, booster)) != 1:
            raise ValueError("register() needs exactly one of model_file, "
                             "model_str or booster")
        handle = LoadHandle(name)
        with self._lock:
            # concurrent registers of one name swap in COMPLETION order:
            # whichever load lands last serves (each swap is still
            # atomic and torn-free); serialize registers per name if
            # strict submission order matters
            self._pending[name] = handle
        t = threading.Thread(
            target=self._load_and_swap,
            args=(handle, name, model_file, model_str, booster),
            name=f"lgbm-serve-load-{name}", daemon=True)
        t.start()
        if block:
            handle.wait(timeout)
        return handle

    def _load_and_swap(self, handle: LoadHandle, name: str,
                       model_file, model_str, booster) -> None:
        try:
            entry = self._build_entry(name, model_file, model_str, booster)
        except BaseException as e:  # noqa: BLE001 - surfaced on the handle
            log.warning(f"Serving model {name!r} failed to load: {e}")
            emit_event("serve_load_failed", model=name, error=str(e))
            global_registry.inc("serve_load_failures")
            handle._finish(error=e)
            return
        with self._lock:
            old = self._models.get(name)
            self._models[name] = entry
            if self._pending.get(name) is handle:
                del self._pending[name]
            if old is not None:
                # fold the retiree's serving-path traces into the
                # recompile ledger before its counters are dropped
                self._retired_extra_traces += max(
                    old.traces() - old.warmup_traces, 0)
        emit_event("serve_swap", model=name, version=entry.version,
                   previous=(old.version if old is not None else None),
                   warmup_traces=entry.warmup_traces)
        global_registry.inc("serve_swaps")
        if old is not None:
            old.retire()  # frees when the last in-flight request releases
        handle._finish(entry=entry)

    def _build_entry(self, name: str, model_file, model_str,
                     booster) -> ModelEntry:
        from ..basic import Booster
        from ..inference import DevicePredictor
        source = model_file or ("<string>" if model_str else "<booster>")
        if model_file is not None:
            if not os.path.exists(model_file):
                raise FileNotFoundError(model_file)
            booster = Booster(model_file=model_file)
        elif model_str is not None:
            booster = Booster(model_str=model_str)
        g = booster._gbdt
        g._sync_model()
        K = max(g.num_tree_per_iteration, 1)
        obj = g.objective
        dp = DevicePredictor(
            list(g.models_), num_class=K, average=g.average_output_,
            convert=(obj.convert_output if obj is not None else None),
            min_bucket=self._min_bucket)
        if not dp.ok:
            raise ValueError(
                "model is not device-servable (linear-tree leaves or an "
                "empty ensemble); see docs/Serving.md fallback matrix")
        num_features = int(booster.num_feature())
        if self._warmup:
            # every servable mode compiles up front — a mode first hit
            # by live traffic would count as a serving-path recompile
            modes = (("convert", "raw", "leaf") if obj is not None
                     else ("raw", "leaf"))
            dp.warmup(num_features, self._warmup_rows, modes=modes,
                      early_stop=self._early_stop)
        with self._lock:
            version = self._versions.get(name, 0) + 1
            self._versions[name] = version
        entry = ModelEntry(name, version, dp, num_features, K, source)
        entry.warmup_traces = dp.total_traces()
        return entry

    # ------------------------------------------------------------- access
    def get(self, name: str) -> ModelEntry:
        """Acquire the current entry for `name` (caller must release)."""
        with self._lock:
            e = self._models.get(name)
            if e is None:
                raise KeyError(f"No model {name!r} is registered "
                               f"(serving: {sorted(self._models)})")
            return e.acquire()

    def wait_ready(self, name: str, timeout: Optional[float] = None) -> None:
        """Block until a pending load for `name` lands (no-op when the
        name is already live with no load in flight)."""
        with self._lock:
            handle = self._pending.get(name)
        if handle is not None:
            handle.wait(timeout)

    def unregister(self, name: str) -> bool:
        with self._lock:
            e = self._models.pop(name, None)
            if e is not None:
                self._retired_extra_traces += max(
                    e.traces() - e.warmup_traces, 0)
        if e is None:
            return False
        e.retire()
        return True

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def versions(self) -> Dict[str, int]:
        with self._lock:
            return {n: e.version for n, e in self._models.items()}

    def ready(self) -> bool:
        """Readiness for the fleet health probe: at least one model is
        live and every live entry finished its warmup ledger (warmup
        disabled counts as complete — the operator opted into cold
        compiles).  A pending load blocks readiness only for a name
        with NO live entry yet (cold start): a hot-swap publish keeps
        the old version serving while the new one warms, so the
        replica stays routable through the roll.  A replica is routed
        to only when this is True, so live traffic never pays a load
        or a ladder compile."""
        with self._lock:
            if not self._models:
                return False
            if any(name not in self._models for name in self._pending):
                return False
            return all((not self._warmup) or e.warmup_traces > 0
                       for e in self._models.values())

    def serve_recompiles(self) -> int:
        """Traces compiled OUTSIDE warmup — 0 in a healthy steady state
        (every request size pads into a pre-compiled bucket)."""
        with self._lock:
            entries = list(self._models.values())
            extra = self._retired_extra_traces
        return extra + sum(max(e.traces() - e.warmup_traces, 0)
                           for e in entries)

    def stats(self) -> Dict[str, object]:
        with self._lock:
            models = {n: {"version": e.version, "in_flight": e.in_flight,
                          "num_features": e.num_features,
                          "warmup_traces": e.warmup_traces,
                          "traces": e.traces()}
                      for n, e in self._models.items()}
        return {"models": models, "serve_recompiles": self.serve_recompiles()}

    def close(self) -> None:
        with self._lock:
            entries = list(self._models.values())
            self._models.clear()
        for e in entries:
            e.retire()
