"""Request router over a replica fleet: retry/backoff routing,
deadline propagation, load-shedding admission, canary auto-rollback.

The router is the fleet's single client-facing door (its own line-JSON
TCP front end, same protocol as a replica's) and owns three policies:

* **Routing + retry.** Requests round-robin over the ROUTABLE replicas
  (healthy + warmup-ready, from the fleet's health probes).  A
  connection error, a timeout, or a structured `shed` retries on a
  DIFFERENT replica with exponential backoff — predicts are idempotent
  (same model version, same rows, same bytes), which is what makes the
  blind retry safe.  Every hop decrements the request's `deadline_ms`,
  so a retry never outlives the client's patience and a replica never
  works on a request its caller already abandoned.

* **Load shedding / admission.** A replica whose bounded queue is full
  FAILS FAST with `shed` (coalescer.ShedError) instead of blocking;
  the router retries the request elsewhere and counts the shed.  Once
  EVERY routable replica is shedding (health-probe `shedding` flag, or
  all attempts in a request shed), the fleet-wide admission controller
  rejects with `overloaded` immediately — queueing more work into a
  saturated fleet only converts overload into timeout storms.

* **Rollout + canary auto-rollback.** `publish(model, path)` rolls the
  new version replica-by-replica (each `op=publish` loads + warms on
  the replica's background thread, then swaps atomically; a mixed
  FLEET is fine mid-roll because each coalesced batch lives inside one
  replica — the per-version grouping in coalescer.py).  With a canary
  share (`serve_canary_pct`), only ONE replica gets the candidate
  first; the router routes that share of the model's traffic to it and
  compares the score distribution online against the incumbent
  replicas (Welford mean/std over per-request mean scores — the cheap
  online form of the byte-identity guardrail `bench.py --serve-fleet`
  applies exactly).  Divergence beyond `serve_canary_max_divergence`
  sigmas or a canary error rate above `serve_canary_max_error_rate`
  triggers AUTO-ROLLBACK: the incumbent version is re-published to the
  canary replica and a `serve_rollback` event (+ counter) lands.  A
  clean canary promotes: the remaining replicas roll one at a time.

Counters (fleet `/metrics`, prefix `lgbm_`): `router_requests`,
`router_rows`, `router_retries`, `router_failed`, `serve_shed`
(router-observed sheds), `serve_overloaded`, `serve_rollback`,
`serve_publish`; gauges via `gauges_cb`: `router_p50_ms`,
`router_p99_ms`, `fleet_replicas_routable`, `fleet_replicas_down`.
"""

from __future__ import annotations

import json
import math
import socketserver
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..observability import emit_event
from ..observability.registry import LatencyWindow, global_registry
from ..observability.tracing import (MAX_SPANS_PER_REQUEST, SloTracker,
                                     SpanAssembler, TraceContext,
                                     make_span)
from ..utils import log
from .coalescer import ShedError
from .fleet import ReplicaEndpoint, ReplicaFleet
from .frontend import LineClient


class OverloadedError(RuntimeError):
    """Fleet-wide admission rejection: every routable replica is
    shedding (or shed this request's every attempt).  Retrying
    immediately is pointless — back off client-side."""


class NoReplicaError(RuntimeError):
    """No routable replica at all (fleet still warming, or every
    replica is down/unhealthy)."""


class RouterReply:
    """One routed request's outcome: result rows plus which replica and
    model version served it, how many retries it took, and the
    request's trace id (greppable in replica logs and the flight
    recorder; `op=trace` / `GET /trace/<id>` resolves it to the
    assembled waterfall when the request was sampled)."""

    __slots__ = ("preds", "version", "replica", "retries", "latency_ms",
                 "trace_id")

    def __init__(self, preds, version, replica, retries, latency_ms,
                 trace_id=None):
        self.preds = preds
        self.version = version
        self.replica = replica
        self.retries = retries
        self.latency_ms = latency_ms
        self.trace_id = trace_id


class _Welford:
    """Online mean/std (Welford) — the canary's score-distribution
    accumulator; O(1) per observation, no sample retention."""

    __slots__ = ("n", "mean", "_m2")

    def __init__(self):
        self.n = 0
        self.mean = 0.0
        self._m2 = 0.0

    def add(self, x: float) -> None:
        self.n += 1
        d = x - self.mean
        self.mean += d / self.n
        self._m2 += d * (x - self.mean)

    @property
    def std(self) -> float:
        return math.sqrt(self._m2 / self.n) if self.n > 1 else 0.0


class _CanaryState:
    """One in-flight canary rollout (guarded by the router's lock)."""

    def __init__(self, model: str, path_new: str, path_old: str,
                 replica: int, pct: float):
        self.model = model
        self.path_new = path_new
        self.path_old = path_old
        self.replica = replica          # the canary arm's replica idx
        self.pct = float(pct)
        self.canary = _Welford()
        self.incumbent = _Welford()
        self.canary_errors = 0
        self.canary_requests = 0
        self.resolved: Optional[str] = None  # promoted | rolled_back
        self.reason: Optional[str] = None
        self.done = threading.Event()
        self.seq = 0                    # traffic-split counter


class Router:
    """Client-facing router over a ReplicaFleet (docs/Serving.md)."""

    def __init__(self, fleet: ReplicaFleet, config=None, **params):
        if config is None:
            from ..config import Config
            config = Config(params)
        self.fleet = fleet
        self.config = config
        self.retry_max = int(config.serve_retry_max)
        self.backoff_s = max(float(config.serve_retry_backoff_ms), 0.0) \
            / 1000.0
        self.timeout_s = float(config.serve_request_timeout_s)
        self.latency = LatencyWindow()
        self._lock = threading.Lock()
        self._rr = 0                         # round-robin cursor
        self._seq = 0                        # request counter (tracing)
        self._trace_sample = max(int(config.serve_trace_sample), 0)
        self._canaries: Dict[str, _CanaryState] = {}
        self._published: Dict[str, str] = {}  # model -> incumbent path
        self._tls = threading.local()        # per-thread replica conns
        self.frontend = None
        self.metrics_server = None
        # cross-process span assembly (docs/Observability.md
        # "Distributed tracing"): sampled requests' spans from every hop
        # join here into the op=trace / GET /trace/<id> waterfalls
        self.assembler = SpanAssembler()
        # SLO burn-rate tracking over the router's own request-outcome
        # stream (client-perceived, so retries/sheds are already folded
        # in); inert until serve_slo_p99_ms > 0
        self.slo = SloTracker(
            p99_ms=float(config.serve_slo_p99_ms),
            error_pct=float(config.serve_slo_error_pct),
            fast_window_s=float(config.serve_slo_fast_window_s),
            slow_window_s=float(config.serve_slo_slow_window_s),
            burn_threshold=float(config.serve_slo_burn_threshold))

    # ---------------------------------------------------------- connections
    def _conn_for(self, ep: ReplicaEndpoint) -> LineClient:
        """Per-(thread, replica-generation) connection: the wire is
        one-request-one-response, so router worker threads never share
        a socket; a restarted replica (new gen, new port) gets a fresh
        connection and the stale one is closed lazily."""
        pool = getattr(self._tls, "conns", None)
        if pool is None:
            pool = self._tls.conns = {}
        key = ep.idx
        conn, gen = pool.get(key, (None, -1))
        if conn is None or gen != ep.gen:
            if conn is not None:
                conn.close()
            conn = LineClient(ep.host, ep.port,
                              backoff_ms=self.backoff_s * 1000.0 or 25.0,
                              max_connect_attempts=2)
            pool[key] = (conn, ep.gen)
        return conn

    # -------------------------------------------------------------- routing
    def _pick(self, model: str, tried: set) -> Optional[ReplicaEndpoint]:
        """Choose the next replica for one attempt.  Canary traffic
        split first; then round-robin over untried, non-shedding
        routable replicas; shedding ones only as a last resort (their
        probe flag may be stale by up to the probe interval)."""
        eps = self.fleet.endpoints(model)
        if not eps:
            return None
        with self._lock:
            canary = self._canaries.get(model)
            if canary is not None and canary.resolved is None:
                canary.seq += 1
                take_canary = (canary.seq * canary.pct) % 100.0 < canary.pct
                if take_canary and canary.replica not in tried:
                    for ep in eps:
                        if ep.idx == canary.replica:
                            return ep
                # incumbent arm: never the canary replica, so the
                # reference distribution stays version-pure
                eps = [ep for ep in eps if ep.idx != canary.replica] or eps
            self._rr += 1
            cursor = self._rr
        fresh = [ep for ep in eps if ep.idx not in tried]
        if not fresh:
            return None
        ranked = ([ep for ep in fresh if not ep.shedding]
                  or fresh)
        return ranked[cursor % len(ranked)]

    def _edge_context(self, trace) -> TraceContext:
        """The trace context for one routed request: honor an incoming
        wire context (the client edge already stamped one), else
        generate here — the router IS the edge for bare clients.  Every
        request gets ids (error replies and replica logs carry the
        trace_id either way); the `sampled` flag — every
        `serve_trace_sample`-th edge-generated request — decides
        whether spans are collected and assembled."""
        ctx = TraceContext.from_wire(trace) if trace is not None else None
        if ctx is not None:
            return ctx
        with self._lock:
            self._seq += 1
            sampled = (self._trace_sample > 0
                       and self._seq % self._trace_sample == 0)
        return TraceContext.new(sampled=sampled)

    def predict(self, model: str, rows, mode: str = "predict",
                deadline_ms: Optional[float] = None,
                trace=None) -> RouterReply:
        """Route one predict with retry/backoff + deadline propagation.
        Raises OverloadedError (every attempt shed / fleet saturated),
        NoReplicaError, TimeoutError (deadline exhausted), or the
        replica's non-retryable error (bad rows, unknown model); every
        raised error carries `.trace_id` so a client-side failure is
        greppable in replica logs and the flight recorder.  `trace` is
        the wire-format context dict (honored when present, generated
        at this edge when absent); sampled requests assemble a
        cross-process span waterfall into `self.assembler`."""
        t0 = time.monotonic()
        w0 = time.time()

        def wall(mono: float) -> float:
            return w0 + (mono - t0)

        ctx = self._edge_context(trace)
        route_ctx = ctx.child()
        spans: List[Dict] = []       # router-side spans (sampled only)
        replica_spans: List[Dict] = []

        def finish_ok(reply, ep, retries) -> RouterReply:
            lat = (time.monotonic() - t0) * 1000.0
            self.latency.record(lat)
            global_registry.inc("router_requests")
            global_registry.inc("router_rows", n_rows)
            preds = np.asarray(reply["preds"])
            self._observe(model, ep, preds=preds)
            self.slo.observe(lat, ok=True)
            if ctx.sampled:
                replica_spans.extend(reply.get("spans") or ())
                spans.append(make_span(
                    route_ctx, "route", w0, wall(time.monotonic()),
                    model=model, rows=n_rows, retries=retries,
                    replica=ep.idx, deadline_ms=deadline_ms))
                self.assembler.assemble(
                    ctx.trace_id,
                    (spans + replica_spans)[:MAX_SPANS_PER_REQUEST],
                    model=model, rows=n_rows, retries=retries,
                    latency_ms=round(lat, 3), outcome="ok")
            return RouterReply(preds, reply.get("version"), ep.idx,
                               retries, lat, trace_id=ctx.trace_id)

        def fail(exc: BaseException) -> BaseException:
            """Terminal failure: stamp the trace id on the exception,
            feed the SLO tracker, and (sampled) assemble the partial
            waterfall so the failure is findable by id."""
            exc.trace_id = ctx.trace_id  # type: ignore[attr-defined]
            self.slo.observe((time.monotonic() - t0) * 1000.0, ok=False)
            if ctx.sampled:
                spans.append(make_span(
                    route_ctx, "route", w0, wall(time.monotonic()),
                    model=model, rows=n_rows, retries=retries,
                    outcome="error", error=str(exc)[:200]))
                self.assembler.assemble(
                    ctx.trace_id,
                    (spans + replica_spans)[:MAX_SPANS_PER_REQUEST],
                    model=model, outcome="error",
                    error=str(exc)[:200])
            return exc

        budget_s = (float(deadline_ms) / 1000.0
                    if deadline_ms is not None else self.timeout_s)
        deadline = t0 + budget_s
        rows_list = (rows.tolist()
                     if isinstance(rows, np.ndarray) else list(rows))
        n_rows = len(rows_list)
        tried: set = set()
        sheds = 0
        attempts_made = 0
        retries = 0
        last_error: Optional[BaseException] = None
        # fleet-wide admission: all routable replicas advertising
        # `shedding` means the fleet is saturated — reject before
        # burning a round trip (the `overloaded` contract)
        eps = self.fleet.endpoints(model)
        if eps and all(ep.shedding for ep in eps):
            global_registry.inc("serve_overloaded")
            raise fail(OverloadedError(
                f"fleet overloaded: all {len(eps)} routable replicas "
                "are shedding"))
        for attempt in range(self.retry_max + 1):
            remaining = deadline - time.monotonic()
            if remaining <= 0.001:
                global_registry.inc("router_failed")
                raise fail(TimeoutError(
                    f"deadline_ms={deadline_ms} exhausted after "
                    f"{attempt} attempt(s)"
                    + (f" (last: {last_error})" if last_error else "")))
            ep = self._pick(model, tried)
            if ep is None:
                if not tried:
                    global_registry.inc("router_failed")
                    raise fail(NoReplicaError(
                        f"no routable replica for model {model!r} "
                        f"(fleet: {self.fleet.describe()})"))
                # every routable replica tried once; with retry budget
                # (and deadline) remaining, start a fresh round — a
                # shed or a mid-restart replica may well answer the
                # next attempt (the failures were all transient, or we
                # would have raised already)
                tried.clear()
                ep = self._pick(model, tried)
                if ep is None:
                    break
            tried.add(ep.idx)
            backoff = 0.0
            if attempt > 0:
                retries += 1
                global_registry.inc("router_retries")
                backoff = min(self.backoff_s * (2 ** (attempt - 1)),
                              max(remaining - 0.001, 0.0))
                if backoff > 0:
                    time.sleep(backoff)
                remaining = deadline - time.monotonic()
                if remaining <= 0.001:
                    continue  # the deadline check above raises
            # each attempt is its own child span context; the replica
            # parents its `serve` span under it, so the assembled
            # waterfall shows exactly which attempt did the work
            attempt_ctx = route_ctx.child()
            a_start = time.monotonic()

            def note_attempt(outcome: str) -> None:
                if ctx.sampled and len(spans) < MAX_SPANS_PER_REQUEST:
                    spans.append(make_span(
                        attempt_ctx, "attempt", wall(a_start),
                        wall(time.monotonic()), replica=ep.idx,
                        gen=ep.gen, outcome=outcome,
                        backoff_ms=round(backoff * 1000.0, 3) or None))

            msg = {"model": model, "rows": rows_list, "mode": mode,
                   "deadline_ms": max(remaining * 1000.0, 1.0),
                   "trace": attempt_ctx.to_wire()}
            attempts_made += 1
            try:
                reply = self._conn_for(ep).request(
                    msg, timeout_s=remaining + 0.25)
            except (ConnectionError, OSError) as e:
                # replica died / restarted mid-exchange: idempotent
                # predict, retry on a different replica
                last_error = e
                global_registry.inc("router_conn_errors")
                self._observe(model, ep, error=True)
                note_attempt("conn_error")
                continue
            if reply.get("ok"):
                note_attempt("ok")
                return finish_ok(reply, ep, retries)
            if reply.get("shed"):
                sheds += 1
                last_error = ShedError(reply.get("error", "shed"))
                global_registry.inc("serve_shed")
                note_attempt("shed")
                continue
            if reply.get("timeout"):
                last_error = TimeoutError(reply.get("error", "timeout"))
                global_registry.inc("router_timeouts")
                self._observe(model, ep, error=True)
                note_attempt("timeout")
                continue
            # non-retryable: the request itself is wrong (unknown
            # model, bad rows, width mismatch) — retrying cannot fix it
            global_registry.inc("router_failed")
            self._observe(model, ep, error=True)
            note_attempt("error")
            raise fail(RuntimeError(reply.get("error", "serving error")))
        global_registry.inc("router_failed")
        if sheds and sheds == attempts_made:
            global_registry.inc("serve_overloaded")
            raise fail(OverloadedError(
                f"fleet overloaded: all {sheds} attempts shed"))
        raise fail(RuntimeError(
            f"request failed after {attempts_made} attempt(s) "
            f"({retries} retries): {last_error}"))

    # -------------------------------------------------------------- rollout
    def register_incumbent(self, model: str, path: str) -> None:
        """Record the currently-published model file for `model` — the
        version a failed canary rolls BACK to.  The fleet runner calls
        this for every model it loads at startup."""
        with self._lock:
            self._published[model] = str(path)

    def publish(self, model: str, path: str,
                canary_pct: Optional[float] = None,
                timeout_s: float = 300.0) -> Dict[str, object]:
        """Roll `path` out for `model`, replica by replica.

        Plain rollout (no canary share, or nothing to roll back to):
        every routable replica gets `op=publish` in turn — each loads +
        warms in the background and swaps atomically, so the fleet is
        temporarily mixed-version but every BATCH is single-version
        (per-process coalescing).  Canary rollout: one replica gets the
        candidate and the traffic split + online comparison decide
        promotion vs auto-rollback asynchronously; this returns
        immediately with `{"canary": True, ...}` — `canary_wait()`
        blocks for the verdict."""
        pct = (float(canary_pct) if canary_pct is not None
               else float(self.config.serve_canary_pct))
        eps = self.fleet.endpoints(model) or self.fleet.endpoints()
        if not eps:
            raise NoReplicaError("no routable replica to publish to")
        with self._lock:
            incumbent = self._published.get(model)
            if self._canaries.get(model) is not None and \
                    self._canaries[model].resolved is None:
                raise RuntimeError(
                    f"a canary rollout for {model!r} is already in "
                    "flight; wait for its verdict first")
        if pct <= 0 or incumbent is None or len(eps) < 2:
            versions = self._roll(model, path,
                                  [ep.idx for ep in eps], timeout_s)
            with self._lock:
                self._published[model] = str(path)
            # relaunches must load the published version too
            self.fleet.set_model_path(model, path)
            emit_event("serve_publish", model=model, path=str(path),
                       canary=False, replicas=sorted(versions),
                       version=max(versions.values()) if versions else None)
            global_registry.inc("serve_publish")
            return {"canary": False, "replicas": versions}
        canary_ep = eps[0]
        state = _CanaryState(model, str(path), incumbent,
                             canary_ep.idx, pct)
        self._publish_one(model, path, canary_ep, timeout_s)
        with self._lock:
            self._canaries[model] = state
        emit_event("serve_publish", model=model, path=str(path),
                   canary=True, replicas=[canary_ep.idx],
                   canary_pct=pct)
        global_registry.inc("serve_publish")
        log.info(f"Canary for {model!r} live on replica "
                 f"{canary_ep.idx} ({pct:g}% of traffic)")
        return {"canary": True, "replica": canary_ep.idx, "pct": pct}

    def canary_wait(self, model: str,
                    timeout: Optional[float] = None) -> Optional[str]:
        """Block until the in-flight canary for `model` resolves;
        returns "promoted" / "rolled_back" (None: no canary)."""
        with self._lock:
            state = self._canaries.get(model)
        if state is None:
            return None
        if not state.done.wait(timeout):
            raise TimeoutError(f"canary for {model!r} unresolved after "
                               f"{timeout}s")
        return state.resolved

    def _publish_one(self, model: str, path: str, ep: ReplicaEndpoint,
                     timeout_s: float) -> int:
        reply = self._conn_for(ep).request(
            {"op": "publish", "model": model, "path": str(path),
             "timeout_s": timeout_s}, timeout_s=timeout_s)
        if not reply.get("ok"):
            raise RuntimeError(f"publish to replica {ep.idx} failed: "
                               f"{reply.get('error')}")
        return int(reply.get("version") or 0)

    def _roll(self, model: str, path: str, idxs: List[int],
              timeout_s: float) -> Dict[int, int]:
        """Sequential rolling publish: one replica at a time, so a
        load/warmup failure stops the roll with the rest of the fleet
        untouched (and still serving the incumbent)."""
        versions: Dict[int, int] = {}
        for idx in idxs:
            ep = next((e for e in self.fleet.endpoints()
                       if e.idx == idx), None)
            if ep is None:
                log.warning(f"Rolling publish: replica {idx} became "
                            "unroutable; skipping")
                continue
            versions[idx] = self._publish_one(model, path, ep, timeout_s)
            log.info(f"Rolled {model!r} v{versions[idx]} onto replica "
                     f"{idx}")
        return versions

    # --------------------------------------------------------------- canary
    def _observe(self, model: str, ep: ReplicaEndpoint,
                 preds: Optional[np.ndarray] = None,
                 error: bool = False) -> None:
        """Feed one routed outcome into the canary comparison."""
        with self._lock:
            state = self._canaries.get(model)
            if state is None or state.resolved is not None:
                return
            arm_canary = ep.idx == state.replica
            if arm_canary:
                state.canary_requests += 1
                if error:
                    state.canary_errors += 1
                elif preds is not None and preds.size:
                    state.canary.add(float(np.mean(preds)))
            elif not error and preds is not None and preds.size:
                state.incumbent.add(float(np.mean(preds)))
            verdict = self._canary_verdict(state)
            if verdict is None:
                return
            state.resolved, state.reason = verdict
        # resolve OFF the serving path: the rollback/promotion publishes
        # are blocking round trips with warmup behind them
        threading.Thread(target=self._resolve_canary, args=(state,),
                         name=f"lgbm-canary-{model}", daemon=True).start()

    @staticmethod
    def _divergence(state: _CanaryState) -> float:
        """Canary-vs-incumbent mean shift in incumbent sigmas (floored
        so a near-constant incumbent distribution cannot divide the
        shift into infinity)."""
        scale = max(state.incumbent.std,
                    1e-3 * max(abs(state.incumbent.mean), 1.0), 1e-9)
        return abs(state.canary.mean - state.incumbent.mean) / scale

    def _canary_verdict(self, state: _CanaryState):
        """(resolved, reason) once the evidence suffices, else None.
        Caller holds the lock."""
        min_n = int(self.config.serve_canary_min_samples)
        max_err = float(self.config.serve_canary_max_error_rate)
        max_div = float(self.config.serve_canary_max_divergence)
        if state.canary_requests >= max(min_n // 4, 8):
            err_rate = state.canary_errors / max(state.canary_requests, 1)
            if err_rate > max_err:
                return ("rolled_back",
                        f"canary error rate {err_rate:.3f} > {max_err}")
        if state.canary.n >= min_n and state.incumbent.n >= min_n:
            div = self._divergence(state)
            if div > max_div:
                return ("rolled_back",
                        f"score divergence {div:.3f} sigma > {max_div}")
            return ("promoted", f"divergence {div:.3f} <= {max_div}")
        return None

    def _resolve_canary(self, state: _CanaryState) -> None:
        model = state.model
        try:
            if state.resolved == "rolled_back":
                # put the incumbent back on the canary replica
                ep = next((e for e in self.fleet.endpoints()
                           if e.idx == state.replica), None)
                if ep is not None:
                    self._publish_one(model, state.path_old, ep, 300.0)
                global_registry.inc("serve_rollback")
                emit_event("serve_rollback", model=model,
                           replica=state.replica, reason=state.reason,
                           candidate=state.path_new,
                           restored=state.path_old,
                           canary_mean=state.canary.mean,
                           incumbent_mean=state.incumbent.mean,
                           canary_errors=state.canary_errors,
                           canary_requests=state.canary_requests)
                log.warning(f"Canary for {model!r} ROLLED BACK: "
                            f"{state.reason}")
            else:
                idxs = [e.idx for e in self.fleet.endpoints()
                        if e.idx != state.replica]
                self._roll(model, state.path_new, idxs, 300.0)
                with self._lock:
                    self._published[model] = state.path_new
                self.fleet.set_model_path(model, state.path_new)
                emit_event("serve_publish", model=model,
                           path=state.path_new, canary=True,
                           promoted=True, reason=state.reason)
                global_registry.inc("serve_publish")
                log.info(f"Canary for {model!r} promoted fleet-wide: "
                         f"{state.reason}")
        except Exception as e:  # noqa: BLE001 - a failed resolution must be visible, not fatal
            log.warning(f"Canary resolution for {model!r} failed: {e}")
        finally:
            state.done.set()

    # ------------------------------------------------------------ telemetry
    def stats(self) -> Dict[str, object]:
        p50, p99 = self.latency.percentiles((50.0, 99.0))
        with self._lock:
            canaries = {m: {"resolved": s.resolved, "reason": s.reason,
                            "replica": s.replica,
                            "canary_requests": s.canary_requests,
                            "canary_errors": s.canary_errors,
                            "divergence": (self._divergence(s)
                                           if s.canary.n > 1
                                           and s.incumbent.n > 1
                                           else None)}
                        for m, s in self._canaries.items()}
        return {
            "router_requests": global_registry.counter("router_requests"),
            "router_rows": global_registry.counter("router_rows"),
            "router_retries": global_registry.counter("router_retries"),
            "router_failed": global_registry.counter("router_failed"),
            "router_conn_errors":
                global_registry.counter("router_conn_errors"),
            "router_timeouts": global_registry.counter("router_timeouts"),
            "serve_shed": global_registry.counter("serve_shed"),
            "serve_overloaded":
                global_registry.counter("serve_overloaded"),
            "serve_rollback": global_registry.counter("serve_rollback"),
            "serve_publish": global_registry.counter("serve_publish"),
            "router_p50_ms": p50,
            "router_p99_ms": p99,
            "replicas": self.fleet.describe(),
            "canaries": canaries,
            "traces_assembled": len(self.assembler.ids()),
            "fleet_metrics": {
                "replicas_scraped":
                    len(self.fleet.aggregator.snapshot()),
                "latency_ms": self.fleet.aggregator.merged_latency_ms(),
            },
            **({"slo": self.slo.stats()} if self.slo.enabled else {}),
        }

    def health(self) -> Dict[str, object]:
        eps = self.fleet.endpoints()
        return {"ready": bool(eps),
                "routable": len(eps),
                "shedding": bool(eps) and all(e.shedding for e in eps),
                "replicas": self.fleet.describe()}

    def _metric_gauges(self) -> Dict[str, float]:
        """Live gauges for the /metrics page (prom.py gauges_cb)."""
        p50, p99 = self.latency.percentiles((50.0, 99.0))
        desc = self.fleet.describe()
        out = {
            "router_p50_ms": p50 if p50 is not None else float("nan"),
            "router_p99_ms": p99 if p99 is not None else float("nan"),
            "fleet_replicas_routable": float(len(self.fleet.endpoints())),
            "fleet_replicas_down": float(
                sum(1 for r in desc if r["down"])),
        }
        if self.slo.enabled:
            rates = self.slo.burn_rates()
            out["slo_burn_rate_fast"] = rates["fast"]
            out["slo_burn_rate_slow"] = rates["slow"]
        return out

    def _fleet_metrics_block(self) -> str:
        """The /metrics `text_cb`: merged per-replica scrape families
        (fleet.FleetAggregator.render)."""
        return self.fleet.aggregator.render(self.fleet.describe())

    def trace_lookup(self, trace_id: Optional[str] = None):
        """`GET /trace/<id>` / `op=trace` resolver: the assembled
        waterfall for `trace_id`, or the newest when None."""
        return (self.assembler.get(trace_id) if trace_id
                else self.assembler.latest())

    # ------------------------------------------------------------ front end
    def start_frontend(self, port: int = 0, host: str = "127.0.0.1",
                       metrics_port: int = -1) -> "RouterFrontend":
        self.frontend = start_router_frontend(self, port=port, host=host)
        if metrics_port >= 0 and self.metrics_server is None:
            from ..observability import start_metrics_http
            self.metrics_server = start_metrics_http(
                port=metrics_port, gauges_cb=self._metric_gauges,
                text_cb=self._fleet_metrics_block,
                traces_cb=self.trace_lookup)
        return self.frontend

    def stop(self) -> None:
        if self.frontend is not None:
            self.frontend.shutdown()
            self.frontend = None
        if self.metrics_server is not None:
            self.metrics_server.shutdown()
            self.metrics_server = None


class _RouterHandler(socketserver.StreamRequestHandler):
    """Line-JSON handler: the router speaks the SAME wire protocol as a
    replica's front end, so a client cannot tell (and need not care)
    whether it is talking to one daemon or a routed fleet."""

    def _reply(self, obj) -> None:
        self.wfile.write((json.dumps(obj) + "\n").encode())
        self.wfile.flush()

    def handle(self) -> None:
        router: Router = self.server.router
        for line in self.rfile:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
                op = msg.get("op", "predict")
                if op == "stats":
                    self._reply({"ok": True, "stats": router.stats()})
                    continue
                if op == "health":
                    h = router.health()
                    h["ok"] = True
                    self._reply(h)
                    continue
                if op == "models":
                    models = sorted({m for ep in
                                     router.fleet.endpoints()
                                     for m in ep.versions})
                    self._reply({"ok": True, "models": models})
                    continue
                if op == "metrics":
                    from ..observability import render_prometheus
                    self._reply({"ok": True, "metrics": render_prometheus(
                        gauges_cb=router._metric_gauges,
                        text_cb=router._fleet_metrics_block)})
                    continue
                if op == "trace":
                    # debug surface: the assembled cross-process
                    # waterfall by id (or the newest sampled one)
                    tid = msg.get("trace_id") or msg.get("id")
                    trace = router.trace_lookup(tid)
                    if trace is None:
                        self._reply({"ok": False,
                                     "error": "no such trace (sampled "
                                              "out, evicted, or never "
                                              "assembled)",
                                     "retained": router.assembler
                                     .ids()[-8:]})
                    else:
                        self._reply({"ok": True, "trace": trace})
                    continue
                if op == "publish":
                    out = router.publish(
                        msg["model"], msg["path"],
                        canary_pct=msg.get("canary_pct"),
                        timeout_s=float(msg.get("timeout_s", 300.0)))
                    out["ok"] = True
                    self._reply(out)
                    continue
                r = router.predict(
                    msg.get("model", "default"), msg["rows"],
                    mode=msg.get("mode", "predict"),
                    deadline_ms=msg.get("deadline_ms"),
                    trace=msg.get("trace"))
                self._reply({"ok": True, "version": r.version,
                             "replica": r.replica, "retries": r.retries,
                             "latency_ms": round(r.latency_ms, 3),
                             "trace_id": r.trace_id,
                             "preds": np.asarray(r.preds).tolist()})
            except OverloadedError as e:
                try:
                    self._reply({"ok": False, "overloaded": True,
                                 "error": str(e),
                                 "trace_id": getattr(e, "trace_id", None)})
                except OSError:
                    return
            except ShedError as e:
                try:
                    self._reply({"ok": False, "shed": True,
                                 "error": str(e),
                                 "trace_id": getattr(e, "trace_id", None)})
                except OSError:
                    return
            except TimeoutError as e:
                try:
                    self._reply({"ok": False, "timeout": True,
                                 "error": str(e),
                                 "trace_id": getattr(e, "trace_id", None)})
                except OSError:
                    return
            except Exception as e:  # noqa: BLE001 - per-line error reply
                try:
                    self._reply({"ok": False, "error": str(e),
                                 "trace_id": getattr(e, "trace_id", None)})
                except OSError:
                    return


class RouterFrontend(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def start_router_frontend(router: Router, port: int = 0,
                          host: str = "127.0.0.1") -> RouterFrontend:
    srv = RouterFrontend((host, int(port)), _RouterHandler)
    srv.router = router
    t = threading.Thread(target=srv.serve_forever,
                         name="lgbm-router-frontend", daemon=True)
    t.start()
    log.info(f"Fleet router listening on "
             f"{srv.server_address[0]}:{srv.server_address[1]}")
    return srv
