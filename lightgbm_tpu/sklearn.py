"""scikit-learn estimator API (ref: python-package/lightgbm/sklearn.py:
LGBMModel/LGBMRegressor/LGBMClassifier/LGBMRanker)."""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .basic import Booster, Dataset
from .engine import train as train_api
from .utils import log

try:
    from sklearn.base import BaseEstimator as _SKBase
    from sklearn.base import ClassifierMixin as _SKClassifier
    from sklearn.base import RegressorMixin as _SKRegressor
    _HAS_SKLEARN = True
except ImportError:  # pragma: no cover - sklearn is in the image
    _SKBase = object
    _SKClassifier = object
    _SKRegressor = object
    _HAS_SKLEARN = False


class LGBMModel(_SKBase):
    """Base estimator (ref: sklearn.py LGBMModel)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, subsample_for_bin: int = 200000,
                 objective: Optional[str] = None,
                 class_weight=None, min_split_gain: float = 0.0,
                 min_child_weight: float = 1e-3, min_child_samples: int = 20,
                 subsample: float = 1.0, subsample_freq: int = 0,
                 colsample_bytree: float = 1.0, reg_alpha: float = 0.0,
                 reg_lambda: float = 0.0, random_state=None,
                 n_jobs: Optional[int] = None, importance_type: str = "split",
                 **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._Booster: Optional[Booster] = None

    # ------------------------------------------------------------ sklearn API
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = (super().get_params(deep=deep) if _HAS_SKLEARN
                  else {k: getattr(self, k) for k in (
                      "boosting_type", "num_leaves", "max_depth",
                      "learning_rate", "n_estimators", "subsample_for_bin",
                      "objective", "class_weight", "min_split_gain",
                      "min_child_weight", "min_child_samples", "subsample",
                      "subsample_freq", "colsample_bytree", "reg_alpha",
                      "reg_lambda", "random_state", "n_jobs",
                      "importance_type")})
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for k, v in params.items():
            setattr(self, k, v)
            if k not in self.__init__.__code__.co_varnames:
                self._other_params[k] = v
        return self

    # --------------------------------------------------------------- mapping
    def _lgb_params(self) -> Dict[str, Any]:
        """Translate sklearn names to native params (ref: sklearn.py
        LGBMModel._process_params alias mapping)."""
        params = dict(
            boosting=self.boosting_type,
            num_leaves=self.num_leaves,
            max_depth=self.max_depth,
            learning_rate=self.learning_rate,
            bin_construct_sample_cnt=self.subsample_for_bin,
            min_gain_to_split=self.min_split_gain,
            min_sum_hessian_in_leaf=self.min_child_weight,
            min_data_in_leaf=self.min_child_samples,
            bagging_fraction=self.subsample,
            bagging_freq=(self.subsample_freq if self.subsample < 1.0
                          and self.subsample_freq > 0
                          else (1 if self.subsample < 1.0 else 0)),
            feature_fraction=self.colsample_bytree,
            lambda_l1=self.reg_alpha,
            lambda_l2=self.reg_lambda,
            verbosity=-1,
        )
        if self.objective is not None:
            params["objective"] = self.objective
        if self.random_state is not None:
            params["seed"] = int(self.random_state) if not hasattr(
                self.random_state, "randint") else int(
                self.random_state.randint(0, 2 ** 31))
        params.update(self._other_params)
        params.pop("n_estimators", None)
        return params

    # ------------------------------------------------------------------- fit
    def _fit(self, X, y, sample_weight=None, group=None, eval_set=None,
             eval_names=None, eval_sample_weight=None, eval_group=None,
             callbacks: Optional[List[Callable]] = None,
             categorical_feature="auto", init_score=None,
             eval_init_score=None, eval_metric=None,
             feature_name="auto") -> "LGBMModel":
        if hasattr(X, "columns"):
            self._feature_names_in = list(map(str, X.columns))
            if feature_name == "auto":
                feature_name = self._feature_names_in
        else:
            self._feature_names_in = None
        X = X.values if hasattr(X, "values") else np.asarray(X)
        y = np.asarray(y, dtype=np.float64).ravel()
        params = self._lgb_params()
        feval = None
        if eval_metric is not None:
            # ref: sklearn.py fit: string metrics merge with the params
            # metric; callables become custom feval functions
            ems = (eval_metric if isinstance(eval_metric, (list, tuple))
                   else [eval_metric])
            names = [m for m in ems if isinstance(m, str)]
            fevals = [m for m in ems if callable(m)]
            if names:
                base = params.get("metric", [])
                if isinstance(base, str):
                    base = [b for b in base.split(",") if b]
                params["metric"] = list(base) + [m for m in names
                                                 if m not in base]
            if fevals:
                if len(fevals) == 1:
                    feval = fevals[0]
                else:
                    def feval(preds, ds, _fs=tuple(fevals)):
                        out = []
                        for f in _fs:
                            r = f(preds, ds)
                            out.extend(r if isinstance(r, list) else [r])
                        return out
        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets, valid_names = [], []
        if eval_set:
            for i, (vX, vy) in enumerate(eval_set):
                vX = vX.values if hasattr(vX, "values") else np.asarray(vX)
                vw = (eval_sample_weight[i]
                      if eval_sample_weight is not None else None)
                vg = eval_group[i] if eval_group is not None else None
                vi = (eval_init_score[i]
                      if eval_init_score is not None else None)
                vy_arr = np.asarray(vy, np.float64).ravel()
                # identical ndarray OR the same view (same start address,
                # shape, and strides — shares_memory alone also matches
                # overlapping/rearranged views, which are NOT the train set)
                same_data = vX is X or (
                    vX.shape == X.shape
                    and vX.strides == X.strides
                    and vX.__array_interface__["data"][0]
                    == X.__array_interface__["data"][0])
                if (same_data and np.array_equal(vy_arr, y)
                        and vw is None and vi is None and vg is None):
                    # the eval set IS the train set (data, labels, and no
                    # overriding weight/init/group)
                    valid_sets.append(train_set)
                else:
                    valid_sets.append(Dataset(
                        vX, label=vy_arr,
                        weight=vw, group=vg, init_score=vi,
                        reference=train_set))
                valid_names.append(eval_names[i] if eval_names else
                                   f"valid_{i}")
        self._evals_result = {}
        cbs = list(callbacks or [])
        if valid_sets:
            from .callback import record_evaluation
            cbs.append(record_evaluation(self._evals_result))
        self._Booster = train_api(params, train_set,
                                  num_boost_round=self.n_estimators,
                                  valid_sets=valid_sets or None,
                                  valid_names=valid_names or None,
                                  feval=feval, callbacks=cbs or None)
        self._n_features = X.shape[1]
        self.fitted_ = True
        return self

    fit = _fit

    # --------------------------------------------------------------- predict
    def _check_fitted(self):
        if self._Booster is None:
            raise ValueError(
                "Estimator not fitted; call fit before predict")

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1,
                pred_leaf: bool = False, pred_contrib: bool = False,
                **kwargs) -> np.ndarray:
        self._check_fitted()
        return self._Booster.predict(X, raw_score=raw_score,
                                     num_iteration=num_iteration,
                                     pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib)

    # ------------------------------------------------------------ attributes
    @property
    def booster_(self) -> Booster:
        self._check_fitted()
        return self._Booster

    @property
    def n_features_(self) -> int:
        self._check_fitted()
        return self._n_features

    n_features_in_ = n_features_

    @property
    def best_iteration_(self) -> int:
        self._check_fitted()
        return self._Booster.best_iteration

    @property
    def best_score_(self):
        self._check_fitted()
        return self._Booster.best_score

    @property
    def feature_importances_(self) -> np.ndarray:
        self._check_fitted()
        return self._Booster._gbdt.feature_importance(self.importance_type)

    @property
    def evals_result_(self):
        self._check_fitted()
        return self._evals_result

    @property
    def feature_name_(self) -> List[str]:
        self._check_fitted()
        return self._Booster.feature_name()

    @property
    def feature_names_in_(self):
        """sklearn-style input feature names (pandas columns)."""
        self._check_fitted()
        if self._feature_names_in is None:
            raise AttributeError(
                "feature_names_in_ is defined only when X has column names")
        return np.asarray(self._feature_names_in, dtype=object)

    @property
    def n_estimators_(self) -> int:
        """Actual number of fitted iterations (<= n_estimators when early
        stopping fires; ref: sklearn.py n_estimators_)."""
        self._check_fitted()
        bi = self._Booster.best_iteration
        return bi if bi > 0 else self._Booster.current_iteration()

    n_iter_ = n_estimators_

    @property
    def objective_(self) -> str:
        self._check_fitted()
        return self.objective


class LGBMRegressor(_SKRegressor, LGBMModel):
    """ref: sklearn.py LGBMRegressor."""

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_init_score=None,
            eval_metric=None, callbacks=None,
            feature_name="auto", categorical_feature="auto"):
        if self.objective is None:
            self.objective = "regression"
        return self._fit(X, y, sample_weight=sample_weight,
                         init_score=init_score, eval_set=eval_set,
                         eval_names=eval_names,
                         eval_sample_weight=eval_sample_weight,
                         eval_init_score=eval_init_score,
                         eval_metric=eval_metric, callbacks=callbacks,
                         feature_name=feature_name,
                         categorical_feature=categorical_feature)


class LGBMClassifier(_SKClassifier, LGBMModel):
    """ref: sklearn.py LGBMClassifier."""

    def fit(self, X, y, sample_weight=None, init_score=None, eval_set=None,
            eval_names=None, eval_sample_weight=None, eval_init_score=None,
            eval_metric=None, callbacks=None,
            feature_name="auto", categorical_feature="auto"):
        y = np.asarray(y).ravel()
        self.classes_, y_enc = np.unique(y, return_inverse=True)
        self.n_classes_ = len(self.classes_)
        if self.objective is None:
            self.objective = ("binary" if self.n_classes_ <= 2
                              else "multiclass")
        if self.n_classes_ > 2:
            self._other_params.setdefault("num_class", self.n_classes_)
        enc_eval = None
        if eval_set:
            enc_eval = []
            lut = {c: i for i, c in enumerate(self.classes_)}
            for vX, vy in eval_set:
                vy = np.asarray([lut[v] for v in np.asarray(vy).ravel()])
                enc_eval.append((vX, vy))
        return self._fit(X, y_enc.astype(np.float64),
                         sample_weight=sample_weight,
                         init_score=init_score, eval_set=enc_eval,
                         eval_names=eval_names,
                         eval_sample_weight=eval_sample_weight,
                         eval_init_score=eval_init_score,
                         eval_metric=eval_metric, callbacks=callbacks,
                         feature_name=feature_name,
                         categorical_feature=categorical_feature)

    def predict_proba(self, X, raw_score: bool = False,
                      num_iteration: int = -1, **kwargs) -> np.ndarray:
        self._check_fitted()
        result = self._Booster.predict(X, raw_score=raw_score,
                                       num_iteration=num_iteration)
        if result.ndim == 1:  # binary: [P(y=0), P(y=1)]
            return np.vstack([1.0 - result, result]).T
        return result

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1,
                pred_leaf: bool = False, pred_contrib: bool = False,
                **kwargs) -> np.ndarray:
        if raw_score or pred_leaf or pred_contrib:
            return super().predict(X, raw_score=raw_score,
                                   num_iteration=num_iteration,
                                   pred_leaf=pred_leaf,
                                   pred_contrib=pred_contrib)
        proba = self.predict_proba(X, num_iteration=num_iteration)
        return self.classes_[np.argmax(proba, axis=1)]


class LGBMRanker(LGBMModel):
    """ref: sklearn.py LGBMRanker (lambdarank)."""

    def fit(self, X, y, group, sample_weight=None, init_score=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            eval_at=(1, 2, 3, 4, 5), callbacks=None,
            feature_name="auto", categorical_feature="auto"):
        if self.objective is None:
            self.objective = "lambdarank"
        self._other_params.setdefault(
            "eval_at", ",".join(str(a) for a in eval_at))
        return self._fit(X, y, sample_weight=sample_weight, group=group,
                         init_score=init_score, eval_set=eval_set,
                         eval_names=eval_names,
                         eval_sample_weight=eval_sample_weight,
                         eval_init_score=eval_init_score,
                         eval_group=eval_group, eval_metric=eval_metric,
                         callbacks=callbacks, feature_name=feature_name,
                         categorical_feature=categorical_feature)
