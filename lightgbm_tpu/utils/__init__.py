from . import log
from .log import LightGBMError
from .timer import Timer, global_timer

__all__ = ["log", "LightGBMError", "Timer", "global_timer"]
