from . import log
from .atomic import atomic_write_bytes, atomic_write_text
from .log import LightGBMError
from .timer import Timer, global_timer

__all__ = ["log", "LightGBMError", "Timer", "global_timer",
           "atomic_write_text", "atomic_write_bytes"]
