"""Atomic file writes: sibling temp file + os.replace.

A crash (or injected fault) anywhere before the final replace leaves the
destination untouched — readers only ever see the old complete file or
the new complete file, never a truncated one.  This is the host-side
analogue of the reference engine writing model files whole."""

from __future__ import annotations

import os
import tempfile


def atomic_write_bytes(path: str, payload: bytes) -> None:
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))
