"""Leveled logger mirroring the reference's Log (ref: include/LightGBM/utils/log.h:78-135).

Fatal raises, Warning/Info/Debug print with level gating, and an optional callback can
redirect output (ref: c_api.h LGBM_RegisterLogCallback).
"""

from __future__ import annotations

import sys
from typing import Callable, Optional


class LightGBMError(RuntimeError):
    """Raised by Log.fatal (ref: utils/log.h Fatal -> std::runtime_error)."""


class _LogState:
    # -1: fatal only, 0: +warning, 1: +info, 2+: +debug (ref: config.h `verbosity`)
    level: int = 1
    callback: Optional[Callable[[str], None]] = None


def set_verbosity(level: int) -> None:
    _LogState.level = level


def get_verbosity() -> int:
    return _LogState.level


def register_callback(callback: Optional[Callable[[str], None]]) -> None:
    _LogState.callback = callback


def register_logger(logger=None, info_method_name: str = "info",
                    warning_method_name: str = "warning") -> None:
    """Route info/warning output through a custom logger object
    (ref: python-package basic.py register_logger).  Passing None
    unregisters the current logger and restores stderr output."""
    if logger is None:
        _LogState.logger = None
        return
    for m in (info_method_name, warning_method_name):
        if not callable(getattr(logger, m, None)):
            raise TypeError(f"Logger must provide '{info_method_name}' and "
                            f"'{warning_method_name}' method")
    _LogState.logger = logger
    _LogState.logger_info = info_method_name
    _LogState.logger_warning = warning_method_name


def reset() -> None:
    """Restore default logging state (stderr sink, verbosity 1, no
    callback/logger) — test runs use this so one test's redirection
    cannot leak into the next."""
    _LogState.level = 1
    _LogState.callback = None
    _LogState.logger = None


def _emit(msg: str, warning: bool = False) -> None:
    logger = getattr(_LogState, "logger", None)
    if logger is not None:
        method = getattr(_LogState, "logger_warning" if warning
                         else "logger_info")
        getattr(logger, method)(msg)
    elif _LogState.callback is not None:
        _LogState.callback(msg + "\n")
    else:
        print(msg, file=sys.stderr, flush=True)


def debug(msg: str, *args) -> None:
    if _LogState.level >= 2:
        _emit("[LightGBM-TPU] [Debug] " + (msg % args if args else msg))


def info(msg: str, *args) -> None:
    if _LogState.level >= 1:
        _emit("[LightGBM-TPU] [Info] " + (msg % args if args else msg))


def warning(msg: str, *args) -> None:
    if _LogState.level >= 0:
        _emit("[LightGBM-TPU] [Warning] " + (msg % args if args else msg),
              warning=True)


def fatal(msg: str, *args) -> None:
    raise LightGBMError(msg % args if args else msg)
