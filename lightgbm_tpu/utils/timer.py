"""Named timing scopes aggregated in a global timer.

TPU-native analogue of the reference's TIMETAG instrumentation
(ref: include/LightGBM/utils/common.h:973-1010 Timer/FunctionTimer,
instantiated as `global_timer` in src/boosting/gbdt.cpp:22 and printed at
process exit).  Enabled by the LIGHTGBM_TPU_TIMETAG env var (the
reference's compile-time flag becomes a runtime switch); scopes can also
emit jax.profiler TraceAnnotations — driven by the LIGHTGBM_TPU_TRACE
env var or `set_trace_annotations(True)` — so device timelines in a
profiler carry the same names.

Two scope flavours (docs/Observability.md):

* `scope(name)` — host-side phases (gradients, grow dispatch, finalize,
  eval, checkpoint I/O).  Wall-clock accumulates per call.  Because jax
  dispatch is asynchronous, callers of device work should `block()` the
  phase's outputs inside the scope so the phase is charged for the work
  it dispatched — `block()` is a no-op when timing is off, so the hot
  path stays fully pipelined in production.
* `device_scope(name)` — for code INSIDE jitted programs (histogram
  build, split find, partition, collectives).  It wraps the traced ops
  in `jax.named_scope`, so the phase name survives into the compiled
  XLA program and shows up on profiler timelines; the host-side
  accumulation only measures trace time (once per compile).

Device-time attribution: `block(x)` inside a scope additionally credits
the settle wait to a separate `<scope>::device` entry, so a phase
breakdown separates HOST dispatch time from DEVICE execution time — the
serving bench and `timer_top_ms` read both.  The scope stack is
thread-local (the serving coalescer times dispatches concurrently with
the main thread); accumulator updates take a lock only when timing is
enabled, so the production hot path is untouched.
"""

from __future__ import annotations

import atexit
import functools
import os
import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Tuple


class Timer:
    """Aggregates wall-clock per named scope (ref: common.h:973 Timer)."""

    def __init__(self, enabled: bool = False,
                 use_jax_profiler: bool = None):
        self.enabled = enabled
        self._acc: Dict[str, float] = defaultdict(float)
        self._cnt: Dict[str, int] = defaultdict(int)
        self._alock = threading.Lock()
        self._tls = threading.local()
        if use_jax_profiler is None:
            use_jax_profiler = bool(os.environ.get("LIGHTGBM_TPU_TRACE", ""))
        self._use_jax_profiler = use_jax_profiler

    def _scope_stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # ------------------------------------------------------- profiler wiring
    def set_trace_annotations(self, on: bool) -> None:
        """Toggle jax.profiler.TraceAnnotation emission from scopes (the
        runtime form of the LIGHTGBM_TPU_TRACE env switch)."""
        self._use_jax_profiler = bool(on)

    def trace_annotations_enabled(self) -> bool:
        return self._use_jax_profiler

    # ---------------------------------------------------------------- scopes
    @contextmanager
    def scope(self, name: str):
        """RAII scope (ref: common.h:1000 FunctionTimer)."""
        use_trace = self._use_jax_profiler
        if not self.enabled and not use_trace:
            yield
            return
        ctx = None
        if use_trace:
            import jax.profiler
            ctx = jax.profiler.TraceAnnotation(name)
            ctx.__enter__()
        stack = self._scope_stack()
        stack.append(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            stack.pop()
            if ctx is not None:
                ctx.__exit__(None, None, None)
            if self.enabled:
                dt = time.perf_counter() - t0
                with self._alock:
                    self._acc[name] += dt
                    self._cnt[name] += 1

    @contextmanager
    def device_scope(self, name: str):
        """Scope for code traced INSIDE a jitted program: tags the traced
        ops with jax.named_scope so the phase name reaches the XLA program
        (and profiler device timelines); host accumulation sees trace time
        only (once per compile), not per-call device time."""
        import jax
        with jax.named_scope(name.replace("::", ".")):
            with self.scope(name):
                yield

    def block(self, x):
        """block_until_ready(x) when timing is on, so the enclosing scope
        is charged for the device work it dispatched (async dispatch
        otherwise bills whichever later phase syncs first).  Identity
        when timing is off — production dispatch stays pipelined.

        The settle wait is ALSO credited to `<enclosing scope>::device`:
        the enclosing scope's total is unchanged (dispatch + settle, as
        before), and the ::device entry says how much of it the chip
        owned — per-phase DEVICE time attribution with no call-site
        changes."""
        if not self.enabled or x is None:
            return x
        t0 = time.perf_counter()
        try:
            import jax
            x = jax.block_until_ready(x)
        except Exception:
            return x
        stack = self._scope_stack()
        if stack:
            dt = time.perf_counter() - t0
            with self._alock:
                self._acc[stack[-1] + "::device"] += dt
                self._cnt[stack[-1] + "::device"] += 1
        return x

    def timeit(self, name: str):
        """Decorator form."""
        def deco(fn):
            @functools.wraps(fn)
            def wrapped(*a, **k):
                with self.scope(name):
                    return fn(*a, **k)
            return wrapped
        return deco

    # --------------------------------------------------------------- results
    def items(self) -> Tuple[Tuple[str, float, int], ...]:
        with self._alock:
            acc = dict(self._acc)
            cnt = dict(self._cnt)
        return tuple((k, acc[k], cnt[k])
                     for k in sorted(acc, key=acc.get, reverse=True))

    def snapshot(self) -> Dict[str, Tuple[float, int]]:
        """Point-in-time copy {name: (seconds, calls)} — per-iteration
        phase breakdowns diff two snapshots (observability/events)."""
        with self._alock:
            return {k: (self._acc[k], self._cnt[k]) for k in self._acc}

    def reset(self) -> None:
        with self._alock:
            self._acc.clear()
            self._cnt.clear()

    def print(self) -> None:
        """ref: Timer::Print at process exit."""
        if not self._acc:
            return
        from . import log
        log.info("LightGBM-TPU timers:")
        for name, sec, cnt in self.items():
            log.info(f"  {name}: {sec * 1000:.3f} ms ({cnt} calls)")


global_timer = Timer(
    enabled=bool(os.environ.get("LIGHTGBM_TPU_TIMETAG", "")))
if global_timer.enabled:
    atexit.register(global_timer.print)
