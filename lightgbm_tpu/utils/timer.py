"""Named timing scopes aggregated in a global timer.

TPU-native analogue of the reference's TIMETAG instrumentation
(ref: include/LightGBM/utils/common.h:973-1010 Timer/FunctionTimer,
instantiated as `global_timer` in src/boosting/gbdt.cpp:22 and printed at
process exit).  Enabled by the LIGHTGBM_TPU_TIMETAG env var (the
reference's compile-time flag becomes a runtime switch); scopes can also
emit jax.profiler TraceAnnotations — driven by the LIGHTGBM_TPU_TRACE
env var or `set_trace_annotations(True)` — so device timelines in a
profiler carry the same names.

Two scope flavours (docs/Observability.md):

* `scope(name)` — host-side phases (gradients, grow dispatch, finalize,
  eval, checkpoint I/O).  Wall-clock accumulates per call.  Because jax
  dispatch is asynchronous, callers of device work should `block()` the
  phase's outputs inside the scope so the phase is charged for the work
  it dispatched — `block()` is a no-op when timing is off, so the hot
  path stays fully pipelined in production.
* `device_scope(name)` — for code INSIDE jitted programs (histogram
  build, split find, partition, collectives).  It wraps the traced ops
  in `jax.named_scope`, so the phase name survives into the compiled
  XLA program and shows up on profiler timelines; the host-side
  accumulation only measures trace time (once per compile).
"""

from __future__ import annotations

import atexit
import functools
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Tuple


class Timer:
    """Aggregates wall-clock per named scope (ref: common.h:973 Timer)."""

    def __init__(self, enabled: bool = False,
                 use_jax_profiler: bool = None):
        self.enabled = enabled
        self._acc: Dict[str, float] = defaultdict(float)
        self._cnt: Dict[str, int] = defaultdict(int)
        if use_jax_profiler is None:
            use_jax_profiler = bool(os.environ.get("LIGHTGBM_TPU_TRACE", ""))
        self._use_jax_profiler = use_jax_profiler

    # ------------------------------------------------------- profiler wiring
    def set_trace_annotations(self, on: bool) -> None:
        """Toggle jax.profiler.TraceAnnotation emission from scopes (the
        runtime form of the LIGHTGBM_TPU_TRACE env switch)."""
        self._use_jax_profiler = bool(on)

    def trace_annotations_enabled(self) -> bool:
        return self._use_jax_profiler

    # ---------------------------------------------------------------- scopes
    @contextmanager
    def scope(self, name: str):
        """RAII scope (ref: common.h:1000 FunctionTimer)."""
        use_trace = self._use_jax_profiler
        if not self.enabled and not use_trace:
            yield
            return
        ctx = None
        if use_trace:
            import jax.profiler
            ctx = jax.profiler.TraceAnnotation(name)
            ctx.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
            if self.enabled:
                self._acc[name] += time.perf_counter() - t0
                self._cnt[name] += 1

    @contextmanager
    def device_scope(self, name: str):
        """Scope for code traced INSIDE a jitted program: tags the traced
        ops with jax.named_scope so the phase name reaches the XLA program
        (and profiler device timelines); host accumulation sees trace time
        only (once per compile), not per-call device time."""
        import jax
        with jax.named_scope(name.replace("::", ".")):
            with self.scope(name):
                yield

    def block(self, x):
        """block_until_ready(x) when timing is on, so the enclosing scope
        is charged for the device work it dispatched (async dispatch
        otherwise bills whichever later phase syncs first).  Identity
        when timing is off — production dispatch stays pipelined."""
        if not self.enabled or x is None:
            return x
        try:
            import jax
            return jax.block_until_ready(x)
        except Exception:
            return x

    def timeit(self, name: str):
        """Decorator form."""
        def deco(fn):
            @functools.wraps(fn)
            def wrapped(*a, **k):
                with self.scope(name):
                    return fn(*a, **k)
            return wrapped
        return deco

    # --------------------------------------------------------------- results
    def items(self) -> Tuple[Tuple[str, float, int], ...]:
        return tuple((k, self._acc[k], self._cnt[k])
                     for k in sorted(self._acc, key=self._acc.get,
                                     reverse=True))

    def snapshot(self) -> Dict[str, Tuple[float, int]]:
        """Point-in-time copy {name: (seconds, calls)} — per-iteration
        phase breakdowns diff two snapshots (observability/events)."""
        return {k: (self._acc[k], self._cnt[k]) for k in self._acc}

    def reset(self) -> None:
        self._acc.clear()
        self._cnt.clear()

    def print(self) -> None:
        """ref: Timer::Print at process exit."""
        if not self._acc:
            return
        from . import log
        log.info("LightGBM-TPU timers:")
        for name, sec, cnt in self.items():
            log.info(f"  {name}: {sec * 1000:.3f} ms ({cnt} calls)")


global_timer = Timer(
    enabled=bool(os.environ.get("LIGHTGBM_TPU_TIMETAG", "")))
if global_timer.enabled:
    atexit.register(global_timer.print)
