"""Named timing scopes aggregated in a global timer.

TPU-native analogue of the reference's TIMETAG instrumentation
(ref: include/LightGBM/utils/common.h:973-1010 Timer/FunctionTimer,
instantiated as `global_timer` in src/boosting/gbdt.cpp:22 and printed at
process exit).  Enabled by the LIGHTGBM_TPU_TIMETAG env var (the
reference's compile-time flag becomes a runtime switch); scopes can also
emit jax.profiler TraceAnnotations so device timelines in a profiler
carry the same names.
"""

from __future__ import annotations

import atexit
import os
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Tuple


class Timer:
    """Aggregates wall-clock per named scope (ref: common.h:973 Timer)."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._acc: Dict[str, float] = defaultdict(float)
        self._cnt: Dict[str, int] = defaultdict(int)
        self._use_jax_profiler = False

    @contextmanager
    def scope(self, name: str):
        """RAII scope (ref: common.h:1000 FunctionTimer)."""
        if not self.enabled:
            yield
            return
        if self._use_jax_profiler:
            import jax.profiler
            ctx = jax.profiler.TraceAnnotation(name)
        else:
            ctx = None
        t0 = time.perf_counter()
        if ctx is not None:
            ctx.__enter__()
        try:
            yield
        finally:
            if ctx is not None:
                ctx.__exit__(None, None, None)
            self._acc[name] += time.perf_counter() - t0
            self._cnt[name] += 1

    def timeit(self, name: str):
        """Decorator form."""
        def deco(fn):
            def wrapped(*a, **k):
                with self.scope(name):
                    return fn(*a, **k)
            return wrapped
        return deco

    def items(self) -> Tuple[Tuple[str, float, int], ...]:
        return tuple((k, self._acc[k], self._cnt[k])
                     for k in sorted(self._acc, key=self._acc.get,
                                     reverse=True))

    def reset(self) -> None:
        self._acc.clear()
        self._cnt.clear()

    def print(self) -> None:
        """ref: Timer::Print at process exit."""
        if not self._acc:
            return
        from . import log
        log.info("LightGBM-TPU timers:")
        for name, sec, cnt in self.items():
            log.info(f"  {name}: {sec * 1000:.3f} ms ({cnt} calls)")


global_timer = Timer(
    enabled=bool(os.environ.get("LIGHTGBM_TPU_TIMETAG", "")))
if global_timer.enabled:
    atexit.register(global_timer.print)
