"""Test harness: run JAX on a virtual 8-device CPU mesh so sharding/collective code
paths are exercised without TPU hardware (multi-chip dry-run model)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
