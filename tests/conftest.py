"""Test harness: run JAX on a virtual 8-device CPU mesh so sharding/collective code
paths are exercised without TPU hardware (multi-chip dry-run model)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# the axon TPU plugin ignores JAX_PLATFORMS; force the CPU backend explicitly
# so tests are fast (no tunnel round-trips) and deterministic
jax.config.update("jax_platforms", "cpu")

# persistent XLA compilation cache: grow_tree compiles (~20-60s each on CPU)
# are reused across pytest runs
jax.config.update("jax_compilation_cache_dir", "/tmp/lgbm_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
