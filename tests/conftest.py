"""Test harness: run JAX on a virtual 8-device CPU mesh so sharding/collective code
paths are exercised without TPU hardware (multi-chip dry-run model)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# the axon TPU plugin ignores JAX_PLATFORMS; force the CPU backend explicitly
# so tests are fast (no tunnel round-trips) and deterministic
jax.config.update("jax_platforms", "cpu")

# NO persistent XLA compilation cache: this environment has two Python
# installs with different jaxlib builds, and the venv build SIGSEGVs both
# when LOADING cache entries written by the other build
# (backend_compile_and_load; the cpu_aot_loader machine-feature warnings
# are the precursor) and when WRITING sharded pjit executables
# (put_executable_and_time).  Cold compiles cost a few extra minutes per
# run; a segfaulting test gate costs a round.


import pytest


@pytest.hookimpl(trylast=True)
def pytest_unconfigure(config):
    """The remote-TPU (axon) plugin can segfault during interpreter
    teardown AFTER every test finished and the summary printed, flipping
    pytest's exit code to 139.  Exit with the real status instead of
    running interpreter shutdown."""
    import atexit
    import os
    import sys
    status = getattr(config, "_lgbt_exitstatus", None)
    if status is None or os.environ.get("LGBT_KEEP_TEARDOWN") == "1":
        # no session ran (usage/startup error) or explicitly opted out:
        # keep normal teardown so pytest's own exit code is preserved
        return
    try:
        atexit._run_exitfuncs()  # coverage/profiler finalizers still run
    except Exception:
        pass
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(int(status))


def pytest_sessionfinish(session, exitstatus):
    session.config._lgbt_exitstatus = exitstatus


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """The venv jaxlib segfaults inside backend_compile_and_load once a
    long-lived process has accumulated a few hundred compiled executables
    (LLVM JIT lifetime state); clearing the jit caches between test
    modules keeps the process below the threshold.  Costs recompiles for
    configs shared across modules, which are rare."""
    yield
    jax.clear_caches()
