"""Stub replica for the fleet tests: speaks the serving wire protocol
(predict/health/publish/metrics) with a deterministic linear "model",
no jax, no lightgbm — so ReplicaFleet/Router supervision, routing,
retry, shed, and canary logic get exercised against REAL processes and
REAL sockets in milliseconds instead of daemon-startup seconds.

Prediction contract: `preds[i] = sum(rows[i]) * scale`, where `scale`
comes from the published model path — a path containing `scale<k>`
serves with scale k (default 1).  `version` increments per publish,
mirroring the real registry.  Env knobs:

  STUB_READY_FILE  — ready-file path (written after bind, like the CLI)
  STUB_WARMUP_S    — delay before health reports ready (default 0)
  STUB_SHED        — 1: every predict answers a structured shed
  STUB_SHED_HEALTH — 1: health probes ADVERTISE shedding (the
                     admission-controller path; independent of
                     STUB_SHED so retry-on-shed and reject-on-probe
                     are testable separately)
  STUB_CRASH_AFTER — os._exit(17) when request N arrives
  STUB_SLOW_MS     — per-predict latency injection
  STUB_SCALE       — initial model scale (default 1)

SIGTERM exits 143 (the drained-daemon contract the fleet gate checks).
"""

import json
import os
import re
import signal
import socketserver
import sys
import threading
import time


def main() -> int:
    state = {
        "version": 1,
        "scale": float(os.environ.get("STUB_SCALE", "1")),
        "requests": 0,
        "ready_at": time.monotonic() + float(
            os.environ.get("STUB_WARMUP_S", "0")),
        "model": os.environ.get("STUB_MODEL", "m"),
    }
    lock = threading.Lock()
    crash_after = int(os.environ.get("STUB_CRASH_AFTER", "0"))
    slow_ms = float(os.environ.get("STUB_SLOW_MS", "0"))

    class Handler(socketserver.StreamRequestHandler):
        def _reply(self, obj):
            self.wfile.write((json.dumps(obj) + "\n").encode())
            self.wfile.flush()

        def handle(self):
            for line in self.rfile:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                    op = msg.get("op", "predict")
                    if op == "health":
                        with lock:
                            ready = time.monotonic() >= state["ready_at"]
                            self._reply({
                                "ok": True, "ready": ready,
                                "models": {state["model"]:
                                           state["version"]},
                                "pending": 0,
                                "shedding": os.environ.get(
                                    "STUB_SHED_HEALTH") == "1",
                                "pid": os.getpid()})
                        continue
                    if op == "publish":
                        m = re.search(r"scale(\d+)", str(msg["path"]))
                        with lock:
                            state["scale"] = float(m.group(1)) if m else 1.0
                            state["version"] += 1
                            self._reply({"ok": True,
                                         "version": state["version"]})
                        continue
                    if op == "stats":
                        with lock:
                            self._reply({"ok": True, "stats":
                                         {"requests": state["requests"]}})
                        continue
                    if op == "metrics":
                        with lock:
                            n_req = state["requests"]
                        self._reply({"ok": True, "metrics": (
                            "# TYPE lgbm_serve_requests counter\n"
                            f"lgbm_serve_requests {n_req}\n"
                            "# TYPE lgbm_serve_latency_ms gauge\n"
                            'lgbm_serve_latency_ms{quantile="0.5"} 0.1\n'
                            'lgbm_serve_latency_ms{quantile="0.99"} 0.2\n')})
                        continue
                    # predict: echo the trace context like a real
                    # replica — trace_id on every reply (errors too),
                    # one "serve" span back when the context is sampled
                    trace = msg.get("trace") or {}
                    trace_id = trace.get("id")
                    with lock:
                        state["requests"] += 1
                        n = state["requests"]
                        scale = state["scale"]
                        version = state["version"]
                    if crash_after and n >= crash_after:
                        os._exit(17)
                    if os.environ.get("STUB_SHED") == "1":
                        self._reply({"ok": False, "shed": True,
                                     "error": "stub shed", "pending": 0,
                                     "trace_id": trace_id})
                        continue
                    if slow_ms:
                        time.sleep(slow_ms / 1000.0)
                    preds = [sum(r) * scale for r in msg["rows"]]
                    reply = {"ok": True, "version": version,
                             "latency_ms": 0.1, "preds": preds}
                    if trace_id is not None:
                        reply["trace_id"] = trace_id
                        if trace.get("sampled"):
                            reply["spans"] = [{
                                "trace_id": trace_id,
                                "span_id": os.urandom(4).hex(),
                                "parent_id": trace.get("span"),
                                "name": "serve", "ts": time.time(),
                                "dur_ms": 0.1, "pid": os.getpid()}]
                    self._reply(reply)
                except Exception as e:  # noqa: BLE001 - per-line reply
                    try:
                        self._reply({"ok": False, "error": str(e)})
                    except OSError:
                        return

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    signal.signal(signal.SIGTERM, lambda *_: os._exit(143))
    srv = Server(("127.0.0.1", 0), Handler)
    ready_file = os.environ.get("STUB_READY_FILE")
    if ready_file:
        tmp = ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"port": srv.server_address[1],
                       "pid": os.getpid()}, f)
        os.replace(tmp, ready_file)
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
