"""Public API surface parity with the reference python package
(ref: python-package/lightgbm/__init__.py __all__): CVBooster, Sequence,
register_logger."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=600, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 4)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0.75).astype(np.float32)
    return X, y


def test_reference_exports_present():
    """Everything the reference exports (minus the Dask estimators —
    dask is not in this runtime) exists here."""
    for name in ["Dataset", "Booster", "CVBooster", "Sequence",
                 "register_logger", "train", "cv", "LGBMModel",
                 "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
                 "log_evaluation", "record_evaluation", "reset_parameter",
                 "early_stopping", "EarlyStopException", "plot_importance",
                 "plot_split_value_histogram", "plot_metric", "plot_tree",
                 "create_tree_digraph"]:
        assert hasattr(lgb, name), name


def test_cvbooster_delegation_and_roundtrip(tmp_path):
    X, y = _data()
    res = lgb.cv({"objective": "binary", "verbosity": -1, "num_leaves": 7,
                  "min_data_in_leaf": 5},
                 lgb.Dataset(X, label=y), num_boost_round=4, nfold=3,
                 return_cvbooster=True)
    cvb = res["cvbooster"]
    assert isinstance(cvb, lgb.CVBooster)
    assert len(cvb.boosters) == 3
    # method redirection returns one result per fold
    preds = cvb.predict(X)
    assert len(preds) == 3 and all(p.shape == (len(X),) for p in preds)
    # JSON round trip
    f = tmp_path / "cvb.json"
    cvb.save_model(str(f))
    cvb2 = lgb.CVBooster(model_file=str(f))
    assert len(cvb2.boosters) == 3
    for p1, p2 in zip(preds, cvb2.predict(X)):
        np.testing.assert_allclose(p1, p2, rtol=1e-6)
    # pickling
    import pickle
    cvb3 = pickle.loads(pickle.dumps(cvb))
    for p1, p3 in zip(preds, cvb3.predict(X)):
        np.testing.assert_allclose(p1, p3, rtol=1e-6)


def test_sequence_dataset_construction():
    X, y = _data(n=500)

    class ArrSeq(lgb.Sequence):
        batch_size = 128

        def __init__(self, arr):
            self.arr = arr

        def __getitem__(self, idx):
            return self.arr[idx]

        def __len__(self):
            return len(self.arr)

    params = {"objective": "binary", "verbosity": -1, "num_leaves": 7,
              "min_data_in_leaf": 5}
    b_seq = lgb.train(params, lgb.Dataset(ArrSeq(X), label=y),
                      num_boost_round=3)
    b_arr = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    np.testing.assert_allclose(b_seq.predict(X), b_arr.predict(X),
                               rtol=1e-6)
    # list of sequences concatenates row-wise
    half = len(X) // 2
    b_two = lgb.train(params, lgb.Dataset([ArrSeq(X[:half]),
                                           ArrSeq(X[half:])], label=y),
                      num_boost_round=3)
    np.testing.assert_allclose(b_two.predict(X), b_arr.predict(X),
                               rtol=1e-6)


def test_register_logger_routes_messages():
    records = []

    class MyLogger:
        def info(self, msg):
            records.append(("info", msg))

        def warning(self, msg):
            records.append(("warning", msg))

    from lightgbm_tpu.utils import log as _log
    lgb.register_logger(MyLogger())
    old_level = _log.get_verbosity()
    _log.set_verbosity(1)
    try:
        _log.info("hello %d", 7)
        _log.warning("watch out")
        assert ("info", "[LightGBM-TPU] [Info] hello 7") in records
        assert ("warning", "[LightGBM-TPU] [Warning] watch out") in records
        with pytest.raises(TypeError):
            lgb.register_logger(object())
    finally:
        _log._LogState.logger = None
        _log.set_verbosity(old_level)


def test_dataset_field_accessors():
    X, y = _data(n=300)
    w = np.ones(len(y), np.float32)
    ds = lgb.Dataset(X, label=y, weight=w, free_raw_data=False)
    ds.set_field("init_score", np.zeros(len(y)))
    ds.construct()
    np.testing.assert_allclose(ds.get_field("label"), y)
    np.testing.assert_allclose(ds.get_field("weight"), w)
    assert ds.get_field("init_score") is not None
    assert ds.get_data() is X
    assert ds.get_feature_name() == ds.feature_names()
    assert ds.feature_num_bin(0) > 1
    chain = ds.create_valid(X, label=y).get_ref_chain()
    assert ds in chain


def test_dataset_add_features_from():
    X, y = _data(n=400)
    d1 = lgb.Dataset(X[:, :2], label=y, free_raw_data=False)
    d2 = lgb.Dataset(X[:, 2:], label=y, free_raw_data=False)
    d1.add_features_from(d2)
    assert d1.num_feature() == 4
    b = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7,
                   "min_data_in_leaf": 5}, d1, num_boost_round=3)
    b_full = lgb.train({"objective": "binary", "verbosity": -1,
                        "num_leaves": 7, "min_data_in_leaf": 5},
                       lgb.Dataset(X, label=y), num_boost_round=3)
    np.testing.assert_allclose(b.predict(X), b_full.predict(X), rtol=1e-6)


def test_booster_leaf_output_and_split_histogram():
    X, y = _data(n=500)
    b = lgb.train({"objective": "binary", "verbosity": -1, "num_leaves": 7,
                   "min_data_in_leaf": 5}, lgb.Dataset(X, label=y),
                  num_boost_round=3)
    v = b.get_leaf_output(0, 0)
    assert np.isfinite(v)
    b.set_leaf_output(0, 0, v + 1.0)
    assert b.get_leaf_output(0, 0) == v + 1.0
    b.set_leaf_output(0, 0, v)
    hist, edges = b.get_split_value_histogram(0)
    assert hist.sum() > 0 and len(edges) == len(hist) + 1
    xgb = b.get_split_value_histogram(0, bins=5, xgboost_style=True)
    assert xgb.ndim == 2 and (xgb[:, 1] > 0).all()
    # network shims
    b.set_network(["host:1"], num_machines=2)
    b.free_network()


def test_get_data_subset_and_freed():
    X, y = _data(n=200)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    sub = ds.subset([3, 5, 7])
    np.testing.assert_allclose(sub.get_data(), X[[3, 5, 7]])
    # default free_raw_data=True frees after construct -> get_data raises
    ds2 = lgb.Dataset(X, label=y)
    ds2.construct()
    with pytest.raises(lgb.LightGBMError, match="free_raw_data=False"):
        ds2.get_data()


def test_ref_chain_cycle_terminates():
    X, y = _data(n=100)
    a = lgb.Dataset(X, label=y)
    b_ds = lgb.Dataset(X, label=y)
    a.reference = b_ds
    b_ds.reference = a
    chain = a.get_ref_chain()
    assert chain == {a, b_ds}


def test_subset_mutators_rejected():
    X, y = _data(n=200)
    ds = lgb.Dataset(X, label=y, free_raw_data=False)
    sub = ds.subset([1, 2, 3])
    with pytest.raises(lgb.LightGBMError, match="subset"):
        sub.set_categorical_feature([0])
    with pytest.raises(lgb.LightGBMError, match="subset"):
        sub.add_features_from(lgb.Dataset(X[:3], free_raw_data=False))


def test_sequence_two_round_streams_without_materializing():
    """Sequence + two_round streams batches twice instead of
    concatenating one big matrix (the LGBM_DatasetPushRows streaming
    ingestion role, c_api.h:177-323): the trained model must equal the
    materialized path's, and the concatenated matrix must never be
    built."""
    import lightgbm_tpu.basic as basic

    X, y = _data(n=5000)

    class ArrSeq(lgb.Sequence):
        batch_size = 512

        def __init__(self, arr):
            self.arr = arr
            self.reads = 0

        def __getitem__(self, idx):
            self.reads += 1
            return self.arr[idx]

        def __len__(self):
            return len(self.arr)

    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    seq = ArrSeq(X)
    calls = {"n": 0}
    orig = basic._materialize_sequences

    def counting(seqs):
        calls["n"] += 1
        return orig(seqs)
    basic._materialize_sequences = counting
    try:
        b_stream = lgb.train(params, lgb.Dataset(
            seq, label=y, params={"two_round": True}), num_boost_round=4)
        assert calls["n"] == 0          # never materialized
        assert seq.reads >= 2 * (5000 // 512)  # two streaming passes
        b_mat = lgb.train(params, lgb.Dataset(ArrSeq(X), label=y),
                          num_boost_round=4)
        assert calls["n"] == 1          # default path still materializes
    finally:
        basic._materialize_sequences = orig
    t_s = b_stream.model_to_string().split("\nparameters:")[0]
    t_m = b_mat.model_to_string().split("\nparameters:")[0]
    assert t_s == t_m
