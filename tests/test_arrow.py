"""Arrow ingestion (ref: include/LightGBM/arrow.h;
LGBM_DatasetCreateFromArrow c_api.h:214; tests/python_package_test/
test_arrow.py)."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import lightgbm_tpu as lgb


def test_dataset_from_arrow_table():
    rng = np.random.RandomState(2)
    X = rng.randn(1200, 3)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    table = pa.table({f"f{i}": X[:, i] for i in range(3)})
    ds = lgb.Dataset(table, label=y)
    b = lgb.train({"objective": "binary", "num_leaves": 15,
                   "verbosity": -1}, ds, num_boost_round=10)
    acc = float(np.mean((b.predict(X) > 0.5) == (y > 0.5)))
    assert acc > 0.9, acc
    assert ds.feature_names() == ["f0", "f1", "f2"]


def test_arrow_matches_numpy_training():
    rng = np.random.RandomState(3)
    X = rng.randn(800, 4)
    y = X[:, 0] * 2 + 0.1 * rng.randn(800)
    table = pa.table({f"c{i}": X[:, i] for i in range(4)})
    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1}
    b_arrow = lgb.train(params, lgb.Dataset(table, label=y),
                        num_boost_round=5)
    b_np = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    np.testing.assert_allclose(b_arrow.predict(X), b_np.predict(X),
                               rtol=1e-6)
