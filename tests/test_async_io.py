"""Async host services + persistent compile cache (ISSUE 5).

The contract under test: `async_host_io` (default ON) moves event-log
appends and checkpoint serialization to a bounded single-worker thread
WITHOUT changing a single byte of output — models, checkpoint files and
eval histories are identical with the writer on and off, including under
an injected checkpoint-write fault.  The compile-cache test pins that a
second process of the same config reports persistent-cache hits.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.callback import record_evaluation
from lightgbm_tpu.observability import AsyncWriter, global_registry
from lightgbm_tpu.reliability import faults
from lightgbm_tpu.reliability.checkpoint import CheckpointManager

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _data(n=500, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 5)
    y = (X[:, 0] + 0.3 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def _strip_io_params(text):
    """Model text embeds changed params; the async knob itself is the
    one legitimate difference between the two runs."""
    return "\n".join(l for l in text.splitlines()
                     if "async_host_io" not in l)


def _run(tmp_path, tag, async_io, fault=None, rounds=6):
    X, y = _data()
    Xv, yv = _data(seed=1)
    ck = str(tmp_path / f"ck_{tag}")
    ev = str(tmp_path / f"ev_{tag}")
    hist = {}
    global_registry.reset()
    if fault:
        os.environ["LGBM_TPU_FAULT"] = fault
    else:
        os.environ.pop("LGBM_TPU_FAULT", None)
    faults.reload()
    try:
        b = lgb.train({"objective": "binary", "num_leaves": 7,
                       "verbosity": -1, "metric": "binary_logloss",
                       "is_provide_training_metric": True,
                       "async_host_io": async_io},
                      lgb.Dataset(X, label=y), num_boost_round=rounds,
                      valid_sets=[lgb.Dataset(Xv, label=yv)],
                      callbacks=[record_evaluation(hist)],
                      checkpoint_dir=ck, checkpoint_freq=2,
                      metrics_dir=ev)
    finally:
        os.environ.pop("LGBM_TPU_FAULT", None)
        faults.reload()
    counters = dict(global_registry.snapshot()["counters"])
    return b, ck, ev, hist, counters


def _ckpt_files(ck):
    return sorted(f for f in os.listdir(ck)
                  if f.startswith("ckpt_") or f == "manifest.json")


@pytest.mark.parametrize("fault", [None, "ckpt_write_fail@2"])
def test_async_matches_sync_byte_for_byte(tmp_path, fault):
    ba, cka, eva, hista, ca = _run(tmp_path, f"a{bool(fault)}", True,
                                   fault)
    bs, cks, evs, hists, cs = _run(tmp_path, f"s{bool(fault)}", False,
                                   fault)
    # models byte-identical (modulo the async knob's own params line)
    assert _strip_io_params(ba.model_to_string()) \
        == _strip_io_params(bs.model_to_string())
    # eval histories identical (device eval is orthogonal to the writer)
    assert hista == hists
    # same checkpoint set, same bytes
    assert _ckpt_files(cka) == _ckpt_files(cks)
    import re
    for f in _ckpt_files(cka):
        a = open(os.path.join(cka, f), "rb").read()
        s = open(os.path.join(cks, f), "rb").read()
        if f.endswith(".txt") or f == "manifest.json":
            a, s = (_strip_io_params(a.decode()).encode(),
                    _strip_io_params(s.decode()).encode())
        if f == "manifest.json":
            # the model-text digest covers the UNstripped bytes, which
            # include the async knob's own params line — mask digest
            # values; the artifacts they describe are byte-compared
            # above, and digest correctness is pinned in test_elastic
            a, s = (re.sub(rb'"[0-9a-f]{64}"', b'"<sha>"', x)
                    for x in (a, s))
        assert a == s, f"checkpoint file {f} differs between modes"
    if fault:
        # the injected write failure was absorbed in BOTH modes
        assert ca.get("checkpoint_failures") == 1
        assert cs.get("checkpoint_failures") == 1
        assert not os.path.exists(os.path.join(cka, "ckpt_0000002.txt"))
    # both runs wrote a complete event log
    for ev in (eva, evs):
        lines = [json.loads(l) for l in
                 open(os.path.join(ev, "events-rank0.jsonl"))]
        assert sum(e["event"] == "iteration" for e in lines) == 6
        assert lines[-1]["event"] == "train_end"


def test_async_event_log_matches_sync(tmp_path):
    """Same events, same payloads (ts excluded).  Checkpoint events are
    compared as a set: the async writer reports a checkpoint AFTER its
    files land, which legitimately reorders it past the iteration event
    emitted while the write was in flight."""
    _, _, eva, _, _ = _run(tmp_path, "evta", True)
    _, _, evs, _, _ = _run(tmp_path, "evts", False)

    def normalized(path):
        seq, ckpts = [], []
        for line in open(os.path.join(path, "events-rank0.jsonl")):
            rec = json.loads(line)
            rec.pop("ts", None)
            rec.pop("phases", None)          # wall-clock dependent
            rec.pop("time_s", None)
            rec.pop("roofline", None)        # mfu = flops / wall-clock
            (rec.get("params") or {}).pop("async_host_io", None)
            if rec["event"].startswith("checkpoint"):
                rec["path"] = os.path.basename(rec.get("path", ""))
                ckpts.append(rec)
            else:
                # counters can lag in async mode (checkpoint_writes
                # lands when the write does)
                rec.pop("counters", None)
                seq.append(rec)
        return seq, sorted(ckpts, key=lambda r: r["iteration"])
    assert normalized(eva) == normalized(evs)


def test_async_checkpoint_resumes_byte_exact(tmp_path):
    """A checkpoint written by the async writer restores the exact score
    buffer: resume reproduces the uninterrupted run byte-for-byte."""
    X, y = _data(seed=3)
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "metric": "none"}
    full = lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=8)
    ck = str(tmp_path / "ck")
    lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=4,
              checkpoint_dir=ck, checkpoint_freq=2)
    resumed = lgb.train(dict(p), lgb.Dataset(X, label=y),
                        num_boost_round=8, checkpoint_dir=ck,
                        checkpoint_freq=2)
    assert resumed.model_to_string() == full.model_to_string()


# --------------------------------------------------------- AsyncWriter
def test_async_writer_fifo_and_flush():
    w = AsyncWriter(max_queue=4)
    seen = []
    for i in range(32):
        w.submit(seen.append, i)
    w.flush()
    assert seen == list(range(32))
    w.close()
    # after close: inline fallback, nothing dropped
    w.submit(seen.append, 99)
    assert seen[-1] == 99


def test_async_writer_error_isolation():
    w = AsyncWriter()
    global_registry.reset()
    before = global_registry.counter("host_io_errors")

    def boom():
        raise OSError("disk gone")
    done = []
    w.submit(boom)
    w.submit(done.append, 1)      # the worker survives the failure
    w.flush()
    assert done == [1]
    assert global_registry.counter("host_io_errors") == before + 1
    w.close()


# ------------------------------------------------------- compile cache
_CACHE_SCRIPT = textwrap.dedent("""
    import sys, os, json
    sys.path.insert(0, {repo!r})
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.observability import global_registry
    rng = np.random.RandomState(0)
    X = rng.randn(400, 6); y = (X[:, 0] > 0).astype(float)
    # num_leaves=31: the tree-program compile must clear the cache's
    # >=1 s persistence gate (observability/compile_cache.py)
    lgb.train({{"objective": "binary", "num_leaves": 31, "verbosity": -1,
               "metric": "none", "compile_cache_dir": sys.argv[1]}},
              lgb.Dataset(X, label=y), num_boost_round=2)
    snap = global_registry.snapshot()["counters"]
    print(json.dumps({{k: v for k, v in snap.items() if "compile" in k}}))
""")


def test_compile_cache_second_run_hits(tmp_path):
    cache = str(tmp_path / "xla-cache")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_REPO)
    outs = []
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", _CACHE_SCRIPT.format(
            repo=_REPO), cache], capture_output=True, text=True, env=env,
            timeout=600)
        assert r.returncode == 0, r.stderr[-2000:]
        outs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    first, second = outs
    assert first.get("compile_cache_misses", 0) > 0
    assert os.listdir(cache), "no persistent cache entries written"
    # the second process deserializes instead of recompiling
    assert second.get("compile_cache_hits", 0) > 0
