import numpy as np
import pytest

from lightgbm_tpu.io.binning import (BIN_CATEGORICAL, MISSING_NAN, MISSING_NONE,
                                     MISSING_ZERO, BinMapper, greedy_find_bin)


def test_greedy_few_distinct_values():
    bounds = greedy_find_bin([1.0, 2.0, 3.0], [10, 10, 10], max_bin=255,
                             total_cnt=30, min_data_in_bin=3)
    # boundaries at midpoints, last is +inf
    assert bounds[-1] == np.inf
    assert len(bounds) == 3
    assert 1.0 < bounds[0] <= 1.5000001
    assert 2.0 < bounds[1] <= 2.5000001


def test_greedy_respects_min_data_in_bin():
    bounds = greedy_find_bin([1.0, 2.0, 3.0, 4.0], [1, 1, 1, 27], max_bin=255,
                             total_cnt=30, min_data_in_bin=3)
    # first three values get merged until >= 3 samples
    assert len(bounds) == 2


def test_find_bin_basic_roundtrip():
    rng = np.random.RandomState(0)
    vals = rng.normal(size=1000)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=1000, max_bin=16)
    assert m.num_bin <= 16
    assert not m.is_trivial
    bins = m.values_to_bins(vals)
    assert bins.min() >= 0 and bins.max() < m.num_bin
    # mapping must be monotone in value
    order = np.argsort(vals)
    assert (np.diff(bins[order]) >= 0).all()


def test_zero_bin_dedicated():
    # mostly zeros with some positives: zero must get its own bin
    vals = np.array([1.0, 2.0, 3.0] * 10)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=1000, max_bin=16)  # 970 implied zeros
    zero_bin = m.value_to_bin(0.0)
    pos_bin = m.value_to_bin(1.0)
    assert zero_bin != pos_bin
    assert m.default_bin == zero_bin


def test_missing_nan_gets_last_bin():
    vals = np.concatenate([np.arange(100, dtype=float), [np.nan] * 50])
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=150, max_bin=16, use_missing=True)
    assert m.missing_type == MISSING_NAN
    assert m.value_to_bin(np.nan) == m.num_bin - 1


def test_missing_zero_maps_nan_to_zero_bin():
    vals = np.arange(1, 101, dtype=float)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=200, max_bin=16, zero_as_missing=True)
    assert m.missing_type in (MISSING_ZERO, MISSING_NONE)
    assert m.value_to_bin(np.nan) == m.value_to_bin(0.0)


def test_trivial_feature():
    m = BinMapper()
    m.find_bin(np.array([]), total_sample_cnt=100, max_bin=16)
    assert m.is_trivial


def test_categorical_binning():
    vals = np.array([0.0] * 5 + [1.0] * 50 + [2.0] * 30 + [3.0] * 15)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=10, bin_type=BIN_CATEGORICAL,
               min_data_in_bin=1)
    assert m.bin_type == BIN_CATEGORICAL
    # most frequent category gets bin 1 (bin 0 reserved for NaN/other)
    assert m.value_to_bin(1.0) == 1
    assert m.value_to_bin(2.0) == 2
    assert m.value_to_bin(np.nan) == 0
    assert m.value_to_bin(99.0) == 0  # unseen category


def test_bin_upper_bounds_are_sorted():
    rng = np.random.RandomState(3)
    vals = np.concatenate([rng.normal(size=500), -rng.exponential(size=200)])
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=800, max_bin=32)
    b = m.bin_upper_bound
    finite = b[np.isfinite(b)]
    assert (np.diff(finite) > 0).all()


def test_serialization_roundtrip():
    rng = np.random.RandomState(1)
    m = BinMapper()
    m.find_bin(rng.normal(size=300), total_sample_cnt=300, max_bin=24)
    m2 = BinMapper.from_dict(m.to_dict())
    vals = rng.normal(size=100)
    assert (m.values_to_bins(vals) == m2.values_to_bins(vals)).all()


def test_device_bucketize_matches_host_searchsorted():
    """The device second pass (io/device_bin.py) must reproduce the host
    values_to_bins codes bit-for-bit on float32 data — including NaN
    handling for both missing conventions and values landing exactly on
    float64 bin bounds (the floor32 rounding argument)."""
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.io.device_bin import bin_matrix_device

    rng = np.random.RandomState(5)
    n, F = 20000, 6
    X = np.empty((n, F), np.float32)
    X[:, 0] = rng.randn(n)
    X[:, 1] = np.abs(rng.randn(n)) ** 1.5
    X[:, 2] = rng.rand(n)
    X[:, 3] = rng.randint(0, 10, n)          # coarse ints -> heavy ties
    X[:, 4] = rng.randn(n)
    X[:, 4][rng.rand(n) < 0.1] = np.nan      # NaN missing
    X[:, 5] = rng.randn(n) * 1e-3
    X[:, 5][rng.rand(n) < 0.3] = 0.0         # zero-heavy

    ds = Dataset.construct_from_arrays(X.astype(np.float64),
                                       label=np.zeros(n))
    # place many values EXACTLY on the float64 bounds of feature 0
    m0 = ds.bin_mappers[ds.used_features[0]]
    finite = m0.bin_upper_bound[np.isfinite(m0.bin_upper_bound)]
    if len(finite):
        X[:len(finite) * 3, 0] = np.tile(
            finite.astype(np.float32), 3)[:len(finite) * 3]

    host = np.stack([ds.bin_mappers[f].values_to_bins(
        X[:, f].astype(np.float64)) for f in ds.used_features])
    dev = bin_matrix_device(X, ds.bin_mappers, ds.used_features,
                            chunk=4096)
    np.testing.assert_array_equal(host.astype(np.int32),
                                  dev.astype(np.int32))


def test_device_binnable_gate():
    from lightgbm_tpu.io.dataset import Dataset
    from lightgbm_tpu.io.device_bin import device_binnable
    rng = np.random.RandomState(0)
    X = rng.randn(2000, 3)
    ds = Dataset.construct_from_arrays(X, label=np.zeros(2000))
    # float64 data must never take the device path (needs full mantissa)
    assert not device_binnable(ds.bin_mappers, ds.used_features,
                               np.float64, 10_000_000)
    # small n stays on host regardless
    assert not device_binnable(ds.bin_mappers, ds.used_features,
                               np.float32, 2000)
