import numpy as np
import pytest

from lightgbm_tpu.io.binning import (BIN_CATEGORICAL, MISSING_NAN, MISSING_NONE,
                                     MISSING_ZERO, BinMapper, greedy_find_bin)


def test_greedy_few_distinct_values():
    bounds = greedy_find_bin([1.0, 2.0, 3.0], [10, 10, 10], max_bin=255,
                             total_cnt=30, min_data_in_bin=3)
    # boundaries at midpoints, last is +inf
    assert bounds[-1] == np.inf
    assert len(bounds) == 3
    assert 1.0 < bounds[0] <= 1.5000001
    assert 2.0 < bounds[1] <= 2.5000001


def test_greedy_respects_min_data_in_bin():
    bounds = greedy_find_bin([1.0, 2.0, 3.0, 4.0], [1, 1, 1, 27], max_bin=255,
                             total_cnt=30, min_data_in_bin=3)
    # first three values get merged until >= 3 samples
    assert len(bounds) == 2


def test_find_bin_basic_roundtrip():
    rng = np.random.RandomState(0)
    vals = rng.normal(size=1000)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=1000, max_bin=16)
    assert m.num_bin <= 16
    assert not m.is_trivial
    bins = m.values_to_bins(vals)
    assert bins.min() >= 0 and bins.max() < m.num_bin
    # mapping must be monotone in value
    order = np.argsort(vals)
    assert (np.diff(bins[order]) >= 0).all()


def test_zero_bin_dedicated():
    # mostly zeros with some positives: zero must get its own bin
    vals = np.array([1.0, 2.0, 3.0] * 10)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=1000, max_bin=16)  # 970 implied zeros
    zero_bin = m.value_to_bin(0.0)
    pos_bin = m.value_to_bin(1.0)
    assert zero_bin != pos_bin
    assert m.default_bin == zero_bin


def test_missing_nan_gets_last_bin():
    vals = np.concatenate([np.arange(100, dtype=float), [np.nan] * 50])
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=150, max_bin=16, use_missing=True)
    assert m.missing_type == MISSING_NAN
    assert m.value_to_bin(np.nan) == m.num_bin - 1


def test_missing_zero_maps_nan_to_zero_bin():
    vals = np.arange(1, 101, dtype=float)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=200, max_bin=16, zero_as_missing=True)
    assert m.missing_type in (MISSING_ZERO, MISSING_NONE)
    assert m.value_to_bin(np.nan) == m.value_to_bin(0.0)


def test_trivial_feature():
    m = BinMapper()
    m.find_bin(np.array([]), total_sample_cnt=100, max_bin=16)
    assert m.is_trivial


def test_categorical_binning():
    vals = np.array([0.0] * 5 + [1.0] * 50 + [2.0] * 30 + [3.0] * 15)
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=100, max_bin=10, bin_type=BIN_CATEGORICAL,
               min_data_in_bin=1)
    assert m.bin_type == BIN_CATEGORICAL
    # most frequent category gets bin 1 (bin 0 reserved for NaN/other)
    assert m.value_to_bin(1.0) == 1
    assert m.value_to_bin(2.0) == 2
    assert m.value_to_bin(np.nan) == 0
    assert m.value_to_bin(99.0) == 0  # unseen category


def test_bin_upper_bounds_are_sorted():
    rng = np.random.RandomState(3)
    vals = np.concatenate([rng.normal(size=500), -rng.exponential(size=200)])
    m = BinMapper()
    m.find_bin(vals, total_sample_cnt=800, max_bin=32)
    b = m.bin_upper_bound
    finite = b[np.isfinite(b)]
    assert (np.diff(finite) > 0).all()


def test_serialization_roundtrip():
    rng = np.random.RandomState(1)
    m = BinMapper()
    m.find_bin(rng.normal(size=300), total_sample_cnt=300, max_bin=24)
    m2 = BinMapper.from_dict(m.to_dict())
    vals = rng.normal(size=100)
    assert (m.values_to_bins(vals) == m2.values_to_bins(vals)).all()
