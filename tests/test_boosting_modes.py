"""DART, RF, GOSS, lambdarank and continued-training e2e tests (mirrors
reference tests/python_package_test/test_engine.py: test_dart, test_rf,
test_goss, rank fixtures, test_continue_train)."""

import numpy as np

import lightgbm_tpu as lgb


def make_binary(n=2000, F=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F)
    logit = 3 * (X[:, 0] - 0.5) + 2 * X[:, 1] * X[:, 2] - X[:, 3]
    y = (rng.rand(n) < 1 / (1 + np.exp(-3 * logit))).astype(np.float64)
    return X, y


def make_regression(n=2000, F=10, noise=0.05, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F)
    y = (np.sin(X[:, 0] * 5) + 2 * X[:, 1] * X[:, 2] + X[:, 3] ** 2
         + noise * rng.randn(n))
    return X, y.astype(np.float64)


def make_ranking(n_queries=60, docs_per_query=20, F=8, seed=3):
    """Synthetic learning-to-rank data with graded relevance labels."""
    rng = np.random.RandomState(seed)
    n = n_queries * docs_per_query
    X = rng.rand(n, F)
    rel_score = 2.5 * X[:, 0] + 1.5 * X[:, 1] - X[:, 2] + 0.3 * rng.randn(n)
    y = np.zeros(n)
    for q in range(n_queries):
        s = slice(q * docs_per_query, (q + 1) * docs_per_query)
        r = rel_score[s]
        y[s] = np.digitize(r, np.quantile(r, [0.5, 0.75, 0.9]))
    group = np.full(n_queries, docs_per_query)
    return X, y, group


def _auc(y, score):
    order = np.argsort(score)
    ranks = np.empty(len(y))
    ranks[order] = np.arange(1, len(y) + 1)
    pos = y > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


# ------------------------------------------------------------------- DART
def test_dart_trains_and_beats_chance():
    X, y = make_binary()
    Xte, yte = make_binary(seed=1)
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "binary", "boosting": "dart",
                         "num_leaves": 15, "learning_rate": 0.15,
                         "drop_rate": 0.3, "verbosity": -1},
                        train, num_boost_round=40)
    assert booster.num_trees() == 40
    auc = _auc(yte, booster.predict(Xte))
    assert auc > 0.8, auc


def test_dart_train_score_consistent_with_model():
    """After normalization, the device training score must equal the summed
    tree predictions (the invariant DART's drop/normalize dance maintains)."""
    X, y = make_regression(n=500, F=5)
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "regression", "boosting": "dart",
                         "num_leaves": 7, "drop_rate": 0.5, "skip_drop": 0.0,
                         "verbosity": -1}, train, num_boost_round=15)
    gbdt = booster._gbdt
    internal = np.asarray(gbdt.scores)[0][:gbdt.num_data]
    from_model = booster.predict(X, raw_score=True)
    np.testing.assert_allclose(internal, from_model, rtol=1e-4, atol=1e-4)


def test_dart_uniform_and_xgboost_modes():
    X, y = make_binary(n=800)
    for extra in ({"uniform_drop": True}, {"xgboost_dart_mode": True}):
        train = lgb.Dataset(X, label=y)
        booster = lgb.train({"objective": "binary", "boosting": "dart",
                             "num_leaves": 7, "verbosity": -1, **extra},
                            train, num_boost_round=10)
        assert booster.num_trees() == 10


# --------------------------------------------------------------------- RF
def test_rf_trains_and_beats_chance():
    X, y = make_binary()
    Xte, yte = make_binary(seed=1)
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "binary", "boosting": "rf",
                         "bagging_freq": 1, "bagging_fraction": 0.7,
                         "feature_fraction": 0.8, "num_leaves": 31,
                         "verbosity": -1}, train, num_boost_round=30)
    auc = _auc(yte, booster.predict(Xte))
    assert auc > 0.8, auc


def test_rf_prediction_is_average(tmp_path):
    """RF predictions average tree outputs; model file carries
    average_output (ref: gbdt_model_text.cpp:330)."""
    X, y = make_regression(n=600, F=5)
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "regression", "boosting": "rf",
                         "bagging_freq": 1, "bagging_fraction": 0.6,
                         "num_leaves": 15, "verbosity": -1},
                        train, num_boost_round=20)
    # averaged output stays on the scale of y, and matches the running
    # average the internal score tracker maintains
    pred = booster.predict(X)
    gbdt = booster._gbdt
    internal = np.asarray(gbdt.scores)[0][:gbdt.num_data]
    np.testing.assert_allclose(internal, pred, rtol=1e-4, atol=1e-4)
    txt = booster.model_to_string()
    assert "average_output" in txt
    path = str(tmp_path / "rf.txt")
    booster.save_model(path)
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(loaded.predict(X), pred, rtol=1e-5, atol=1e-5)


def test_rf_requires_bagging():
    import pytest
    X, y = make_binary(n=300)
    train = lgb.Dataset(X, label=y)
    with pytest.raises(Exception):
        lgb.train({"objective": "binary", "boosting": "rf",
                   "verbosity": -1}, train, num_boost_round=3)


# ------------------------------------------------------------------- GOSS
def test_goss_quality():
    X, y = make_binary(n=4000)
    Xte, yte = make_binary(seed=1)
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "binary",
                         "data_sample_strategy": "goss",
                         "top_rate": 0.2, "other_rate": 0.1,
                         "num_leaves": 15, "learning_rate": 0.1,
                         "verbosity": -1}, train, num_boost_round=50)
    auc = _auc(yte, booster.predict(Xte))
    assert auc > 0.85, auc


def test_goss_sample_math():
    """Mask keeps ~top_rate+other_rate of rows; small-gradient rows are
    amplified by rest/other_k (ref: goss.hpp:118-165)."""
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.boosting.gbdt import _goss_sample

    n = 1000
    rng = np.random.RandomState(0)
    grad_host = rng.randn(1, n).astype(np.float32)
    # the jit entry DONATES grad/hess (their buffers are dead in the
    # training loop after sampling, ISSUE 5) — keep host copies
    grad = jnp.asarray(grad_host)
    hess = jnp.ones((1, n), jnp.float32)
    pad_mask = jnp.ones(n, jnp.float32)
    top_k, other_k = 200, 100
    keep, g2, h2 = _goss_sample(grad, hess, pad_mask, jax.random.PRNGKey(0),
                                top_k, other_k)
    kept = int(np.asarray(keep).sum())
    assert abs(kept - (top_k + other_k)) < 60, kept
    # top rows keep their gradient unchanged
    imp = np.abs(grad_host[0])
    top_idx = np.argsort(-imp)[:top_k]
    np.testing.assert_allclose(np.asarray(g2)[0][top_idx],
                               grad_host[0][top_idx], rtol=1e-6)
    # sampled small-gradient rows are amplified
    amplified = np.asarray(g2)[0] / np.where(grad_host[0] == 0, 1,
                                             grad_host[0])
    small_kept = (np.asarray(keep) > 0) & ~np.isin(np.arange(n), top_idx)
    if small_kept.any():
        assert np.all(amplified[small_kept] > 1.0)


# ------------------------------------------------------------- lambdarank
def test_lambdarank_ndcg_improves():
    X, y, group = make_ranking()
    Xte, yte, gte = make_ranking(seed=7)
    train = lgb.Dataset(X, label=y, group=group)
    valid = train.create_valid(Xte, label=yte, group=gte)
    record = {}
    booster = lgb.train({"objective": "lambdarank", "metric": "ndcg",
                         "ndcg_eval_at": [5], "num_leaves": 15,
                         "learning_rate": 0.1, "verbosity": -1},
                        train, num_boost_round=50, valid_sets=[valid],
                        callbacks=[lgb.record_evaluation(record)])
    curve = record["valid_0"]["ndcg@5"]
    assert curve[-1] > curve[0] + 0.02, curve[:3] + curve[-3:]
    assert curve[-1] > 0.8, curve[-1]


def test_rank_xendcg_trains():
    X, y, group = make_ranking(n_queries=40)
    train = lgb.Dataset(X, label=y, group=group)
    booster = lgb.train({"objective": "rank_xendcg", "num_leaves": 7,
                         "verbosity": -1}, train, num_boost_round=15)
    assert booster.num_trees() == 15


# ------------------------------------------------- continued training
def test_continued_training_matches_single_run(tmp_path):
    """train 10 + save + load + train 10 more ≈ train 20 (ref:
    test_engine.py test_continue_train; application.cpp:94-97)."""
    X, y = make_regression(n=1500)
    p = {"objective": "regression", "num_leaves": 15,
         "learning_rate": 0.1, "verbosity": -1}

    b20 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=20)

    b10 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=10)
    path = str(tmp_path / "m10.txt")
    b10.save_model(path)
    b_cont = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=10,
                       init_model=path)
    assert b_cont.num_trees() == 20
    np.testing.assert_allclose(b_cont.predict(X), b20.predict(X),
                               rtol=1e-4, atol=1e-4)


def test_continued_training_from_booster():
    X, y = make_binary(n=1200)
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    b10 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=10)
    b_cont = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=10,
                       init_model=b10)
    b20 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=20)
    np.testing.assert_allclose(b_cont.predict(X), b20.predict(X),
                               rtol=1e-4, atol=1e-4)


def test_rf_continued_training_scores_consistent():
    """Continuing an RF from an RF keeps the internal running-average score
    equal to the merged model's own (averaged) prediction."""
    X, y = make_regression(n=800, F=5)
    p = {"objective": "regression", "boosting": "rf", "bagging_freq": 1,
         "bagging_fraction": 0.6, "num_leaves": 15, "verbosity": -1}
    b5 = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=5)
    b_cont = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=5,
                       init_model=b5)
    gbdt = b_cont._gbdt
    internal = np.asarray(gbdt.scores)[0][:gbdt.num_data]
    np.testing.assert_allclose(internal, b_cont.predict(X),
                               rtol=1e-4, atol=1e-4)


def test_continue_across_averaging_modes_rejected():
    import pytest
    X, y = make_regression(n=300, F=5)
    prf = {"objective": "regression", "boosting": "rf", "bagging_freq": 1,
           "bagging_fraction": 0.6, "num_leaves": 7, "verbosity": -1}
    brf = lgb.train(prf, lgb.Dataset(X, label=y), num_boost_round=3)
    with pytest.raises(Exception):
        lgb.train({"objective": "regression", "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=3, init_model=brf)


def test_dart_custom_objective_sees_dropped_score():
    """With fobj, the score handed to the objective reflects this iteration's
    dropout (ref: dart.hpp GetTrainingScore)."""
    X, y = make_regression(n=400, F=5)
    train = lgb.Dataset(X, label=y)
    seen_scores = []

    def fobj(score, _ds):
        seen_scores.append(np.array(score, copy=True))
        g = score - y
        h = np.ones_like(score)
        return g, h

    booster = lgb.Booster(params={"objective": "none", "boosting": "dart",
                                  "num_leaves": 7, "drop_rate": 1.0,
                                  "skip_drop": 0.0, "verbosity": -1},
                          train_set=train)
    booster.update(fobj=fobj)
    gbdt = booster._gbdt
    # after iter 1 normalization, internal score == ensemble prediction
    internal = np.asarray(gbdt.scores)[0][:gbdt.num_data]
    booster.update(fobj=fobj)
    # with drop_rate=1/skip_drop=0 every tree is dropped, so the score the
    # second fobj saw must differ from the post-normalization ensemble score
    assert not np.allclose(seen_scores[1], internal)


def test_num_boost_round_alias_precedence():
    """Explicit num_boost_round arg is honored unless num_iterations was
    explicitly passed in params (reference alias precedence)."""
    X, y = make_regression(n=400, F=5)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_iterations": 5}, lgb.Dataset(X, label=y),
                  num_boost_round=100)
    assert b.num_trees() == 5
    b2 = lgb.train({"objective": "regression", "verbosity": -1},
                   lgb.Dataset(X, label=y), num_boost_round=7)
    assert b2.num_trees() == 7


def test_lambdarank_position_bias():
    """Position bias factors (rank_objective.hpp:290): clicks biased
    toward top positions train learnable per-position offsets; the model
    with bias correction ranks the true-relevance feature higher."""
    rng = np.random.RandomState(8)
    n_q, per_q = 80, 10
    n = n_q * per_q
    X = rng.rand(n, 2)
    true_rel = (X[:, 0] > 0.6).astype(int)
    position = np.tile(np.arange(per_q), n_q).astype(np.int32)
    # observed label: true relevance AND seen (top positions seen more)
    seen = rng.rand(n) < (1.0 / (1 + position))
    label = (true_rel & seen).astype(np.float64)
    group = np.full(n_q, per_q)
    ds = lgb.Dataset(X, label=label, group=group, position=position)
    b = lgb.train({"objective": "lambdarank", "num_leaves": 7,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "lambdarank_position_bias_regularization": 0.1},
                  ds, num_boost_round=10)
    obj = b._gbdt.objective
    assert obj.positions is not None
    assert obj.pos_biases.shape == (per_q,)
    assert np.any(obj.pos_biases != 0)
    # learned biases must decrease with position (top seen more)
    assert obj.pos_biases[0] > obj.pos_biases[-1]


def test_lambdarank_device_gradients_match_host():
    """The bucketed device lambda program (ranking.py
    make_device_grad_fn) must reproduce the host per-query loop: same
    lambdas/hessians (fp32 tolerance) on irregular query lengths, and
    the same trained model."""
    import jax.numpy as jnp
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata
    from lightgbm_tpu.ranking import LambdarankNDCG

    rng = np.random.RandomState(3)
    lens = [1, 2, 3, 7, 8, 9, 31, 40, 64, 100, 130]
    n = sum(lens)
    labels = rng.randint(0, 5, n).astype(np.float64)
    md = Metadata(n)
    md.set_label(labels)
    md.set_group(np.asarray(lens, np.int64))
    obj = LambdarankNDCG(Config({"objective": "lambdarank"}))
    obj.init(md, n)
    score = rng.randn(n)
    g_h, h_h = obj.get_gradients_host(score.copy())

    n_pad = 512
    fn = obj.make_device_grad_fn(n_pad)
    assert fn is not None
    sc = jnp.zeros((1, n_pad)).at[0, :n].set(jnp.asarray(score, jnp.float32))
    g_d, h_d = fn(sc, None)
    np.testing.assert_allclose(np.asarray(g_d[0, :n]), g_h,
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(h_d[0, :n]), h_h,
                               rtol=2e-3, atol=2e-4)
    # padding rows must receive no gradient
    assert float(jnp.abs(g_d[0, n:]).max()) == 0.0


def test_lambdarank_device_vs_host_training_close():
    X, y, group = make_ranking()
    params = {"objective": "lambdarank", "num_leaves": 15,
              "learning_rate": 0.1, "verbosity": -1}
    b_dev = lgb.train(params, lgb.Dataset(X, label=y, group=group),
                      num_boost_round=10)
    # force the host loop by disabling the device program
    import lightgbm_tpu.ranking as rk
    orig = rk.LambdarankNDCG.make_device_grad_fn
    rk.LambdarankNDCG.make_device_grad_fn = lambda self, n_pad: None
    try:
        b_host = lgb.train(params, lgb.Dataset(X, label=y, group=group),
                           num_boost_round=10)
    finally:
        rk.LambdarankNDCG.make_device_grad_fn = orig
    p_d = b_dev.predict(X[:500])
    p_h = b_host.predict(X[:500])
    # fp32 device vs fp64 host lambdas: trees may diverge late; scores
    # must stay close in aggregate
    assert np.corrcoef(p_d, p_h)[0, 1] > 0.999, np.corrcoef(p_d, p_h)


def test_lambdarank_position_bias_device_matches_host():
    """Position-bias mode also runs on device: per-iteration gradients
    AND the Newton bias state must track the host loop across several
    iterations (the bias feeds back into the next iteration's scores)."""
    import jax.numpy as jnp
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Metadata
    from lightgbm_tpu.ranking import LambdarankNDCG

    rng = np.random.RandomState(4)
    lens = [5, 9, 17, 33, 12, 24]
    n = sum(lens)
    md = Metadata(n)
    md.set_label(rng.randint(0, 5, n).astype(np.float64))
    md.set_group(np.asarray(lens, np.int64))
    md.set_position(rng.randint(0, 10, n).astype(np.int64))

    obj_h = LambdarankNDCG(Config({"objective": "lambdarank"}))
    obj_h.init(md, n)
    obj_d = LambdarankNDCG(Config({"objective": "lambdarank"}))
    obj_d.init(md, n)
    n_pad = 128
    fn = obj_d.make_device_grad_fn(n_pad)
    assert fn is not None       # position bias no longer forces host

    score = rng.randn(n)
    for it in range(3):
        g_h, h_h = obj_h.get_gradients_host(score.copy())
        sc = jnp.zeros((1, n_pad)).at[0, :n].set(
            jnp.asarray(score, jnp.float32))
        g_d, h_d = fn(sc, None)
        np.testing.assert_allclose(np.asarray(g_d[0, :n]), g_h,
                                   rtol=3e-3, atol=3e-4,
                                   err_msg=f"iter {it} grad")
        np.testing.assert_allclose(np.asarray(h_d[0, :n]), h_h,
                                   rtol=3e-3, atol=3e-4,
                                   err_msg=f"iter {it} hess")
        np.testing.assert_allclose(np.asarray(obj_d._pos_biases_dev),
                                   obj_h.pos_biases, rtol=2e-3,
                                   atol=2e-4, err_msg=f"iter {it} bias")
        score = score * 0.9 + 0.1 * rng.randn(n)   # evolve scores
