"""Categorical split finding (ref: feature_histogram.cpp:144
FindBestThresholdCategoricalInner; tree.h:372 CategoricalDecision)."""

import numpy as np
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.learner import FeatureMeta, GrowParams, grow_tree
from lightgbm_tpu.ops.split import (MISSING_NONE, SplitParams,
                                    find_best_split)

RNG = np.random.RandomState(7)


def _cat_problem(n=4000, k=12, noise=0.1, seed=7):
    """Label depends on membership of a category SUBSET whose ids are
    shuffled, so an ordered numerical split cannot separate it."""
    rng = np.random.RandomState(seed)
    cat = rng.randint(0, k, size=n)
    good = set(rng.permutation(k)[:k // 2])
    y = (np.isin(cat, list(good)).astype(np.float32)
         + noise * rng.randn(n).astype(np.float32))
    X = np.stack([cat.astype(np.float64),
                  rng.rand(n)], axis=1)
    return X, y, good


def test_find_best_split_picks_category_subset():
    """With a pure subset-separable gradient, the categorical scan must
    recover (a superset of) the good-category set in its bitset."""
    n, k = 4000, 12
    X, y, good = _cat_problem(n, k, noise=0.0)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    core = ds._core_or_construct()
    binned = core.binned
    F = core.num_features
    mappers = [core.bin_mappers[f] for f in core.used_features]
    B = max(m.num_bin for m in mappers)
    grad = -y.astype(np.float32)
    hess = np.ones(n, np.float32)
    # full-scan histogram
    hist = np.zeros((F, B, 2), np.float32)
    for f in range(F):
        np.add.at(hist[f, :, 0], binned[f], grad)
        np.add.at(hist[f, :, 1], binned[f], hess)
    params = SplitParams(min_data_in_leaf=5, has_categorical=True,
                         max_cat_to_onehot=4, min_data_per_group=10,
                         cat_smooth=10.0, cat_l2=1.0)
    meta_nb = jnp.asarray([m.num_bin for m in mappers], jnp.int32)
    res = find_best_split(
        jnp.asarray(hist), meta_nb,
        jnp.asarray([m.missing_type for m in mappers], jnp.int32),
        jnp.asarray([m.default_bin for m in mappers], jnp.int32),
        jnp.ones(F, jnp.float32), jnp.ones(F, bool),
        jnp.asarray(grad.sum()), jnp.asarray(hess.sum()),
        jnp.asarray(n, jnp.int32), jnp.asarray(0.0), params,
        is_cat_feature=jnp.asarray([m.bin_type == 1 for m in mappers]))
    assert bool(res.is_cat)
    assert int(res.feature) == 0
    # decode bitset -> bins -> category values
    words = np.asarray(res.cat_bitset)
    bins_left = [b for b in range(mappers[0].num_bin)
                 if (words[b // 32] >> (b % 32)) & 1]
    cats_left = {mappers[0].bin_2_categorical[b] for b in bins_left}
    # grad of good categories is negative (y=1) -> they sort first -> left
    assert cats_left == good, (cats_left, good)


def test_categorical_e2e_beats_numerical_treatment():
    X, y, _ = _cat_problem(noise=0.05)
    params = {"objective": "regression", "num_leaves": 4, "verbosity": -1,
              "min_data_in_leaf": 5, "learning_rate": 0.5,
              "min_data_per_group": 5, "max_cat_to_onehot": 1}
    b_cat = lgb.train(params, lgb.Dataset(X, label=y,
                                          categorical_feature=[0]),
                      num_boost_round=6)
    b_num = lgb.train(params, lgb.Dataset(X, label=y),
                      num_boost_round=6)
    mse_cat = float(np.mean((b_cat.predict(X) - y) ** 2))
    mse_num = float(np.mean((b_num.predict(X) - y) ** 2))
    # the subset is one categorical split but needs many numerical ones
    assert mse_cat < mse_num, (mse_cat, mse_num)


def test_categorical_model_roundtrip(tmp_path):
    X, y, _ = _cat_problem(noise=0.05)
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "min_data_per_group": 5}
    booster = lgb.train(params, lgb.Dataset(X, label=y,
                                            categorical_feature=[0]),
                        num_boost_round=5)
    pred = booster.predict(X)
    path = str(tmp_path / "cat_model.txt")
    booster.save_model(path)
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(loaded.predict(X), pred, rtol=1e-6)
    # model text must carry the categorical block
    text = open(path).read()
    assert "cat_boundaries" in text or booster._gbdt.models_[0].num_cat > 0


def test_onehot_split_excludes_cat_l2():
    """One-hot categorical gain/output use lambda_l2 only; cat_l2 applies
    solely to the sorted-subset branch (feature_histogram.cpp:250 puts
    'l2 += cat_l2' in the else of use_onehot)."""
    n, k = 300, 3
    rng = np.random.RandomState(3)
    cat = rng.randint(0, k, size=n)
    grad = np.where(cat == 1, -1.0, 0.5).astype(np.float32)
    grad += 0.01 * rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    X = cat.astype(np.float64)[:, None]
    ds = lgb.Dataset(X, label=grad, categorical_feature=[0])
    core = ds._core_or_construct()
    mapper = core.bin_mappers[0]
    B = mapper.num_bin
    hist = np.zeros((1, B, 2), np.float32)
    np.add.at(hist[0, :, 0], core.binned[0], grad)
    np.add.at(hist[0, :, 1], core.binned[0], hess)
    lambda_l2, cat_l2 = 0.5, 10.0

    def run(cl2):
        params = SplitParams(min_data_in_leaf=1, has_categorical=True,
                             max_cat_to_onehot=k + 1, lambda_l2=lambda_l2,
                             cat_l2=cl2, cat_smooth=0.0,
                             min_data_per_group=1)
        return find_best_split(
            jnp.asarray(hist), jnp.asarray([B], jnp.int32),
            jnp.asarray([mapper.missing_type], jnp.int32),
            jnp.asarray([mapper.default_bin], jnp.int32),
            jnp.ones(1, jnp.float32), jnp.ones(1, bool),
            jnp.asarray(grad.sum()), jnp.asarray(hess.sum()),
            jnp.asarray(n, jnp.int32), jnp.asarray(0.0), params,
            is_cat_feature=jnp.asarray([True]))

    res = run(cat_l2)
    res0 = run(0.0)
    assert bool(res.is_cat) and bool(res0.is_cat)
    # cat_l2 must not alter a one-hot split's gain or leaf outputs
    np.testing.assert_allclose(float(res.gain), float(res0.gain), rtol=1e-6)
    np.testing.assert_allclose(float(res.left_output),
                               float(res0.left_output), rtol=1e-6)
    # and both must equal the closed form with lambda_l2 only
    lg = float(res.left_sum_gradient)
    lh = float(res.left_sum_hessian)
    np.testing.assert_allclose(float(res.left_output),
                               -lg / (lh + lambda_l2), rtol=1e-5)


def test_categorical_onehot_mode():
    """num_bin <= max_cat_to_onehot selects single-category splits."""
    n, k = 2000, 3
    rng = np.random.RandomState(9)
    cat = rng.randint(0, k, size=n)
    y = (cat == 1).astype(np.float32)
    X = cat.astype(np.float64)[:, None]
    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5, "max_cat_to_onehot": 8,
              "min_data_per_group": 5, "learning_rate": 0.5}
    booster = lgb.train(params, lgb.Dataset(X, label=y,
                                            categorical_feature=[0]),
                        num_boost_round=8)
    pred = booster.predict(X)
    # perfect separation achievable with one-hot splits
    assert float(np.mean((pred - y) ** 2)) < 0.05


import pytest


@pytest.mark.parametrize("strategy", ["leafwise", "wave"])
def test_categorical_extra_trees_random_candidates(strategy):
    """extra_trees x categorical (ref: feature_histogram.cpp:187,268
    USE_RAND draws): each scan evaluates ONE random one-hot bin / subset
    prefix, so the model differs from the exhaustive scan but still
    learns the subset structure.  Parametrized over both engines (the
    wave path has its own rand-draw plumbing)."""
    X, y, good = _cat_problem(n=3000, k=10, noise=0.1)
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 5, "learning_rate": 0.3,
            "categorical_feature": [0], "tpu_growth_strategy": strategy}
    b_full = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=10)
    b_rand = lgb.train({**base, "extra_trees": True, "extra_seed": 9},
                       lgb.Dataset(X, label=y), num_boost_round=10)
    p_full, p_rand = b_full.predict(X), b_rand.predict(X)
    assert not np.allclose(p_full, p_rand), \
        "extra_trees must randomize the categorical scan"
    # still learns: good-subset membership is predicted
    target = np.isin(X[:, 0].astype(int), list(good))
    auc_like = np.mean(p_rand[target] > np.median(p_rand))
    assert auc_like > 0.7, auc_like
    # different seeds -> different draws
    b_rand2 = lgb.train({**base, "extra_trees": True, "extra_seed": 10},
                        lgb.Dataset(X, label=y), num_boost_round=10)
    assert not np.allclose(b_rand2.predict(X), p_rand)
