"""Cost-effective gradient boosting (ref:
src/treelearner/cost_effective_gradient_boosting.hpp: DeltaGain =
tradeoff * (penalty_split * num_data_in_leaf + coupled[f] if unused))."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=3000, seed=10):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 4)
    y = (X[:, 0] + 0.9 * X[:, 1] + 0.1 * rng.randn(n) > 0).astype(float)
    return X, y


def test_penalty_split_shrinks_trees():
    """A per-split penalty proportional to leaf size stops splitting
    earlier: fewer total leaves than the unpenalized model."""
    X, y = _data()
    base = {"objective": "binary", "num_leaves": 63, "verbosity": -1,
            "min_data_in_leaf": 5}
    b0 = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=5)
    b1 = lgb.train({**base, "cegb_penalty_split": 0.01},
                   lgb.Dataset(X, label=y), num_boost_round=5)
    b0._gbdt._sync_model()
    b1._gbdt._sync_model()
    leaves0 = sum(t.num_leaves for t in b0._gbdt.models_)
    leaves1 = sum(t.num_leaves for t in b1._gbdt.models_)
    assert leaves1 < leaves0, (leaves1, leaves0)
    assert leaves1 > len(b1._gbdt.models_)  # still splits at the root


def test_coupled_penalty_concentrates_features():
    """Expensive coupled features are avoided unless they pay for
    themselves; the model concentrates on the cheap ones."""
    X, y = _data()
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 5}
    # make features 2,3 (noise) expensive and 0,1 free
    b = lgb.train({**base,
                   "cegb_penalty_feature_coupled": [0.0, 0.0, 1e5, 1e5]},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    imp = b._gbdt.feature_importance("split")
    assert imp[2] == 0 and imp[3] == 0, imp
    assert imp[0] > 0 and imp[1] > 0, imp
    # without penalties the noise features do appear occasionally
    b0 = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=10)
    imp0 = b0._gbdt.feature_importance("split")
    assert imp0[2] + imp0[3] > 0, imp0


def test_coupled_penalty_paid_once():
    """Once a coupled feature is bought, later trees use it freely: with a
    penalty it can just afford, it appears in many trees."""
    rng = np.random.RandomState(3)
    n = 2000
    X = rng.randn(n, 2)
    y = X[:, 0] * 2 + 0.05 * rng.randn(n)   # only feature 0 matters
    b = lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "cegb_penalty_feature_coupled": [1.0, 1.0]},
                  lgb.Dataset(X, label=y), num_boost_round=8)
    imp = b._gbdt.feature_importance("split")
    assert imp[0] >= 8, imp  # used across trees after first purchase


def test_lazy_penalty_avoids_expensive_features():
    """cegb_penalty_feature_lazy charges penalty x (rows in the leaf whose
    value is not yet fetched) per candidate (ref:
    cost_effective_gradient_boosting.hpp:139 CalculateOndemandCosts):
    prohibitively lazy-expensive noise features never get used."""
    X, y = _data()
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 5}
    b = lgb.train({**base,
                   "cegb_penalty_feature_lazy": [0.0, 0.0, 1e5, 1e5]},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    imp = b._gbdt.feature_importance("split")
    assert imp[2] == 0 and imp[3] == 0, imp
    assert imp[0] > 0 and imp[1] > 0, imp


def test_lazy_penalty_charged_per_row_not_per_feature():
    """Lazy differs from coupled: the charge scales with the number of
    not-yet-fetched rows in the leaf.  A penalty small enough to pay at a
    leaf but too big at the root forces the first split elsewhere, and
    once rows are fetched, re-splits on the same rows are free (the
    bitset persists across trees)."""
    rng = np.random.RandomState(7)
    n = 2000
    X = np.stack([rng.rand(n), rng.rand(n)], 1)
    # feature 1 slightly better at the root, feature 0 nearly as good
    y = (1.1 * (X[:, 1] > 0.5) + 1.0 * (X[:, 0] > 0.5)
         + 0.05 * rng.randn(n))
    base = {"objective": "regression", "num_leaves": 4, "verbosity": -1,
            "min_data_in_leaf": 5, "learning_rate": 0.5}
    # per-row penalty on feature 1 big enough to lose the root contest
    # (root charge = p * 2000 exceeds its gain edge) but affordable at
    # half-size child leaves
    b = lgb.train({**base, "cegb_penalty_feature_lazy": [0.0, 0.1],
                   "cegb_tradeoff": 1.0},
                  lgb.Dataset(X, label=y), num_boost_round=3)
    b._gbdt._sync_model()
    t0 = b._gbdt.models_[0]
    assert t0.split_feature[0] == 0, "root should dodge the lazy charge"
    # the first tree's child splits fetch feature 1's rows; the bitset
    # persists across trees, so the SECOND tree's root uses it for free
    imp = b._gbdt.feature_importance("split")
    assert imp[1] > 0, imp
    t1 = b._gbdt.models_[1]
    assert t1.split_feature[0] == 1, "fetched rows should be free now"


def test_lazy_penalty_composes_with_split_penalty():
    X, y = _data(n=1500)
    b = lgb.train({"objective": "binary", "num_leaves": 15,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "cegb_penalty_split": 1e-4,
                   "cegb_penalty_feature_lazy": [1e-4] * 4},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    p = b.predict(X)
    assert np.isfinite(p).all()
    auc_like = np.mean((p > 0.5) == (y > 0.5))
    assert auc_like > 0.8


def test_lazy_penalty_composes_with_basic_monotone():
    """Regression: the monotone kwargs must not clobber the lazy cost in
    the scan (kw overwrite bug) — expensive features stay unused even
    with monotone constraints active."""
    X, y = _data()
    b = lgb.train({"objective": "binary", "num_leaves": 15,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "monotone_constraints": [1, 0, 0, 0],
                   "cegb_penalty_feature_lazy": [0.0, 0.0, 1e5, 1e5]},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    imp = b._gbdt.feature_importance("split")
    assert imp[2] == 0 and imp[3] == 0, imp


def test_lazy_penalty_under_rf_boosting():
    """Regression: RF's grow call must thread (and persist) the lazy
    bitset instead of crashing on the 3-tuple return."""
    X, y = _data(n=1500)
    b = lgb.train({"objective": "binary", "boosting": "rf",
                   "bagging_freq": 1, "bagging_fraction": 0.7,
                   "num_leaves": 15, "verbosity": -1,
                   "min_data_in_leaf": 5,
                   "cegb_penalty_feature_lazy": [0.0, 0.0, 1e5, 1e5]},
                  lgb.Dataset(X, label=y), num_boost_round=4)
    imp = b._gbdt.feature_importance("split")
    assert imp[2] == 0 and imp[3] == 0, imp
    assert np.isfinite(b.predict(X)).all()
