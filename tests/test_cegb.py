"""Cost-effective gradient boosting (ref:
src/treelearner/cost_effective_gradient_boosting.hpp: DeltaGain =
tradeoff * (penalty_split * num_data_in_leaf + coupled[f] if unused))."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=3000, seed=10):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 4)
    y = (X[:, 0] + 0.9 * X[:, 1] + 0.1 * rng.randn(n) > 0).astype(float)
    return X, y


def test_penalty_split_shrinks_trees():
    """A per-split penalty proportional to leaf size stops splitting
    earlier: fewer total leaves than the unpenalized model."""
    X, y = _data()
    base = {"objective": "binary", "num_leaves": 63, "verbosity": -1,
            "min_data_in_leaf": 5}
    b0 = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=5)
    b1 = lgb.train({**base, "cegb_penalty_split": 0.01},
                   lgb.Dataset(X, label=y), num_boost_round=5)
    b0._gbdt._sync_model()
    b1._gbdt._sync_model()
    leaves0 = sum(t.num_leaves for t in b0._gbdt.models_)
    leaves1 = sum(t.num_leaves for t in b1._gbdt.models_)
    assert leaves1 < leaves0, (leaves1, leaves0)
    assert leaves1 > len(b1._gbdt.models_)  # still splits at the root


def test_coupled_penalty_concentrates_features():
    """Expensive coupled features are avoided unless they pay for
    themselves; the model concentrates on the cheap ones."""
    X, y = _data()
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 5}
    # make features 2,3 (noise) expensive and 0,1 free
    b = lgb.train({**base,
                   "cegb_penalty_feature_coupled": [0.0, 0.0, 1e5, 1e5]},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    imp = b._gbdt.feature_importance("split")
    assert imp[2] == 0 and imp[3] == 0, imp
    assert imp[0] > 0 and imp[1] > 0, imp
    # without penalties the noise features do appear occasionally
    b0 = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=10)
    imp0 = b0._gbdt.feature_importance("split")
    assert imp0[2] + imp0[3] > 0, imp0


def test_coupled_penalty_paid_once():
    """Once a coupled feature is bought, later trees use it freely: with a
    penalty it can just afford, it appears in many trees."""
    rng = np.random.RandomState(3)
    n = 2000
    X = rng.randn(n, 2)
    y = X[:, 0] * 2 + 0.05 * rng.randn(n)   # only feature 0 matters
    b = lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "cegb_penalty_feature_coupled": [1.0, 1.0]},
                  lgb.Dataset(X, label=y), num_boost_round=8)
    imp = b._gbdt.feature_importance("split")
    assert imp[0] >= 8, imp  # used across trees after first purchase
