"""CLI application (ref: src/main.cpp; application.cpp:31;
examples/*/train.conf are parsed directly)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.cli import main, parse_args

EXAMPLES = "/root/reference/examples"
BINARY = f"{EXAMPLES}/binary_classification"


def test_parse_args_precedence(tmp_path):
    conf = tmp_path / "c.conf"
    conf.write_text("num_leaves = 31\nlearning_rate = 0.05\n# comment\n")
    params = parse_args([f"config={conf}", "num_leaves=7", "data=x.txt"])
    assert params["num_leaves"] == "7"       # CLI wins over config file
    assert params["learning_rate"] == "0.05"
    assert params["data"] == "x.txt"


def test_train_and_predict_roundtrip(tmp_path):
    model = tmp_path / "model.txt"
    out = tmp_path / "preds.txt"
    rc = main([f"data={BINARY}/binary.train", "objective=binary",
               "num_iterations=15", "num_leaves=31", "verbosity=-1",
               f"output_model={model}"])
    assert rc == 0 and model.exists()
    rc = main(["task=predict", f"data={BINARY}/binary.test",
               f"input_model={model}", f"output_result={out}",
               "verbosity=-1"])
    assert rc == 0
    preds = np.loadtxt(out)
    y = np.loadtxt(f"{BINARY}/binary.test")[:, 0]
    assert preds.shape == y.shape
    assert 0 <= preds.min() and preds.max() <= 1
    acc = np.mean((preds > 0.5) == (y > 0.5))
    assert acc > 0.7, acc


def test_train_with_reference_example_conf(tmp_path):
    """The reference's own train.conf files must parse and run."""
    model = tmp_path / "model.txt"
    rc = main([f"config={BINARY}/train.conf",
               f"data={BINARY}/binary.train",
               f"valid={BINARY}/binary.test",
               "num_iterations=3", f"output_model={model}",
               "verbosity=-1"])
    assert rc == 0 and model.exists()
    text = model.read_text()
    assert text.startswith("tree\n")


def test_cli_refit(tmp_path):
    model = tmp_path / "model.txt"
    refitted = tmp_path / "model2.txt"
    main([f"data={BINARY}/binary.train", "objective=binary",
          "num_iterations=3", "num_leaves=15", "verbosity=-1",
          f"output_model={model}"])
    rc = main(["task=refit", f"data={BINARY}/binary.train",
               f"input_model={model}", f"output_model={refitted}",
               "verbosity=-1"])
    assert rc == 0 and refitted.exists()
    assert refitted.read_text() != model.read_text()


def test_cli_convert_model(tmp_path):
    model = tmp_path / "model.txt"
    cpp = tmp_path / "pred.cpp"
    main([f"data={BINARY}/binary.train", "objective=binary",
          "num_iterations=2", "num_leaves=7", "verbosity=-1",
          f"output_model={model}"])
    rc = main(["task=convert_model", f"input_model={model}",
               f"convert_model={cpp}", "verbosity=-1"])
    assert rc == 0
    src = cpp.read_text()
    assert "double Predict(const double* row)" in src
    assert "PredictTree0" in src


def test_python_dash_m_entrypoint(tmp_path):
    model = tmp_path / "model.txt"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu",
         f"data={BINARY}/binary.train", "objective=binary",
         "num_iterations=2", "num_leaves=7", "verbosity=-1",
         f"output_model={model}"],
        capture_output=True, text=True, timeout=300,
        cwd="/root/repo", env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert model.exists()


def test_snapshot_freq(tmp_path):
    """snapshot_freq writes model.snapshot_iter_N checkpoints
    (ref: gbdt.cpp:244-248) that resume via input_model."""
    model = tmp_path / "model.txt"
    rc = main([f"data={BINARY}/binary.train", "objective=binary",
               "num_iterations=6", "num_leaves=7", "verbosity=-1",
               "snapshot_freq=2", f"output_model={model}"])
    assert rc == 0
    snaps = sorted(tmp_path.glob("model.txt.snapshot_iter_*"))
    assert len(snaps) == 3, snaps
    import lightgbm_tpu as lgb
    b = lgb.Booster(model_file=str(snaps[0]))
    assert b._gbdt.current_iteration() == 2


def test_parallel_learning_example_conf(tmp_path, monkeypatch):
    """The reference's parallel_learning config (tree_learner=feature +
    machine list params).  machines is no longer a silent no-op: a host
    that is not in the machine list fails LOUDLY (the reference's
    Network::Init would likewise fail to bind its listed port), while
    the single-machine form of the same config trains with the feature
    axis sharded over the local mesh (SURVEY §2.3 #2)."""
    import pytest as _pytest

    from lightgbm_tpu.utils.log import LightGBMError
    ex = f"{EXAMPLES}/parallel_learning"
    monkeypatch.chdir(ex)      # relative data paths resolve like the ref CLI
    model = tmp_path / "model.txt"
    # this host is not one of mlist.txt's machines -> loud failure
    with _pytest.raises(LightGBMError, match="machine list"):
        main(["config=train.conf", "num_iterations=2",
              f"output_model={model}", "verbosity=-1"])
    # the same config minus the cluster params trains locally
    rc = main(["config=train.conf", "num_iterations=2", "num_machines=1",
               f"output_model={model}", "verbosity=-1"])
    assert rc == 0 and model.exists()


@pytest.mark.parametrize("example", [
    "regression", "binary_classification", "multiclass_classification",
    "lambdarank", "xendcg"])
def test_cli_runs_every_reference_example(example, tmp_path, monkeypatch):
    """Every reference example's own train.conf must train AND its
    predict.conf must predict through our CLI, unmodified except the
    output paths (the switch-over contract: a reference user's configs
    keep working).  Mirrors tests/python_package_test/test_consistency.py
    driving examples/*/train.conf."""
    ex = f"{EXAMPLES}/{example}"
    model = tmp_path / "model.txt"
    monkeypatch.chdir(ex)  # configs use relative data paths
    rc = main([f"config={ex}/train.conf", "num_trees=5",
               f"output_model={model}", "verbosity=-1"])
    assert rc == 0 and model.exists()
    pred_out = tmp_path / "pred.txt"
    rc = main([f"config={ex}/predict.conf", f"input_model={model}",
               f"output_result={pred_out}"])
    assert rc == 0
    preds = np.loadtxt(pred_out)
    assert np.isfinite(preds).all() and len(preds) > 0


def test_cli_predict_streams_chunks(tmp_path, monkeypatch):
    """File prediction must run in bounded row chunks (ref:
    predictor.hpp:30 PipelineReader) and produce byte-identical output
    to a single-chunk run."""
    import lightgbm_tpu.cli as cli
    model = tmp_path / "m.txt"
    rc = main(["task=train", "objective=binary",
               f"data={BINARY}/binary.train", f"output_model={model}",
               "num_trees=5", "verbosity=-1"])
    assert rc == 0
    out_full = tmp_path / "pred_full.txt"
    rc = main(["task=predict", f"data={BINARY}/binary.test",
               f"input_model={model}", f"output_result={out_full}"])
    assert rc == 0
    # force many small chunks and compare byte-for-byte
    monkeypatch.setattr(cli, "_PREDICT_CHUNK_BUDGET", 8 * 28 * 100)
    out_chunked = tmp_path / "pred_chunked.txt"
    rc = main(["task=predict", f"data={BINARY}/binary.test",
               f"input_model={model}", f"output_result={out_chunked}"])
    assert rc == 0
    assert out_full.read_text() == out_chunked.read_text()
    assert len(out_full.read_text().splitlines()) == 500


def test_parse_file_stream_matches_parse_file(tmp_path):
    """The streamed parser must produce the same rows as the one-shot
    parser for dense and libsvm inputs, across chunk boundaries."""
    import numpy as np
    from lightgbm_tpu.io.parser import parse_file, parse_file_stream
    dense = f"{BINARY}/binary.train"
    f_full, l_full, _ = parse_file(dense)
    chunks = list(parse_file_stream(dense, chunk_rows=777))
    f_s = np.concatenate([c[0] for c in chunks])
    l_s = np.concatenate([c[1] for c in chunks])
    np.testing.assert_array_equal(f_full, f_s)
    np.testing.assert_array_equal(l_full, l_s)
    assert len(chunks) > 1
    # libsvm with a width hint covering indices missing from late chunks
    svm = tmp_path / "t.svm"
    rng = np.random.RandomState(0)
    lines = []
    for i in range(500):
        k = rng.randint(0, 9)
        lines.append(f"{i % 2} {k}:{rng.rand():.6f}" +
                     (" 9:1.5" if i < 100 else ""))
    svm.write_text("\n".join(lines) + "\n")
    f_full, l_full, _ = parse_file(str(svm))
    chunks = list(parse_file_stream(str(svm), chunk_rows=150,
                                    num_features=f_full.shape[1]))
    f_s = np.concatenate([c[0] for c in chunks])
    np.testing.assert_array_equal(f_full, f_s)
