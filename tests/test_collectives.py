"""Multi-chip collective accounting regression gate.

The data-parallel wave engine's only cross-device traffic should be the
per-wave histogram psum of the COMPUTED (smaller-child) slots plus a few
scalar reductions (ref: data_parallel_tree_learner.cpp:284
ReduceScatter traffic model).  This test compiles the tree builder over
the 8-device virtual mesh and pins the all-reduce count and byte volume
so a change that starts reducing full-slot histograms (or sneaks a new
collective into the wave loop) fails loudly.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lightgbm_tpu.learner import FeatureMeta, GrowParams, grow_tree_wave
from lightgbm_tpu.ops.split import SplitParams
from tools.collective_accounting import all_reduce_stats

N = 1 << 13
F = 8
B = 64
L = 31


def expected_hist_bytes(L, F, B):
    """Per-tree psum volume model: one [Kb, F, B, 2] fp32 computed-slot
    histogram per wave of the subtraction engine's ladder plus the
    while-loop wave."""
    from lightgbm_tpu.ops.histogram import wave_slot_pad
    import math
    num_waves = max(1, math.ceil(math.log2(L)))
    kbs = [wave_slot_pad(min(1 << max(k - 1, 0), L))
           for k in range(num_waves)] + [wave_slot_pad(max(L // 2, 1))]
    return sum(k * F * B * 2 * 4 for k in kbs)



@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_wave_allreduce_count_and_volume():
    rng = np.random.RandomState(0)
    mesh = Mesh(np.array(jax.devices()[:8]), ("row",))
    shard = NamedSharding(mesh, P(None, "row"))
    repl = NamedSharding(mesh, P())
    rowsh = NamedSharding(mesh, P("row"))
    binned = jax.device_put(
        rng.randint(0, B, size=(F, N)).astype(np.uint8), shard)
    grad = jax.device_put(rng.randn(N).astype(np.float32), rowsh)
    hess = jax.device_put(np.abs(rng.rand(N).astype(np.float32)) + 0.1,
                          rowsh)
    mask = jax.device_put(np.ones(N, np.float32), rowsh)
    cmask = jax.device_put(np.ones(F, bool), repl)
    meta = FeatureMeta(
        num_bin=jax.device_put(np.full(F, B, np.int32), repl),
        missing_type=jax.device_put(np.zeros(F, np.int32), repl),
        default_bin=jax.device_put(np.zeros(F, np.int32), repl),
        penalty=jax.device_put(np.ones(F, np.float32), repl))
    gp = GrowParams(num_leaves=L, max_bin=B, hist_method="segment",
                    split=SplitParams(min_data_in_leaf=20))
    hlo = jax.jit(grow_tree_wave, static_argnames=("params",)).lower(
        binned, grad, hess, mask, cmask, meta, gp).compile().as_text()
    n_ar, bytes_ar = all_reduce_stats(hlo)

    # expected psum volume (+ [Kb] counts per wave and small scalar
    # reductions: root sums, final count matmul)
    hist_bytes = expected_hist_bytes(L, F, B)
    assert bytes_ar >= hist_bytes, (bytes_ar, hist_bytes)
    # regression bound: within 2x of the pure-histogram volume (scalar
    # side reductions are small) and a fixed op-count envelope
    assert bytes_ar <= 2 * hist_bytes, (bytes_ar, hist_bytes)
    assert n_ar <= 10, n_ar


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_sharded_wave_shardmap_allreduce_volume():
    """The shard_map'd wave path (parallel.make_sharded_wave_fn — the
    DEFAULT engine's distributed form) must reduce the same computed-slot
    histograms the GSPMD test above pins: per wave one [Kb, F, B, 2]
    psum (+ counts + scalar root sums), nothing more.

    Lowered through make_sharded_wave_fn's OWN cached builder, so the
    production in_specs/out_specs are what compiles.  The CPU test
    backend lowers the segment histogram inside the shard_map; on TPU
    the same `_psum` call sites in wave.py wrap the Pallas kernel
    instead — a pallas_call is shard-local by construction (it cannot
    emit collectives), so the psum accounting pinned here is the whole
    cross-device story for both lowerings."""
    from lightgbm_tpu.parallel import make_sharded_wave_fn

    rng = np.random.RandomState(0)
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    binned = rng.randint(0, B, size=(F, N)).astype(np.uint8)
    grad = rng.randn(N).astype(np.float32)
    hess = np.abs(rng.rand(N).astype(np.float32)) + 0.1
    mask = np.ones(N, np.float32)
    cmask = np.ones(F, bool)
    meta = FeatureMeta(
        num_bin=np.full(F, B, np.int32),
        missing_type=np.zeros(F, np.int32),
        default_bin=np.zeros(F, np.int32),
        penalty=np.ones(F, np.float32))
    gp = GrowParams(num_leaves=L, max_bin=B, hist_method="segment",
                    split=SplitParams(min_data_in_leaf=20))
    fn = make_sharded_wave_fn(mesh)
    # the builder adds data_axis itself (the production path)
    jitted = fn.build(gp, ())
    hlo = jitted.lower(jnp.asarray(binned), jnp.asarray(grad),
                       jnp.asarray(hess), jnp.asarray(mask),
                       jnp.asarray(cmask), meta).compile().as_text()
    n_ar, bytes_ar = all_reduce_stats(hlo)

    hist_bytes = expected_hist_bytes(L, F, B)
    assert bytes_ar >= hist_bytes, (bytes_ar, hist_bytes)
    assert bytes_ar <= 2 * hist_bytes, (bytes_ar, hist_bytes)
    assert n_ar <= 12, n_ar
