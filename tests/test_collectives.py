"""Multi-chip collective accounting regression gate.

The data-parallel wave engine's only cross-device traffic should be the
per-wave histogram psum of the COMPUTED (smaller-child) slots plus a few
scalar reductions (ref: data_parallel_tree_learner.cpp:284
ReduceScatter traffic model).  This test compiles the tree builder over
the 8-device virtual mesh and pins the all-reduce count and byte volume
so a change that starts reducing full-slot histograms (or sneaks a new
collective into the wave loop) fails loudly.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from lightgbm_tpu.learner import FeatureMeta, GrowParams, grow_tree_wave
from lightgbm_tpu.ops.split import SplitParams
from tools.collective_accounting import all_reduce_stats

N = 1 << 13
F = 8
B = 64
L = 31


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8-device mesh")
def test_wave_allreduce_count_and_volume():
    rng = np.random.RandomState(0)
    mesh = Mesh(np.array(jax.devices()[:8]), ("row",))
    shard = NamedSharding(mesh, P(None, "row"))
    repl = NamedSharding(mesh, P())
    rowsh = NamedSharding(mesh, P("row"))
    binned = jax.device_put(
        rng.randint(0, B, size=(F, N)).astype(np.uint8), shard)
    grad = jax.device_put(rng.randn(N).astype(np.float32), rowsh)
    hess = jax.device_put(np.abs(rng.rand(N).astype(np.float32)) + 0.1,
                          rowsh)
    mask = jax.device_put(np.ones(N, np.float32), rowsh)
    cmask = jax.device_put(np.ones(F, bool), repl)
    meta = FeatureMeta(
        num_bin=jax.device_put(np.full(F, B, np.int32), repl),
        missing_type=jax.device_put(np.zeros(F, np.int32), repl),
        default_bin=jax.device_put(np.zeros(F, np.int32), repl),
        penalty=jax.device_put(np.ones(F, np.float32), repl))
    gp = GrowParams(num_leaves=L, max_bin=B, hist_method="segment",
                    split=SplitParams(min_data_in_leaf=20))
    hlo = jax.jit(grow_tree_wave, static_argnames=("params",)).lower(
        binned, grad, hess, mask, cmask, meta, gp).compile().as_text()
    n_ar, bytes_ar = all_reduce_stats(hlo)

    # expected psum volume: one [Kb, F, B, 2] histogram (+ [Kb] counts)
    # per wave — Kb is the subtraction engine's computed-slot ladder —
    # plus one [Kb, F, B, 2]-shaped reduction for the while-loop wave and
    # small scalar reductions (root sums, final count matmul)
    from lightgbm_tpu.ops.histogram import wave_slot_pad
    import math
    num_waves = max(1, math.ceil(math.log2(L)))
    kbs = [wave_slot_pad(min(1 << max(k - 1, 0), L))
           for k in range(num_waves)] + [wave_slot_pad(max(L // 2, 1))]
    hist_bytes = sum(k * F * B * 2 * 4 for k in kbs)
    assert bytes_ar >= hist_bytes, (bytes_ar, hist_bytes)
    # regression bound: within 2x of the pure-histogram volume (scalar
    # side reductions are small) and a fixed op-count envelope
    assert bytes_ar <= 2 * hist_bytes, (bytes_ar, hist_bytes)
    assert n_ar <= 10, n_ar
