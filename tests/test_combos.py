"""Feature-combination smoke matrix (ref: the breadth strategy of
tests/python_package_test/test_engine.py — objectives x boosting modes x
sampling x constraints trained end-to-end).

Every combination trains a few rounds through the public API and must
produce finite predictions with non-trivial fit; combos that compose two
subsystems (e.g. DART x GOSS, RF x EFB, quantized x data-parallel) are
exactly where integration bugs hide."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=1200, seed=0, cat=False):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 5)
    if cat:
        X[:, 4] = rng.randint(0, 8, n)
        y = (X[:, 0] + 0.5 * np.isin(X[:, 4], [1, 3, 5])
             + 0.1 * rng.randn(n))
    else:
        y = X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.1 * rng.randn(n)
    return X, y


COMBOS = [
    ("dart_goss", {"boosting": "dart", "data_sample_strategy": "goss"}),
    ("dart_categorical", {"boosting": "dart",
                          "categorical_feature": [4]}, True),
    ("rf_efb", {"boosting": "rf", "bagging_freq": 1,
                "bagging_fraction": 0.7, "enable_bundle": True}),
    ("goss_monotone", {"data_sample_strategy": "goss",
                       "monotone_constraints": [1, 0, 0, 0, 0]}),
    ("goss_quantized", {"data_sample_strategy": "goss",
                        "use_quantized_grad": True}),
    ("quantized_data_parallel", {"use_quantized_grad": True,
                                 "tree_learner": "data"}),
    ("quantized_linear", {"use_quantized_grad": True, "linear_tree": True}),
    ("extra_monotone", {"extra_trees": True,
                        "monotone_constraints": [1, 0, 0, 0, 0]}),
    ("bagging_feature_fraction", {"bagging_freq": 1,
                                  "bagging_fraction": 0.6,
                                  "feature_fraction": 0.8}),
    ("cegb_goss", {"cegb_penalty_split": 1e-5,
                   "data_sample_strategy": "goss"}),
    ("linear_monotone", {"linear_tree": True,
                         "monotone_constraints": [1, 0, 0, 0, 0]}),
    ("dart_monotone_intermediate", {
        "boosting": "dart", "monotone_constraints": [1, 0, 0, 0, 0],
        "monotone_constraints_method": "intermediate"}),
    ("voting_goss", {"tree_learner": "voting", "top_k": 3,
                     "data_sample_strategy": "goss"}),
    ("feature_parallel_categorical", {"tree_learner": "feature",
                                      "categorical_feature": [4]}, True),
    ("path_smooth_bynode", {"path_smooth": 1.0,
                            "feature_fraction_bynode": 0.8}),
    ("maxdepth_interaction", {
        "max_depth": 3,
        "interaction_constraints": "[0,1,2],[2,3,4]"}),
    ("l1_max_delta", {"lambda_l1": 0.5, "max_delta_step": 0.5}),
    ("quantized_monotone", {"use_quantized_grad": True,
                            "monotone_constraints": [1, 0, 0, 0, 0]}),
    ("efb_categorical", {"enable_bundle": True,
                         "categorical_feature": [4]}, True),
    ("dart_linear", {"boosting": "dart", "linear_tree": True}),
    ("goss_intermediate_monotone", {
        "data_sample_strategy": "goss",
        "monotone_constraints": [1, 0, 0, 0, 0],
        "monotone_constraints_method": "intermediate"}),
    ("rf_categorical", {"boosting": "rf", "bagging_freq": 1,
                        "bagging_fraction": 0.7,
                        "categorical_feature": [4]}, True),
    ("quantized_extra_trees", {"use_quantized_grad": True,
                               "extra_trees": True}),
]


@pytest.mark.parametrize(
    "combo", COMBOS, ids=[c[0] for c in COMBOS])
def test_combo_trains(combo):
    name, extra = combo[0], combo[1]
    use_cat = len(combo) > 2 and combo[2]
    X, y = _data(cat=use_cat)
    params = {"objective": "regression", "num_leaves": 15,
              "verbosity": -1, "min_data_in_leaf": 5,
              "learning_rate": 0.3, **extra}
    booster = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    pred = booster.predict(X)
    assert np.isfinite(pred).all(), name
    corr = float(np.corrcoef(pred, y)[0, 1])
    assert corr > 0.5, (name, corr)
    # model text round-trips
    b2 = lgb.Booster(model_str=booster.model_to_string())
    np.testing.assert_allclose(b2.predict(X[:100]), pred[:100], rtol=1e-5)
