"""Regression tests for the ISSUE-9 concurrency sweep.

tpulint v3's signal-safety / lockset / atomic-write families flagged
real hazards in the reliability stack; each fix here gets a behavioral
pin:

* the SIGTERM flush and the stall watchdog's exit path used to route
  their terminal event through the AsyncWriter — a blocking `put` on a
  bounded queue whose worker may be exactly what is hung.  Both now go
  through `emit_event_sync` (private O_APPEND handle): the subprocess
  drills wedge the worker, FILL the queue, and require the process to
  still die promptly with the terminal record on disk;
* `CheckpointManager._write` runs on the writer thread in async mode
  and on the training thread for `save_now` (preemption): the
  generations read-modify-write is now serialized by `_gen_lock`, so
  concurrent writers cannot lose a generation from the manifest;
* `RunGuard.tick` state shared with the watchdog thread is under
  `_state_lock`;
* tombstones are written atomically (`faults.write_tombstone`).

No jax needed: everything here is host-side.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from lightgbm_tpu.observability.events import (EventLogger,  # noqa: E402
                                               set_event_logger)
from lightgbm_tpu.observability.hostio import AsyncWriter  # noqa: E402


def _read_events(tmp_path, rank=0):
    p = tmp_path / f"events-rank{rank}.jsonl"
    if not p.exists():
        return []
    return [json.loads(ln) for ln in p.read_text().splitlines()]


def _wedge_and_fill(writer, maxq):
    """Park the worker on an Event nobody sets, then fill the queue."""
    gate = threading.Event()
    writer.submit(gate.wait)
    deadline = time.monotonic() + 5.0
    while writer.pending < maxq and time.monotonic() < deadline:
        # the worker may not have dequeued the gate task yet
        try:
            writer._q.put_nowait((lambda: None, (), {}))
        except Exception:
            time.sleep(0.01)
    return gate


# ----------------------------------------------------------- emit_sync
def test_emit_sync_bypasses_wedged_writer(tmp_path):
    """emit_sync must return promptly and land its record even when the
    AsyncWriter worker is wedged and the bounded queue is FULL — the
    state in which the old emit_event path blocked forever on put()."""
    w = AsyncWriter(max_queue=1)
    lg = EventLogger(str(tmp_path), rank=0, writer=w)
    gate = _wedge_and_fill(w, 1)
    try:
        t0 = time.monotonic()
        lg.emit_sync("stall", silent_s=1.5)
        elapsed = time.monotonic() - t0
        assert elapsed < 1.0, f"emit_sync blocked {elapsed:.1f}s"
        events = [r["event"] for r in _read_events(tmp_path)]
        assert "stall" in events
        assert lg.last_record["event"] == "stall"
    finally:
        gate.set()
        w.close()
        lg.close()


def test_emit_sync_no_writer(tmp_path):
    lg = EventLogger(str(tmp_path), rank=0)
    lg.emit("iteration", iteration=0)
    lg.emit_sync("sigterm", pid=123)
    lg.close()
    events = [r["event"] for r in _read_events(tmp_path)]
    assert events == ["iteration", "sigterm"]


# ---------------------------------------------------- SIGTERM drill
@pytest.mark.skipif(not hasattr(signal, "SIGTERM"), reason="no SIGTERM")
def test_sigterm_exits_promptly_with_wedged_writer(tmp_path):
    """The preemption-notice handler must never block on the writer
    queue: with the worker wedged and the queue full, SIGTERM still
    kills the process within the bounded flush window and the terminal
    `sigterm` record is on disk.  Before the emit_event_sync fix this
    drill deadlocked in queue.put and timed out."""
    code = f"""
import os, signal, sys, threading, time
sys.path.insert(0, {_REPO!r})
from lightgbm_tpu.observability.hostio import AsyncWriter, \\
    install_sigterm_flush
from lightgbm_tpu.observability.events import EventLogger, \\
    set_event_logger
from lightgbm_tpu.observability import hostio
hostio.TERMINAL_FLUSH_TIMEOUT_S = 0.5   # shorten the drill's wait

w = AsyncWriter(max_queue=1)
lg = EventLogger({str(tmp_path)!r}, rank=0, writer=w)
set_event_logger(lg)
assert install_sigterm_flush()
gate = threading.Event()
w.submit(gate.wait)                      # wedge the worker
deadline = time.monotonic() + 5.0
while time.monotonic() < deadline:       # fill the bounded queue
    try:
        w._q.put_nowait((lambda: None, (), {{}}))
    except Exception:
        break
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(60)                           # never reached
"""
    t0 = time.monotonic()
    res = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=45)
    elapsed = time.monotonic() - t0
    assert res.returncode in (-signal.SIGTERM, 128 + signal.SIGTERM), \
        f"rc={res.returncode}\n{res.stderr}"
    # bounded: the 5 s flush timeout plus generous slack, nowhere near
    # the 60 s sleep (or the 45 s subprocess cap) a deadlock would eat
    assert elapsed < 30, f"SIGTERM handling took {elapsed:.1f}s"
    events = [r["event"] for r in _read_events(tmp_path)]
    assert events[-1] == "sigterm", events[-5:]


@pytest.mark.skipif(not hasattr(signal, "SIGTERM"), reason="no SIGTERM")
def test_stall_exit_path_with_wedged_writer(tmp_path):
    """Same contract for the watchdog's exit path: a tripped RunGuard
    with a wedged writer and a full queue must still write its stall
    diagnosis, emit the terminal `stall` record synchronously, and exit
    STALL_EXIT_CODE — not hang inside its own hang handler."""
    code = f"""
import os, sys, threading, time
sys.path.insert(0, {_REPO!r})
from lightgbm_tpu.observability.hostio import AsyncWriter
from lightgbm_tpu.observability.events import EventLogger, \\
    set_event_logger
from lightgbm_tpu.observability import hostio
from lightgbm_tpu.reliability.guard import RunGuard
hostio.TERMINAL_FLUSH_TIMEOUT_S = 0.5   # shorten the drill's wait

w = AsyncWriter(max_queue=1)
lg = EventLogger({str(tmp_path)!r}, rank=0, writer=w)
set_event_logger(lg)
gate = threading.Event()
w.submit(gate.wait)
deadline = time.monotonic() + 5.0
while time.monotonic() < deadline:
    try:
        w._q.put_nowait((lambda: None, (), {{}}))
    except Exception:
        break
g = RunGuard({str(tmp_path)!r}, rank=0, stall_floor_s=0.2,
             stall_factor=1.0, first_deadline_s=0.4, writer=w,
             poll_interval=0.05)
g.start()
time.sleep(60)                           # never reached: watchdog exits
"""
    res = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=45)
    from lightgbm_tpu.reliability.guard import STALL_EXIT_CODE
    assert res.returncode == STALL_EXIT_CODE, \
        f"rc={res.returncode}\n{res.stderr}"
    diag = json.loads((tmp_path / "stall-rank0.json").read_text())
    assert diag["kind"] == "stall" and diag["exit_code"] == STALL_EXIT_CODE
    events = [r["event"] for r in _read_events(tmp_path)]
    assert "stall" in events, events


# ------------------------------------------------ checkpoint gen lock
class _FakeBooster:
    def __init__(self, tag="t"):
        self.tag = tag

    def model_to_string(self, num_iteration=None, **kw):
        return f"tree_{self.tag}_{num_iteration}\n"


def test_checkpoint_generations_survive_concurrent_writers(tmp_path):
    """Hammer `_write` from two threads with distinct iterations: the
    `_gen_lock` serialization must keep EVERY generation in the
    manifest.  Without the lock the read-modify-write of
    `_generations` loses entries (exactly the async-save vs
    preemption-save_now race)."""
    from lightgbm_tpu.reliability.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path), keep_last=64,
                            params={"a": 1})
    start = threading.Barrier(2)
    errs = []

    def writer(base):
        try:
            start.wait(timeout=10)
            for i in range(20):
                mgr._write(base + i, f"tree {base + i}\n", None, None)
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=writer, args=(b,))
          for b in (100, 200)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not errs
    manifest = json.loads((tmp_path / "manifest.json").read_text())
    its = sorted(int(g["iteration"]) for g in manifest["generations"])
    assert its == sorted(list(range(100, 120)) + list(range(200, 220)))


def test_checkpoint_async_save_and_save_now_both_land(tmp_path):
    """A queued async save plus an out-of-band save_now (the preemption
    shape) must BOTH end up in the manifest, in iteration order."""
    from lightgbm_tpu.reliability.checkpoint import CheckpointManager
    w = AsyncWriter()
    mgr = CheckpointManager(str(tmp_path), keep_last=8, params={"a": 1},
                            writer=w)
    gate = threading.Event()
    w.submit(gate.wait)                 # hold the async save back
    mgr.save(_FakeBooster(), 5)         # queued behind the gate
    ck = mgr.save_now(_FakeBooster(), 6)   # synchronous, on this thread
    assert ck is not None and ck.iteration == 6
    gate.set()
    w.close()
    its = sorted(int(g["iteration"]) for g in mgr._generations)
    assert its == [5, 6]
    resumed = mgr.resumable({"a": 1})
    assert resumed is not None and resumed.iteration == 6


# ------------------------------------------------- RunGuard state lock
def test_runguard_tick_is_thread_safe(tmp_path):
    """Two threads hammering tick() while the watchdog polls at 100 Hz:
    no trip, no exception, and the rolling median stays sane."""
    from lightgbm_tpu.reliability.guard import RunGuard
    g = RunGuard(str(tmp_path), rank=0, stall_floor_s=30.0,
                 poll_interval=0.01)
    g.start()
    errs = []

    def hammer():
        try:
            for i in range(400):
                g.tick(i)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=hammer) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    med = g.median_iter_s()
    tripped = g.tripped
    g.stop()
    assert not errs
    assert not tripped
    assert med is not None and med < 1.0


# ---------------------------------------------------- atomic tombstone
def test_tombstone_written_atomically(tmp_path):
    from lightgbm_tpu.reliability import faults
    faults.write_tombstone(str(tmp_path), 2, 8, "worker_lost at iter 3")
    p = faults.tombstone_path(str(tmp_path), 2, 8)
    assert open(p).read() == "worker_lost at iter 3\n"
    # no temp-file droppings: the write went through temp + os.replace
    assert os.listdir(tmp_path) == [os.path.basename(p)]


def test_sigterm_event_still_last_with_healthy_worker(tmp_path):
    """Ordering pin: with a HEALTHY worker the terminal record must
    still be the log's last line — the bounded flush drains the queue
    before emit_event_sync appends `sigterm`."""
    code = f"""
import os, signal, sys, time
sys.path.insert(0, {_REPO!r})
from lightgbm_tpu.observability.hostio import AsyncWriter, \\
    install_sigterm_flush
from lightgbm_tpu.observability.events import EventLogger, \\
    set_event_logger
w = AsyncWriter()
lg = EventLogger({str(tmp_path)!r}, rank=0, writer=w)
set_event_logger(lg)
assert install_sigterm_flush()
for i in range(50):
    lg.emit("iteration", iteration=i)
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(60)
"""
    res = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=45)
    assert res.returncode in (-signal.SIGTERM, 128 + signal.SIGTERM), \
        f"rc={res.returncode}\n{res.stderr}"
    recs = _read_events(tmp_path)
    its = [r["iteration"] for r in recs if r["event"] == "iteration"]
    assert its == list(range(50))
    assert recs[-1]["event"] == "sigterm"


def test_event_log_rotation_serialized_with_sync_emit(tmp_path):
    """Rotation (writer thread) vs emit_sync (main): no lost lines, no
    interleaved half-records, across a rotation boundary."""
    w = AsyncWriter()
    lg = EventLogger(str(tmp_path), rank=0, rotate_mb=0.0005, writer=w)
    set_event_logger(lg)
    try:
        for i in range(200):
            lg.emit("iteration", iteration=i, pad="x" * 32)
            if i % 50 == 0:
                lg.emit_sync("marker", i=i)
        w.flush()
    finally:
        set_event_logger(None)
        w.close()
        lg.close()
    recs = []
    for name in sorted(os.listdir(tmp_path)):
        for ln in (tmp_path / name).read_text().splitlines():
            recs.append(json.loads(ln))  # every line parses whole
    its = sorted(r["iteration"] for r in recs if r["event"] == "iteration")
    assert its == list(range(200))
    assert sum(1 for r in recs if r["event"] == "marker") == 4
