import pytest

from lightgbm_tpu.config import Config, alias_table, kv2map, read_config_file


def test_defaults():
    cfg = Config()
    assert cfg.num_iterations == 100
    assert cfg.learning_rate == 0.1
    assert cfg.num_leaves == 31
    assert cfg.max_bin == 255
    assert cfg.objective == "regression"


def test_aliases_normalize():
    cfg = Config({"n_estimators": 7, "eta": 0.3, "min_child_samples": 5,
                  "reg_lambda": 1.5, "subsample": 0.8})
    assert cfg.num_iterations == 7
    assert cfg.learning_rate == 0.3
    assert cfg.min_data_in_leaf == 5
    assert cfg.lambda_l2 == 1.5
    assert cfg.bagging_fraction == 0.8


def test_alias_table_contains_reference_aliases():
    at = alias_table()
    assert at["num_boost_round"] == "num_iterations"
    assert at["shrinkage_rate"] == "learning_rate"
    assert at["query"] == "group_column"
    assert at["unbalanced_sets"] == "is_unbalance"


def test_kv_strings_first_wins():
    m = kv2map(["a=1", "a=2", "b=3"])
    assert m == {"a": "1", "b": "3"}


def test_objective_normalization():
    assert Config({"objective": "mse"}).objective == "regression"
    assert Config({"objective": "mae"}).objective == "regression_l1"
    assert Config({"objective": "softmax", "num_class": 3}).objective == "multiclass"
    assert Config({"objective": "xendcg"}).objective == "rank_xendcg"


def test_boosting_goss_alias():
    cfg = Config({"boosting": "goss"})
    assert cfg.boosting == "gbdt"
    assert cfg.data_sample_strategy == "goss"


def test_type_coercion_from_strings():
    cfg = Config(["num_leaves=63", "learning_rate=0.05", "feature_fraction=0.9",
                  "is_unbalance=true"])
    assert cfg.num_leaves == 63
    assert cfg.learning_rate == 0.05
    assert cfg.is_unbalance is True


def test_config_file_parsing(tmp_path):
    p = tmp_path / "train.conf"
    p.write_text("task = train\nobjective = binary\n# comment\nnum_trees = 12\n")
    m = read_config_file(str(p))
    cfg = Config(m)
    assert cfg.task == "train"
    assert cfg.objective == "binary"
    assert cfg.num_iterations == 12


def test_parameters_doc_in_sync():
    """docs/Parameters.md is generated from PARAMS (the reference keeps
    Parameters.rst generated from config.h the same way); a stale doc is
    a test failure, mirroring the reference's parameter-generator CI."""
    import subprocess, sys, os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "gen_params_doc.py"),
         "--check"], capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
