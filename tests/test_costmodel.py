"""Compiled-cost roofline accounting (observability/costmodel.py): the
harvest path against real jitted programs, signature keying shared with
the RecompileDetector, roofline classification math, and the
per-iteration delta plumbing record_metrics uses."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from lightgbm_tpu.observability.costmodel import (CostModel, backend_peaks,
                                                  global_cost_model,
                                                  group_of, roofline)
from lightgbm_tpu.observability.watchdog import RecompileDetector


@pytest.fixture()
def cost_model_off():
    """Every test leaves the process-wide model exactly as it found it."""
    prev = global_cost_model.enabled
    global_cost_model.enabled = False
    yield
    global_cost_model.enabled = prev


def test_group_of_folds_bucket_entries():
    assert group_of("device_predict[convert@4096]") == "device_predict"
    assert group_of("grow_tree") == "grow_tree"


def test_roofline_classification_and_mfu(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_PEAK_FLOPS", "100.0")
    monkeypatch.setenv("LGBM_TPU_PEAK_BYTES_PER_S", "10.0")
    # ridge = 10 flops/byte; below it -> hbm-bound, above -> compute
    lo = roofline(flops=50.0, bytes_accessed=10.0, seconds=1.0)
    assert lo["bound"] == "hbm" and lo["arithmetic_intensity"] == 5.0
    assert lo["mfu"] == 0.5 and lo["bw_util"] == 1.0
    hi = roofline(flops=500.0, bytes_accessed=10.0, seconds=2.0)
    assert hi["bound"] == "compute"
    assert hi["mfu"] == 2.5  # 500/2/100 — over "peak" only because the
    # peaks are synthetic; the math is what's pinned
    z = roofline(flops=0.0, bytes_accessed=0.0, seconds=0.0)
    assert z["bound"] == "unknown" and z["mfu"] is None


def test_backend_peaks_env_override(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_PEAK_FLOPS", "123.0")
    monkeypatch.setenv("LGBM_TPU_PEAK_BYTES_PER_S", "7.0")
    assert backend_peaks("tpu") == (123.0, 7.0)
    monkeypatch.setenv("LGBM_TPU_PEAK_FLOPS", "nonsense")
    flops, _bw = backend_peaks("tpu")
    assert flops == 197e12  # malformed override ignored, table wins


def test_harvest_real_jit_and_accumulate(cost_model_off):
    cm = CostModel()
    cm.enabled = True
    fn = jax.jit(lambda a, b: a @ b)
    x = jnp.ones((64, 32), jnp.float32)
    y = jnp.ones((32, 16), jnp.float32)
    sig = (("f32[64,32]", "f32[32,16]"), ())
    cm.observe("matmul", sig, fn, (x, y), {})
    cm.observe("matmul", sig, fn, (x, y), {})
    snap = cm.snapshot()
    assert snap["matmul"]["calls"] == 2
    assert snap["matmul"]["unharvested"] == 0
    # one matmul = 2*M*N*K flops; two calls accumulated
    assert snap["matmul"]["flops"] == pytest.approx(2 * 2 * 64 * 32 * 16)
    assert snap["matmul"]["bytes"] > 0
    assert cm.per_call("matmul") is not None
    assert cm.signatures_harvested() == 1


def test_unharvestable_entry_counts_calls(cost_model_off):
    cm = CostModel()
    cm.enabled = True
    cm.observe("plain", ("sig",), lambda x: x, (1,), {})  # no .lower
    snap = cm.snapshot()
    assert snap["plain"]["calls"] == 1
    assert snap["plain"]["unharvested"] == 1
    assert cm.per_call("plain") is None


def test_recompile_detector_reports_when_enabled(cost_model_off):
    global_cost_model.reset()
    fn = RecompileDetector(jax.jit(lambda v: v * 2.0), "doubler")
    x = jnp.ones((8,), jnp.float32)
    fn(x)  # cost model off: nothing recorded
    assert "doubler" not in global_cost_model.snapshot()
    global_cost_model.enabled = True
    fn(x)
    fn(x)
    snap = global_cost_model.snapshot()
    assert snap["doubler"]["calls"] == 2
    global_cost_model.enabled = False
    global_cost_model.reset()


def test_phase_roofline_diffs_windows(monkeypatch, cost_model_off):
    monkeypatch.setenv("LGBM_TPU_PEAK_FLOPS", "1000.0")
    monkeypatch.setenv("LGBM_TPU_PEAK_BYTES_PER_S", "100.0")
    cm = CostModel()
    prev = {"grow_tree": {"flops": 100.0, "bytes": 10.0, "calls": 1}}
    cur = {"grow_tree": {"flops": 300.0, "bytes": 30.0, "calls": 3},
           "gradients": {"flops": 50.0, "bytes": 500.0, "calls": 1},
           "idle": {"flops": 9.0, "bytes": 9.0, "calls": 3}}
    prev["idle"] = dict(cur["idle"])  # no calls this window -> omitted
    phases = {"GBDT::grow_tree": 2.0, "GBDT::grow_tree::device": 1.0,
              "GBDT::gradients": 0.5}
    out = cm.phase_roofline(prev, cur, phases)
    assert set(out) == {"grow_tree", "gradients"}
    g = out["grow_tree"]
    # delta flops=200 over the ::device split (1.0 s), not the host scope
    assert g["calls"] == 2 and g["device_s"] == 1.0
    assert g["mfu"] == pytest.approx(200.0 / 1.0 / 1000.0)
    assert g["bound"] == "compute"  # ai=200/20=10 >= ridge 10
    gr = out["gradients"]
    # no ::device entry -> host-scope fallback
    assert gr["device_s"] == 0.5 and gr["bound"] == "hbm"


def test_training_iteration_events_carry_roofline(tmp_path):
    """End to end: a metrics run's iteration events include per-phase
    measured MFU for the grow and gradient programs."""
    import json

    rng = np.random.RandomState(3)
    X = rng.rand(300, 4)
    y = (X[:, 0] + X[:, 1] * X[:, 2]).astype(np.float64)
    d = str(tmp_path / "metrics")
    import lightgbm_tpu as lgb
    lgb.train({"objective": "regression", "num_leaves": 7,
               "verbosity": -1, "min_data_in_leaf": 5, "metrics_dir": d},
              lgb.Dataset(X, label=y), num_boost_round=3)
    evts = [json.loads(line)
            for line in open(tmp_path / "metrics" / "events-rank0.jsonl")]
    iters = [e for e in evts if e["event"] == "iteration"]
    assert len(iters) == 3
    rl = iters[-1].get("roofline")
    assert rl and "grow_tree" in rl and "gradients" in rl
    for entry in rl.values():
        assert entry["bound"] in ("compute", "hbm", "unknown")
        assert entry["flops"] >= 0 and entry["calls"] >= 1
    # the run restores the process-wide switch on exit
    assert global_cost_model.enabled is False


def test_roofline_param_off_omits_field(tmp_path):
    import json

    rng = np.random.RandomState(4)
    X = rng.rand(200, 4)
    y = X[:, 0].astype(np.float64)
    d = str(tmp_path / "metrics")
    import lightgbm_tpu as lgb
    lgb.train({"objective": "regression", "num_leaves": 7,
               "verbosity": -1, "min_data_in_leaf": 5, "metrics_dir": d,
               "roofline": False},
              lgb.Dataset(X, label=y), num_boost_round=2)
    evts = [json.loads(line)
            for line in open(tmp_path / "metrics" / "events-rank0.jsonl")]
    iters = [e for e in evts if e["event"] == "iteration"]
    assert iters and all("roofline" not in e for e in iters)
