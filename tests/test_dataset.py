import numpy as np
import pytest

from lightgbm_tpu.io.dataset import Dataset, load_dataset_from_file

BINARY_TRAIN = "/root/reference/examples/binary_classification/binary.train"


def _toy(n=500, f=5, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + rng.normal(scale=0.1, size=n) > 0).astype(np.float32)
    return X, y


def test_construct_from_arrays():
    X, y = _toy()
    ds = Dataset.construct_from_arrays(X, label=y, max_bin=32)
    assert ds.num_data == 500
    assert ds.num_features == 5
    assert ds.binned.shape == (5, 500)
    assert ds.binned.max() < 32
    np.testing.assert_allclose(ds.metadata.label, y)


def test_trivial_feature_dropped():
    X, y = _toy()
    X = np.concatenate([X, np.ones((len(X), 1))], axis=1)  # constant column
    ds = Dataset.construct_from_arrays(X, label=y, max_bin=32)
    assert ds.num_total_features == 6
    assert ds.num_features == 5
    assert ds.used_feature_map[5] == -1


def test_valid_aligned_with_reference():
    X, y = _toy()
    Xv, yv = _toy(seed=1)
    ds = Dataset.construct_from_arrays(X, label=y, max_bin=32)
    dv = ds.create_valid(Xv, label=yv)
    assert dv.bin_mappers is ds.bin_mappers
    # same value must bin identically in both datasets
    col = ds.bin_mappers[0].values_to_bins(Xv[:, 0])
    np.testing.assert_array_equal(dv.binned[0], col)


def test_copy_subrow():
    X, y = _toy()
    w = np.arange(len(y), dtype=np.float32)
    ds = Dataset.construct_from_arrays(X, label=y, weight=w, max_bin=32)
    idx = np.array([3, 10, 100])
    sub = ds.copy_subrow(idx)
    assert sub.num_data == 3
    np.testing.assert_array_equal(sub.binned, ds.binned[:, idx])
    np.testing.assert_allclose(sub.metadata.weight, w[idx])


def test_group_metadata():
    X, y = _toy(n=10)
    ds = Dataset.construct_from_arrays(X, label=y, group=[4, 6], max_bin=16)
    np.testing.assert_array_equal(ds.metadata.query_boundaries, [0, 4, 10])
    assert ds.metadata.num_queries == 2


def test_binary_save_load(tmp_path):
    X, y = _toy()
    ds = Dataset.construct_from_arrays(X, label=y, max_bin=32)
    path = str(tmp_path / "data.bin")
    ds.save_binary(path)
    ds2 = Dataset.load_binary(path)
    np.testing.assert_array_equal(ds.binned, ds2.binned)
    np.testing.assert_allclose(ds.metadata.label, ds2.metadata.label)
    assert ds2.bin_mappers[0].num_bin == ds.bin_mappers[0].num_bin


def test_load_reference_example_file():
    ds = load_dataset_from_file(BINARY_TRAIN)
    assert ds.num_data == 7000
    assert ds.num_total_features == 28
    assert set(np.unique(ds.metadata.label)) == {0.0, 1.0}
    # weight sidecar file should be auto-loaded (binary.train.weight exists)
    assert ds.metadata.weight is not None
    assert len(ds.metadata.weight) == 7000


def test_dataset_from_scipy_sparse():
    """CSR/CSC input (ref: LGBM_DatasetCreateFromCSR/CSC): densified into
    the binned tensors; EFB re-compresses exclusive sparse columns."""
    import scipy.sparse as sp
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    dense = rng.rand(800, 5) * (rng.rand(800, 5) < 0.3)
    y = dense[:, 0] + dense[:, 1]
    for mat in (sp.csr_matrix(dense), sp.csc_matrix(dense)):
        b = lgb.train({"objective": "regression", "num_leaves": 7,
                       "verbosity": -1, "min_data_in_leaf": 5},
                      lgb.Dataset(mat, label=y), num_boost_round=15)
        # predict accepts sparse input too (train-CSR/predict-CSR flow)
        np.testing.assert_allclose(b.predict(mat), b.predict(dense),
                                   rtol=1e-9)
        mse = float(np.mean((b.predict(dense) - y) ** 2))
        var = float(np.var(y))
        assert mse < 0.3 * var, (mse, var)
