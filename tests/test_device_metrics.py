"""Device-resident eval metrics (ops/metrics.py, ISSUE 5 tentpole).

Three layers:

* function-level parity — device_exact_auc / average_precision vs the
  host Metric classes over adversarial score vectors (NaN scores, exact
  ties, weights, degenerate all-pos/all-neg label sets);
* end-to-end parity — eval histories recorded with device eval (the
  default) vs the forced host path (`device_eval=false`) agree to f32
  summation rounding for every covered metric family, including
  weighted and multiclass runs;
* the host-boundary contract — an eval tick performs EXACTLY ONE
  device->host fetch (the packed vector), the host metric path is never
  entered, and the non-finite sentinel consumes the flags folded into
  the same fetch.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.callback import record_evaluation
from lightgbm_tpu.config import Config
from lightgbm_tpu.metric import AUCMetric, AveragePrecisionMetric
from lightgbm_tpu.ops.metrics import (device_exact_auc,
                                      device_exact_average_precision)


class _Meta:
    query_boundaries = None

    def __init__(self, label, weight=None):
        self.label = label
        self.weight = weight


def _host_metric(cls, label, weight, score):
    m = cls(Config({}))
    m.init(_Meta(label, weight), len(label))
    return m.eval(score, None)[0][1]


# ------------------------------------------------------- function parity
@pytest.mark.parametrize("case", ["plain", "weighted", "ties", "nan",
                                  "all_pos", "all_neg"])
def test_exact_auc_and_ap_match_host(case):
    rng = np.random.RandomState(7)
    n = 500
    score = rng.randn(n)
    label = (rng.rand(n) < 0.4).astype(np.float64)
    weight = None
    if case == "weighted":
        weight = (rng.rand(n) * 3).astype(np.float64)
    elif case == "ties":
        score = np.round(score, 1)  # heavy exact-tie blocks
    elif case == "nan":
        score[rng.rand(n) < 0.1] = np.nan
    elif case == "all_pos":
        label[:] = 1.0
    elif case == "all_neg":
        label[:] = 0.0
    s32 = score.astype(np.float32)
    w32 = (np.ones(n, np.float32) if weight is None
           else weight.astype(np.float32))
    dev_auc = float(device_exact_auc(s32, label.astype(np.float32), w32))
    dev_ap = float(device_exact_average_precision(
        s32, label.astype(np.float32), w32))
    # host metrics sort the FLOAT32 scores too, so tie blocks match
    host_auc = _host_metric(AUCMetric, label, weight,
                            s32.astype(np.float64))
    host_ap = _host_metric(AveragePrecisionMetric, label, weight,
                           s32.astype(np.float64))
    np.testing.assert_allclose(dev_auc, host_auc, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(dev_ap, host_ap, rtol=2e-5, atol=2e-6)


# ----------------------------------------------------- end-to-end parity
def _histories(params, X, y, rounds=5, weight=None):
    out = []
    for device_eval in ("auto", "false"):
        hist = {}
        p = dict(params, device_eval=device_eval,
                 is_provide_training_metric=True, verbosity=-1)
        lgb.train(p, lgb.Dataset(X, label=y, weight=weight),
                  num_boost_round=rounds,
                  callbacks=[record_evaluation(hist)])
        out.append(hist.get("training", {}))
    dev, host = out
    assert set(dev) == set(host) and dev, (dev, host)
    return dev, host


def _assert_close(dev, host, rtol=2e-4):
    for metric in host:
        np.testing.assert_allclose(np.asarray(dev[metric]),
                                   np.asarray(host[metric]),
                                   rtol=rtol, atol=1e-5, err_msg=metric)


def _xy(n=800, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 6)
    y = X[:, 0] + 0.2 * rng.randn(n)
    return X, y, rng


def test_regression_metrics_parity():
    X, y, rng = _xy()
    dev, host = _histories(
        {"objective": "regression", "num_leaves": 7,
         "metric": ["l2", "rmse", "l1", "quantile", "huber", "fair",
                    "mape"]}, X, y)
    _assert_close(dev, host)


def test_positive_regression_metrics_parity():
    X, y, rng = _xy(seed=3)
    y = np.abs(y) + 0.1
    dev, host = _histories(
        {"objective": "poisson", "num_leaves": 7,
         "metric": ["poisson", "gamma", "gamma_deviance", "tweedie"]},
        X, y)
    _assert_close(dev, host)


def test_binary_metrics_weighted_parity():
    X, y, rng = _xy(seed=5)
    yb = (y > 0).astype(np.float64)
    w = rng.rand(len(y)) * 2 + 0.25
    dev, host = _histories(
        {"objective": "binary", "num_leaves": 7,
         "metric": ["binary_logloss", "binary_error", "auc",
                    "average_precision"]}, X, yb, weight=w)
    _assert_close(dev, host)


def test_multiclass_metrics_parity():
    X, y, rng = _xy(seed=8)
    yc = rng.randint(0, 3, len(y)).astype(np.float64)
    dev, host = _histories(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
         "metric": ["multi_logloss", "multi_error"]}, X, yc)
    _assert_close(dev, host)


def test_xentropy_metrics_parity():
    X, y, rng = _xy(seed=11)
    yp = 1.0 / (1.0 + np.exp(-y))          # labels in [0, 1]
    dev, host = _histories(
        {"objective": "cross_entropy", "num_leaves": 7,
         "metric": ["cross_entropy", "kullback_leibler"]}, X, yp)
    _assert_close(dev, host)


def test_uncovered_metric_falls_back_to_host():
    """auc_mu has no single-process device form: the whole metric set
    keeps the host path (all-or-nothing gate, no partial fetch)."""
    X, y, rng = _xy(seed=13)
    yc = rng.randint(0, 3, len(y)).astype(np.float64)
    p = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
         "metric": ["multi_logloss", "auc_mu"], "verbosity": -1}
    b = lgb.train(p, lgb.Dataset(X, label=yc), num_boost_round=2)
    res = dict(b._gbdt.eval_train())
    assert "auc_mu" in res and "multi_logloss" in res
    assert b._gbdt._device_eval is not None
    assert not b._gbdt._device_eval.ok


# ------------------------------------------------- host-boundary contract
def test_eval_tick_is_one_fetch(monkeypatch):
    X, y, _ = _xy(seed=17)
    yb = (y > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
         "metric": ["binary_logloss", "auc"]}
    b = lgb.train(p, lgb.Dataset(X, label=yb), num_boost_round=3)
    g = b._gbdt
    first = g.eval_train()          # builds the evaluator
    de = g._device_eval
    assert de is not None and de.ok
    assert dict(first)["binary_logloss"] > 0
    # the host metric path must never run during a device eval tick
    monkeypatch.setattr(
        type(g), "_eval",
        lambda *a, **k: pytest.fail("host metric path entered"))
    before = de.fetches
    evals = g.eval_train()
    assert de.fetches == before + 1          # exactly one packed D2H
    assert len(evals) == 2
    # the sentinel flags rode the SAME fetch: consuming them costs no
    # further sync (run() would bump the counter; the flag fold doesn't)
    assert g._finite_cache is not None
    assert g.gradients_finite() and g.scores_finite()
    assert de.fetches == before + 1


def test_sentinel_consumes_device_flags(monkeypatch):
    """NaN gradients still raise through the packed-flag path."""
    from lightgbm_tpu.reliability import faults
    X, y, _ = _xy(seed=19)
    yb = (y > 0).astype(np.float64)
    monkeypatch.setenv("LGBM_TPU_FAULT", "nan_grad@2")
    faults.reload()
    try:
        with pytest.raises(lgb.LightGBMError, match="[Nn]on-finite"):
            lgb.train({"objective": "binary", "num_leaves": 7,
                       "verbosity": -1, "nonfinite_check_freq": 1,
                       "metric": "binary_logloss",
                       "is_provide_training_metric": True},
                      lgb.Dataset(X, label=yb), num_boost_round=5)
    finally:
        monkeypatch.delenv("LGBM_TPU_FAULT")
        faults.reload()
