"""Parity suite for the TPU-resident inference path (docs/Inference.md).

Three predictors must agree on the same model:
  * DevicePredictor (jitted tensor traversal, float32)
  * native PackedPredictor (predict.c, float64, the serving reference)
  * Tree.predict (models/tree.py, float64, the semantic source of truth)

For float32 inputs the device ROUTING (leaf indices) must be bit-identical
across the whole parity matrix — NaN missing values, zero-as-missing,
categorical bitset splits, multiclass K>1 and RF output averaging; raw
scores differ from the float64 host sums only by float32 summation
rounding.  float64 inputs must fall back to the host paths (gating test).
The recompile-watchdog test pins the bucketing contract: varying batch
sizes inside one bucket re-enter a single trace.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.inference import DevicePredictor, pack_ensemble
from lightgbm_tpu.native import PackedPredictor, predictor_lib

# f32 leaf values, <=40 trees: per-tree rounding is ~1 ulp each
RTOL, ATOL = 2e-6, 2e-6


def _mk_xy(n, seed=0, cats=True):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 6).astype(np.float32)
    X[rng.rand(n) < 0.15, 0] = np.nan            # NaN missing
    X[:, 4] = np.where(rng.rand(n) < 0.3, 0.0, X[:, 4])  # zeros
    if cats:
        X[:, 5] = rng.randint(0, 12, n)          # categorical
    y = ((np.nan_to_num(X[:, 0]) + X[:, 1] > 0)
         | (X[:, 5] % 4 == 1)).astype(np.float32)
    return X, y


def _train(params, X, y, rounds=6, **dskw):
    p = dict(objective="binary", num_leaves=15, verbosity=-1, metric="none",
             min_data_in_leaf=5, device_predict="false")
    p.update(params)
    bst = lgb.train(p, lgb.Dataset(X, label=y, **dskw),
                    num_boost_round=rounds)
    bst._gbdt._sync_model()
    return bst


@pytest.fixture(scope="module")
def binary_cat():
    X, y = _mk_xy(1500)
    return _train({}, X, y, categorical_feature=[5]), X


@pytest.fixture(scope="module")
def multiclass():
    X, _ = _mk_xy(1200, seed=3, cats=False)
    y = np.random.RandomState(5).randint(0, 3, 1200).astype(np.float32)
    bst = _train({"objective": "multiclass", "num_class": 3,
                  "num_leaves": 8}, X, y, rounds=4)
    return bst, X


def _test_points(seed=9):
    """Adversarial evaluation points: NaN, exact zeros, out-of-range and
    negative categoricals, huge values."""
    X, _ = _mk_xy(400, seed=seed)
    X[:7, 5] = [-3, -0.5, 0, 31, 64, 1e7, 2.5e9]   # cat edge cases
    X[7, 2] = np.float32(1e30)
    X[8, 2] = -np.float32(1e30)
    X[9, 4] = np.float32(1e-36)                     # below zero threshold
    return X


def _device(bst, **kw):
    g = bst._gbdt
    dp = DevicePredictor(g.models_, num_class=g.num_tree_per_iteration,
                         average=g.average_output_,
                         convert=(g.objective.convert_output
                                  if g.objective is not None else None),
                         min_bucket=256, **kw)
    assert dp.ok
    return dp


def _tree_leaves(models, X64):
    return np.stack([t.get_leaf_index(X64) for t in models], axis=1)


# ------------------------------------------------------------------ routing
def test_leaf_routing_bit_exact_binary_cat(binary_cat):
    bst, X = binary_cat
    Xt = _test_points()
    dp = _device(bst)
    leaf_dev = dp.predict_leaf(Xt)
    X64 = np.asarray(Xt, np.float64)
    assert np.array_equal(leaf_dev, _tree_leaves(bst._gbdt.models_, X64))
    if predictor_lib() is not None:
        native = PackedPredictor(bst._gbdt.models_).predict_leaf(X64)
        assert np.array_equal(leaf_dev, native)


def test_leaf_routing_bit_exact_zero_as_missing():
    X, y = _mk_xy(1000, seed=11, cats=False)
    X = np.nan_to_num(X)  # zero_as_missing rejects NaN-style missing
    bst = _train({"zero_as_missing": True, "use_missing": True}, X, y)
    Xt = np.nan_to_num(_test_points(seed=12))
    Xt[:50, 4] = 0.0
    dp = _device(bst)
    assert np.array_equal(dp.predict_leaf(Xt),
                          _tree_leaves(bst._gbdt.models_,
                                       np.asarray(Xt, np.float64)))


def test_leaf_routing_bit_exact_multiclass(multiclass):
    bst, X = multiclass
    Xt = X[:300]
    dp = _device(bst)
    assert np.array_equal(dp.predict_leaf(Xt),
                          _tree_leaves(bst._gbdt.models_,
                                       np.asarray(Xt, np.float64)))


# ------------------------------------------------------------------- values
def test_raw_scores_match_host(binary_cat):
    bst, X = binary_cat
    Xt = _test_points()
    dp = _device(bst)
    raw_dev = dp.predict_raw(Xt)
    g = bst._gbdt
    raw_host = g._predict_raw_impl(np.asarray(Xt, np.float64), 0, -1,
                                   False, 10, 10.0)
    np.testing.assert_allclose(raw_dev, raw_host, rtol=RTOL, atol=ATOL)


def test_converted_predictions_fused_on_device(binary_cat):
    bst, X = binary_cat
    Xt = _test_points()
    dp = _device(bst)
    pred_dev = dp.predict(Xt)
    bst._gbdt.config.device_predict = "false"
    pred_host = bst.predict(Xt)
    np.testing.assert_allclose(pred_dev, pred_host, rtol=RTOL, atol=ATOL)
    assert (pred_dev >= 0).all() and (pred_dev <= 1).all()  # sigmoid fused


def test_multiclass_softmax_and_shapes(multiclass):
    bst, X = multiclass
    Xt = X[:200]
    dp = _device(bst)
    pred = dp.predict(Xt)
    assert pred.shape == (200, 3)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-5)
    bst._gbdt.config.device_predict = "false"
    np.testing.assert_allclose(pred, bst.predict(Xt), rtol=RTOL, atol=ATOL)


def test_average_output_rf():
    X, y = _mk_xy(1200, seed=21, cats=False)
    bst = _train({"boosting": "rf", "bagging_fraction": 0.7,
                  "bagging_freq": 1}, X, y, rounds=5)
    g = bst._gbdt
    assert g.average_output_
    dp = _device(bst)
    Xt = X[:250]
    assert np.array_equal(dp.predict_leaf(Xt),
                          _tree_leaves(g.models_, np.asarray(Xt, np.float64)))
    raw_host = g._predict_raw_impl(np.asarray(Xt, np.float64), 0, -1,
                                   False, 10, 10.0)
    np.testing.assert_allclose(dp.predict_raw(Xt), raw_host,
                               rtol=RTOL, atol=ATOL)


def test_loaded_model_round_trip(binary_cat):
    """Text-loaded models (no leaf_depth) pack and route identically."""
    bst, X = binary_cat
    loaded = lgb.Booster(model_str=bst.model_to_string())
    Xt = _test_points()
    g = loaded._gbdt
    g.config.device_predict = "true"
    hit = g._device_predictor(Xt, 0, -1)
    assert hit is not None
    dp, Xt32 = hit
    assert np.array_equal(dp.predict_leaf(Xt32),
                          _tree_leaves(g.models_, np.asarray(Xt, np.float64)))


# ------------------------------------------------------------------ routing gate
def test_float64_lossless_serves_device(binary_cat):
    """f32-round-trippable float64 (integral features, f32-sourced
    pipelines) is downcast and served by the device path — the ROADMAP'd
    Serving follow-up; routing stays bit-identical because the downcast
    is exact."""
    bst, X = binary_cat
    g = bst._gbdt
    g.config.device_predict = "true"
    try:
        X64 = np.asarray(_test_points(), np.float64)  # f32-sourced
        hit = g._device_predictor(X64, 0, -1)
        assert hit is not None
        assert hit[1].dtype == np.float32
        # end to end: lossless float64 equals the pure host reference
        pred64 = bst.predict(X64)
        g.config.device_predict = "false"
        np.testing.assert_allclose(pred64, bst.predict(X64),
                                   rtol=RTOL, atol=ATOL)
    finally:
        g.config.device_predict = "false"


def test_float64_lossy_falls_back_to_host(binary_cat):
    """float64 values that do NOT survive the f32 round trip keep the
    host path (the bit-exact routing argument needs float32 inputs)."""
    bst, X = binary_cat
    g = bst._gbdt
    g.config.device_predict = "true"
    try:
        X64 = np.asarray(_test_points(), np.float64)
        X64[0, 1] = 0.1          # not representable in float32
        assert g._device_predictor(X64, 0, -1) is None
        pred64 = bst.predict(X64)
        g.config.device_predict = "false"
        np.testing.assert_allclose(pred64, bst.predict(X64), rtol=0, atol=0)
    finally:
        g.config.device_predict = "false"


def test_pred_early_stop_device_matches_host(binary_cat):
    """Device early stopping (traverse.py masked accumulation scan) must
    reproduce the host path's SEMANTICS: rows whose margin clears the
    threshold at a round check keep their partial sum.  Scores agree to
    f32 accumulation rounding; a small margin must actually change the
    answer (rows stopped), a huge margin must stop nobody."""
    bst, X = binary_cat
    g = bst._gbdt
    Xt = _test_points()
    host_es = g._predict_raw_impl(np.asarray(Xt, np.float64), 0, -1,
                                  True, 2, 0.2)
    host_plain = g._predict_raw_impl(np.asarray(Xt, np.float64), 0, -1,
                                     False, 10, 10.0)
    assert not np.allclose(host_es, host_plain)  # es engaged host-side
    g.config.device_predict = "true"
    try:
        dev_es = g.predict_raw(Xt, pred_early_stop=True,
                               pred_early_stop_freq=2,
                               pred_early_stop_margin=0.2)
        np.testing.assert_allclose(dev_es, host_es, rtol=1e-5, atol=1e-5)
        dev_off = g.predict_raw(Xt, pred_early_stop=True,
                                pred_early_stop_freq=2,
                                pred_early_stop_margin=1e9)
        np.testing.assert_allclose(dev_off, host_plain,
                                   rtol=RTOL, atol=ATOL)
    finally:
        g.config.device_predict = "false"


def test_pred_early_stop_device_multiclass(multiclass):
    """Multiclass margin = top1 - top2 (prediction_early_stop.cpp)."""
    bst, X = multiclass
    g = bst._gbdt
    Xt = np.asarray(X[:200], np.float32)
    host_es = g._predict_raw_impl(np.asarray(Xt, np.float64), 0, -1,
                                  True, 2, 0.02)
    g.config.device_predict = "true"
    try:
        dev_es = g.predict_raw(Xt, pred_early_stop=True,
                               pred_early_stop_freq=2,
                               pred_early_stop_margin=0.02)
        np.testing.assert_allclose(dev_es, host_es, rtol=1e-5, atol=1e-5)
    finally:
        g.config.device_predict = "false"


def test_pred_early_stop_margin_sweep_no_retrace(binary_cat):
    """The margin rides as a traced f32 scalar: sweeping thresholds and
    batch sizes inside a bucket re-enters ONE compiled program."""
    bst, X = binary_cat
    g = bst._gbdt
    g.config.device_predict = "true"
    try:
        g.predict_raw(X[:40], pred_early_stop=True,
                      pred_early_stop_freq=3, pred_early_stop_margin=0.5)
        dp = g._device_pred[1]
        t0 = dp.total_traces()
        assert any("+es3" in m for (m, _, _) in dp._fns)
        for margin, n in ((0.1, 17), (2.0, 40), (7.5, 256)):
            g.predict_raw(X[:n], pred_early_stop=True,
                          pred_early_stop_freq=3,
                          pred_early_stop_margin=margin)
        assert dp.total_traces() == t0
    finally:
        g.config.device_predict = "false"


def test_dart_inplace_mutation_invalidates_device_cache():
    """DART re-weights OLD trees in place (drop/normalize); the cached
    DevicePredictor must repack so a mid-training model serves its
    CURRENT drop state, matching Booster.predict (ISSUE 10 satellite)."""
    X, y = _mk_xy(600, seed=21)
    bst = _train({"boosting": "dart", "drop_rate": 0.9, "skip_drop": 0.0,
                  "learning_rate": 0.3}, X, y, rounds=5)
    g = bst._gbdt
    g.config.device_predict = "true"
    try:
        Xt = np.asarray(X[:64], np.float32)
        before = g.predict_raw(Xt)
        g.pre_gradient_hook()          # drops trees: in-place -w flip
        assert g.drop_index_, "no drop fired; raise drop_rate"
        expected = np.zeros(len(Xt))
        for t in g.models_:            # semantic truth: current trees
            expected += t.predict(np.asarray(Xt, np.float64))
        after = g.predict_raw(Xt)
        np.testing.assert_allclose(after, expected, rtol=RTOL, atol=1e-5)
        assert not np.allclose(after, before)   # stale cache would match
    finally:
        g.config.device_predict = "false"


def test_linear_tree_pack_refuses():
    X, y = _mk_xy(600, seed=31, cats=False)
    X = np.nan_to_num(X)
    bst = _train({"linear_tree": True, "objective": "regression"}, X, y,
                 rounds=2)
    assert pack_ensemble(bst._gbdt.models_) is None
    g = bst._gbdt
    g.config.device_predict = "true"
    try:
        assert g._device_predictor(X[:10], 0, -1) is None  # dp.ok False
    finally:
        g.config.device_predict = "false"


def test_booster_predict_routes_device(binary_cat):
    """Booster.predict on float32 with device_predict=true serves from the
    device path (leaf ids identical, conversion fused)."""
    bst, X = binary_cat
    g = bst._gbdt
    Xt = _test_points()
    g.config.device_predict = "false"
    host_pred = bst.predict(Xt)
    host_leaf = bst.predict(Xt, pred_leaf=True)
    g.config.device_predict = "true"
    try:
        from lightgbm_tpu.utils.timer import global_timer
        was = global_timer.enabled
        global_timer.enabled = True
        global_timer.reset()
        dev_pred = bst.predict(Xt)
        dev_leaf = bst.predict(Xt, pred_leaf=True)
        scopes = [name for name, _, _ in global_timer.items()]
        global_timer.enabled = was
        global_timer.reset()
        assert "GBDT::predict_device" in scopes
        assert np.array_equal(dev_leaf, host_leaf)
        np.testing.assert_allclose(dev_pred, host_pred, rtol=RTOL, atol=ATOL)
    finally:
        g.config.device_predict = "false"


def test_eval_fresh_data_through_device(binary_cat):
    """The fresh-data eval path feeds float32 raw data to predict_raw, so
    a forced device config serves it (and the metric still matches)."""
    bst, X = binary_cat
    Xe, ye = _mk_xy(400, seed=41)
    g = bst._gbdt
    g.config.device_predict = "false"
    ref = lgb.Booster(model_str=bst.model_to_string())
    ref._gbdt.config.metric = ["auc"]
    host = ref.eval(lgb.Dataset(Xe, label=ye), "fresh")
    dev_bst = lgb.Booster(model_str=bst.model_to_string())
    dev_bst._gbdt.config.metric = ["auc"]
    dev_bst._gbdt.config.device_predict = "true"
    dev = dev_bst.eval(lgb.Dataset(Xe, label=ye), "fresh")
    assert host and dev
    assert host[0][1] == dev[0][1] == "auc"
    assert abs(host[0][2] - dev[0][2]) < 1e-6


# -------------------------------------------------------------- recompiles
def test_bucketing_zero_new_traces_within_bucket(binary_cat):
    bst, X = binary_cat
    dp = _device(bst)
    assert dp.bucket_rows(1) == 256 and dp.bucket_rows(256) == 256
    assert dp.bucket_rows(257) == 512 and dp.bucket_rows(1000) == 1024
    for n in (3, 50, 199, 255, 256):
        dp.predict_leaf(X[:n])
    # one bucket touched -> exactly one traced signature, one executable
    assert dp.num_traces("leaf") == 1
    (fn,) = [f for (m, _, _), f in dp._fns.items() if m == "leaf"]
    assert fn._cache_size() == 1
    # crossing the bucket boundary compiles exactly one more entry
    dp.predict_leaf(X[:300])
    dp.predict_leaf(X[:500])
    assert dp.num_traces("leaf") == 2


def test_raw_and_convert_share_routing(binary_cat):
    """convert mode must not add traces for the same buckets."""
    bst, X = binary_cat
    dp = _device(bst)
    for n in (10, 100, 10, 100):
        dp.predict(X[:n])
        dp.predict_raw(X[:n])
    assert dp.num_traces("convert") == 1
    assert dp.num_traces("raw") == 1


def test_mesh_sharded_offline_scoring(binary_cat):
    """Rows shard over the parallel/ mesh (conftest's 8 virtual CPU
    devices); results identical to the single-device program."""
    from lightgbm_tpu.parallel import make_mesh
    bst, X = binary_cat
    g = bst._gbdt
    dp = _device(bst, mesh=make_mesh(8))
    assert dp._min_bucket % 8 == 0  # buckets tile the mesh
    dp0 = _device(bst)
    Xt = X[:777]
    assert np.array_equal(dp.predict_leaf(Xt), dp0.predict_leaf(Xt))
    np.testing.assert_allclose(dp.predict(Xt), dp0.predict(Xt),
                               rtol=1e-6, atol=1e-7)


def test_model_slice_and_cache_invalidation(binary_cat):
    bst, X = binary_cat
    g = bst._gbdt
    Xt = _test_points()
    g.config.device_predict = "true"
    try:
        full = g.predict_raw(Xt)
        half = g.predict_raw(Xt, num_iteration=3)
        assert not np.allclose(full, half)
        g.config.device_predict = "false"
        host_half = g.predict_raw(np.asarray(Xt, np.float64),
                                  num_iteration=3)
        np.testing.assert_allclose(half, host_half, rtol=RTOL, atol=ATOL)
    finally:
        g.config.device_predict = "false"
