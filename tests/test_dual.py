"""CPU-vs-TPU training consistency gate (ref: tests/python_package_test/
test_dual.py — the reference compares CPU and CUDA learners the same way,
env-gated).

Set LIGHTGBM_TEST_DUAL_CPU_TPU=1 on a host with a real TPU attached.
Each backend trains in a subprocess (the backend choice is fixed at jax
init), and predictions must agree closely: the TPU engine (wave growth +
fused Pallas histograms, bf16 one-hot accumulation) against the CPU
engine (leaf-wise + XLA scatter histograms, fp32)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("LIGHTGBM_TEST_DUAL_CPU_TPU") != "1",
    reason="dual CPU/TPU gate disabled (set LIGHTGBM_TEST_DUAL_CPU_TPU=1)")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = r"""
import json, sys, os
sys.path.insert(0, os.environ["LGBT_REPO"])
import jax
platform, out_path = sys.argv[1], sys.argv[2]
if platform == "cpu":
    # the axon TPU plugin ignores the JAX_PLATFORMS env var; force it
    jax.config.update("jax_platforms", "cpu")
import numpy as np
import lightgbm_tpu as lgb
rng = np.random.RandomState(7)
n, F = 20000, 12
X = rng.rand(n, F)
logit = 3*(X[:,0]-0.5) + 2*X[:,1]*X[:,2] - X[:,3]
y = (rng.rand(n) < 1/(1+np.exp(-3*logit))).astype(np.float32)
b = lgb.train({"objective": "binary", "num_leaves": 63, "verbose": -1,
               "min_data_in_leaf": 20, "learning_rate": 0.1},
              lgb.Dataset(X, label=y), num_boost_round=10)
p = b.predict(X[:4000])
json.dump({"platform": platform, "backend": jax.default_backend(),
           "pred": p.tolist()}, open(out_path, "w"))
"""


def _run(platform: str, tmp_path):
    out = tmp_path / f"pred_{platform}.json"
    script = tmp_path / "dual.py"
    script.write_text(_SCRIPT)
    env = dict(os.environ)
    env["LGBT_REPO"] = _REPO
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    else:
        env.pop("JAX_PLATFORMS", None)
    r = subprocess.run([sys.executable, str(script), platform, str(out)],
                       capture_output=True, text=True, timeout=900, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.load(open(out))
    # the comparison is vacuous unless each run REALLY used its backend
    assert payload["backend"] == platform, payload["backend"]
    return np.asarray(payload["pred"])


def test_cpu_tpu_training_consistency(tmp_path):
    p_cpu = _run("cpu", tmp_path)
    p_tpu = _run("tpu", tmp_path)
    # engines differ (wave vs leaf-wise, bf16 vs fp32 accumulation), so
    # assert close agreement rather than bit equality — the reference's
    # dual gate likewise compares predictions within tolerance
    corr = float(np.corrcoef(p_cpu, p_tpu)[0, 1])
    assert corr > 0.995, corr
    assert float(np.mean(np.abs(p_cpu - p_tpu))) < 0.02
