"""Exclusive feature bundling (ref: feature_group.h:25; greedy bundling
in dataset.cpp FindGroups; FixHistogram dataset.h:759)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.bundle import build_bundled, plan_bundles


def _sparse_problem(n=4000, seed=12):
    """Three mutually exclusive LOW-CARDINALITY sparse features (the
    one-hot-encoding shape EFB exists for) + one dense feature."""
    rng = np.random.RandomState(seed)
    which = rng.randint(0, 3, n)          # exactly one sparse feature set
    X = np.zeros((n, 4))
    for j in range(3):
        m = which == j
        X[m, j] = rng.randint(1, 6, m.sum()) * 0.5   # 5 distinct values
    X[:, 3] = rng.randn(n)
    y = (X[:, 0] * 2 - X[:, 1] + 0.5 * X[:, 2] + 0.3 * X[:, 3]
         + 0.05 * rng.randn(n))
    return X, y


def test_plan_bundles_merges_exclusive_features():
    X, y = _sparse_problem()
    ds = lgb.Dataset(X, label=y)
    core = ds._core_or_construct()
    plan = plan_bundles(core.binned, core.bin_mappers, core.used_features)
    assert plan.effective
    assert plan.num_groups < core.num_features
    sizes = sorted(len(g) for g in plan.groups)
    assert sizes[-1] == 3  # the three exclusive features share a bundle
    bundled = build_bundled(core.binned, plan)
    assert bundled.shape[0] == plan.num_groups
    # decode invariant: every non-default row's code maps back to its bin
    for f in range(core.num_features):
        if not plan.in_bundle[f]:
            continue
        gi = plan.group_idx[f]
        nz = core.binned[f] != plan.zero_bin[f]
        local = bundled[gi].astype(int) - plan.offsets[f]
        m = core.bin_mappers[core.used_features[f]]
        dec = np.where((local >= 0) & (local < m.num_bin), local,
                       plan.zero_bin[f])
        # rows may lose to a conflicting member only if conflicts allowed
        np.testing.assert_array_equal(dec[nz], core.binned[f][nz])


@pytest.mark.parametrize("strategy", ["leafwise", "wave"])
def test_bundled_training_matches_unbundled(strategy):
    """EFB is a device-layout optimization: with zero allowed conflicts
    the trained model must match enable_bundle=false up to NEAR-TIE
    split choices — FixHistogram reconstructs each member's default bin
    by subtraction (dataset.h:759, same as the reference's most_freq_bin
    path), so gains differ at the ulp level and a split whose gain gap
    is below that noise may flip.  Structural equality is asserted
    per tree with a small flip budget; predictions must agree tightly
    regardless."""
    import re
    X, y = _sparse_problem()
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 5, "tpu_growth_strategy": strategy}
    b_on = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=8)
    b_off = lgb.train({**base, "enable_bundle": False},
                      lgb.Dataset(X, label=y), num_boost_round=8)
    assert b_on._gbdt.bundle_plan is not None
    assert b_off._gbdt.bundle_plan is None
    b_on._gbdt._sync_model()
    b_off._gbdt._sync_model()

    def tree_struct(t):
        return (tuple(np.asarray(t.split_feature_inner)),
                tuple(np.asarray(t.threshold_in_bin)),
                tuple(np.asarray(t.left_child)),
                tuple(np.asarray(t.right_child)))

    same = sum(tree_struct(a) == tree_struct(b) for a, b in
               zip(b_on._gbdt.models_, b_off._gbdt.models_))
    # the first tree sees constant gradients: no near-ties from score
    # noise, must match exactly; later trees may flip near-ties
    assert tree_struct(b_on._gbdt.models_[0]) == \
        tree_struct(b_off._gbdt.models_[0])
    assert same >= 6, f"only {same}/8 trees structurally identical"
    np.testing.assert_allclose(b_on.predict(X), b_off.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_dense_data_is_not_bundled():
    rng = np.random.RandomState(0)
    X = rng.randn(1000, 5)
    y = X[:, 0]
    b = lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1}, lgb.Dataset(X, label=y),
                  num_boost_round=2)
    assert b._gbdt.bundle_plan is None
