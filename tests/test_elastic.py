"""Elastic fault domain (ISSUE 8): checkpoint integrity + generation
fallback, preemption checkpoint-on-demand, and shrink-to-fit relaunch.

The seeded chaos matrix from the issue — {preempt mid-run, corrupt
newest checkpoint, permanent rank loss, rank loss + corruption
combined} — drilled on the 8-device CPU mesh the conftest provides.
Multi-process SPMD collectives do not run on this CPU backend (the
test_multiprocess probe), so the rank-loss drills exercise the REAL
supervisor/elastic relaunch machinery (`_train_distributed_in`:
processes, tombstones, shrink, events) with a lightweight worker body,
while the training-math halves (digest fallback byte-parity, preempt
resume byte-parity, shrunken-mesh metric parity) run in-process on the
8-device mesh.  An end-to-end 8->7 SPMD drill runs where a multi-process
backend exists (slow-marked; skipped on CPU-only containers).
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.parallel import reshard_plan, rows_of
from lightgbm_tpu.reliability import (WORKER_LOST_EXIT_CODE, ElasticPolicy,
                                      CheckpointManager, faults)
from lightgbm_tpu.reliability.elastic import GIVE_UP, RETRY, SHRINK
from lightgbm_tpu.reliability.guard import STALL_EXIT_CODE, classify_returncode
from lightgbm_tpu.reliability.supervisor import SuperviseResult, WorkerFailure

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PARAMS = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 5, "learning_rate": 0.2}
# the sharded-wave configuration of test_multichip_smoke: the drills
# must cover the MESH paths, not just the single-device engine
MESH_PARAMS = dict(PARAMS, tree_learner="data", tpu_growth_strategy="wave")


def _data(n=768, F=5, seed=11):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F)
    y = (2 * X[:, 0] + X[:, 1] * X[:, 2] + 0.1 * rng.randn(n))
    return X, y


@pytest.fixture(autouse=True)
def _clean_fault_env(monkeypatch):
    monkeypatch.delenv("LGBM_TPU_FAULT", raising=False)
    monkeypatch.delenv("LGBM_TPU_FAULT_CORRUPT", raising=False)
    faults.reload()
    yield
    faults.reload()


def _model_text(booster):
    return booster.model_to_string(num_iteration=-1)


def _events(path):
    if not os.path.exists(path):
        return []
    return [json.loads(ln) for ln in open(path) if ln.strip()]


# ------------------------------------------------------- reshard plan
def test_reshard_plan_covers_rows_exactly_once():
    for old_n, new_n, n in ((8, 7, 1000), (8, 4, 1024), (3, 2, 17),
                            (7, 8, 100), (5, 5, 50), (8, 1, 9)):
        plan = reshard_plan(old_n, new_n, n)
        segs = sorted((s.start, s.stop) for s in plan.segments)
        assert segs[0][0] == 0 and segs[-1][1] == n
        assert sum(b - a for a, b in segs) == n, "overlap or gap"
        for (a0, b0), (a1, b1) in zip(segs, segs[1:]):
            assert b0 == a1, "segments must tile contiguously"
        # every new rank's sources concatenate to exactly its block
        for nr in range(new_n):
            srcs = plan.sources_of(nr)
            lo, hi = rows_of(n, new_n, nr)
            assert srcs[0].start == lo and srcs[-1].stop == hi


def test_reshard_plan_identity_and_determinism():
    p = reshard_plan(8, 8, 640)
    assert p.moved_rows() == 0
    assert all(s.old_rank == s.new_rank for s in p.segments)
    # rank-independence: the plan is a pure function of three ints, so
    # any two processes (here: two calls) agree byte-for-byte
    a, b = reshard_plan(8, 7, 123457), reshard_plan(8, 7, 123457)
    assert a == b
    assert a.summary()["moved_rows"] == a.moved_rows()


# ------------------------------------------------- exit classification
def test_classify_preempt_and_lost():
    assert classify_returncode(143) == "preempt"   # SIGTERM via shell
    assert classify_returncode(-15) == "preempt"   # SIGTERM via Popen
    assert classify_returncode(WORKER_LOST_EXIT_CODE) == "lost"
    # the PR-7 table is unchanged
    assert classify_returncode(0) == "ok"
    assert classify_returncode(STALL_EXIT_CODE) == "hang"
    assert classify_returncode(None) == "hang"
    assert classify_returncode(17) == "crash"


def _result(*failures):
    return SuperviseResult(ok=False, timed_out=False,
                           failures=list(failures))


def _fail(rank, kind, rc=1):
    return WorkerFailure(rank, rc, "", kind=kind)


# ---------------------------------------------------- elastic policy
def test_policy_lost_rank_shrinks_immediately():
    p = ElasticPolicy(8, min_machines=1, rank_grace_s=3600)
    d = p.observe(_result(_fail(3, "lost", WORKER_LOST_EXIT_CODE)))
    assert d.action == SHRINK and d.num_machines == 7
    assert d.lost_ranks == [3]
    assert p.num_machines == 7


def test_policy_crash_streak_across_grace_shrinks():
    now = [0.0]
    p = ElasticPolicy(4, min_machines=1, rank_grace_s=10.0,
                      clock=lambda: now[0])
    assert p.observe(_result(_fail(2, "crash"))).action == RETRY
    now[0] = 5.0  # second failure inside the grace window: still retry
    assert p.observe(_result(_fail(2, "crash"))).action == RETRY
    now[0] = 12.0  # persisting past the window: permanently lost
    d = p.observe(_result(_fail(2, "hang")))
    assert d.action == SHRINK and d.num_machines == 3


def test_policy_alternating_ranks_and_preempt_never_shrink():
    now = [0.0]
    p = ElasticPolicy(4, min_machines=1, rank_grace_s=0.0,
                      clock=lambda: now[0])
    # alternating ranks: each failure resets the other's streak
    for t, rank in ((0, 0), (100, 1), (200, 0), (300, 1)):
        now[0] = t
        assert p.observe(_result(_fail(rank, "crash"))).action == RETRY
    # preemption is not rank damage
    for t in (400, 500, 600):
        now[0] = t
        assert p.observe(_result(_fail(2, "preempt", -15))).action == RETRY
    assert p.num_machines == 4


def test_policy_min_machines_floor_gives_up():
    p = ElasticPolicy(2, min_machines=2, rank_grace_s=0.0)
    d = p.observe(_result(_fail(1, "lost", WORKER_LOST_EXIT_CODE)))
    assert d.action == GIVE_UP
    assert "elastic_min_machines" in d.reason
    assert p.num_machines == 2


def test_supervise_result_classification_ranking():
    assert _result(_fail(0, "preempt"), _fail(1, "crash")
                   ).classification == "crash"
    assert _result(_fail(0, "lost"), _fail(1, "hang")
                   ).classification == "lost"
    assert _result(_fail(0, "preempt")).classification == "preempt"


# ------------------------------------- checkpoint integrity + fallback
def test_manifest_records_digests_for_every_generation(tmp_path):
    X, y = _data()
    ck = str(tmp_path / "ck")
    lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=8,
              checkpoint_dir=ck, checkpoint_freq=2)
    m = json.load(open(os.path.join(ck, "manifest.json")))
    assert m["format"] == 2
    assert m["num_rows"] == len(X)
    gens = m["generations"]
    assert [g["iteration"] for g in gens] == [4, 6, 8]
    mgr = CheckpointManager(ck, params=PARAMS)
    for g in gens:
        ok, detail = mgr._ck_from_entry(g).verify()
        assert ok, detail


def test_ckpt_corrupt_fallback_resumes_byte_identical(tmp_path, monkeypatch):
    """The acceptance drill: LGBM_TPU_FAULT=ckpt_corrupt@4 damages the
    newest checkpoint AFTER it lands; the resume quarantines it, falls
    back to generation N-1 with a ckpt_fallback event, and the finished
    run is byte-identical to an uninterrupted one.  Runs the sharded
    wave over the 8-device mesh — the production path."""
    X, y = _data()
    full = lgb.train(dict(MESH_PARAMS), lgb.Dataset(X, label=y),
                     num_boost_round=10)
    ck, mx = str(tmp_path / "ck"), str(tmp_path / "mx")
    monkeypatch.setenv("LGBM_TPU_FAULT", "ckpt_corrupt@4")
    faults.reload()
    lgb.train(dict(MESH_PARAMS), lgb.Dataset(X, label=y), num_boost_round=4,
              checkpoint_dir=ck, checkpoint_freq=1)
    monkeypatch.delenv("LGBM_TPU_FAULT")
    faults.reload()
    resumed = lgb.train(dict(MESH_PARAMS), lgb.Dataset(X, label=y),
                        num_boost_round=10, checkpoint_dir=ck,
                        checkpoint_freq=1, metrics_dir=mx)
    assert _model_text(resumed) == _model_text(full)
    # the damaged generation was quarantined, not deleted
    assert glob.glob(os.path.join(ck, "ckpt_0000004.*.corrupt-*"))
    evs = _events(os.path.join(mx, "events-rank0.jsonl"))
    fb = [e for e in evs if e["event"] == "ckpt_fallback"]
    assert len(fb) == 1 and fb[0]["from_iteration"] == 4 \
        and fb[0]["to_iteration"] == 3
    # every surviving generation still verifies
    m = json.load(open(os.path.join(ck, "manifest.json")))
    mgr = CheckpointManager(ck, params=MESH_PARAMS)
    for g in m["generations"]:
        ok, detail = mgr._ck_from_entry(g).verify()
        assert ok, detail


def test_ckpt_corrupt_bitflip_state_detected(tmp_path, monkeypatch):
    """A single flipped byte in the state npz — silent score corruption
    without digests — must also fall back, not resume into garbage."""
    X, y = _data(n=400)
    ck = str(tmp_path / "ck")
    monkeypatch.setenv("LGBM_TPU_FAULT", "ckpt_corrupt@5")
    monkeypatch.setenv("LGBM_TPU_FAULT_CORRUPT", "bitflip")
    faults.reload()
    lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=5,
              checkpoint_dir=ck, checkpoint_freq=1)
    monkeypatch.delenv("LGBM_TPU_FAULT")
    faults.reload()
    mgr = CheckpointManager(ck, params=PARAMS)
    ck_obj = mgr.resumable(PARAMS)
    assert ck_obj is not None and ck_obj.iteration == 4
    assert glob.glob(os.path.join(ck, "ckpt_0000005.npz.corrupt-*"))


def test_corrupt_all_generations_starts_over(tmp_path, monkeypatch):
    X, y = _data(n=400)
    ck = str(tmp_path / "ck")
    lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=4,
              checkpoint_dir=ck, checkpoint_freq=2)
    for p in glob.glob(os.path.join(ck, "ckpt_*.txt")):
        with open(p, "r+b") as f:
            f.truncate(64)
    mgr = CheckpointManager(ck, params=PARAMS)
    assert mgr.resumable(PARAMS) is None
    # resume=True on a fully-corrupt dir trains from scratch, rc=0
    b = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=2,
                  checkpoint_dir=ck, checkpoint_freq=2)
    assert b.current_iteration() == 2


# --------------------------------------------- DART byte-exact resume
def test_dart_resume_byte_identical(tmp_path):
    """Carried-over PR-1 follow-up: boosting=dart resume is now
    byte-identical like GBDT (drop RNG + normalization counters + the
    full-precision shrinkage/internal_value the %g model text loses)."""
    X, y = _data(n=500)
    P = dict(PARAMS, boosting="dart", drop_rate=0.5, skip_drop=0.3)
    full = lgb.train(dict(P), lgb.Dataset(X, label=y), num_boost_round=12)
    ck = str(tmp_path / "ck")
    lgb.train(dict(P), lgb.Dataset(X, label=y), num_boost_round=7,
              checkpoint_dir=ck, checkpoint_freq=1)
    resumed = lgb.train(dict(P), lgb.Dataset(X, label=y),
                        num_boost_round=12, checkpoint_dir=ck,
                        checkpoint_freq=1)
    assert _model_text(resumed) == _model_text(full)


# ------------------------------------------------ preemption (SIGTERM)
# single-device engine on purpose: a fresh subprocess pays every compile
# cold (no cache, see conftest), and the mesh paths are already drilled
# by the corrupt-fallback and shrunken-mesh tests in this module
_PREEMPT_CHILD = r"""
import os, sys, time
sys.path.insert(0, os.environ["ELASTIC_REPO"])
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import lightgbm_tpu as lgb
from tests.test_elastic import PARAMS, _data
d = os.environ["ELASTIC_DIR"]
X, y = _data()
def slow(env):
    time.sleep(0.25)  # keep the run alive long enough to be preempted
b = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
              num_boost_round=40,
              checkpoint_dir=os.path.join(d, "ckpt"),
              checkpoint_freq=0,  # the ONLY checkpoint is the preempt one
              metrics_dir=os.path.join(d, "metrics"), callbacks=[slow])
print("PREEMPT_CHILD_FINISHED", b.current_iteration(), flush=True)
"""


def test_preempt_saves_on_demand_and_resume_is_byte_identical(tmp_path):
    """SIGTERM mid-run: the handler checkpoints within the grace budget
    (no periodic checkpointing configured at all), the exit classifies
    as *preempt*, and resuming reproduces the uninterrupted run
    byte-for-byte."""
    script = tmp_path / "child.py"
    script.write_text(_PREEMPT_CHILD)
    env = dict(os.environ, ELASTIC_DIR=str(tmp_path), ELASTIC_REPO=REPO)
    proc = subprocess.Popen([sys.executable, str(script)], cwd=REPO,
                            env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    ev_path = tmp_path / "metrics" / "events-rank0.jsonl"
    deadline = time.monotonic() + 240
    preempt_at = None
    while time.monotonic() < deadline:
        its = [e["iteration"] for e in _events(str(ev_path))
               if e["event"] == "iteration"]
        if its and max(its) >= 3:
            preempt_at = max(its)
            break
        time.sleep(0.2)
    assert preempt_at is not None, "child never reached iteration 3"
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert "PREEMPT_CHILD_FINISHED" not in out
    assert classify_returncode(proc.returncode) == "preempt", \
        f"rc={proc.returncode}\n{out[-2000:]}"

    evs = _events(str(ev_path))
    pre = [e for e in evs if e["event"] == "preempt"]
    assert len(pre) == 1 and pre[0]["saved"] is True
    assert pre[0]["elapsed_s"] <= pre[0]["grace_s"]
    saved_it = pre[0]["iteration"]
    assert saved_it >= 3
    m = json.load(open(tmp_path / "ckpt" / "manifest.json"))
    assert m["iteration"] == saved_it and m["digests"]

    # resume in-process: byte-identical to an uninterrupted run
    X, y = _data()
    full = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                     num_boost_round=saved_it + 3)
    resumed = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                        num_boost_round=saved_it + 3,
                        checkpoint_dir=str(tmp_path / "ckpt"))
    assert _model_text(resumed) == _model_text(full)


# ------------------------------------- elastic shrink (supervisor e2e)
# Worker body for the supervisor drills: the REAL spec/env/tombstone/
# heartbeat/fault plumbing of distributed._WORKER_MAIN with the SPMD
# training replaced by a deterministic loop — multi-process collectives
# do not run on this CPU backend (see module docstring), and what these
# drills pin is the supervisor: classification, tombstones, shrink,
# renumbering, events.
_FAKE_WORKER = r"""
import json, os, sys, time
spec = json.load(open(sys.argv[1]))
rank = int(sys.argv[2])
for k, v in spec.get("env", {}).items():
    os.environ[k] = v
os.environ["LGBM_TPU_FAULT_SELF_RANK"] = str(rank)
os.environ["LGBM_TPU_FAULT_ATTEMPT"] = str(spec.get("attempt", 0))
os.environ["LGBM_TPU_WORLD_SIZE"] = str(spec["num_machines"])
if spec.get("tombstone_dir"):
    os.environ["LGBM_TPU_TOMBSTONE_DIR"] = spec["tombstone_dir"]
sys.path.insert(0, spec["repo"])
from lightgbm_tpu.reliability import faults
faults.check_tombstone()
if spec.get("reshard"):
    from lightgbm_tpu.parallel import reshard_plan
    rs = spec["reshard"]
    plan = reshard_plan(rs["old_n"], rs["new_n"], rs["num_rows"] or 0)
    assert plan.new_n == spec["num_machines"]
hb = None
if spec.get("heartbeat_dir"):
    hb = os.path.join(spec["heartbeat_dir"], f"heartbeat-rank{rank}")
for i in range(4):
    faults.maybe_crash(i)
    faults.maybe_worker_lost(i)
    if hb:
        open(hb, "a").close(); os.utime(hb, None)
    time.sleep(0.05)
if rank == 0:
    with open(os.environ["FAKE_MODEL_SRC"]) as f:
        txt = f.read()
    with open(spec["model_out"], "w") as f:
        f.write(txt)
print(f"worker {rank} done", flush=True)
"""


def _run_fake_cluster(tmp_path, monkeypatch, fault, num_machines=3,
                      extra_params=None, max_retries=3):
    from lightgbm_tpu import distributed

    X, y = _data(n=256)
    seed_model = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                           num_boost_round=2)
    src = tmp_path / "seed_model.txt"
    seed_model.save_model(str(src))
    monkeypatch.setenv("FAKE_MODEL_SRC", str(src))
    monkeypatch.setattr(distributed, "_WORKER_MAIN", _FAKE_WORKER)
    params = dict(PARAMS, metrics_dir=str(tmp_path / "mx"),
                  elastic_rank_grace_s=0.0, **(extra_params or {}))
    env = {"LGBM_TPU_FAULT": fault} if fault else {}
    booster = distributed.train_distributed(
        params, X, y, num_boost_round=2, num_machines=num_machines,
        worker_env=env, force_cpu=True, timeout=120,
        max_retries=max_retries, retry_backoff=0.01, poll_interval=0.05)
    sup = _events(str(tmp_path / "mx" / "events-ranksupervisor.jsonl"))
    return booster, sup


def test_worker_lost_shrinks_and_completes(tmp_path, monkeypatch):
    """The rank-loss drill: worker_lost@2 on rank 1 of 3 tombstones the
    rank; the supervisor classifies *lost*, shrinks 3 -> 2 (renumbered
    ranks clear the tombstone key), and the relaunch completes.  The
    elastic_shrink event carries the old/new topology."""
    monkeypatch.setenv("LGBM_TPU_FAULT_RANK", "1")
    booster, sup = _run_fake_cluster(tmp_path, monkeypatch,
                                     "worker_lost@2")
    monkeypatch.delenv("LGBM_TPU_FAULT_RANK")
    assert booster.current_iteration() == 2
    assert booster.elastic_shrinks == 1
    assert booster.final_num_machines == 2
    fails = [e for e in sup if e["event"] == "cluster_attempt_failed"]
    assert fails and fails[0]["classification"] == "lost"
    shr = [e for e in sup if e["event"] == "elastic_shrink"]
    assert len(shr) == 1
    assert shr[0]["old_num_machines"] == 3
    assert shr[0]["new_num_machines"] == 2
    assert shr[0]["lost_ranks"] == [1]
    # the tombstone outlived the attempt — that is what forces the
    # shrink instead of an endless same-size relaunch loop
    assert [e for e in sup if e["event"] == "cluster_retry_succeeded"]


def test_combined_rank_loss_with_repeated_crash(tmp_path, monkeypatch):
    """Combined drill: the same rank crashing on consecutive attempts
    (grace 0) is promoted to permanently lost even without a tombstone
    — the dead-PID-persisting shape — and the cluster still shrinks and
    completes (2 -> 1: the floor world size still trains)."""
    monkeypatch.setenv("LGBM_TPU_FAULT_RANK", "1")
    booster, sup = _run_fake_cluster(
        tmp_path, monkeypatch, "worker_crash@1@0,worker_crash@1@1",
        num_machines=2)
    monkeypatch.delenv("LGBM_TPU_FAULT_RANK")
    assert booster.current_iteration() == 2
    shr = [e for e in sup if e["event"] == "elastic_shrink"]
    assert len(shr) == 1 and shr[0]["lost_ranks"] == [1]
    assert shr[0]["old_num_machines"] == 2
    assert shr[0]["new_num_machines"] == 1


# --------------------------------- shrunken-mesh completion parity
def test_shrunken_mesh_resume_metric_parity(tmp_path):
    """The training-math half of the shrink drill, on real devices: a
    run checkpointed on an 8-device mesh and COMPLETED on a 7-device
    mesh must match the fixed-topology run's eval metrics within 1e-6
    (the resume is predict-seeded across topologies, not byte-exact —
    padding and reduction shapes legitimately change)."""
    X, y = _data()
    Xte, yte = _data(seed=12)
    p8 = dict(MESH_PARAMS, num_machines=8)
    p7 = dict(MESH_PARAMS, num_machines=7)
    ck = str(tmp_path / "ck")
    lgb.train(dict(p8), lgb.Dataset(X, label=y), num_boost_round=5,
              checkpoint_dir=ck, checkpoint_freq=1)
    shrunken = lgb.train(dict(p7), lgb.Dataset(X, label=y),
                         num_boost_round=10, checkpoint_dir=ck,
                         checkpoint_freq=1)
    assert shrunken._gbdt.mesh is not None
    assert int(shrunken._gbdt.mesh.devices.size) == 7
    fixed = lgb.train(dict(p8), lgb.Dataset(X, label=y),
                      num_boost_round=10)
    mse_s = float(np.mean((shrunken.predict(Xte) - yte) ** 2))
    mse_f = float(np.mean((fixed.predict(Xte) - yte) ** 2))
    assert abs(mse_s - mse_f) < 1e-6, (mse_s, mse_f)


# ------------------------------------------- full SPMD drill (slow)
@pytest.mark.slow
def test_spmd_worker_lost_8_to_7(tmp_path):
    """The full acceptance drill on a real multi-process backend:
    worker_lost@3 on the 8-rank cluster completes on 7 ranks with an
    elastic_shrink event and eval metrics within 1e-6 of the fixed
    7-rank run.  CPU-only jaxlib builds cannot run multi-process
    collectives (probed, like test_multiprocess) — skipped there."""
    from tests.test_fault_distributed import _multiprocess_spmd_available

    class _TF:
        def mktemp(self, name):
            d = tmp_path / name
            d.mkdir()
            return d

    if not _multiprocess_spmd_available(_TF()):
        pytest.skip("no multi-process SPMD on this backend")
    from lightgbm_tpu import distributed
    X, y = _data(n=1024)
    os.environ["LGBM_TPU_FAULT_RANK"] = "3"
    try:
        booster = distributed.train_distributed(
            dict(MESH_PARAMS, metrics_dir=str(tmp_path / "mx"),
                 elastic_rank_grace_s=0.0),
            X, y, num_boost_round=4, num_machines=8,
            worker_env={"LGBM_TPU_FAULT": "worker_lost@2"},
            force_cpu=True, timeout=600, max_retries=3,
            checkpoint_dir=str(tmp_path / "ck"), checkpoint_freq=1)
    finally:
        os.environ.pop("LGBM_TPU_FAULT_RANK", None)
    assert booster.final_num_machines == 7
    fixed = distributed.train_distributed(
        dict(MESH_PARAMS), X, y, num_boost_round=4, num_machines=7,
        force_cpu=True, timeout=600)
    d = np.abs(booster.predict(X) - fixed.predict(X))
    assert float(d.max()) < 1e-6
