"""End-to-end train/eval/predict tests (mirrors the reference's
tests/python_package_test/test_engine.py strategy: assert on metric quality and
model round-trips rather than internals)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

RNG = np.random.RandomState(42)


def make_regression(n=2000, F=10, noise=0.05, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F)
    y = (np.sin(X[:, 0] * 5) + 2 * X[:, 1] * X[:, 2] + X[:, 3] ** 2
         + noise * rng.randn(n))
    return X, y.astype(np.float64)


def make_binary(n=2000, F=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F)
    logit = 3 * (X[:, 0] - 0.5) + 2 * X[:, 1] * X[:, 2] - X[:, 3]
    y = (rng.rand(n) < 1 / (1 + np.exp(-3 * logit))).astype(np.float64)
    return X, y


def test_regression_quality():
    X, y = make_regression()
    Xte, yte = make_regression(seed=1)
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "regression", "num_leaves": 31,
                         "learning_rate": 0.1, "verbosity": -1}, train,
                        num_boost_round=60)
    pred = booster.predict(Xte)
    mse = float(np.mean((pred - yte) ** 2))
    assert mse < 0.05 * float(np.var(yte)), mse


def test_regression_train_improves_with_rounds():
    X, y = make_regression(n=1000)
    train = lgb.Dataset(X, label=y)
    p = {"objective": "regression", "num_leaves": 15, "verbosity": -1}
    b10 = lgb.train(p, train, num_boost_round=10)
    train2 = lgb.Dataset(X, label=y)
    b60 = lgb.train(p, train2, num_boost_round=60)
    m10 = float(np.mean((b10.predict(X) - y) ** 2))
    m60 = float(np.mean((b60.predict(X) - y) ** 2))
    assert m60 < m10


def test_binary_auc_and_logloss():
    X, y = make_binary()
    Xte, yte = make_binary(seed=1)
    train = lgb.Dataset(X, label=y)
    valid = train.create_valid(Xte, label=yte)
    record = {}
    booster = lgb.train({"objective": "binary", "metric": ["auc", "binary_logloss"],
                         "num_leaves": 15, "verbosity": -1}, train,
                        num_boost_round=50, valid_sets=[valid],
                        callbacks=[lgb.record_evaluation(record)])
    auc = record["valid_0"]["auc"][-1]
    assert auc > 0.85, auc
    prob = booster.predict(Xte)
    assert prob.min() >= 0 and prob.max() <= 1
    acc = float(((prob > 0.5) == (yte > 0)).mean())
    assert acc > 0.75, acc


def test_model_save_load_roundtrip(tmp_path):
    X, y = make_regression(n=800)
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "regression", "num_leaves": 15,
                         "verbosity": -1}, train, num_boost_round=20)
    pred1 = booster.predict(X)
    path = str(tmp_path / "model.txt")
    booster.save_model(path)
    loaded = lgb.Booster(model_file=path)
    pred2 = loaded.predict(X)
    np.testing.assert_allclose(pred1, pred2, rtol=1e-5, atol=1e-6)
    assert loaded.num_trees() == booster.num_trees()
    # text round-trips exactly through a second save
    s1 = booster.model_to_string()
    s2 = loaded.model_to_string()
    t1 = s1[s1.index("Tree=0"):s1.index("end of trees")]
    t2 = s2[s2.index("Tree=0"):s2.index("end of trees")]
    for a, b in zip(t1.splitlines(), t2.splitlines()):
        if a.startswith(("split_gain", "internal_")):
            continue  # float formatting of %g fields may differ in last digit
        assert a == b, (a, b)


def test_binary_model_loads_probability(tmp_path):
    X, y = make_binary(n=600)
    train = lgb.Dataset(X, label=y)
    booster = lgb.train({"objective": "binary", "verbosity": -1}, train,
                        num_boost_round=15)
    path = str(tmp_path / "model.txt")
    booster.save_model(path)
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(booster.predict(X), loaded.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_multiclass():
    n, F, K = 1500, 10, 3
    rng = np.random.RandomState(5)
    X = rng.rand(n, F)
    y = (X[:, 0] * 3).astype(np.int64) % K
    train = lgb.Dataset(X, label=y.astype(np.float64))
    booster = lgb.train({"objective": "multiclass", "num_class": K,
                         "num_leaves": 15, "verbosity": -1}, train,
                        num_boost_round=20)
    prob = booster.predict(X)
    assert prob.shape == (n, K)
    np.testing.assert_allclose(prob.sum(axis=1), 1.0, rtol=1e-4)
    acc = float((prob.argmax(axis=1) == y).mean())
    assert acc > 0.9, acc


def test_early_stopping():
    X, y = make_binary(n=2000)
    Xte, yte = make_binary(n=600, seed=9)
    train = lgb.Dataset(X, label=y)
    valid = train.create_valid(Xte, label=yte)
    booster = lgb.train({"objective": "binary", "metric": "binary_logloss",
                         "num_leaves": 15, "learning_rate": 0.3,
                         "verbosity": -1, "early_stopping_round": 5},
                        train, num_boost_round=150, valid_sets=[valid])
    assert 0 < booster.best_iteration < 150


def test_weights_affect_training():
    X, y = make_regression(n=800)
    w = np.ones(len(y))
    w[:400] = 10.0
    b1 = lgb.train({"objective": "regression", "verbosity": -1},
                   lgb.Dataset(X, label=y), num_boost_round=10)
    b2 = lgb.train({"objective": "regression", "verbosity": -1},
                   lgb.Dataset(X, label=y, weight=w), num_boost_round=10)
    assert not np.allclose(b1.predict(X), b2.predict(X))


def test_bagging_and_feature_fraction():
    X, y = make_regression(n=2000)
    booster = lgb.train({"objective": "regression", "bagging_fraction": 0.6,
                         "bagging_freq": 1, "feature_fraction": 0.7,
                         "num_leaves": 15, "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=30)
    mse = float(np.mean((booster.predict(X) - y) ** 2))
    assert mse < 0.3 * float(np.var(y))


def test_l1_objective_renew():
    X, y = make_regression(n=800)
    booster = lgb.train({"objective": "regression_l1", "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=30)
    mae = float(np.mean(np.abs(booster.predict(X) - y)))
    base = float(np.mean(np.abs(np.median(y) - y)))
    assert mae < 0.5 * base


def test_custom_objective_fobj():
    X, y = make_regression(n=600)
    train = lgb.Dataset(X, label=y)

    def fobj(score, dset):
        return score - y, np.ones_like(y)

    booster = lgb.train({"objective": "custom", "verbosity": -1}, train,
                        num_boost_round=20, fobj=fobj)
    pred = booster.predict(X)  # raw score for custom objective
    assert float(np.mean((pred - y) ** 2)) < 0.3 * float(np.var(y))


def test_feature_importance():
    X, y = make_regression(n=800)
    booster = lgb.train({"objective": "regression", "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=10)
    imp = booster.feature_importance()
    assert imp.shape == (X.shape[1],)
    # informative features 0..3 should dominate
    assert imp[:4].sum() > imp[4:].sum()


def test_predict_leaf_index():
    X, y = make_regression(n=500)
    booster = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1}, lgb.Dataset(X, label=y),
                        num_boost_round=5)
    leaves = booster.predict(X, pred_leaf=True)
    assert leaves.shape == (500, 5)
    assert leaves.max() < 7


def test_extra_trees():
    """extra_trees evaluates one random threshold per feature per leaf
    (ref: feature_histogram.hpp:192 USE_RAND): trees differ from the
    exhaustive scan but the model still learns."""
    rng = np.random.RandomState(5)
    X = rng.rand(3000, 5)
    y = (2 * (X[:, 0] > 0.4) + X[:, 1] + 0.1 * rng.randn(3000))
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "min_data_in_leaf": 5}
    b_norm = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=15)
    b_et = lgb.train({**base, "extra_trees": True},
                     lgb.Dataset(X, label=y), num_boost_round=15)
    from lightgbm_tpu.boosting.model_io import save_model_to_string
    assert (save_model_to_string(b_norm._gbdt)
            != save_model_to_string(b_et._gbdt))
    mse_et = float(np.mean((b_et.predict(X) - y) ** 2))
    mse_norm = float(np.mean((b_norm.predict(X) - y) ** 2))
    assert mse_et < mse_norm * 3.0, (mse_et, mse_norm)


def test_extra_trees_wave_engine():
    rng = np.random.RandomState(6)
    X = rng.rand(2000, 4)
    y = (X[:, 0] > 0.5).astype(np.float64)
    b = lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1,
                   "extra_trees": True, "tpu_growth_strategy": "wave",
                   "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    acc = float(np.mean((b.predict(X) > 0.5) == (y > 0.5)))
    assert acc > 0.9, acc


def test_pred_early_stop():
    """pred_early_stop freezes decisive rows' partial sums
    (ref: prediction_early_stop.cpp CreateBinary: margin = 2|score|)."""
    rng = np.random.RandomState(7)
    X = rng.randn(1500, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    b = lgb.train({"objective": "binary", "num_leaves": 15,
                   "verbosity": -1, "learning_rate": 0.3},
                  lgb.Dataset(X, label=y), num_boost_round=40)
    full = b.predict(X)
    es = b.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                   pred_early_stop_margin=1.0)
    # same class decisions, different (partial) probabilities on easy rows
    assert np.mean((full > 0.5) == (es > 0.5)) > 0.98
    assert not np.allclose(full, es)
    # a huge margin disables stopping entirely
    es_off = b.predict(X, pred_early_stop=True,
                       pred_early_stop_margin=1e9)
    np.testing.assert_allclose(full, es_off, rtol=1e-12)


def test_path_smooth():
    """path_smooth blends leaf outputs toward the parent
    (ref: CalculateSplittedLeafOutput USE_SMOOTHING,
    feature_histogram.hpp:716): predictions shrink toward the mean and
    small-leaf variance drops."""
    rng = np.random.RandomState(9)
    X = rng.rand(1500, 3)
    y = 2 * X[:, 0] + 0.5 * rng.randn(1500)
    base = {"objective": "regression", "num_leaves": 63, "verbosity": -1,
            "min_data_in_leaf": 2}
    b0 = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=5)
    b1 = lgb.train({**base, "path_smooth": 100.0},
                   lgb.Dataset(X, label=y), num_boost_round=5)
    from lightgbm_tpu.boosting.model_io import save_model_to_string
    assert (save_model_to_string(b0._gbdt)
            != save_model_to_string(b1._gbdt))
    # smoothed model is less extreme (regularized toward parents)
    p0, p1 = b0.predict(X), b1.predict(X)
    assert np.std(p1 - p1.mean()) < np.std(p0 - p0.mean())
    assert np.corrcoef(p1, y)[0, 1] > 0.7


def test_extra_trees_varies_across_trees():
    """The random thresholds must differ between boosting iterations
    (the reference's rand_ is stateful across the run)."""
    rng = np.random.RandomState(3)
    X = rng.rand(2000, 1)
    y = X[:, 0] + 0.01 * rng.randn(2000)
    b = lgb.train({"objective": "regression", "num_leaves": 2,
                   "verbosity": -1, "extra_trees": True,
                   "min_data_in_leaf": 5, "learning_rate": 0.01},
                  lgb.Dataset(X, label=y), num_boost_round=6)
    b._gbdt._sync_model()
    thresholds = {round(float(t.threshold[0]), 6)
                  for t in b._gbdt.models_ if t.num_leaves > 1}
    assert len(thresholds) > 1, thresholds


def test_cv():
    """K-fold CV (ref: engine.py:580 cv): mean/stdv histories per metric,
    stratified folds for binary."""
    rng = np.random.RandomState(1)
    X = rng.randn(1200, 4)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
    res = lgb.cv({"objective": "binary", "num_leaves": 7, "verbosity": -1,
                  "metric": "auc"}, lgb.Dataset(X, label=y),
                 num_boost_round=5, nfold=3, seed=3)
    assert "valid auc-mean" in res and "valid auc-stdv" in res
    assert len(res["valid auc-mean"]) == 5
    assert res["valid auc-mean"][-1] > 0.8
    assert all(s >= 0 for s in res["valid auc-stdv"])


def test_feature_fraction_bynode():
    """feature_fraction_bynode draws a fresh column subset per leaf scan
    (ref: col_sampler.hpp GetByNode): the model differs from full-column
    training and still learns."""
    rng = np.random.RandomState(4)
    X = rng.randn(2000, 8)
    y = X[:, 0] + X[:, 3] + 0.1 * rng.randn(2000)
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 5}
    b0 = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=10)
    b1 = lgb.train({**base, "feature_fraction_bynode": 0.5},
                   lgb.Dataset(X, label=y), num_boost_round=10)
    from lightgbm_tpu.boosting.model_io import save_model_to_string
    assert (save_model_to_string(b0._gbdt)
            != save_model_to_string(b1._gbdt))
    mse0 = float(np.mean((b0.predict(X) - y) ** 2))
    mse1 = float(np.mean((b1.predict(X) - y) ** 2))
    # regularized but still learning (label variance is ~2)
    assert mse1 < 1.0 and mse1 < 8 * mse0, (mse1, mse0)
    # by-node sampling spreads splits over more features
    imp = b1._gbdt.feature_importance("split")
    assert (imp > 0).sum() >= 4


def test_cv_ranking_query_aware_folds():
    """cv on ranking data assigns WHOLE queries to folds (ref:
    python-package engine.py _make_n_folds group branch) — rows of one
    query never straddle the train/valid split."""
    rng = np.random.RandomState(0)
    sizes = rng.randint(5, 30, size=40)
    n = int(sizes.sum())
    X = rng.rand(n, 5)
    y = rng.randint(0, 4, n).astype(np.float64)
    ds = lgb.Dataset(X, label=y, group=sizes)
    res = lgb.cv({"objective": "lambdarank", "metric": "ndcg",
                  "ndcg_eval_at": [3], "num_leaves": 7, "verbosity": -1,
                  "min_data_in_leaf": 2}, ds, num_boost_round=3, nfold=4,
                 return_cvbooster=True, seed=7)
    assert "valid ndcg@3-mean" in res
    cvb = res["cvbooster"]
    total_queries = 0
    for b in cvb.boosters:
        qb = b._gbdt.train_data.metadata.query_boundaries
        assert qb is not None          # group info survived the subset
        total_queries += len(qb) - 1
    # each of the 40 queries lands whole in exactly nfold-1 train folds
    assert total_queries == 40 * 3
