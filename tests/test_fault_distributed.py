"""Worker supervision and retry in the multi-process launcher (ISSUE 1
tentpole pillar 2): a dead rank must fail the run in seconds — with the
failing rank's log tail in the error — instead of stalling every rank to
the 900 s deadline, and with retries enabled the cluster relaunches and
resumes from the last checkpoint."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from lightgbm_tpu.utils.log import LightGBMError

_WENV = {"JAX_PLATFORMS": "cpu",
         "XLA_FLAGS": "--xla_force_host_platform_device_count=2"}


def _make_data():
    rng = np.random.RandomState(3)
    n = 1024
    X = rng.rand(n, 5)
    y = (rng.rand(n) < 1 / (1 + np.exp(-4 * (X[:, 0] - 0.5)))
         ).astype(np.float64)
    return X, y


_PARAMS = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
           "min_data_in_leaf": 5, "tpu_growth_strategy": "leafwise"}


_MP_PROBE = r"""
import os, sys
pid = int(sys.argv[1]); port = sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec
mesh = Mesh(np.array(jax.devices()).reshape(-1), ("x",))
a = jax.device_put(np.arange(8.0), NamedSharding(mesh, PartitionSpec("x")))
print("probe ok", flush=True)
"""


def _multiprocess_spmd_available(tmp_path_factory) -> bool:
    """Some jaxlib builds cannot run multi-process collectives on the CPU
    backend at all (every seed test in test_multiprocess.py fails there
    too).  Probe once; retry/resume needs a working cluster."""
    import socket
    d = tmp_path_factory.mktemp("mp_probe")
    script = d / "probe.py"
    script.write_text(_MP_PROBE)
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen([sys.executable, str(script), str(i), port],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True, env=env)
             for i in range(2)]
    ok = True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
            ok = False
            continue
        ok = ok and p.returncode == 0 and "probe ok" in out
    return ok


@pytest.fixture(scope="session")
def mp_spmd_ok(tmp_path_factory):
    return _multiprocess_spmd_available(tmp_path_factory)


@pytest.mark.skipif(bool(os.environ.get("LIGHTGBM_TPU_SKIP_MULTIPROC")),
                    reason="multiproc disabled")
def test_worker_crash_fast_fail_with_log_tail(tmp_path):
    """Satellite: an injected rank crash must surface within seconds —
    not the old serial rank-ordered wait that left every other rank
    blocked in collectives until the global deadline — and the error
    must carry the failing rank's log tail.  This holds whether the
    rank dies from the injected fault or (on jaxlib builds without
    CPU multi-process collectives) from backend init itself."""
    from lightgbm_tpu.distributed import train_distributed
    X, y = _make_data()
    wenv = dict(_WENV, LGBM_TPU_FAULT="worker_crash@1",
                LGBM_TPU_FAULT_RANK="1")
    t0 = time.monotonic()
    with pytest.raises(LightGBMError) as ei:
        train_distributed(_PARAMS, X, y, num_boost_round=4, num_machines=2,
                          force_cpu=True, worker_env=wenv, timeout=600)
    elapsed = time.monotonic() - t0
    # the supervision poll loop kills the cluster on the first failure;
    # "seconds" here budgets jax import + compile, not the 600 s deadline
    assert elapsed < 300, f"fast-fail took {elapsed:.0f}s"
    msg = str(ei.value)
    assert "rank" in msg
    assert "log tail" in msg


@pytest.mark.skipif(bool(os.environ.get("LIGHTGBM_TPU_SKIP_MULTIPROC")),
                    reason="multiproc disabled")
def test_worker_crash_retry_resumes_from_checkpoint(tmp_path, mp_spmd_ok):
    """Acceptance: rank 0 crashes at iteration 2 on the first attempt;
    with max_retries=1 the cluster relaunches (fault gated to attempt 0)
    and resumes from the auto checkpoint, matching single-process
    training."""
    if not mp_spmd_ok:
        pytest.skip("this jaxlib cannot run multi-process SPMD on CPU "
                    "(seed-known limitation; test_multiprocess.py fails "
                    "identically)")
    import lightgbm_tpu as lgb
    from lightgbm_tpu.distributed import train_distributed
    X, y = _make_data()
    wenv = dict(_WENV, LGBM_TPU_FAULT="worker_crash@2",
                LGBM_TPU_FAULT_RANK="0")
    b = train_distributed(_PARAMS, X, y, num_boost_round=4, num_machines=2,
                          force_cpu=True, worker_env=wenv, timeout=600,
                          max_retries=1, retry_backoff=0.1)
    b_single = lgb.train({**_PARAMS, "tree_learner": "serial"},
                         lgb.Dataset(X, label=y), num_boost_round=4)
    np.testing.assert_allclose(b.predict(X[:256]), b_single.predict(X[:256]),
                               rtol=2e-4, atol=2e-6)


def test_join_cluster_unreachable_coordinator_diagnostics(tmp_path):
    """join_cluster must fail within its initialize timeout with an
    error naming the coordinator, not hang for jax's 300 s default or
    dump a bare gRPC traceback.  Run in a subprocess: jax.distributed
    state is process-global."""
    script = tmp_path / "join.py"
    script.write_text(r"""
import sys
sys.path.insert(0, %r)
from lightgbm_tpu.distributed import join_cluster
from lightgbm_tpu.utils.log import LightGBMError
try:
    join_cluster(["localhost:1", "localhost:2"], rank=1,
                 initialize_timeout=3)
    print("JOINED (unexpected)")
except LightGBMError as e:
    msg = str(e)
    assert "localhost:1" in msg and "coordinator" in msg, msg
    print("DIAG OK", flush=True)
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    t0 = time.monotonic()
    r = subprocess.run([sys.executable, str(script)], capture_output=True,
                       text=True, timeout=180)
    elapsed = time.monotonic() - t0
    assert "DIAG OK" in r.stdout, r.stdout + r.stderr
    assert elapsed < 120, f"diagnostic took {elapsed:.0f}s"
