"""Serving fault domain suite (docs/Serving.md fleet section): replica
fleet supervision, retry/backoff routing, load-shedding admission,
rolling publish, canary auto-rollback, drain semantics.

Two layers of fixture:

* **Stub replicas** (`tests/fleet_stub.py`) — real processes + real
  sockets speaking the serving wire protocol with a deterministic
  linear "model" (`preds = sum(row) * scale`), but no jax and no
  model load: the fleet/router machinery (spawn, poll, classify,
  backoff relaunch, health gating, retry, shed, canary math) is
  exercised end to end in milliseconds.  `fault_envs` doubles as the
  per-replica env injection hook, exactly as the bench uses it.
* **Real in-process daemons** for the daemon-side contracts the stubs
  fake: warmup-ledger readiness, ShedError fail-fast, serve_* fault
  points, drain-abandoned accounting, and the TCP client's
  deadline/reconnect behaviour.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.observability.registry import global_registry
from lightgbm_tpu.reliability import faults
from lightgbm_tpu.serving import (OverloadedError, ReplicaFleet, Router,
                                  ServingClient, ServingDaemon, ShedError,
                                  serve_counters_reset, start_frontend)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STUB = os.path.join(REPO, "tests", "fleet_stub.py")


# ------------------------------------------------------------ stub fixtures
def _mk_fleet(workdir, n=3, max_restarts=2, envs=None,
              entries=(("m", "scale1"),)):
    """Fleet of stub replicas; `envs[idx]` adds per-replica env."""
    fault_envs = {}
    for i in range(n):
        e = {"STUB_READY_FILE": os.path.join(
            str(workdir), f"replica-{i}.ready.json")}
        e.update((envs or {}).get(i, {}))
        fault_envs[i] = e
    return ReplicaFleet(
        n, list(entries), str(workdir), max_restarts=max_restarts,
        health_interval_s=0.1,
        spawn_cmd=lambda idx, rf: [sys.executable, STUB],
        fault_envs=fault_envs)


def _mk_router(fleet, **overrides):
    p = {"serve_retry_max": 3, "serve_retry_backoff_ms": 5.0,
         "serve_request_timeout_s": 15.0, "serve_canary_pct": 50.0,
         "serve_canary_min_samples": 12,
         "serve_canary_max_divergence": 2.0,
         "serve_canary_max_error_rate": 0.25}
    p.update(overrides)
    return Router(fleet, Config(p))


ROWS = np.arange(12, dtype=np.float64).reshape(3, 4)
SUMS = ROWS.sum(axis=1)


@pytest.fixture(autouse=True)
def _reset_counters():
    serve_counters_reset()
    for key in ("router_requests", "router_rows", "router_retries",
                "router_failed", "router_conn_errors", "router_timeouts",
                "serve_replica_down", "serve_replica_restarts"):
        global_registry.inc(key, -global_registry.counter(key))
    yield


# ---------------------------------------------------------------- fault core
def test_router_survives_replica_kill_zero_failed_requests(tmp_path):
    """A replica killed mid-load costs ZERO client requests: in-flight
    requests retry on a different replica, the supervisor relaunches
    the dead one with backoff, and it rejoins the rotation."""
    fleet = _mk_fleet(tmp_path, n=3).start()
    try:
        assert fleet.wait_ready(timeout=20)
        router = _mk_router(fleet)
        failures, done = [], [0]
        lock = threading.Lock()
        kill_gate = threading.Event()

        def client(tid):
            for i in range(40):
                try:
                    r = router.predict("m", ROWS, deadline_ms=10_000)
                    assert np.allclose(r.preds, SUMS)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        failures.append(repr(e))
                with lock:
                    done[0] += 1
                    if done[0] >= 20:
                        kill_gate.set()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        assert kill_gate.wait(timeout=30)
        fleet.replicas[0].proc.kill()     # hard kill, mid-load
        for t in threads:
            t.join(timeout=60)
        assert done[0] == 160 and not failures, failures[:3]
        # the supervisor classified the kill and relaunched with backoff
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            d = fleet.describe()[0]
            if d["healthy"] and d["ready"]:
                break
            time.sleep(0.05)
        d = fleet.describe()[0]
        assert d["restarts"] == 1 and d["gen"] == 2
        assert d["healthy"] and not d["down"]
        assert global_registry.counter("serve_replica_down") == 1
        assert global_registry.counter("serve_replica_restarts") == 1
    finally:
        fleet.stop(drain=False)


def test_restart_budget_exhaustion_marks_replica_down(tmp_path):
    """A replica that dies more than serve_max_replica_restarts times
    stays down; the fleet keeps serving on the survivors."""
    # replica 0 crashes on its first request, every generation
    fleet = _mk_fleet(tmp_path, n=2, max_restarts=1,
                      envs={0: {"STUB_CRASH_AFTER": "1"}}).start()
    try:
        assert fleet.wait_ready(timeout=20)
        router = _mk_router(fleet)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                router.predict("m", ROWS, deadline_ms=5_000)
            except Exception:  # noqa: BLE001 - draining the budget
                pass
            if fleet.describe()[0]["down"]:
                break
            time.sleep(0.02)
        d = fleet.describe()[0]
        assert d["down"] and d["restarts"] == 1
        # the fleet still serves on the survivor
        r = router.predict("m", ROWS, deadline_ms=5_000)
        assert np.allclose(r.preds, SUMS) and r.replica == 1
        assert fleet.alive()
    finally:
        fleet.stop(drain=False)


# ------------------------------------------------------------ shed/admission
def test_shed_retries_on_another_replica(tmp_path):
    """A structured shed is retryable: the router counts it and the
    request lands on a non-shedding replica — zero caller errors."""
    fleet = _mk_fleet(tmp_path, n=2,
                      envs={0: {"STUB_SHED": "1"}}).start()
    try:
        assert fleet.wait_ready(timeout=20)
        router = _mk_router(fleet)
        for _ in range(20):
            r = router.predict("m", ROWS, deadline_ms=10_000)
            assert np.allclose(r.preds, SUMS) and r.replica == 1
        assert global_registry.counter("serve_shed") > 0
        assert global_registry.counter("router_retries") > 0
        assert router.stats()["router_failed"] == 0
    finally:
        fleet.stop(drain=False)


def test_all_replicas_shedding_rejects_overloaded(tmp_path):
    """Admission matrix: every attempt shedding -> OverloadedError;
    every health probe advertising shed -> rejected BEFORE any attempt
    (the fleet-wide admission controller)."""
    fleet = _mk_fleet(tmp_path, n=2,
                      envs={0: {"STUB_SHED": "1"},
                            1: {"STUB_SHED": "1"}}).start()
    try:
        assert fleet.wait_ready(timeout=20)
        router = _mk_router(fleet)
        with pytest.raises(OverloadedError, match="shed"):
            router.predict("m", ROWS, deadline_ms=10_000)
        assert global_registry.counter("serve_overloaded") == 1
    finally:
        fleet.stop(drain=False)
    serve_counters_reset()
    fleet = _mk_fleet(tmp_path, n=2,
                      envs={0: {"STUB_SHED_HEALTH": "1"},
                            1: {"STUB_SHED_HEALTH": "1"}}).start()
    try:
        assert fleet.wait_ready(timeout=20)
        router = _mk_router(fleet)
        before = global_registry.counter("router_retries")
        with pytest.raises(OverloadedError, match="routable replicas"):
            router.predict("m", ROWS)
        # rejected at admission: no retries burned, no attempt made
        assert global_registry.counter("router_retries") == before
        assert global_registry.counter("serve_overloaded") == 1
    finally:
        fleet.stop(drain=False)


# -------------------------------------------------------------- publish path
def test_rolling_publish_is_version_consistent_under_load(tmp_path):
    """Rolling publish under live traffic: every response matches
    exactly the scale of the version that served it (version 1 <->
    scale1, version 2 <-> scale3) — a mixed-fleet window is fine, a
    mixed RESPONSE never is; after the roll, only v2 answers."""
    fleet = _mk_fleet(tmp_path, n=3).start()
    try:
        assert fleet.wait_ready(timeout=20)
        router = _mk_router(fleet)
        router.register_incumbent("m", "scale1")
        mismatches, errors = [], []
        stop = threading.Event()
        lock = threading.Lock()

        def client():
            while not stop.is_set():
                try:
                    r = router.predict("m", ROWS, deadline_ms=10_000)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))
                    continue
                exp = SUMS if r.version == 1 else SUMS * 3
                if not np.allclose(r.preds, exp):
                    with lock:
                        mismatches.append((r.version, list(r.preds)))

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        out = router.publish("m", "v2_scale3", canary_pct=0)
        assert out == {"canary": False,
                       "replicas": {0: 2, 1: 2, 2: 2}}
        time.sleep(0.1)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors and not mismatches, (errors[:3],
                                               mismatches[:3])
        r = router.predict("m", ROWS)
        assert r.version == 2 and np.allclose(r.preds, SUMS * 3)
        # relaunched replicas will load the NEW incumbent
        assert dict(fleet.model_entries)["m"] == "v2_scale3"
    finally:
        fleet.stop(drain=False)


def test_canary_divergence_auto_rollback(tmp_path):
    """The auto-rollback drill: a canary whose score distribution
    diverges is rolled back — the incumbent returns to the canary
    replica, `serve_rollback` counts it, and traffic never sees an
    error."""
    fleet = _mk_fleet(tmp_path, n=2).start()
    try:
        assert fleet.wait_ready(timeout=20)
        router = _mk_router(fleet)
        router.register_incumbent("m", "scale1")
        out = router.publish("m", "bad_scale100")
        assert out["canary"] is True and out["pct"] == 50.0
        stop = threading.Event()
        errors = []

        def load():
            while not stop.is_set():
                try:
                    router.predict("m", ROWS, deadline_ms=10_000)
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))
                time.sleep(0.001)

        threads = [threading.Thread(target=load) for _ in range(3)]
        for t in threads:
            t.start()
        verdict = router.canary_wait("m", timeout=60)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert verdict == "rolled_back" and not errors
        assert global_registry.counter("serve_rollback") == 1
        stats = router.stats()
        assert "divergence" in stats["canaries"]["m"]
        assert stats["canaries"]["m"]["resolved"] == "rolled_back"
        # the canary replica serves the incumbent again (version
        # bumped by the rollback publish, scores back to scale 1)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            seen = {router.predict("m", ROWS).replica for _ in range(6)}
            if len(seen) == 2:
                break
        for _ in range(10):
            r = router.predict("m", ROWS)
            assert np.allclose(r.preds, SUMS), (r.replica, r.version)
    finally:
        fleet.stop(drain=False)


def test_canary_clean_promotes_fleet_wide(tmp_path):
    """A canary that tracks the incumbent's distribution promotes: the
    remaining replicas roll, the published path becomes the incumbent
    for future relaunches."""
    fleet = _mk_fleet(tmp_path, n=3).start()
    try:
        assert fleet.wait_ready(timeout=20)
        router = _mk_router(fleet)
        router.register_incumbent("m", "scale1")
        router.publish("m", "v2_scale1")   # same distribution
        stop = threading.Event()

        def load():
            while not stop.is_set():
                try:
                    router.predict("m", ROWS, deadline_ms=10_000)
                except Exception:  # noqa: BLE001
                    pass
                time.sleep(0.001)

        threads = [threading.Thread(target=load) for _ in range(3)]
        for t in threads:
            t.start()
        verdict = router.canary_wait("m", timeout=60)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert verdict == "promoted"
        assert global_registry.counter("serve_rollback") == 0
        # every replica now answers with the new version
        deadline = time.monotonic() + 10
        versions = set()
        while time.monotonic() < deadline:
            versions = {router.predict("m", ROWS).version
                        for _ in range(8)}
            if versions == {2}:
                break
        assert versions == {2}
        assert dict(fleet.model_entries)["m"] == "v2_scale1"
    finally:
        fleet.stop(drain=False)


# ------------------------------------------------------------------ health
def test_health_gates_routing_until_warmup(tmp_path):
    """A replica is NOT routable until its health probe reports the
    warmup ledger complete — churn never leaks cold compiles into
    live traffic."""
    fleet = _mk_fleet(tmp_path, n=1,
                      envs={0: {"STUB_WARMUP_S": "1.2"}}).start()
    try:
        deadline = time.monotonic() + 0.9
        while time.monotonic() < deadline:
            assert fleet.endpoints() == []
            time.sleep(0.1)
        assert fleet.wait_ready(timeout=20)
        assert len(fleet.endpoints()) == 1
    finally:
        fleet.stop(drain=False)


# ===================== real-daemon half (in-process) =======================
_PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
           "metric": "none", "min_data_in_leaf": 5,
           "device_predict": "true", "device_predict_min_bucket": 32}


def _train(rounds=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(500, 6).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float32)
    bst = lgb.train(dict(_PARAMS), lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    bst._gbdt._sync_model()
    return bst, X


def _daemon(**overrides):
    p = dict(_PARAMS, serve_max_batch_rows=128,
             serve_max_coalesce_wait_ms=0.0)
    p.update(overrides)
    serve_counters_reset()
    return ServingDaemon(Config(p)).start()


@pytest.fixture
def _clean_faults():
    yield
    os.environ.pop("LGBM_TPU_FAULT", None)
    os.environ.pop("LGBM_TPU_FAULT_SLOW_S", None)
    faults.reload()


def test_daemon_health_readiness_before_and_after_warmup():
    """registry.ready() is the warmup ledger: False while a load is in
    flight, True only once every model warmed; daemon.health() carries
    it plus the shed state."""
    bst, X = _train()
    d = _daemon()
    try:
        h = d.health()
        assert h["ready"] is False and h["models"] == {}
        handle = d.registry.register("m", booster=bst, block=False)
        # a pending load parks readiness even if probed mid-warmup
        assert d.registry.ready() is False or handle.done()
        handle.wait(timeout=120)
        deadline = time.monotonic() + 10
        while not d.registry.ready() and time.monotonic() < deadline:
            time.sleep(0.02)
        h = d.health()
        assert h["ready"] is True and h["models"] == {"m": 1}
        assert h["shedding"] is False and h["pid"] == os.getpid()
    finally:
        d.stop()


def test_queue_full_sheds_fast_and_counts(_clean_faults):
    """The bounded queue FAILS FAST with ShedError (no blocking) and
    the health probe flips `shedding` inside the shed window."""
    bst, X = _train()
    os.environ["LGBM_TPU_FAULT"] = "serve_slow@1"
    os.environ["LGBM_TPU_FAULT_SLOW_S"] = "2.0"
    faults.reload()
    d = _daemon(serve_queue_depth=2)
    try:
        d.registry.register("m", booster=bst, block=True)
        futs = [d.submit("m", X[:2])]      # dispatcher pops + sleeps 2 s
        time.sleep(0.3)
        shed = None
        t0 = time.monotonic()
        for _ in range(8):                 # 2 fill the queue, then shed
            try:
                futs.append(d.submit("m", X[:2]))
            except ShedError as e:
                shed = e
                break
        elapsed = time.monotonic() - t0
        assert shed is not None and shed.depth == 2
        assert elapsed < 1.0, "shed must fail fast, not block"
        assert global_registry.counter("serve_shed") >= 1
        assert d.health()["shedding"] is True
        for f in futs:                     # queued work still completes
            assert f.result(timeout=30) is not None
    finally:
        d.stop()


def test_serve_fault_points_crash_shed_slow(_clean_faults):
    """The serve_* fault specs parse, rank-gate, and fire on the
    request counter (serve_crash drills live in the bench subprocess;
    here the shed + slow halves and the spec plumbing)."""
    bst, X = _train()
    os.environ["LGBM_TPU_FAULT"] = "serve_shed@2,serve_slow@3"
    faults.reload()
    os.environ["LGBM_TPU_FAULT_SLOW_S"] = "0.5"
    d = _daemon()
    try:
        d.registry.register("m", booster=bst, block=True)
        assert d.predict("m", X[:2]) is not None      # request 1: clean
        with pytest.raises(ShedError):                # request 2: shed
            d.submit("m", X[:2])
        t0 = time.monotonic()
        assert d.predict("m", X[:2]) is not None      # request 3: slow
        assert time.monotonic() - t0 >= 0.45
        assert global_registry.counter("faults_injected") >= 2
    finally:
        d.stop()
    # rank gating: a spec aimed at another replica never fires here
    os.environ["LGBM_TPU_FAULT"] = "serve_shed@1"
    os.environ["LGBM_TPU_FAULT_RANK"] = "5"
    faults.reload()
    try:
        d = _daemon()
        d.registry.register("m", booster=bst, block=True)
        assert d.predict("m", X[:2]) is not None
    finally:
        os.environ.pop("LGBM_TPU_FAULT_RANK", None)
        d.stop()


def test_drain_deadline_abandonment_is_announced(_clean_faults):
    """stop(drain=True) that misses its deadline counts the abandoned
    requests (`serve_drain_abandoned`) instead of dropping them
    silently; their futures fail with the stop error."""
    bst, X = _train()
    os.environ["LGBM_TPU_FAULT"] = "serve_slow@1"
    os.environ["LGBM_TPU_FAULT_SLOW_S"] = "2.0"
    faults.reload()
    d = _daemon(serve_queue_depth=64)
    d.registry.register("m", booster=bst, block=True)
    futs = [d.submit("m", X[:2])]          # holds the dispatcher 2 s
    time.sleep(0.2)
    futs += [d.submit("m", X[:2]) for _ in range(5)]
    before = global_registry.counter("serve_drain_abandoned")
    drained = d.stop(drain=True, timeout=0.2)
    assert drained is False
    assert d.coalescer.last_abandoned == 5
    assert global_registry.counter("serve_drain_abandoned") - before == 5
    failed = 0
    for f in futs[1:]:
        with pytest.raises(RuntimeError, match="stopped"):
            f.result(timeout=30)
        failed += 1
    assert failed == 5


def test_tcp_client_deadline_and_reconnect_with_backoff():
    """ServingClient.connect: deadline_ms propagates to the replica
    (a spent deadline fails fast server-side), and a dropped TCP
    connection reconnects with backoff instead of raising — the
    replica-restart shape."""
    bst, X = _train()
    d = _daemon()
    try:
        d.registry.register("m", booster=bst, block=True)
        srv = start_frontend(d, port=0, request_timeout_s=30.0)
        port = srv.server_address[1]
        c = ServingClient.connect("127.0.0.1", port)
        exp = bst.predict(X[:3])
        assert np.array_equal(c.predict("m", X[:3]), exp)
        with pytest.raises(TimeoutError):
            c.predict("m", X[:3], deadline_ms=0.001)
        # drop the server; a restart on the same port must be invisible
        srv.shutdown()
        srv.server_close()
        srv2 = start_frontend(d, port=port, request_timeout_s=30.0)
        try:
            assert np.array_equal(c.predict("m", X[:3]), exp)
            assert c.health()["ready"] is True
        finally:
            srv2.shutdown()
        c.close()
    finally:
        d.stop()


# --------------------------------------------------------------- SIGTERM
_FLEET_SIGTERM_CHILD = r"""
import os, sys, time
sys.path.insert(0, os.environ["FLEET_REPO"])
import jax
jax.config.update("jax_platforms", "cpu")  # axon plugin ignores the env
from lightgbm_tpu.config import Config
from lightgbm_tpu.observability import (install_sigterm_flush,
                                        set_preemption_hook)
from lightgbm_tpu.serving import ReplicaFleet, Router

work = os.environ["FLEET_WORK"]
stub = os.environ["FLEET_STUB"]
n = 2
fleet = ReplicaFleet(
    n, [("m", "scale1")], work, max_restarts=1, health_interval_s=0.1,
    spawn_cmd=lambda idx, rf: [sys.executable, stub],
    fault_envs={i: {"STUB_READY_FILE":
                    os.path.join(work, f"replica-{i}.ready.json")}
                for i in range(n)}).start()
assert fleet.wait_ready(timeout=30)
router = Router(fleet, Config({}))
router.start_frontend(port=0)

def _drain():
    router.stop()
    rcs = fleet.stop(drain=True, timeout=20.0)
    print("DRAINED", sorted(rcs.values()), flush=True)
    return None

assert install_sigterm_flush()
set_preemption_hook(_drain)
print("FLEET_READY", flush=True)
time.sleep(60)
"""


def test_fleet_sigterm_drains_whole_fleet_rc143(tmp_path):
    """SIGTERM to the fleet runner drains the WHOLE fleet: the router
    stops, every replica gets its own SIGTERM drain (each exits 143),
    and the runner re-delivers — its exit stays 143 so supervisors
    classify *preempt*."""
    script = tmp_path / "child.py"
    script.write_text(_FLEET_SIGTERM_CHILD)
    work = tmp_path / "fleet"
    work.mkdir()
    env = dict(os.environ, FLEET_REPO=REPO, FLEET_WORK=str(work),
               FLEET_STUB=STUB, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-u", str(script)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    try:
        t0 = time.monotonic()
        while time.monotonic() - t0 < 120:
            line = proc.stdout.readline()
            if "FLEET_READY" in line:
                break
            if proc.poll() is not None:
                pytest.fail(f"fleet child exited early: {line}")
        else:
            pytest.fail("fleet child never became ready")
        proc.send_signal(signal.SIGTERM)
        out_rest, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode in (-signal.SIGTERM, 143), (proc.returncode,
                                                       out_rest)
    assert "DRAINED [143, 143]" in out_rest, out_rest
