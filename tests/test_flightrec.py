"""Flight recorder (observability/flightrec.py): ring bounds, batch
histograms, the synchronous dump artifact, the SIGUSR2 on-demand dump,
and the stall diagnosis embedding the iteration tail."""

import json
import os
import signal

import numpy as np

from lightgbm_tpu.observability.flightrec import (FlightRecorder,
                                                  dump_flight_record,
                                                  flight_file_path,
                                                  flight_recorder)


def test_iteration_ring_is_bounded_and_keeps_newest():
    fr = FlightRecorder(capacity=16)
    for i in range(50):
        fr.record_iteration(iteration=i)
    tail = fr.tail(99)
    assert len(tail) == 16
    assert [r["iteration"] for r in tail] == list(range(34, 50))
    assert all("ts" in r for r in tail)


def test_resize_keeps_newest_records():
    fr = FlightRecorder(capacity=64)
    for i in range(40):
        fr.record_iteration(iteration=i)
    fr.resize(10)
    assert [r["iteration"] for r in fr.tail(99)] == list(range(30, 40))
    fr.resize(3)  # floored to 8
    assert len(fr.tail(99)) == 8


def test_batch_histogram_buckets_are_log2():
    fr = FlightRecorder()
    for n in (1, 2, 3, 4, 1 << 20):
        fr.record_batch(num_requests=n, num_rows=n * 4)
    hist = fr.contents()["coalesce_batch_requests_hist"]
    assert hist[0] == 1          # n=1 -> bucket 0
    assert hist[1] == 2          # n=2,3 -> bucket 1
    assert hist[2] == 1          # n=4 -> bucket 2
    assert hist[-1] == 1         # open-ended top bucket
    assert sum(hist) == 5


def test_trace_ring_and_ids():
    fr = FlightRecorder(trace_capacity=8)
    ids = [fr.next_trace_id() for _ in range(3)]
    assert ids == [0, 1, 2]
    for i in range(20):
        fr.record_trace(trace_id=i, rows=4)
    assert [t["trace_id"] for t in fr.trace_tail(99)] == list(range(12, 20))


def test_dump_writes_parseable_artifact(tmp_path):
    fr_path = dump_flight_record(str(tmp_path), rank=3, reason="unit")
    assert fr_path == flight_file_path(str(tmp_path), 3)
    payload = json.load(open(fr_path))
    assert payload["kind"] == "flight_record"
    assert payload["reason"] == "unit" and payload["rank"] == 3
    for key in ("iterations", "serve_traces",
                "coalesce_batch_requests_hist", "registry"):
        assert key in payload
    assert "counters" in payload["registry"]


def test_sigusr2_dumps_without_killing_process(tmp_path):
    """The satellite contract: `kill -USR2` on a live process writes
    flight-rank<r>.json through the signal-safe path and the process
    carries on."""
    from lightgbm_tpu.reliability.faults import register_flight_dump_signal
    flight_recorder.record_iteration(iteration=123, marker="sigusr2-test")
    assert register_flight_dump_signal(str(tmp_path), rank=0)
    try:
        os.kill(os.getpid(), signal.SIGUSR2)
        path = flight_file_path(str(tmp_path), 0)
        assert os.path.exists(path)
        payload = json.load(open(path))
        assert payload["reason"] == "sigusr2"
        assert any(r.get("marker") == "sigusr2-test"
                   for r in payload["iterations"])
    finally:
        signal.signal(signal.SIGUSR2, signal.SIG_DFL)


def test_stall_diagnosis_embeds_flight_tail_and_dumps_file(tmp_path):
    """RunGuard wiring: a tripped watchdog's diagnosis carries the
    recorder's iteration tail under `flight`, and the full flight
    record lands next to stall-rank<r>.json."""
    import time

    from lightgbm_tpu.reliability.guard import RunGuard

    flight_recorder.record_iteration(iteration=77, marker="pre-stall")
    hits = []
    g = RunGuard(str(tmp_path), rank=0, stall_floor_s=0.1,
                 stall_factor=1.0, first_deadline_s=0.2,
                 on_stall=hits.append, poll_interval=0.05)
    g.start()
    deadline = time.monotonic() + 10.0
    while not hits and time.monotonic() < deadline:
        time.sleep(0.02)
    g.stop()
    assert hits, "watchdog never tripped"
    diag = hits[0]
    assert any(r.get("marker") == "pre-stall" for r in diag["flight"])
    fpath = flight_file_path(str(tmp_path), 0)
    assert os.path.exists(fpath)
    assert json.load(open(fpath))["reason"] == "stall"


def test_crash_dump_lands_next_to_event_log(tmp_path):
    """engine.train's unwind dumps the flight record when a metrics run
    dies, so the supervisor's failure report can surface it."""
    import lightgbm_tpu as lgb

    rng = np.random.RandomState(5)
    X = rng.rand(200, 4)
    y = X[:, 0].astype(np.float64)
    d = str(tmp_path / "metrics")

    def boom(env):
        if env.iteration >= 1:
            raise RuntimeError("injected crash")

    try:
        lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "metrics_dir": d},
                  lgb.Dataset(X, label=y), num_boost_round=5,
                  callbacks=[boom])
    except RuntimeError:
        pass
    else:
        raise AssertionError("injected crash did not propagate")
    payload = json.load(open(flight_file_path(d, 0)))
    assert payload["reason"] == "crash"
    assert any(r.get("iteration") == 1 for r in payload["iterations"])
