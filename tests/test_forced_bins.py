"""forcedbins_filename: forced bin upper bounds inside FindBin
(ref: src/io/bin.cpp:157-240 FindBinWithPredefinedBin,
dataset_loader.cpp:1493 GetForcedBins; examples/regression/forced_bins.json)."""

import json

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.io.dataset import get_forced_bins

REF_JSON = "/root/reference/examples/regression/forced_bins.json"


def _data(n=3000, F=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F) * 2 - 1
    y = X[:, 0] * 3 + np.where(X[:, 1] > -0.15, 1.0, -1.0)
    return X, y


def test_forced_bounds_change_boundaries(tmp_path):
    X, y = _data()
    fb = tmp_path / "forced.json"
    fb.write_text(json.dumps([
        {"feature": 0, "bin_upper_bound": [0.3, 0.35, 0.4]},
        {"feature": 1, "bin_upper_bound": [-0.1, -0.15, -0.2]},
    ]))
    params = {"objective": "regression", "verbosity": -1, "max_bin": 16}
    ds_plain = lgb.Dataset(X, label=y, params=params)
    ds_plain._core_or_construct()
    ds_forced = lgb.Dataset(X, label=y, params={
        **params, "forcedbins_filename": str(fb)})
    ds_forced._core_or_construct()

    ub0 = ds_forced._core.bin_mappers[0].bin_upper_bound
    ub1 = ds_forced._core.bin_mappers[1].bin_upper_bound
    for v in (0.3, 0.35, 0.4):
        assert np.any(np.isclose(ub0, v)), (v, ub0)
    for v in (-0.1, -0.15, -0.2):
        assert np.any(np.isclose(ub1, v)), (v, ub1)
    # untouched feature keeps identical boundaries
    np.testing.assert_array_equal(
        ds_plain._core.bin_mappers[2].bin_upper_bound,
        ds_forced._core.bin_mappers[2].bin_upper_bound)
    # and the boundaries really differ where forced
    assert not np.array_equal(ds_plain._core.bin_mappers[0].bin_upper_bound,
                              ub0)
    # training works end to end with the forced mappers
    b = lgb.train({**params, "forcedbins_filename": str(fb)},
                  ds_forced, num_boost_round=5)
    assert b.current_iteration() == 5


def test_forced_bins_reference_example_round_trip():
    """The reference's own forced_bins.json drives bin boundaries through
    the file-loading (CLI) path."""
    ds = lgb.Dataset("/root/reference/examples/regression/regression.train",
                     params={"forcedbins_filename": REF_JSON,
                             "max_bin": 32})
    ds._core_or_construct()
    ub0 = ds._core.bin_mappers[0].bin_upper_bound
    for v in (0.3, 0.35, 0.4):
        assert np.any(np.isclose(ub0, v)), (v, ub0)


def test_forced_bins_categorical_skipped_and_missing_file_warns(tmp_path):
    X, y = _data()
    X[:, 3] = np.random.RandomState(1).randint(0, 5, len(X))
    fb = tmp_path / "forced.json"
    fb.write_text(json.dumps([
        {"feature": 3, "bin_upper_bound": [1.0, 2.0]}]))
    ds = lgb.Dataset(X, label=y, params={
        "forcedbins_filename": str(fb), "verbosity": -1},
        categorical_feature=[3])
    ds._core_or_construct()              # categorical: warn + ignore
    assert ds._core.bin_mappers[3].bin_type == 1  # BIN_CATEGORICAL
    # missing file: warn + ignore, identical to no forced bins
    got = get_forced_bins(str(tmp_path / "nope.json"), 4, ())
    assert got == [[], [], [], []]


def test_forced_bins_out_of_range_feature_fatals(tmp_path):
    from lightgbm_tpu.utils.log import LightGBMError
    fb = tmp_path / "forced.json"
    fb.write_text(json.dumps([{"feature": 9, "bin_upper_bound": [1.0]}]))
    with pytest.raises(LightGBMError):
        get_forced_bins(str(fb), 4, ())


def test_forced_bins_sparse_path(tmp_path):
    import scipy.sparse as sp
    rng = np.random.RandomState(0)
    m = sp.random(3000, 10, density=0.2, random_state=rng,
                  data_rvs=lambda k: rng.rand(k)).tocsr()
    y = np.asarray(m[:, 0].todense()).ravel()
    fb = tmp_path / "forced.json"
    fb.write_text(json.dumps([
        {"feature": 0, "bin_upper_bound": [0.25, 0.5, 0.75]}]))
    ds = lgb.Dataset(m, label=y, params={
        "forcedbins_filename": str(fb), "verbosity": -1})
    ds._core_or_construct()
    ub0 = ds._core.bin_mappers[0].bin_upper_bound
    for v in (0.25, 0.5, 0.75):
        assert np.any(np.isclose(ub0, v)), (v, ub0)
