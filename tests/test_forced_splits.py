"""Forced splits (ref: serial_tree_learner.cpp:614 ForceSplits;
examples/binary_classification/forced_splits.json format)."""

import json

import numpy as np

import lightgbm_tpu as lgb


def _train_with_forced(tmp_path, forced, n=2000, rounds=2, leaves=8):
    rng = np.random.RandomState(4)
    X = rng.rand(n, 3)
    y = X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.randn(n)
    path = tmp_path / "forced.json"
    path.write_text(json.dumps(forced))
    b = lgb.train({"objective": "regression", "num_leaves": leaves,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "forcedsplits_filename": str(path)},
                  lgb.Dataset(X, label=y), num_boost_round=rounds)
    b._gbdt._sync_model()
    return b


def test_root_split_is_forced(tmp_path):
    b = _train_with_forced(tmp_path,
                           {"feature": 2, "threshold": 0.5})
    for t in b._gbdt.models_:
        assert t.split_feature[0] == 2          # noise feature forced
        assert abs(t.threshold[0] - 0.5) < 0.02


def test_nested_forced_splits(tmp_path):
    forced = {"feature": 2, "threshold": 0.5,
              "left": {"feature": 1, "threshold": 0.25},
              "right": {"feature": 1, "threshold": 0.75}}
    b = _train_with_forced(tmp_path, forced)
    t = b._gbdt.models_[0]
    assert t.split_feature[0] == 2
    # node 1 splits the LEFT child (leaf 0), node 2 the RIGHT (leaf 1)
    assert t.split_feature[1] == 1 and t.split_feature[2] == 1
    assert t.left_child[0] == 1 and t.right_child[0] == 2
    assert abs(t.threshold[1] - 0.25) < 0.02
    assert abs(t.threshold[2] - 0.75) < 0.02


def test_growth_continues_after_forced(tmp_path):
    b = _train_with_forced(tmp_path, {"feature": 2, "threshold": 0.5},
                           leaves=16)
    t = b._gbdt.models_[0]
    assert t.num_leaves == 16
    # the model still learns the real signal after the forced noise split
    rng = np.random.RandomState(4)
    X = rng.rand(2000, 3)
    y = X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.randn(2000)
    assert np.corrcoef(b.predict(X), y)[0, 1] > 0.8


def test_invalid_forced_split_is_skipped(tmp_path):
    """A forced threshold outside the data range produces an empty child:
    the forced split is abandoned but best-gain growth continues
    (ForceSplits semantics), not a dead stump."""
    b = _train_with_forced(tmp_path, {"feature": 2, "threshold": 99.0},
                           leaves=8)
    t = b._gbdt.models_[0]
    assert t.num_leaves == 8          # growth continued
    assert t.split_feature[0] != 2    # forced split was skipped


def test_forced_abort_chain(tmp_path):
    """Once a forced split is skipped, the remaining forced splits abort
    (parse-time leaf numbers are stale) and best-gain growth fills the
    budget."""
    forced = {"feature": 2, "threshold": 99.0,            # invalid: skips
              "left": {"feature": 1, "threshold": 0.5},   # must abort
              "right": {"feature": 1, "threshold": 0.5}}  # must abort
    b = _train_with_forced(tmp_path, forced, leaves=8)
    t = b._gbdt.models_[0]
    assert t.num_leaves == 8
    assert t.split_feature[0] != 2  # root chosen by gain, not forcing


def test_forced_respects_max_depth(tmp_path):
    import json
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(4)
    X = rng.rand(2000, 3)
    y = X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.randn(2000)
    forced = {"feature": 2, "threshold": 0.5,
              "left": {"feature": 1, "threshold": 0.25,
                       "left": {"feature": 0, "threshold": 0.5}}}
    path = tmp_path / "forced.json"
    path.write_text(json.dumps(forced))
    b = lgb.train({"objective": "regression", "num_leaves": 8,
                   "verbosity": -1, "min_data_in_leaf": 5, "max_depth": 2,
                   "forcedsplits_filename": str(path)},
                  lgb.Dataset(X, label=y), num_boost_round=1)
    b._gbdt._sync_model()
    t = b._gbdt.models_[0]
    assert t.leaf_depth[:t.num_leaves].max() <= 2


def _train_with_forced_wave(tmp_path, forced, n=2048, rounds=2, leaves=8):
    rng = np.random.RandomState(4)
    X = rng.rand(n, 3)
    y = X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.randn(n)
    path = tmp_path / "forced_w.json"
    path.write_text(json.dumps(forced))
    b = lgb.train({"objective": "regression", "num_leaves": leaves,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "tpu_growth_strategy": "wave",
                   "forcedsplits_filename": str(path)},
                  lgb.Dataset(X, label=y), num_boost_round=rounds)
    b._gbdt._sync_model()
    assert b._gbdt.growth_strategy == "wave"
    return b


def test_wave_root_split_is_forced(tmp_path):
    """Forced splits now run ON THE WAVE ENGINE (one forced split per
    prologue wave, wave.py) instead of falling back to leaf-wise."""
    b = _train_with_forced_wave(tmp_path, {"feature": 2, "threshold": 0.5})
    for t in b._gbdt.models_:
        assert t.split_feature[0] == 2
        assert abs(t.threshold[0] - 0.5) < 0.02


def test_wave_nested_forced_matches_leafwise_prefix(tmp_path):
    forced = {"feature": 2, "threshold": 0.5,
              "left": {"feature": 1, "threshold": 0.25},
              "right": {"feature": 1, "threshold": 0.75}}
    bw = _train_with_forced_wave(tmp_path, forced)
    bl = _train_with_forced(tmp_path, forced, n=2048)
    tw, tl = bw._gbdt.models_[0], bl._gbdt.models_[0]
    # the forced prefix (3 nodes) is engine-independent
    for s in range(3):
        assert tw.split_feature[s] == tl.split_feature[s], s
        assert abs(tw.threshold[s] - tl.threshold[s]) < 1e-9, s
    assert tw.left_child[0] == 1 and tw.right_child[0] == 2


def test_wave_growth_continues_after_forced(tmp_path):
    b = _train_with_forced_wave(tmp_path, {"feature": 2, "threshold": 0.5},
                                leaves=16)
    t = b._gbdt.models_[0]
    assert t.num_leaves == 16
    rng = np.random.RandomState(4)
    X = rng.rand(2048, 3)
    y = X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.randn(2048)
    assert np.corrcoef(b.predict(X), y)[0, 1] > 0.8


def test_wave_invalid_forced_split_is_skipped(tmp_path):
    b = _train_with_forced_wave(tmp_path,
                                {"feature": 2, "threshold": 99.0},
                                leaves=8)
    t = b._gbdt.models_[0]
    assert t.num_leaves == 8
    assert t.split_feature[0] != 2


def test_wave_forced_abort_chain(tmp_path):
    forced = {"feature": 2, "threshold": 99.0,
              "left": {"feature": 1, "threshold": 0.5},
              "right": {"feature": 1, "threshold": 0.5}}
    b = _train_with_forced_wave(tmp_path, forced, leaves=8)
    t = b._gbdt.models_[0]
    assert t.num_leaves == 8
    assert t.split_feature[0] != 2


def test_wave_forced_deep_growth_cache_consistency(tmp_path):
    """Regression: with a forced prologue the ladder's slot bounds must
    be MULTIPLICATIVE in (KF+1) — the old additive bound undersized the
    computed-slot kernel from wave ~5 on (>= ~24 splits/wave), silently
    zero-padding real children and corrupting sibling subtraction.
    Detectable as leaf counts that no longer partition the rows."""
    rng = np.random.RandomState(7)
    n = 16384
    X = rng.rand(n, 6)
    y = (X[:, 0] + 2 * X[:, 1] * X[:, 2] + 0.5 * np.sin(6 * X[:, 3])
         + 0.1 * rng.randn(n))
    path = tmp_path / "forced_deep.json"
    path.write_text(json.dumps({"feature": 5, "threshold": 0.5}))
    b = lgb.train({"objective": "regression", "num_leaves": 96,
                   "verbosity": -1, "min_data_in_leaf": 2,
                   "tpu_growth_strategy": "wave",
                   "forcedsplits_filename": str(path)},
                  lgb.Dataset(X, label=y), num_boost_round=2)
    b._gbdt._sync_model()
    for t in b._gbdt.models_:
        assert t.split_feature[0] == 5
        assert t.num_leaves >= 64, t.num_leaves
        # exact row partition: corruption in the cache shows up here
        assert int(t.leaf_count[:t.num_leaves].sum()) == n
        assert int(t.internal_count[0]) == n
