"""Tests for the jitted whole-tree grower (learner/grow.py)."""

import numpy as np
import jax.numpy as jnp

from lightgbm_tpu.learner import FeatureMeta, GrowParams, grow_tree
from lightgbm_tpu.ops.split import MISSING_NONE, SplitParams

RNG = np.random.RandomState(3)


def _meta(F, B):
    return FeatureMeta(num_bin=jnp.full(F, B, jnp.int32),
                       missing_type=jnp.full(F, MISSING_NONE, jnp.int32),
                       default_bin=jnp.zeros(F, jnp.int32),
                       penalty=jnp.ones(F, jnp.float32))


def _grow(binned, grad, hess, params):
    F, n = binned.shape
    return grow_tree(jnp.array(binned), jnp.array(grad), jnp.array(hess),
                     jnp.ones(n, jnp.float32), jnp.ones(F, bool),
                     _meta(F, params.max_bin), params)


def test_single_split_tree():
    """One perfectly-separating feature, num_leaves=2."""
    n = 100
    binned = np.zeros((1, n), dtype=np.int32)
    binned[0, n // 2:] = 5
    grad = np.where(np.arange(n) >= n // 2, 2.0, -2.0).astype(np.float32)
    hess = np.ones(n, np.float32)
    params = GrowParams(num_leaves=2, max_bin=8,
                        split=SplitParams(min_data_in_leaf=1))
    tree, leaf_id = _grow(binned, grad, hess, params)
    assert int(tree.num_leaves) == 2
    assert int(tree.split_feature[0]) == 0
    assert 0 <= int(tree.threshold_bin[0]) < 5
    # left leaf (id 0) holds grad=-2 rows -> output +2; right (id 1) -> -2
    np.testing.assert_allclose(float(tree.leaf_value[0]), 2.0, atol=0.01)
    np.testing.assert_allclose(float(tree.leaf_value[1]), -2.0, atol=0.01)
    lid = np.asarray(leaf_id)
    assert (lid[:n // 2] == 0).all() and (lid[n // 2:] == 1).all()
    assert int(tree.leaf_count[0]) == n // 2
    assert int(tree.leaf_count[1]) == n // 2


def test_grow_reduces_squared_error():
    """Leaf outputs on L2 gradients must reduce train MSE monotonically in leaves."""
    n, F, B = 1024, 4, 32
    X = RNG.rand(n, F)
    y = (np.sin(X[:, 0] * 6) + X[:, 1] ** 2 + 0.1 * RNG.randn(n)).astype(np.float32)
    binned = np.stack([np.clip((X[:, f] * B).astype(np.int32), 0, B - 1)
                       for f in range(F)]).astype(np.int32)
    grad = -y  # L2 gradients at score 0 (grad = score - y)
    hess = np.ones(n, np.float32)
    prev = np.inf
    for L in (2, 8, 31):
        params = GrowParams(num_leaves=L, max_bin=B,
                            split=SplitParams(min_data_in_leaf=5, lambda_l2=0.0))
        tree, leaf_id = _grow(binned, grad, hess, params)
        pred = np.asarray(tree.leaf_value)[np.asarray(leaf_id)]
        mse = float(np.mean((y - pred) ** 2))
        assert mse < prev, (L, mse, prev)
        prev = mse
    assert prev < float(np.var(y)) * 0.35


def test_gain_stopping():
    """Pure-noise constant gradients: no split has positive gain -> 1 leaf."""
    n = 256
    binned = RNG.randint(0, 16, size=(2, n)).astype(np.int32)
    grad = np.ones(n, np.float32)  # constant -> no variance to explain
    hess = np.ones(n, np.float32)
    params = GrowParams(num_leaves=31, max_bin=16,
                        split=SplitParams(min_data_in_leaf=5, min_gain_to_split=0.0))
    tree, leaf_id = _grow(binned, grad, hess, params)
    assert int(tree.num_leaves) == 1
    assert (np.asarray(leaf_id) == 0).all()


def test_max_depth_limits_leaves():
    n, F, B = 2048, 3, 64
    X = RNG.rand(n, F)
    y = (X[:, 0] + X[:, 1] * X[:, 2]).astype(np.float32)
    binned = np.stack([np.clip((X[:, f] * B).astype(np.int32), 0, B - 1)
                       for f in range(F)]).astype(np.int32)
    params = GrowParams(num_leaves=64, max_depth=3, max_bin=B,
                        split=SplitParams(min_data_in_leaf=1))
    tree, _ = _grow(binned, -y, np.ones(n, np.float32), params)
    assert int(tree.num_leaves) <= 8  # 2^3
    assert int(np.asarray(tree.leaf_depth)[:int(tree.num_leaves)].max()) <= 3


def test_row_mask_excludes_rows():
    """Bagged-out rows must not influence the tree (leaf counts)."""
    n = 400
    binned = np.zeros((1, n), dtype=np.int32)
    binned[0, :200] = 1
    grad = np.where(np.arange(n) < 200, -1.0, 1.0).astype(np.float32)
    hess = np.ones(n, np.float32)
    mask = np.zeros(n, np.float32)
    mask[:300] = 1.0
    params = GrowParams(num_leaves=2, max_bin=4,
                        split=SplitParams(min_data_in_leaf=1))
    F = 1
    tree, leaf_id = grow_tree(jnp.array(binned), jnp.array(grad), jnp.array(hess),
                              jnp.array(mask), jnp.ones(F, bool),
                              _meta(F, 4), params)
    assert int(tree.num_leaves) == 2
    assert int(tree.leaf_count[0]) + int(tree.leaf_count[1]) == 300


def test_subtraction_equals_rebuild():
    """use_hist_stack=True (subtraction) and False (rebuild) give identical trees."""
    n, F, B = 1024, 5, 32
    X = RNG.rand(n, F)
    y = (X[:, 0] * 3 + np.cos(X[:, 2] * 7) + 0.05 * RNG.randn(n)).astype(np.float32)
    binned = np.stack([np.clip((X[:, f] * B).astype(np.int32), 0, B - 1)
                       for f in range(F)]).astype(np.int32)
    grad, hess = -y, np.ones(n, np.float32)
    t1, l1 = _grow(binned, grad, hess,
                   GrowParams(num_leaves=16, max_bin=B, use_hist_stack=True,
                              split=SplitParams(min_data_in_leaf=5)))
    t2, l2 = _grow(binned, grad, hess,
                   GrowParams(num_leaves=16, max_bin=B, use_hist_stack=False,
                              split=SplitParams(min_data_in_leaf=5)))
    assert int(t1.num_leaves) == int(t2.num_leaves)
    np.testing.assert_array_equal(np.asarray(t1.split_feature),
                                  np.asarray(t2.split_feature))
    np.testing.assert_array_equal(np.asarray(t1.threshold_bin),
                                  np.asarray(t2.threshold_bin))
    np.testing.assert_allclose(np.asarray(t1.leaf_value), np.asarray(t2.leaf_value),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


def test_compaction_matches_full_scan():
    """Smaller-child row compaction must produce the identical tree to the
    full masked scan (it gathers exactly the child's rows; fp32 segment
    histograms make both paths bit-comparable)."""
    n, F, B = 4096, 6, 32
    rng = np.random.RandomState(11)
    binned = rng.randint(0, B, size=(F, n)).astype(np.int32)
    grad = rng.randn(n).astype(np.float32)
    hess = np.abs(rng.randn(n)).astype(np.float32) + 0.1
    base = GrowParams(num_leaves=31, max_bin=B,
                      split=SplitParams(min_data_in_leaf=5),
                      hist_method="segment")
    t_full, lid_full = _grow(binned, grad, hess,
                             base._replace(compact_min=0))
    t_comp, lid_comp = _grow(binned, grad, hess,
                             base._replace(compact_min=128))
    assert int(t_full.num_leaves) == int(t_comp.num_leaves)
    np.testing.assert_array_equal(np.asarray(t_full.split_feature),
                                  np.asarray(t_comp.split_feature))
    np.testing.assert_array_equal(np.asarray(t_full.threshold_bin),
                                  np.asarray(t_comp.threshold_bin))
    np.testing.assert_array_equal(np.asarray(lid_full), np.asarray(lid_comp))
    np.testing.assert_allclose(np.asarray(t_full.leaf_value),
                               np.asarray(t_comp.leaf_value),
                               rtol=1e-5, atol=1e-6)


def test_compaction_with_bagging_mask():
    """Bagged-out rows are excluded from compaction buffers (their gh is
    zero AND they are not gathered), so masked training matches."""
    n, F, B = 2048, 4, 16
    rng = np.random.RandomState(12)
    binned = rng.randint(0, B, size=(F, n)).astype(np.int32)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    row_mask = (rng.rand(n) > 0.4).astype(np.float32)
    meta = _meta(F, B)
    base = GrowParams(num_leaves=15, max_bin=B,
                      split=SplitParams(min_data_in_leaf=3),
                      hist_method="segment")
    import jax.numpy as jnp_
    args = (jnp_.array(binned), jnp_.array(grad), jnp_.array(hess),
            jnp_.array(row_mask), jnp_.ones(F, bool), meta)
    t_full, _ = grow_tree(*args, base._replace(compact_min=0))
    t_comp, _ = grow_tree(*args, base._replace(compact_min=128))
    assert int(t_full.num_leaves) == int(t_comp.num_leaves)
    np.testing.assert_array_equal(np.asarray(t_full.split_feature),
                                  np.asarray(t_comp.split_feature))
    np.testing.assert_array_equal(np.asarray(t_full.threshold_bin),
                                  np.asarray(t_comp.threshold_bin))


def test_wave_matches_leafwise_on_depth_monotone_gains():
    """Wave growth (split every positive-gain leaf per round) equals strict
    leaf-wise only when split gains decrease monotonically with depth —
    otherwise leaf-wise may spend its budget deepening one branch while wave
    spreads level-by-level.  Build such data: y = 8*x0 + 4*x1 + 2*x2 + 1*x3
    on binary features, whose balanced tree has per-depth gains
    ~ amplitude^2 * count, strictly decreasing; both engines must then grow
    the identical full depth-4 tree with identical per-row predictions."""
    from lightgbm_tpu.learner import grow_tree_wave
    n, F = 2048, 4
    rng = np.random.RandomState(21)
    binned = rng.randint(0, 2, size=(F, n)).astype(np.int32)
    y = (8.0 * binned[0] + 4.0 * binned[1] + 2.0 * binned[2]
         + 1.0 * binned[3]).astype(np.float32)
    grad = -y
    hess = np.ones(n, np.float32)
    params = GrowParams(num_leaves=16, max_bin=4,
                        split=SplitParams(min_data_in_leaf=5),
                        hist_method="segment")
    t_lw, lid_lw = _grow(binned, grad, hess, params)
    args = (jnp.array(binned), jnp.array(grad), jnp.array(hess),
            jnp.ones(n, jnp.float32), jnp.ones(F, bool), _meta(F, 4))
    t_wv, lid_wv = grow_tree_wave(*args, params)
    assert int(t_lw.num_leaves) == 16
    assert int(t_wv.num_leaves) == 16
    pred_lw = np.asarray(t_lw.leaf_value)[np.asarray(lid_lw)]
    pred_wv = np.asarray(t_wv.leaf_value)[np.asarray(lid_wv)]
    np.testing.assert_allclose(pred_lw, pred_wv, rtol=1e-4, atol=1e-5)


def test_wave_respects_budget_and_quality():
    """Non-pow2 budget: wave must stop exactly at num_leaves and reduce MSE
    comparably to leaf-wise."""
    from lightgbm_tpu.learner import grow_tree_wave
    n, F, B = 2048, 5, 32
    rng = np.random.RandomState(22)
    X = rng.rand(n, F)
    y = (np.sin(X[:, 0] * 6) + X[:, 1] ** 2 + 0.1 * rng.randn(n)).astype(np.float32)
    binned = np.stack([np.clip((X[:, f] * B).astype(np.int32), 0, B - 1)
                       for f in range(F)]).astype(np.int32)
    grad, hess = -y, np.ones(n, np.float32)
    params = GrowParams(num_leaves=23, max_bin=B,
                        split=SplitParams(min_data_in_leaf=5),
                        hist_method="segment")
    args = (jnp.array(binned), jnp.array(grad), jnp.array(hess),
            jnp.ones(n, jnp.float32), jnp.ones(F, bool), _meta(F, B))
    t_wv, lid_wv = grow_tree_wave(*args, params)
    assert int(t_wv.num_leaves) <= 23
    t_lw, lid_lw = _grow(binned, grad, hess, params)
    mse_wv = float(np.mean((y - np.asarray(t_wv.leaf_value)[np.asarray(lid_wv)]) ** 2))
    mse_lw = float(np.mean((y - np.asarray(t_lw.leaf_value)[np.asarray(lid_lw)]) ** 2))
    assert mse_wv < 1.3 * mse_lw, (mse_wv, mse_lw)


def test_wave_tree_structure_is_consistent():
    """Wave trees must be structurally valid: child pointers resolve, leaf
    ids match traversal, counts sum to n."""
    from lightgbm_tpu.learner import grow_tree_wave
    n, F, B = 1024, 4, 16
    rng = np.random.RandomState(23)
    binned = rng.randint(0, B, size=(F, n)).astype(np.int32)
    grad = rng.randn(n).astype(np.float32)
    hess = np.ones(n, np.float32)
    params = GrowParams(num_leaves=15, max_bin=B,
                        split=SplitParams(min_data_in_leaf=2),
                        hist_method="segment")
    args = (jnp.array(binned), jnp.array(grad), jnp.array(hess),
            jnp.ones(n, jnp.float32), jnp.ones(F, bool), _meta(F, B))
    tree, leaf_id = grow_tree_wave(*args, params)
    nl = int(tree.num_leaves)
    lid = np.asarray(leaf_id)
    counts = np.asarray(tree.leaf_count)[:nl]
    assert counts.sum() == n
    # every row's leaf matches a fresh traversal of the built tree
    node = np.zeros(n, dtype=np.int64)
    sf = np.asarray(tree.split_feature)
    tb = np.asarray(tree.threshold_bin)
    lc = np.asarray(tree.left_child)
    rc = np.asarray(tree.right_child)
    for _ in range(nl):
        active = node >= 0
        if not active.any():
            break
        nd = node[active].astype(int)
        b = binned[sf[nd], np.nonzero(active)[0]]
        go_left = b <= tb[nd]
        node[active] = np.where(go_left, lc[nd], rc[nd])
    np.testing.assert_array_equal((~node).astype(np.int64), lid)
