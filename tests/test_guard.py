"""Stall watchdog + graceful-degradation ladder (ISSUE 7 tentpole).

MULTICHIP_r05 hung to the wall-clock cap with one stderr line; these
tests pin the machinery that turns that shape into a diagnosis and an
auto-recovered run: the RunGuard trips on a missing heartbeat and writes
a parseable stall diagnosis, a hung process exits with the distinct
STALL code (classified hang, not crash), the supervisor catches
live-but-silent ranks by heartbeat mtime, and an auto_degrade relaunch
resumes from checkpoint with exactly one ladder knob disabled —
producing a byte-identical model to an uninterrupted run with that knob
off."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.reliability.guard import (DEGRADE_LADDER,
                                            STALL_EXIT_CODE, RunGuard,
                                            apply_auto_degrade,
                                            classify_returncode,
                                            disabled_value, knob_enabled,
                                            next_degradation)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# wall-clock bound for each guard subprocess (compile + a few rounds +
# the ~3 s stall deadline; a REAL runaway blows far past this)
SUBPROC_BUDGET_S = 240.0


# --------------------------------------------------------------------------
# RunGuard unit behavior (in-process, no subprocesses)
# --------------------------------------------------------------------------

def test_watchdog_trips_and_writes_parseable_diagnosis(tmp_path):
    hits = []
    g = RunGuard(str(tmp_path), rank=3, stall_floor_s=0.2, stall_factor=2.0,
                 first_deadline_s=0.3, knobs={"tpu_donate_buffers": True},
                 on_stall=hits.append, poll_interval=0.05)
    g.start()
    try:
        deadline = time.monotonic() + 10.0
        while not g.tripped and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        g.stop()
    assert g.tripped and len(hits) == 1
    diag = json.load(open(tmp_path / "stall-rank3.json"))
    for key in ("kind", "rank", "silent_s", "deadline_s", "last_iteration",
                "knobs", "stacks", "jax", "exit_code"):
        assert key in diag, f"diagnosis missing {key}"
    assert diag["kind"] == "stall"
    assert diag["rank"] == 3
    assert diag["exit_code"] == STALL_EXIT_CODE
    assert diag["knobs"]["tpu_donate_buffers"] is True
    # the faulthandler dump really captured Python frames
    assert any("File" in line for line in diag["stacks"])


def test_first_compile_deadline_is_larger_then_median_takes_over(tmp_path):
    g = RunGuard(str(tmp_path), stall_floor_s=1.0, stall_factor=2.0,
                 first_deadline_s=50.0)
    # before any tick: the first-compile deadline rules
    assert g.current_deadline_s() == 50.0
    g._started_at = time.monotonic()
    g.tick(1)
    # one tick but no duration sample yet: still the conservative deadline
    assert g.current_deadline_s() == 50.0
    g.tick(2)
    # median known: deadline drops to max(floor, factor * median)
    assert g.median_iter_s() is not None
    assert g.current_deadline_s() == pytest.approx(
        max(1.0, 2.0 * g.median_iter_s()))
    assert g.current_deadline_s() < 50.0


def test_default_first_deadline_scales_with_floor(tmp_path):
    assert RunGuard(str(tmp_path),
                    stall_floor_s=120.0).first_deadline_s == 1200.0
    # tiny test floors still get a compile-sized first window
    assert RunGuard(str(tmp_path),
                    stall_floor_s=2.0).first_deadline_s == 600.0


def test_slow_iteration_under_deadline_does_not_trip(tmp_path):
    g = RunGuard(str(tmp_path), stall_floor_s=1.0, stall_factor=20.0,
                 first_deadline_s=30.0, on_stall=lambda d: None,
                 poll_interval=0.05)
    g.start()
    try:
        for i in range(1, 5):
            time.sleep(0.05)
            g.tick(i)
        time.sleep(0.5)  # slow_iter-shaped pause, well under the 1 s floor
        g.tick(5)
    finally:
        g.stop()
    assert not g.tripped


def test_tick_touches_heartbeat_file(tmp_path):
    hb = tmp_path / "heartbeat-rank0"
    g = RunGuard(str(tmp_path), stall_floor_s=60.0, heartbeat_path=str(hb))
    g._started_at = time.monotonic()
    g.tick(1)
    assert hb.exists()
    first = hb.stat().st_mtime
    time.sleep(0.05)
    g.tick(2)
    assert hb.stat().st_mtime >= first


# --------------------------------------------------------------------------
# classification + ladder units
# --------------------------------------------------------------------------

def test_classify_returncode():
    assert classify_returncode(0) == "ok"
    assert classify_returncode(STALL_EXIT_CODE) == "hang"
    assert classify_returncode(None) == "hang"   # killed past a deadline
    assert classify_returncode(124) == "hang"    # timeout(1)
    assert classify_returncode(17) == "crash"    # faults.CRASH_EXIT_CODE
    assert classify_returncode(1) == "crash"


def test_degradation_ladder_order_and_values():
    assert [k for k, _ in DEGRADE_LADDER] == [
        "tpu_donate_buffers", "compile_cache_dir", "async_host_io",
        "device_eval"]
    enabled = {"tpu_donate_buffers": True, "compile_cache_dir": "/c",
               "async_host_io": True, "device_eval": "auto"}
    order = []
    done = []
    while True:
        k = next_degradation(enabled, done)
        if k is None:
            break
        order.append(k)
        done.append(k)
    assert order == [k for k, _ in DEGRADE_LADDER]
    # knobs already off are skipped
    assert next_degradation({**enabled, "tpu_donate_buffers": False},
                            []) == "compile_cache_dir"
    assert next_degradation({"tpu_donate_buffers": False,
                             "compile_cache_dir": "", "async_host_io": False,
                             "device_eval": "false"}, []) is None
    assert disabled_value("device_eval") == "false"
    assert knob_enabled("device_eval", "auto")
    assert not knob_enabled("compile_cache_dir", "  ")


def test_apply_auto_degrade_walks_the_ladder(tmp_path):
    mdir = str(tmp_path)

    def stall_once(cfg):
        """Simulate a watchdog trip with cfg's effective knobs."""
        with open(os.path.join(mdir, "stall-rank0.json"), "w") as f:
            json.dump({"kind": "stall", "last_iteration": 3,
                       "knobs": {k: getattr(cfg, k)
                                 for k, _ in DEGRADE_LADDER}}, f)

    params = {"compile_cache_dir": "/tmp/cache"}
    seen = []
    for expect in ("tpu_donate_buffers", "compile_cache_dir",
                   "async_host_io", "device_eval"):
        cfg = Config(dict(params))
        # re-apply prior degradations (as a restarted engine does), then
        # hang and restart once more
        apply_auto_degrade(cfg, params, mdir)
        stall_once(cfg)
        cfg = Config(dict(params))
        out = apply_auto_degrade(cfg, params, mdir)
        assert out["new"] == [expect]
        seen.append(expect)
        assert out["applied"] == seen
        assert not knob_enabled(expect, getattr(cfg, expect))
    # ladder exhausted: a fifth stall degrades nothing further
    stall_once(Config(dict(params)))
    out = apply_auto_degrade(Config(dict(params)), params, mdir)
    assert out["new"] == []
    assert out["applied"] == seen
    # every consumed stall file was archived, none left pending
    assert not os.path.exists(os.path.join(mdir, "stall-rank0.json"))
    assert len([p for p in os.listdir(mdir) if ".handled-" in p]) == 5


# --------------------------------------------------------------------------
# supervisor: live-but-silent ranks via heartbeat mtime
# --------------------------------------------------------------------------

def test_supervise_kills_cluster_on_stale_heartbeat(tmp_path):
    from lightgbm_tpu.reliability.supervisor import supervise
    logs = []
    hbs = []
    for r in range(2):
        lp = tmp_path / f"w{r}.log"
        lp.write_text(f"worker {r} alive\n")
        logs.append(str(lp))
        hb = tmp_path / f"heartbeat-rank{r}"
        hb.write_text("")
        hbs.append(str(hb))
    # rank 1 stalled 60 s ago; rank 0 is current
    old = time.time() - 60.0
    os.utime(hbs[1], (old, old))
    os.utime(hbs[0], None)
    # rank 1's guard wrote its diagnosis before wedging completely
    (tmp_path / "stall-rank1.json").write_text(
        json.dumps({"kind": "stall", "last_iteration": 4,
                    "knobs": {"tpu_donate_buffers": True}}))
    procs = [subprocess.Popen([sys.executable, "-c",
                               "import time; time.sleep(600)"])
             for _ in range(2)]
    t0 = time.monotonic()
    try:
        res = supervise(procs, logs, timeout=120.0, poll_interval=0.1,
                        heartbeats=hbs, stall_timeout=5.0,
                        stall_dir=str(tmp_path))
    finally:
        for p in procs:
            p.kill()
            p.wait()
    elapsed = time.monotonic() - t0
    assert elapsed < 30, f"stale-heartbeat kill took {elapsed:.0f}s"
    assert not res.ok
    assert res.hang, "a live-but-silent rank must classify as hang"
    stalled = [f for f in res.failures if f.kind == "hang"]
    assert [f.rank for f in stalled] == [1]
    msg = res.describe()
    assert "live-but-hung" in msg
    # the stalled rank's diagnosis tail is surfaced in the failure log
    assert "stall-rank1.json" in msg and "last_iteration" in msg


def test_supervise_classifies_stall_exit_code_as_hang(tmp_path):
    from lightgbm_tpu.reliability.supervisor import supervise
    lp = tmp_path / "w0.log"
    lp.write_text("about to stall\n")
    p = subprocess.Popen([sys.executable, "-c",
                          f"import os; os._exit({STALL_EXIT_CODE})"])
    res = supervise([p], [str(lp)], timeout=60.0, poll_interval=0.05)
    assert not res.ok and res.hang
    assert res.failures[0].kind == "hang"
    assert f"exit code {STALL_EXIT_CODE} (hang)" in res.describe()


# --------------------------------------------------------------------------
# SIGTERM flush: a supervisor kill keeps the event log complete
# --------------------------------------------------------------------------

@pytest.mark.skipif(not hasattr(signal, "SIGTERM"), reason="no SIGTERM")
def test_sigterm_flushes_async_event_log(tmp_path):
    code = f"""
import os, signal, sys, time
sys.path.insert(0, {REPO!r})
from lightgbm_tpu.observability import (AsyncWriter, EventLogger,
                                        install_sigterm_flush,
                                        set_event_logger)
w = AsyncWriter()
lg = EventLogger({str(tmp_path)!r}, rank=0, writer=w)
set_event_logger(lg)
assert install_sigterm_flush()
for i in range(200):
    lg.emit("iteration", iteration=i)
os.kill(os.getpid(), signal.SIGTERM)
time.sleep(30)  # never reached: the handler re-raises SIGTERM
"""
    res = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=120)
    # died OF SIGTERM (not a normal exit): the handler re-delivers it
    assert res.returncode in (-signal.SIGTERM, 128 + signal.SIGTERM), \
        f"rc={res.returncode}\n{res.stderr}"
    lines = [json.loads(ln) for ln in
             (tmp_path / "events-rank0.jsonl").read_text().splitlines()]
    its = [r["iteration"] for r in lines if r["event"] == "iteration"]
    assert its == list(range(200)), "queued events were dropped on SIGTERM"
    assert lines[-1]["event"] == "sigterm"


def test_register_stack_dump_signal():
    from lightgbm_tpu.reliability import faults
    if not hasattr(signal, "SIGUSR1"):
        pytest.skip("no SIGUSR1 on this platform")
    assert faults.register_stack_dump_signal()


# --------------------------------------------------------------------------
# end-to-end: injected hang -> diagnosis -> degraded resume (acceptance)
# --------------------------------------------------------------------------

_E2E_CHILD = r"""
import os, sys
sys.path.insert(0, os.environ["GUARD_REPO"])
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.model_io import save_model_to_string

d = os.environ["GUARD_DIR"]
rng = np.random.RandomState(5)
X = rng.rand(512, 5)
y = (3 * (X[:, 0] - 0.5) + X[:, 1] * X[:, 2]).astype(np.float64)
params = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 5}
if os.environ.get("GUARD_MODE") == "clean":
    # the uninterrupted reference run, trained with the knob the ladder
    # will disable already off
    params["tpu_donate_buffers"] = False
else:
    params.update({"metrics_dir": os.path.join(d, "metrics"),
                   "checkpoint_dir": os.path.join(d, "ckpt"),
                   "checkpoint_freq": 1, "auto_degrade": True,
                   "stall_floor_s": 1.0, "stall_factor": 3.0})
b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
txt = save_model_to_string(b._gbdt).split("\nparameters:")[0]
with open(os.path.join(d, os.environ["GUARD_MODEL"]), "w") as f:
    f.write(txt)
print("GUARD_DONE", b.current_iteration(), flush=True)
"""


def _run_child(tmp_path, script, mode, model_name, attempt, fault=""):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "GUARD_REPO": REPO,
                "GUARD_DIR": str(tmp_path), "GUARD_MODE": mode,
                "GUARD_MODEL": model_name,
                "LGBM_TPU_FAULT_ATTEMPT": str(attempt)})
    if fault:
        env["LGBM_TPU_FAULT"] = fault
    else:
        env.pop("LGBM_TPU_FAULT", None)
    t0 = time.monotonic()
    res = subprocess.run([sys.executable, str(script)], env=env,
                         capture_output=True, text=True,
                         timeout=SUBPROC_BUDGET_S)
    assert time.monotonic() - t0 < SUBPROC_BUDGET_S
    return res


def test_injected_hang_diagnosed_then_degraded_resume_byte_identical(
        tmp_path):
    """Acceptance: hang@3 trips the watchdog (distinct exit code +
    parseable diagnosis), and the auto_degrade relaunch completes from
    the checkpoint with exactly one ladder knob disabled, a `degrade`
    event logged, and a model byte-identical to an uninterrupted run
    with that knob off."""
    script = tmp_path / "child.py"
    script.write_text(_E2E_CHILD)
    fault = "hang@3@0"

    # attempt 0: wedges at iteration 3, watchdog diagnoses + exits
    r0 = _run_child(tmp_path, script, "guard", "model_a0.txt", 0, fault)
    assert r0.returncode == STALL_EXIT_CODE, \
        f"rc={r0.returncode}\nstdout:{r0.stdout}\nstderr:{r0.stderr}"
    assert classify_returncode(r0.returncode) == "hang"
    spath = tmp_path / "metrics" / "stall-rank0.json"
    diag = json.load(open(spath))
    assert diag["last_iteration"] == 3
    assert diag["knobs"]["tpu_donate_buffers"] is True
    assert any("File" in line for line in diag["stacks"])
    # the run's last logged event rode into the diagnosis
    assert diag["last_event"] is not None

    # attempt 1: same command; the engine consumes the stall file,
    # disables donation (ladder rung 1) and resumes from the checkpoint
    r1 = _run_child(tmp_path, script, "guard", "model_deg.txt", 1, fault)
    assert r1.returncode == 0, \
        f"rc={r1.returncode}\nstdout:{r1.stdout}\nstderr:{r1.stderr}"
    assert "GUARD_DONE 6" in r1.stdout
    state = json.load(open(tmp_path / "metrics" / "degrade-state.json"))
    assert state["degraded_knobs"] == ["tpu_donate_buffers"]
    assert not spath.exists(), "the stall file must be consumed"
    events = [json.loads(ln) for ln in
              (tmp_path / "metrics" / "events-rank0.jsonl")
              .read_text().splitlines()]
    degrades = [e for e in events if e["event"] == "degrade"]
    assert len(degrades) == 1
    assert degrades[0]["knobs"] == ["tpu_donate_buffers"]

    # byte parity vs an uninterrupted run with the degraded knob set
    r2 = _run_child(tmp_path, script, "clean", "model_clean.txt", 2)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert (tmp_path / "model_deg.txt").read_bytes() == \
        (tmp_path / "model_clean.txt").read_bytes()
