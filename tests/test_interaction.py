"""Interaction constraints (ref: config.h:585 interaction_constraints;
col_sampler.hpp:91 GetByNode: a leaf splits only on its branch features
plus sets containing the whole branch)."""

import numpy as np

import lightgbm_tpu as lgb


def _collect_paths(tree):
    """Set of features on each root->node path."""
    paths = []

    def walk(node, feats):
        if node < 0:
            paths.append(feats)
            return
        f = int(tree.split_feature[node])
        walk(int(tree.left_child[node]), feats | {f})
        walk(int(tree.right_child[node]), feats | {f})

    if tree.num_leaves > 1:
        walk(0, set())
    return paths


def test_branches_respect_interaction_sets():
    rng = np.random.RandomState(6)
    n = 3000
    X = rng.rand(n, 4)
    # y needs interactions both within and across groups
    y = (X[:, 0] * X[:, 1] + X[:, 2] * X[:, 3]
         + 0.05 * rng.randn(n))
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 5,
              "interaction_constraints": "[0,1],[2,3]"}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    b._gbdt._sync_model()
    allowed = [{0, 1}, {2, 3}]
    for t in b._gbdt.models_:
        for feats in _collect_paths(t):
            assert any(feats <= s for s in allowed), feats


def test_unconstrained_mixes_features():
    rng = np.random.RandomState(6)
    n = 3000
    X = rng.rand(n, 4)
    y = X[:, 0] * X[:, 1] + X[:, 2] * X[:, 3] + 0.05 * rng.randn(n)
    b = lgb.train({"objective": "regression", "num_leaves": 31,
                   "verbosity": -1, "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=10)
    b._gbdt._sync_model()
    allowed = [{0, 1}, {2, 3}]
    mixed = any(not any(feats <= s for s in allowed)
                for t in b._gbdt.models_ for feats in _collect_paths(t))
    assert mixed  # non-vacuity: without constraints branches mix groups


def test_interaction_constraints_list_form():
    """The python API's list-of-lists form must parse too."""
    rng = np.random.RandomState(1)
    X = rng.rand(800, 4)
    y = X[:, 0] * X[:, 1] + 0.05 * rng.randn(800)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "interaction_constraints": [[0, 1], [2, 3]]},
                  lgb.Dataset(X, label=y), num_boost_round=3)
    b._gbdt._sync_model()
    allowed = [{0, 1}, {2, 3}]
    for t in b._gbdt.models_:
        for feats in _collect_paths(t):
            assert any(feats <= s for s in allowed), feats


def test_interaction_on_wave_engine_matches_leafwise():
    """Interaction constraints run on the wave engine (per-leaf branch
    masks): branches must respect the sets, and under full overgrowth
    coverage the pruned wave tree must equal the leaf-wise tree
    structurally (the allowed-feature mask depends only on the path, so
    kept gains are unchanged)."""
    rng = np.random.RandomState(6)
    n = 3000
    X = rng.rand(n, 4)
    y = X[:, 0] * X[:, 1] + X[:, 2] * X[:, 3] + 0.05 * rng.randn(n)
    params = {"objective": "regression", "num_leaves": 15, "max_depth": 5,
              "verbosity": -1, "min_data_in_leaf": 5,
              "interaction_constraints": "[0,1],[2,3]",
              "wave_prune_overshoot": 2.2}
    b_w = lgb.train({**params, "tpu_growth_strategy": "wave"},
                    lgb.Dataset(X, label=y), num_boost_round=6)
    b_l = lgb.train({**params, "tpu_growth_strategy": "leafwise"},
                    lgb.Dataset(X, label=y), num_boost_round=6)
    b_w._gbdt._sync_model(); b_l._gbdt._sync_model()
    allowed = [{0, 1}, {2, 3}]
    for t in b_w._gbdt.models_:
        for feats in _collect_paths(t):
            assert any(feats <= s for s in allowed), feats
    for m_w, m_l in zip(b_w._gbdt.models_, b_l._gbdt.models_):
        assert m_w.num_leaves == m_l.num_leaves
        np.testing.assert_array_equal(np.asarray(m_w.split_feature),
                                      np.asarray(m_l.split_feature))
        np.testing.assert_array_equal(np.asarray(m_w.threshold_in_bin),
                                      np.asarray(m_l.threshold_in_bin))
