"""LatencyWindow correctness (observability/registry.py): percentile
parity vs numpy.percentile, empty/one-sample edges, ring eviction —
the p50/p99 these windows report are the numbers the serving bench
gates on and the /metrics page exports, so they get their own pins."""

import numpy as np

from lightgbm_tpu.observability.registry import LatencyWindow


def test_percentiles_match_numpy_on_random_windows():
    rng = np.random.RandomState(7)
    for trial in range(5):
        n = int(rng.randint(2, 400))
        vals = rng.gamma(2.0, 10.0, size=n)  # latency-shaped tail
        w = LatencyWindow(capacity=1024)
        for v in vals:
            w.record(float(v))
        qs = (50.0, 90.0, 99.0)
        got = w.percentiles(qs)
        want = tuple(float(np.percentile(np.asarray(vals, np.float64), q))
                     for q in qs)
        assert got == want, f"trial {trial}: {got} != {want}"


def test_empty_window_returns_nones():
    w = LatencyWindow()
    assert w.percentiles((50.0, 99.0)) == (None, None)
    assert w.count == 0


def test_single_sample_is_every_percentile():
    w = LatencyWindow()
    w.record(12.5)
    p50, p99 = w.percentiles((50.0, 99.0))
    assert p50 == 12.5 and p99 == 12.5
    assert w.count == 1


def test_ring_bound_evicts_oldest_but_count_is_total():
    w = LatencyWindow(capacity=100)
    for v in range(250):
        w.record(float(v))
    # only the newest 100 samples remain: values 150..249
    p0, p100 = w.percentiles((0.0, 100.0))
    assert p0 == 150.0 and p100 == 249.0
    # count is the lifetime total, not the retained window
    assert w.count == 250


def test_capacity_floor_and_reset():
    w = LatencyWindow(capacity=1)  # floored to 16 internally
    for v in range(20):
        w.record(float(v))
    p0, _ = w.percentiles((0.0, 100.0))
    assert p0 == 4.0  # newest 16 of 20 retained
    w.reset()
    assert w.count == 0
    assert w.percentiles((50.0,)) == (None,)
