"""Linear trees (ref: src/treelearner/linear_tree_learner.cpp:184
CalculateLinear, Shi et al. arXiv:1802.05640; tree.h leaf_coeff_)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _linear_problem(n=3000, seed=9):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 3)
    # piecewise-linear: constant trees need many leaves, linear ones few
    y = np.where(X[:, 0] > 0.5, 2.0 * X[:, 1], -1.5 * X[:, 1]) \
        + 0.05 * rng.randn(n)
    return X, y


def test_linear_tree_beats_constant_leaves():
    X, y = _linear_problem()
    base = {"objective": "regression", "num_leaves": 4, "verbosity": -1,
            "min_data_in_leaf": 20, "learning_rate": 0.5}
    b_const = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=8)
    b_lin = lgb.train({**base, "linear_tree": True},
                      lgb.Dataset(X, label=y, free_raw_data=False),
                      num_boost_round=8)
    mse_const = float(np.mean((b_const.predict(X) - y) ** 2))
    mse_lin = float(np.mean((b_lin.predict(X) - y) ** 2))
    assert mse_lin < mse_const * 0.5, (mse_lin, mse_const)


def test_linear_tree_exact_on_pure_linear():
    """Leaf models regress on BRANCH features (ref: branch_features in
    CalculateLinear): a function piecewise-linear in the split feature is
    represented almost exactly by one split + linear leaves."""
    rng = np.random.RandomState(1)
    n = 2000
    X = rng.rand(n, 2)
    y = np.where(X[:, 0] > 0.5, 3.0 * X[:, 0] - 1.5, -2.0 * X[:, 0])
    # the STRUCTURE is grown with constant-leaf gains (as in the
    # reference), so the split lands near but not at 0.5; a few leaves
    # plus linear models recover the function to high precision
    b = lgb.train({"objective": "regression", "num_leaves": 8,
                   "verbosity": -1, "learning_rate": 1.0,
                   "linear_tree": True, "boost_from_average": False,
                   "min_data_in_leaf": 20},
                  lgb.Dataset(X, label=y, free_raw_data=False),
                  num_boost_round=2)
    mse = float(np.mean((b.predict(X) - y) ** 2))
    # residual error concentrates in the one bin straddling the true
    # breakpoint (thresholds are bin boundaries) — irreducible
    assert mse < 5e-3, mse


def test_linear_tree_model_roundtrip(tmp_path):
    X, y = _linear_problem(n=1500)
    b = lgb.train({"objective": "regression", "num_leaves": 4,
                   "verbosity": -1, "linear_tree": True,
                   "min_data_in_leaf": 20},
                  lgb.Dataset(X, label=y, free_raw_data=False),
                  num_boost_round=4)
    pred = b.predict(X)
    path = str(tmp_path / "linear.txt")
    b.save_model(path)
    text = open(path).read()
    assert "is_linear=1" in text
    assert "leaf_coeff=" in text
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(loaded.predict(X), pred, rtol=1e-6)


def test_linear_tree_nan_rows_fall_back():
    X, y = _linear_problem(n=1500)
    params = {"objective": "regression", "num_leaves": 4, "verbosity": -1,
              "linear_tree": True, "min_data_in_leaf": 20,
              "use_missing": True}
    b = lgb.train(params, lgb.Dataset(X, label=y, free_raw_data=False),
                  num_boost_round=3)
    Xn = X[:10].copy()
    Xn[:, 1] = np.nan
    pred = b.predict(Xn)
    assert np.isfinite(pred).all()


def test_linear_tree_rejects_renewal_objectives():
    X, y = _linear_problem(n=500)
    with pytest.raises(Exception):
        lgb.train({"objective": "regression_l1", "linear_tree": True,
                   "verbosity": -1},
                  lgb.Dataset(X, label=y, free_raw_data=False),
                  num_boost_round=2)
