"""auc_mu metric (ref: multiclass_metric.hpp:183 AucMuMetric): the
vectorized implementation vs a direct transcription of the reference's
sequential Eval loop, on identical scores — including ties, row weights,
and a custom weights matrix."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Metadata
from lightgbm_tpu.metric import AucMuMetric

_EPS = 1e-15


def _auc_mu_reference(score, label, weights, W):
    """Line-faithful port of AucMuMetric::Eval (multiclass_metric.hpp:239)."""
    K = W.shape[0]
    n = score.shape[1]
    label = label.astype(np.int64)
    order = np.argsort(label, kind="stable")     # sorted_data_idx_
    class_sizes = np.bincount(label, minlength=K)
    class_w = (np.array([weights[label == k].sum() for k in range(K)])
               if weights is not None else None)
    S = np.zeros((K, K))
    i_start = 0
    for i in range(K):
        j_start = i_start + class_sizes[i]
        for j in range(i + 1, K):
            v = W[i] - W[j]
            t1 = v[i] - v[j]
            idx = np.concatenate([order[i_start:i_start + class_sizes[i]],
                                  order[j_start:j_start + class_sizes[j]]])
            dist = [(a, t1 * float(v @ score[:, a])) for a in idx]
            import functools
            def cmp(a, b):
                if abs(a[1] - b[1]) < _EPS:
                    return -1 if label[a[0]] > label[b[0]] else 1
                return -1 if a[1] < b[1] else 1
            dist.sort(key=functools.cmp_to_key(cmp))
            num_j = 0.0
            last_j_dist = 0.0
            num_cur_j = 0.0
            for a, d in dist:
                wa = 1.0 if weights is None else float(weights[a])
                if label[a] == i:
                    if abs(d - last_j_dist) < _EPS:
                        S[i][j] += wa * (num_j - 0.5 * num_cur_j)
                    else:
                        S[i][j] += wa * num_j
                else:
                    num_j += wa
                    if abs(d - last_j_dist) < _EPS:
                        num_cur_j += wa
                    else:
                        last_j_dist = d
                        num_cur_j = wa
            j_start += class_sizes[j]
        i_start += class_sizes[i]
    ans = 0.0
    for i in range(K):
        for j in range(i + 1, K):
            den = ((class_sizes[i] * class_sizes[j]) if weights is None
                   else class_w[i] * class_w[j])
            if den > 0:
                ans += S[i][j] / den
    return 2.0 * ans / (K * (K - 1))


def _run(score, label, weights=None, auc_mu_weights=None, num_class=3):
    cfg = Config({"num_class": num_class, "objective": "multiclass",
                  **({"auc_mu_weights": auc_mu_weights}
                     if auc_mu_weights else {})})
    m = AucMuMetric(cfg)
    md = Metadata(len(label))
    md.set_label(label)
    md.set_weight(weights)
    m.init(md, len(label))
    return m.eval(score)[0][1]


@pytest.mark.parametrize("weighted", [False, True])
@pytest.mark.parametrize("tied", [False, True])
def test_auc_mu_matches_reference_loop(weighted, tied):
    rng = np.random.RandomState(3 + tied)
    K, n = 3, 400
    label = rng.randint(0, K, n).astype(np.float64)
    score = rng.randn(K, n)
    if tied:
        # quantize scores so many projected distances tie exactly
        score = np.round(score * 2) / 2
    # weights round-trip through float32 (Metadata stores label_t=float,
    # matching the reference's label_t) — feed the transcription the same
    weights = ((rng.rand(n) + 0.25).astype(np.float32).astype(np.float64)
               if weighted else None)
    W = np.ones((K, K)); np.fill_diagonal(W, 0.0)
    want = _auc_mu_reference(score, label, weights, W)
    got = _run(score, label, weights)
    assert got == pytest.approx(want, abs=1e-12)


def test_auc_mu_custom_weight_matrix():
    rng = np.random.RandomState(9)
    K, n = 4, 300
    label = rng.randint(0, K, n).astype(np.float64)
    score = rng.randn(K, n)
    Wflat = rng.rand(K * K).tolist()
    W = np.asarray(Wflat).reshape(K, K).copy()
    np.fill_diagonal(W, 0.0)
    want = _auc_mu_reference(score, label, None, W)
    got = _run(score, label, auc_mu_weights=Wflat, num_class=K)
    assert got == pytest.approx(want, abs=1e-12)


def test_auc_mu_perfect_and_random():
    # perfectly separated scores -> 1.0
    K, n = 3, 90
    label = np.repeat(np.arange(K), n // K).astype(np.float64)
    score = np.full((K, n), -10.0)
    score[label.astype(int), np.arange(n)] = 10.0
    assert _run(score, label) == pytest.approx(1.0)


def test_auc_mu_via_train_metric():
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    n = 600
    X = rng.randn(n, 5)
    label = (X[:, 0] + 0.5 * rng.randn(n) > 0).astype(int) \
        + (X[:, 1] > 0.5).astype(int)
    vals = []
    def cb(env):
        vals.append(dict((nm, v) for _, nm, v, _ in
                         env.evaluation_result_list))
    lgb.train({"objective": "multiclass", "num_class": 3,
               "metric": "auc_mu", "num_leaves": 7, "verbosity": -1,
               "is_training_metric": True},
              lgb.Dataset(X, label=label), num_boost_round=3,
              callbacks=[cb])
    assert vals and all(0.5 < v["auc_mu"] <= 1.0 for v in vals)
    assert vals[-1]["auc_mu"] >= vals[0]["auc_mu"]


def test_device_auc_mu_matches_host_metric():
    """The sharded (binned) device form tracks the exact host metric to
    bin resolution."""
    import jax.numpy as jnp
    from lightgbm_tpu.metric import device_auc_mu
    rng = np.random.RandomState(7)
    K, n = 4, 2000
    label = rng.randint(0, K, n).astype(np.float64)
    score = rng.randn(K, n)
    host = _run(score, label, num_class=K)
    W = np.ones((K, K)); np.fill_diagonal(W, 0.0)
    dev = float(device_auc_mu(jnp.asarray(score, jnp.float32),
                              jnp.asarray(label, jnp.float32),
                              jnp.ones(n, jnp.float32), W))
    assert dev == pytest.approx(host, abs=2e-3)


def test_device_average_precision_matches_host_metric():
    import jax.numpy as jnp
    from lightgbm_tpu.metric import (AveragePrecisionMetric,
                                     device_binned_average_precision)
    from lightgbm_tpu.io.dataset import Metadata
    rng = np.random.RandomState(8)
    n = 4000
    label = (rng.rand(n) < 0.3).astype(np.float64)
    score = rng.randn(n) + label        # informative scores
    w = (rng.rand(n) + 0.5).astype(np.float64)
    cfg = Config({"objective": "binary"})
    m = AveragePrecisionMetric(cfg)
    md = Metadata(n); md.set_label(label); md.set_weight(w)
    m.init(md, n)
    host = m.eval(score)[0][1]
    dev = float(device_binned_average_precision(
        jnp.asarray(score, jnp.float32), jnp.asarray(label, jnp.float32),
        jnp.asarray(w, jnp.float32)))
    assert dev == pytest.approx(host, abs=3e-3)
