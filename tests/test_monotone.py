"""Monotone constraints, basic mode (ref: monotone_constraints.hpp:465
BasicLeafConstraints; feature_histogram.hpp:758 GetSplitGains USE_MC;
serial_tree_learner.cpp:987 monotone_penalty)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _problem(n=4000, seed=4):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 3)
    # y increases in X0, decreases in X1, noisy in X2
    y = (2 * X[:, 0] - 1.5 * X[:, 1] + 0.3 * np.sin(8 * X[:, 2])
         + 0.1 * rng.randn(n))
    return X, y


def _is_monotone(booster, feature, direction, others=(0.5, 0.5)):
    grid = np.linspace(0.01, 0.99, 50)
    X = np.full((50, 3), 0.5)
    for j, v in zip([f for f in range(3) if f != feature], others):
        X[:, j] = v
    X[:, feature] = grid
    pred = booster.predict(X)
    diffs = np.diff(pred)
    if direction > 0:
        return bool((diffs >= -1e-10).all())
    return bool((diffs <= 1e-10).all())


@pytest.mark.parametrize("strategy", ["leafwise", "wave"])
def test_predictions_respect_constraints(strategy):
    X, y = _problem()
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "learning_rate": 0.2, "min_data_in_leaf": 5,
              "monotone_constraints": [1, -1, 0],
              "tpu_growth_strategy": strategy}
    booster = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20)
    assert _is_monotone(booster, 0, +1)
    assert _is_monotone(booster, 1, -1)
    # sanity: the model still learns (not constant)
    pred = booster.predict(X)
    assert float(np.corrcoef(pred, y)[0, 1]) > 0.7


def test_unconstrained_model_violates():
    """The same noisy monotone problem WITHOUT constraints should produce at
    least one local violation — otherwise the constrained test is vacuous."""
    rng = np.random.RandomState(0)
    n = 2000
    X = np.stack([rng.rand(n), rng.rand(n), rng.rand(n)], 1)
    y = X[:, 0] + 0.8 * np.sin(12 * X[:, 0]) + 0.2 * rng.randn(n)
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "learning_rate": 0.2, "min_data_in_leaf": 5}
    b_free = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20)
    assert not _is_monotone(b_free, 0, +1)
    b_mc = lgb.train({**params, "monotone_constraints": [1, 0, 0]},
                     lgb.Dataset(X, label=y), num_boost_round=20)
    assert _is_monotone(b_mc, 0, +1)


def test_monotone_penalty_discourages_root_splits():
    """monotone_penalty shrinks monotone features' gains near the root
    (1 - p/2^depth; monotone_constraints.hpp:357): with a huge penalty the
    root split must pick the unconstrained feature."""
    rng = np.random.RandomState(2)
    n = 3000
    X = np.stack([rng.rand(n), rng.rand(n)], 1)
    # feature 0 slightly stronger, but penalized
    y = (1.2 * (X[:, 0] > 0.5) + 1.0 * (X[:, 1] > 0.5)
         + 0.05 * rng.randn(n))
    params = {"objective": "regression", "num_leaves": 4, "verbosity": -1,
              "min_data_in_leaf": 5, "monotone_constraints": [1, 0]}
    b0 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=1)
    b0._gbdt._sync_model()
    t0 = b0._gbdt.models_[0]
    b1 = lgb.train({**params, "monotone_penalty": 2.0},
                   lgb.Dataset(X, label=y), num_boost_round=1)
    b1._gbdt._sync_model()
    t1 = b1._gbdt.models_[0]
    assert t0.split_feature[0] == 0       # unpenalized: monotone feat wins
    assert t1.split_feature[0] == 1       # penalized at depth 0 and 1


def test_monotone_with_alias_param():
    X, y = _problem(n=1000)
    booster = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1, "mc": [1, 0, 0],
                         "min_data_in_leaf": 5},
                        lgb.Dataset(X, label=y), num_boost_round=5)
    assert _is_monotone(booster, 0, +1)


def test_intermediate_mode_monotone_and_tighter_fit():
    """monotone_constraints_method=intermediate (ref:
    monotone_constraints.hpp:516 IntermediateLeafConstraints): output-based
    constraints are looser than basic's midpoints, so the fit improves, and
    the vectorized pairwise recompute keeps predictions monotone on every
    feature slice."""
    X, y = _problem()
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "learning_rate": 0.2, "min_data_in_leaf": 5,
            "monotone_constraints": [1, -1, 0],
            "tpu_growth_strategy": "leafwise"}
    b_basic = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=20)
    b_int = lgb.train({**base, "monotone_constraints_method": "intermediate"},
                      lgb.Dataset(X, label=y), num_boost_round=20)
    # monotone on randomized slices of the other features
    rng = np.random.RandomState(11)
    for _ in range(10):
        others = tuple(rng.rand(2))
        assert _is_monotone(b_int, 0, +1, others)
        assert _is_monotone(b_int, 1, -1, others)
    mse_basic = float(np.mean((b_basic.predict(X) - y) ** 2))
    mse_int = float(np.mean((b_int.predict(X) - y) ** 2))
    assert mse_int <= mse_basic * 1.02, (mse_int, mse_basic)


def test_advanced_mode_maps_to_intermediate():
    X, y = _problem(n=1500)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "monotone_constraints": [1, -1, 0],
                   "monotone_constraints_method": "advanced",
                   "tpu_growth_strategy": "leafwise"},
                  lgb.Dataset(X, label=y), num_boost_round=8)
    assert b._gbdt.grow_params.monotone_intermediate
    assert _is_monotone(b, 0, +1)
    assert _is_monotone(b, 1, -1)


def test_intermediate_falls_back_with_extra_trees():
    X, y = _problem(n=1500)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "monotone_constraints": [1, 0, 0], "extra_trees": True,
                   "monotone_constraints_method": "intermediate"},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    assert not b._gbdt.grow_params.monotone_intermediate
    assert _is_monotone(b, 0, +1)
