"""Monotone constraints, basic mode (ref: monotone_constraints.hpp:465
BasicLeafConstraints; feature_histogram.hpp:758 GetSplitGains USE_MC;
serial_tree_learner.cpp:987 monotone_penalty)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _problem(n=4000, seed=4):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 3)
    # y increases in X0, decreases in X1, noisy in X2
    y = (2 * X[:, 0] - 1.5 * X[:, 1] + 0.3 * np.sin(8 * X[:, 2])
         + 0.1 * rng.randn(n))
    return X, y


def _is_monotone(booster, feature, direction, others=(0.5, 0.5)):
    grid = np.linspace(0.01, 0.99, 50)
    X = np.full((50, 3), 0.5)
    for j, v in zip([f for f in range(3) if f != feature], others):
        X[:, j] = v
    X[:, feature] = grid
    pred = booster.predict(X)
    diffs = np.diff(pred)
    if direction > 0:
        return bool((diffs >= -1e-10).all())
    return bool((diffs <= 1e-10).all())


@pytest.mark.parametrize("strategy", ["leafwise", "wave"])
def test_predictions_respect_constraints(strategy):
    X, y = _problem()
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "learning_rate": 0.2, "min_data_in_leaf": 5,
              "monotone_constraints": [1, -1, 0],
              "tpu_growth_strategy": strategy}
    booster = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20)
    assert _is_monotone(booster, 0, +1)
    assert _is_monotone(booster, 1, -1)
    # sanity: the model still learns (not constant)
    pred = booster.predict(X)
    assert float(np.corrcoef(pred, y)[0, 1]) > 0.7


def test_unconstrained_model_violates():
    """The same noisy monotone problem WITHOUT constraints should produce at
    least one local violation — otherwise the constrained test is vacuous."""
    rng = np.random.RandomState(0)
    n = 2000
    X = np.stack([rng.rand(n), rng.rand(n), rng.rand(n)], 1)
    y = X[:, 0] + 0.8 * np.sin(12 * X[:, 0]) + 0.2 * rng.randn(n)
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "learning_rate": 0.2, "min_data_in_leaf": 5}
    b_free = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20)
    assert not _is_monotone(b_free, 0, +1)
    b_mc = lgb.train({**params, "monotone_constraints": [1, 0, 0]},
                     lgb.Dataset(X, label=y), num_boost_round=20)
    assert _is_monotone(b_mc, 0, +1)


def test_monotone_penalty_discourages_root_splits():
    """monotone_penalty shrinks monotone features' gains near the root
    (1 - p/2^depth; monotone_constraints.hpp:357): with a huge penalty the
    root split must pick the unconstrained feature."""
    rng = np.random.RandomState(2)
    n = 3000
    X = np.stack([rng.rand(n), rng.rand(n)], 1)
    # feature 0 slightly stronger, but penalized
    y = (1.2 * (X[:, 0] > 0.5) + 1.0 * (X[:, 1] > 0.5)
         + 0.05 * rng.randn(n))
    params = {"objective": "regression", "num_leaves": 4, "verbosity": -1,
              "min_data_in_leaf": 5, "monotone_constraints": [1, 0]}
    b0 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=1)
    b0._gbdt._sync_model()
    t0 = b0._gbdt.models_[0]
    b1 = lgb.train({**params, "monotone_penalty": 2.0},
                   lgb.Dataset(X, label=y), num_boost_round=1)
    b1._gbdt._sync_model()
    t1 = b1._gbdt.models_[0]
    assert t0.split_feature[0] == 0       # unpenalized: monotone feat wins
    assert t1.split_feature[0] == 1       # penalized at depth 0 and 1


def test_monotone_with_alias_param():
    X, y = _problem(n=1000)
    booster = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1, "mc": [1, 0, 0],
                         "min_data_in_leaf": 5},
                        lgb.Dataset(X, label=y), num_boost_round=5)
    assert _is_monotone(booster, 0, +1)


def test_intermediate_mode_monotone_and_tighter_fit():
    """monotone_constraints_method=intermediate (ref:
    monotone_constraints.hpp:516 IntermediateLeafConstraints): output-based
    constraints are looser than basic's midpoints, so the fit improves, and
    the vectorized pairwise recompute keeps predictions monotone on every
    feature slice."""
    X, y = _problem()
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "learning_rate": 0.2, "min_data_in_leaf": 5,
            "monotone_constraints": [1, -1, 0],
            "tpu_growth_strategy": "leafwise"}
    b_basic = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=20)
    b_int = lgb.train({**base, "monotone_constraints_method": "intermediate"},
                      lgb.Dataset(X, label=y), num_boost_round=20)
    # monotone on randomized slices of the other features
    rng = np.random.RandomState(11)
    for _ in range(10):
        others = tuple(rng.rand(2))
        assert _is_monotone(b_int, 0, +1, others)
        assert _is_monotone(b_int, 1, -1, others)
    mse_basic = float(np.mean((b_basic.predict(X) - y) ** 2))
    mse_int = float(np.mean((b_int.predict(X) - y) ** 2))
    assert mse_int <= mse_basic * 1.02, (mse_int, mse_basic)


def test_advanced_mode_monotone_and_at_least_intermediate_fit():
    """monotone_constraints_method=advanced (ref:
    monotone_constraints.hpp:858 AdvancedLeafConstraints): per-(feature,
    threshold) constraint surfaces are looser than the intermediate
    whole-leaf scalar, so the fit must be at least as good, while every
    feature slice stays monotone."""
    X, y = _problem()
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "learning_rate": 0.2, "min_data_in_leaf": 5,
            "monotone_constraints": [1, -1, 0],
            "tpu_growth_strategy": "leafwise"}
    b_int = lgb.train({**base,
                       "monotone_constraints_method": "intermediate"},
                      lgb.Dataset(X, label=y), num_boost_round=20)
    b_adv = lgb.train({**base, "monotone_constraints_method": "advanced"},
                      lgb.Dataset(X, label=y), num_boost_round=20)
    assert b_adv._gbdt.grow_params.monotone_advanced
    rng = np.random.RandomState(11)
    for _ in range(10):
        others = tuple(rng.rand(2))
        assert _is_monotone(b_adv, 0, +1, others)
        assert _is_monotone(b_adv, 1, -1, others)
    mse_int = float(np.mean((b_int.predict(X) - y) ** 2))
    mse_adv = float(np.mean((b_adv.predict(X) - y) ** 2))
    # looser (per-threshold) constraints must not fit WORSE
    assert mse_adv <= mse_int * 1.005, (mse_adv, mse_int)


def test_advanced_differs_from_intermediate_when_slack_matters():
    """A landscape where a far leaf constrains the whole leaf under
    intermediate but only part of the threshold range under advanced:
    the two modes must produce different models (the slack is real)."""
    rng = np.random.RandomState(3)
    n = 3000
    X = rng.rand(n, 2)
    y = (np.where(X[:, 0] > 0.5, 2.0, 0.0) * (0.5 + X[:, 1])
         + 0.05 * rng.randn(n))
    base = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
            "min_data_in_leaf": 5, "monotone_constraints": [1, 0],
            "tpu_growth_strategy": "leafwise"}
    b_int = lgb.train({**base,
                       "monotone_constraints_method": "intermediate"},
                      lgb.Dataset(X, label=y), num_boost_round=10)
    b_adv = lgb.train({**base, "monotone_constraints_method": "advanced"},
                      lgb.Dataset(X, label=y), num_boost_round=10)
    assert b_int.model_to_string() != b_adv.model_to_string()
    assert _is_monotone_2f(b_adv)


def _is_monotone_2f(booster):
    grid = np.linspace(0.01, 0.99, 50)
    for x1 in np.linspace(0.05, 0.95, 7):
        X = np.column_stack([grid, np.full(50, x1)])
        d = np.diff(booster.predict(X))
        if not (d >= -1e-10).all():
            return False
    return True


def test_intermediate_falls_back_with_extra_trees():
    X, y = _problem(n=1500)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1, "min_data_in_leaf": 5,
                   "monotone_constraints": [1, 0, 0], "extra_trees": True,
                   "monotone_constraints_method": "intermediate"},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    assert not b._gbdt.grow_params.monotone_intermediate
    assert _is_monotone(b, 0, +1)
