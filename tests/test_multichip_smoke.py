"""8-device smoke test over the PR-5 host-boundary knob matrix.

MULTICHIP_r05 timed out after PR 5 landed buffer donation, async host
I/O and the compile cache; rounds r02-r04 (pre-PR-5) passed the same
8-device check.  This file localizes that interaction and guards it
from silently regressing: a short sharded-wave training (the exact
engine configuration the dry run compiles) runs across the knob
matrix on the virtual 8-device CPU mesh the conftest provides.

Invariants pinned:

* every combination TRAINS (a hang here is the r05 signature — the
  per-run wall-clock guard turns it into a named failure instead of a
  silent tier-1 cap eat);
* the model is IDENTICAL across knob combinations — donation, async
  I/O and the compile cache are performance knobs and must never
  change results;
* no "Some donated buffers were not usable" warnings: grow-buffer
  donation is gated off under a device mesh (boosting/gbdt.py), since
  the row-sharded f32 grad/hess slices cannot alias any grow output —
  the donation x SPMD interaction implicated in r05;
* the compile cache composes with the 8-device mesh in a fresh
  process (subprocess-isolated: a cache-write crash or hang must not
  take the test process down with it).
"""

import os
import subprocess
import sys
import time
import warnings

import numpy as np

import lightgbm_tpu as lgb

# a genuine r05-style hang blows past this by an order of magnitude;
# normal runs (incl. the one-time sharded compile) finish well inside it
RUN_BUDGET_S = 300.0


def _problem(n=1024, F=5, seed=9):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F)
    y = (3 * (X[:, 0] - 0.5) + X[:, 1] * X[:, 2]
         + 0.1 * rng.randn(n)).astype(np.float64)
    return X, y


def _params(donate, async_io, cache_dir=""):
    return {
        "objective": "regression", "num_leaves": 7, "verbosity": -1,
        "min_data_in_leaf": 5, "learning_rate": 0.2,
        "tree_learner": "data", "tpu_growth_strategy": "wave",
        "tpu_donate_buffers": donate, "async_host_io": async_io,
        "compile_cache_dir": cache_dir,
    }


def _train(donate, async_io, cache_dir="", rounds=4):
    X, y = _problem()
    t0 = time.monotonic()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        booster = lgb.train(_params(donate, async_io, cache_dir),
                            lgb.Dataset(X, label=y),
                            num_boost_round=rounds)
    elapsed = time.monotonic() - t0
    donate_warns = [w for w in caught
                    if "donated buffers were not usable"
                    in str(w.message)]
    return booster, elapsed, donate_warns


def _model_text(booster):
    from lightgbm_tpu.boosting.model_io import save_model_to_string
    txt = save_model_to_string(booster._gbdt)
    return txt.split("\nparameters:")[0]


def test_knob_matrix_trains_identically():
    """donation x async_host_io: every combination completes inside the
    budget, produces the same model, and emits no unusable-donation
    warnings (the mesh gate in boosting/gbdt.py)."""
    X, _ = _problem()
    results = {}
    for donate in (True, False):
        for async_io in (True, False):
            booster, elapsed, donate_warns = _train(donate, async_io)
            assert elapsed < RUN_BUDGET_S, (
                f"donate={donate} async={async_io} took {elapsed:.0f}s — "
                "the MULTICHIP_r05 hang signature")
            assert not donate_warns, (
                f"donate={donate} async={async_io}: grow-buffer donation "
                "leaked through the mesh gate: "
                f"{[str(w.message) for w in donate_warns]}")
            g = booster._gbdt
            assert g.mesh is not None and g.mesh.devices.size == 8, \
                "the 8-device mesh was not engaged"
            assert g.growth_strategy == "wave"
            pred = booster.predict(X)
            assert np.isfinite(pred).all()
            results[(donate, async_io)] = _model_text(booster)
    texts = set(results.values())
    assert len(texts) == 1, (
        "knob matrix changed the model: "
        f"{sorted(k for k in results if results[k] != results[(False, False)])}")


def test_donation_gated_off_under_mesh():
    """The gate itself: tpu_donate_buffers=True under the mesh must warn
    and fall back to the non-donating grow entry."""
    from lightgbm_tpu.utils import log

    class _Capture:
        def __init__(self):
            self.lines = []

        def info(self, msg):
            self.lines.append(msg)

        warning = info

    cap = _Capture()
    log.register_logger(cap)
    try:
        X, y = _problem()
        params = _params(True, False)
        params["verbosity"] = 0  # warnings on
        booster = lgb.train(params, lgb.Dataset(X, label=y),
                            num_boost_round=2)
    finally:
        log.register_logger(None)
    assert any("donation is disabled under a device mesh" in line
               for line in cap.lines), \
        f"expected the mesh donation gate to warn; got {cap.lines!r}"
    assert booster.current_iteration() == 2


_STALL_CHILD = r"""
import os, sys
sys.path.insert(0, os.environ["STALL_REPO"])
import jax; jax.config.update('jax_platforms', 'cpu')
import numpy as np
import lightgbm_tpu as lgb
from tests.test_multichip_smoke import _problem, _params
d = os.environ["STALL_DIR"]
X, y = _problem()
p = _params(True, True)
p.update({"metrics_dir": os.path.join(d, "metrics"),
          "checkpoint_dir": os.path.join(d, "ckpt"), "checkpoint_freq": 1,
          "auto_degrade": True, "stall_floor_s": 2.0, "stall_factor": 3.0})
b = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=5)
assert np.isfinite(b.predict(X[:64])).all()
print("STALL_SMOKE_OK", b.current_iteration(), flush=True)
"""


def test_stall_injection_diagnosed_and_degraded_under_mesh(tmp_path):
    """ISSUE 7 acceptance on the 8-device mesh: an injected hang during
    sharded-wave training produces a stall-rank0.json (stack + knob
    fingerprint with the mesh engaged), the exit is classified as a
    HANG (not a crash) by the supervisor, and the auto_degrade relaunch
    completes from checkpoint with exactly one ladder knob disabled."""
    import json

    from lightgbm_tpu.reliability.guard import STALL_EXIT_CODE
    from lightgbm_tpu.reliability.supervisor import classify_returncode

    script = tmp_path / "stall_child.py"
    script.write_text(_STALL_CHILD)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    env.update({"STALL_DIR": str(tmp_path), "STALL_REPO": repo,
                "LGBM_TPU_FAULT": "hang@2@0",
                "LGBM_TPU_FAULT_ATTEMPT": "0"})

    # attempt 0: wedges at iteration 2 mid-mesh-training
    r0 = subprocess.run([sys.executable, str(script)], cwd=repo, env=env,
                        capture_output=True, text=True,
                        timeout=RUN_BUDGET_S)
    assert r0.returncode == STALL_EXIT_CODE, (
        f"expected the stall exit code, got rc={r0.returncode}\n"
        f"stdout: {r0.stdout[-2000:]}\nstderr: {r0.stderr[-2000:]}")
    assert classify_returncode(r0.returncode) == "hang"
    diag = json.load(open(tmp_path / "metrics" / "stall-rank0.json"))
    assert diag["last_iteration"] == 2
    assert diag["knobs"]["sharded_wave"] is True
    assert any("File" in line for line in diag["stacks"])

    # attempt 1: the engine consumes the diagnosis, disables the first
    # ladder knob and resumes from the iteration-2 checkpoint
    env["LGBM_TPU_FAULT_ATTEMPT"] = "1"
    r1 = subprocess.run([sys.executable, str(script)], cwd=repo, env=env,
                        capture_output=True, text=True,
                        timeout=RUN_BUDGET_S)
    assert r1.returncode == 0, (
        f"degraded relaunch failed rc={r1.returncode}\n"
        f"stdout: {r1.stdout[-2000:]}\nstderr: {r1.stderr[-2000:]}")
    assert "STALL_SMOKE_OK 5" in r1.stdout
    state = json.load(open(tmp_path / "metrics" / "degrade-state.json"))
    assert state["degraded_knobs"] == ["tpu_donate_buffers"]
    events = [json.loads(ln) for ln in
              (tmp_path / "metrics" / "events-rank0.jsonl")
              .read_text().splitlines()]
    assert any(e["event"] == "degrade"
               and e["knobs"] == ["tpu_donate_buffers"] for e in events)


def test_compile_cache_under_mesh_subprocess(tmp_path):
    """compile_cache_dir x 8-device mesh in a FRESH process (the r05 dry
    run is also a fresh process): must train and exit 0 inside the
    budget.  Subprocess isolation keeps a cache-layer crash or hang from
    killing the whole test session."""
    cache = tmp_path / "xla-cache"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np, lightgbm_tpu as lgb\n"
        "from tests.test_multichip_smoke import _problem, _params\n"
        "X, y = _problem()\n"
        f"p = _params(True, True, cache_dir={str(cache)!r})\n"
        "b = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=3)\n"
        "assert np.isfinite(b.predict(X)).all()\n"
        "print('SMOKE_OK', b.current_iteration())\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run([sys.executable, "-c", code], cwd=repo, env=env,
                         capture_output=True, text=True,
                         timeout=RUN_BUDGET_S)
    assert res.returncode == 0, (
        f"compile-cache x mesh run failed rc={res.returncode}\n"
        f"stdout: {res.stdout[-2000:]}\nstderr: {res.stderr[-2000:]}")
    assert "SMOKE_OK 3" in res.stdout
