"""Multi-process SPMD training (SURVEY §2.3 #6): N real OS processes,
each with local devices, train the same sharded model via
jax.distributed — the TPU-native analogue of the reference's N CLI
workers over sockets (tests/distributed/_test_distributed.py pattern:
train in every process, assert identical models across ranks)."""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
pid = int(sys.argv[1])
out_path = sys.argv[2]
port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
sys.path.insert(0, "/root/repo")
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.model_io import save_model_to_string

rng = np.random.RandomState(3)
n = 4096
X = rng.rand(n, 6)
logit = 4 * (X[:, 0] - 0.5) + 2 * X[:, 1] * X[:, 2] - X[:, 3]
y = (rng.rand(n) < 1 / (1 + np.exp(-3 * logit))).astype(np.float64)

booster = lgb.train(
    {"objective": "regression", "num_leaves": 15, "verbosity": -1,
     "min_data_in_leaf": 5, "learning_rate": 0.2,
     "tree_learner": "data", "tpu_growth_strategy": "leafwise"},
    lgb.Dataset(X, label=y), num_boost_round=4)
assert booster._gbdt.mesh is not None
assert len(booster._gbdt.mesh.devices.ravel()) == 4  # 2 procs x 2 devs
txt = save_model_to_string(booster._gbdt)
with open(out_path, "w") as f:
    f.write(txt)
print(f"proc {pid} done", flush=True)
"""


def _run_two_workers(tmp_path, worker_src, out_suffix, extra_args=()):
    """Shared 2-process harness: free port, env strip, spawn, reap.
    Returns (out_paths, logs); asserts both workers exited 0."""
    import socket
    script = tmp_path / "worker_h.py"
    script.write_text(worker_src)
    outs = [tmp_path / f"out_{i}.{out_suffix}" for i in range(2)]
    with socket.socket() as sock:          # pick a free port per run
        sock.bind(("localhost", 0))
        port = str(sock.getsockname()[1])
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), str(outs[i]), port,
         *map(str, extra_args)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd="/root/repo") for i in range(2)]
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=600)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out = "(timeout)\n" + (out or "")
        logs.append(out)
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    return outs, logs


@pytest.mark.skipif(bool(os.environ.get("LIGHTGBM_TPU_SKIP_MULTIPROC")),
                    reason="multiproc disabled")
def test_two_process_training_identical_models(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    outs = [tmp_path / f"model_{i}.txt" for i in range(2)]
    import socket
    with socket.socket() as sock:          # pick a free port per run
        sock.bind(("localhost", 0))
        port = str(sock.getsockname()[1])
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), str(outs[i]), port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd="/root/repo") for i in range(2)]
    logs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        logs.append(out)
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)

    texts = [o.read_text() for o in outs]
    # every rank must write the IDENTICAL model file
    # (_test_distributed.py's core assertion)
    assert texts[0] == texts[1]

    # and the multi-process model must match single-process training
    # structurally (float payloads to rounded precision)
    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.model_io import save_model_to_string
    rng = np.random.RandomState(3)
    n = 4096
    X = rng.rand(n, 6)
    logit = 4 * (X[:, 0] - 0.5) + 2 * X[:, 1] * X[:, 2] - X[:, 3]
    y = (rng.rand(n) < 1 / (1 + np.exp(-3 * logit))).astype(np.float64)
    b1 = lgb.train({"objective": "regression", "num_leaves": 15,
                    "verbosity": -1, "min_data_in_leaf": 5,
                    "learning_rate": 0.2,
                    "tpu_growth_strategy": "leafwise"},
                   lgb.Dataset(X, label=y), num_boost_round=4)
    serial = save_model_to_string(b1._gbdt)

    def structure(txt):
        txt = txt.split("\nparameters:")[0]
        txt = "\n".join(l for l in txt.splitlines()
                        if not l.startswith("tree_sizes="))
        return re.sub(r"-?\d+\.\d+(e[-+]?\d+)?", "F", txt)

    assert structure(texts[0]) == structure(serial)


_CLI_WORKER = r"""
import os, sys
rank = sys.argv[1]
port = sys.argv[2]
ports = sys.argv[3]
model_out = sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["LIGHTGBM_TPU_MACHINE_RANK"] = rank
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "/root/repo")
from lightgbm_tpu.cli import main
rc = main([
    "task=train", "objective=regression", "tree_learner=data",
    "data=/root/reference/examples/regression/regression.train",
    "num_trees=3", "num_leaves=15", "verbosity=-1",
    "tpu_growth_strategy=leafwise", "num_machines=2",
    f"machines={ports}", f"local_listen_port={port}",
    f"output_model={model_out}",
])
assert rc == 0
print(f"cli rank {rank} done", flush=True)
"""


@pytest.mark.skipif(bool(os.environ.get("LIGHTGBM_TPU_SKIP_MULTIPROC")),
                    reason="multiproc disabled")
def test_cli_machines_two_workers_identical_models(tmp_path):
    """The CLI's machines=/local_listen_port launch (ref:
    application.cpp:100-115): two worker processes join one
    jax.distributed cluster, train tree_learner=data over the global
    mesh, and write identical model files."""
    import socket
    script = tmp_path / "cli_worker.py"
    script.write_text(_CLI_WORKER)
    with socket.socket() as s1, socket.socket() as s2:
        s1.bind(("localhost", 0))
        s2.bind(("localhost", 0))
        p1, p2 = (str(s1.getsockname()[1]), str(s2.getsockname()[1]))
    machines = f"localhost:{p1},localhost:{p2}"
    outs = [tmp_path / f"cli_model_{i}.txt" for i in range(2)]
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), (p1, p2)[i], machines,
         str(outs[i])],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd="/root/repo") for i in range(2)]
    logs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        logs.append(out)
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    # identical models; only the parameters dump may differ (each worker
    # records its own local_listen_port / output_model)
    texts = [o.read_text().split("parameters:")[0] for o in outs]
    assert texts[0] == texts[1]
    assert "Tree=2" in texts[0]


_EVAL_WORKER = r"""
import json, os, sys
pid = int(sys.argv[1]); out_path = sys.argv[2]; port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
sys.path.insert(0, "/root/repo")
import numpy as np
import lightgbm_tpu as lgb

rng = np.random.RandomState(3)
n = 4096
X = rng.rand(n, 6)
y = (rng.rand(n) < 1/(1+np.exp(-4*(X[:, 0]-0.5)))).astype(np.float64)
b = lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1,
               "tree_learner": "data", "metric": "binary_logloss,auc",
               "tpu_growth_strategy": "leafwise", "min_data_in_leaf": 5},
              lgb.Dataset(X, label=y), num_boost_round=4)
res = b._gbdt.eval_train()
with open(out_path, "w") as f:
    json.dump({k: float(v) for k, v in res}, f)
print(f"proc {pid} eval done", flush=True)
"""


@pytest.mark.skipif(bool(os.environ.get("LIGHTGBM_TPU_SKIP_MULTIPROC")),
                    reason="multiproc disabled")
def test_multiprocess_train_eval_identical_and_correct(tmp_path):
    """VERDICT r3 item 7: workers must evaluate during distributed
    training.  Train-set metrics under multi-process SPMD are computed
    as shard-local partials + GSPMD all-reduce: every rank reports the
    IDENTICAL value, and the values match a single-process run of the
    same config (AUC via the global score-bin histogram, 1/16384
    resolution)."""
    import json
    outs, _ = _run_two_workers(tmp_path, _EVAL_WORKER, "json")
    r0 = json.loads(outs[0].read_text())
    r1 = json.loads(outs[1].read_text())
    assert r0 == r1, (r0, r1)

    # single-process reference: identical data/params, host eval path
    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(3)
    n = 4096
    X = rng.rand(n, 6)
    y = (rng.rand(n) < 1 / (1 + np.exp(-4 * (X[:, 0] - 0.5)))
         ).astype(np.float64)
    b = lgb.train({"objective": "binary", "num_leaves": 15,
                   "verbosity": -1, "metric": "binary_logloss,auc",
                   "tpu_growth_strategy": "leafwise",
                   "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=4)
    ref = dict(b._gbdt.eval_train())
    assert abs(ref["binary_logloss"] - r0["binary_logloss"]) < 2e-4
    assert abs(ref["auc"] - r0["auc"]) < 2e-3


@pytest.mark.skipif(bool(os.environ.get("LIGHTGBM_TPU_SKIP_MULTIPROC")),
                    reason="multiproc disabled")
def test_programmatic_cluster_launcher(tmp_path):
    """lightgbm_tpu.distributed.train_distributed — the reference
    dask.py _train equivalent: spawn workers, train tree_learner=data
    over the combined mesh, return the rank-0 Booster.  The distributed
    model must match single-process training on the same data."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.distributed import train_distributed

    rng = np.random.RandomState(3)
    n = 4096
    X = rng.rand(n, 6)
    y = (rng.rand(n) < 1 / (1 + np.exp(-4 * (X[:, 0] - 0.5)))
         ).astype(np.float64)
    params = {"objective": "regression", "num_leaves": 15,
              "verbosity": -1, "min_data_in_leaf": 5,
              "tpu_growth_strategy": "leafwise"}
    b_dist = train_distributed(
        params, X, y, num_boost_round=4, num_machines=2,
        force_cpu=True,
        worker_env={"JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=2"})
    b_single = lgb.train({**params, "tree_learner": "serial"},
                         lgb.Dataset(X, label=y), num_boost_round=4)
    p_d = b_dist.predict(X[:512])
    p_s = b_single.predict(X[:512])
    np.testing.assert_allclose(p_d, p_s, rtol=2e-4, atol=2e-6)


_MC_EVAL_WORKER = r"""
import json, os, sys
pid = int(sys.argv[1]); out_path = sys.argv[2]; port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
sys.path.insert(0, "/root/repo")
import numpy as np
import lightgbm_tpu as lgb

rng = np.random.RandomState(5)
n = 3072
X = rng.rand(n, 5)
y = (X[:, 0] * 3 + X[:, 1]).astype(np.int64) % 3
b = lgb.train({"objective": "multiclass", "num_class": 3, "num_leaves": 7,
               "verbosity": -1, "tree_learner": "data",
               "metric": "multi_logloss,multi_error,auc_mu",
               "tpu_growth_strategy": "leafwise", "min_data_in_leaf": 5},
              lgb.Dataset(X, label=y.astype(np.float64)),
              num_boost_round=3)
res = b._gbdt.eval_train()
with open(out_path, "w") as f:
    json.dump({k: float(v) for k, v in res}, f)
print(f"proc {pid} mc eval done", flush=True)
"""


@pytest.mark.skipif(bool(os.environ.get("LIGHTGBM_TPU_SKIP_MULTIPROC")),
                    reason="multiproc disabled")
def test_multiprocess_multiclass_train_eval(tmp_path):
    """Multiclass train metrics reduce on device under multi-process
    SPMD: identical on every rank, matching the single-process host
    evaluation."""
    import json
    outs, _ = _run_two_workers(tmp_path, _MC_EVAL_WORKER, "json")
    r0 = json.loads(outs[0].read_text())
    r1 = json.loads(outs[1].read_text())
    assert r0 == r1, (r0, r1)
    assert set(r0) == {"multi_logloss", "multi_error", "auc_mu"}

    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(5)
    n = 3072
    X = rng.rand(n, 5)
    y = (X[:, 0] * 3 + X[:, 1]).astype(np.int64) % 3
    b = lgb.train({"objective": "multiclass", "num_class": 3,
                   "num_leaves": 7, "verbosity": -1,
                   "metric": "multi_logloss,multi_error,auc_mu",
                   "tpu_growth_strategy": "leafwise",
                   "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y.astype(np.float64)),
                  num_boost_round=3)
    ref = dict(b._gbdt.eval_train())
    assert abs(ref["multi_logloss"] - r0["multi_logloss"]) < 2e-4
    # models differ in leaf-value ulps; allow a few row flips
    assert abs(ref["multi_error"] - r0["multi_error"]) < 5 / 3072
    # auc_mu: binned pairwise AUCs (resolution 1/4096) vs exact host
    assert abs(ref["auc_mu"] - r0["auc_mu"]) < 3e-3


_RANK_EVAL_WORKER = r"""
import json, os, sys
pid = int(sys.argv[1]); out_path = sys.argv[2]; port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
sys.path.insert(0, "/root/repo")
import numpy as np
import lightgbm_tpu as lgb

rng = np.random.RandomState(9)
sizes = rng.randint(4, 40, size=64)
n = int(sizes.sum())
X = rng.rand(n, 5)
y = rng.randint(0, 4, n).astype(np.float64)
b = lgb.train({"objective": "lambdarank", "num_leaves": 7, "verbosity": -1,
               "tree_learner": "data", "metric": "ndcg,map",
               "ndcg_eval_at": [1, 5], "min_data_in_leaf": 2,
               "tpu_growth_strategy": "leafwise"},
              lgb.Dataset(X, label=y, group=sizes), num_boost_round=3)
res = b._gbdt.eval_train()
with open(out_path, "w") as f:
    json.dump({k: float(v) for k, v in res}, f)
print(f"proc {pid} rank eval done", flush=True)
"""


@pytest.mark.skipif(bool(os.environ.get("LIGHTGBM_TPU_SKIP_MULTIPROC")),
                    reason="multiproc disabled")
def test_multiprocess_ndcg_train_eval(tmp_path):
    """NDCG train metrics under multi-process SPMD: per-query partials
    from bucketed device sort programs; identical on every rank and
    matching the single-process host evaluation (queries straddle the
    row shards — GSPMD handles the cross-shard gathers)."""
    import json
    outs, _ = _run_two_workers(tmp_path, _RANK_EVAL_WORKER, "json")
    r0 = json.loads(outs[0].read_text())
    r1 = json.loads(outs[1].read_text())
    assert r0 == r1, (r0, r1)
    assert set(r0) == {"ndcg@1", "ndcg@5", "map@1", "map@5"}

    import numpy as np
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(9)
    sizes = rng.randint(4, 40, size=64)
    n = int(sizes.sum())
    X = rng.rand(n, 5)
    y = rng.randint(0, 4, n).astype(np.float64)
    b = lgb.train({"objective": "lambdarank", "num_leaves": 7,
                   "verbosity": -1, "metric": "ndcg,map",
                   "ndcg_eval_at": [1, 5], "min_data_in_leaf": 2,
                   "tpu_growth_strategy": "leafwise"},
                  lgb.Dataset(X, label=y, group=sizes), num_boost_round=3)
    ref = dict(b._gbdt.eval_train())
    # the worker trains tree_learner=data, the reference serially: leaf
    # values differ in ulps, so budget a couple of per-query rank flips
    # (1/64 each at ndcg@1); rank-identity across workers is asserted
    # exactly above
    for k in ("ndcg@1", "ndcg@5", "map@1", "map@5"):
        assert abs(ref[k] - r0[k]) < 2.5 / 64, (k, ref[k], r0[k])


_WORKER_WAVE = r"""
import os, sys
pid = int(sys.argv[1])
out_path = sys.argv[2]
port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
sys.path.insert(0, "/root/repo")
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.boosting.model_io import save_model_to_string

rng = np.random.RandomState(3)
n = 4096
X = rng.rand(n, 6)
logit = 4 * (X[:, 0] - 0.5) + 2 * X[:, 1] * X[:, 2] - X[:, 3]
y = (rng.rand(n) < 1 / (1 + np.exp(-3 * logit))).astype(np.float64)

booster = lgb.train(
    {"objective": "regression", "num_leaves": 15, "verbosity": -1,
     "min_data_in_leaf": 5, "learning_rate": 0.2,
     "tree_learner": "data", "tpu_growth_strategy": "wave"},
    lgb.Dataset(X, label=y), num_boost_round=4)
g = booster._gbdt
assert g.mesh is not None
assert len(g.mesh.devices.ravel()) == 4  # 2 procs x 2 devs
assert g.growth_strategy == "wave", g.growth_strategy
txt = save_model_to_string(g)
with open(out_path, "w") as f:
    f.write(txt)
print(f"proc {pid} done", flush=True)
"""


@pytest.mark.skipif(bool(os.environ.get("LIGHTGBM_TPU_SKIP_MULTIPROC")),
                    reason="multiproc disabled")
def test_two_process_wave_training_identical_models(tmp_path):
    """The DEFAULT (wave) engine under 2-process SPMD (2 procs x 2 CPU
    devices): the shard_map'd histogram psum spans both processes' devices
    and every rank writes the identical model — the wave-engine form of
    the reference's distributed-identity assertion
    (_test_distributed.py:168-184)."""
    outs, _ = _run_two_workers(tmp_path, _WORKER_WAVE, "txt")
    texts = [o.read_text() for o in outs]
    assert texts[0] == texts[1]
    # structural sanity vs a single-process wave run of the same problem
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(3)
    n = 4096
    X = rng.rand(n, 6)
    logit = 4 * (X[:, 0] - 0.5) + 2 * X[:, 1] * X[:, 2] - X[:, 3]
    y = (rng.rand(n) < 1 / (1 + np.exp(-3 * logit))).astype(np.float64)
    b1 = lgb.train({"objective": "regression", "num_leaves": 15,
                    "verbosity": -1, "min_data_in_leaf": 5,
                    "learning_rate": 0.2, "tpu_growth_strategy": "wave"},
                   lgb.Dataset(X, label=y), num_boost_round=4)
    b1._gbdt._drain_pending(keep_depth=0)
    got_feats = re.findall(r"split_feature=([\d ]*)", texts[0])
    got_leaves = re.findall(r"num_leaves=(\d+)", texts[0])
    want_feats = [" ".join(str(f) for f in
                           t.split_feature[:t.num_leaves - 1])
                  for t in b1._gbdt.models_]
    want_leaves = [str(t.num_leaves) for t in b1._gbdt.models_]
    assert got_feats == want_feats
    assert got_leaves == want_leaves
