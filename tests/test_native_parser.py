"""Native C parser vs Python fallback parity (ref: src/io/parser.cpp —
the reference's parsers are native too; io/parser.py keeps detection and
label resolution, native/parser.c does the token hot loops)."""

import numpy as np
import pytest

from lightgbm_tpu.io.parser import parse_file
from lightgbm_tpu.native import (parse_dense_native, parse_libsvm_native,
                                 parser_lib)

pytestmark = pytest.mark.skipif(parser_lib() is None,
                                reason="no C compiler available")


def test_dense_native_matches_python(tmp_path):
    txt = ("1\t0.5\t\t3.25\n"
           "0\tna\t2e-3\t-1\n"
           "\n"
           "1\tNaN\t7\tnull\n")
    mat = parse_dense_native(txt.encode(), "\t", 4, 4)
    assert mat.shape == (3, 4)
    np.testing.assert_allclose(mat[0], [1, 0.5, np.nan, 3.25])
    np.testing.assert_allclose(mat[1], [0, np.nan, 2e-3, -1])
    np.testing.assert_allclose(mat[2], [1, np.nan, 7, np.nan])


def test_dense_ragged_row_raises():
    with pytest.raises(ValueError, match="line 2"):
        parse_dense_native(b"1,2,3\n4,5\n", ",", 2, 3)


def test_libsvm_native_matches_python():
    txt = b"1 0:0.5 3:2.5\n0 1:-1\n1\n"
    feats, labels = parse_libsvm_native(txt)
    np.testing.assert_allclose(labels, [1, 0, 1])
    np.testing.assert_allclose(
        feats, [[0.5, 0, 0, 2.5], [0, -1, 0, 0], [0, 0, 0, 0]])


def test_parse_file_on_reference_examples():
    """End-to-end parse of the reference's real example files goes through
    the native path and matches numpy's own parse."""
    path = "/root/reference/examples/binary_classification/binary.train"
    feats, labels, names = parse_file(path)
    ref = np.loadtxt(path)
    np.testing.assert_allclose(labels, ref[:, 0])
    np.testing.assert_allclose(feats, ref[:, 1:])


def test_parse_file_libsvm_rank(tmp_path):
    path = "/root/reference/examples/lambdarank/rank.train"
    feats, labels, _ = parse_file(path)
    assert feats.shape[0] == len(labels) > 0
    assert np.isfinite(labels).all()
    # spot-check the first line against a manual parse
    with open(path) as f:
        first = f.readline().split()
    assert labels[0] == float(first[0])
    for pair in first[1:]:
        k, v = pair.split(":")
        np.testing.assert_allclose(feats[0, int(k)], float(v))


def test_dense_bad_token_raises_like_python():
    """Native strictness matches the Python fallback: garbage tokens are
    rejected, not silently NaN'd (environment-independent behavior)."""
    with pytest.raises(ValueError, match="line 2"):
        parse_dense_native(b"1,2\n3,abc\n", ",", 2, 2)
    with pytest.raises(ValueError, match="line 1"):
        parse_dense_native(b"1.5x,2\n", ",", 1, 2)
    # but inf and nan still parse
    m = parse_dense_native(b"inf,nan\n", ",", 1, 2)
    assert np.isinf(m[0, 0]) and np.isnan(m[0, 1])


def test_libsvm_bad_pair_raises():
    with pytest.raises(ValueError, match="line 1"):
        parse_libsvm_native(b"1 0x10:1\n")
    with pytest.raises(ValueError, match="line 2"):
        parse_libsvm_native(b"1 0:1\n0 1:2q\n")


def test_libsvm_negative_index_rejected_both_paths(tmp_path):
    """Native and Python-fallback LibSVM parsers must reject a negative
    feature index identically (the fallback used to train silently via
    Python negative indexing)."""
    import pytest

    import lightgbm_tpu.io.parser as P
    import lightgbm_tpu.native as N

    f = tmp_path / "bad.svm"
    f.write_text("1 0:1.5 -2:3.0\n0 1:2.0\n")
    # native path (when a compiler exists) and forced Python fallback must
    # both raise ValueError with the native parser's message shape
    if N.parser_lib() is not None:
        with pytest.raises(ValueError, match="malformed libsvm pair"):
            P.parse_file(str(f))
    orig = N.parse_libsvm_native
    N.parse_libsvm_native = lambda *a, **k: None
    try:
        with pytest.raises(ValueError, match="malformed libsvm pair"):
            P.parse_file(str(f))
    finally:
        N.parse_libsvm_native = orig
