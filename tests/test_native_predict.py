"""Native batch predictor vs the Python traversal (ref:
src/application/predictor.hpp — the reference's batch predictor is
native too).  Must be bit-identical: same doubles, same missing/
categorical routing."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
import lightgbm_tpu.native as N

pytestmark = pytest.mark.skipif(N.predictor_lib() is None,
                                reason="no C compiler available")


def _predict_both(booster, X):
    """Native-path prediction vs the pure-Python traversal.  The native
    path is the cached PackedPredictor behind gbdt._packed_for, gated on
    native.predictor_lib(); stubbing THAT to None forces the Python
    traversal (stubbing the unused predict_batch_native would compare the
    native path against itself)."""
    p_native = booster.predict(X)
    orig = N.predictor_lib
    N.predictor_lib = lambda: None
    try:
        p_py = booster.predict(X)
    finally:
        N.predictor_lib = orig
    return p_native, p_py


def test_native_predict_binary_nan_categorical():
    rng = np.random.RandomState(0)
    X = rng.rand(3000, 6)
    X[rng.rand(*X.shape) < 0.1] = np.nan
    X[:, 3] = rng.randint(0, 8, len(X))
    y = ((np.nan_to_num(X[:, 0]) > 0.5)
         | np.isin(X[:, 3], [1, 5])).astype(float)
    b = lgb.train({"objective": "binary", "num_leaves": 31, "verbosity": -1,
                   "categorical_feature": [3], "use_missing": True,
                   "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=8)
    p_n, p_p = _predict_both(b, X)
    np.testing.assert_array_equal(p_n, p_p)


def test_native_predict_multiclass_and_rf():
    rng = np.random.RandomState(1)
    X = rng.rand(2000, 5)
    y = (X[:, 0] * 3).astype(int).clip(0, 2).astype(float)
    b = lgb.train({"objective": "multiclass", "num_class": 3,
                   "num_leaves": 15, "verbosity": -1,
                   "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    p_n, p_p = _predict_both(b, X)
    np.testing.assert_array_equal(p_n, p_p)
    # RF averages raw scores (average_output_)
    b_rf = lgb.train({"objective": "binary", "boosting": "rf",
                      "bagging_freq": 1, "bagging_fraction": 0.7,
                      "num_leaves": 15, "verbosity": -1,
                      "min_data_in_leaf": 5},
                     lgb.Dataset(X, label=(y > 1).astype(float)),
                     num_boost_round=6)
    p_n, p_p = _predict_both(b_rf, X)
    np.testing.assert_array_equal(p_n, p_p)


def test_native_predict_start_num_iteration():
    rng = np.random.RandomState(2)
    X = rng.rand(1000, 4)
    y = X[:, 0] + 0.1 * rng.randn(1000)
    b = lgb.train({"objective": "regression", "num_leaves": 15,
                   "verbosity": -1, "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=8)
    for kw in ({"start_iteration": 2, "num_iteration": 3},
               {"num_iteration": 5},):
        p_n = b.predict(X, **kw)
        orig = N.predictor_lib
        N.predictor_lib = lambda: None
        try:
            p_p = b.predict(X, **kw)
        finally:
            N.predictor_lib = orig
        np.testing.assert_array_equal(p_n, p_p)


def test_linear_tree_falls_back_to_python():
    rng = np.random.RandomState(3)
    X = rng.rand(1500, 4)
    y = 2 * X[:, 0] + X[:, 1] + 0.05 * rng.randn(1500)
    b = lgb.train({"objective": "regression", "linear_tree": True,
                   "num_leaves": 15, "verbosity": -1,
                   "min_data_in_leaf": 20},
                  lgb.Dataset(X, label=y), num_boost_round=4)
    assert np.isfinite(b.predict(X)).all()


def test_set_leaf_output_invalidates_packed_cache():
    rng = np.random.RandomState(4)
    X = rng.rand(800, 3)
    y = X[:, 0] + 0.05 * rng.randn(800)
    b = lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1, "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=3)
    p1 = b.predict(X)
    v = b.get_leaf_output(0, 0)
    b.set_leaf_output(0, 0, v + 5.0)
    p2 = b.predict(X)
    assert not np.allclose(p1, p2), "cached pack must be invalidated"


def test_negative_fraction_categorical_matches_python():
    """fv in (-1, 0) truncates to category 0 (int(v) semantics)."""
    rng = np.random.RandomState(5)
    X = rng.rand(2000, 3)
    X[:, 1] = rng.randint(0, 6, len(X))
    y = np.isin(X[:, 1], [0, 2]).astype(float)
    b = lgb.train({"objective": "binary", "num_leaves": 15,
                   "verbosity": -1, "categorical_feature": [1],
                   "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=4)
    Xq = X[:50].copy()
    Xq[:, 1] = -0.5   # truncates to category 0
    Xq[25:, 1] = -3.7  # negative -> right
    p_n, p_p = _predict_both(b, Xq)
    np.testing.assert_array_equal(p_n, p_p)


def test_native_pred_leaf_matches_python():
    rng = np.random.RandomState(6)
    X = rng.rand(1500, 5)
    X[rng.rand(*X.shape) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) > 0.5).astype(float)
    b = lgb.train({"objective": "binary", "num_leaves": 15,
                   "verbosity": -1, "use_missing": True,
                   "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    leaves_n = b.predict(X, pred_leaf=True)
    # python oracle: per-tree get_leaf_index
    b._gbdt._sync_model()
    leaves_p = np.stack([t.get_leaf_index(X) for t in b._gbdt.models_], 1)
    np.testing.assert_array_equal(leaves_n, leaves_p)


def test_refit_invalidates_packed_cache():
    """refit() mutates leaf values in place AFTER predict_leaf_index has
    (re)populated the packed-predictor cache; native predictions must
    reflect the refitted values (regression test for the mutation-counter
    ordering bug)."""
    rng = np.random.RandomState(11)
    X = rng.rand(600, 4)
    y = (X[:, 0] > 0.5).astype(float)
    b = lgb.train({"objective": "binary", "num_leaves": 15,
                   "verbosity": -1, "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=5)
    _ = b.predict(X)                      # populate the packed cache
    b.refit(X, 1.0 - y)                   # inverted labels
    p_n, p_p = _predict_both(b, X)
    np.testing.assert_array_equal(p_n, p_p)
    # refitted native predictions must differ from the pre-refit model
    b2 = lgb.train({"objective": "binary", "num_leaves": 15,
                    "verbosity": -1, "min_data_in_leaf": 5},
                   lgb.Dataset(X, label=y), num_boost_round=5)
    assert not np.allclose(b.predict(X), b2.predict(X))
