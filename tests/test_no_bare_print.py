"""Tier-1 lint shim: no bare print() in the runtime package.

The standalone checker (tools/check_no_bare_print.py, ISSUE 2) was
retired in favor of the tpulint rule of the same name (ISSUE 3,
tools/tpulint/rules/bare_print.py — same whitelist and rationale).
This file stays so the historical tier-1 entry keeps passing; the full
suite (all rules) runs in tests/test_tpulint.py.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tools.tpulint import run_lint  # noqa: E402


def test_no_bare_print_in_package():
    report = run_lint(os.path.join(_REPO, "lightgbm_tpu"),
                      rules=["no-bare-print"])
    assert report.active == [], (
        "bare print() calls found (route through utils.log or the event "
        f"log): {[f.render() for f in report.active]}")
