"""Tier-1 lint: no bare print() in the runtime package — all output goes
through utils.log or the structured event log (ISSUE 2 satellite;
tools/check_no_bare_print.py)."""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tools.check_no_bare_print import find_bare_prints  # noqa: E402


def test_no_bare_print_in_package():
    violations = find_bare_prints(os.path.join(_REPO, "lightgbm_tpu"))
    assert violations == [], (
        "bare print() calls found (route through utils.log or the event "
        f"log): {violations}")
