"""End-to-end training across the full objective suite (ref:
tests/python_package_test/test_engine.py trains every objective)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _pos_problem(n=2000, seed=5):
    """Positive-target regression problem (poisson/gamma/tweedie need
    non-negative labels)."""
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 4)
    mu = np.exp(1.5 * X[:, 0] + 0.5 * X[:, 1])
    y = rng.poisson(mu).astype(np.float64)
    return X, y, mu


@pytest.mark.parametrize("objective", ["huber", "fair", "quantile", "mape"])
def test_robust_regression_objectives(objective):
    rng = np.random.RandomState(3)
    X = rng.rand(3000, 4)
    y = 3 * X[:, 0] + X[:, 1] + 0.1 * rng.randn(3000)
    y[::50] += 20  # outliers the robust losses should shrug off
    b = lgb.train({"objective": objective, "num_leaves": 15,
                   "verbosity": -1, "learning_rate": 0.2,
                   "min_data_in_leaf": 5},
                  lgb.Dataset(X, label=y), num_boost_round=30)
    pred = b.predict(X)
    clean = np.ones(len(y), bool)
    clean[::50] = False
    mse = float(np.mean((pred[clean] - y[clean]) ** 2))
    # quantile's +/-alpha gradients converge slowest; others are tight
    limit = 2.0 if objective == "quantile" else 0.5
    assert mse < limit, (objective, mse)


@pytest.mark.parametrize("objective", ["poisson", "gamma", "tweedie"])
def test_count_and_tweedie_objectives(objective):
    X, y, mu = _pos_problem()
    if objective == "gamma":
        y = y + 0.1  # gamma needs strictly positive labels
    b = lgb.train({"objective": objective, "num_leaves": 15,
                   "verbosity": -1, "learning_rate": 0.1,
                   "min_data_in_leaf": 20},
                  lgb.Dataset(X, label=y), num_boost_round=40)
    pred = b.predict(X)
    assert (pred > 0).all()          # log-link predictions are positive
    corr = np.corrcoef(pred, mu)[0, 1]
    assert corr > 0.8, (objective, corr)


def test_multiclassova():
    rng = np.random.RandomState(1)
    X = rng.randn(1500, 4)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    b = lgb.train({"objective": "multiclassova", "num_class": 3,
                   "num_leaves": 7, "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=15)
    proba = b.predict(X)
    assert proba.shape == (1500, 3)
    acc = float(np.mean(np.argmax(proba, 1) == y))
    assert acc > 0.85, acc


@pytest.mark.parametrize("objective", ["cross_entropy",
                                       "cross_entropy_lambda"])
def test_cross_entropy_objectives(objective):
    rng = np.random.RandomState(2)
    X = rng.randn(2000, 4)
    p = 1 / (1 + np.exp(-(X[:, 0] + X[:, 1])))
    y = p  # soft labels in [0, 1]
    b = lgb.train({"objective": objective, "num_leaves": 15,
                   "verbosity": -1, "learning_rate": 0.1},
                  lgb.Dataset(X, label=y), num_boost_round=30)
    pred = b.predict(X)
    if objective == "cross_entropy":
        assert ((pred >= 0) & (pred <= 1)).all()
    else:
        # xentlambda predicts the unbounded intensity via softplus
        # (ref: xentropy_objective.hpp CrossEntropyLambda::ConvertOutput)
        assert (pred >= 0).all()
    corr = np.corrcoef(pred, p)[0, 1]
    assert corr > 0.9, (objective, corr)
