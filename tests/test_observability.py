"""Observability subsystem (ISSUE 2 tentpole): metrics registry, JSONL
event log schema, recompile watchdog, device-memory sampling, logger
reset path."""

import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.observability import (EventLogger, MetricsRegistry,
                                        RecompileDetector,
                                        global_registry,
                                        sample_device_memory)
from lightgbm_tpu.utils import log
from lightgbm_tpu.utils.timer import global_timer


def _data(n=600, f=4, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X[:, 0] + 0.5 * rng.randn(n)
    return X, y


def _read_events(metrics_dir, rank=0):
    path = os.path.join(metrics_dir, f"events-rank{rank}.jsonl")
    assert os.path.exists(path), f"missing event log {path}"
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


# --------------------------------------------------------------- registry
def test_metrics_registry_counters_and_gauges():
    reg = MetricsRegistry()
    reg.inc("a")
    reg.inc("a", 2)
    reg.set_gauge("g", 7)
    reg.set_gauge("g", 9)
    snap = reg.snapshot()
    assert snap["counters"]["a"] == 3
    assert snap["gauges"]["g"] == 9
    reg.reset()
    assert reg.snapshot() == {"counters": {}, "gauges": {}}


def test_sample_device_memory_shape():
    stats = sample_device_memory()   # {} on backends without memory_stats
    assert isinstance(stats, dict)
    for v in stats.values():
        assert isinstance(v, int) and v >= 0


# -------------------------------------------------------------- event log
def test_event_log_one_iteration_event_per_round(tmp_path):
    """Acceptance: a 10-iteration metrics run writes a parseable JSONL
    with exactly one rank-tagged `iteration` event per round whose phase
    breakdown carries the bulk of the measured wall-clock."""
    X, y = _data()
    md = str(tmp_path / "metrics")
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbosity": -1, "metric": "l2",
                     "is_provide_training_metric": True},
                    lgb.Dataset(X, label=y), num_boost_round=10,
                    valid_sets=[lgb.Dataset(X[:100], label=y[:100])],
                    metrics_dir=md)
    assert bst.current_iteration() == 10
    events = _read_events(md)
    kinds = [e["event"] for e in events]
    assert kinds[0] == "train_start"
    assert kinds[-1] == "train_end"
    iters = [e for e in events if e["event"] == "iteration"]
    assert len(iters) == 10
    assert [e["iteration"] for e in iters] == list(range(1, 11))
    for e in iters:
        assert e["rank"] == 0
        assert e["time_s"] > 0
        assert e["phases"], "iteration event must carry a phase breakdown"
        assert e["trees"] and all(t["leaves"] >= 1 for t in e["trees"])
        assert "valid_0 l2" in e["valid"]
        assert "l2" in e["train"]
        assert e["counters"].get("trees_grown", 0) >= e["iteration"]
    # the named phases account for the bulk of the measured wall-clock
    total_time = sum(e["time_s"] for e in iters)
    total_phase = sum(sum(e["phases"].values()) for e in iters)
    assert total_phase >= 0.5 * total_time, (
        f"phases cover {total_phase:.4f}s of {total_time:.4f}s")
    # grow is always among the recorded phases
    assert any("GBDT::grow_tree" in e["phases"] for e in iters)
    # metrics run must not leave the global timer force-enabled
    assert global_timer.enabled == bool(
        os.environ.get("LIGHTGBM_TPU_TIMETAG", ""))


def test_event_log_checkpoint_and_fault_events(tmp_path, monkeypatch):
    """Checkpoint writes and injected faults land on the event log
    (rank-tagged), including the failure path under LGBM_TPU_FAULT."""
    from lightgbm_tpu.reliability import faults
    monkeypatch.setenv("LGBM_TPU_FAULT", "ckpt_write_fail@5")
    faults.reload()
    X, y = _data()
    md = str(tmp_path / "metrics")
    ck = str(tmp_path / "ckpt")
    writes0 = global_registry.counter("checkpoint_writes")
    fails0 = global_registry.counter("checkpoint_failures")
    try:
        lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1, "metric": "none"},
                  lgb.Dataset(X, label=y), num_boost_round=10,
                  metrics_dir=md, checkpoint_dir=ck, checkpoint_freq=5)
    finally:
        monkeypatch.delenv("LGBM_TPU_FAULT", raising=False)
        faults.reload()
    events = _read_events(md)
    faults_seen = [e for e in events if e["event"] == "fault_injected"]
    assert [f["kind"] for f in faults_seen] == ["ckpt_write_fail"]
    assert faults_seen[0]["iteration"] == 5
    failed = [e for e in events if e["event"] == "checkpoint_write_failed"]
    assert len(failed) == 1 and failed[0]["iteration"] == 5
    ok = [e for e in events if e["event"] == "checkpoint"]
    assert [e["iteration"] for e in ok] == [10]
    # counters must reflect both outcomes.  Per-iteration events can lag
    # the ASYNC checkpoint writer (the final write lands after the last
    # iteration event snapshots the registry), so the settled numbers
    # live in train_end's post-flush snapshot (ISSUE 5).
    last = [e for e in events if e["event"] == "train_end"][-1]
    assert last["counters"].get("checkpoint_failures", 0) == fails0 + 1
    assert last["counters"].get("checkpoint_writes", 0) == writes0 + 1


def test_record_metrics_requires_sink():
    with pytest.raises(ValueError):
        lgb.record_metrics()


def test_event_log_size_rotation(tmp_path):
    """metrics_rotate_mb (ISSUE 3 satellite): when the live file would
    exceed the cap it rolls to .1, .2, ... oldest-highest, the live file
    always holds the newest events, and no event is lost."""
    md = str(tmp_path / "rot")
    # ~1 KiB cap => every few ~120-byte events rotate the file
    logger = EventLogger(md, rank=0, rotate_mb=1.0 / 1024)
    n = 50
    for i in range(n):
        logger.emit("tick", i=i, pad="x" * 80)
    logger.close()
    base = os.path.join(md, "events-rank0.jsonl")
    rolls = sorted(f for f in os.listdir(md) if f != "events-rank0.jsonl")
    assert rolls, "a 1KiB cap over ~6KiB of events must have rotated"
    assert all(f.startswith("events-rank0.jsonl.") for f in rolls)
    # every roll respects the cap; chronology: .N oldest ... .1, then live
    order = sorted((int(f.rsplit(".", 1)[1]) for f in rolls), reverse=True)
    seen = []
    for idx in order:
        p = f"{base}.{idx}"
        assert os.path.getsize(p) <= 1024
        seen += [json.loads(line)["i"] for line in open(p) if line.strip()]
    seen += [json.loads(line)["i"] for line in open(base) if line.strip()]
    assert seen == list(range(n)), "rotation lost or reordered events"


def test_event_log_rotation_via_train_param(tmp_path):
    """The metrics_rotate_mb param reaches the engine's EventLogger."""
    X, y = _data(n=200)
    md = str(tmp_path / "metrics")
    lgb.train({"objective": "regression", "num_leaves": 4,
               "verbosity": -1, "metric": "l2",
               "metrics_rotate_mb": 1.0 / 1024},
              lgb.Dataset(X, label=y), num_boost_round=8,
              metrics_dir=md)
    names = os.listdir(md)
    assert "events-rank0.jsonl" in names
    assert any(n.startswith("events-rank0.jsonl.") for n in names), (
        f"expected rotated files under a 1KiB cap, got {names}")


# ------------------------------------------------------ recompile watchdog
def test_recompile_detector_warns_once_per_new_signature():
    """Acceptance: exactly one warning per NEW shape signature after the
    first call; repeats of a seen signature stay silent."""
    import jax
    import jax.numpy as jnp

    warnings = []
    log.set_verbosity(1)   # earlier trainings may have left -1
    log.register_callback(
        lambda msg: warnings.append(msg) if "[Warning]" in msg else None)
    try:
        fn = RecompileDetector(jax.jit(lambda x: x * 2.0), "toy")
        before = global_registry.counter("recompiles")
        fn(jnp.zeros(3))                 # first signature: no warning
        assert len(warnings) == 0
        fn(jnp.zeros(4))                 # new signature: one warning
        assert len(warnings) == 1 and "re-trace" in warnings[0]
        fn(jnp.zeros(4))                 # seen signature: silent
        assert len(warnings) == 1
        fn(jnp.zeros((2, 2)))            # another new one
        assert len(warnings) == 2
        assert fn.signatures_seen == 3
        assert global_registry.counter("recompiles") == before + 2
    finally:
        log.reset()


def test_recompile_detector_fires_in_training():
    """The wrapped grow entry warns when a mid-training shape change
    re-traces the grower (forced here by shrinking the row count)."""
    X, y = _data(n=512)
    params = {"objective": "regression", "num_leaves": 7,
              "verbosity": -1, "metric": "none"}
    bst = lgb.Booster(params=params,
                      train_set=lgb.Dataset(X, label=y))
    bst.update()
    gbdt = bst._gbdt
    assert gbdt._grow_fn.signatures_seen == 1
    warnings = []
    log.set_verbosity(1)   # the booster's verbosity=-1 gated warnings off
    log.register_callback(
        lambda msg: warnings.append(msg) if "[Warning]" in msg else None)
    try:
        import jax.numpy as jnp
        # force a shape change on the jitted grow entry (what a buggy
        # caller mutating n_pad mid-run would do)
        n2 = gbdt.n_pad // 2
        gbdt._grow_fn(gbdt.binned_dev[:, :n2],
                      jnp.zeros(n2, jnp.float32),
                      jnp.ones(n2, jnp.float32),
                      jnp.ones(n2, jnp.float32),
                      gbdt._ones_col_mask, gbdt.meta, gbdt.grow_params)
    finally:
        log.reset()
    assert sum("re-trace" in w for w in warnings) == 1
    assert gbdt._grow_fn.signatures_seen == 2


# ----------------------------------------------------------- logger reset
def test_register_logger_none_unregisters(capsys):
    records = []

    class L:
        def info(self, m):
            records.append(m)

        def warning(self, m):
            records.append(m)

    lgb.register_logger(L())
    log.set_verbosity(1)   # earlier trainings may have left -1
    try:
        log.info("routed")
        assert any("routed" in r for r in records)
        lgb.register_logger(None)       # must NOT raise; unregisters
        log.info("back to stderr")
        assert not any("back to stderr" in r for r in records)
    finally:
        log.reset()


def test_log_reset_clears_state():
    log.set_verbosity(2)
    log.register_callback(lambda m: None)
    log.reset()
    assert log.get_verbosity() == 1
    assert log._LogState.callback is None
    assert getattr(log._LogState, "logger", None) is None
