"""Online continual-learning suite (docs/Online.md): chunk-source
sequencing, the OnlineTrainer loop (boost/refit/auto), per-generation
checkpoint + atomic publish, the failure semantics (corrupt chunk ->
skip, failed publish -> retry with the old generation serving), and
byte-exact resume across a mid-loop stop.

The byte-identity oracle is the same as the serving suite's: a
published generation must serve exactly `Booster.predict` of the model
text the trainer checkpointed — any tolerance would hide a torn publish
or a stale pack."""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.observability.registry import global_registry
from lightgbm_tpu.online import (DirectoryChunkSource, LocalPublisher,
                                 MemoryChunkSource, OnlineTrainer,
                                 write_chunk)
from lightgbm_tpu.reliability import faults
from lightgbm_tpu.serving import ModelRegistry

_PARAMS = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
           "min_data_in_leaf": 5, "device_predict": "true",
           "device_predict_min_bucket": 32, "serve_warmup": False,
           "online_trees_per_chunk": 2, "online_publish_backoff_ms": 1.0}


def _mk(n, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 6).astype(np.float32)
    y = ((X[:, 0] + X[:, 1] > 0)).astype(np.float32)
    return X, y


def _registry():
    return ModelRegistry(min_bucket=32, warmup_rows=64, warmup=False)


def _reset_counters():
    for key in ("online_generations_published",
                "online_generations_skipped", "online_publish_retries"):
        global_registry.inc(key, -global_registry.counter(key))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    _reset_counters()
    monkeypatch.delenv("LGBM_TPU_FAULT", raising=False)
    faults.reload()
    yield
    faults.reload()


# ---------------------------------------------------------------- sources
def test_memory_source_monotone_generations():
    src = MemoryChunkSource()
    X, y = _mk(10)
    assert src.poll() is None
    assert src.push(X, y) == 1
    assert src.push(X, y) == 2
    c1, c2 = src.poll(), src.poll()
    assert (c1.generation, c2.generation) == (1, 2)
    assert c1.ok and c1.num_rows == 10
    assert src.poll() is None
    with pytest.raises(ValueError):
        src.push(X[:0], y[:0])


def test_directory_source_orders_and_ignores_partials(tmp_path):
    d = str(tmp_path)
    X, y = _mk(8)
    # out-of-order landing + junk the watcher must never surface
    write_chunk(d, 2, X, y)
    write_chunk(d, 1, X, y)
    (tmp_path / "chunk-0000003.npz.12345.tmp").write_bytes(b"partial")
    (tmp_path / ".chunk-0000004.npz").write_bytes(b"hidden")
    (tmp_path / "notes.txt").write_text("ignored")
    src = DirectoryChunkSource(d)
    c = src.poll()
    assert c.generation == 1 and c.ok
    assert np.array_equal(c.X, X) and np.array_equal(c.y, y)
    assert src.poll().generation == 2
    assert src.poll() is None
    # a resumed cursor never re-reads consumed generations
    src2 = DirectoryChunkSource(d, start_generation=2)
    assert src2.poll().generation == 2
    assert src2.poll() is None


def test_directory_source_csv_and_npy_label_first_column(tmp_path):
    X, y = _mk(6)
    mat = np.column_stack([y, X]).astype(np.float64)
    np.savetxt(tmp_path / "chunk-0000001.csv", mat, delimiter=",")
    np.save(tmp_path / "chunk-0000002.npy", mat)
    src = DirectoryChunkSource(str(tmp_path))
    for gen in (1, 2):
        c = src.poll()
        assert c.generation == gen and c.ok
        assert np.allclose(c.X, X) and np.allclose(c.y, y)


def test_directory_source_torn_chunk_surfaces_error(tmp_path):
    (tmp_path / "chunk-0000001.npz").write_bytes(b"not an npz at all")
    src = DirectoryChunkSource(str(tmp_path))
    c = src.poll()
    assert c.generation == 1 and not c.ok and c.error
    assert src.poll() is None  # monotone: the damaged gen is consumed


# ---------------------------------------------------------------- trainer
def test_trainer_boost_loop_publishes_and_checkpoints(tmp_path):
    reg = _registry()
    src = MemoryChunkSource()
    seen = []
    tr = OnlineTrainer(src, LocalPublisher(reg), params=dict(_PARAMS),
                       checkpoint_dir=str(tmp_path),
                       on_publish=lambda g, v, s: seen.append((g, v, s)))
    src.push(*_mk(300, 1))
    src.push(*_mk(300, 2))
    tr.start()
    assert tr.step() and tr.step()
    assert not tr.step()  # source drained
    assert reg.versions() == {"online": 2}
    assert [(g, v) for g, v, _ in seen] == [(1, 1), (2, 2)]
    # each published generation IS its checkpoint: byte-identical text
    for gen, _v, model_str in seen:
        on_disk = open(tmp_path / f"ckpt_{gen:07d}.txt").read()
        assert on_disk == model_str
    # the published entry serves exactly Booster.predict of that text
    Xq = _mk(40, 9)[0]
    entry = reg.get("online")
    try:
        got = np.asarray(entry.predictor.predict(Xq))
    finally:
        entry.release()
    oracle = lgb.Booster(model_str=seen[-1][2])
    oracle._gbdt.config.device_predict = "true"  # same path as serving
    exp = oracle.predict(Xq)
    assert np.array_equal(got, exp)
    stats = tr.stats()
    assert stats["generations_published"] == 2
    assert stats["generation"] == 2
    assert stats["freshness_lag_s"] is not None \
        and stats["freshness_lag_s"] > 0
    assert global_registry.gauge("model_freshness_lag_s") is not None


def test_auto_mode_refits_small_chunks_boosts_large(tmp_path):
    reg = _registry()
    src = MemoryChunkSource()
    tr = OnlineTrainer(src, LocalPublisher(reg),
                       params={**_PARAMS, "online_mode": "auto"},
                       checkpoint_dir=str(tmp_path))
    src.push(*_mk(300, 1))     # first chunk always boosts (no model yet)
    tr.start()
    assert tr.step()
    n0 = tr.booster.num_trees()
    assert n0 == 2
    src.push(*_mk(300, 2))     # 300 rows >= 2 trees -> boost
    assert tr.step()
    assert tr.booster.num_trees() == n0 + 2
    b_before = tr.booster.model_to_string()
    src.push(*_mk(3, 3))       # 3 rows < 4 trees -> refit in place
    assert tr.step()
    assert tr.booster.num_trees() == n0 + 2      # no new trees
    assert tr.booster.model_to_string() != b_before  # leaves moved
    assert reg.versions()["online"] == 3


def test_publish_fail_fault_retries_and_lands(tmp_path, monkeypatch):
    monkeypatch.setenv("LGBM_TPU_FAULT", "online_publish_fail@1")
    faults.reload()
    reg = _registry()
    src = MemoryChunkSource()
    tr = OnlineTrainer(src, LocalPublisher(reg), params=dict(_PARAMS),
                       checkpoint_dir=str(tmp_path))
    src.push(*_mk(200, 1))
    tr.start()
    assert tr.step()
    # first attempt raised (injected), the retry published — never a
    # half-published model, never a lost generation
    assert reg.versions() == {"online": 1}
    assert global_registry.counter("online_publish_retries") == 1
    assert global_registry.counter("online_generations_published") == 1
    assert global_registry.counter("online_generations_skipped") == 0


class _AlwaysFailPublisher:
    def publish(self, name, model_str, path):
        raise RuntimeError("publish target down")

    def probe(self, name, rows):
        raise RuntimeError("unreachable")


def test_publish_exhausted_skips_and_keeps_old_generation(tmp_path):
    reg = _registry()
    src = MemoryChunkSource()
    good = OnlineTrainer(src, LocalPublisher(reg), params=dict(_PARAMS),
                         checkpoint_dir=str(tmp_path / "a"))
    src.push(*_mk(200, 1))
    good.start()
    assert good.step()
    assert reg.versions() == {"online": 1}
    # a second trainer whose publisher is down: the generation is
    # counted SKIPPED after the bounded retries and the registry still
    # serves the old version untouched
    src2 = MemoryChunkSource()
    bad = OnlineTrainer(src2, _AlwaysFailPublisher(),
                        params={**_PARAMS, "online_publish_retry_max": 1},
                        checkpoint_dir=str(tmp_path / "b"))
    src2.push(*_mk(200, 2))
    bad.start()
    assert bad.step()
    assert reg.versions() == {"online": 1}          # old gen serving
    assert global_registry.counter("online_generations_skipped") == 1
    assert global_registry.counter("online_publish_retries") == 2


def test_chunk_corrupt_fault_skips_generation(tmp_path, monkeypatch):
    monkeypatch.setenv("LGBM_TPU_FAULT", "online_chunk_corrupt@2")
    faults.reload()
    d = tmp_path / "chunks"
    d.mkdir()
    write_chunk(str(d), 1, *_mk(200, 1))
    write_chunk(str(d), 2, *_mk(200, 2))
    write_chunk(str(d), 3, *_mk(200, 3))
    reg = _registry()
    tr = OnlineTrainer(DirectoryChunkSource(str(d)), LocalPublisher(reg),
                       params=dict(_PARAMS),
                       checkpoint_dir=str(tmp_path / "ck"))
    tr.start()
    assert tr.step()                      # gen 1 publishes (v1)
    assert tr.step()                      # gen 2 corrupt -> skipped
    assert reg.versions() == {"online": 1}  # old generation kept serving
    assert global_registry.counter("online_generations_skipped") == 1
    assert tr.step()                      # gen 3 publishes (v2)
    assert reg.versions() == {"online": 2}
    assert tr.stats()["generation"] == 3


def test_resume_from_checkpoint_is_byte_exact(tmp_path):
    """A trainer stopped after generation 2 and relaunched must publish
    its checkpoint FIRST (no served-version regression) and re-train
    generation 3 into exactly the bytes the uninterrupted run
    produced — generation N is a pure function of (model text N-1,
    chunk bytes N)."""
    d = tmp_path / "chunks"
    d.mkdir()
    for g in (1, 2, 3):
        write_chunk(str(d), g, *_mk(250, 10 + g))
    # control: all three generations in one process
    reg_a = _registry()
    tr_a = OnlineTrainer(DirectoryChunkSource(str(d)),
                         LocalPublisher(reg_a), params=dict(_PARAMS),
                         checkpoint_dir=str(tmp_path / "ck_a"))
    tr_a.start()
    assert tr_a.step() and tr_a.step() and tr_a.step()
    final_a = tr_a.booster.model_to_string()
    gen2_a = open(tmp_path / "ck_a" / "ckpt_0000002.txt").read()
    # interrupted: generations 1-2, then the process "dies"
    reg_b = _registry()
    tr_b = OnlineTrainer(DirectoryChunkSource(str(d)),
                         LocalPublisher(reg_b), params=dict(_PARAMS),
                         checkpoint_dir=str(tmp_path / "ck_b"))
    tr_b.start()
    assert tr_b.step() and tr_b.step()
    # relaunch: resume must land at generation 2 with identical bytes,
    # publish it immediately, then consume ONLY generation 3
    reg_c = _registry()
    published = []
    tr_c = OnlineTrainer(DirectoryChunkSource(str(d)),
                         LocalPublisher(reg_c), params=dict(_PARAMS),
                         checkpoint_dir=str(tmp_path / "ck_b"),
                         on_publish=lambda g, v, s:
                         published.append((g, v, s)))
    tr_c.start()
    assert tr_c.generation == 2
    assert published and published[0][0] == 2     # resume re-publish
    assert published[0][2] == gen2_a              # == control's gen 2
    assert reg_c.versions() == {"online": 1}
    assert tr_c.step()
    assert not tr_c.step()   # generations 1-2 never re-consumed
    assert tr_c.generation == 3
    assert tr_c.booster.model_to_string() == final_a   # byte-exact
    assert open(tmp_path / "ck_b" / "ckpt_0000003.txt").read() == final_a


def test_freshness_slo_feeds_burn_tracker(tmp_path):
    """online_max_lag_s wires the per-generation lag into the PR-14
    SloTracker: skipped generations count against the error budget."""
    reg = _registry()
    src = MemoryChunkSource()
    tr = OnlineTrainer(src, _AlwaysFailPublisher(),
                       params={**_PARAMS, "online_max_lag_s": 5.0,
                               "online_publish_retry_max": 0,
                               "serve_slo_fast_window_s": 1.0,
                               "serve_slo_slow_window_s": 2.0},
                       checkpoint_dir=str(tmp_path))
    assert tr.slo.enabled
    for g in range(1, 4):
        src.push(*_mk(120, g))
        tr.start()
        assert tr.step()
    # every generation skipped -> both windows burn
    assert tr.slo.evaluate() is True
    assert global_registry.gauge("fleet_slo_burning") == 1.0
