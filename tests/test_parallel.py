"""Distributed training over a device mesh (ref: SURVEY §2.3 #2-3;
data_parallel_tree_learner.cpp, feature_parallel_tree_learner.cpp;
test pattern: tests/distributed/_test_distributed.py:168-184 — train the
same problem sharded and unsharded, assert identical models).

tests/conftest.py provides the 8-device virtual CPU platform.
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import lightgbm_tpu as lgb
from lightgbm_tpu.learner import FeatureMeta, GrowParams, grow_tree
from lightgbm_tpu.ops.split import MISSING_NONE, SplitParams
from lightgbm_tpu.parallel import (data_parallel_shardings, make_mesh,
                                   grow_params_for_mesh)


def _problem(n=4096, F=6, B=32, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F)
    logit = 4 * (X[:, 0] - 0.5) + 2 * X[:, 1] * X[:, 2] - X[:, 3]
    y = (rng.rand(n) < 1 / (1 + np.exp(-3 * logit))).astype(np.float32)
    binned = np.stack([np.clip((X[:, f] * B).astype(np.int64), 0, B - 1)
                       for f in range(F)]).astype(np.uint8)
    return X, y, binned


def _tree_fields(t):
    return {k: np.asarray(v) for k, v in t._asdict().items()}


def test_sharded_grow_tree_matches_unsharded():
    """Row-sharded grow_tree must produce the identical tree: same splits,
    thresholds, and leaf stats (the GSPMD psum replaces ReduceScatter)."""
    X, y, binned = _problem()
    F, n = binned.shape
    B, L = 32, 15
    grad = (0.5 - y).astype(np.float32)
    hess = np.ones(n, np.float32)
    meta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.full(F, MISSING_NONE, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    params = grow_params_for_mesh(
        GrowParams(num_leaves=L, max_bin=B,
                   split=SplitParams(min_data_in_leaf=5)))
    args_host = (binned, grad, hess, np.ones(n, np.float32),
                 np.ones(F, bool))

    t_ref, leaf_ref = grow_tree(*[jnp.asarray(a) for a in args_host],
                                meta, params)

    mesh = make_mesh(8)
    by_row, row, _ = data_parallel_shardings(mesh)
    sharded = (jax.device_put(binned, by_row),
               jax.device_put(grad, row),
               jax.device_put(hess, row),
               jax.device_put(np.ones(n, np.float32), row),
               jnp.asarray(np.ones(F, bool)))
    t_sh, leaf_sh = grow_tree(*sharded, meta, params)

    ref, sh = _tree_fields(t_ref), _tree_fields(t_sh)
    assert int(ref["num_leaves"]) == int(sh["num_leaves"]) > 1
    for k in ("split_feature", "threshold_bin", "left_child", "right_child",
              "leaf_count", "internal_count", "default_left", "leaf_parent",
              "leaf_depth"):
        np.testing.assert_array_equal(ref[k], sh[k], err_msg=k)
    for k in ("leaf_value", "leaf_weight", "split_gain", "internal_value"):
        np.testing.assert_allclose(ref[k], sh[k], rtol=1e-4, atol=1e-6,
                                   err_msg=k)
    np.testing.assert_array_equal(np.asarray(leaf_ref), np.asarray(leaf_sh))


def _train_model_text(X, y, extra_params, rounds=8):
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "learning_rate": 0.2,
              "tpu_growth_strategy": "leafwise"}
    params.update(extra_params)
    booster = lgb.train(params, lgb.Dataset(X, label=y),
                        num_boost_round=rounds)
    return booster, _structure_text(booster)


def _structure_text(booster):
    """Model text with float payloads rounded to 5 significant digits:
    sharded psum reduction order differs from the sequential sum in ulps
    (the reference's distributed test likewise asserts quality, not text:
    _test_distributed.py:168-184), so structural fields must be exact and
    float fields equal to rounded precision."""
    import re
    from lightgbm_tpu.boosting.model_io import save_model_to_string
    txt = save_model_to_string(booster._gbdt)
    txt = txt.split("\nparameters:")[0]  # params echo names the learner
    return re.sub(r"-?\d+\.\d+(e[-+]?\d+)?",
                  lambda m: "%.5g" % float(m.group(0)), txt)


def test_data_parallel_training_identical_model():
    """tree_learner=data on the 8-device mesh == serial, model-text equal
    (mirrors _test_distributed.py's identical-model assertion)."""
    X, y, _ = _problem(n=4096)
    b_serial, txt_serial = _train_model_text(X, y, {"tree_learner": "serial"})
    b_data, txt_data = _train_model_text(X, y, {"tree_learner": "data"})
    assert b_data._gbdt.mesh is not None, "mesh was not engaged"
    assert txt_serial == txt_data
    np.testing.assert_allclose(b_data.predict(X), b_serial.predict(X),
                               rtol=1e-5)


def test_data_parallel_respects_num_machines():
    X, y, _ = _problem(n=2048)
    b2, txt2 = _train_model_text(X, y, {"tree_learner": "data",
                                        "num_machines": 2}, rounds=4)
    assert b2._gbdt.mesh is not None
    assert len(b2._gbdt.mesh.devices.ravel()) == 2
    _, txt_serial = _train_model_text(X, y, {"tree_learner": "serial"},
                                      rounds=4)
    assert txt2 == txt_serial


def test_feature_parallel_training_identical_model():
    """tree_learner=feature shards the feature axis; same model as serial
    (ref: feature_parallel_tree_learner.cpp:23 — full data, sharded scan)."""
    X, y, _ = _problem(n=2048)
    b_f, txt_f = _train_model_text(X, y, {"tree_learner": "feature"},
                                   rounds=4)
    assert b_f._gbdt.mesh is not None
    _, txt_serial = _train_model_text(X, y, {"tree_learner": "serial"},
                                      rounds=4)
    assert txt_f == txt_serial


def test_voting_parallel_full_topk_matches_serial():
    """PV-Tree voting (ref: voting_parallel_tree_learner.cpp:151
    GlobalVoting): when top_k >= F every feature with a valid local gain
    is elected, so the elected global scan reproduces the serial model
    (up to psum reduction-order float noise)."""
    X, y, _ = _problem(n=2048)
    b_v, _ = _train_model_text(X, y, {"tree_learner": "voting",
                                      "min_data_in_leaf": 40}, rounds=3)
    assert b_v._gbdt.mesh is not None
    assert b_v._gbdt.grow_params.voting is not None, \
        "voting must take the PV-Tree path, not alias to data"
    b_s, _ = _train_model_text(X, y, {"tree_learner": "serial",
                                      "min_data_in_leaf": 40}, rounds=3)
    np.testing.assert_allclose(b_v.predict(X), b_s.predict(X), atol=1e-5)


def test_voting_parallel_small_topk_trains():
    """top_k < F reduces the reduced histogram set (the PV-Tree traffic
    saving); training stays close to serial quality on a problem whose
    signal is concentrated in few features."""
    X, y, _ = _problem(n=2048)
    b_v, _ = _train_model_text(X, y, {"tree_learner": "voting", "top_k": 2,
                                      "min_data_in_leaf": 40}, rounds=5)
    assert b_v._gbdt.grow_params.voting is not None
    assert b_v._gbdt.grow_params.voting.top_k == 2
    b_s, _ = _train_model_text(X, y, {"tree_learner": "serial",
                                      "min_data_in_leaf": 40}, rounds=5)
    corr = np.corrcoef(b_v.predict(X), b_s.predict(X))[0, 1]
    assert corr > 0.95, f"voting model diverged from serial (corr={corr})"


def test_voting_composes_with_extra_trees_and_monotone():
    """The local vote scan must not trip the extra-trees/monotone/CEGB
    branches of find_best_split (those need per-leaf state the vote region
    does not carry); they apply in the exact global scan instead."""
    X, y, _ = _problem(n=2048)
    b_et, _ = _train_model_text(
        X, y, {"tree_learner": "voting", "extra_trees": True,
               "min_data_in_leaf": 40}, rounds=2)
    assert b_et._gbdt.grow_params.voting is not None
    assert np.isfinite(b_et.predict(X)).all()
    b_mc, _ = _train_model_text(
        X, y, {"tree_learner": "voting",
               "monotone_constraints": [1, 0, 0, 0, 0, 0],
               "min_data_in_leaf": 40}, rounds=2)
    assert np.isfinite(b_mc.predict(X)).all()


def test_sharded_histogram_psum_semantics():
    """The histogram of sharded rows equals the histogram of all rows: the
    per-shard partial sums must be psum'd, not dropped (the exact invariant
    Network::ReduceScatter + HistogramSumReducer maintains)."""
    from lightgbm_tpu.ops.histogram import build_histogram
    _, _, binned = _problem(n=2048, F=4, B=16)
    n = binned.shape[1]
    rng = np.random.RandomState(0)
    gh = np.stack([rng.randn(n), np.abs(rng.randn(n))], 1).astype(np.float32)
    mask = jnp.ones(n, jnp.float32)
    ref = build_histogram(jnp.asarray(binned), jnp.asarray(gh), mask, max_bin=16)

    mesh = make_mesh(8)
    by_row = NamedSharding(mesh, P(None, "data"))
    row2 = NamedSharding(mesh, P("data", None))
    rowv = NamedSharding(mesh, P("data"))
    out = build_histogram(jax.device_put(binned, by_row),
                          jax.device_put(gh, row2),
                          jax.device_put(np.ones(n, np.float32), rowv),
                          max_bin=16)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-5, atol=1e-5)


def test_distributed_binning_matches_global():
    """Sample-replicated distributed binning (parallel/binning.py): the
    mappers computed from allgathered per-host samples equal the mappers
    a single host computes from the same merged sample, and remain
    deterministic across 'ranks' (ref: dataset_loader.cpp:1070)."""
    from lightgbm_tpu.parallel import merged_bin_mappers, sample_rows
    rng = np.random.RandomState(11)
    Xfull = rng.randn(40_000, 5)
    shards = np.array_split(Xfull, 8)
    samples = [sample_rows(s, 2000, seed=1) for s in shards]
    m_dist = merged_bin_mappers(samples, max_bin=63)
    # every rank computes the same mappers from the same gathered sample
    m_dist2 = merged_bin_mappers(samples, max_bin=63)
    for a, b in zip(m_dist, m_dist2):
        np.testing.assert_array_equal(a.bin_upper_bound, b.bin_upper_bound)
    # and the mappers bin the full data sensibly
    for f, m in enumerate(m_dist):
        bins = m.values_to_bins(Xfull[:, f])
        assert bins.max() < m.num_bin
        assert len(np.unique(bins)) > 30


def test_sharded_wave_engine_matches_unsharded():
    """The WAVE engine (the default/Pallas engine's growth loop) executed
    under shard_map over the 8-device mesh must produce the identical tree
    and row partition as single-device wave: the per-shard histograms are
    psum'd exactly like the reference's ReduceScatter of its serial
    learner's histograms (data_parallel_tree_learner.cpp:282-295)."""
    from lightgbm_tpu.learner.wave import grow_tree_wave
    from lightgbm_tpu.parallel import make_sharded_wave_fn

    X, y, binned = _problem(n=8192)
    F, n = binned.shape
    B, L = 32, 15
    grad = (0.5 - y).astype(np.float32)
    hess = np.ones(n, np.float32)
    meta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.full(F, MISSING_NONE, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    params = GrowParams(num_leaves=L, max_bin=B,
                        split=SplitParams(min_data_in_leaf=5))
    t_ref, leaf_ref = grow_tree_wave(
        jnp.asarray(binned), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(n, jnp.float32), jnp.ones(F, bool), meta, params)

    mesh = make_mesh(8)
    by_row, row, _ = data_parallel_shardings(mesh)
    fn = make_sharded_wave_fn(mesh)
    t_sh, leaf_sh = fn(jax.device_put(binned, by_row),
                       jax.device_put(grad, row),
                       jax.device_put(hess, row),
                       jax.device_put(np.ones(n, np.float32), row),
                       jnp.asarray(np.ones(F, bool)), meta, params)
    ref, sh = _tree_fields(t_ref), _tree_fields(t_sh)
    assert int(ref["num_leaves"]) == int(sh["num_leaves"]) > 1
    for k in ("split_feature", "threshold_bin", "left_child", "right_child",
              "leaf_count", "internal_count", "default_left", "leaf_parent",
              "leaf_depth"):
        np.testing.assert_array_equal(ref[k], sh[k], err_msg=k)
    for k in ("leaf_value", "leaf_weight", "split_gain", "internal_value"):
        np.testing.assert_allclose(ref[k], sh[k], rtol=1e-4, atol=1e-6,
                                   err_msg=k)
    np.testing.assert_array_equal(np.asarray(leaf_ref), np.asarray(leaf_sh))


def test_sharded_wave_prune_matches_unsharded():
    """Same invariant with the overgrow-and-prune quality mode on (the
    bench default): the prune replay runs replicated on psum'd gains and
    the final exact counts ride a psum."""
    from lightgbm_tpu.learner.wave import grow_tree_wave
    from lightgbm_tpu.parallel import make_sharded_wave_fn

    X, y, binned = _problem(n=8192, seed=7)
    F, n = binned.shape
    B, L = 32, 15
    grad = (0.5 - y).astype(np.float32)
    hess = np.ones(n, np.float32)
    meta = FeatureMeta(
        num_bin=jnp.full(F, B, jnp.int32),
        missing_type=jnp.full(F, MISSING_NONE, jnp.int32),
        default_bin=jnp.zeros(F, jnp.int32),
        penalty=jnp.ones(F, jnp.float32))
    params = GrowParams(num_leaves=L, max_bin=B, wave_prune=True,
                        split=SplitParams(min_data_in_leaf=5))
    t_ref, leaf_ref = grow_tree_wave(
        jnp.asarray(binned), jnp.asarray(grad), jnp.asarray(hess),
        jnp.ones(n, jnp.float32), jnp.ones(F, bool), meta, params)
    mesh = make_mesh(8)
    by_row, row, _ = data_parallel_shardings(mesh)
    fn = make_sharded_wave_fn(mesh)
    t_sh, leaf_sh = fn(jax.device_put(binned, by_row),
                       jax.device_put(grad, row),
                       jax.device_put(hess, row),
                       jax.device_put(np.ones(n, np.float32), row),
                       jnp.asarray(np.ones(F, bool)), meta, params)
    ref, sh = _tree_fields(t_ref), _tree_fields(t_sh)
    for k in ("num_leaves", "split_feature", "threshold_bin", "leaf_count",
              "internal_count"):
        np.testing.assert_array_equal(ref[k], sh[k], err_msg=k)
    np.testing.assert_array_equal(np.asarray(leaf_ref), np.asarray(leaf_sh))


def test_data_parallel_wave_training_identical_model():
    """tree_learner=data with the WAVE engine on the 8-device mesh == serial
    wave training: structurally identical trees (psum reduction order may
    shift float payloads by ulps, so floats compare to tolerance)."""
    X, y, _ = _problem(n=4096)

    def train(extra):
        p = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
             "min_data_in_leaf": 5, "learning_rate": 0.2,
             "tpu_growth_strategy": "wave"}
        p.update(extra)
        return lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=8)

    b_s = train({"tree_learner": "serial"})
    b_d = train({"tree_learner": "data"})
    g = b_d._gbdt
    assert g.mesh is not None and g.mesh.devices.size == 8
    assert g.growth_strategy == "wave"
    # the default engine must NOT have been downgraded under the mesh
    assert g.grow_params.hist_method != "segment" or \
        jax.default_backend() != "tpu"
    b_s._gbdt._drain_pending(keep_depth=0)
    g._drain_pending(keep_depth=0)
    ts, td = b_s._gbdt.models_, g.models_
    assert len(ts) == len(td)
    for a, b in zip(ts, td):
        assert a.num_leaves == b.num_leaves
        np.testing.assert_array_equal(a.split_feature, b.split_feature)
        np.testing.assert_array_equal(a.threshold_in_bin, b.threshold_in_bin)
        np.testing.assert_array_equal(a.leaf_count, b.leaf_count)
        np.testing.assert_allclose(a.leaf_value, b.leaf_value,
                                   rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(b_d.predict(X), b_s.predict(X),
                               rtol=1e-4, atol=1e-6)
