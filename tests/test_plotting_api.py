"""Plotting + extended Booster API (ref: plotting.py; basic.py Booster)."""

import matplotlib
matplotlib.use("Agg")

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def booster():
    rng = np.random.RandomState(0)
    X = rng.randn(1000, 4)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.randn(1000)
    return lgb.train({"objective": "regression", "num_leaves": 7,
                      "verbosity": -1},
                     lgb.Dataset(X, label=y), num_boost_round=5), X, y


def test_plot_importance(booster):
    b, X, y = booster
    ax = lgb.plot_importance(b)
    labels = [t.get_text() for t in ax.get_yticklabels()]
    assert labels  # informative features present
    ax2 = lgb.plot_importance(b, importance_type="gain")
    assert ax2 is not None


def test_plot_metric(booster):
    rng = np.random.RandomState(1)
    X = rng.randn(600, 3)
    y = (X[:, 0] > 0).astype(np.float64)
    hist = {}
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
               "metric": "auc"},
              lgb.Dataset(X[:400], label=y[:400]), num_boost_round=5,
              valid_sets=[lgb.Dataset(X[400:], label=y[400:])],
              valid_names=["valid"],
              callbacks=[lgb.record_evaluation(hist)])
    ax = lgb.plot_metric(hist, metric="auc")
    assert ax.get_title() == "Metric during training"


def test_plot_split_value_histogram(booster):
    b, X, y = booster
    ax = lgb.plot_split_value_histogram(b, 0)
    assert ax is not None


def test_dump_model_and_dataframe(booster):
    b, X, y = booster
    d = b.dump_model()
    assert d["name"] == "tree"
    assert len(d["tree_info"]) == 5
    assert "tree_structure" in d["tree_info"][0] or d["tree_info"][0]
    df = b.trees_to_dataframe()
    assert len(df) > 5
    assert set(df["tree_index"]) == set(range(5))


def test_bounds_and_shuffle(booster):
    b, X, y = booster
    lo, hi = b.lower_bound(), b.upper_bound()
    raw = b.predict(X, raw_score=True)
    assert lo <= raw.min() and raw.max() <= hi
    pred_before = b.predict(X)
    b.shuffle_models()
    np.testing.assert_allclose(b.predict(X), pred_before, rtol=1e-12)


def test_eval_arbitrary_dataset(booster):
    b, X, y = booster
    res = b.eval(lgb.Dataset(X, label=y), "holdout")
    assert res and res[0][0] == "holdout"
    assert np.isfinite(res[0][2])


def test_reset_parameter_callback():
    rng = np.random.RandomState(2)
    X = rng.randn(800, 3)
    y = X[:, 0]
    b = lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1, "learning_rate": 0.5},
                  lgb.Dataset(X, label=y), num_boost_round=6,
                  callbacks=[lgb.reset_parameter(
                      learning_rate=lambda it: 0.5 * (0.5 ** it))])
    b._gbdt._sync_model()
    shr = [t.shrinkage for t in b._gbdt.models_ if t.num_leaves > 1]
    assert shr[0] > shr[-1]


def test_eval_on_loaded_booster(tmp_path, booster):
    """eval() must work on a predictor-mode booster loaded from file."""
    b, X, y = booster
    path = str(tmp_path / "m.txt")
    b.save_model(path)
    loaded = lgb.Booster(model_file=path)
    res = loaded.eval(lgb.Dataset(X, label=y), "holdout")
    assert res and res[0][0] == "holdout"
    assert np.isfinite(res[0][2])
    # matches the trained booster's own eval on the same data
    res0 = b.eval(lgb.Dataset(X, label=y), "holdout2")
    assert abs(res[0][2] - res0[0][2]) < 1e-5


def test_model_from_string_resets_state(booster):
    b, X, y = booster
    s = b.model_to_string()
    b2 = lgb.train({"objective": "regression", "num_leaves": 7,
                    "verbosity": -1},
                   lgb.Dataset(X, label=y), num_boost_round=2,
                   valid_sets=[lgb.Dataset(X, label=y)],
                   valid_names=["v"])
    b2.model_from_string(s)
    assert b2.name_valid_sets == []
    assert b2.num_trees() == b.num_trees()
    # eval_valid on the fresh model must not crash or ghost old sets
    assert b2.eval_valid() == []


def test_reset_parameter_rebuilds_grow_params():
    rng = np.random.RandomState(5)
    X = rng.randn(1200, 3)
    y = X[:, 0] * 2 + 0.1 * rng.randn(1200)
    b = lgb.Booster(params={"objective": "regression", "num_leaves": 31,
                            "verbosity": -1, "min_data_in_leaf": 5},
                    train_set=lgb.Dataset(X, label=y))
    for _ in range(2):
        b.update()
    b.reset_parameter({"min_data_in_leaf": 400})
    assert b._gbdt.grow_params.split.min_data_in_leaf == 400
    for _ in range(2):
        b.update()
    b._gbdt._sync_model()
    trees = b._gbdt.models_
    # later trees obey the tighter leaf-size bound
    assert min(t.leaf_count[:t.num_leaves].min() for t in trees[2:]) >= 400
    assert min(t.leaf_count[:t.num_leaves].min() for t in trees[:2]) < 400
