"""Prometheus exposition (observability/prom.py): text-format shape,
label folding and escaping, the HTTP listener round trip, and the
trainer-side export path."""

import urllib.request

from lightgbm_tpu.observability.prom import (render_prometheus,
                                             start_metrics_http)
from lightgbm_tpu.observability.registry import MetricsRegistry


def _parse(page):
    """{name_or_labelled_series: float} for every sample line."""
    out = {}
    for line in page.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        out[name] = float(value)
    return out


def test_render_counters_gauges_and_types():
    reg = MetricsRegistry()
    reg.inc("serve_requests", 42)
    reg.set_gauge("device_bytes_in_use", 1024)
    page = render_prometheus(registry=reg)
    assert "# TYPE lgbm_serve_requests counter" in page
    assert "# TYPE lgbm_device_bytes_in_use gauge" in page
    samples = _parse(page)
    assert samples["lgbm_serve_requests"] == 42.0
    assert samples["lgbm_device_bytes_in_use"] == 1024.0
    assert page.endswith("\n")


def test_labelled_series_fold_and_escape():
    reg = MetricsRegistry()
    reg.inc("serve_requests_by_model::higgs", 7)
    reg.inc("serve_requests_by_model::ctr", 3)
    reg.inc('serve_requests_by_model::we"ird\nname', 1)
    page = render_prometheus(registry=reg)
    # one TYPE line for the family, three labelled samples
    assert page.count("# TYPE lgbm_serve_requests_by_model counter") == 1
    samples = _parse(page)
    assert samples['lgbm_serve_requests_by_model{model="higgs"}'] == 7.0
    assert samples['lgbm_serve_requests_by_model{model="ctr"}'] == 3.0
    assert ('lgbm_serve_requests_by_model{model="we\\"ird\\nname"}'
            in samples)


def test_metric_name_sanitization():
    reg = MetricsRegistry()
    reg.inc("weird metric-name!", 1)
    page = render_prometheus(registry=reg)
    assert "lgbm_weird_metric_name_ 1" in page


def test_every_sample_line_is_two_fields():
    reg = MetricsRegistry()
    reg.inc("a", 1)
    reg.inc("b::x", 2)
    reg.set_gauge("c", 3.75)
    for line in render_prometheus(registry=reg).splitlines():
        if line and not line.startswith("#"):
            assert len(line.rsplit(" ", 1)) == 2


def test_http_listener_round_trip():
    reg = MetricsRegistry()
    reg.inc("serve_requests", 5)
    srv = start_metrics_http(port=0, registry=reg)
    assert srv is not None
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=30)
        assert resp.status == 200
        assert "text/plain" in resp.headers["Content-Type"]
        body = resp.read().decode()
        assert _parse(body)["lgbm_serve_requests"] == 5.0
        # non-/metrics paths 404 instead of serving the page
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/other", timeout=30)
        except urllib.error.HTTPError as e:
            assert e.code == 404
        else:
            raise AssertionError("expected 404")
    finally:
        srv.shutdown()


def test_cost_model_totals_export(monkeypatch):
    from lightgbm_tpu.observability.costmodel import global_cost_model
    prev = global_cost_model.enabled
    global_cost_model.reset()
    global_cost_model.enabled = True
    try:
        import jax
        import jax.numpy as jnp
        from lightgbm_tpu.observability.watchdog import RecompileDetector
        fn = RecompileDetector(jax.jit(lambda v: v + 1.0), "export_probe")
        fn(jnp.ones((4,), jnp.float32))
        page = render_prometheus(registry=MetricsRegistry())
        assert 'lgbm_cost_calls_total{phase="export_probe"} 1' in page
    finally:
        global_cost_model.enabled = prev
        global_cost_model.reset()
