"""Quantized training (ref: src/treelearner/gradient_discretizer.{hpp,cpp};
config.h:619-641 use_quantized_grad / num_grad_quant_bins /
quant_train_renew_leaf / stochastic_rounding)."""

import numpy as np

import lightgbm_tpu as lgb


def _binary_problem(n=4000, F=8, seed=11):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, F)
    logit = X[:, 0] + 0.8 * X[:, 1] * X[:, 2] - 0.5 * X[:, 3]
    y = (rng.rand(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    return X, y


def _auc(y, s):
    order = np.argsort(s)
    ranks = np.empty(len(y))
    ranks[order] = np.arange(len(y))
    pos = y > 0
    np_, nn = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - np_ * (np_ - 1) / 2) / (np_ * nn)


def test_quantized_quality_parity_binary():
    """AUC with 4-bin quantized gradients stays within a small delta of the
    fp32 path (the reference's whole premise, gradient_discretizer.hpp)."""
    X, y = _binary_problem()
    base = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
            "learning_rate": 0.1, "seed": 7}
    rounds = 30
    b_fp = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=rounds)
    b_q = lgb.train({**base, "use_quantized_grad": True},
                    lgb.Dataset(X, label=y), num_boost_round=rounds)
    auc_fp = _auc(y, b_fp.predict(X))
    auc_q = _auc(y, b_q.predict(X))
    assert auc_q > auc_fp - 0.01, (auc_q, auc_fp)


def test_quantized_regression_with_renew():
    """quant_train_renew_leaf recomputes leaf outputs from float grads —
    required for regression quality (ref: RenewIntGradTreeOutput)."""
    rng = np.random.RandomState(3)
    X = rng.randn(3000, 6)
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.1 * rng.randn(3000)
    base = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
            "learning_rate": 0.1}
    rounds = 30
    b_fp = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=rounds)
    b_q = lgb.train({**base, "use_quantized_grad": True,
                     "quant_train_renew_leaf": True},
                    lgb.Dataset(X, label=y), num_boost_round=rounds)
    mse_fp = float(np.mean((b_fp.predict(X) - y) ** 2))
    mse_q = float(np.mean((b_q.predict(X) - y) ** 2))
    assert mse_q < mse_fp * 1.3, (mse_q, mse_fp)


def test_quantized_gradients_live_on_grid():
    """Discretized gradients must be integer multiples of the scale with
    |k| <= num_grad_quant_bins/2 (gradient_discretizer.cpp:120)."""
    import jax.numpy as jnp
    X, y = _binary_problem(n=1000)
    booster = lgb.train({"objective": "binary", "num_leaves": 7,
                         "verbosity": -1, "use_quantized_grad": True,
                         "num_grad_quant_bins": 4},
                        lgb.Dataset(X, label=y), num_boost_round=1)
    g = booster._gbdt
    grad, hess = g._grad_fn(g.scores)
    gq, hq, _ = g._discretize_fn(g._slice_row_fn(grad, 0),
                              g._slice_row_fn(hess, 0), np.int32(0))
    gq = np.asarray(gq)
    grad0 = np.asarray(grad)[0]
    gscale = np.abs(grad0).max() / 2
    k = gq / gscale
    np.testing.assert_allclose(k, np.round(k), atol=1e-4)
    assert np.abs(k).max() <= 2 + 1e-6
    hq = np.asarray(hq)
    hscale = np.abs(np.asarray(hess)[0]).max() / 4
    kh = hq / hscale
    np.testing.assert_allclose(kh, np.round(kh), atol=1e-4)
    assert kh.min() >= -1e-6


def test_quantized_deterministic_rounding_mode():
    """stochastic_rounding=False uses round-half-away deterministically:
    identical runs give identical models."""
    X, y = _binary_problem(n=1500)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "use_quantized_grad": True, "stochastic_rounding": False}
    from lightgbm_tpu.boosting.model_io import save_model_to_string
    b1 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    b2 = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=5)
    assert (save_model_to_string(b1._gbdt)
            == save_model_to_string(b2._gbdt))


def test_quantized_constant_hessian_is_exact_ones():
    """Constant-hessian objectives keep hess == 1 (hscale = max|h|,
    int hess = 1; gradient_discretizer.cpp:128)."""
    rng = np.random.RandomState(5)
    X = rng.randn(1000, 4)
    y = X[:, 0] + 0.1 * rng.randn(1000)
    booster = lgb.train({"objective": "regression", "num_leaves": 7,
                         "verbosity": -1, "use_quantized_grad": True},
                        lgb.Dataset(X, label=y), num_boost_round=1)
    g = booster._gbdt
    grad, hess = g._grad_fn(g.scores)
    _, hq, _ = g._discretize_fn(g._slice_row_fn(grad, 0),
                             g._slice_row_fn(hess, 0), np.int32(0))
    np.testing.assert_allclose(np.asarray(hq), 1.0, rtol=1e-6)
