"""Reference-parity harness: train real LightGBM (CLI oracle built by
tools/build_reference_oracle.sh) and lightgbm_tpu on identical data with
identical params, then compare models and predictions
(ref test pattern: tests/python_package_test/test_consistency.py:1-143).

Skipped when the oracle binary is absent (env LIGHTGBM_ORACLE overrides
the default /tmp/lgb_ref_src/lightgbm path).
"""

import os
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb

ORACLE = os.environ.get("LIGHTGBM_ORACLE", "/tmp/lgb_ref_src/lightgbm")
DATA = "/root/reference/examples/binary_classification/binary.train"
TEST = "/root/reference/examples/binary_classification/binary.test"

pytestmark = pytest.mark.skipif(
    not os.path.exists(ORACLE),
    reason="reference oracle not built (run tools/build_reference_oracle.sh)")

PARAMS = dict(objective="binary", num_leaves=15, learning_rate=0.1,
              num_iterations=10, min_data_in_leaf=20, max_bin=255,
              deterministic=True, force_row_wise=True, verbosity=-1,
              feature_fraction=1.0, bagging_fraction=1.0)


def _oracle_predict(tmp_path, model, data_file, tag="pred"):
    """Run the oracle CLI predictor and return its output."""
    pred_conf = tmp_path / f"{tag}.conf"
    pred_out = tmp_path / f"{tag}_out.txt"
    pred_conf.write_text(
        f"task = predict\ndata = {data_file}\ninput_model = {model}\n"
        f"output_result = {pred_out}\nverbosity = -1\n")
    r = subprocess.run([ORACLE, f"config={pred_conf}"], capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    return np.loadtxt(pred_out)


def _run_oracle(tmp_path, extra=""):
    conf = tmp_path / "train.conf"
    model = tmp_path / "model.txt"
    conf.write_text(
        f"task = train\ndata = {DATA}\noutput_model = {model}\n"
        + "".join(f"{k} = {v}\n" for k, v in PARAMS.items()) + extra)
    r = subprocess.run([ORACLE, f"config={conf}"], capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    return model, _oracle_predict(tmp_path, model, TEST)


@pytest.fixture(scope="module")
def oracle_run(tmp_path_factory):
    return _run_oracle(tmp_path_factory.mktemp("oracle"))


def test_loads_real_reference_model_and_matches_predictions(oracle_run):
    """Our Booster must parse a model file written by REAL LightGBM and
    reproduce its predictions (model-format interop, both directions of
    the v4 text format)."""
    model, ref_pred = oracle_run
    booster = lgb.Booster(model_file=str(model))
    X = np.loadtxt(TEST)[:, 1:]
    ours = booster.predict(X)
    np.testing.assert_allclose(ours, ref_pred, rtol=1e-5, atol=1e-7)


def test_training_parity_same_data_same_params(oracle_run):
    """Training on the same file with the same params must produce a model
    of near-identical quality and highly correlated predictions.  (Exact
    tree equality needs bit-identical histogram accumulation; quality
    parity is what test_consistency.py-style runs assert.)"""
    _, ref_pred = oracle_run
    train = np.loadtxt(DATA)
    test = np.loadtxt(TEST)
    params = dict(PARAMS)
    params.pop("num_iterations")
    booster = lgb.train(params, lgb.Dataset(train[:, 1:],
                                            label=train[:, 0]),
                        num_boost_round=10)
    ours = booster.predict(test[:, 1:])

    def auc(y, s):
        order = np.argsort(s)
        ranks = np.empty(len(y))
        ranks[order] = np.arange(len(y))
        pos = y > 0
        return ((ranks[pos].sum() - pos.sum() * (pos.sum() - 1) / 2)
                / (pos.sum() * (~pos).sum()))

    y = test[:, 0]
    auc_ref = auc(y, ref_pred)
    auc_ours = auc(y, ours)
    assert abs(auc_ref - auc_ours) < 0.01, (auc_ref, auc_ours)
    corr = np.corrcoef(ref_pred, ours)[0, 1]
    assert corr > 0.97, corr


@pytest.mark.parametrize("example,objective,extra", [
    ("regression", "regression", ""),
    ("multiclass_classification", "multiclass", "num_class = 5\n"),
    ("lambdarank", "lambdarank", ""),
    ("xendcg", "rank_xendcg", ""),
])
def test_model_interop_all_objectives(tmp_path, example, objective, extra):
    """Every example family: a model trained by REAL LightGBM loads in
    our Booster and reproduces the oracle's own predictions."""
    ex = f"/root/reference/examples/{example}"
    data = next(p for p in (f"{ex}/{example.split('_')[0]}.train",
                            f"{ex}/rank.train")
                if os.path.exists(p))
    test_file = data.replace(".train", ".test")
    conf = tmp_path / "train.conf"
    model = tmp_path / "model.txt"
    conf.write_text(
        f"task = train\ndata = {data}\noutput_model = {model}\n"
        f"objective = {objective}\nnum_iterations = 8\nnum_leaves = 15\n"
        f"min_data_in_leaf = 20\ndeterministic = true\n"
        f"force_row_wise = true\nverbosity = -1\n" + extra)
    r = subprocess.run([ORACLE, f"config={conf}"], capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    ref_pred = _oracle_predict(tmp_path, model, test_file)

    booster = lgb.Booster(model_file=str(model))
    from lightgbm_tpu.io.parser import parse_file
    X, _, _ = parse_file(test_file, has_header=False, label_column="0")
    ours = booster.predict(X)
    np.testing.assert_allclose(ours, ref_pred, rtol=1e-4, atol=1e-6)


def test_first_tree_root_split_matches(oracle_run):
    """With identical GreedyFindBin binning, the first tree's root split
    (feature, threshold) must match the reference exactly."""
    model, _ = oracle_run
    ref_booster = lgb.Booster(model_file=str(model))
    ref_tree = ref_booster._gbdt.models_[0]

    train = np.loadtxt(DATA)
    params = dict(PARAMS)
    params.pop("num_iterations")
    ours = lgb.train(params, lgb.Dataset(train[:, 1:], label=train[:, 0]),
                     num_boost_round=1)
    ours._gbdt._sync_model()
    our_tree = ours._gbdt.models_[0]
    assert our_tree.split_feature[0] == ref_tree.split_feature[0]
    np.testing.assert_allclose(our_tree.threshold[0], ref_tree.threshold[0],
                               rtol=1e-10)


@pytest.mark.parametrize("objective,extra_params", [
    ("binary", {}),
    ("regression", {}),
    ("multiclass", {"num_class": 3}),
])
def test_reverse_interop_reference_reads_our_models(tmp_path, objective,
                                                    extra_params):
    """The OTHER direction: a model trained and saved by lightgbm_tpu must
    load in REAL LightGBM and reproduce our predictions through its CLI
    predictor (the v4 text format is a two-way contract; ref:
    gbdt_model_text.cpp LoadModelFromString)."""
    rng = np.random.RandomState(5)
    n, F = 1200, 6
    X = rng.rand(n, F)
    if objective == "multiclass":
        y = (X[:, 0] * 3).astype(int).clip(0, 2).astype(float)
    elif objective == "binary":
        y = (X[:, 0] + 0.3 * X[:, 1] > 0.6).astype(float)
    else:
        y = X[:, 0] + 0.5 * X[:, 1] + 0.05 * rng.randn(n)
    params = {"objective": objective, "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 10, **extra_params}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=6)
    ours = b.predict(X)
    model = tmp_path / "ours.txt"
    b.save_model(str(model))

    # the oracle predicts from a TSV data file (label col 0)
    data_file = tmp_path / "data.tsv"
    np.savetxt(data_file, np.column_stack([y, X]), delimiter="\t")
    ref_pred = _oracle_predict(tmp_path, model, data_file)
    np.testing.assert_allclose(ref_pred.reshape(ours.shape), ours,
                               rtol=1e-5, atol=1e-6)


def test_first_tree_structural_parity_with_oracle(tmp_path):
    """VERDICT r3 item 8: structural comparison of the first trees
    against the oracle under deterministic settings.

    Measured divergence analysis (round 4, on-chip + CPU):
      * tree 0's split features, internal counts, and leaf counts match
        the oracle split-for-split at this config; real-valued
        thresholds are the same doubles (the texts differ only in C++
        %.17g vs Python repr shortest-roundtrip formatting);
      * later trees eventually flip a NEAR-TIE split: our gain scan and
        leaf sums are fp32 (reference: double), so gains agree to
        ~2e-5 relative with gpu_use_dp=true (fp32 3-pass histograms)
        and ~1e-3 with the default bf16 operands; splits whose gain gap
        is below that noise floor are coin flips (first observed flip:
        default tree 1 split 22, gpu_use_dp tree 0 split 24 — gap
        |dgain|/gain ~ 4e-3 and ~1e-5 respectively).  Closing it fully
        needs double histograms + scan, which TPUs only emulate.
    This test pins the tree-0 guarantee."""
    model, _ = _run_oracle(tmp_path)

    ds = lgb.Dataset(DATA, params={"label_column": "0"})
    b = lgb.train({**{k: v for k, v in PARAMS.items()
                      if k != "num_iterations"},
                   "tpu_growth_strategy": "leafwise"},
                  ds, num_boost_round=1)
    ours = tmp_path / "ours.txt"
    b.save_model(str(ours))

    def tree0(path):
        cur = None
        out = {}
        for line in open(path):
            line = line.strip()
            if line.startswith("Tree=1"):
                break
            if line.startswith("Tree=0"):
                cur = out
            elif cur is not None and "=" in line:
                k, v = line.split("=", 1)
                out[k] = v
        return out

    rt, ot = tree0(str(model)), tree0(str(ours))
    assert rt["split_feature"] == ot["split_feature"]
    assert rt["internal_count"] == ot["internal_count"]
    assert rt["leaf_count"] == ot["leaf_count"]
    assert rt["left_child"] == ot["left_child"]
    assert rt["right_child"] == ot["right_child"]
    # thresholds: identical doubles, formatting-independent comparison
    np.testing.assert_array_equal(
        np.array([float(x) for x in rt["threshold"].split()]),
        np.array([float(x) for x in ot["threshold"].split()]))


def test_trees_0_to_4_structural_parity_with_oracle(tmp_path):
    """VERDICT r4 item 10: structural comparison of the FIRST FIVE trees
    at 31 leaves under deterministic settings, with the exact divergence
    point written down and pinned.

    Ground truth about the divergence (measured here, enforced below):
    our histograms/gain scan are fp32 (gpu_use_dp's 3-pass variant keeps
    fp32 operands with exact accumulation; the oracle is double), so a
    split whose gain gap to the runner-up is below the fp32 noise floor
    is a coin flip.  Tree 0 matches split-for-split; each later tree
    must match UP TO its first sub-noise near-tie, at which point our
    chosen split's gain must agree with the oracle's chosen split's
    gain to ~1e-3 relative — i.e. every divergence is a measured
    near-tie, never a different split decision."""
    conf = tmp_path / "t5.conf"
    model = tmp_path / "t5_model.txt"
    p5 = {**PARAMS, "num_iterations": 5, "num_leaves": 31}
    conf.write_text(
        f"task = train\ndata = {DATA}\noutput_model = {model}\n"
        + "".join(f"{k} = {v}\n" for k, v in p5.items()))
    r = subprocess.run([ORACLE, f"config={conf}"], capture_output=True,
                       text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr

    ds = lgb.Dataset(DATA, params={"label_column": "0"})
    b = lgb.train({**{k: v for k, v in p5.items()
                      if k != "num_iterations"},
                   "tpu_growth_strategy": "leafwise",
                   "gpu_use_dp": True},
                  ds, num_boost_round=5)
    ours = tmp_path / "t5_ours.txt"
    b.save_model(str(ours))

    def trees_of(path):
        trees, cur = [], None
        for line in open(path):
            line = line.strip()
            if line.startswith("Tree="):
                cur = {}
                trees.append(cur)
            elif cur is not None and "=" in line:
                k, v = line.split("=", 1)
                cur[k] = v
        return trees

    rts, ots = trees_of(str(model))[:5], trees_of(str(ours))[:5]
    assert len(rts) == 5 and len(ots) == 5
    first_div = None
    for ti, (rt, ot) in enumerate(zip(rts, ots)):
        rf = rt["split_feature"].split()
        of = ot["split_feature"].split()
        rthr = [float(x) for x in rt["threshold"].split()]
        othr = [float(x) for x in ot["threshold"].split()]
        rg = [float(x) for x in rt["split_gain"].split()]
        og = [float(x) for x in ot["split_gain"].split()]
        n = min(len(rf), len(of))
        div = next((s for s in range(n)
                    if rf[s] != of[s] or rthr[s] != othr[s]), None)
        if div is None:
            # full structural match for this tree
            assert rt["internal_count"] == ot["internal_count"], ti
            assert rt["leaf_count"] == ot["leaf_count"], ti
            continue
        first_div = (ti, div)
        # the divergent split must be a measured near-tie: both engines'
        # chosen splits carry (to fp32 noise) the same gain
        rel = abs(og[div] - rg[div]) / max(abs(rg[div]), 1e-12)
        assert rel < 2e-3, (ti, div, rg[div], og[div], rel)
        break   # after a flip the residuals differ; later trees are
        # grown on different scores and are not split-comparable
    # THE EXACT DIVERGENCE POINT (measured and pinned): tree 0 matches
    # the oracle split-for-split through split 23 and flips at split 24,
    # a sub-noise near-tie — the same split index the round-4 analysis
    # recorded for gpu_use_dp.  (At 15 leaves tree 0 is exact: the
    # test above.)  If the engines ever match further, relax this pin
    # forward, never backward.
    assert first_div is not None and first_div[0] == 0 \
        and first_div[1] >= 24, first_div
