"""Booster.refit interplay with serving and continued training
(ISSUE 15 satellite): refit mutates leaf values IN PLACE, so it must
bump the model-mutation counter — the same slice-keyed cache hazard the
PR-10 DART fix closed — or device/native packs keep serving the stale
leaves.  Plus refit -> checkpoint -> resume byte-exactness: the online
loop's cheap-update path has to round-trip through the checkpoint
machinery exactly."""

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.reliability import CheckpointManager

_PARAMS = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
           "min_data_in_leaf": 5, "device_predict": "true",
           "device_predict_min_bucket": 32}


def _mk(n, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 5).astype(np.float32)
    y = (X[:, 0] * 2 + X[:, 1] + 0.1 * rng.randn(n)).astype(np.float32)
    return X, y


def _train(rounds=6, seed=0):
    X, y = _mk(400, seed=seed)
    bst = lgb.train(dict(_PARAMS), lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    bst._gbdt._sync_model()
    return bst, X


def _host_predict(bst, X):
    g = bst._gbdt
    prev = g.config.device_predict
    g.config.device_predict = "false"
    try:
        return bst.predict(np.asarray(X, np.float64))
    finally:
        g.config.device_predict = prev


def _fresh_device_oracle(bst):
    """A cache-free booster built from the live model's text with the
    device path forced: whatever IT predicts is what a correctly
    invalidated cache must also predict, byte-for-byte (same float32
    traversal, same packed layout)."""
    b = lgb.Booster(model_str=bst.model_to_string(num_iteration=-1))
    b._gbdt.config.device_predict = "true"
    return b


def test_refit_bumps_mutations_and_device_pack_repacks():
    """Device predict must serve the refit leaves IMMEDIATELY after an
    in-place refit: the slice-keyed device pack is only invalidated by
    the mutation counter refit bumps (the PR-10 hazard).  The oracle is
    a cache-free booster rebuilt from the refit model's text — stale
    packs cannot match it byte-for-byte."""
    bst, X = _train()
    g = bst._gbdt
    before_dev = bst.predict(X)            # device path (float32 input)
    m0 = getattr(g, "_model_mutations", 0)
    X2, y2 = _mk(400, seed=7)
    bst.refit(X2, y2)
    assert getattr(g, "_model_mutations", 0) == m0 + 1
    after_dev = bst.predict(X)             # must repack, not reuse
    assert not np.array_equal(after_dev, before_dev)  # leaves moved
    assert np.array_equal(after_dev, _fresh_device_oracle(bst).predict(X))
    # and the device result agrees with the float64 host traversal to
    # float32 rounding (the two paths differ only in accumulator width)
    assert np.allclose(np.asarray(after_dev, np.float64),
                       _host_predict(bst, X), rtol=1e-5, atol=1e-5)


def test_refit_invalidates_single_row_fast_cache():
    bst, X = _train()
    row = X[:1]
    before = np.asarray(bst.predict(row))
    # populate the single-row fast cache, then refit in place
    _ = bst._single_row_fast_for(X.shape[1], 0, -1, False)
    bst.refit(*_mk(400, seed=9))
    after = np.asarray(bst.predict(row))
    assert not np.array_equal(after, before)
    assert np.array_equal(after,
                          np.asarray(_fresh_device_oracle(bst)
                                     .predict(row)))


def test_refit_checkpoint_resume_byte_exact(tmp_path):
    """refit -> checkpoint -> reload must reproduce the refit model's
    trees byte-for-byte, and CONTINUED TRAINING from the reloaded model
    must equal continued training from the live refit booster — the
    exact interplay the online loop's refit+boost mix exercises."""
    bst, X = _train()
    bst.refit(*_mk(400, seed=11))
    mgr = CheckpointManager(str(tmp_path), params=dict(_PARAMS))
    ck = mgr.save(bst, 1)
    reloaded = lgb.Booster(model_file=ck.model_path)
    live_txt = bst.model_to_string(num_iteration=-1)
    assert _trees_of(reloaded.model_to_string(num_iteration=-1)) \
        == _trees_of(live_txt)
    # verified resumable: digests intact, params hash matches
    ck2 = mgr.resumable(dict(_PARAMS))
    assert ck2 is not None and ck2.iteration == 1
    # continued training: live refit booster vs checkpoint round trip
    Xc, yc = _mk(400, seed=12)
    cont_live = lgb.train(dict(_PARAMS), lgb.Dataset(Xc, label=yc),
                          num_boost_round=2, init_model=bst)
    cont_ck = lgb.train(dict(_PARAMS), lgb.Dataset(Xc, label=yc),
                        num_boost_round=2, init_model=ck.model_path)
    assert _trees_of(cont_live.model_to_string()) \
        == _trees_of(cont_ck.model_to_string())
    # and the continued models serve identically on the device path
    assert np.array_equal(cont_live.predict(X), cont_ck.predict(X))


def _trees_of(model_txt: str) -> str:
    """The tree section of a model text (everything before the embedded
    `parameters:` block, which a load/serialize round trip may
    normalize — the trees are the byte-exactness contract)."""
    return model_txt.split("\nparameters:", 1)[0]
