"""Reliability subsystem (ISSUE 1): checkpoint/resume parity, atomic
model writes, fault injection, and the non-finite sentinel.  All
tier-1-safe: single process, JAX_PLATFORMS=cpu (conftest)."""

import json
import os
import shutil

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.reliability import CheckpointManager, NonFiniteError, faults
from lightgbm_tpu.reliability.checkpoint import hash_params
from lightgbm_tpu.utils.log import LightGBMError


def _data(seed=7, n=800, F=5):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, F)
    y = X[:, 0] * 2 + X[:, 1] ** 2 + 0.1 * rng.randn(n)
    return X, y


PARAMS = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
          "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _clean_fault_env(monkeypatch):
    """Each test starts with no injected faults and leaves none behind."""
    monkeypatch.delenv("LGBM_TPU_FAULT", raising=False)
    faults.reload()
    yield
    faults.reload()


# --------------------------------------------------- checkpoint/resume
def test_checkpoint_resume_byte_parity(tmp_path):
    """The acceptance criterion: interrupt at iteration k, resume, and
    the final model text is byte-for-byte identical to an uninterrupted
    run (exact score-buffer restore, not predict-based reseeding)."""
    X, y = _data()
    full = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                     num_boost_round=10)
    full_txt = full.model_to_string(num_iteration=-1)

    ck = str(tmp_path / "ck")
    # "interrupted" run: stops after 6 of the 10 rounds
    lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=6,
              checkpoint_dir=ck, checkpoint_freq=3)
    resumed = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y),
                        num_boost_round=10, checkpoint_dir=ck,
                        checkpoint_freq=3)
    assert resumed.model_to_string(num_iteration=-1) == full_txt


def test_checkpoint_resume_byte_parity_with_bagging(tmp_path):
    """Bagging draws must continue the interrupted run's RNG stream
    (checkpointed), not replay from the seed."""
    X, y = _data()
    p = dict(PARAMS, bagging_freq=2, bagging_fraction=0.7)
    full = lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=10)
    ck = str(tmp_path / "ck")
    lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=5,
              checkpoint_dir=ck, checkpoint_freq=5)
    resumed = lgb.train(dict(p), lgb.Dataset(X, label=y),
                        num_boost_round=10, checkpoint_dir=ck,
                        checkpoint_freq=5)
    assert resumed.model_to_string(num_iteration=-1) \
        == full.model_to_string(num_iteration=-1)


def test_checkpoint_rotation_and_manifest(tmp_path):
    X, y = _data()
    ck = str(tmp_path / "ck")
    lgb.train(dict(PARAMS, checkpoint_keep=2), lgb.Dataset(X, label=y),
              num_boost_round=9, checkpoint_dir=ck, checkpoint_freq=2)
    models = sorted(f for f in os.listdir(ck) if f.endswith(".txt"))
    # saves at 2,4,6,8 and the final iteration 9; keep_last=2 -> 8, 9
    assert models == ["ckpt_0000008.txt", "ckpt_0000009.txt"]
    with open(os.path.join(ck, "manifest.json")) as f:
        m = json.load(f)
    assert m["iteration"] == 9
    mgr = CheckpointManager(ck)
    ckpt = mgr.latest()
    assert ckpt.iteration == 9
    assert os.path.exists(ckpt.model_path)
    assert ckpt.load_state() is not None


def test_resume_ignores_mismatched_params(tmp_path):
    """A checkpoint from a different config must not be resumed into
    this run (params-hash gate)."""
    X, y = _data()
    ck = str(tmp_path / "ck")
    lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=3,
              checkpoint_dir=ck, checkpoint_freq=1)
    b = lgb.train(dict(PARAMS, num_leaves=15), lgb.Dataset(X, label=y),
                  num_boost_round=3, checkpoint_dir=ck, checkpoint_freq=1)
    assert b.num_trees() == 3  # trained from scratch, not 3 + 3
    # volatile knobs (verbosity, output paths) must NOT change the hash
    assert hash_params(dict(PARAMS)) == \
        hash_params(dict(PARAMS, verbosity=2, output_model="x.txt"))
    assert hash_params(dict(PARAMS)) != hash_params(dict(PARAMS, num_leaves=15))


def test_resume_false_starts_over(tmp_path):
    X, y = _data()
    ck = str(tmp_path / "ck")
    lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=4,
              checkpoint_dir=ck, checkpoint_freq=2)
    b = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=3,
                  checkpoint_dir=ck, checkpoint_freq=2, resume=False)
    assert b.num_trees() == 3


def test_resume_past_target_returns_checkpoint_model(tmp_path):
    """Resuming with num_boost_round <= checkpoint iteration trains no
    further trees."""
    X, y = _data()
    ck = str(tmp_path / "ck")
    lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=6,
              checkpoint_dir=ck, checkpoint_freq=2)
    b = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=4,
                  checkpoint_dir=ck, checkpoint_freq=2)
    assert b.num_trees() == 6


# ----------------------------------------------------- atomic writes
def test_save_model_atomic_on_replace_failure(tmp_path, monkeypatch):
    """A failed save must leave the previous model file intact and no
    temp litter (temp sibling + os.replace)."""
    X, y = _data(n=300)
    b = lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=3)
    path = str(tmp_path / "model.txt")
    b.save_model(path)
    original = open(path).read()

    def _boom(src, dst):
        raise OSError("simulated crash at publish")
    monkeypatch.setattr(os, "replace", _boom)
    with pytest.raises(OSError):
        b.save_model(path, num_iteration=1)
    monkeypatch.undo()
    assert open(path).read() == original
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []


def test_cvbooster_save_model_atomic(tmp_path, monkeypatch):
    X, y = _data(n=400)
    res = lgb.cv(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=2,
                 nfold=2, return_cvbooster=True)
    cvb = res["cvbooster"]
    path = str(tmp_path / "cv.json")
    cvb.save_model(path)
    original = open(path).read()

    def _boom(src, dst):
        raise OSError("simulated crash at publish")
    monkeypatch.setattr(os, "replace", _boom)
    with pytest.raises(OSError):
        cvb.save_model(path, num_iteration=1)
    monkeypatch.undo()
    assert open(path).read() == original


def test_ckpt_write_fail_injection_keeps_training_and_old_ckpt(
        tmp_path, monkeypatch):
    """An injected checkpoint-write failure warns and training continues;
    the previous checkpoint stays the resumable one until the next good
    write."""
    X, y = _data()
    monkeypatch.setenv("LGBM_TPU_FAULT", "ckpt_write_fail@2")
    faults.reload()
    ck = str(tmp_path / "ck")
    b = lgb.train(dict(PARAMS, verbosity=-1), lgb.Dataset(X, label=y),
                  num_boost_round=4, checkpoint_dir=ck, checkpoint_freq=1)
    assert b.num_trees() == 4  # the failed write did not kill the run
    assert CheckpointManager(ck).latest().iteration == 4
    # iteration 2's checkpoint is the one that failed
    assert not os.path.exists(os.path.join(ck, "ckpt_0000002.txt"))


# ------------------------------------------------ non-finite sentinel
def test_nan_grad_sentinel_raises_actionable_error(monkeypatch):
    X, y = _data()
    monkeypatch.setenv("LGBM_TPU_FAULT", "nan_grad@2")
    faults.reload()
    with pytest.raises(LightGBMError, match="[Nn]on-finite"):
        lgb.train(dict(PARAMS, nonfinite_check_freq=1),
                  lgb.Dataset(X, label=y), num_boost_round=5)


def test_nan_grad_rolls_back_to_checkpoint(tmp_path, monkeypatch):
    """With a checkpoint available the sentinel rolls back and retries;
    the injected fault is one-shot, so the rerun matches a clean run
    byte-for-byte."""
    X, y = _data()
    p = dict(PARAMS, nonfinite_check_freq=1)
    clean = lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=6)
    monkeypatch.setenv("LGBM_TPU_FAULT", "nan_grad@3")
    faults.reload()
    ck = str(tmp_path / "ck")
    b = lgb.train(dict(p), lgb.Dataset(X, label=y), num_boost_round=6,
                  checkpoint_dir=ck, checkpoint_freq=1)
    assert b.num_trees() == 6
    assert b.model_to_string(num_iteration=-1) \
        == clean.model_to_string(num_iteration=-1)


def test_custom_fobj_nan_gradients_rejected():
    X, y = _data(n=300)

    def bad_fobj(score, ds):
        g = score - y
        g[10] = np.nan
        return g, np.ones_like(g)

    with pytest.raises(NonFiniteError, match="objective"):
        lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=3,
                  fobj=bad_fobj)


# -------------------------------------------------- callback hygiene
def test_early_stopping_warns_once_without_valid_set():
    """The 'requires at least one validation set' warning fired every
    iteration; now it warns once and disables itself."""
    X, y = _data(n=300)
    msgs = []
    lgb.register_callback(msgs.append)
    try:
        lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": 0, "metric": "none"},
                  lgb.Dataset(X, label=y), num_boost_round=5,
                  callbacks=[lgb.early_stopping(2)])
    finally:
        lgb.register_callback(None)
    warn = [m for m in msgs if "Early stopping requires" in m]
    assert len(warn) == 1, msgs


def test_fault_spec_parsing(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_FAULT",
                       "worker_crash@3,nan_grad@5@1,bogus@1,nan_grad@x")
    faults.reload()
    assert faults.active()
    # malformed specs are dropped with a warning, valid ones kept
    assert faults._parse() == [("worker_crash", 3, 0), ("nan_grad", 5, 1)]
    # attempt gating: nan_grad@5@1 only fires on attempt 1
    monkeypatch.setenv("LGBM_TPU_FAULT_ATTEMPT", "0")
    g, h = np.ones(4), np.ones(4)
    g2, _ = faults.maybe_nan_grad(g, h, 5)
    assert np.all(np.isfinite(g2))
    monkeypatch.setenv("LGBM_TPU_FAULT_ATTEMPT", "1")
    faults.reload()
    g2, _ = faults.maybe_nan_grad(g, h, 5)
    assert np.all(np.isnan(g2))
    # one-shot: the spec does not fire twice
    g3, _ = faults.maybe_nan_grad(g, h, 5)
    assert np.all(np.isfinite(g3))


def test_cli_checkpoint_resume_flags(tmp_path):
    """task=train checkpoint_dir=/resume= flags: a re-run of the same
    command continues from the newest checkpoint and reproduces an
    uninterrupted run's trees."""
    from lightgbm_tpu.cli import main
    X, y = _data(n=400)
    data = str(tmp_path / "train.tsv")
    np.savetxt(data, np.column_stack([y, X]), delimiter="\t", fmt="%.8g")
    common = [f"data={data}", "objective=regression", "num_leaves=7",
              "min_data_in_leaf=5", "verbosity=-1"]
    clean_out = str(tmp_path / "clean.txt")
    assert main(common + ["num_trees=6", f"output_model={clean_out}"]) == 0

    ck = str(tmp_path / "ck")
    out = str(tmp_path / "model.txt")
    # "interrupted" run stops at 4 rounds, checkpointing every 2
    assert main(common + ["num_trees=4", f"checkpoint_dir={ck}",
                          "checkpoint_freq=2",
                          f"output_model={out}"]) == 0
    # re-run to the full 6 rounds: resumes from iteration 4
    assert main(common + ["num_trees=6", f"checkpoint_dir={ck}",
                          "checkpoint_freq=2",
                          f"output_model={out}"]) == 0

    def trees(path):
        return open(path).read().split("\nparameters:")[0]
    assert trees(out) == trees(clean_out)

    # resume=false starts from scratch (4 trees, not 6+)
    out2 = str(tmp_path / "model2.txt")
    assert main(common + ["num_trees=4", f"checkpoint_dir={ck}",
                          "checkpoint_freq=2", "resume=false",
                          f"output_model={out2}"]) == 0
    b = lgb.Booster(model_file=out2)
    assert b.num_trees() == 4


def test_bench_backend_fallback(monkeypatch):
    """bench.py must not die with rc=1 when the configured JAX backend
    cannot initialize (BENCH_r05.json: RuntimeError: Unable to
    initialize backend 'axon'); it probes in a subprocess and falls
    back to CPU."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    monkeypatch.setenv("JAX_PLATFORMS", "bogus_backend")
    assert bench._ensure_jax_backend(probe_timeout=120) is True
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    # with a working backend config the probe takes no fallback
    assert bench._ensure_jax_backend(probe_timeout=120) is False


def test_manifest_fallback_scan(tmp_path):
    """A damaged manifest falls back to scanning ckpt_*.txt."""
    X, y = _data()
    ck = str(tmp_path / "ck")
    lgb.train(dict(PARAMS), lgb.Dataset(X, label=y), num_boost_round=4,
              checkpoint_dir=ck, checkpoint_freq=2)
    with open(os.path.join(ck, "manifest.json"), "w") as f:
        f.write("{truncated")
    ckpt = CheckpointManager(ck).latest()
    assert ckpt is not None and ckpt.iteration == 4
