"""Serving daemon suite (docs/Serving.md): registry hot swap, request
coalescing, byte-exactness vs Booster.predict, drain semantics.

The byte-identity oracle is `Booster.predict` with the device path
forced (device_predict=true): the daemon packs the same trees through
the same jitted traversal, so responses must match BIT-FOR-BIT — any
relative-tolerance pass here would hide a cross-wired coalescer split
or a torn hot swap, the two bug classes this suite exists to catch.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.serving import (ServingClient, ServingDaemon,
                                  serve_counters_reset, start_frontend)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mk_xy(n, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 6).astype(np.float32)
    X[rng.rand(n) < 0.1, 0] = np.nan
    y = ((np.nan_to_num(X[:, 0]) + X[:, 1] > 0)).astype(np.float32)
    return X, y


_PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
           "metric": "none", "min_data_in_leaf": 5,
           "device_predict": "true", "device_predict_min_bucket": 32}


def _train(rounds=8, seed=0, **extra):
    X, y = _mk_xy(600, seed=seed)
    p = dict(_PARAMS)
    p.update(extra)
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=rounds)
    bst._gbdt._sync_model()
    return bst, X


def _daemon(**overrides):
    p = dict(_PARAMS, serve_max_batch_rows=256,
             serve_max_coalesce_wait_ms=1.0)
    p.update(overrides)
    serve_counters_reset()
    return ServingDaemon(Config(p)).start()


@pytest.fixture(scope="module")
def served():
    """One daemon + model + oracle booster shared by the read-only
    parity tests (hot-swap / drain tests build their own)."""
    bst, X = _train()
    d = _daemon()
    d.registry.register("m", booster=bst, block=True)
    yield d, bst, X
    d.stop(drain=True, timeout=10)


# ---------------------------------------------------------------- parity
def test_responses_byte_identical_to_booster_predict(served):
    d, bst, X = served
    c = ServingClient(d)
    for n in (1, 7, 32, 100):
        got = c.predict("m", X[:n])
        exp = bst.predict(X[:n])
        assert np.array_equal(got, exp)      # byte-identical, no tolerance
        raw = c.predict("m", X[:n], mode="raw")
        assert np.array_equal(raw, bst.predict(X[:n], raw_score=True))
        leaf = c.predict("m", X[:n], mode="leaf")
        assert np.array_equal(leaf, bst.predict(X[:n], pred_leaf=True))


def test_float64_lossless_served_lossy_rejected(served):
    d, bst, X = served
    X64 = np.asarray(X[:16], np.float64)          # lossless round trip
    assert np.array_equal(d.predict("m", X64), bst.predict(X[:16]))
    bad = X64 + 1e-12                              # not f32-representable
    bad[np.isnan(bad)] = 0.0
    with pytest.raises(ValueError, match="losslessly"):
        d.predict("m", bad)


def test_multiclass_and_dtype_matrix():
    X, _ = _mk_xy(500, seed=3)
    y = np.random.RandomState(5).randint(0, 3, 500).astype(np.float32)
    p = dict(_PARAMS, objective="multiclass", num_class=3, num_leaves=8)
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=4)
    d = _daemon()
    try:
        d.registry.register("mc", booster=bst, block=True)
        got = d.predict("mc", X[:40])
        assert got.shape == (40, 3)
        assert np.array_equal(got, bst.predict(X[:40]))
        # integer rows are exactly representable -> served
        Xi = np.arange(12, dtype=np.int64).reshape(2, 6)
        assert np.array_equal(d.predict("mc", Xi),
                              bst.predict(Xi.astype(np.float32)))
    finally:
        d.stop()


def test_zero_new_traces_after_warmup(served):
    d, _, X = served
    base = d.registry.serve_recompiles()
    for n in (1, 3, 17, 33, 64, 128, 200, 256):
        d.predict("m", X[:n])
        d.predict("m", X[:n], mode="raw")
    assert d.registry.serve_recompiles() == base == 0


# ------------------------------------------------------------- coalescing
def test_coalescer_merges_concurrent_requests():
    bst, X = _train()
    d = _daemon(serve_max_coalesce_wait_ms=150.0)
    try:
        d.registry.register("m", booster=bst, block=True)
        before = d.stats()
        futs = []
        starts = [5 * i for i in range(8)]
        for s in starts:
            futs.append((s, d.submit("m", X[s:s + 3])))
        outs = [(s, f.result(timeout=30)) for s, f in futs]
        after = d.stats()
        # merged: 8 requests, ONE coalesced dispatch window
        assert after["serve_requests"] - before["serve_requests"] == 8
        assert after["serve_batches"] - before["serve_batches"] == 1
        # split back per request, no cross-wiring
        exp = bst.predict(X)
        for s, out in outs:
            assert np.array_equal(out, exp[s:s + 3])
    finally:
        d.stop()


def test_coalescer_wait_zero_dispatches_immediately():
    bst, X = _train()
    d = _daemon(serve_max_coalesce_wait_ms=0.0)
    try:
        d.registry.register("m", booster=bst, block=True)
        before = d.stats()["serve_batches"]
        for _ in range(4):
            d.predict("m", X[:2])      # sequential: nothing to merge
        assert d.stats()["serve_batches"] - before == 4
    finally:
        d.stop()


def test_coalescer_wait_bounds_latency():
    """A lone request must not wait out a large coalesce window many
    times over: the wait is ONE bounded window after the first pop."""
    bst, X = _train()
    d = _daemon(serve_max_coalesce_wait_ms=100.0)
    try:
        d.registry.register("m", booster=bst, block=True)
        d.predict("m", X[:2])          # warm the dispatch path
        t0 = time.monotonic()
        d.predict("m", X[:2], timeout=30)
        elapsed_ms = (time.monotonic() - t0) * 1000
        assert elapsed_ms < 1000.0, elapsed_ms
    finally:
        d.stop()


# ---------------------------------------------------------------- hot swap
def test_hot_swap_under_concurrent_load_never_tears():
    b1, X = _train(rounds=6, seed=1)
    b2, _ = _train(rounds=14, seed=1)
    pool = X[:256]
    exp = {1: b1.predict(pool), 2: b2.predict(pool)}
    assert not np.allclose(exp[1], exp[2])
    d = _daemon()
    try:
        h1 = d.registry.register("m", booster=b1, block=True)
        errors, mismatches, done = [], [], [0]
        lock = threading.Lock()

        def client(tid):
            r = np.random.RandomState(tid)
            for _ in range(40):
                s, n = int(r.randint(0, 250)), int(r.randint(1, 6))
                try:
                    fut = d.submit("m", pool[s:s + n])
                    out = fut.result(timeout=30)
                    # response matches EXACTLY the version that served
                    # it — old or new, never a mix, never garbage
                    if not np.array_equal(out, exp[fut.version][s:s + n]):
                        with lock:
                            mismatches.append((fut.version, s, n))
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))
                with lock:
                    done[0] += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.02)
        h2 = d.registry.register("m", booster=b2, block=False)  # mid-load
        for t in threads:
            t.join(timeout=120)
        h2.wait(timeout=60)
        assert done[0] == 240 and not errors and not mismatches
        assert h2.entry.version == 2
        # new traffic serves v2; retired v1 freed once idle
        fut = d.submit("m", pool[:4])
        assert fut.result(timeout=30) is not None and fut.version == 2
        deadline = time.monotonic() + 10
        while not h1.entry.released and time.monotonic() < deadline:
            time.sleep(0.02)
        assert h1.entry.released and h1.entry.in_flight == 0
        assert d.registry.serve_recompiles() == 0
    finally:
        d.stop()


def test_failed_load_keeps_old_version_serving():
    bst, X = _train()
    d = _daemon()
    try:
        d.registry.register("m", booster=bst, block=True)
        h = d.registry.register("m", model_file="/nonexistent/model.txt")
        with pytest.raises(RuntimeError, match="failed to load"):
            h.wait(timeout=30)
        assert h.error is not None
        # old version unaffected
        assert np.array_equal(d.predict("m", X[:8]), bst.predict(X[:8]))
        assert d.registry.stats()["models"]["m"]["version"] == 1
    finally:
        d.stop()


def test_register_rejects_linear_trees():
    rng = np.random.RandomState(2)
    X = rng.rand(400, 4)
    y = (X @ rng.rand(4)).astype(np.float64)
    bst = lgb.train({"objective": "regression", "linear_tree": True,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    d = _daemon()
    try:
        h = d.registry.register("lin", booster=bst)
        with pytest.raises(RuntimeError, match="device-servable"):
            h.wait(timeout=30)
    finally:
        d.stop()


# ---------------------------------------------------------------- rejects
def test_unknown_model_and_feature_mismatch_rejected(served):
    d, _, X = served
    with pytest.raises(KeyError, match="No model"):
        d.predict("nope", X[:2])
    with pytest.raises(ValueError, match="features"):
        d.predict("m", X[:2, :4])     # width mismatch would re-trace
    with pytest.raises(ValueError, match="mode"):
        d.predict("m", X[:2], mode="bogus")
    assert d.registry.serve_recompiles() == 0


# ------------------------------------------------------------- early stop
def test_early_stop_serving_matches_booster():
    bst, X = _train(rounds=20)
    d = _daemon(pred_early_stop=True, pred_early_stop_freq=3,
                pred_early_stop_margin=0.5)
    try:
        d.registry.register("m", booster=bst, block=True)
        got = d.predict("m", X[:64], mode="raw")
        exp = bst.predict(X[:64], raw_score=True, pred_early_stop=True,
                          pred_early_stop_freq=3,
                          pred_early_stop_margin=0.5)
        assert np.array_equal(got, exp)
        # early stopping actually engaged (differs from the full sum)
        assert not np.allclose(got, bst.predict(X[:64], raw_score=True))
        assert d.registry.serve_recompiles() == 0
    finally:
        d.stop()


# ------------------------------------------------------------------- DART
def test_dart_mid_training_model_serves_current_drop_state():
    X, y = _mk_xy(600, seed=4)
    p = dict(_PARAMS, boosting="dart", drop_rate=0.9, skip_drop=0.0,
             learning_rate=0.3)
    bst = lgb.train(p, lgb.Dataset(X, label=y), num_boost_round=6)
    g = bst._gbdt
    g._sync_model()
    d = _daemon()
    try:
        d.registry.register("dart", booster=bst, block=True)
        assert np.array_equal(d.predict("dart", X[:32]),
                              bst.predict(X[:32]))
        # mutate drop state in place (what the next train iteration
        # does): a re-register must repack the CURRENT weights
        g.pre_gradient_hook()
        assert g.drop_index_, "drop did not trigger; bump drop_rate"
        d.registry.register("dart", booster=bst, block=True)
        got = d.predict("dart", X[:32])
        assert np.array_equal(got, bst.predict(X[:32]))
    finally:
        d.stop()


# ------------------------------------------------------------------ stats
def test_stats_and_latency_window(served):
    d, _, X = served
    d.predict("m", X[:8])
    s = d.stats()
    assert s["serve_requests"] >= 1 and s["serve_errors"] == 0
    assert s["serve_p50_ms"] is not None and s["serve_p99_ms"] is not None
    assert s["serve_p50_ms"] <= s["serve_p99_ms"] or np.isclose(
        s["serve_p50_ms"], s["serve_p99_ms"])
    assert "m" in s["models"] and s["models"]["m"]["in_flight"] == 0


def test_roofline_stats_and_sampled_traces(served):
    """ISSUE 11: serving stats carry a measured dispatch-site roofline
    (warmup excluded) and sampled requests leave stage-waterfall traces
    in the flight recorder."""
    from lightgbm_tpu.observability.flightrec import flight_recorder
    d, _, X = served
    before = len(flight_recorder.trace_tail(256))
    # serve_trace_sample defaults to 64: push enough requests through
    # that at least one gets traced
    for i in range(70):
        d.predict("m", X[i % 16:(i % 16) + 4])
    rl = d.stats().get("roofline")
    assert rl is not None and rl["dispatches"] >= 70
    assert rl["measured_mfu"] is not None and rl["measured_mfu"] > 0
    assert rl["bound"] in ("compute", "hbm")
    assert rl["flops"] > 0 and rl["dispatch_s"] > 0
    traces = flight_recorder.trace_tail(256)
    assert len(traces) > before
    t = traces[-1]
    assert t["model"] == "m" and t["version"] >= 1
    # stage waterfall is monotone: enqueue(0) <= coalesce <= dispatch
    # <= settle <= respond
    stages = [t["coalesce_ms"], t["dispatch_ms"],
              t["device_settle_ms"], t["respond_ms"]]
    assert all(s is not None for s in stages)
    assert stages == sorted(stages) and stages[0] >= 0
    # the coalesce-batch histogram counted these dispatches
    assert sum(flight_recorder.contents()
               ["coalesce_batch_requests_hist"]) > 0


def test_metrics_port_http_and_op_metrics(served):
    """The daemon's two scrape surfaces: GET /metrics (fleet-facing)
    and op=metrics on the TCP wire — same Prometheus text."""
    import urllib.request

    from lightgbm_tpu.observability import start_metrics_http
    d, _, X = served
    d.predict("m", X[:4])
    srv = start_metrics_http(port=0, daemon=d)
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=30
        ).read().decode()
    finally:
        srv.shutdown()
    assert "# TYPE lgbm_serve_requests counter" in body
    assert 'lgbm_serve_latency_ms{quantile="0.99"}' in body
    assert 'lgbm_serve_model_version{model="m"} 1' in body
    assert "lgbm_serve_queue_pending" in body
    assert 'lgbm_serve_requests_by_model{model="m"}' in body
    fe = start_frontend(d, port=0)
    try:
        port = fe.server_address[1]
        with socket.create_connection(("127.0.0.1", port), timeout=30) as s:
            f = s.makefile("rwb")
            f.write(b'{"op": "metrics"}\n')
            f.flush()
            resp = json.loads(f.readline())
    finally:
        fe.shutdown()
    assert resp["ok"]
    assert "# TYPE lgbm_serve_requests counter" in resp["metrics"]


# --------------------------------------------------------------- frontend
def test_tcp_frontend_round_trip(served):
    d, bst, X = served
    srv = start_frontend(d, port=0)
    try:
        port = srv.server_address[1]
        with socket.create_connection(("127.0.0.1", port), timeout=30) as s:
            f = s.makefile("rwb")
            f.write((json.dumps(
                {"model": "m", "rows": X[:3].tolist()}) + "\n").encode())
            f.flush()
            resp = json.loads(f.readline())
            assert resp["ok"] and resp["version"] == 1
            np.testing.assert_allclose(resp["preds"], bst.predict(X[:3]),
                                       rtol=0, atol=0)
            f.write(b'{"op": "stats"}\n')
            f.flush()
            stats = json.loads(f.readline())
            assert stats["ok"] and "serve_requests" in stats["stats"]
            f.write(b'not json\n')
            f.flush()
            err = json.loads(f.readline())
            assert not err["ok"]
            f.write((json.dumps(
                {"model": "ghost", "rows": [[0.0] * 6]}) + "\n").encode())
            f.flush()
            assert not json.loads(f.readline())["ok"]
    finally:
        srv.shutdown()


# ----------------------------------------------------------------- SIGTERM
_SIGTERM_CHILD = r"""
import os, sys, threading, time
sys.path.insert(0, os.environ["SERVE_REPO"])
import jax
jax.config.update("jax_platforms", "cpu")  # axon plugin ignores the env
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.observability import set_event_logger
from lightgbm_tpu.observability.events import EventLogger
from lightgbm_tpu.serving import ServingDaemon

rng = np.random.RandomState(0)
X = rng.randn(400, 6).astype(np.float32)
y = (X[:, 0] > 0).astype(np.float32)
bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
                 "min_data_in_leaf": 5, "device_predict": "true",
                 "device_predict_min_bucket": 32},
                lgb.Dataset(X, label=y), num_boost_round=4)
set_event_logger(EventLogger(os.environ["SERVE_METRICS"]))
cfg = Config({"device_predict": "true", "device_predict_min_bucket": 32,
              "serve_max_batch_rows": 128, "verbosity": -1,
              # big window: queued requests SIT until drain proves them
              "serve_max_coalesce_wait_ms": 5000.0,
              "serve_drain_timeout_s": 30.0})
daemon = ServingDaemon(cfg).start()
daemon.registry.register("m", booster=bst, block=True)
daemon.install_signal_handlers()
futs = [daemon.submit("m", X[i:i+2]) for i in range(24)]
print("SUBMITTED", len(futs), flush=True)
def watch():
    for f in futs:
        f.result(timeout=60)
    print("ALL_COMPLETED", flush=True)
threading.Thread(target=watch, daemon=True).start()
time.sleep(60)
"""


def test_sigterm_drains_queue_and_exits_143(tmp_path):
    """SIGTERM mid-backlog: every queued request completes (drain), a
    `serve_drain` event lands, and the exit status stays `killed by
    SIGTERM` so supervisors classify *preempt* — the serving analogue
    of training's checkpoint-on-demand."""
    metrics = tmp_path / "metrics"
    metrics.mkdir()
    script = tmp_path / "child.py"
    script.write_text(_SIGTERM_CHILD)
    env = dict(os.environ, SERVE_REPO=REPO, SERVE_METRICS=str(metrics),
               JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, str(script)],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    try:
        # wait for the backlog to be queued, then preempt
        t0 = time.monotonic()
        while time.monotonic() - t0 < 120:
            line = proc.stdout.readline()
            if "SUBMITTED" in line:
                break
        else:
            pytest.fail("child never submitted its backlog")
        time.sleep(0.3)
        proc.send_signal(signal.SIGTERM)
        out_rest, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode in (-signal.SIGTERM, 143), (proc.returncode,
                                                       out_rest)
    assert "ALL_COMPLETED" in out_rest
    events = []
    for pth in metrics.glob("events-rank*.jsonl"):
        for ln in pth.read_text().splitlines():
            events.append(json.loads(ln))
    kinds = [e.get("event") for e in events]
    assert "serve_drain" in kinds
    drain = [e for e in events if e.get("event") == "serve_drain"][-1]
    assert drain["drained"] is True and drain["requests"] >= 24


_CLI_CHILD = r"""
import os, sys
sys.path.insert(0, os.environ["SERVE_REPO"])
import jax
jax.config.update("jax_platforms", "cpu")  # axon plugin ignores the env
from lightgbm_tpu.cli import main
sys.exit(main(sys.argv[1:]))
"""


def test_model_load_does_not_clobber_verbosity(tmp_path):
    """Loading a model builds a quiet predictor-mode Config; that must
    not silence the PROCESS log level — the daemon loads models
    mid-flight and its swap/drain logs have to keep flowing (this bug
    ate the CLI serve banner until fixed)."""
    from lightgbm_tpu.utils import log as _log
    bst, _ = _train(rounds=2)
    f = tmp_path / "m.txt"
    bst.save_model(str(f))
    prev = _log.get_verbosity()
    try:
        _log.set_verbosity(1)
        lgb.Booster(model_file=str(f))
        assert _log.get_verbosity() == 1
    finally:
        _log.set_verbosity(prev)


def test_cli_serve_end_to_end(tmp_path):
    """`python -m lightgbm_tpu serve`: loads + warms the model file,
    answers over the TCP front end, and SIGTERM drains + exits 143.
    (Driven through cli.main in a CPU-pinned child: the axon TPU plugin
    ignores JAX_PLATFORMS and would hang a bare `python -m` child on
    backend init — the same workaround bench.py's _backend_guard does.)"""
    bst, X = _train(rounds=4)
    model = tmp_path / "model.txt"
    bst.save_model(str(model))
    script = tmp_path / "cli_child.py"
    script.write_text(_CLI_CHILD)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1",
               SERVE_REPO=REPO)
    proc = subprocess.Popen(
        [sys.executable, "-u", str(script), "serve",
         f"serve_models=m={model}", "serve_port=0", "verbosity=1",
         "device_predict=true", "device_predict_min_bucket=32",
         "serve_max_batch_rows=64"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    try:
        port = None
        t0 = time.monotonic()
        while time.monotonic() - t0 < 180:
            line = proc.stdout.readline()
            if "front end listening on" in line:
                port = int(line.rsplit(":", 1)[1])
                break
            if proc.poll() is not None:
                pytest.fail(f"CLI serve exited early: {line}")
        assert port is not None, "front end never came up"
        with socket.create_connection(("127.0.0.1", port), timeout=60) as s:
            f = s.makefile("rwb")
            f.write((json.dumps(
                {"model": "m", "rows": X[:2].tolist()}) + "\n").encode())
            f.flush()
            resp = json.loads(f.readline())
        assert resp["ok"]
        np.testing.assert_allclose(resp["preds"], bst.predict(X[:2]),
                                   rtol=1e-6, atol=1e-6)
        proc.send_signal(signal.SIGTERM)
        proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode in (-signal.SIGTERM, 143)


# -------------------------------------------------- adaptive coalescing
def test_adaptive_coalesce_wait_decision():
    """The EWMA decision function (docs/Serving.md): static mode keeps
    the configured window unconditionally; adaptive mode keeps it under
    burst (EWMA gap <= window) and shrinks to 0 when arrivals are
    sparse (gap beyond the window) or unknown (no history)."""
    from lightgbm_tpu.serving import Coalescer
    static = Coalescer(max_wait_ms=40.0)
    assert static.effective_wait_s() == pytest.approx(0.040)
    c = Coalescer(max_wait_ms=40.0, adaptive=True)
    assert c.effective_wait_s() == 0.0          # no history yet
    with c._lock:
        c._ewma_gap_s = 0.001                   # burst: 1 ms gaps
    assert c.effective_wait_s() == pytest.approx(0.040)
    with c._lock:
        c._ewma_gap_s = 0.300                   # sparse: 300 ms gaps
    assert c.effective_wait_s() == 0.0


def test_adaptive_coalesce_sparse_p50_drops_vs_static():
    """Sparse sequential load: the static window makes EVERY request
    wait out serve_max_coalesce_wait_ms for batch-mates that never
    come; the adaptive window learns the arrival gap exceeds the
    window and dispatches immediately — p50 drops by at least the
    window."""
    bst, X = _train()

    def run(adaptive):
        d = _daemon(serve_max_coalesce_wait_ms=60.0,
                    serve_adaptive_coalesce="auto" if adaptive else "off")
        d.registry.register("m", booster=bst, block=True)
        try:
            lats = []
            for i in range(8):
                fut = d.submit("m", X[i:i + 1])
                fut.result(timeout=60)
                lats.append(fut.latency_ms)
                time.sleep(0.09)     # arrival gap 90 ms > 60 ms window
            return float(np.median(lats))
        finally:
            d.stop(drain=True, timeout=10)

    static_p50 = run(adaptive=False)
    adaptive_p50 = run(adaptive=True)
    assert static_p50 >= 55.0         # every request waits the window
    assert adaptive_p50 < static_p50 - 40.0


def test_adaptive_coalesce_burst_batches_unchanged():
    """Burst load: once the EWMA has seen burst-rate gaps, adaptive
    mode keeps the FULL static window, so bursts coalesce into the
    same fused dispatches as the static config (the batching
    efficiency the window exists to buy)."""
    bst, X = _train()

    def run(adaptive):
        from lightgbm_tpu.observability.registry import global_registry
        serve_counters_reset()
        d = _daemon(serve_max_coalesce_wait_ms=40.0,
                    serve_adaptive_coalesce="auto" if adaptive else "off")
        d.registry.register("m", booster=bst, block=True)
        try:
            for _round in range(3):   # round 0 warms the EWMA
                futs = [d.submit("m", X[i:i + 1]) for i in range(12)]
                for fut in futs:
                    fut.result(timeout=60)
            reqs = global_registry.counter("serve_requests")
            disp = global_registry.counter("serve_dispatches")
            return reqs / max(disp, 1)
        finally:
            d.stop(drain=True, timeout=10)

    static_ratio = run(adaptive=False)
    adaptive_ratio = run(adaptive=True)
    # both must coalesce bursts into fused dispatches (>= 2 requests
    # per dispatch on average), adaptive no worse than ~half static
    assert static_ratio >= 2.0
    assert adaptive_ratio >= 2.0
    assert adaptive_ratio >= 0.5 * static_ratio


# ------------------------------------------------------- UDS front end
def test_uds_frontend_round_trip_and_drain(tmp_path):
    """The Unix-socket front end speaks the SAME wire as TCP: predict
    (byte-identical to Booster.predict), health, metrics and publish
    all answer; after a drain-stop the daemon rejects instead of
    wedging the socket."""
    from lightgbm_tpu.serving import start_uds_frontend
    bst, X = _train()
    d = _daemon()
    d.registry.register("m", booster=bst, block=True)
    sock = str(tmp_path / "serve.sock")
    srv = start_uds_frontend(d, sock, request_timeout_s=60.0)
    try:
        c = ServingClient.connect_uds(sock)
        got = c.predict("m", X[:5])
        assert np.array_equal(got, bst.predict(X[:5]))  # byte-identical
        h = c.health()
        assert h["ready"] and h["models"] == {"m": 1}
        assert "m" in c.models()
        assert c.stats()["serve_requests"] >= 1
        # op=publish over the same socket: the rollout hook works on
        # UDS exactly like TCP (same handler)
        model2 = tmp_path / "m2.txt"
        bst2, _ = _train(rounds=4, seed=3)
        bst2.save_model(str(model2))
        from lightgbm_tpu.serving import LineClient
        lc = LineClient(uds_path=sock)
        reply = lc.request({"op": "publish", "model": "m",
                            "path": str(model2)}, timeout_s=120)
        assert reply["ok"] and reply["version"] == 2
        got2 = c.predict("m", X[:5])
        assert np.array_equal(got2, bst2.predict(X[:5]))
        reply = lc.request({"op": "metrics"}, timeout_s=30)
        assert reply["ok"] and "lgbm_serve_requests" in reply["metrics"]
        lc.close()
        # drain: stop the daemon, the socket answers a structured error
        d.stop(drain=True, timeout=10)
        reply = LineClient(uds_path=sock).request(
            {"model": "m", "rows": X[:1].tolist()}, timeout_s=30)
        assert not reply["ok"] and "error" in reply
        c.close()
    finally:
        srv.shutdown()
        d.stop(drain=False)


def test_uds_stale_socket_is_replaced(tmp_path):
    from lightgbm_tpu.serving import start_uds_frontend
    bst, X = _train()
    sock = str(tmp_path / "serve.sock")
    open(sock, "w").close()           # stale file where the socket goes
    d = _daemon()
    d.registry.register("m", booster=bst, block=True)
    srv = start_uds_frontend(d, sock)
    try:
        got = ServingClient.connect_uds(sock).predict("m", X[:2])
        assert np.array_equal(got, bst.predict(X[:2]))
    finally:
        srv.shutdown()
        d.stop(drain=True, timeout=10)
