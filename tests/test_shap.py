"""SHAP / pred_contrib (ref: tree.h:139 PredictContrib; TreeSHAP in
src/io/tree.cpp; python predict(pred_contrib=True))."""

import itertools

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _problem(n=2000, F=5, seed=8):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, F)
    y = X[:, 0] * 2 + X[:, 1] * X[:, 2] + 0.1 * rng.randn(n)
    return X, y


def test_contrib_sums_to_raw_prediction():
    """Additivity: sum of contributions + expected value == raw score."""
    X, y = _problem()
    booster = lgb.train({"objective": "regression", "num_leaves": 31,
                         "verbosity": -1, "min_data_in_leaf": 5},
                        lgb.Dataset(X, label=y), num_boost_round=10)
    sub = X[:100]
    contrib = booster.predict(sub, pred_contrib=True)
    assert contrib.shape == (100, X.shape[1] + 1)
    raw = booster.predict(sub, raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-5,
                               atol=1e-7)


def test_contrib_binary_sums_to_raw():
    X, y = _problem()
    yb = (y > 0).astype(np.float64)
    booster = lgb.train({"objective": "binary", "num_leaves": 15,
                         "verbosity": -1}, lgb.Dataset(X, label=yb),
                        num_boost_round=8)
    contrib = booster.predict(X[:50], pred_contrib=True)
    raw = booster.predict(X[:50], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-5,
                               atol=1e-7)


def test_contrib_multiclass_shape_and_sum():
    rng = np.random.RandomState(1)
    X = rng.randn(900, 4)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int) + (X[:, 2] > 1)
    booster = lgb.train({"objective": "multiclass", "num_class": 3,
                         "num_leaves": 7, "verbosity": -1},
                        lgb.Dataset(X, label=y), num_boost_round=5)
    contrib = booster.predict(X[:40], pred_contrib=True)
    assert contrib.shape == (40, 3 * (4 + 1))
    raw = booster.predict(X[:40], raw_score=True)
    per_class = contrib.reshape(40, 3, 5).sum(axis=2)
    np.testing.assert_allclose(per_class, raw, rtol=1e-5, atol=1e-7)


def test_contrib_matches_brute_force_shapley():
    """On a tiny 2-feature tree, TreeSHAP must equal the exact Shapley
    values computed by brute-force path enumeration."""
    rng = np.random.RandomState(2)
    n = 800
    X = rng.rand(n, 2)
    y = 1.0 * (X[:, 0] > 0.5) + 2.0 * (X[:, 1] > 0.5)
    booster = lgb.train({"objective": "regression", "num_leaves": 4,
                         "verbosity": -1, "min_data_in_leaf": 5,
                         "learning_rate": 1.0, "boost_from_average": False},
                        lgb.Dataset(X, label=y), num_boost_round=1)
    booster._gbdt._sync_model()
    tree = booster._gbdt.models_[0]

    def cond_exp(x, S):
        """E[f(X) | X_S = x_S] under the tree's path-dependent weighting."""
        def rec(node, w):
            if node < 0:
                return w * tree.leaf_value[~node]
            f = tree.split_feature[node]
            lc, rc = tree.left_child[node], tree.right_child[node]
            if f in S:
                go_left = x[f] <= tree.threshold[node]
                return rec(lc if go_left else rc, w)
            cl = (tree.leaf_count[~lc] if lc < 0
                  else tree.internal_count[lc])
            cr = (tree.leaf_count[~rc] if rc < 0
                  else tree.internal_count[rc])
            tot = cl + cr
            return rec(lc, w * cl / tot) + rec(rc, w * cr / tot)
        return rec(0, 1.0)

    xs = X[:5]
    contrib = booster.predict(xs, pred_contrib=True)
    import math
    F = 2
    for r, x in enumerate(xs):
        for j in range(F):
            phi = 0.0
            others = [f for f in range(F) if f != j]
            for k in range(len(others) + 1):
                for S in itertools.combinations(others, k):
                    wgt = (math.factorial(len(S))
                           * math.factorial(F - len(S) - 1)
                           / math.factorial(F))
                    phi += wgt * (cond_exp(x, set(S) | {j})
                                  - cond_exp(x, set(S)))
            np.testing.assert_allclose(contrib[r, j], phi, rtol=1e-6,
                                       atol=1e-9)
        np.testing.assert_allclose(contrib[r, -1], cond_exp(x, set()),
                                   rtol=1e-6)


def test_native_lib_compiles():
    from lightgbm_tpu.native import treeshap_lib
    assert treeshap_lib() is not None, \
        "native TreeSHAP failed to compile (cc available in the image)"


def test_contrib_sparse_input_returns_sparse():
    """pred_contrib on scipy-sparse input returns a scipy CSR matrix that
    matches the dense result (ref: python-package basic.py predict returns
    sparse contribs for sparse input)."""
    from scipy import sparse as sps
    X, y = _problem()
    Xs = np.where(np.abs(X) > 0.8, X, 0.0)
    booster = lgb.train({"objective": "regression", "num_leaves": 15,
                         "verbosity": -1, "min_data_in_leaf": 5},
                        lgb.Dataset(Xs, label=y), num_boost_round=5)
    dense = booster.predict(Xs[:64], pred_contrib=True)
    out = booster.predict(sps.csr_matrix(Xs[:64]), pred_contrib=True)
    assert sps.issparse(out)
    np.testing.assert_allclose(out.toarray(), dense, rtol=1e-6, atol=1e-9)
