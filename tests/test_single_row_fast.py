"""Single-row fast predict (ref: c_api.h:1350-1379
LGBM_BoosterPredictForMatSingleRowFastInit/...Fast; FastConfig caching
c_api.cpp:125-160): parse/pack once, per-call work is one buffer write +
one pre-bound native call."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.native import predictor_lib

pytestmark = pytest.mark.skipif(predictor_lib() is None,
                                reason="native predictor unavailable")


def _fit(objective, y, **extra):
    rng = np.random.RandomState(0)
    X = rng.rand(2000, 6)
    p = {"objective": objective, "num_leaves": 15, "verbosity": -1}
    p.update(extra)
    return X, lgb.train(p, lgb.Dataset(X, label=y(X)), num_boost_round=12)


@pytest.mark.parametrize("objective,y,kw", [
    ("binary", lambda X: (X[:, 0] + X[:, 1] > 1).astype(float), {}),
    ("regression", lambda X: X[:, 0] * 3 + X[:, 1], {}),
    ("regression", lambda X: np.abs(X[:, 0] * 3), {"reg_sqrt": True}),
    ("multiclass", lambda X: (X[:, 0] * 3).astype(int) % 3,
     {"num_class": 3}),
])
def test_fast_matches_batch_path(objective, y, kw):
    X, b = _fit(objective, y, **kw)
    for i in (0, 17, 0, 999):     # repeats catch output-buffer reuse bugs
        want = b.predict(X[i:i + 1])
        got = b.predict(X[i:i + 1], single_row_fast=True)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)
        want_raw = b.predict(X[i:i + 1], raw_score=True)
        got_raw = b.predict(X[i:i + 1], raw_score=True,
                            single_row_fast=True)
        np.testing.assert_allclose(got_raw, want_raw, rtol=1e-9)


def test_fast_handles_nan_and_1d_input():
    X, b = _fit("binary", lambda X: (X[:, 0] > 0.5).astype(float))
    row = X[3].copy()
    row[2] = np.nan
    want = b.predict(row[None, :])
    got = b.predict(row, single_row_fast=True)        # 1-D input allowed
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_fast_cache_invalidated_by_growth():
    rng = np.random.RandomState(1)
    X = rng.rand(1000, 5)
    y = (X[:, 0] > 0.5).astype(float)
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1}, ds, num_boost_round=3,
                  keep_training_booster=True)
    p1 = b.predict(X[5:6], single_row_fast=True)
    b.update()                                       # model grows
    p2 = b.predict(X[5:6], single_row_fast=True)
    np.testing.assert_allclose(p2, b.predict(X[5:6]), rtol=1e-5)
    assert not np.allclose(p1, p2)                   # new tree changed it


def test_fast_direct_api_latency_is_micro_scale():
    X, b = _fit("binary", lambda X: (X[:, 0] + X[:, 1] > 1).astype(float))
    sp = b._gbdt.make_single_row_fast(X.shape[1])
    assert sp is not None and sp.ok
    import time
    rows = [np.ascontiguousarray(X[i % 2000]) for i in range(3000)]
    sp.predict(rows[0])
    t0 = time.time()
    for r in rows:
        sp.predict(r)
    per_row = (time.time() - t0) / len(rows)
    assert per_row < 500e-6, f"{per_row * 1e6:.0f} us/row"
