"""sklearn estimator API (ref: python-package/lightgbm/sklearn.py;
tests/python_package_test/test_sklearn.py)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _cls_data(n=2000, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 6)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(int)
    return X, y


def test_classifier_binary():
    X, y = _cls_data()
    clf = lgb.LGBMClassifier(n_estimators=20, num_leaves=15)
    clf.fit(X, y)
    assert clf.n_features_ == 6
    assert list(clf.classes_) == [0, 1]
    acc = float(np.mean(clf.predict(X) == y))
    assert acc > 0.9, acc
    proba = clf.predict_proba(X)
    assert proba.shape == (len(y), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-6)


def test_classifier_string_labels():
    X, y = _cls_data(800)
    labels = np.array(["neg", "pos"])[y]
    clf = lgb.LGBMClassifier(n_estimators=10, num_leaves=7)
    clf.fit(X, labels)
    assert set(clf.predict(X)) <= {"neg", "pos"}
    acc = float(np.mean(clf.predict(X) == labels))
    assert acc > 0.85, acc


def test_classifier_multiclass():
    rng = np.random.RandomState(1)
    X = rng.randn(1500, 4)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    clf = lgb.LGBMClassifier(n_estimators=15, num_leaves=15)
    clf.fit(X, y)
    assert clf.n_classes_ == 3
    proba = clf.predict_proba(X)
    assert proba.shape == (1500, 3)
    acc = float(np.mean(clf.predict(X) == y))
    assert acc > 0.85, acc


def test_regressor_with_eval_and_early_stopping():
    rng = np.random.RandomState(2)
    X = rng.randn(2000, 5)
    y = X[:, 0] * 2 + np.sin(X[:, 1]) + 0.1 * rng.randn(2000)
    reg = lgb.LGBMRegressor(n_estimators=200, num_leaves=15,
                            early_stopping_round=5)
    reg.fit(X[:1500], y[:1500], eval_set=[(X[1500:], y[1500:])])
    assert reg.best_iteration_ > 0
    mse = float(np.mean((reg.predict(X[1500:]) - y[1500:]) ** 2))
    assert mse < 0.1, mse


def test_regressor_sklearn_params_roundtrip():
    reg = lgb.LGBMRegressor(num_leaves=63, learning_rate=0.05,
                            min_child_samples=7, reg_lambda=0.5)
    params = reg.get_params()
    assert params["num_leaves"] == 63
    assert params["reg_lambda"] == 0.5
    reg.set_params(num_leaves=31)
    assert reg.get_params()["num_leaves"] == 31


def test_sklearn_clone_and_cv_compat():
    from sklearn.base import clone
    from sklearn.model_selection import cross_val_score
    X, y = _cls_data(900)
    clf = lgb.LGBMClassifier(n_estimators=5, num_leaves=7)
    clf2 = clone(clf)
    assert clf2.get_params()["n_estimators"] == 5
    scores = cross_val_score(clf, X, y, cv=2)
    assert scores.mean() > 0.8, scores


def test_feature_importances():
    X, y = _cls_data()
    clf = lgb.LGBMClassifier(n_estimators=10, num_leaves=15).fit(X, y)
    imp = clf.feature_importances_
    assert imp.shape == (6,)
    assert imp[:3].sum() > imp[3:].sum()  # informative features dominate


def test_ranker():
    rng = np.random.RandomState(3)
    n_queries, per_q = 60, 20
    n = n_queries * per_q
    X = rng.rand(n, 4)
    rel = (3 * X[:, 0] + rng.rand(n) > 2).astype(int) + (X[:, 1] > 0.8)
    group = np.full(n_queries, per_q)
    rk = lgb.LGBMRanker(n_estimators=10, num_leaves=7,
                        min_child_samples=5)
    rk.fit(X, rel, group=group)
    s = rk.predict(X)
    # scores must rank relevant docs above irrelevant within queries
    corr = np.corrcoef(s, rel)[0, 1]
    assert corr > 0.5, corr


def test_pickle_roundtrip():
    """Boosters and sklearn estimators pickle via the model text
    (ref: basic.py Booster.__getstate__) — required for joblib
    persistence and sklearn model selection."""
    import pickle
    X, y = _cls_data(800)
    clf = lgb.LGBMClassifier(n_estimators=8, num_leaves=7).fit(X, y)
    blob = pickle.dumps(clf)
    clf2 = pickle.loads(blob)
    np.testing.assert_array_equal(clf2.predict(X), clf.predict(X))
    np.testing.assert_allclose(clf2.predict_proba(X),
                               clf.predict_proba(X), rtol=1e-6)
    # bare Booster too
    b = clf.booster_
    b2 = pickle.loads(pickle.dumps(b))
    np.testing.assert_allclose(b2.predict(X), b.predict(X), rtol=1e-6)


def test_pickled_booster_eval_valid_safe():
    """eval_valid on an unpickled booster must not ghost old valid sets."""
    import pickle
    X, y = _cls_data(600)
    b = lgb.train({"objective": "binary", "num_leaves": 7,
                   "verbosity": -1},
                  lgb.Dataset(X[:400], label=y[:400]), num_boost_round=3,
                  valid_sets=[lgb.Dataset(X[400:], label=y[400:])],
                  valid_names=["v"])
    b2 = pickle.loads(pickle.dumps(b))
    assert b2.eval_valid() == []
    res = b2.eval(lgb.Dataset(X, label=y), "new")
    assert res and np.isfinite(res[0][2])


def test_sklearn_new_fit_params_and_attrs():
    """eval_metric / init_score / evals_result_ / feature_name_ /
    n_estimators_ / objective_ (ref: sklearn.py fit + fitted attrs)."""
    rng = np.random.RandomState(0)
    X = rng.rand(500, 4)
    y = X[:, 0] + 0.2 * rng.randn(500)
    reg = lgb.LGBMRegressor(n_estimators=6, num_leaves=7,
                            min_child_samples=5)
    reg.fit(X, y, eval_set=[(X[:100], y[:100])], eval_metric="l1",
            init_score=np.zeros(len(y)),
            feature_name=["a", "b", "c", "d"])
    assert "l1" in next(iter(reg.evals_result_.values()))
    assert reg.feature_name_ == ["a", "b", "c", "d"]
    assert reg.n_estimators_ == 6 and reg.n_iter_ == 6
    assert reg.objective_ == "regression"


def test_sklearn_feature_names_in_from_pandas():
    pd = pytest.importorskip("pandas")
    rng = np.random.RandomState(0)
    df = pd.DataFrame(rng.rand(300, 3), columns=["f1", "f2", "f3"])
    y = df["f1"] + 0.1 * rng.randn(300)
    reg = lgb.LGBMRegressor(n_estimators=3, num_leaves=7,
                            min_child_samples=5).fit(df, y)
    assert list(reg.feature_names_in_) == ["f1", "f2", "f3"]
    assert reg.feature_name_ == ["f1", "f2", "f3"]


def test_eval_metric_merges_and_callable_feval():
    """eval_metric strings merge with the configured metric; callables
    route to feval (ref: sklearn.py _EvalFunctionWrapper)."""
    rng = np.random.RandomState(1)
    X = rng.rand(400, 3)
    y = X[:, 0] + 0.1 * rng.randn(400)

    def my_metric(preds, ds):
        return ("my_mae", float(np.mean(np.abs(preds - ds.get_label()))),
                False)

    reg = lgb.LGBMRegressor(n_estimators=4, num_leaves=7,
                            min_child_samples=5, metric="rmse")
    reg.fit(X, y, eval_set=[(X[:100], y[:100])],
            eval_metric=["l1", my_metric])
    res = next(iter(reg.evals_result_.values()))
    assert "rmse" in res and "l1" in res and "my_mae" in res, res.keys()


def test_eval_set_aliasing_train_uses_own_labels():
    """eval_set=(X, other_y) must NOT silently reuse the train labels."""
    rng = np.random.RandomState(2)
    X = rng.rand(300, 3)
    y = X[:, 0] + 0.05 * rng.randn(300)
    y_shifted = y + 100.0
    reg = lgb.LGBMRegressor(n_estimators=3, num_leaves=7,
                            min_child_samples=5)
    reg.fit(X, y, eval_set=[(X, y_shifted)], eval_metric="l1")
    l1 = next(iter(reg.evals_result_.values()))["l1"][-1]
    assert l1 > 50, l1  # evaluated against the SHIFTED labels
