"""Sparse (scipy CSR/CSC) ingestion without densification.

ref: src/io/sparse_bin.hpp, multi_val_sparse_bin.hpp, and the density
heuristics in Dataset::GetShareStates — redesigned as CSC-direct-to-EFB
bundle codes (lightgbm_tpu/io/sparse.py).  The dense [n, F] matrix must
NEVER be materialized at ingestion; models must match the densified
path bit-for-bit on the same data.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import lightgbm_tpu as lgb


def _make_sparse(n=4000, F=60, density=0.02, seed=0):
    rng = np.random.RandomState(seed)
    m = sp.random(n, F, density=density, random_state=rng,
                  data_rvs=lambda k: rng.randn(k) + 1.0).tocsr()
    # label depends on a few columns so trees have something to learn
    d = np.asarray(m[:, :5].todense())
    logit = d.sum(axis=1) + 0.5 * (d[:, 0] > 0)
    y = (rng.rand(n) < 1 / (1 + np.exp(-logit))).astype(np.float64)
    return m, y


PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5, "seed": 7, "deterministic": True}


def test_sparse_never_densified():
    """Ingestion must not call toarray/todense on the input."""
    m, y = _make_sparse()

    class Guarded(sp.csr_matrix):
        def toarray(self, *a, **k):
            raise AssertionError("sparse input was densified at ingestion")
        todense = toarray

    g = Guarded(m)
    ds = lgb.Dataset(g, label=y)
    ds._core_or_construct()
    core = ds._core
    assert core.pre_bundled_plan is not None
    # wide-sparse input lands in far fewer device columns than features
    assert core.binned.shape[0] < core.num_features
    b = lgb.train(PARAMS, ds, num_boost_round=5)
    assert b.current_iteration() == 5


def test_sparse_matches_dense_path_bitwise():
    """Same data through the sparse path and the densified path must give
    identical bin mappers, identical bundle plans, and identical models."""
    m, y = _make_sparse()
    b_sparse = lgb.train(PARAMS, lgb.Dataset(m, label=y), num_boost_round=8)
    b_dense = lgb.train(PARAMS, lgb.Dataset(np.asarray(m.todense()),
                                            label=y), num_boost_round=8)
    assert b_sparse.model_to_string() == b_dense.model_to_string()


def test_sparse_predict_chunked_matches_dense():
    m, y = _make_sparse()
    b = lgb.train(PARAMS, lgb.Dataset(m, label=y), num_boost_round=5)
    p_sparse = b.predict(m)
    p_dense = b.predict(np.asarray(m.todense()))
    np.testing.assert_array_equal(p_sparse, p_dense)


def test_sparse_valid_sets_and_early_stopping():
    m, y = _make_sparse()
    mv, yv = _make_sparse(seed=1)
    ds = lgb.Dataset(m, label=y)
    dv = lgb.Dataset(mv, label=yv, reference=ds)
    ev = {}
    b = lgb.train({**PARAMS, "metric": "auc"}, ds, num_boost_round=8,
                  valid_sets=[dv], valid_names=["v"],
                  callbacks=[lgb.record_evaluation(ev)])
    aucs = ev["v"]["auc"]
    assert len(aucs) == 8 and aucs[-1] > 0.5


def test_sparse_csc_and_coo_inputs():
    m, y = _make_sparse()
    p = None
    for conv in (m.tocsc(), m.tocoo()):
        b = lgb.train(PARAMS, lgb.Dataset(conv, label=y), num_boost_round=4)
        q = b.predict(np.asarray(m.todense()))
        if p is not None:
            np.testing.assert_array_equal(p, q)
        p = q


def test_sparse_save_binary_roundtrip(tmp_path):
    m, y = _make_sparse()
    ds = lgb.Dataset(m, label=y)
    ds._core_or_construct()
    path = str(tmp_path / "sparse_ds.npz")
    ds._core.save_binary(path)
    from lightgbm_tpu.io.dataset import Dataset as CoreDataset
    back = CoreDataset.load_binary(path)
    assert back.pre_bundled_plan is not None
    np.testing.assert_array_equal(back.binned, ds._core.binned)
    np.testing.assert_array_equal(back.pre_bundled_plan.offsets,
                                  ds._core.pre_bundled_plan.offsets)


def test_sparse_subset_keeps_plan():
    m, y = _make_sparse()
    ds = lgb.Dataset(m, label=y)
    ds._core_or_construct()
    sub = ds._core.copy_subrow(np.arange(100))
    assert sub.pre_bundled_plan is ds._core.pre_bundled_plan
    assert sub.binned.shape == (ds._core.binned.shape[0], 100)


def test_wide_sparse_memory_budget():
    """Structurally exclusive one-hot blocks (the news20/Criteo shape EFB
    is built for) must collapse to ~one bundle column per block; peak
    ingest memory is O(nnz + bundles*n), not O(n*F)."""
    rng = np.random.RandomState(3)
    n, F, block = 20_000, 1000, 50
    cols = rng.randint(0, block, size=(n, F // block))
    cols += np.arange(F // block)[None, :] * block
    rows = np.repeat(np.arange(n), F // block)
    # binary indicator features (the one-hot case EFB compresses):
    # each feature then has 2 bins and ~127 fit one bundle column
    vals = np.ones(n * (F // block))
    m = sp.csr_matrix((vals, (rows, cols.ravel())), shape=(n, F))
    # label depends on WHICH indicator is hot in the first block
    y = (cols[:, 0] % 2 == 0).astype(np.float64)
    ds = lgb.Dataset(m, label=y)
    ds._core_or_construct()
    ncols = ds._core.binned.shape[0]
    assert ncols <= 2 * (F // block), \
        f"{ncols} bundle columns for {F} one-hot features"
    b = lgb.train(PARAMS, ds, num_boost_round=3)
    assert b.current_iteration() == 3


def test_sparse_enable_bundle_false_keeps_per_feature_bins():
    """enable_bundle=False must disable EFB on the sparse path too: the
    dataset then stores exact per-feature bins (no conflict loss) and
    matches the dense path's model."""
    m, y = _make_sparse()
    p = {**PARAMS, "enable_bundle": False}
    ds = lgb.Dataset(m, label=y, params=p)
    ds._core_or_construct()
    assert ds._core.pre_bundled_plan is None
    assert ds._core.binned.shape[0] == ds._core.num_features
    b_sparse = lgb.train(p, ds, num_boost_round=5)
    b_dense = lgb.train(p, lgb.Dataset(np.asarray(m.todense()), label=y,
                                       params=p), num_boost_round=5)
    assert b_sparse.model_to_string() == b_dense.model_to_string()


def test_sparse_categorical_matches_dense_path():
    """Categorical features whose category 0 is a real observed bin used
    to diverge from the dense path (absent entries were filled with the
    bundle default instead of bin(0)); models must match bit-for-bit."""
    rng = np.random.RandomState(5)
    n = 3000
    X = np.zeros((n, 4))
    X[:, 0] = rng.randn(n)
    X[:, 1] = np.where(rng.rand(n) < 0.7, 0.0,
                       rng.randint(1, 6, n)).astype(float)  # sparse cat
    X[:, 2] = np.where(rng.rand(n) < 0.8, 0.0, rng.randn(n))
    X[:, 3] = rng.randint(0, 3, n).astype(float)            # dense-ish cat
    y = ((X[:, 1] == 0) & (X[:, 0] > 0)).astype(np.float64)
    import scipy.sparse as sp2
    m = sp2.csr_matrix(X)
    p = {**PARAMS, "min_data_in_leaf": 10}
    b_sp = lgb.train(p, lgb.Dataset(m, label=y, categorical_feature=[1, 3]),
                     num_boost_round=6)
    b_dn = lgb.train(p, lgb.Dataset(X, label=y, categorical_feature=[1, 3]),
                     num_boost_round=6)
    assert b_sp.model_to_string() == b_dn.model_to_string()
    np.testing.assert_array_equal(b_sp.predict(m), b_dn.predict(X))
