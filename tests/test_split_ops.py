"""Tests for ops/histogram.py and ops/split.py against independent NumPy oracles.

The oracle re-implements the reference's sequential scan loop directly
(ref: src/treelearner/feature_histogram.hpp:831-1057) so the vectorized XLA
version is checked candidate-for-candidate, including epsilon conventions,
hessian-derived counts, missing-bin routing and tie-breaking.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.ops.histogram import build_histogram
from lightgbm_tpu.ops.split import (K_EPSILON, MISSING_NAN, MISSING_NONE,
                                    MISSING_ZERO, SplitParams, find_best_split)

RNG = np.random.RandomState(7)


# ---------------------------------------------------------------- histogram --
def _hist_oracle(binned, gh, mask, max_bin):
    F, n = binned.shape
    out = np.zeros((F, max_bin, gh.shape[1]), dtype=np.float64)
    for f in range(F):
        for r in range(n):
            out[f, binned[f, r]] += gh[r] * mask[r]
    return out


# "onehot" is single-pass bf16 (reference GPU learner analogue: its default
# is single-precision histograms, gpu_tree_learner.h:79); tolerance reflects
# bf16 rounding of gh inputs.  "segment"/"onehot_hp" are fp32-exact paths.
@pytest.mark.parametrize("method,rtol,atol",
                         [("segment", 2e-4, 2e-4),
                          ("onehot_hp", 2e-4, 2e-4),
                          ("onehot", 5e-2, 1e-1)])
@pytest.mark.parametrize("n,F,B", [(256, 3, 8), (4096, 5, 16)])
def test_histogram_matches_oracle(method, rtol, atol, n, F, B):
    binned = RNG.randint(0, B, size=(F, n)).astype(np.int32)
    gh = RNG.randn(n, 2).astype(np.float32)
    mask = (RNG.rand(n) > 0.3).astype(np.float32)
    hist = build_histogram(jnp.array(binned), jnp.array(gh), jnp.array(mask),
                           max_bin=B, method=method)
    expect = _hist_oracle(binned, gh, mask, B)
    np.testing.assert_allclose(np.asarray(hist), expect, rtol=rtol, atol=atol)


def test_histogram_chunked_matches_unchunked():
    n, F, B = 8192, 4, 32
    binned = RNG.randint(0, B, size=(F, n)).astype(np.int32)
    gh = RNG.randn(n, 2).astype(np.float32)
    mask = np.ones(n, dtype=np.float32)
    h1 = build_histogram(jnp.array(binned), jnp.array(gh), jnp.array(mask),
                         max_bin=B, row_chunk=1024)
    h2 = build_histogram(jnp.array(binned), jnp.array(gh), jnp.array(mask),
                         max_bin=B, row_chunk=8192)
    np.testing.assert_allclose(np.asarray(h1), np.asarray(h2), rtol=1e-5, atol=1e-5)


# -------------------------------------------------------------- split oracle --
def _leaf_gain(g, h, l1, l2):
    s = np.sign(g) * max(0.0, abs(g) - l1)
    return s * s / (h + l2)


def _scan_oracle(hist_g, hist_h, nb, mt, db, sum_g, sum_h_base, num_data, p):
    """Direct loop port of FindBestThresholdSequentially (float path, offset=0)."""
    sum_h = sum_h_base + 2 * K_EPSILON
    cnt_factor = num_data / sum_h
    gain_shift = _leaf_gain(sum_g, sum_h, p.lambda_l1, p.lambda_l2)
    min_gain_shift = gain_shift + p.min_gain_to_split
    na = 1 if mt == MISSING_NAN else 0
    skip_db = mt == MISSING_ZERO

    best = dict(gain=-np.inf, thr=nb, dl=True, lg=np.nan, lh=np.nan, lc=0)

    # REVERSE
    srg, srh, src = 0.0, K_EPSILON, 0
    for t in range(nb - 1 - na, 0, -1):
        if skip_db and t == db:
            continue
        srg += hist_g[t]
        srh += hist_h[t]
        src += int(np.floor(hist_h[t] * cnt_factor + 0.5))
        if src < p.min_data_in_leaf or srh < p.min_sum_hessian_in_leaf:
            continue
        lc = num_data - src
        if lc < p.min_data_in_leaf:
            break
        slh = sum_h - srh
        if slh < p.min_sum_hessian_in_leaf:
            break
        slg = sum_g - srg
        gain = _leaf_gain(slg, slh, p.lambda_l1, p.lambda_l2) + \
            _leaf_gain(srg, srh, p.lambda_l1, p.lambda_l2)
        if gain <= min_gain_shift or gain <= best["gain"]:
            continue
        best.update(gain=gain, thr=t - 1, dl=True, lg=slg, lh=slh, lc=lc)

    # FORWARD (only when a missing direction exists)
    if mt != MISSING_NONE:
        fwd = dict(gain=-np.inf, thr=nb, lg=np.nan, lh=np.nan, lc=0)
        slg, slh, slc = 0.0, K_EPSILON, 0
        for t in range(0, nb - 1):
            if skip_db and t == db:
                continue
            if not (na and t == nb - 1):
                slg += hist_g[t]
                slh += hist_h[t]
                slc += int(np.floor(hist_h[t] * cnt_factor + 0.5))
            if slc < p.min_data_in_leaf or slh < p.min_sum_hessian_in_leaf:
                continue
            rc = num_data - slc
            if rc < p.min_data_in_leaf:
                break
            srh2 = sum_h - slh
            if srh2 < p.min_sum_hessian_in_leaf:
                break
            srg2 = sum_g - slg
            gain = _leaf_gain(slg, slh, p.lambda_l1, p.lambda_l2) + \
                _leaf_gain(srg2, srh2, p.lambda_l1, p.lambda_l2)
            if gain <= min_gain_shift or gain <= fwd["gain"]:
                continue
            fwd.update(gain=gain, thr=t, lg=slg, lh=slh, lc=slc)
        if fwd["gain"] > best["gain"]:
            best.update(gain=fwd["gain"], thr=fwd["thr"], dl=False,
                        lg=fwd["lg"], lh=fwd["lh"], lc=fwd["lc"])
    if np.isfinite(best["gain"]):
        best["gain"] -= min_gain_shift
    return best


def _run_one(nb, mt, db, p, seed, num_data=500):
    rng = np.random.RandomState(seed)
    B = 16
    hist = np.zeros((1, B, 2), dtype=np.float32)
    hist[0, :nb, 0] = rng.randn(nb).astype(np.float32)
    hist[0, :nb, 1] = rng.rand(nb).astype(np.float32) * num_data / nb
    sum_g = float(hist[0, :, 0].sum())
    sum_h = float(hist[0, :, 1].sum())
    res = find_best_split(
        jnp.array(hist), jnp.array([nb], jnp.int32), jnp.array([mt], jnp.int32),
        jnp.array([db], jnp.int32), jnp.ones(1, jnp.float32),
        jnp.ones(1, bool), jnp.float32(sum_g), jnp.float32(sum_h),
        jnp.int32(num_data), jnp.float32(0.0), p)
    oracle = _scan_oracle(hist[0, :, 0].astype(np.float64),
                          hist[0, :, 1].astype(np.float64),
                          nb, mt, db, sum_g, sum_h, num_data, p)
    return res, oracle


@pytest.mark.parametrize("mt,db", [(MISSING_NONE, 0), (MISSING_ZERO, 3),
                                   (MISSING_NAN, 0)])
@pytest.mark.parametrize("seed", range(8))
def test_split_matches_scan_oracle(mt, db, seed):
    p = SplitParams(lambda_l1=0.0, lambda_l2=0.01, min_data_in_leaf=5,
                    min_sum_hessian_in_leaf=1e-3, min_gain_to_split=0.0)
    res, oracle = _run_one(12, mt, db, p, seed)
    if not np.isfinite(oracle["gain"]) or oracle["gain"] <= 0:
        assert float(res.gain) <= 0 or not np.isfinite(float(res.gain))
        return
    assert int(res.threshold) == oracle["thr"], (oracle, res)
    assert bool(res.default_left) == oracle["dl"]
    np.testing.assert_allclose(float(res.gain), oracle["gain"], rtol=1e-4)
    np.testing.assert_allclose(float(res.left_sum_gradient), oracle["lg"], rtol=1e-4)
    assert int(res.left_count) == oracle["lc"]


@pytest.mark.parametrize("seed", range(4))
def test_split_l1_and_min_gain(seed):
    p = SplitParams(lambda_l1=0.5, lambda_l2=1.0, min_data_in_leaf=3,
                    min_sum_hessian_in_leaf=1e-3, min_gain_to_split=0.1)
    res, oracle = _run_one(10, MISSING_NONE, 0, p, seed)
    if not np.isfinite(oracle["gain"]) or oracle["gain"] <= 0:
        assert float(res.gain) <= 0 or not np.isfinite(float(res.gain))
        return
    assert int(res.threshold) == oracle["thr"]
    np.testing.assert_allclose(float(res.gain), oracle["gain"], rtol=1e-4)


def test_split_multifeature_prefers_informative():
    """Feature 1 perfectly separates the gradients; must be chosen."""
    B = 8
    n = 200
    binned = np.zeros((2, n), dtype=np.int32)
    binned[0] = RNG.randint(0, B, n)          # noise feature
    binned[1] = (np.arange(n) >= n // 2).astype(np.int32) * 4  # informative
    grad = np.where(np.arange(n) >= n // 2, 1.0, -1.0).astype(np.float32)
    hess = np.ones(n, dtype=np.float32)
    gh = np.stack([grad, hess], 1)
    hist = build_histogram(jnp.array(binned), jnp.array(gh),
                           jnp.ones(n, jnp.float32), max_bin=B)
    res = find_best_split(
        hist, jnp.array([B, B], jnp.int32),
        jnp.array([MISSING_NONE, MISSING_NONE], jnp.int32),
        jnp.zeros(2, jnp.int32), jnp.ones(2, jnp.float32), jnp.ones(2, bool),
        jnp.float32(grad.sum()), jnp.float32(hess.sum()),
        jnp.int32(n), jnp.float32(0.0),
        SplitParams(min_data_in_leaf=1, min_sum_hessian_in_leaf=1e-3))
    assert int(res.feature) == 1
    assert int(res.threshold) in (0, 1, 2, 3)
    assert float(res.gain) > 0
    # perfect separation: left mean -1, right mean +1
    np.testing.assert_allclose(float(res.left_output), 1.0, atol=0.02)
    np.testing.assert_allclose(float(res.right_output), -1.0, atol=0.02)
