"""Timer/profiling subsystem (ref: utils/common.h:973 Timer/FunctionTimer,
global_timer printed at exit when TIMETAG is on)."""

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.timer import Timer, global_timer


def test_timer_scopes_aggregate():
    t = Timer(enabled=True)
    with t.scope("a"):
        with t.scope("b"):
            pass
    with t.scope("a"):
        pass
    items = dict((k, c) for k, _, c in t.items())
    assert items == {"a": 2, "b": 1}


def test_timer_disabled_is_noop():
    t = Timer(enabled=False)
    with t.scope("a"):
        pass
    assert t.items() == ()


def test_timer_reset_and_snapshot():
    t = Timer(enabled=True)
    with t.scope("x"):
        pass
    snap = t.snapshot()
    assert set(snap) == {"x"} and snap["x"][1] == 1
    with t.scope("x"):
        pass
    snap2 = t.snapshot()
    assert snap2["x"][1] == 2 and snap2["x"][0] >= snap["x"][0]
    t.reset()
    assert t.items() == () and t.snapshot() == {}


def test_timeit_preserves_wrapped_metadata():
    """Satellite fix: Timer.timeit must not eat __name__/__doc__."""
    t = Timer(enabled=True)

    @t.timeit("f")
    def my_fn(a, b=1):
        """my docstring"""
        return a + b

    assert my_fn.__name__ == "my_fn"
    assert my_fn.__doc__ == "my docstring"
    assert my_fn(2, b=3) == 5
    assert dict((k, c) for k, _, c in t.items()) == {"f": 1}


def test_trace_annotation_switch():
    """Satellite fix: the jax-profiler flag is drivable — by the
    LIGHTGBM_TPU_TRACE env at construction and the public setter."""
    t = Timer(enabled=False)
    assert t.trace_annotations_enabled() == bool(
        __import__("os").environ.get("LIGHTGBM_TPU_TRACE", ""))
    t.set_trace_annotations(True)
    assert t.trace_annotations_enabled()
    # scopes still work (and emit TraceAnnotations) with timing off
    with t.scope("annotated"):
        pass
    assert t.items() == ()   # timing stays off
    t.set_trace_annotations(False)
    assert not t.trace_annotations_enabled()
    t2 = Timer(enabled=False, use_jax_profiler=True)
    assert t2.trace_annotations_enabled()


def test_timer_block_passthrough():
    t = Timer(enabled=False)
    obj = object()
    assert t.block(obj) is obj          # disabled: identity
    t.enabled = True
    import jax.numpy as jnp
    arr = jnp.arange(4)
    out = t.block(arr)
    np.testing.assert_array_equal(np.asarray(out), np.arange(4))


def test_global_timer_instruments_training():
    global_timer.enabled = True
    global_timer.reset()
    try:
        rng = np.random.RandomState(0)
        X = rng.randn(500, 3)
        y = X[:, 0]
        lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1}, lgb.Dataset(X, label=y),
                  num_boost_round=2)
        names = {k for k, _, _ in global_timer.items()}
        assert "GBDT::grow_tree" in names
        assert "GBDT::finalize_tree" in names
    finally:
        global_timer.enabled = False
        global_timer.reset()


def test_block_attributes_device_time_to_scope():
    """block() inside a scope credits the settle wait to a separate
    `<scope>::device` entry (per-phase DEVICE time attribution, ISSUE 10
    satellite): the scope total still includes the settle, the ::device
    entry says how much of it the chip owned."""
    import jax.numpy as jnp
    t = Timer(enabled=True)
    with t.scope("Phase"):
        t.block(jnp.arange(1000) * 2)
    snap = t.snapshot()
    assert "Phase" in snap and "Phase::device" in snap
    assert snap["Phase::device"][0] <= snap["Phase"][0]
    assert snap["Phase::device"][1] == 1
    # nested scopes credit the INNERMOST phase
    t.reset()
    with t.scope("Outer"):
        with t.scope("Inner"):
            t.block(jnp.arange(8))
    snap = t.snapshot()
    assert "Inner::device" in snap and "Outer::device" not in snap
    # no enclosing scope: settle happens, nothing is credited
    t.reset()
    t.block(jnp.arange(8))
    assert t.snapshot() == {}


def test_block_outside_scope_disabled_no_attribution():
    t = Timer(enabled=False)
    with t.scope("X"):
        t.block(None)
    assert t.snapshot() == {}


def test_scope_stack_is_thread_local():
    """The serving coalescer times dispatches concurrently with the main
    thread: each thread's block() must credit ITS OWN scope."""
    import threading

    import jax.numpy as jnp
    t = Timer(enabled=True)
    done = threading.Event()
    ready = threading.Event()

    def worker():
        with t.scope("WorkerPhase"):
            ready.set()
            done.wait(timeout=10)
            t.block(jnp.arange(16))

    th = threading.Thread(target=worker)
    th.start()
    ready.wait(timeout=10)
    with t.scope("MainPhase"):
        t.block(jnp.arange(16))
    done.set()
    th.join(timeout=10)
    snap = t.snapshot()
    assert "MainPhase::device" in snap and "WorkerPhase::device" in snap
    assert snap["MainPhase::device"][1] == 1
    assert snap["WorkerPhase::device"][1] == 1
