"""Timer/profiling subsystem (ref: utils/common.h:973 Timer/FunctionTimer,
global_timer printed at exit when TIMETAG is on)."""

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.timer import Timer, global_timer


def test_timer_scopes_aggregate():
    t = Timer(enabled=True)
    with t.scope("a"):
        with t.scope("b"):
            pass
    with t.scope("a"):
        pass
    items = dict((k, c) for k, _, c in t.items())
    assert items == {"a": 2, "b": 1}


def test_timer_disabled_is_noop():
    t = Timer(enabled=False)
    with t.scope("a"):
        pass
    assert t.items() == ()


def test_global_timer_instruments_training():
    global_timer.enabled = True
    global_timer.reset()
    try:
        rng = np.random.RandomState(0)
        X = rng.randn(500, 3)
        y = X[:, 0]
        lgb.train({"objective": "regression", "num_leaves": 7,
                   "verbosity": -1}, lgb.Dataset(X, label=y),
                  num_boost_round=2)
        names = {k for k, _, _ in global_timer.items()}
        assert "GBDT::grow_tree" in names
        assert "GBDT::finalize_tree" in names
    finally:
        global_timer.enabled = False
        global_timer.reset()
