"""tpulint static-analysis suite (ISSUE 3 tentpole).

Two layers:

* fixture tests — for every rule, at least one true positive and one
  true negative over a synthetic mini-package, pinning the analysis
  contract (what taints, what is static, what is in scope);
* package tests — the full suite over the real `lightgbm_tpu` tree
  must report ZERO unsuppressed findings (the merge bar), and every
  suppression must carry a justification.

No jax import needed: the lint is pure-AST by design, so this file is
cheap tier-1.
"""

import json
import os
import subprocess
import sys
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tools.tpulint import RULES, run_lint  # noqa: E402

PACKAGE = os.path.join(_REPO, "lightgbm_tpu")


def _mk_pkg(tmp_path, files):
    """Write {relpath: source} under tmp_path/pkg and return its path."""
    pkg = tmp_path / "pkg"
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    # ensure __init__.py files exist so the tree looks like a package
    for root, _dirs, _files in os.walk(pkg):
        init = os.path.join(root, "__init__.py")
        if not os.path.exists(init):
            open(init, "w").close()
    return str(pkg)


def _lint(tmp_path, files, rules):
    return run_lint(_mk_pkg(tmp_path, files), rules=rules)


def _rules_of(report):
    return [(f.path.split(os.sep, 1)[1], f.line, f.rule)
            for f in report.active]


# ------------------------------------------------------------ registry/CLI
def test_registry_has_all_rules():
    from tools.tpulint import rules as _  # noqa: F401
    assert {"no-host-sync-in-jit", "no-tracer-branch", "explicit-dtype",
            "collective-discipline", "no-bare-print", "config-doc-sync",
            "no-device-put-in-loop", "donate-argnums",
            # v2 (ISSUE 6): interprocedural rule families
            "no-dynamic-shape-in-jit", "donated-buffer-reuse",
            "spmd-axis-discipline", "donated-sharding"} <= set(RULES)


def test_cli_json_format_and_exit_codes(tmp_path):
    pkg = _mk_pkg(tmp_path, {"learner/m.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)
        """})
    env = dict(os.environ, PYTHONPATH=_REPO)
    r = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", pkg, "--format=json",
         "--rules=explicit-dtype"],
        capture_output=True, text=True, env=env, cwd=_REPO)
    assert r.returncode == 1, r.stderr
    rep = json.loads(r.stdout)
    assert rep["num_active"] == 1
    assert rep["counts"] == {"explicit-dtype": 1}
    f0 = rep["findings"][0]
    assert f0["rule"] == "explicit-dtype" and f0["line"] == 4
    # clean tree -> exit 0
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", pkg,
         "--rules=no-bare-print"],
        capture_output=True, text=True, env=env, cwd=_REPO)
    assert r2.returncode == 0, r2.stdout


# ------------------------------------------------------------- suppression
def test_suppression_same_line_and_next_line(tmp_path):
    rep = _lint(tmp_path, {"learner/m.py": """
        import jax.numpy as jnp
        def f(n):
            a = jnp.zeros(n)  # tpulint: disable=explicit-dtype -- fixture
            # tpulint: disable-next=explicit-dtype -- fixture
            b = jnp.ones(n)
            c = jnp.full(n, 0)
            return a, b, c
        """}, rules=["explicit-dtype"])
    assert _rules_of(rep) == [("learner/m.py", 7, "explicit-dtype")]
    assert len(rep.suppressed) == 2
    assert all(f.justification == "fixture" for f in rep.suppressed)


def test_suppression_without_justification_is_reported(tmp_path):
    rep = _lint(tmp_path, {"learner/m.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)  # tpulint: disable=explicit-dtype
        """}, rules=["explicit-dtype"])
    assert [f.rule for f in rep.active] == ["bad-suppression"]
    assert len(rep.suppressed) == 1


def test_suppression_only_masks_named_rule(tmp_path):
    rep = _lint(tmp_path, {"m.py": """
        def f():
            print("hi")  # tpulint: disable=explicit-dtype -- wrong rule
        """}, rules=["no-bare-print"])
    assert [f.rule for f in rep.active] == ["no-bare-print"]


# ----------------------------------------------------------- explicit-dtype
def test_explicit_dtype_positives_and_negatives(tmp_path):
    rep = _lint(tmp_path, {
        "ops/dev.py": """
        import jax.numpy as jnp
        def f(n):
            bad1 = jnp.zeros(n)
            bad2 = jnp.arange(n)
            bad3 = jnp.full((n, 2), 0.0)
            ok1 = jnp.zeros(n, jnp.float32)     # positional dtype
            ok2 = jnp.arange(n, dtype=jnp.int32)
            ok3 = jnp.full((n, 2), 0.0, jnp.float32)
            ok4 = jnp.where(ok1 > 0, 1.0, 0.0)  # not a constructor
            return bad1, bad2, bad3, ok2, ok3, ok4
        """,
        # host-side module: out of scope by design
        "host.py": """
        import jax.numpy as jnp
        def g(n):
            return jnp.zeros(n)
        """}, rules=["explicit-dtype"])
    assert _rules_of(rep) == [("ops/dev.py", 4, "explicit-dtype"),
                              ("ops/dev.py", 5, "explicit-dtype"),
                              ("ops/dev.py", 6, "explicit-dtype")]


def test_explicit_dtype_covers_inference(tmp_path):
    rep = _lint(tmp_path, {"inference/t.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)
        """}, rules=["explicit-dtype"])
    assert _rules_of(rep) == [("inference/t.py", 4, "explicit-dtype")]


def test_explicit_dtype_covers_serving(tmp_path):
    """ISSUE 10: serving/ coalesces request buckets into jitted
    dispatches, so it is device-code scope (explicit-dtype and
    no-device-put-in-loop both key off the same scope list)."""
    rep = _lint(tmp_path, {"serving/s.py": """
        import jax
        import jax.numpy as jnp
        def pad(reqs, n):
            buf = jnp.zeros(n)                      # flagged: no dtype
            for r in reqs:
                x = jax.device_put(r)               # flagged: put in loop
            return buf, x
        """}, rules=["explicit-dtype", "no-device-put-in-loop"])
    assert _rules_of(rep) == [
        ("serving/s.py", 5, "explicit-dtype"),
        ("serving/s.py", 7, "no-device-put-in-loop")]


# ------------------------------------------------- no-device-put-in-loop
def test_no_device_put_in_loop(tmp_path):
    rep = _lint(tmp_path, {
        "inference/b.py": """
        import jax
        import jax.numpy as jnp
        def bad(batches):
            out = []
            for b in batches:
                out.append(jax.device_put(b))       # flagged
            i = 0
            while i < 3:
                x = jnp.asarray(batches[i])         # flagged
                i += 1
            return out, x
        def ok(batches):
            big = jnp.asarray(batches)              # one transfer, no loop
            return [b * 2 for b in big]
        def ok_comprehension(parts):
            # comprehensions converting scalars are the benign form
            return tuple(jnp.asarray(p) for p in parts)
        """,
        # host-side module: out of scope by design
        "metric.py": """
        import jax.numpy as jnp
        def g(vals):
            out = []
            for v in vals:
                out.append(jnp.asarray(v))
            return out
        """}, rules=["no-device-put-in-loop"])
    assert _rules_of(rep) == [
        ("inference/b.py", 7, "no-device-put-in-loop"),
        ("inference/b.py", 10, "no-device-put-in-loop")]


def test_no_device_put_in_loop_suppression(tmp_path):
    rep = _lint(tmp_path, {"learner/m.py": """
        import jax
        def f(bs):
            for b in bs:
                x = jax.device_put(b)  # tpulint: disable=no-device-put-in-loop -- fixture
            return x
        """}, rules=["no-device-put-in-loop"])
    assert not rep.active
    assert len(rep.suppressed) == 1


# --------------------------------------------------------- donate-argnums
def test_donate_argnums_positives_and_negatives(tmp_path):
    rep = _lint(tmp_path, {"boosting/u.py": """
        import functools
        import jax

        @jax.jit
        def bad_update(scores, delta):              # flagged (line 5)
            return scores + delta

        @functools.partial(jax.jit, static_argnames=("k",))
        def bad_grow(binned, grad, hess, k):        # flagged (line 9)
            return grad * hess

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def ok_grow(binned, grad, hess):            # covered
            return grad * hess

        @jax.jit
        def ok_names(sc, g, h):                     # not canonical names
            return sc + g * h

        def upd(scores, delta):
            return scores + delta
        bad_assign = jax.jit(upd)                   # flagged (line 23)
        ok_assign = jax.jit(upd, donate_argnums=(0,))
        ok_named = jax.jit(upd, donate_argnames=("scores",))
        _gate = (0,)
        ok_gated = jax.jit(upd, donate_argnums=_gate)   # config-gated
        """}, rules=["donate-argnums"])
    assert _rules_of(rep) == [
        ("boosting/u.py", 5, "donate-argnums"),
        ("boosting/u.py", 9, "donate-argnums"),
        ("boosting/u.py", 23, "donate-argnums")]


def test_donate_argnums_suppression(tmp_path):
    rep = _lint(tmp_path, {"boosting/v.py": """
        import jax

        def eval_fn(scores):
            return scores.sum()
        # tpulint: disable-next=donate-argnums -- read-only eval, caller keeps the buffer
        jitted = jax.jit(eval_fn)
        """}, rules=["donate-argnums"])
    assert not rep.active
    assert len(rep.suppressed) == 1


# ----------------------------------------------------- collective-discipline
def test_collective_discipline(tmp_path):
    rep = _lint(tmp_path, {
        "learner/eng.py": """
        import jax
        def f(x, axis):
            return jax.lax.psum(x, axis)
        """,
        "parallel/dp.py": """
        import jax
        from jax import lax
        def g(x, axis):
            return lax.pmean(jax.lax.all_gather(x, axis), axis)
        """,
        "distributed.py": """
        import jax
        def h(x, axis):
            return jax.lax.psum(x, axis)
        """}, rules=["collective-discipline"])
    assert _rules_of(rep) == [("learner/eng.py", 4,
                               "collective-discipline")]


# ------------------------------------------------------------ no-bare-print
def test_no_bare_print(tmp_path):
    rep = _lint(tmp_path, {
        "boost.py": """
        from .utils import log
        def f():
            print("bad")
            log.info("ok")
        """,
        "utils/log.py": """
        def info(msg):
            print(msg)   # the whitelisted default sink
        """}, rules=["no-bare-print"])
    assert _rules_of(rep) == [("boost.py", 4, "no-bare-print")]


def test_no_bare_print_clean_on_real_package():
    rep = run_lint(PACKAGE, rules=["no-bare-print"])
    assert rep.active == [], [f.render() for f in rep.active]


# ------------------------------------------------------- no-host-sync-in-jit
_JIT_PKG = {
    "learner/mod.py": """
    import functools
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ..ops.helper import downstream

    @functools.partial(jax.jit, static_argnames=("cfg",))
    def entry(x, y, cfg):
        z = x * 2 + jnp.sum(y)
        f = float(z)                  # BAD: host sync
        a = np.asarray(x)             # BAD: host sync
        i = z.item()                  # BAD: host sync
        w = z.block_until_ready()     # BAD: host sync
        n = x.shape[0]
        ok1 = int(n)                  # ok: shape is static
        ok2 = jnp.asarray(y)          # ok: device-side
        ok3 = float(cfg.lr)           # ok: static param
        downstream(z, 3)
        return z

    def host_fn(a):
        return float(a)               # ok: not jit-reachable
    """,
    "ops/helper.py": """
    def downstream(v, k):
        bad = bool(v)                 # BAD: tainted via call graph
        ok = int(k)                   # ok: untainted arg at call site
        return bad, ok
    """,
}


def test_no_host_sync_in_jit(tmp_path):
    rep = _lint(tmp_path, dict(_JIT_PKG), rules=["no-host-sync-in-jit"])
    got = _rules_of(rep)
    assert ("learner/mod.py", 11, "no-host-sync-in-jit") in got  # float
    assert ("learner/mod.py", 12, "no-host-sync-in-jit") in got  # asarray
    assert ("learner/mod.py", 13, "no-host-sync-in-jit") in got  # .item
    assert ("learner/mod.py", 14, "no-host-sync-in-jit") in got  # block
    assert ("ops/helper.py", 3, "no-host-sync-in-jit") in got    # callee
    # and nothing else: the ok/host_fn lines are all clean
    assert len(got) == 5, got


# --------------------------------------------------------- no-tracer-branch
def test_no_tracer_branch(tmp_path):
    rep = _lint(tmp_path, {"learner/mod.py": """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("params",))
        def entry(x, y, params):
            z = jnp.sum(x)
            if z > 0:                   # BAD
                pass
            while y.sum() > 0:          # BAD (method call on tracer)
                break
            assert x[0] > 0             # BAD
            t = 1 if z > 0 else 2       # BAD ternary
            if params.max_depth > 0:    # ok: static param
                pass
            if x.shape[0] > 4:          # ok: shape
                pass
            if x is None:               # ok: identity test
                pass
            if params.forced:
                for k, s in enumerate(params.forced):
                    if k > 3:           # ok: python loop over statics
                        break

            def body(i, carry):
                if carry > 0:           # BAD: fori_loop carry is traced
                    return carry
                return carry + i
            return jax.lax.fori_loop(0, 3, body, z), t
        """}, rules=["no-tracer-branch"])
    lines = [ln for _, ln, _ in _rules_of(rep)]
    assert lines == [9, 11, 13, 14, 27], _rules_of(rep)


def test_jit_assignment_form_and_static_argnums(tmp_path):
    rep = _lint(tmp_path, {"learner/mod.py": """
        import jax

        def raw(x, k):
            if k > 0:       # ok: static_argnums=1
                pass
            if (x > 0).any():   # BAD
                pass
            return x

        fn = jax.jit(raw, static_argnums=(1,))
        """}, rules=["no-tracer-branch"])
    assert [ln for _, ln, _ in _rules_of(rep)] == [7]


# ---------------------------------------------------------- config-doc-sync
_CONFIG = """
PARAMS = [
    ("alpha", "float", 1.0, ()),
    ("beta", "int", 2, ("b",)),
]
"""


def _doc(tmp_path, rows):
    d = tmp_path / "docs"
    d.mkdir(exist_ok=True)
    body = "| Parameter | Type | Default | Aliases |\n|---|---|---|---|\n"
    body += "\n".join(f"| `{r}` | x | `0` | — |" for r in rows) + "\n"
    (d / "Parameters.md").write_text("# Parameters\n\n" + body)


def test_config_doc_sync(tmp_path):
    pkg = _mk_pkg(tmp_path, {"config.py": _CONFIG})
    _doc(tmp_path, ["alpha", "beta"])
    assert run_lint(pkg, rules=["config-doc-sync"]).active == []
    _doc(tmp_path, ["alpha", "gamma"])   # beta undocumented, gamma stale
    rep = run_lint(pkg, rules=["config-doc-sync"])
    msgs = sorted(f.message for f in rep.active)
    assert len(msgs) == 2
    assert "`beta`" in msgs[0] and "not documented" in msgs[0]
    assert "`gamma`" in msgs[1] and "stale" in msgs[1]


def test_config_doc_sync_missing_doc(tmp_path):
    pkg = _mk_pkg(tmp_path, {"config.py": _CONFIG})
    rep = run_lint(pkg, rules=["config-doc-sync"])
    assert [f.rule for f in rep.active] == ["config-doc-sync"]
    assert "missing" in rep.active[0].message


# ------------------------------------------------------------- package-wide
def test_package_is_clean():
    """The merge bar: zero unsuppressed findings over lightgbm_tpu with
    ALL rules enabled (acceptance: `python -m tools.tpulint lightgbm_tpu`
    exits 0)."""
    rep = run_lint(PACKAGE)
    assert rep.active == [], "\n".join(f.render() for f in rep.active)


def test_package_suppressions_are_justified():
    rep = run_lint(PACKAGE)
    for f in rep.suppressed:
        assert f.justification, f.render()


def test_package_finds_jit_roots():
    """Sanity: the call-graph analysis actually sees the engine's jit
    entry points (an empty reachable set would make the two taint rules
    vacuously green)."""
    from tools.tpulint.callgraph import PackageIndex, build_reachable
    from tools.tpulint.core import LintContext
    funcs = build_reachable(PackageIndex(LintContext(PACKAGE)))
    names = {f.qualname for f in funcs}
    assert {"grow_tree_impl", "grow_tree_wave_impl", "find_best_split",
            "build_histogram"} <= names
    roots = {f.qualname for f in funcs if f.jit_root}
    # the impls are rooted through BOTH jit entries (plain and donated)
    assert {"grow_tree_impl", "grow_tree_wave_impl"} <= roots
    # static_argnames honored on the engine entry points
    by_name = {f.qualname: f for f in funcs}
    assert "params" in by_name["grow_tree_impl"].static_params
    assert "params" not in by_name["grow_tree_impl"].tainted_params
    assert "binned" in by_name["grow_tree_impl"].tainted_params


# ===================================================== v2: call graph
def test_taint_flows_through_method_call(tmp_path):
    """Acceptance: jit-taint must flow through a self.method() call —
    the class-hierarchy resolution of callgraph v2."""
    rep = _lint(tmp_path, {"learner/eng.py": """
        import jax

        class Engine:
            def helper(self, v, k):
                bad = float(v)          # BAD: v tainted via self.helper
                ok = int(k)             # ok: literal at the call site
                return bad, ok

            @jax.jit
            def run(self, x):
                return self.helper(x * 2, 3)

            def host(self, y):
                return float(y)         # ok: not jit-reachable
        """}, rules=["no-host-sync-in-jit"])
    assert _rules_of(rep) == [("learner/eng.py", 6, "no-host-sync-in-jit")]


def test_taint_flows_through_inherited_method(tmp_path):
    rep = _lint(tmp_path, {"learner/eng.py": """
        import jax

        class Base:
            def helper(self, v):
                return v.item()          # BAD: reached from Child.run

        class Child(Base):
            @jax.jit
            def run(self, x):
                return self.helper(x)
        """}, rules=["no-host-sync-in-jit"])
    assert _rules_of(rep) == [("learner/eng.py", 6, "no-host-sync-in-jit")]


def test_taint_flows_through_dict_dispatch(tmp_path):
    """Acceptance: jit-taint must flow through a dict-dispatched entry
    (the jit-entry-table shape the boosting loop uses)."""
    rep = _lint(tmp_path, {"ops/table.py": """
        import jax

        def impl_a(x):
            return float(x)             # BAD: dispatched with traced x
        def impl_b(x):
            return x * 2                # ok
        TABLE = {"a": impl_a, "b": impl_b}

        @jax.jit
        def entry(x):
            return TABLE["a"](x)
        """}, rules=["no-host-sync-in-jit"])
    assert _rules_of(rep) == [("ops/table.py", 5, "no-host-sync-in-jit")]


def test_taint_flows_through_function_argument(tmp_path):
    """A function reference passed as an argument is called inside the
    callee — the higher-order edge of callgraph v2."""
    rep = _lint(tmp_path, {"ops/hof.py": """
        import jax

        def apply(fn, v):
            return fn(v)

        def helper(v):
            return bool(v)              # BAD: bound via apply(helper, x)

        @jax.jit
        def entry(x):
            return apply(helper, x)
        """}, rules=["no-host-sync-in-jit"])
    assert _rules_of(rep) == [("ops/hof.py", 8, "no-host-sync-in-jit")]


def test_taint_flows_through_attr_binding_and_reexport(tmp_path):
    """self._fn = jax.jit(work) where `work` arrives through a package
    __init__ re-export: the binding + import-chain resolution."""
    rep = _lint(tmp_path, {
        "learner/impl.py": """
        def work(v):
            return v.tolist()           # BAD: jit-rooted via the attr
        """,
        "learner/__init__.py": """
        from .impl import work
        """,
        "boosting/g.py": """
        import jax
        from ..learner import work

        class G:
            def __init__(self):
                self._fn = jax.jit(work)
        """}, rules=["no-host-sync-in-jit"])
    assert _rules_of(rep) == [("learner/impl.py", 3,
                               "no-host-sync-in-jit")]


def test_tracer_branch_through_method(tmp_path):
    rep = _lint(tmp_path, {"learner/m.py": """
        import jax

        class T:
            def decide(self, v):
                if v > 0:               # BAD: tracer branch via method
                    return 1
                return 0

            @jax.jit
            def run(self, x):
                return self.decide(x)
        """}, rules=["no-tracer-branch"])
    assert _rules_of(rep) == [("learner/m.py", 6, "no-tracer-branch")]


# ======================================== v2: no-dynamic-shape-in-jit
def test_dynamic_shape_positives(tmp_path):
    rep = _lint(tmp_path, {"learner/d.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def entry(x, idx):
            nz = jnp.nonzero(x)             # BAD: no size=
            u = jnp.unique(x)               # BAD: no size=
            w = jnp.where(x > 0)            # BAD: 1-arg where
            m = x[x > 0]                    # BAD: boolean mask index
            r = jnp.repeat(x, idx)          # BAD: traced repeats
            z = jnp.zeros(idx)              # BAD: traced shape arg
            return nz, u, w, m, r, z
        """}, rules=["no-dynamic-shape-in-jit"])
    lines = [ln for _, ln, _ in _rules_of(rep)]
    assert lines == [7, 8, 9, 10, 11, 12], _rules_of(rep)


def test_dynamic_shape_negatives(tmp_path):
    rep = _lint(tmp_path, {"learner/ok.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def entry(x):
            nz = jnp.nonzero(x, size=8)         # size given
            w3 = jnp.where(x > 0, x, 0.0)       # 3-arg select
            fl = x.reshape(-1)                  # static geometry
            z = jnp.zeros(x.shape[0], jnp.float32)  # shape is static
            g = x[jnp.argmax(x)]                # int index, not a mask
            r = jnp.repeat(x, 3)                # constant repeats
            return nz, w3, fl, z, g, r

        def host(mask, vals):
            return vals[mask > 0]               # ok: not jit-reachable
        """}, rules=["no-dynamic-shape-in-jit"])
    assert _rules_of(rep) == [], _rules_of(rep)


def test_dynamic_shape_bool_name_is_scoped(tmp_path):
    """A bool-mask name in one nested function must not poison an
    integer index of the same name in a sibling scope (the grow.py
    `pos` false positive the scope-keyed _BoolNames fixes)."""
    rep = _lint(tmp_path, {"learner/s.py": """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def entry(x):
            def a(v):
                pos = v > 0                       # bool here
                return jnp.where(pos, v, 0.0)
            def b(v):
                pos = jnp.where(v > 0, 1, 0).cumsum() - 1
                return v.at[pos].set(v)           # int index: clean
            return a(x) + b(x)
        """}, rules=["no-dynamic-shape-in-jit"])
    assert _rules_of(rep) == [], _rules_of(rep)


def test_dynamic_shape_static_param_is_clean(tmp_path):
    rep = _lint(tmp_path, {"learner/st.py": """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n",))
        def entry(x, n):
            return jnp.zeros(n, jnp.float32) + x  # static shape param
        """}, rules=["no-dynamic-shape-in-jit"])
    assert _rules_of(rep) == []


# ========================================== v2: donated-buffer-reuse
def test_donated_reuse_read_after_donate(tmp_path):
    rep = _lint(tmp_path, {"boosting/u.py": """
        import jax

        def upd(scores, delta):
            return scores + delta
        donated = jax.jit(upd, donate_argnums=(0,))

        def bad_loop(scores, deltas):
            out = donated(scores, deltas)
            return scores.sum() + out.sum()      # BAD: scores donated

        def ok_rebind(scores, deltas):
            scores = donated(scores, deltas)     # donate-and-rebind
            return scores.sum()
        """}, rules=["donated-buffer-reuse"])
    assert _rules_of(rep) == [("boosting/u.py", 10,
                               "donated-buffer-reuse")]


def test_donated_reuse_alias_tracking(tmp_path):
    """gq, hq = g_k, h_k then donating gq consumes g_k too — the exact
    gbdt.py float_grads hazard the sweep fixed."""
    rep = _lint(tmp_path, {"boosting/a.py": """
        import jax

        def grow(binned, grad, hess):
            return binned
        grow_donated = jax.jit(grow, donate_argnums=(1, 2))

        def train(binned, g_k, h_k):
            gq, hq = g_k, h_k
            out = grow_donated(binned, gq, hq)
            return out, (g_k, h_k)               # BAD x2: aliases died

        def train_ok(binned, g_k, h_k):
            snap = (g_k, h_k)                    # read BEFORE donation
            gq, hq = g_k, h_k
            out = grow_donated(binned, gq, hq)
            return out, snap
        """}, rules=["donated-buffer-reuse"])
    assert [(p, ln) for p, ln, _ in _rules_of(rep)] == [
        ("boosting/a.py", 11), ("boosting/a.py", 11)]


def test_donated_reuse_self_attr_entry(tmp_path):
    """Donated entries bound to self attributes — including the
    config-gated spec and a wrapper rebind — are resolved at call
    sites; the idiomatic self.scores = self._fn(self.scores) is clean."""
    rep = _lint(tmp_path, {"boosting/c.py": """
        import jax

        class Wrap:
            def __init__(self, fn, tag):
                self.fn = fn

        class G:
            def __init__(self, cfg):
                def upd(scores, v):
                    return scores + v
                _donate0 = (0,) if cfg else ()
                self._fn = jax.jit(upd, donate_argnums=_donate0)
                self._fn = Wrap(self._fn, "tag")

            def ok(self, v):
                self.scores = self._fn(self.scores, v)
                return self.scores

            def bad(self, v):
                out = self._fn(self.scores, v)   # donates self.scores
                return self.scores + out          # BAD
        """}, rules=["donated-buffer-reuse"])
    assert _rules_of(rep) == [("boosting/c.py", 22,
                               "donated-buffer-reuse")]


def test_donated_reuse_branch_merge_and_suppression(tmp_path):
    rep = _lint(tmp_path, {"boosting/b.py": """
        import jax

        def upd(scores, v):
            return scores + v
        donated = jax.jit(upd, donate_argnames=("scores",))

        def branchy(scores, v, flag):
            if flag:
                out = donated(scores, v)
            else:
                out = scores * 2
            return scores + out                  # BAD: either branch

        def suppressed(scores, v):
            out = donated(scores, v)
            # tpulint: disable-next=donated-buffer-reuse -- fixture: donation is off in this config
            return scores + out
        """}, rules=["donated-buffer-reuse"])
    assert _rules_of(rep) == [("boosting/b.py", 13,
                               "donated-buffer-reuse")]
    assert len(rep.suppressed) == 1


# ========================================= v2: spmd-axis-discipline
_SPMD_BASE = {
    "parallel/mesh.py": """
    import numpy as np
    import jax
    from jax.sharding import Mesh

    DATA_AXIS = "data"

    def make_mesh(devices):
        return Mesh(np.array(devices), (DATA_AXIS,))
    """,
}


def test_spmd_axis_name_mismatch(tmp_path):
    files = dict(_SPMD_BASE)
    files["parallel/dp.py"] = """
        import jax
        from .compat import shard_map
        from jax.sharding import PartitionSpec as P

        def reduce_local(x):
            return jax.lax.psum(x, "nodes")      # BAD: undeclared axis

        def run(mesh, x):
            return shard_map(reduce_local, mesh=mesh,
                             in_specs=(P("data"),), out_specs=P())(x)
        """
    files["parallel/compat.py"] = """
        def shard_map(f, mesh, in_specs, out_specs):
            return f
        """
    rep = _lint(tmp_path, files, rules=["spmd-axis-discipline"])
    assert [(p, ln) for p, ln, _ in _rules_of(rep)] == [
        ("parallel/dp.py", 7)]
    assert "nodes" in rep.active[0].message


def test_spmd_partition_spec_axis_checked(tmp_path):
    files = dict(_SPMD_BASE)
    files["parallel/sp.py"] = """
        from jax.sharding import PartitionSpec as P

        GOOD = P(None, "data")
        BAD = P("rows")                          # BAD: undeclared axis
        """
    rep = _lint(tmp_path, files, rules=["spmd-axis-discipline"])
    assert [(p, ln) for p, ln, _ in _rules_of(rep)] == [
        ("parallel/sp.py", 5)]


def test_spmd_collective_needs_shard_map(tmp_path):
    files = dict(_SPMD_BASE)
    files["parallel/loose.py"] = """
        import jax

        def stray(x):
            return jax.lax.psum(x, "data")       # BAD: no shard_map
        """
    rep = _lint(tmp_path, files, rules=["spmd-axis-discipline"])
    assert [(p, ln) for p, ln, _ in _rules_of(rep)] == [
        ("parallel/loose.py", 5)]


def test_spmd_collective_reachable_from_shard_map_is_clean(tmp_path):
    """The wave-engine shape: the psum lives two calls away from the
    shard_map wrapper, connected only through the v2 call graph."""
    files = dict(_SPMD_BASE)
    files["learner/engine.py"] = """
        import jax

        def _psum(x, axis):
            return jax.lax.psum(x, "data")       # ok: reachable

        def grow_impl(x):
            return _psum(x, "data")
        """
    files["parallel/dp.py"] = """
        from ..learner.engine import grow_impl
        from .compat import shard_map

        def make_fn(mesh):
            def inner(x):
                return grow_impl(x)
            return shard_map(inner, mesh=mesh, in_specs=(),
                             out_specs=())
        """
    files["parallel/compat.py"] = """
        def shard_map(f, mesh, in_specs, out_specs):
            return f
        """
    rep = _lint(tmp_path, files, rules=["spmd-axis-discipline"])
    assert _rules_of(rep) == [], _rules_of(rep)


# ============================================== v2: donated-sharding
def test_donated_sharding_positive_and_negative(tmp_path):
    rep = _lint(tmp_path, {
        "parallel/compat.py": """
        def shard_map(f, mesh, in_specs, out_specs):
            return f
        """,
        "parallel/d.py": """
        import jax
        from .compat import shard_map

        def build(mesh, inner, specs, donate):
            mapped = shard_map(inner, mesh=mesh, in_specs=specs,
                               out_specs=specs)
            bad = jax.jit(mapped, donate_argnums=(1, 2))      # BAD
            bad2 = jax.jit(
                shard_map(inner, mesh=mesh, in_specs=specs,
                          out_specs=specs),
                donate_argnums=(1, 2) if donate else ())      # BAD (gated)
            ok = jax.jit(mapped, in_shardings=specs,
                         donate_argnums=(1, 2))
            ok2 = jax.jit(mapped, donate_argnums=())
            ok3 = jax.jit(mapped)
            return bad, bad2, ok, ok2, ok3
        """}, rules=["donated-sharding"])
    assert [(p, ln) for p, ln, _ in _rules_of(rep)] == [
        ("parallel/d.py", 8), ("parallel/d.py", 9)]


# ============================================ v2: CLI baseline/github
def _run_cli(args, cwd=_REPO):
    env = dict(os.environ, PYTHONPATH=_REPO)
    return subprocess.run(
        [sys.executable, "-m", "tools.tpulint"] + args,
        capture_output=True, text=True, env=env, cwd=cwd)


def test_cli_baseline_accepts_legacy_fails_new(tmp_path):
    pkg = _mk_pkg(tmp_path, {"learner/m.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)
        """})
    base = str(tmp_path / "base.json")
    r = _run_cli([pkg, "--rules=explicit-dtype", "--no-cache",
                  f"--write-baseline={base}"])
    assert r.returncode == 0, r.stderr
    assert json.load(open(base))["counts"] == {
        f"explicit-dtype|{os.path.join('pkg', 'learner', 'm.py')}": 1}
    # legacy finding accepted -> exit 0
    r = _run_cli([pkg, "--rules=explicit-dtype", "--no-cache",
                  f"--baseline={base}"])
    assert r.returncode == 0, r.stdout
    assert "0 new finding(s), 1 accepted by baseline" in r.stdout
    # a NEW finding -> exit 1, github annotation names it
    with open(os.path.join(pkg, "learner", "m.py"), "a") as f:
        f.write("def g(n):\n    return jnp.ones(n)\n")
    r = _run_cli([pkg, "--rules=explicit-dtype", "--no-cache",
                  f"--baseline={base}", "--format=github"])
    assert r.returncode == 1, r.stdout
    lines = [ln for ln in r.stdout.splitlines()
             if ln.startswith("::error ")]
    assert len(lines) == 1 and "line=6" in lines[0] \
        and "explicit-dtype" in lines[0], r.stdout


def test_cli_list_suppressions(tmp_path):
    pkg = _mk_pkg(tmp_path, {"m.py": """
        def f():
            print("x")  # tpulint: disable=no-bare-print -- fixture reason
        """})
    r = _run_cli([pkg, "--list-suppressions"])
    assert r.returncode == 0
    assert "fixture reason" in r.stdout
    assert "1 suppression(s)" in r.stdout


def test_cache_warm_run_matches_and_invalidates(tmp_path):
    pkg = _mk_pkg(tmp_path, {"learner/m.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)
        """})
    cache = os.path.join(os.path.dirname(pkg), ".tpulint_cache.json")
    cold = run_lint(pkg, rules=["explicit-dtype"], cache_path=cache)
    assert os.path.exists(cache)
    warm = run_lint(pkg, rules=["explicit-dtype"], cache_path=cache)
    assert [f.to_dict() for f in warm.findings] == \
        [f.to_dict() for f in cold.findings]
    # edit the file: the cache must notice and re-analyze
    p = os.path.join(pkg, "learner", "m.py")
    src = open(p).read()
    with open(p, "w") as f:
        f.write(src + "def g(n):\n    return jnp.ones(n)\n")
    os.utime(p, (os.path.getmtime(p) + 2, os.path.getmtime(p) + 2))
    after = run_lint(pkg, rules=["explicit-dtype"], cache_path=cache)
    assert len(after.active) == len(cold.active) + 1


def test_package_clean_under_all_new_rules():
    """The four ISSUE-6 rule families individually report zero
    unsuppressed findings on the real package (the sweep fixed the
    true positives: gbdt.py float_grads-after-donate for
    donated-buffer-reuse, data_parallel.py donate-without-shardings
    for donated-sharding)."""
    for rule in ("no-dynamic-shape-in-jit", "donated-buffer-reuse",
                 "spmd-axis-discipline", "donated-sharding"):
        rep = run_lint(PACKAGE, rules=[rule])
        assert rep.active == [], (rule, [f.render()
                                         for f in rep.active])


# ===================================================== v3 (ISSUE 9)
def test_registry_has_v3_rules():
    from tools.tpulint import rules as _  # noqa: F401
    assert {"signal-handler-safety", "thread-shared-state",
            "rng-stream-discipline", "atomic-write-discipline"} <= set(RULES)


# ------------------------------------------- signal-handler-safety
_SIGNAL_PKG = {
    "observability/w.py": """
    import queue
    import signal
    import threading

    import jax.numpy as jnp

    class Writer:
        def __init__(self):
            self._q = queue.Queue(maxsize=4)
            self._lock = threading.Lock()

        def submit(self, item):
            self._q.put(item)                   # BAD: blocking put

        def submit_bounded(self, item):
            self._q.put(item, timeout=2.0)      # ok: bounded

        def drop(self, item):
            self._q.put(item, block=False)      # ok: non-blocking

        def locked(self):
            with self._lock:                    # BAD: with <lock>
                return 1

    W = Writer()

    def _handler(signum, frame):
        W.submit("bye")
        W.submit_bounded("bye")
        W.drop("bye")
        W.locked()
        jnp.sum(jnp.zeros(3, jnp.float32))      # BAD: jax dispatch

    def install():
        signal.signal(signal.SIGTERM, _handler)

    def host_side(q2):
        q2.put(1)                               # ok: not handler-reachable
    """,
}


def test_signal_handler_safety_fixture(tmp_path):
    rep = _lint(tmp_path, dict(_SIGNAL_PKG),
                rules=["signal-handler-safety"])
    got = _rules_of(rep)
    lines = sorted(ln for _, ln, _ in got)
    # blocking put (14), with-lock (23), jax dispatch x2 on line 33
    # (jnp.sum + inner jnp.zeros)
    assert 14 in lines and 23 in lines and 33 in lines, got
    assert all(p == "observability/w.py" for p, _, _ in got)
    # bounded put / non-blocking put / host-side put stay clean
    assert 17 not in lines and 20 not in lines and 40 not in lines, got


def test_signal_handler_safety_watchdog_exit_path(tmp_path):
    rep = _lint(tmp_path, {"reliability/g.py": """
        import os
        import queue
        import threading

        q = queue.Queue(maxsize=8)

        def _exit_path():
            q.put("diagnosis")                  # BAD: exit-path put
            os._exit(86)

        def _watch():
            _exit_path()

        def start():
            threading.Thread(target=_watch, daemon=True).start()

        def plain_thread_put():
            q.put("fine")                       # ok: ordinary thread work
        """}, rules=["signal-handler-safety"])
    assert [(p, ln) for p, ln, _ in _rules_of(rep)] == [
        ("reliability/g.py", 9)]


# --------------------------------------------- thread-shared-state
def test_thread_shared_state_fixture(tmp_path):
    rep = _lint(tmp_path, {"reliability/g.py": """
        import threading

        class Guard:
            def __init__(self):
                self._lock = threading.Lock()
                self._last = None
                self._safe = None
                self._cfg = 7                  # init-only: clean

            def start(self):
                self._pre = 1                  # pre-start write: clean
                t = threading.Thread(target=self._watch)
                t.start()

            def tick(self, v):
                self._last = v                 # BAD: unlocked vs _watch
                with self._lock:
                    self._safe = v             # ok: locked both sides

            def _watch(self):
                a = self._last
                with self._lock:
                    b = self._safe
                c = self._pre
                d = self._cfg
                return a, b, c, d
        """}, rules=["thread-shared-state"])
    got = _rules_of(rep)
    assert [(p, ln) for p, ln, _ in got] == [("reliability/g.py", 17)]
    assert "_last" in rep.active[0].message


def test_thread_shared_state_global_and_suppression(tmp_path):
    rep = _lint(tmp_path, {"observability/h.py": """
        import signal

        _hook = None
        _quiet = None

        def set_hook(fn):
            global _hook
            _hook = fn                          # BAD: handler reads it

        def set_quiet(fn):
            global _quiet
            # tpulint: disable-next=thread-shared-state -- fixture: atomic pointer swap
            _quiet = fn

        def _h(signum, frame):
            if _hook is not None:
                _hook()
            if _quiet is not None:
                _quiet()

        def install():
            signal.signal(signal.SIGTERM, _h)
        """}, rules=["thread-shared-state"])
    assert [(p, ln) for p, ln, _ in _rules_of(rep)] == [
        ("observability/h.py", 9)]
    assert len(rep.suppressed) == 1


def test_thread_shared_state_same_function_race(tmp_path):
    """A method reachable from BOTH the submit()-deferred thread side
    and main races with itself — the CheckpointManager._write shape."""
    rep = _lint(tmp_path, {"reliability/c.py": """
        class Mgr:
            def __init__(self, writer):
                self.writer = writer
                self._gens = []

            def save_async(self, item):
                self.writer.submit(self._write, item)

            def save_now(self, item):
                self._write(item)

            def _write(self, item):
                self._gens = self._gens + [item]   # BAD: RMW races
        """}, rules=["thread-shared-state"])
    assert [(p, ln) for p, ln, _ in _rules_of(rep)] == [
        ("reliability/c.py", 14)]


# ------------------------------------------- rng-stream-discipline
def test_rng_key_reuse_and_np_module_state(tmp_path):
    rep = _lint(tmp_path, {"boosting/r.py": """
        import jax
        import numpy as np

        def reuse(seed):
            k = jax.random.PRNGKey(seed)
            a = jax.random.normal(k, (3,))
            b = jax.random.uniform(k, (3,))      # BAD: k consumed twice
            return a, b

        def ok_split(seed):
            k = jax.random.PRNGKey(seed)
            k, sub = jax.random.split(k)         # consume + rebind: ok
            a = jax.random.normal(sub, (3,))
            u = jax.random.uniform(jax.random.fold_in(k, 1), (3,))
            return a, u

        def bad_np():
            np.random.seed(0)                    # BAD: module state
            return np.random.rand(3)             # BAD: module state

        def ok_np(seed):
            rng = np.random.RandomState(seed)    # instance stream: ok
            return rng.rand(3)
        """}, rules=["rng-stream-discipline"])
    assert [(p, ln) for p, ln, _ in _rules_of(rep)] == [
        ("boosting/r.py", 8), ("boosting/r.py", 19),
        ("boosting/r.py", 20)]


def test_rng_loop_discipline(tmp_path):
    rep = _lint(tmp_path, {"boosting/l.py": """
        import jax

        def bad_loop_reuse(seed, n):
            key = jax.random.PRNGKey(seed)
            for i in range(n):
                x = jax.random.normal(key, ())   # BAD: same key each pass
            return x

        def ok_fold_loop(seed, n):
            key = jax.random.PRNGKey(seed)
            out = 0.0
            for i in range(n):
                out += jax.random.normal(jax.random.fold_in(key, i), ())
            return out

        def bad_ctor_loop(seed, n):
            for i in range(n):
                k = jax.random.PRNGKey(seed)     # BAD: loop-invariant seed
                v = jax.random.normal(k, ())
            return v

        def ok_ctor_loop(seed, n):
            for it in range(n):
                k = jax.random.PRNGKey(seed + it)  # keyed by iteration: ok
                v = jax.random.normal(k, ())
            return v
        """}, rules=["rng-stream-discipline"])
    got = [(p, ln) for p, ln, _ in _rules_of(rep)]
    assert ("boosting/l.py", 7) in got, got
    assert ("boosting/l.py", 19) in got, got
    assert len(got) == 2, got
    assert "loop iteration" in rep.active[0].message


# ----------------------------------------- atomic-write-discipline
def test_atomic_write_discipline(tmp_path):
    rep = _lint(tmp_path, {
        "reliability/w.py": """
        import os

        def bad(path):
            with open(path, "w") as f:          # BAD: direct write
                f.write("x")

        def ok_append(path):
            with open(path, "a") as f:          # append-only log: ok
                f.write("x")

        def ok_read(path):
            with open(path) as f:               # read: ok
                return f.read()

        def ok_inline(path, payload):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:           # inline atomic idiom: ok
                f.write(payload)
            os.replace(tmp, path)
        """,
        "io/h.py": """
        def host(path):
            with open(path, "w") as f:          # outside reliability/: ok
                f.write("x")
        """}, rules=["atomic-write-discipline"])
    assert [(p, ln) for p, ln, _ in _rules_of(rep)] == [
        ("reliability/w.py", 5)]


def test_atomic_write_suppression(tmp_path):
    rep = _lint(tmp_path, {"reliability/f.py": """
        def corrupt(path):
            # tpulint: disable-next=atomic-write-discipline -- fixture: deliberate damage
            with open(path, "r+b") as f:
                f.truncate(1)
        """}, rules=["atomic-write-discipline"])
    assert not rep.active
    assert len(rep.suppressed) == 1


# --------------------------------------------- v3 package gates
def test_package_clean_under_v3_rules():
    """Each ISSUE-9 family individually reports zero unsuppressed
    findings on the real package — the sweep fixed the true positives
    (hostio sigterm-through-AsyncWriter, RunGuard tick state,
    CheckpointManager generations, faults tombstone) and the remaining
    patterns carry justified suppressions."""
    for rule in ("signal-handler-safety", "thread-shared-state",
                 "rng-stream-discipline", "atomic-write-discipline"):
        rep = run_lint(PACKAGE, rules=[rule])
        assert rep.active == [], (rule, [f.render()
                                         for f in rep.active])


def test_package_concurrency_roots_found():
    """Sanity: the v3 root discovery actually sees the reliability
    stack's handlers and threads (an empty root set would make the two
    concurrency rules vacuously green)."""
    from tools.tpulint.callgraph import PackageIndex
    from tools.tpulint.core import LintContext
    index = PackageIndex(LintContext(PACKAGE))
    handlers, threads = index.concurrency_roots()
    assert "_handler" in {f.qualname for f in handlers}
    tnames = {f.qualname for f in threads}
    assert {"AsyncWriter._run", "RunGuard._watch",
            "EventLogger._append",
            "CheckpointManager._write_reporting"} <= tnames


# --------------------------------------------- v3 CLI: sarif / jobs
def test_cli_sarif_format(tmp_path):
    pkg = _mk_pkg(tmp_path, {"learner/m.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)
        """})
    r = _run_cli([pkg, "--rules=explicit-dtype", "--no-cache",
                  "--format=sarif"])
    assert r.returncode == 1, r.stderr
    sarif = json.loads(r.stdout)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "tpulint"
    res = run["results"]
    assert len(res) == 1
    assert res[0]["ruleId"] == "explicit-dtype"
    loc = res[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("learner/m.py")
    assert loc["region"]["startLine"] == 4
    # rule metadata is indexable
    assert run["tool"]["driver"]["rules"][res[0]["ruleIndex"]]["id"] \
        == "explicit-dtype"
    # clean subset -> empty results, exit 0
    r2 = _run_cli([pkg, "--rules=no-bare-print", "--no-cache",
                   "--format=sarif"])
    assert r2.returncode == 0
    assert json.loads(r2.stdout)["runs"][0]["results"] == []


def test_parallel_jobs_matches_serial(tmp_path):
    files = {f"learner/m{i}.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)
        """ for i in range(10)}
    pkg = _mk_pkg(tmp_path, files)
    serial = run_lint(pkg, rules=["explicit-dtype"], jobs=1)
    parallel = run_lint(pkg, rules=["explicit-dtype"], jobs=2)
    assert [f.to_dict() for f in parallel.findings] == \
        [f.to_dict() for f in serial.findings]
    assert len(parallel.active) == 10


def test_stale_suppression_audit(tmp_path):
    pkg = _mk_pkg(tmp_path, {"m.py": """
        def f():
            print("x")  # tpulint: disable=no-bare-print -- fixture: live
            return 1    # tpulint: disable=no-bare-print -- fixture: stale
        """})
    from tools.tpulint.core import audit_suppressions
    entries = {line: used for _, line, _, _, used
               in audit_suppressions(pkg)}
    assert entries == {3: True, 4: False}
    r = _run_cli([pkg, "--list-suppressions", "--no-cache"])
    assert r.returncode == 1, r.stdout
    assert "STALE" in r.stdout
    assert "2 suppression(s), 1 stale" in r.stdout
