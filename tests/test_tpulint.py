"""tpulint static-analysis suite (ISSUE 3 tentpole).

Two layers:

* fixture tests — for every rule, at least one true positive and one
  true negative over a synthetic mini-package, pinning the analysis
  contract (what taints, what is static, what is in scope);
* package tests — the full suite over the real `lightgbm_tpu` tree
  must report ZERO unsuppressed findings (the merge bar), and every
  suppression must carry a justification.

No jax import needed: the lint is pure-AST by design, so this file is
cheap tier-1.
"""

import json
import os
import subprocess
import sys
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tools.tpulint import RULES, run_lint  # noqa: E402

PACKAGE = os.path.join(_REPO, "lightgbm_tpu")


def _mk_pkg(tmp_path, files):
    """Write {relpath: source} under tmp_path/pkg and return its path."""
    pkg = tmp_path / "pkg"
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    # ensure __init__.py files exist so the tree looks like a package
    for root, _dirs, _files in os.walk(pkg):
        init = os.path.join(root, "__init__.py")
        if not os.path.exists(init):
            open(init, "w").close()
    return str(pkg)


def _lint(tmp_path, files, rules):
    return run_lint(_mk_pkg(tmp_path, files), rules=rules)


def _rules_of(report):
    return [(f.path.split(os.sep, 1)[1], f.line, f.rule)
            for f in report.active]


# ------------------------------------------------------------ registry/CLI
def test_registry_has_all_rules():
    from tools.tpulint import rules as _  # noqa: F401
    assert {"no-host-sync-in-jit", "no-tracer-branch", "explicit-dtype",
            "collective-discipline", "no-bare-print", "config-doc-sync",
            "no-device-put-in-loop", "donate-argnums"} <= set(RULES)


def test_cli_json_format_and_exit_codes(tmp_path):
    pkg = _mk_pkg(tmp_path, {"learner/m.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)
        """})
    env = dict(os.environ, PYTHONPATH=_REPO)
    r = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", pkg, "--format=json",
         "--rules=explicit-dtype"],
        capture_output=True, text=True, env=env, cwd=_REPO)
    assert r.returncode == 1, r.stderr
    rep = json.loads(r.stdout)
    assert rep["num_active"] == 1
    assert rep["counts"] == {"explicit-dtype": 1}
    f0 = rep["findings"][0]
    assert f0["rule"] == "explicit-dtype" and f0["line"] == 4
    # clean tree -> exit 0
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", pkg,
         "--rules=no-bare-print"],
        capture_output=True, text=True, env=env, cwd=_REPO)
    assert r2.returncode == 0, r2.stdout


# ------------------------------------------------------------- suppression
def test_suppression_same_line_and_next_line(tmp_path):
    rep = _lint(tmp_path, {"learner/m.py": """
        import jax.numpy as jnp
        def f(n):
            a = jnp.zeros(n)  # tpulint: disable=explicit-dtype -- fixture
            # tpulint: disable-next=explicit-dtype -- fixture
            b = jnp.ones(n)
            c = jnp.full(n, 0)
            return a, b, c
        """}, rules=["explicit-dtype"])
    assert _rules_of(rep) == [("learner/m.py", 7, "explicit-dtype")]
    assert len(rep.suppressed) == 2
    assert all(f.justification == "fixture" for f in rep.suppressed)


def test_suppression_without_justification_is_reported(tmp_path):
    rep = _lint(tmp_path, {"learner/m.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)  # tpulint: disable=explicit-dtype
        """}, rules=["explicit-dtype"])
    assert [f.rule for f in rep.active] == ["bad-suppression"]
    assert len(rep.suppressed) == 1


def test_suppression_only_masks_named_rule(tmp_path):
    rep = _lint(tmp_path, {"m.py": """
        def f():
            print("hi")  # tpulint: disable=explicit-dtype -- wrong rule
        """}, rules=["no-bare-print"])
    assert [f.rule for f in rep.active] == ["no-bare-print"]


# ----------------------------------------------------------- explicit-dtype
def test_explicit_dtype_positives_and_negatives(tmp_path):
    rep = _lint(tmp_path, {
        "ops/dev.py": """
        import jax.numpy as jnp
        def f(n):
            bad1 = jnp.zeros(n)
            bad2 = jnp.arange(n)
            bad3 = jnp.full((n, 2), 0.0)
            ok1 = jnp.zeros(n, jnp.float32)     # positional dtype
            ok2 = jnp.arange(n, dtype=jnp.int32)
            ok3 = jnp.full((n, 2), 0.0, jnp.float32)
            ok4 = jnp.where(ok1 > 0, 1.0, 0.0)  # not a constructor
            return bad1, bad2, bad3, ok2, ok3, ok4
        """,
        # host-side module: out of scope by design
        "host.py": """
        import jax.numpy as jnp
        def g(n):
            return jnp.zeros(n)
        """}, rules=["explicit-dtype"])
    assert _rules_of(rep) == [("ops/dev.py", 4, "explicit-dtype"),
                              ("ops/dev.py", 5, "explicit-dtype"),
                              ("ops/dev.py", 6, "explicit-dtype")]


def test_explicit_dtype_covers_inference(tmp_path):
    rep = _lint(tmp_path, {"inference/t.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)
        """}, rules=["explicit-dtype"])
    assert _rules_of(rep) == [("inference/t.py", 4, "explicit-dtype")]


# ------------------------------------------------- no-device-put-in-loop
def test_no_device_put_in_loop(tmp_path):
    rep = _lint(tmp_path, {
        "inference/b.py": """
        import jax
        import jax.numpy as jnp
        def bad(batches):
            out = []
            for b in batches:
                out.append(jax.device_put(b))       # flagged
            i = 0
            while i < 3:
                x = jnp.asarray(batches[i])         # flagged
                i += 1
            return out, x
        def ok(batches):
            big = jnp.asarray(batches)              # one transfer, no loop
            return [b * 2 for b in big]
        def ok_comprehension(parts):
            # comprehensions converting scalars are the benign form
            return tuple(jnp.asarray(p) for p in parts)
        """,
        # host-side module: out of scope by design
        "metric.py": """
        import jax.numpy as jnp
        def g(vals):
            out = []
            for v in vals:
                out.append(jnp.asarray(v))
            return out
        """}, rules=["no-device-put-in-loop"])
    assert _rules_of(rep) == [
        ("inference/b.py", 7, "no-device-put-in-loop"),
        ("inference/b.py", 10, "no-device-put-in-loop")]


def test_no_device_put_in_loop_suppression(tmp_path):
    rep = _lint(tmp_path, {"learner/m.py": """
        import jax
        def f(bs):
            for b in bs:
                x = jax.device_put(b)  # tpulint: disable=no-device-put-in-loop -- fixture
            return x
        """}, rules=["no-device-put-in-loop"])
    assert not rep.active
    assert len(rep.suppressed) == 1


# --------------------------------------------------------- donate-argnums
def test_donate_argnums_positives_and_negatives(tmp_path):
    rep = _lint(tmp_path, {"boosting/u.py": """
        import functools
        import jax

        @jax.jit
        def bad_update(scores, delta):              # flagged (line 5)
            return scores + delta

        @functools.partial(jax.jit, static_argnames=("k",))
        def bad_grow(binned, grad, hess, k):        # flagged (line 9)
            return grad * hess

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def ok_grow(binned, grad, hess):            # covered
            return grad * hess

        @jax.jit
        def ok_names(sc, g, h):                     # not canonical names
            return sc + g * h

        def upd(scores, delta):
            return scores + delta
        bad_assign = jax.jit(upd)                   # flagged (line 23)
        ok_assign = jax.jit(upd, donate_argnums=(0,))
        ok_named = jax.jit(upd, donate_argnames=("scores",))
        _gate = (0,)
        ok_gated = jax.jit(upd, donate_argnums=_gate)   # config-gated
        """}, rules=["donate-argnums"])
    assert _rules_of(rep) == [
        ("boosting/u.py", 5, "donate-argnums"),
        ("boosting/u.py", 9, "donate-argnums"),
        ("boosting/u.py", 23, "donate-argnums")]


def test_donate_argnums_suppression(tmp_path):
    rep = _lint(tmp_path, {"boosting/v.py": """
        import jax

        def eval_fn(scores):
            return scores.sum()
        # tpulint: disable-next=donate-argnums -- read-only eval, caller keeps the buffer
        jitted = jax.jit(eval_fn)
        """}, rules=["donate-argnums"])
    assert not rep.active
    assert len(rep.suppressed) == 1


# ----------------------------------------------------- collective-discipline
def test_collective_discipline(tmp_path):
    rep = _lint(tmp_path, {
        "learner/eng.py": """
        import jax
        def f(x, axis):
            return jax.lax.psum(x, axis)
        """,
        "parallel/dp.py": """
        import jax
        from jax import lax
        def g(x, axis):
            return lax.pmean(jax.lax.all_gather(x, axis), axis)
        """,
        "distributed.py": """
        import jax
        def h(x, axis):
            return jax.lax.psum(x, axis)
        """}, rules=["collective-discipline"])
    assert _rules_of(rep) == [("learner/eng.py", 4,
                               "collective-discipline")]


# ------------------------------------------------------------ no-bare-print
def test_no_bare_print(tmp_path):
    rep = _lint(tmp_path, {
        "boost.py": """
        from .utils import log
        def f():
            print("bad")
            log.info("ok")
        """,
        "utils/log.py": """
        def info(msg):
            print(msg)   # the whitelisted default sink
        """}, rules=["no-bare-print"])
    assert _rules_of(rep) == [("boost.py", 4, "no-bare-print")]


def test_no_bare_print_clean_on_real_package():
    rep = run_lint(PACKAGE, rules=["no-bare-print"])
    assert rep.active == [], [f.render() for f in rep.active]


# ------------------------------------------------------- no-host-sync-in-jit
_JIT_PKG = {
    "learner/mod.py": """
    import functools
    import numpy as np
    import jax
    import jax.numpy as jnp
    from ..ops.helper import downstream

    @functools.partial(jax.jit, static_argnames=("cfg",))
    def entry(x, y, cfg):
        z = x * 2 + jnp.sum(y)
        f = float(z)                  # BAD: host sync
        a = np.asarray(x)             # BAD: host sync
        i = z.item()                  # BAD: host sync
        w = z.block_until_ready()     # BAD: host sync
        n = x.shape[0]
        ok1 = int(n)                  # ok: shape is static
        ok2 = jnp.asarray(y)          # ok: device-side
        ok3 = float(cfg.lr)           # ok: static param
        downstream(z, 3)
        return z

    def host_fn(a):
        return float(a)               # ok: not jit-reachable
    """,
    "ops/helper.py": """
    def downstream(v, k):
        bad = bool(v)                 # BAD: tainted via call graph
        ok = int(k)                   # ok: untainted arg at call site
        return bad, ok
    """,
}


def test_no_host_sync_in_jit(tmp_path):
    rep = _lint(tmp_path, dict(_JIT_PKG), rules=["no-host-sync-in-jit"])
    got = _rules_of(rep)
    assert ("learner/mod.py", 11, "no-host-sync-in-jit") in got  # float
    assert ("learner/mod.py", 12, "no-host-sync-in-jit") in got  # asarray
    assert ("learner/mod.py", 13, "no-host-sync-in-jit") in got  # .item
    assert ("learner/mod.py", 14, "no-host-sync-in-jit") in got  # block
    assert ("ops/helper.py", 3, "no-host-sync-in-jit") in got    # callee
    # and nothing else: the ok/host_fn lines are all clean
    assert len(got) == 5, got


# --------------------------------------------------------- no-tracer-branch
def test_no_tracer_branch(tmp_path):
    rep = _lint(tmp_path, {"learner/mod.py": """
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("params",))
        def entry(x, y, params):
            z = jnp.sum(x)
            if z > 0:                   # BAD
                pass
            while y.sum() > 0:          # BAD (method call on tracer)
                break
            assert x[0] > 0             # BAD
            t = 1 if z > 0 else 2       # BAD ternary
            if params.max_depth > 0:    # ok: static param
                pass
            if x.shape[0] > 4:          # ok: shape
                pass
            if x is None:               # ok: identity test
                pass
            if params.forced:
                for k, s in enumerate(params.forced):
                    if k > 3:           # ok: python loop over statics
                        break

            def body(i, carry):
                if carry > 0:           # BAD: fori_loop carry is traced
                    return carry
                return carry + i
            return jax.lax.fori_loop(0, 3, body, z), t
        """}, rules=["no-tracer-branch"])
    lines = [ln for _, ln, _ in _rules_of(rep)]
    assert lines == [9, 11, 13, 14, 27], _rules_of(rep)


def test_jit_assignment_form_and_static_argnums(tmp_path):
    rep = _lint(tmp_path, {"learner/mod.py": """
        import jax

        def raw(x, k):
            if k > 0:       # ok: static_argnums=1
                pass
            if (x > 0).any():   # BAD
                pass
            return x

        fn = jax.jit(raw, static_argnums=(1,))
        """}, rules=["no-tracer-branch"])
    assert [ln for _, ln, _ in _rules_of(rep)] == [7]


# ---------------------------------------------------------- config-doc-sync
_CONFIG = """
PARAMS = [
    ("alpha", "float", 1.0, ()),
    ("beta", "int", 2, ("b",)),
]
"""


def _doc(tmp_path, rows):
    d = tmp_path / "docs"
    d.mkdir(exist_ok=True)
    body = "| Parameter | Type | Default | Aliases |\n|---|---|---|---|\n"
    body += "\n".join(f"| `{r}` | x | `0` | — |" for r in rows) + "\n"
    (d / "Parameters.md").write_text("# Parameters\n\n" + body)


def test_config_doc_sync(tmp_path):
    pkg = _mk_pkg(tmp_path, {"config.py": _CONFIG})
    _doc(tmp_path, ["alpha", "beta"])
    assert run_lint(pkg, rules=["config-doc-sync"]).active == []
    _doc(tmp_path, ["alpha", "gamma"])   # beta undocumented, gamma stale
    rep = run_lint(pkg, rules=["config-doc-sync"])
    msgs = sorted(f.message for f in rep.active)
    assert len(msgs) == 2
    assert "`beta`" in msgs[0] and "not documented" in msgs[0]
    assert "`gamma`" in msgs[1] and "stale" in msgs[1]


def test_config_doc_sync_missing_doc(tmp_path):
    pkg = _mk_pkg(tmp_path, {"config.py": _CONFIG})
    rep = run_lint(pkg, rules=["config-doc-sync"])
    assert [f.rule for f in rep.active] == ["config-doc-sync"]
    assert "missing" in rep.active[0].message


# ------------------------------------------------------------- package-wide
def test_package_is_clean():
    """The merge bar: zero unsuppressed findings over lightgbm_tpu with
    ALL rules enabled (acceptance: `python -m tools.tpulint lightgbm_tpu`
    exits 0)."""
    rep = run_lint(PACKAGE)
    assert rep.active == [], "\n".join(f.render() for f in rep.active)


def test_package_suppressions_are_justified():
    rep = run_lint(PACKAGE)
    for f in rep.suppressed:
        assert f.justification, f.render()


def test_package_finds_jit_roots():
    """Sanity: the call-graph analysis actually sees the engine's jit
    entry points (an empty reachable set would make the two taint rules
    vacuously green)."""
    from tools.tpulint.callgraph import PackageIndex, build_reachable
    from tools.tpulint.core import LintContext
    funcs = build_reachable(PackageIndex(LintContext(PACKAGE)))
    names = {f.qualname for f in funcs}
    assert {"grow_tree_impl", "grow_tree_wave_impl", "find_best_split",
            "build_histogram"} <= names
    roots = {f.qualname for f in funcs if f.jit_root}
    # the impls are rooted through BOTH jit entries (plain and donated)
    assert {"grow_tree_impl", "grow_tree_wave_impl"} <= roots
    # static_argnames honored on the engine entry points
    by_name = {f.qualname: f for f in funcs}
    assert "params" in by_name["grow_tree_impl"].static_params
    assert "params" not in by_name["grow_tree_impl"].tainted_params
    assert "binned" in by_name["grow_tree_impl"].tainted_params
