"""tpulint IR layer (ISSUE 12 tentpole): jaxpr-level audit tests.

Mirrors test_tpulint.py's two layers at the IR level:

* fixture tests — per ir-rule, a true positive and a true negative over
  a synthetic package with its own `_lint_entries.py` manifest,
  pinning the abstract-trace contract (enable_x64 visibility of
  weak-type f64, declares-based exemptions, trace-failure reporting);
* package tests — the IR audit over the real `lightgbm_tpu` manifest
  must trace every entry and report ZERO findings, and every
  RecompileDetector-fingerprinted hot-entry group must have a manifest
  row.

Unlike test_tpulint.py this file DOES import jax (abstract tracing),
but nothing ever compiles or touches data — each fixture traces in
tens of milliseconds.
"""

import itertools
import json
import os
import subprocess
import sys
import textwrap

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tools.tpulint import RULES, run_lint  # noqa: E402
from tools.tpulint.ir import run_ir_audit  # noqa: E402

PACKAGE = os.path.join(_REPO, "lightgbm_tpu")

IR_RULES = ["ir-no-f64", "ir-no-callback", "ir-convert-churn",
            "ir-giant-constant", "ir-scatter-audit",
            "ir-manifest-coverage", "ir-trace-error"]

# every fixture package gets a unique name: the manifest is imported
# for real, and two same-named packages would collide in sys.modules
_counter = itertools.count()

_MANIFEST_PRELUDE = textwrap.dedent("""
    ENTRIES = []

    class _E:
        def __init__(self, name, group, build, declares, line):
            self.name, self.group = name, group
            self.build, self.declares, self.line = build, declares, line

    def lint_entry(name, declares=()):
        def deco(build):
            ENTRIES.append(_E(name, name.split("[", 1)[0], build,
                              frozenset(declares),
                              build.__code__.co_firstlineno))
            return build
        return deco
    """)


def _mk_pkg(tmp_path, files):
    name = f"irfix{os.getpid()}_{next(_counter)}"
    pkg = tmp_path / name
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    init = pkg / "__init__.py"
    if not init.exists():
        init.write_text("")
    return str(pkg)


def _manifest_pkg(tmp_path, entries_src, extra_files=None):
    files = dict(extra_files or {})
    files["_lint_entries.py"] = _MANIFEST_PRELUDE + textwrap.dedent(
        entries_src)
    return _mk_pkg(tmp_path, files)


def _ir_lint(tmp_path, entries_src, rules=None, extra_files=None):
    pkg = _manifest_pkg(tmp_path, entries_src, extra_files)
    rules = list(rules) + ["ir-trace-error"] if rules else None
    return run_lint(pkg, rules=rules, ir=True)


def _active(report, rule=None):
    return [f for f in report.active
            if rule is None or f.rule == rule]


# ---------------------------------------------------------------- registry
def test_ir_rules_registered_and_excluded_by_default():
    from tools.tpulint import rules as _  # noqa: F401
    for name in IR_RULES:
        assert name in RULES and RULES[name].ir, name
    # a default (non --ir) run must NOT try to trace anything: a
    # package without a manifest lints clean
    rep = run_lint(PACKAGE)  # ir=False
    assert not [f for f in rep.active if f.rule.startswith("ir-")]


# ----------------------------------------------------------------- ir-no-f64
def test_no_f64_weak_type_promotion_tp(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[f64]")
    def _b():
        import jax, numpy as np
        def f(x):
            return (x * np.asarray([2.0])).sum()   # f64 under x64
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """, rules=["ir-no-f64"])
    fs = _active(rep, "ir-no-f64")
    assert fs, rep.render_text()
    assert any("float64" in f.message for f in fs)
    assert all(f.path.endswith("_lint_entries.py") for f in fs)
    assert not _active(rep, "ir-trace-error")


def test_no_f64_clean_f32_tn(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[f32]")
    def _b():
        import jax, numpy as np
        def f(x):
            return (x * np.asarray([2.0], np.float32)).sum()
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """, rules=["ir-no-f64"])
    assert not _active(rep), rep.render_text()


# ------------------------------------------------------------ ir-no-callback
def test_no_callback_pure_callback_tp(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[cb]")
    def _b():
        import jax, numpy as np
        def f(x):
            y = jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct((), np.float32), x[0])
            return x.sum() + y
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """, rules=["ir-no-callback"])
    fs = _active(rep, "ir-no-callback")
    assert fs and "pure_callback" in fs[0].message, rep.render_text()


def test_no_callback_debug_print_tp_and_clean_tn(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[dbg]")
    def _b():
        import jax, numpy as np
        def f(x):
            jax.debug.print("x0 {}", x[0])
            return x.sum()
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)

    @lint_entry("hot[clean]")
    def _b2():
        import jax, numpy as np
        def f(x):
            return x.sum()
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """, rules=["ir-no-callback"])
    fs = _active(rep, "ir-no-callback")
    assert len(fs) == 1 and "[hot[dbg]]" in fs[0].message, \
        rep.render_text()


# --------------------------------------------------------- ir-convert-churn
def test_convert_churn_round_trip_tp(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[churn]")
    def _b():
        import jax, numpy as np
        import jax.numpy as jnp
        def f(x):
            return x.astype(jnp.float64).astype(jnp.float32) + 1.0
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """, rules=["ir-convert-churn"])
    fs = _active(rep, "ir-convert-churn")
    assert fs and "float32 -> float64 -> float32" in fs[0].message, \
        rep.render_text()


def test_convert_churn_precision_squeeze_and_compute_tn(tmp_path):
    # f32->bf16->f32 is a deliberate precision squeeze; a round trip
    # WITH intervening compute is semantic — neither is churn
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[squeeze]")
    def _b():
        import jax, numpy as np
        import jax.numpy as jnp
        def f(x):
            a = x.astype(jnp.bfloat16).astype(jnp.float32)
            b = (x.astype(jnp.float64) + 1.0).astype(jnp.float32)
            return a + b
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """, rules=["ir-convert-churn"])
    assert not _active(rep, "ir-convert-churn"), rep.render_text()


# -------------------------------------------------------- ir-giant-constant
def test_giant_constant_tp_and_tn(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[giant]")
    def _b():
        import jax, numpy as np
        import jax.numpy as jnp
        big = jnp.zeros(100_000, jnp.float32)     # 400 KB baked in
        small = jnp.zeros(16, jnp.float32)
        def f(x):
            return x + big[:x.shape[0]] + small[:x.shape[0]].sum()
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """, rules=["ir-giant-constant"])
    fs = _active(rep, "ir-giant-constant")
    assert len(fs) == 1 and "391 KiB" in fs[0].message, rep.render_text()


# --------------------------------------------------------- ir-scatter-audit
def test_scatter_audit_undeclared_onehot_dot_tp(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[onehot]")
    def _b():
        import jax, numpy as np
        import jax.numpy as jnp
        def f(codes, vals):
            oh = (codes[:, None]
                  == jnp.arange(16, dtype=jnp.int32)[None, :])
            return oh.astype(jnp.float32).T @ vals
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.int32),
                            jax.ShapeDtypeStruct((64,), np.float32))
    """, rules=["ir-scatter-audit"])
    fs = _active(rep, "ir-scatter-audit")
    assert fs and "one-hot" in fs[0].message, rep.render_text()


def test_scatter_audit_declared_onehot_dot_tn(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[onehot]", declares=("onehot-dot",))
    def _b():
        import jax, numpy as np
        import jax.numpy as jnp
        def f(codes, vals):
            oh = (codes[:, None]
                  == jnp.arange(16, dtype=jnp.int32)[None, :])
            return oh.astype(jnp.float32).T @ vals
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.int32),
                            jax.ShapeDtypeStruct((64,), np.float32))
    """, rules=["ir-scatter-audit"])
    assert not _active(rep), rep.render_text()


def test_scatter_audit_narrow_accumulator_tp_tn(tmp_path):
    src = """

    @lint_entry("hot[i8]"{declares})
    def _b():
        import jax, numpy as np
        import jax.numpy as jnp
        def f(idx, vals):
            acc = jnp.zeros(16, jnp.int8)
            return acc.at[idx].add(vals)
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.int32),
                            jax.ShapeDtypeStruct((64,), np.int8))
    """
    rep = _ir_lint(tmp_path, src.format(declares=""),
                   rules=["ir-scatter-audit"])
    fs = _active(rep, "ir-scatter-audit")
    assert fs and "int8 scatter accumulator" in fs[0].message, \
        rep.render_text()
    rep2 = _ir_lint(tmp_path,
                    src.format(declares=", declares=('narrow-acc',)"),
                    rules=["ir-scatter-audit"])
    assert not _active(rep2), rep2.render_text()


# ----------------------------------------------------------- ir-trace-error
def test_trace_error_builder_raises(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[broken]")
    def _b():
        raise RuntimeError("boom")
    """, rules=["ir-no-f64"])
    fs = _active(rep, "ir-trace-error")
    assert fs and "boom" in fs[0].message, rep.render_text()


def test_trace_error_missing_manifest(tmp_path):
    pkg = _mk_pkg(tmp_path, {"m.py": "x = 1\n"})
    rep = run_lint(pkg, rules=["ir-trace-error"], ir=True)
    fs = _active(rep, "ir-trace-error")
    assert fs and "_lint_entries.py" in fs[0].message, rep.render_text()


# ------------------------------------------------------ ir-manifest-coverage
def test_manifest_coverage_missing_group_tp(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("covered[x]")
    def _b():
        import jax, numpy as np
        return (jax.jit(lambda x: x + 1.0),
                (jax.ShapeDtypeStruct((8,), np.float32),))
    """, rules=["ir-manifest-coverage"], extra_files={"hot.py": """
        from .obs import RecompileDetector

        def setup(fn):
            wrapped = RecompileDetector(fn, "covered")
            other = RecompileDetector(fn, "uncovered_entry")
            ladder = RecompileDetector(fn, f"covered[raw@{4096}]")
            return wrapped, other, ladder
        """, "obs.py": """
        class RecompileDetector:
            def __init__(self, fn, name):
                self.fn, self.name = fn, name
        """})
    fs = _active(rep, "ir-manifest-coverage")
    assert len(fs) == 1 and "uncovered_entry" in fs[0].message, \
        rep.render_text()
    assert fs[0].path.endswith("hot.py")  # anchored at the detector site


# ------------------------------------------------------------- suppressions
def test_ir_finding_suppressible_at_manifest_line(tmp_path):
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[f64]")  # tpulint: disable=ir-no-f64 -- fixture: deliberate f64
    def _b():
        import jax, numpy as np
        def f(x):
            return (x * np.asarray([2.0])).sum()
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """, rules=["ir-no-f64"])
    assert not _active(rep), rep.render_text()
    assert rep.suppressed and \
        rep.suppressed[0].justification.startswith("fixture")


# ------------------------------------------------------------- determinism
def test_ir_jobs_serial_equals_parallel(tmp_path):
    src = """

    @lint_entry("hot[f64]")
    def _b():
        import jax, numpy as np
        def f(x):
            return (x * np.asarray([2.0])).sum()
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """
    pkg = _manifest_pkg(tmp_path, src)
    r1 = run_lint(pkg, ir=True, jobs=1)
    r4 = run_lint(pkg, ir=True, jobs=4)
    key = lambda r: [(f.rule, f.path, f.line, f.message)  # noqa: E731
                     for f in r.active]
    assert key(r1) == key(r4) and key(r1)


# ------------------------------------------------------------------- cache
def test_ir_results_cached_per_entry_and_invalidated(tmp_path,
                                                    monkeypatch):
    src = """

    @lint_entry("hot[f64]")
    def _b():
        import jax, numpy as np
        def f(x):
            return (x * np.asarray([2.0])).sum()
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """
    pkg = _manifest_pkg(tmp_path, src)
    cache = str(tmp_path / "cache.json")
    r1 = run_lint(pkg, ir=True, cache_path=cache)
    assert _active(r1, "ir-no-f64")
    stored = json.load(open(cache))
    assert "ir" in stored and stored["ir"]["entry_sigs"], \
        "per-entry signatures recorded"
    # a warm re-run must replay from the cache without tracing
    import tools.tpulint.ir.rules as ir_rules

    def _boom(*a, **k):
        raise AssertionError("IR pass re-ran on an unchanged package")
    monkeypatch.setattr(ir_rules, "run_ir_pass", _boom)
    r2 = run_lint(pkg, ir=True, cache_path=cache)
    assert [(f.rule, f.line) for f in r2.active] == \
        [(f.rule, f.line) for f in r1.active]
    monkeypatch.undo()
    # editing any package source invalidates the IR section (content
    # hash key), even when the mtime is restored
    mf = os.path.join(pkg, "_lint_entries.py")
    st = os.stat(mf)
    with open(mf, "a") as f:
        f.write("\n# content change\n")
    os.utime(mf, ns=(st.st_atime_ns, st.st_mtime_ns))
    seen = []
    real = ir_rules.run_ir_pass
    monkeypatch.setattr(ir_rules, "run_ir_pass",
                        lambda *a, **k: seen.append(1) or real(*a, **k))
    run_lint(pkg, ir=True, cache_path=cache)
    assert seen, "content change must re-run the IR pass"


# ---------------------------------------------------------------- CLI / e2e
@pytest.mark.slow
def test_cli_ir_exit_codes(tmp_path):
    pkg = _manifest_pkg(tmp_path, """

    @lint_entry("hot[f64]")
    def _b():
        import jax, numpy as np
        def f(x):
            return (x * np.asarray([2.0])).sum()
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """)
    env = dict(os.environ, PYTHONPATH=_REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", pkg, "--ir",
         "--format=json", "--no-cache"],
        capture_output=True, text=True, env=env, cwd=_REPO)
    assert r.returncode == 1, r.stderr
    rep = json.loads(r.stdout)
    # two findings: the baked f64 constant + the introducing convert
    assert rep["counts"].get("ir-no-f64", 0) >= 1, rep["counts"]
    # without --ir the same package is clean (no ir rules selected)
    r2 = subprocess.run(
        [sys.executable, "-m", "tools.tpulint", pkg, "--no-cache"],
        capture_output=True, text=True, env=env, cwd=_REPO)
    assert r2.returncode == 0, r2.stdout


def test_sarif_includes_ir_findings(tmp_path):
    from tools.tpulint.core import to_sarif
    rep = _ir_lint(tmp_path, """

    @lint_entry("hot[f64]")
    def _b():
        import jax, numpy as np
        def f(x):
            return (x * np.asarray([2.0])).sum()
        return jax.jit(f), (jax.ShapeDtypeStruct((64,), np.float32),)
    """, rules=["ir-no-f64"])
    sarif = to_sarif(rep)
    results = sarif["runs"][0]["results"]
    assert any(r["ruleId"] == "ir-no-f64" for r in results)
    rules_meta = sarif["runs"][0]["tool"]["driver"]["rules"]
    assert any(r["id"] == "ir-no-f64" and "shortDescription" in r
               for r in rules_meta)


# ------------------------------------------------------------ package gates
@pytest.fixture(scope="module")
def package_ir_report():
    return run_lint(PACKAGE, ir=True)


def test_package_ir_audit_clean(package_ir_report):
    active = [f for f in package_ir_report.active
              if f.rule.startswith("ir-")]
    assert not active, "\n".join(f.render() for f in active)


@pytest.mark.parametrize("rule", IR_RULES)
def test_package_clean_per_ir_family(package_ir_report, rule):
    fs = [f for f in package_ir_report.active if f.rule == rule]
    assert not fs, "\n".join(f.render() for f in fs)


def test_package_manifest_covers_every_detector_group():
    from tools.tpulint.core import LintContext
    from tools.tpulint.ir.rules import detector_sites
    from tools.tpulint.ir.trace import load_manifest
    entries, err = load_manifest(PACKAGE)
    assert err is None, err
    covered = {e.group for e in entries}
    ctx = LintContext(PACKAGE)
    runtime = {g for _p, _l, g in detector_sites(ctx)}
    # the four hot-entry families the cost model/recompile watchdog
    # fingerprint today, plus anything added later
    assert {"grow_tree", "gradients", "device_eval",
            "device_predict"} <= runtime
    assert runtime <= covered, f"uncovered groups: {runtime - covered}"


def test_package_every_entry_traces():
    findings, num = run_ir_audit(PACKAGE)
    from lightgbm_tpu._lint_entries import ENTRIES
    assert num == len(ENTRIES) and num >= 15
    assert not [f for f in findings if not f.suppressed]


def test_group_filter_restricts_tracing():
    findings, num = run_ir_audit(PACKAGE, groups=["gradients"])
    from lightgbm_tpu._lint_entries import ENTRIES
    expect = sum(1 for e in ENTRIES if e.group == "gradients")
    assert num == expect >= 2
    assert not [f for f in findings if not f.suppressed]


# ----------------------------------------------- cache-staleness regression
def test_tool_fingerprint_is_content_hashed(tmp_path):
    """ISSUE 12 satellite: editing a RULE with (mtime, size) preserved
    must still invalidate the cache — the fingerprint hashes content."""
    from tools.tpulint.core import _tool_fingerprint
    d = tmp_path / "tool"
    d.mkdir()
    p = d / "rule.py"
    p.write_text("FLAG = True \n")
    st = os.stat(p)
    fp1 = _tool_fingerprint(str(d))
    # same byte LENGTH, same mtime — only the content differs (the
    # git-checkout / same-second-editor-save shape)
    p.write_text("FLAG = False\n")
    os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns))
    assert os.stat(p).st_size == st.st_size
    assert os.stat(p).st_mtime_ns == st.st_mtime_ns
    fp2 = _tool_fingerprint(str(d))
    assert fp1 != fp2, "mtime/size-keyed fingerprint served stale rules"


def test_rule_edit_invalidates_cached_report(tmp_path, monkeypatch):
    """End to end: with a cache on disk, a changed tool fingerprint
    (the content hash) must force a full re-lint."""
    import tools.tpulint.core as core
    pkg = _mk_pkg(tmp_path, {"learner/m.py": """
        import jax.numpy as jnp
        def f(n):
            return jnp.zeros(n)
        """})
    cache = str(tmp_path / "c.json")
    r1 = run_lint(pkg, rules=["explicit-dtype"], cache_path=cache)
    assert len(r1.active) == 1
    # simulate a rule edit: the content fingerprint changes even though
    # every mtime stayed put
    real_fp = core._tool_fingerprint()
    monkeypatch.setattr(core, "_tool_fingerprint",
                        lambda d=None: real_fp + [["edited-rule.py",
                                                   "deadbeef"]])
    calls = []
    real_ctx = core.LintContext

    class _SpyCtx(real_ctx):
        def __init__(self, *a, **k):
            calls.append(1)
            super().__init__(*a, **k)
    monkeypatch.setattr(core, "LintContext", _SpyCtx)
    r2 = run_lint(pkg, rules=["explicit-dtype"], cache_path=cache)
    assert len(r2.active) == 1
    # the cache was NOT served from the stale meta: the stored meta
    # mismatches, so findings were recomputed (and the cache rewritten
    # under the new fingerprint)
    stored = json.load(open(cache))
    assert stored["meta"]["tool"][-1] == ["edited-rule.py", "deadbeef"]
